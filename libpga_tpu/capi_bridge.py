"""Python side of the C ABI shim (see ``capi/``).

The native ``libpga_tpu_c.so`` embeds CPython and calls the flat functions
in this module. Each function takes/returns only ints, floats, strings and
bytes so the C side can marshal with plain ``PyObject_CallMethod`` format
strings — no pybind11, no buffer-protocol gymnastics.

Handle model: solvers live in a process-global table keyed by integer
handles (the C side wraps them in opaque ``pga_t*``); populations are
addressed by their index inside a solver, mirroring the reference where
``population_t*`` points into the solver's own array
(``/root/reference/src/pga.cu:48-56``).

Custom operators through the C ABI: the reference hands CUDA *device*
function pointers across the API (``include/pga.h:66`` requires callbacks
be ``__device__``). A TPU has no device function pointers, so the shim
offers three surfaces:

- named builtin objectives (``pga_set_objective_name``) — the fast path;
  the whole GA stays on-device;
- CUSTOM objectives at device speed via the expression surface
  (``pga_set_objective_expr`` + ``_const`` — ``objectives/expr.py``):
  the expression compiles to the same rowwise form the builtins use and
  fuses into the breed kernel, constants riding along as kernel inputs;
- raw *host* C function pointers with the reference's exact signatures
  (``float (*)(gene*, unsigned)`` etc.) — the compatibility path. The
  engine evaluates them through ``ctypes`` + ``jax.pure_callback``, so
  genomes round-trip to the host each generation. The per-row callback
  loop itself runs in C (``capi/pga_rowloop.c``): one Python<->C
  crossing per generation, whatever the population size.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
from typing import Dict, Optional, Set

import numpy as np

# Host-callback operators need a backend that supports jax host callbacks;
# tunneled TPU transports may not (axon: "does not support host send/recv
# callbacks"). Make sure a CPU backend is also available so host-callback
# solvers can execute there. Must happen before the first backend init.
_platforms = os.environ.get("JAX_PLATFORMS", "")
if _platforms and "cpu" not in _platforms.split(","):
    os.environ["JAX_PLATFORMS"] = _platforms + ",cpu"
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # backends already initialized — leave as-is
        pass

_solvers: Dict[int, object] = {}
_next_handle = 1

# Keep ctypes callback wrappers alive for the lifetime of their solver.
_retained: Dict[int, list] = {}

# Which of a solver's operators are host C callbacks ("obj" / "mut" /
# "cross"): while any is installed, the solver's device code must run on
# the CPU backend (see note above). Restoring a default via NULL removes
# the entry so a fully builtin solver returns to the accelerator.
_host_ops: Dict[int, Set[str]] = {}


def _set_host_op(handle: int, kind: str, on: bool) -> None:
    import dataclasses

    ops = _host_ops.setdefault(handle, set())
    (ops.add if on else ops.discard)(kind)
    # The Pallas fast path must not be selected for a CPU-pinned solver:
    # the engine's backend gate checks jax.default_backend(), which still
    # reports "tpu" inside a jax.default_device(cpu) context. Force the
    # config off while any host op is installed; restore auto when clear.
    pga = _solver(handle)
    want = False if ops else None
    if pga.config.use_pallas != want:
        pga.config = dataclasses.replace(pga.config, use_pallas=want)
        pga._compiled.clear()


def _exec_ctx(handle: int):
    """Device placement for a solver's jitted programs."""
    if _host_ops.get(handle):
        import jax

        return jax.default_device(jax.devices("cpu")[0])
    return contextlib.nullcontext()

# ------------------------------------------------------------- row loop
# Batched marshaling: the per-row callback loop runs in C
# (capi/pga_rowloop.c), so a whole generation costs ONE Python<->C
# crossing instead of one per individual. Loaded lazily; when the shared
# library is absent a best-effort local build is attempted, and failing
# that the pure-Python row loop below remains the fallback.

_ROWLOOP = None  # None = not probed; False = unavailable; else CDLL


def _rowloop_lib():
    global _ROWLOOP
    if _ROWLOOP is None:
        _ROWLOOP = _load_rowloop() or False
    return _ROWLOOP or None


def _load_rowloop():
    import shutil
    import subprocess
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "..", "capi", "pga_rowloop.c")
    so = os.path.join(here, "..", "capi", "libpga_rowloop.so")
    stale = (
        os.path.exists(so) and os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(so)
    )
    if (not os.path.exists(so) or stale) and os.path.exists(src):
        cc = shutil.which("cc") or shutil.which("gcc")
        if cc:
            target = so if os.access(os.path.dirname(so), os.W_OK) else (
                os.path.join(tempfile.mkdtemp(), "libpga_rowloop.so")
            )
            try:
                subprocess.run(
                    [cc, "-O2", "-fPIC", "-shared", src, "-o", target],
                    check=True, capture_output=True, timeout=60,
                )
                so = target
            except Exception:
                return None
    if not os.path.exists(so):
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    u, fp, vp = ctypes.c_uint, ctypes.POINTER(ctypes.c_float), ctypes.c_void_p
    lib.pga_rowloop_obj.argtypes = [vp, fp, fp, u, u]
    lib.pga_rowloop_obj.restype = None
    lib.pga_rowloop_mut.argtypes = [vp, fp, fp, u, u]
    lib.pga_rowloop_mut.restype = None
    lib.pga_rowloop_cross.argtypes = [vp, fp, fp, fp, fp, u, u]
    lib.pga_rowloop_cross.restype = None
    return lib


_OBJ_SIG = ctypes.CFUNCTYPE(ctypes.c_float, ctypes.POINTER(ctypes.c_float), ctypes.c_uint)
_MUT_SIG = ctypes.CFUNCTYPE(
    None, ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float), ctypes.c_uint
)
_CROSS_SIG = ctypes.CFUNCTYPE(
    None,
    ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_float),
    ctypes.c_uint,
)


def _solver(handle: int):
    try:
        return _solvers[handle]
    except KeyError:
        raise ValueError(f"invalid pga handle {handle}") from None


def init(seed: int) -> int:
    """``pga_init`` (pga.h:53). seed < 0 → OS entropy (the reference seeds
    with time(NULL), pga.cu:154)."""
    global _next_handle
    from libpga_tpu.engine import PGA
    from libpga_tpu.config import PGAConfig

    config = PGAConfig(max_populations=10)  # reference cap, pga.h:44
    pga = PGA(seed=None if seed < 0 else seed, config=config)
    h = _next_handle
    _next_handle += 1
    _solvers[h] = pga
    _retained[h] = []
    return h


def deinit(handle: int) -> None:
    _solvers.pop(handle, None)
    _retained.pop(handle, None)
    _host_ops.pop(handle, None)
    _expr_consts.pop(handle, None)
    _serving_execs.pop(handle, None)
    _gp_cfgs.pop(handle, None)


def create_population(handle: int, size: int, genome_len: int, ptype: int) -> int:
    """Returns the population index, or raises (C side maps to NULL)."""
    init_name = {0: "random"}.get(ptype)
    if init_name is None:
        raise ValueError(f"unknown population_type {ptype}")
    pga = _solver(handle)
    # An expression with vector constants implies a genome length; the
    # set_*_expr calls check populations that exist AT REGISTRATION
    # time, so re-check here for populations created AFTERWARD — same
    # diagnostic, at the call that introduces the mismatch, instead of
    # a raw broadcast (or mid-run kernel-build) error at first use.
    # Breeding expressions carry the same pinned_genome_len contract as
    # objectives.
    _check_expr_const_lens(pga._objective, {genome_len})
    _check_expr_const_lens(pga._crossover, {genome_len})
    _check_expr_const_lens(pga._mutate, {genome_len})
    return pga.create_population(size, genome_len, init=init_name).index


def set_objective_name(handle: int, name: str) -> None:
    _solver(handle).set_objective(name)
    _set_host_op(handle, "obj", False)


# Named constants registered per solver for expression objectives
# (pga_set_objective_expr_const): consts first, then the expression that
# references them.
_expr_consts: Dict[int, Dict[str, np.ndarray]] = {}


def set_objective_expr(handle: int, expr: str) -> None:
    """Install a DEVICE-SPEED custom objective from an expression
    (``pga_set_objective_expr``). The expression compiles to the same
    rowwise form the builtin objectives use — eligible for in-kernel
    fusion, with registered constants riding along as kernel inputs —
    so, unlike the host-pointer path (``set_objective_ptr``), the whole
    solver stays on the accelerator. This is the TPU answer to the
    reference's ``__device__`` objective pointers (``pga.h:66``).
    Validation errors raise (→ -1 through the ABI, details on stderr).
    """
    from libpga_tpu.objectives import from_expression

    pga = _solver(handle)
    obj = from_expression(expr, **_expr_consts.get(handle, {}))
    # Vector constants imply a genome length (they broadcast against the
    # gene axis); catch a mismatch with the solver's populations HERE,
    # with a diagnostic, rather than as a raw broadcast error inside the
    # first jitted evaluate (the header promises shape errors -> -1 at
    # set time). create_population runs the same check for populations
    # created after this registration.
    _check_expr_const_lens(obj, {p.genome_len for p in pga.populations})
    pga.set_objective(obj)
    _set_host_op(handle, "obj", False)


def _check_expr_const_lens(obj, genome_lens) -> None:
    """The one vector-constant/genome-length diagnostic, shared by
    set_objective_expr (existing populations) and create_population
    (populations added after the expression was installed). Scoped to
    EXPRESSION objectives (from_expression stamps ``.expression``):
    builtins also carry kernel_rowwise_consts, but setting one by name
    and creating a differently-shaped population afterward was always
    legal (the caller may install a matching objective later). The
    pinned length comes from the compiler (``pinned_genome_len``): it
    counts only constants that pair with the gene axis — a 1-D gather
    TABLE's length is an index domain, not a genome length."""
    if getattr(obj, "expression", None) is None:
        return
    n = getattr(obj, "pinned_genome_len", None)
    if n and genome_lens and n not in genome_lens:
        raise ValueError(
            f"expression uses a length-{n} vector constant but the "
            f"solver's population genome length is "
            f"{sorted(genome_lens)}"
        )


def set_objective_expr_const(handle: int, name: str, data: bytes) -> None:
    """Register/replace a named constant (raw little-endian float32
    bytes; one value = scalar, else a length-L vector) for use by a
    SUBSEQUENT set_objective_expr call on this solver."""
    arr = _expr_const_array(handle, name, data)
    if arr.size == 1:
        arr = arr.reshape(())
    _expr_consts.setdefault(handle, {})[name] = arr


def set_objective_expr_const2(
    handle: int, name: str, data: bytes, rows: int, cols: int
) -> None:
    """Register/replace a 2-D rows×cols constant (row-major float32
    bytes) — a per-locus gather table for the expression surface
    (``pga_set_objective_expr_const2``); the compiler rejects any other
    use of a 2-D constant."""
    arr = _expr_const_array(handle, name, data)
    if rows <= 0 or cols <= 0 or arr.size != rows * cols:
        raise ValueError(
            f"constant {name!r}: {arr.size} values do not fill "
            f"{rows}x{cols}"
        )
    _expr_consts.setdefault(handle, {})[name] = arr.reshape(rows, cols)


def _expr_const_array(handle: int, name: str, data: bytes) -> np.ndarray:
    """Shared validation for the expression-constant registrations."""
    from libpga_tpu.objectives.expr import _KEYWORDS

    _solver(handle)  # validate before mutating
    if not name.isidentifier():
        raise ValueError(f"constant name {name!r} is not an identifier")
    if name in _KEYWORDS:
        # Rejecting here keeps the solver's expression surface usable:
        # a registered shadow name would fail EVERY later
        # set_objective_expr on this solver, with no unregister API.
        raise ValueError(f"constant name {name!r} shadows a builtin name")
    if not data:
        raise ValueError(f"constant {name!r} has no values (n == 0)")
    return np.frombuffer(data, dtype=np.float32).copy()


def set_crossover_name(handle: int, name: str) -> None:
    """Install a BUILTIN crossover by name (``pga_set_crossover_name``):
    uniform / one_point / arithmetic / order. ``order`` is the
    uniqueness-preserving operator of the reference's flagship TSP
    driver (test3/test.cu:48-64) and runs IN-KERNEL (the VMEM
    visited-bitmask walk) — the path expressions cannot reach (the walk
    is sequential, not per-gene). uniform also runs in-kernel;
    one_point/arithmetic use the XLA path (prefer
    ``pga_set_crossover_expr`` for per-gene customs)."""
    from libpga_tpu.ops import crossover as _c

    ops = {
        "uniform": _c.uniform_crossover,
        "one_point": _c.one_point_crossover,
        "arithmetic": _c.arithmetic_crossover,
        "order": _c.order_preserving_crossover,
    }
    if name not in ops:
        raise ValueError(
            f"unknown crossover {name!r}; available: {sorted(ops)}"
        )
    _solver(handle).set_crossover(ops[name])
    _set_host_op(handle, "cross", False)


def set_mutate_name(handle: int, name: str, rate: float, sigma: float) -> None:
    """Install a BUILTIN mutation by name (``pga_set_mutate_name``):
    point / gaussian / swap, all in-kernel with runtime parameters
    (negative = the operator's default). ``swap`` is the permutation
    GA's operator (pairs with ``order`` crossover)."""
    from libpga_tpu.ops import mutate as _m

    if name == "point":
        op = _m.make_point_mutate(0.01 if rate < 0 else float(rate))
    elif name == "gaussian":
        op = _m.make_gaussian_mutate(
            0.1 if rate < 0 else float(rate),
            0.1 if sigma < 0 else float(sigma),
        )
    elif name == "swap":
        op = _m.make_swap_mutate(0.5 if rate < 0 else float(rate))
    else:
        raise ValueError(
            f"unknown mutation {name!r}; available: "
            f"['gaussian', 'point', 'swap']"
        )
    _solver(handle).set_mutate(op)
    _set_host_op(handle, "mut", False)


def set_objective_tsp_coords(
    handle: int, data: bytes, n_cities: int, penalty: float, genes_mode: int
) -> None:
    """Install a Euclidean TSP objective over city coordinates
    (``pga_set_objective_tsp_coords``): ``data`` is n_cities (x, y)
    float32 pairs. ``genes_mode`` nonzero selects
    ``duplicate_mode="genes"`` — the form whose evaluation fuses
    INTO the breed kernel with order crossover (the long-genome TSP
    path, BASELINE.md round 5); zero keeps the reference driver's
    ordered-pairs penalty semantics. This is how a C user runs the
    reference's test3 workload at device speed beyond its 110-city
    cap."""
    from libpga_tpu.objectives.classic import make_tsp_coords

    pga = _solver(handle)
    arr = np.frombuffer(data, dtype=np.float32)
    if n_cities <= 0 or arr.size != 2 * n_cities:
        raise ValueError(
            f"coords carry {arr.size} floats; expected 2*{n_cities}"
        )
    obj = make_tsp_coords(
        arr.reshape(n_cities, 2).copy(),
        duplicate_penalty=10_000.0 if penalty < 0 else float(penalty),
        duplicate_mode="genes" if genes_mode else "pairs",
    )
    pga.set_objective(obj)
    _set_host_op(handle, "obj", False)


#: Per-solver GP encoding installed by ``pga_gp_config`` — the context
#: ``pga_set_objective_sr`` builds its objective against.
_gp_cfgs: Dict[int, object] = {}


def gp_config(
    handle: int, max_nodes: int, n_vars: int, mutation_rate: float
) -> None:
    """``pga_gp_config``: switch a solver to tree-GP breeding (ISSUE
    11). Installs the postfix encoding (default constant/function
    tables), size-fair subtree crossover, and the standard chained
    subtree+point mutation (``mutation_rate`` drives the subtree half;
    negative = the operator default). Populations created AFTER this
    call with ``genome_len == 2 * max_nodes`` are initialized as
    well-formed random programs. Validation runs BEFORE any state
    changes — an invalid encoding leaves the solver's operators and
    any previous GP config intact (the round-15 error-surface
    pattern)."""
    from libpga_tpu.gp.encoding import GPConfig
    from libpga_tpu.gp.operators import (
        make_gp_mutate,
        make_subtree_crossover,
    )

    pga = _solver(handle)  # validate the handle first
    gp = GPConfig(max_nodes=int(max_nodes), n_vars=int(n_vars))
    rate = 0.4 if mutation_rate < 0 else float(mutation_rate)
    if not (0.0 <= rate <= 1.0):
        raise ValueError(f"mutation_rate {rate} not in [0, 1]")
    pga.set_crossover(make_subtree_crossover(gp))
    pga.set_mutate(make_gp_mutate(gp, subtree_rate=rate))
    _gp_cfgs[handle] = gp
    _set_host_op(handle, "cross", False)
    _set_host_op(handle, "mut", False)


def gp_create_population(handle: int, size: int) -> int:
    """``pga_gp_create_population``: a population of STRICTLY
    WELL-FORMED random postfix programs under the solver's installed
    GP encoding (``pga_gp_config`` first) — the GP analog of
    ``pga_create_population``'s RANDOM_POPULATION init. Returns the
    population index."""
    from libpga_tpu.gp.encoding import random_population

    pga = _solver(handle)
    gp = _gp_cfgs.get(handle)
    if gp is None:
        raise ValueError(
            "pga_gp_create_population requires pga_gp_config first"
        )
    h = pga.install_population(
        random_population(pga.next_key(), int(size), gp)
    )
    return h.index


def gp_n_vars(handle: int) -> int:
    """Input-variable count of the solver's installed GP encoding, or
    -1 — how the C shim sizes the ``pga_set_objective_sr`` X buffer
    before marshaling it."""
    gp = _gp_cfgs.get(handle)
    return -1 if gp is None else int(gp.n_vars)


def set_objective_sr(
    handle: int, xdata: bytes, ydata: bytes, n_samples: int
) -> None:
    """``pga_set_objective_sr``: install a symbolic-regression
    objective over an ``(n_samples, n_vars)`` float32 dataset
    (``gp/sr.symbolic_regression`` — fitness is -RMSE, higher better,
    evaluated by the fused stack machine on TPU and the XLA
    interpreter elsewhere). Requires ``pga_gp_config`` first (the
    encoding gives ``n_vars``); all validation precedes any state
    change, so an error leaves the previously installed objective
    intact."""
    from libpga_tpu.gp.sr import symbolic_regression

    pga = _solver(handle)
    gp = _gp_cfgs.get(handle)
    if gp is None:
        raise ValueError("pga_set_objective_sr requires pga_gp_config first")
    X = np.frombuffer(xdata, dtype=np.float32)
    y = np.frombuffer(ydata, dtype=np.float32)
    if n_samples <= 0 or X.size != n_samples * gp.n_vars:
        raise ValueError(
            f"X carries {X.size} floats; expected {n_samples} x "
            f"{gp.n_vars}"
        )
    if y.size != n_samples:
        raise ValueError(
            f"y carries {y.size} floats; expected {n_samples}"
        )
    obj = symbolic_regression(
        X.reshape(n_samples, gp.n_vars).copy(), y.copy(), gp=gp
    )
    pga.set_objective(obj)
    _set_host_op(handle, "obj", False)


def set_crossover_expr(handle: int, expr: str) -> None:
    """Install a DEVICE-SPEED custom crossover from an expression
    (``pga_set_crossover_expr``): compiles to the rowwise form the fused
    kernel's ``_deme_child`` evaluates on VMEM-resident parents — the
    TPU answer to the reference's ``__device__`` crossover pointers
    (``pga.h:48``; its TSP driver's custom operator, test3/test.cu:48-64,
    is the motivating workload). Unlike ``set_crossover_ptr``, the
    solver stays on the accelerator. Registered constants
    (``set_objective_expr_const``) are visible here too."""
    from libpga_tpu.ops.breed_expr import (
        _CROSS_VARS, crossover_from_expression,
    )

    pga = _solver(handle)
    op = crossover_from_expression(
        expr, **_scalar_vector_consts(handle, _CROSS_VARS)
    )
    _check_expr_const_lens(op, {p.genome_len for p in pga.populations})
    pga.set_crossover(op)
    _set_host_op(handle, "cross", False)


def set_mutate_expr(handle: int, expr: str, rate: float, sigma: float) -> None:
    """Install a DEVICE-SPEED custom mutation from an expression
    (``pga_set_mutate_expr``) — the custom-``__device__``-mutation
    analog (``pga.h:47``). ``rate``/``sigma`` bind the expression's
    runtime variables; negative values take the library defaults
    (0.01 / 0.0)."""
    from libpga_tpu.ops.breed_expr import _MUT_VARS, mutate_from_expression

    pga = _solver(handle)
    op = mutate_from_expression(
        expr,
        rate=0.01 if rate < 0 else float(rate),
        sigma=0.0 if sigma < 0 else float(sigma),
        **_scalar_vector_consts(handle, _MUT_VARS),
    )
    _check_expr_const_lens(op, {p.genome_len for p in pga.populations})
    pga.set_mutate(op)
    _set_host_op(handle, "mut", False)


def _scalar_vector_consts(handle: int, reserved=()) -> Dict[str, np.ndarray]:
    """The solver's registered constants minus 2-D gather tables
    (breeding expressions are strictly per-gene) and minus any name a
    breeding VARIABLE reserves (r, q, p1, rate, ...): constants register
    per solver across surfaces, so a name legal for objectives must not
    make every later set_*_expr fail its shadow check — and the parser
    resolves variables before constants anyway, so a colliding constant
    could never be referenced."""
    return {
        n: a
        for n, a in _expr_consts.get(handle, {}).items()
        if a.ndim <= 1 and n not in reserved
    }


def set_objective_ptr(handle: int, addr: int) -> None:
    """Install a host C objective ``float fn(gene*, unsigned)``.

    Wrapped through jax.pure_callback: genomes come to the host once per
    evaluation, the C function runs per individual. Matches the reference
    callback contract (pga.h:46) at host speed.
    """
    import jax
    import jax.numpy as jnp

    cfn = _OBJ_SIG(addr)
    _retained[handle].append(cfn)
    _set_host_op(handle, "obj", True)

    def host_eval(batch: np.ndarray) -> np.ndarray:
        batch = np.ascontiguousarray(batch, dtype=np.float32)
        out = np.empty(batch.shape[0], dtype=np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        lib = _rowloop_lib()
        if lib is not None:  # one crossing for the whole generation
            lib.pga_rowloop_obj(
                addr, batch.ctypes.data_as(fp), out.ctypes.data_as(fp),
                batch.shape[0], batch.shape[1],
            )
            return out
        n = ctypes.c_uint(batch.shape[1])
        for i in range(batch.shape[0]):
            out[i] = cfn(batch[i].ctypes.data_as(fp), n)
        return out

    def objective(genome):
        # Per-genome signature; the engine vmaps. pure_callback with
        # vmap_method="expand_dims" turns the vmap into ONE host call on
        # the whole (P, L) batch.
        return jax.pure_callback(
            lambda g: host_eval(g.reshape(1, -1) if g.ndim == 1 else g).reshape(
                () if g.ndim == 1 else g.shape[:1]
            ),
            jax.ShapeDtypeStruct((), jnp.float32),
            genome,
            vmap_method="expand_dims",
        )

    _solver(handle).set_objective(objective)


def set_mutate_ptr(handle: int, addr: int) -> None:
    """Install a host C mutation ``void fn(gene*, float* rand, unsigned)``
    (pga.h:47, in-place). addr == 0 restores the default."""
    import jax
    import jax.numpy as jnp

    pga = _solver(handle)
    if addr == 0:
        pga.set_mutate(None)
        _set_host_op(handle, "mut", False)
        return
    cfn = _MUT_SIG(addr)
    _retained[handle].append(cfn)
    _set_host_op(handle, "mut", True)

    def host_mut(batch: np.ndarray, rand: np.ndarray) -> np.ndarray:
        batch = np.ascontiguousarray(batch, dtype=np.float32).copy()
        rand = np.ascontiguousarray(rand, dtype=np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        lib = _rowloop_lib()
        if lib is not None:
            lib.pga_rowloop_mut(
                addr, batch.ctypes.data_as(fp), rand.ctypes.data_as(fp),
                batch.shape[0], batch.shape[1],
            )
            return batch
        n = ctypes.c_uint(batch.shape[1])
        for i in range(batch.shape[0]):
            cfn(batch[i].ctypes.data_as(fp), rand[i].ctypes.data_as(fp), n)
        return batch

    def mutate(genome, rand):
        return jax.pure_callback(
            lambda g, r: host_mut(
                g.reshape(1, -1) if g.ndim == 1 else g,
                r.reshape(1, -1) if r.ndim == 1 else r,
            ).reshape(g.shape),
            jax.ShapeDtypeStruct(genome.shape, jnp.float32),
            genome,
            rand,
            vmap_method="expand_dims",
        )

    pga.set_mutate(mutate)


def set_crossover_ptr(handle: int, addr: int) -> None:
    """Install a host C crossover
    ``void fn(gene* p1, gene* p2, gene* child, float* rand, unsigned)``
    (pga.h:48). addr == 0 restores the default."""
    import jax
    import jax.numpy as jnp

    pga = _solver(handle)
    if addr == 0:
        pga.set_crossover(None)
        _set_host_op(handle, "cross", False)
        return
    cfn = _CROSS_SIG(addr)
    _retained[handle].append(cfn)
    _set_host_op(handle, "cross", True)

    def host_cross(p1: np.ndarray, p2: np.ndarray, rand: np.ndarray) -> np.ndarray:
        p1 = np.ascontiguousarray(p1, dtype=np.float32)
        p2 = np.ascontiguousarray(p2, dtype=np.float32)
        rand = np.ascontiguousarray(rand, dtype=np.float32)
        child = np.zeros_like(p1)
        fp = ctypes.POINTER(ctypes.c_float)
        lib = _rowloop_lib()
        if lib is not None:
            lib.pga_rowloop_cross(
                addr, p1.ctypes.data_as(fp), p2.ctypes.data_as(fp),
                child.ctypes.data_as(fp), rand.ctypes.data_as(fp),
                p1.shape[0], p1.shape[1],
            )
            return child
        n = ctypes.c_uint(p1.shape[1])
        for i in range(p1.shape[0]):
            cfn(
                p1[i].ctypes.data_as(fp),
                p2[i].ctypes.data_as(fp),
                child[i].ctypes.data_as(fp),
                rand[i].ctypes.data_as(fp),
                n,
            )
        return child

    def crossover(p1, p2, rand):
        return jax.pure_callback(
            lambda a, b, r: host_cross(
                a.reshape(1, -1) if a.ndim == 1 else a,
                b.reshape(1, -1) if b.ndim == 1 else b,
                r.reshape(1, -1) if r.ndim == 1 else r,
            ).reshape(a.shape),
            jax.ShapeDtypeStruct(p1.shape, jnp.float32),
            p1,
            p2,
            rand,
            vmap_method="expand_dims",
        )

    pga.set_crossover(crossover)


def _handle_pop(handle: int, pop: int):
    from libpga_tpu.engine import PopulationHandle

    pga = _solver(handle)
    if not (0 <= pop < pga.num_populations):
        raise ValueError(f"invalid population index {pop}")
    return pga, PopulationHandle(pop)


def evaluate(handle: int, pop: int) -> None:
    pga, h = _handle_pop(handle, pop)
    with _exec_ctx(handle):
        pga.evaluate(h)


def evaluate_all(handle: int) -> None:
    with _exec_ctx(handle):
        _solver(handle).evaluate_all()


def set_selection(handle: int, kind: int, param: float) -> None:
    """Selection strategy for the improved ABI (``pga_set_selection``):
    kind indexes ``crossover_selection_type`` in pga_tpu.h (0 tournament,
    1 truncation, 2 linear_rank); ``param`` < 0 means the strategy
    default (τ 0.5 / pressure 2.0). Validation (and defaults) come from
    the same resolver both compute paths use."""
    import dataclasses

    from libpga_tpu.ops.select import resolve_selection

    pga = _solver(handle)
    name = _selection_name(kind)
    p = None if param < 0 else float(param)
    resolve_selection(name, p)  # raise before mutating solver state
    pga.config = dataclasses.replace(
        pga.config, selection=name, selection_param=p
    )


def _selection_name(kind: int) -> str:
    """Validate a C-enum selection id and return its kind name — the ONE
    range check + diagnostic shared by pga_set_selection and the
    pga_crossover* selection argument, so their error surfaces cannot
    drift."""
    from libpga_tpu.ops.select import SELECTION_KINDS

    if not 0 <= kind < len(SELECTION_KINDS):
        raise ValueError(
            f"unknown selection kind id {kind}; 0..{len(SELECTION_KINDS)-1}"
        )
    return SELECTION_KINDS[kind]


def _apply_selection_arg(handle: int, selection: int) -> None:
    """The reference ignores pga_crossover's selection argument
    (pga.cu:329, enum is a placeholder). The improved ABI honors
    NON-tournament values: they switch the solver's strategy at its
    default parameter (use pga_set_selection for an explicit
    τ/pressure). TOURNAMENT (0) — what every reference-style driver
    passes on each call — is left inert so it cannot clobber a strategy
    chosen via pga_set_selection; switch back explicitly with
    pga_set_selection(p, TOURNAMENT, -1). Out-of-range values raise
    (→ -1 through the ABI) — the same error surface as
    pga_set_selection, instead of silently behaving like the inert
    TOURNAMENT."""
    name = _selection_name(selection)
    if selection != 0:
        if _solver(handle).config.selection != name:
            set_selection(handle, selection, -1.0)


def crossover(handle: int, pop: int, selection: int) -> None:
    # Validate the handles BEFORE the selection side effect: a failed
    # call must not leave the solver on a different strategy.
    pga, h = _handle_pop(handle, pop)
    _apply_selection_arg(handle, selection)
    with _exec_ctx(handle):
        pga.crossover(h)


def crossover_all(handle: int, selection: int) -> None:
    pga = _solver(handle)
    _apply_selection_arg(handle, selection)
    with _exec_ctx(handle):
        pga.crossover_all()


def mutate(handle: int, pop: int) -> None:
    pga, h = _handle_pop(handle, pop)
    with _exec_ctx(handle):
        pga.mutate(h)


def mutate_all(handle: int) -> None:
    with _exec_ctx(handle):
        _solver(handle).mutate_all()


def swap_generations(handle: int, pop: int) -> None:
    pga, h = _handle_pop(handle, pop)
    pga.swap_generations(h)


def fill_random_values(handle: int, pop: int) -> None:
    pga, h = _handle_pop(handle, pop)
    pga.fill_random_values(h)


def migrate(handle: int, pct: float) -> None:
    _solver(handle).migrate(pct)


def migrate_between(handle: int, src: int, dst: int, pct: float) -> None:
    pga, hs = _handle_pop(handle, src)
    _, hd = _handle_pop(handle, dst)
    pga.migrate_between(hs, hd, pct)


def run(handle: int, n: int, has_target: int, target: float) -> int:
    pga = _solver(handle)
    with _exec_ctx(handle):
        return pga.run(n, target=target if has_target else None)


def run_islands(handle: int, n: int, m: int, pct: float) -> int:
    with _exec_ctx(handle):
        return _solver(handle).run_islands(n, m, pct)


def get_best(handle: int, pop: int) -> bytes:
    """Best genome as raw float32 little-endian bytes (len = 4*genome_len)."""
    pga, h = _handle_pop(handle, pop)
    return np.ascontiguousarray(pga.get_best(h), dtype=np.float32).tobytes()


def get_best_top(handle: int, pop: int, k: int) -> bytes:
    pga, h = _handle_pop(handle, pop)
    # The Python engine clamps k to the population size; a C caller has
    # no way to see the clamp and would read k rows out of a shorter
    # buffer — make an oversized request an error (C side returns NULL).
    size = pga.population(h).size
    if k > size:
        raise ValueError(f"top-k length {k} exceeds population size {size}")
    return np.ascontiguousarray(
        pga.get_best_top(h, k), dtype=np.float32
    ).tobytes()


def get_best_all(handle: int) -> bytes:
    return np.ascontiguousarray(
        _solver(handle).get_best_all(), dtype=np.float32
    ).tobytes()


def get_best_top_all(handle: int, k: int) -> bytes:
    pga = _solver(handle)
    total = sum(p.size for p in pga.populations)
    if k > total:  # same C-caller buffer contract as get_best_top
        raise ValueError(f"top-k length {k} exceeds total population {total}")
    return np.ascontiguousarray(
        pga.get_best_top_all(k), dtype=np.float32
    ).tobytes()


def genome_len(handle: int, pop: int) -> int:
    pga, h = _handle_pop(handle, pop)
    return pga.population(h).genome_len


# --------------------------------------------------------------- serving
#
# Async run submission (pga_submit / pga_poll / pga_await): requests
# from every solver in the process flow through ONE module-global
# RunQueue, bucketed by exact shape signature, so same-shaped solvers
# share compiled mega-runs (serving/). A ticket is an integer handle;
# pga_await installs the finished run into the solver's population —
# the same state transition pga_run performs — and releases the ticket.

_serving_queue = None
_serving_execs: Dict[int, object] = {}
_tickets: Dict[int, tuple] = {}  # id -> (handle, pop_index, ticket, pga)
_next_ticket = 1


def _get_serving_queue():
    global _serving_queue
    if _serving_queue is None:
        from libpga_tpu.config import ServingConfig
        from libpga_tpu.serving.queue import RunQueue

        _serving_queue = RunQueue(serving=ServingConfig())
    return _serving_queue


def serving_config(max_batch: int, max_wait_ms: float) -> None:
    """Reconfigure the process-global submission queue
    (``pga_serving_config``). Flushes pending work first so in-flight
    tickets complete under the settings they were admitted with."""
    global _serving_queue
    from libpga_tpu.config import ServingConfig

    cfg = ServingConfig(
        max_batch=int(max_batch), max_wait_ms=float(max_wait_ms)
    )
    if _serving_queue is not None:
        _serving_queue.close()
    from libpga_tpu.serving.queue import RunQueue

    _serving_queue = RunQueue(serving=cfg)


def set_tuning_db(path: str) -> int:
    """``pga_set_tuning_db``: install (path) or clear ("") the
    process-global kernel tuning database (``libpga_tpu/tuning``,
    ISSUE 10). Eager load — a missing/torn/schema-mismatched file
    raises here (→ -1 through the ABI) and leaves the previous
    installation in place."""
    from libpga_tpu.tuning import set_tuning_db as _set

    _set(path or None)
    return 0


def autotune(
    size: int, genome_len: int, objective: str, budget: int,
    db_path: str, seed: int,
) -> int:
    """``pga_autotune``: run the evolutionary kernel autotuner for one
    (size, genome_len) signature of a named builtin objective and merge
    the verdict into the database at ``db_path`` (atomic replace).
    Returns the number of distinct configurations measured. The C
    surface keeps the tuner's defaults for the measurement protocol;
    the Python CLI (``tools/autotune.py``) exposes the full knob set."""
    from libpga_tpu.tuning import tuner as _tuner

    entry = _tuner.autotune(
        int(size), int(genome_len), objective=str(objective),
        settings=_tuner.TunerSettings(
            budget=int(budget), seed=int(seed),
        ),
        db_path=str(db_path),
    )
    return int(entry.evaluated)


def _serving_executor(handle: int):
    """A BatchedRuns matching the solver's current objective/operators.

    Rebuilt whenever the identity-relevant pieces change; executors for
    equal configurations produce equal signatures, so distinct solvers
    still share buckets and compiled programs."""
    from libpga_tpu.serving.batch import BatchedRuns

    pga = _solver(handle)
    if _host_ops.get(handle):
        raise ValueError(
            "pga_submit: host-pointer callbacks cannot be batch-served "
            "(they pin the solver to per-host-call execution) — use a "
            "named/expression operator, or pga_run"
        )
    obj = pga._require_objective()
    kind = pga._mutate_kind()
    if kind not in ("point", "gaussian", "swap"):
        raise ValueError(
            "pga_submit requires a builtin mutation kind "
            "(point/gaussian/swap); expression mutations run via pga_run"
        )
    ident = (obj, pga._crossover, kind, pga.config)
    cached = _serving_execs.get(handle)
    if cached is not None and cached[0] == ident:
        return cached[1]
    ex = BatchedRuns(
        obj, config=pga.config, crossover=pga._crossover, mutate_kind=kind
    )
    _serving_execs[handle] = (ident, ex)
    return ex


def submit(
    handle: int, n: int, has_target: int, target: float,
    tenant: str = "",
) -> int:
    """``pga_submit``: admit an async run of the solver's FIRST
    population (the population pga_run operates on) and return a
    ticket id (> 0). ``tenant`` attributes the ticket (ISSUE 14);
    the empty string — the C side's NULL — submits as ``anon``."""
    global _next_ticket
    from libpga_tpu.serving.batch import RunRequest

    pga = _solver(handle)
    if pga.num_populations == 0:
        raise ValueError("no populations")
    from libpga_tpu.engine import PopulationHandle

    ex = _serving_executor(handle)
    pop = pga.population(PopulationHandle(0))
    mp = np.asarray(pga._mutate_params())
    req = RunRequest(
        size=pop.size,
        genome_len=pop.genome_len,
        n=int(n),
        key=pga.next_key(),
        genomes=pop.genomes,
        target=float(target) if has_target else None,
        mutation_rate=float(mp[0, 0]),
        mutation_sigma=float(mp[0, 1]),
    )
    ticket = _get_serving_queue().submit(
        req, executor=ex, tenant=tenant or None
    )
    tid = _next_ticket
    _next_ticket += 1
    _tickets[tid] = (handle, 0, ticket, pga)
    return tid


def poll(ticket_id: int) -> int:
    """``pga_poll``: 1 once the ticket's mega-run has launched and
    assigned its result, else 0."""
    entry = _tickets.get(ticket_id)
    if entry is None:
        raise ValueError(f"invalid ticket {ticket_id}")
    return 1 if entry[2].poll() else 0


def _await_install(ticket_id: int):
    """Shared body of ``pga_await`` / ``pga_await_ex``: block for the
    run, install its final population into the solver (the pga_run
    state transition), release the ticket. Returns ``(gens, ticket)``
    — the ticket keeps its latency breakdown after release."""
    from libpga_tpu.population import Population

    entry = _tickets.pop(ticket_id, None)
    if entry is None:
        raise ValueError(f"invalid ticket {ticket_id}")
    handle, pop_index, ticket, pga = entry
    result = ticket.result(timeout=600.0)
    gens = result.generations
    if _solvers.get(handle) is pga:  # solver may have been deinit'd
        pga._populations[pop_index] = Population(
            genomes=result.genomes, scores=result.scores
        )
        pga._staged[pop_index] = None
        pga._history[pop_index] = result.history
    return gens, ticket


def await_ticket(ticket_id: int) -> int:
    """``pga_await``: block for the run, install its final population
    into the solver (the pga_run state transition), release the ticket,
    and return the generations executed."""
    return _await_install(ticket_id)[0]


def await_ticket_ex(ticket_id: int) -> bytes:
    """``pga_await_ex``: like ``pga_await``, additionally reporting the
    ticket's latency breakdown. Returns five float32s: generations,
    then queue_wait / execute / readback / end-to-end milliseconds
    (NaN for spans the lifecycle never reached)."""
    gens, ticket = _await_install(ticket_id)
    lat = ticket.latency()
    vals = [float(gens)] + [
        float("nan") if lat[k] is None else float(lat[k])
        for k in ("queue_wait_ms", "execute_ms", "readback_ms", "e2e_ms")
    ]
    return np.asarray(vals, dtype=np.float32).tobytes()


# Retry-once parking lot for the sized-snapshot entry points (ISSUE 12
# satellite): snapshot kind -> last rendering that did not fit the
# caller's buffer (including the cap=0 size query).
_snapshot_pending: Dict[str, bytes] = {}


def _sized_snapshot(kind: str, render, cap: int) -> bytes:
    """Size-query hardening for the ``pga_*_snapshot`` entry points.

    These snapshots are LIVE — they can grow between a caller's size
    query and its fill call (new sessions, new metric series, even the
    timestamp width). Whenever a call cannot be satisfied by ``cap``
    (the cap=0 size query included), the rendered bytes are PARKED, and
    the caller's immediate retry with a sufficient cap receives exactly
    the parked snapshot instead of a fresh (possibly larger) rendering
    — which is what makes the header's retry-ONCE contract a guarantee
    rather than a hope. A retry with a still-too-small cap re-parks the
    fresh rendering, preserving the invariant for the next retry."""
    cap = int(cap)
    parked = _snapshot_pending.pop(kind, None)
    if parked is not None and cap > len(parked):
        return parked
    data = render()
    if cap <= len(data):
        _snapshot_pending[kind] = data
    return data


def metrics_snapshot_json(cap: int = 0) -> bytes:
    """``pga_metrics_snapshot``: the process-global metrics registry
    snapshot (counters, gauges, histograms with p50/p95/p99) as UTF-8
    JSON — the C-side export of the ISSUE 6 observability layer.
    ``cap`` is the caller's buffer capacity (retry-once contract, see
    :func:`_sized_snapshot`)."""
    import json

    from libpga_tpu.utils import metrics as _metrics

    return _sized_snapshot(
        "metrics",
        lambda: json.dumps(_metrics.REGISTRY.snapshot()).encode("utf-8"),
        cap,
    )


def program_report_snapshot_json(handle: int, pop: int, cap: int = 0) -> bytes:
    """``pga_program_report_snapshot``: the roofline-attributed program
    report for one population's resolved program (ISSUE 17 —
    ``PGA.program_report`` / ``libpga_tpu/perf/cost``) as UTF-8 JSON.
    Parked per (solver, population), so concurrent callers reporting on
    different populations can't swap each other's retry bytes. ``cap``
    is the caller's buffer capacity (retry-once contract,
    :func:`_sized_snapshot`)."""
    import json

    pga, h = _handle_pop(handle, pop)

    def render() -> bytes:
        with _exec_ctx(handle):
            report = pga.program_report(h)
        return json.dumps(report, default=str).encode("utf-8")

    return _sized_snapshot(f"program_report/{handle}/{pop}", render, cap)


# ------------------------------------------------------------------ fleet

_fleet = None
_fleet_handles: Dict[int, object] = {}
_next_fleet_ticket = 1


def fleet_start(
    spool_dir: str, objective: str, n_workers: int, max_batch: int,
    max_wait_ms: float, ring: int = 1, coordinators: int = 1,
) -> int:
    """``pga_fleet_start``: create (or replace) the process-global
    cross-process serving fleet (``serving/fleet.py``) on ``spool_dir``
    and spawn ``n_workers`` worker processes. Replacing an existing
    fleet closes it first (drain + monitor stop). ``ring`` != 0 enables
    the shared-memory ticket ring fast path (ISSUE 18); 0 forces
    pure-spool polling coordination (identical results either way).
    ``coordinators`` > 1 joins the spool's leader election (ISSUE 20):
    this process becomes a candidate — leader or hot standby — with
    journaled intake and epoch-fenced failover; 1 keeps the pre-HA
    single-coordinator spool format byte-for-byte."""
    global _fleet
    from libpga_tpu.config import FleetConfig
    from libpga_tpu.serving.fleet import Fleet

    if _fleet is not None:
        _fleet.close()
        _fleet = None
    _fleet = Fleet(
        spool_dir, objective,
        fleet=FleetConfig(
            n_workers=int(n_workers), max_batch=int(max_batch),
            max_wait_ms=float(max_wait_ms), ring=bool(ring),
            coordinators=max(int(coordinators), 1),
        ),
    )
    _fleet.start()
    return 0


def fleet_submit(
    size: int, genome_len: int, n: int, seed: int,
    checkpoint_every: int, priority: int = -1, tenant: str = "",
) -> int:
    """``pga_fleet_submit``: admit one ticket to the process-global
    fleet; returns a ticket id (> 0). ``checkpoint_every`` > 0 makes
    the ticket supervised (drain-safe at that cadence). ``priority``
    picks the scheduling lane (ISSUE 15; negative = the tenant
    policy's default). ``tenant`` attributes it (ISSUE 14; empty
    string = ``anon``). A tenant at its quota raises
    :class:`~libpga_tpu.serving.scheduler.QuotaExceeded` — the C side
    sees a NULL ticket with the installed fleet state intact."""
    global _next_fleet_ticket
    from libpga_tpu.serving.fleet import FleetTicket

    if _fleet is None:
        raise ValueError("no fleet: call pga_fleet_start first")
    handle = _fleet.submit(FleetTicket(
        size=int(size), genome_len=int(genome_len), n=int(n),
        seed=int(seed), checkpoint_every=int(checkpoint_every),
        priority=None if priority < 0 else int(priority),
        tenant=tenant or None,
    ))
    tid = _next_fleet_ticket
    _next_fleet_ticket += 1
    _fleet_handles[tid] = handle
    return tid


def fleet_tenant_policy(
    tenant: str, weight: float, max_pending: int, priority: int,
) -> int:
    """``pga_fleet_tenant_policy``: install or replace one tenant's
    scheduling policy (ISSUE 15) on the process-global fleet —
    deficit-round-robin ``weight``, submission quota ``max_pending``
    (<= 0 = unlimited), default priority lane. Invalid values raise
    (the C side sees -1) and leave the installed policies intact."""
    from libpga_tpu.config import TenantPolicy

    if _fleet is None:
        raise ValueError("no fleet: call pga_fleet_start first")
    _fleet.set_tenant_policy(tenant, TenantPolicy(
        weight=float(weight),
        max_pending=None if max_pending <= 0 else int(max_pending),
        priority=int(priority),
    ))
    return 0


def fleet_await(ticket_id: int, timeout_s: float) -> bytes:
    """``pga_fleet_await``: block for one fleet ticket and release it.
    Returns two float32s: generations executed, best score."""
    handle = _fleet_handles.pop(int(ticket_id), None)
    if handle is None:
        raise ValueError(f"invalid fleet ticket {ticket_id}")
    res = handle.result(timeout=float(timeout_s) if timeout_s > 0 else None)
    return np.asarray(
        [float(res.generations), float(res.best_score)], dtype=np.float32
    ).tobytes()


def fleet_await_ex(ticket_id: int, timeout_s: float) -> bytes:
    """``pga_fleet_await_ex``: like ``fleet_await``, additionally
    reporting the ticket's CROSS-PROCESS latency breakdown (ISSUE 9).
    Returns eight float32s: generations, best score, then the six
    breakdown values intake / spool_wait / execute / publish /
    readback / e2e in milliseconds (NaN for spans tracing-off or an
    incomplete lifecycle suppressed)."""
    handle = _fleet_handles.pop(int(ticket_id), None)
    if handle is None:
        raise ValueError(f"invalid fleet ticket {ticket_id}")
    res = handle.result(timeout=float(timeout_s) if timeout_s > 0 else None)
    lat = handle.latency()
    vals = [float(res.generations), float(res.best_score)] + [
        float("nan") if lat[k] is None else float(lat[k])
        for k in ("intake_ms", "spool_wait_ms", "execute_ms",
                  "publish_ms", "readback_ms", "e2e_ms")
    ]
    return np.asarray(vals, dtype=np.float32).tobytes()


def fleet_metrics_snapshot_json(cap: int = 0) -> bytes:
    """``pga_fleet_metrics_snapshot``: the MERGED fleet metrics
    snapshot — every worker's latest spool flush + the coordinator's
    live registry, per-process labels, aggregate histograms — as UTF-8
    JSON. ``cap`` is the caller's buffer capacity (retry-once
    contract, see :func:`_sized_snapshot`)."""
    import json

    if _fleet is None:
        raise ValueError("no fleet: call pga_fleet_start first")
    return _sized_snapshot(
        "fleet_metrics",
        lambda: json.dumps(
            _fleet.merged_snapshot(), default=str
        ).encode("utf-8"),
        cap,
    )


def fleet_leader_snapshot_json(cap: int = 0) -> bytes:
    """``pga_fleet_leader_snapshot``: the spool's leadership block
    (``serving.ha.leadership_snapshot`` — leader pid/liveness, election
    epoch, lease age, standby count, last-failover timestamp;
    ``{"enabled": false}`` under ``coordinators=1``) as UTF-8 JSON.
    ``cap`` is the caller's buffer capacity (retry-once contract, see
    :func:`_sized_snapshot`)."""
    import json

    from libpga_tpu.serving import ha as _ha
    from libpga_tpu.serving.fleet import load_spool_metrics

    if _fleet is None:
        raise ValueError("no fleet: call pga_fleet_start first")

    def render() -> bytes:
        payloads, _skipped = load_spool_metrics(_fleet.spool)
        snap = _ha.leadership_snapshot(_fleet.spool, payloads)
        return json.dumps(snap, default=str).encode("utf-8")

    return _sized_snapshot("fleet_leader", render, cap)


def fleet_drain() -> int:
    """``pga_fleet_drain``: SIGTERM-drain the fleet's workers
    (checkpoint + lease return); returns workers drained. The fleet
    stays open — ``pga_fleet_start`` on the same spool resumes."""
    if _fleet is None:
        raise ValueError("no fleet: call pga_fleet_start first")
    return int(_fleet.drain())


def fleet_close() -> int:
    """``pga_fleet_close``: drain and close the process-global fleet."""
    global _fleet
    if _fleet is None:
        return 0
    _fleet.close()
    _fleet = None
    _fleet_handles.clear()
    return 0


# -------------------------------------------------- streaming (ISSUE 12)

_streaming_sessions: Dict[int, object] = {}
_next_session_handle = 1
_streaming_pool = None


def _session_pool():
    """The process-global warm engine pool the C ABI's sessions share —
    a second pga_session_open of one signature compiles 0 programs."""
    global _streaming_pool
    if _streaming_pool is None:
        from libpga_tpu.config import PGAConfig
        from libpga_tpu.streaming import EnginePool

        _streaming_pool = EnginePool(config=PGAConfig())
    return _streaming_pool


def _session(handle: int):
    session = _streaming_sessions.get(int(handle))
    if session is None:
        raise ValueError(f"invalid session handle {handle}")
    return session


def session_open(
    objective: str, size: int, genome_len: int, seed: int,
    tenant: str = "",
) -> int:
    """``pga_session_open``: a warm streaming session over a named
    builtin objective. Returns a session handle (> 0). ``tenant``
    attributes the session and its warm-pool traffic (ISSUE 14;
    empty string = ``anon``)."""
    global _next_session_handle
    session = _session_pool().acquire(
        objective, int(size), int(genome_len), seed=int(seed),
        tenant=tenant or None,
    )
    handle = _next_session_handle
    _next_session_handle += 1
    _streaming_sessions[handle] = session
    return handle


def session_genome_len(handle: int) -> int:
    """Genome length of a session — the C shim reads it back to size
    tell() marshal buffers (the ``gp_n_vars`` pattern)."""
    return int(_session(handle).genome_len)


def session_ask(handle: int, k: int) -> bytes:
    """``pga_session_ask``: k bred candidate genomes as raw float32
    bytes (k * genome_len values, row-major)."""
    return np.ascontiguousarray(
        _session(handle).ask(int(k)), dtype=np.float32
    ).tobytes()


def session_tell(handle: int, genomes: bytes, fitness: bytes, k: int) -> int:
    """``pga_session_tell``: fold k externally evaluated candidates in
    at the next generation boundary."""
    session = _session(handle)
    g = np.frombuffer(genomes, dtype=np.float32).reshape(
        int(k), session.genome_len
    )
    f = np.frombuffer(fitness, dtype=np.float32)[: int(k)]
    session.tell(g, f)
    return 0


def session_step(handle: int, n: int, has_target: int, target: float) -> int:
    """``pga_session_step``: advance n generations on the internal
    objective (folding pending tells); returns generations executed."""
    return int(_session(handle).step(
        int(n), target=float(target) if has_target else None
    ))


def session_best(handle: int) -> bytes:
    """``pga_session_best``: float32 [best_score, genome...] of the
    current population."""
    genome, score = _session(handle).best()
    return np.concatenate(
        [np.asarray([score], np.float32), genome.astype(np.float32)]
    ).tobytes()


def session_suspend(handle: int, path: str) -> int:
    """``pga_session_suspend``: durably persist the session (atomic
    checkpoint + sidecars); the handle stays usable."""
    _session(handle).suspend(path)
    return 0


def session_resume(path: str, objective: str) -> int:
    """``pga_session_resume``: restore a suspended session
    bit-identically. ``objective`` may be empty to use the name
    recorded at suspend time."""
    global _next_session_handle
    from libpga_tpu.streaming import EvolutionSession

    session = EvolutionSession.resume(path, objective=objective or None)
    handle = _next_session_handle
    _next_session_handle += 1
    _streaming_sessions[handle] = session
    return handle


def session_close(handle: int) -> int:
    """``pga_session_close``: release the session's engine back to the
    process-global warm pool (the population is dropped — suspend first
    to keep it)."""
    session = _streaming_sessions.pop(int(handle), None)
    if session is None:
        return -1
    if getattr(session, "_pool", None) is not None:
        _session_pool().release(session)
    return 0


def session_snapshot_json(cap: int = 0) -> bytes:
    """``pga_session_snapshot``: the streaming layer's state — one
    record per open session (id, shape, generations done, pending
    tells, last known best) plus the warm-pool counters — as UTF-8
    JSON. Same retry-once size-query contract as
    ``pga_metrics_snapshot`` (:func:`_sized_snapshot`); this snapshot
    GROWS with every opened session, which is exactly the race the
    contract exists for."""
    import json

    def render() -> bytes:
        sessions = []
        for handle, s in sorted(_streaming_sessions.items()):
            import jax.numpy as jnp

            pop = s.population()
            best = float(jnp.max(pop.scores))
            sessions.append({
                "handle": handle,
                "session": s.sid,
                "tenant": s.tenant,
                "population_size": s.size,
                "genome_len": s.genome_len,
                "gens_done": s.gens_done,
                "pending_tells": s.pending_tells,
                "best": best if np.isfinite(best) else None,
            })
        return json.dumps({
            "sessions": sessions,
            "pool": _session_pool().stats(),
        }).encode("utf-8")

    return _sized_snapshot("session", render, cap)


# ------------------------------------------------------------ robustness


def set_fault_plan(spec: str) -> None:
    """``pga_set_fault_plan``: install (or clear) the process-global
    fault-injection plan from a JSON spec — the chaos driver's entry
    point (``robustness/faults``).

    Spec forms:
      - ``""`` / ``"[]"`` / ``"null"`` / ``"off"``: clear the plan;
      - a JSON object: one plan — ``{"site": ..., "kind": "raise"|"nan",
        "at_call_n": N | "probability": p, "times": M|null}``;
      - a JSON array of such objects;
      - ``{"seed": S, "plans": [...]}`` to set the registry's PRNG seed
        for probability-triggered plans.

    The parsing lives in ``faults.install_spec`` — the same transport
    the fleet worker's ``PGA_FAULT_SPEC`` environment hook uses.
    """
    from libpga_tpu.robustness import faults

    faults.install_spec(spec)


def supervised_run(
    handle: int, n: int, checkpoint_every: int, max_retries: int,
    checkpoint_path: str, resume: int,
) -> int:
    """``pga_supervised_run``: run the solver under the supervisor
    (``robustness/supervisor``) — retry with exponential backoff,
    auto-checkpoint every ``checkpoint_every`` generations to
    ``checkpoint_path`` (empty string = no durability), and
    ``resume`` != 0 restores the checkpoint + progress sidecar before
    running. Returns generations completed toward ``n`` (including
    resumed progress); -1 through the ABI on error."""
    from libpga_tpu.robustness.supervisor import RetryPolicy
    from libpga_tpu.robustness.supervisor import supervised_run as _sr

    pga = _solver(handle)
    with _exec_ctx(handle):
        report = _sr(
            pga,
            int(n),
            checkpoint_path=checkpoint_path or None,
            checkpoint_every=int(checkpoint_every),
            retry=RetryPolicy(max_retries=int(max_retries)),
            resume=bool(resume),
        )
    return report.generations


# ------------------------------------------------------------- telemetry


def set_telemetry(handle: int, max_gens: int) -> None:
    """``pga_set_telemetry``: enable the in-run per-generation history
    with a ``max_gens``-row on-device buffer (0 disables). Subsequent
    ``pga_run``/``pga_run_islands`` calls record best/mean/std fitness, a
    diversity proxy, and a stall counter per generation, readable via
    ``pga_get_history`` — the C-side view of ``PGA.history``."""
    import dataclasses

    from libpga_tpu.utils.telemetry import TelemetryConfig

    pga = _solver(handle)
    tel = (
        None if max_gens <= 0
        else TelemetryConfig(history_gens=int(max_gens))
    )
    if pga.config.telemetry != tel:
        pga.config = dataclasses.replace(pga.config, telemetry=tel)


def set_pop_shards(handle: int, shards: int) -> None:
    """``pga_set_pop_shards``: split subsequent ``pga_run`` calls'
    population axis across ``shards`` mesh devices
    (``parallel/shard_pop.py``); 1 restores the unsharded
    byte-identical path. Validation of the population-size
    admissibility (``shards² | pop``, shards <= devices) happens at
    the next run, where the shape is known — an out-of-range value
    here fails fast."""
    import dataclasses

    if shards < 1:
        raise ValueError("pop_shards must be >= 1")
    pga = _solver(handle)
    if pga.config.pop_shards != int(shards):
        pga.config = dataclasses.replace(
            pga.config, pop_shards=int(shards)
        )


def history_cols() -> int:
    from libpga_tpu.utils.telemetry import NUM_STATS

    return NUM_STATS


def history_rows(handle: int, pop: int) -> int:
    """Recorded generation rows for the population's last telemetry run
    (0 when telemetry was off or no run has happened)."""
    pga, h = _handle_pop(handle, pop)
    hist = pga.history(h)
    return 0 if hist is None else len(hist)


def get_history(handle: int, pop: int) -> bytes:
    """History rows as raw float32 little-endian bytes, row-major
    ``rows x history_cols()`` in HISTORY_COLUMNS order (best, mean, std,
    diversity, stall). Empty bytes when no history is recorded."""
    pga, h = _handle_pop(handle, pop)
    hist = pga.history(h)
    if hist is None:
        return b""
    import numpy as _np

    rows = _np.stack(
        [hist.as_dict()[c].astype(_np.float32) for c in hist.columns],
        axis=1,
    ) if len(hist) else _np.zeros((0, history_cols()), dtype=_np.float32)
    return _np.ascontiguousarray(rows, dtype=_np.float32).tobytes()
