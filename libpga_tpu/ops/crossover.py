"""Crossover operators.

All operators are per-individual pure functions with signature
``(p1, p2, rand) -> child`` where ``p1``/``p2``/``child`` are ``(L,)`` gene
vectors and ``rand`` is an ``(L,)`` uniform [0,1) vector — the functional
equivalent of the reference callback
``void (*crossover_f)(gene*, gene*, gene* child, float* rand, unsigned)``
(``include/pga.h:48``). The engine vmaps them across the population.

Custom crossovers are plain Python functions with the same signature; no
device-function-pointer plumbing (``cudaMemcpyFromSymbol``) is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def uniform_crossover(p1: jax.Array, p2: jax.Array, rand: jax.Array) -> jax.Array:
    """Per-gene coin flip: ``rand[i] > 0.5 ? p1[i] : p2[i]``.

    Semantics of the reference default ``__default_crossover``
    (``src/pga.cu:135-143``).
    """
    return jnp.where(rand > 0.5, p1, p2)


# Already elementwise: the identical expression is the whole-population
# implementation (see the operator protocol in ops/step.py).
uniform_crossover.batched = uniform_crossover


def one_point_crossover(p1: jax.Array, p2: jax.Array, rand: jax.Array) -> jax.Array:
    """Single cut point drawn from ``rand[0]``; prefix from p1, suffix from p2."""
    L = p1.shape[0]
    cut = jnp.floor(rand[0] * L).astype(jnp.int32)
    pos = jnp.arange(L)
    return jnp.where(pos < cut, p1, p2)


def _one_point_batched(p1, p2, rand):
    L = p1.shape[1]
    cut = jnp.floor(rand[:, 0] * L).astype(jnp.int32)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    return jnp.where(pos < cut[:, None], p1, p2)


one_point_crossover.batched = _one_point_batched
one_point_crossover.rand_cols = 1


def arithmetic_crossover(p1: jax.Array, p2: jax.Array, rand: jax.Array) -> jax.Array:
    """Per-gene convex blend ``a*p1 + (1-a)*p2`` with ``a = rand`` (real-coded GAs)."""
    return rand * p1 + (1.0 - rand) * p2


arithmetic_crossover.batched = arithmetic_crossover


def order_preserving_crossover(
    p1: jax.Array, p2: jax.Array, rand: jax.Array
) -> jax.Array:
    """Uniqueness-preserving crossover for permutation-coded genomes.

    Reproduces the semantics of the reference TSP driver's custom crossover
    (``test3/test.cu:48-64``): walk the genome left to right; take ``p1[i]``
    if the city it decodes to is unvisited, else ``p2[i]`` if that city is
    unvisited, else fall back to the raw random value ``rand[i]``. Cities
    decode as ``int(g*L)`` with genes in [0,1).

    The reference implements this as a sequential per-thread loop over a
    ``visited`` table — inherently data-dependent. TPU-natively it is a
    ``lax.scan`` over gene positions carrying a one-hot visited vector;
    under ``vmap`` the scan body is batched across the population, so each
    scan step is a wide vector op rather than a scalar loop.
    """
    L = p1.shape[0]
    c1 = jnp.clip(jnp.floor(p1 * L).astype(jnp.int32), 0, L - 1)
    c2 = jnp.clip(jnp.floor(p2 * L).astype(jnp.int32), 0, L - 1)

    def body(visited, xs):
        g1, g2, city1, city2, r = xs
        take1 = ~visited[city1]
        take2 = (~take1) & (~visited[city2])
        gene = jnp.where(take1, g1, jnp.where(take2, g2, r))
        city = jnp.where(take1, city1, city2)
        mark = take1 | take2
        visited = visited.at[city].set(visited[city] | mark)
        return visited, gene

    visited0 = jnp.zeros((L,), dtype=jnp.bool_)
    _, child = jax.lax.scan(body, visited0, (p1, p2, c1, c2, rand))
    return child
