"""Crossover operators.

All operators are per-individual pure functions with signature
``(p1, p2, rand) -> child`` where ``p1``/``p2``/``child`` are ``(L,)`` gene
vectors and ``rand`` is an ``(L,)`` uniform [0,1) vector — the functional
equivalent of the reference callback
``void (*crossover_f)(gene*, gene*, gene* child, float* rand, unsigned)``
(``include/pga.h:48``). The engine vmaps them across the population.

Custom crossovers are plain Python functions with the same signature; no
device-function-pointer plumbing (``cudaMemcpyFromSymbol``) is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def uniform_crossover(p1: jax.Array, p2: jax.Array, rand: jax.Array) -> jax.Array:
    """Per-gene coin flip: ``rand[i] > 0.5 ? p1[i] : p2[i]``.

    Semantics of the reference default ``__default_crossover``
    (``src/pga.cu:135-143``).
    """
    return jnp.where(rand > 0.5, p1, p2)


# Already elementwise: the identical expression is the whole-population
# implementation (see the operator protocol in ops/step.py).
uniform_crossover.batched = uniform_crossover


def one_point_crossover(p1: jax.Array, p2: jax.Array, rand: jax.Array) -> jax.Array:
    """Single cut point drawn from ``rand[0]``; prefix from p1, suffix from p2."""
    L = p1.shape[0]
    cut = jnp.floor(rand[0] * L).astype(jnp.int32)
    pos = jnp.arange(L)
    return jnp.where(pos < cut, p1, p2)


def _one_point_batched(p1, p2, rand):
    L = p1.shape[1]
    cut = jnp.floor(rand[:, 0] * L).astype(jnp.int32)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    return jnp.where(pos < cut[:, None], p1, p2)


one_point_crossover.batched = _one_point_batched
one_point_crossover.rand_cols = 1


def arithmetic_crossover(p1: jax.Array, p2: jax.Array, rand: jax.Array) -> jax.Array:
    """Per-gene convex blend ``a*p1 + (1-a)*p2`` with ``a = rand`` (real-coded GAs)."""
    return rand * p1 + (1.0 - rand) * p2


arithmetic_crossover.batched = arithmetic_crossover


def order_preserving_crossover(
    p1: jax.Array, p2: jax.Array, rand: jax.Array
) -> jax.Array:
    """Uniqueness-preserving crossover for permutation-coded genomes.

    Reproduces the semantics of the reference TSP driver's custom crossover
    (``test3/test.cu:48-64``): walk the genome left to right; take ``p1[i]``
    if the city it decodes to is unvisited, else ``p2[i]`` if that city is
    unvisited, else fall back to the raw random value ``rand[i]``. Cities
    decode as ``int(g*L)`` with genes in [0,1).

    The reference implements this as a sequential per-thread loop over a
    ``visited`` table — inherently data-dependent. TPU-natively it is a
    ``lax.scan`` over gene positions carrying a one-hot visited vector;
    under ``vmap`` the scan body is batched across the population, so each
    scan step is a wide vector op rather than a scalar loop.
    """
    L = p1.shape[0]
    c1 = jnp.clip(jnp.floor(p1 * L).astype(jnp.int32), 0, L - 1)
    c2 = jnp.clip(jnp.floor(p2 * L).astype(jnp.int32), 0, L - 1)

    def body(visited, xs):
        g1, g2, city1, city2, r = xs
        take1 = ~visited[city1]
        take2 = (~take1) & (~visited[city2])
        gene = jnp.where(take1, g1, jnp.where(take2, g2, r))
        city = jnp.where(take1, city1, city2)
        mark = take1 | take2
        visited = visited.at[city].set(visited[city] | mark)
        return visited, gene

    visited0 = jnp.zeros((L,), dtype=jnp.bool_)
    _, child = jax.lax.scan(body, visited0, (p1, p2, c1, c2, rand))
    return child


def _order_preserving_batched(p1, p2, rand):
    """Whole-population order-preserving crossover without gathers.

    Identical semantics to :func:`order_preserving_crossover`, but the
    per-step visited-table lookups/updates are one-hot lane masks over a
    ``(P, L)`` visited matrix instead of per-row gathers/scatters — TPU
    gathers cost ~10 ns/element, which made the vmapped scan dominate the
    whole TSP generation (91 gens/sec at the reference's 1000×100; this
    formulation reaches 736 — see BASELINE.md). Still a ``lax.scan``
    over gene positions (the visited set is inherently sequential), but
    each step is pure elementwise/reduce work.
    """
    P, L = p1.shape
    c1 = jnp.clip(jnp.floor(p1 * L).astype(jnp.int32), 0, L - 1)
    c2 = jnp.clip(jnp.floor(p2 * L).astype(jnp.int32), 0, L - 1)
    iota = jnp.arange(L, dtype=jnp.int32)[None, :]  # (1, L)

    def body(visited, xs):  # visited: (P, L) bool
        g1, g2, city1, city2, r = xs  # each (P,)
        oh1 = iota == city1[:, None]  # (P, L)
        oh2 = iota == city2[:, None]
        seen1 = jnp.any(visited & oh1, axis=1)
        seen2 = jnp.any(visited & oh2, axis=1)
        take1 = ~seen1
        take2 = seen1 & ~seen2
        gene = jnp.where(take1, g1, jnp.where(take2, g2, r))
        mark = jnp.where(take1[:, None], oh1, oh2) & (take1 | take2)[:, None]
        return visited | mark, gene

    xs = (p1.T, p2.T, c1.T, c2.T, rand.T)  # scan over the gene axis
    visited0 = jnp.zeros((P, L), dtype=jnp.bool_)
    _, child = jax.lax.scan(body, visited0, xs)
    return child.T


order_preserving_crossover.batched = _order_preserving_batched
