"""The fused generation step — the framework's hot loop.

One call = one full generation: tournament-select → crossover → mutate →
evaluate. The whole thing traces into a single XLA program, which is the
structural win over the reference's hot loop: there, every generation is
1 cuRAND fill + 3 operators × ceil(pop/512) chunked kernel launches, each
followed by a full ``cudaDeviceSynchronize()`` (``src/pga.cu:376-391,62-77``
— ~23,700 synchronous launches for the 40k×100 OneMax driver).

Split into two pieces:

- :func:`make_breed` — select+crossover+mutate: ``(genomes, scores, key) ->
  next_genomes``. Selection reads the *given* scores, i.e. the fitness of
  the current generation, matching the reference (``pga.cu:294-317``).
- :func:`make_step` — breed then evaluate: ``(genomes, key[, scores]) ->
  (next_genomes, next_scores)``; the returned scores describe the
  returned genomes.

Run loops carry ``(genomes, scores)`` together and check termination
targets against the carried scores BEFORE breeding again — so the
generation that reaches the target is the one returned, never its
offspring.

Replacement ordering matches the reference: the next generation fully
replaces the current one (no implicit elitism unless configured).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from libpga_tpu.ops.evaluate import evaluate
from libpga_tpu.ops.select import select_parent_pairs


def make_breed(
    crossover_fn: Callable,
    mutate_fn: Callable,
    *,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    elitism: int = 0,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """Build the selection+variation half of a generation.

    Args:
      crossover_fn: per-child ``(p1, p2, rand) -> child``.
      mutate_fn: per-genome ``(genome, rand) -> genome``.
      tournament_size: k of the k-way tournament.
      selection_kind: "tournament" (the reference's strategy),
        "truncation", or "linear_rank" (see ``ops/select.py``).
      selection_param: τ for truncation, pressure s for linear ranking.
      elitism: copy the top-e of the current generation unchanged into the
        next one (slots 0..e-1). 0 = pure generational replacement (the
        reference's behavior).

    Returns:
      ``breed(genomes, scores, key) -> next_genomes``. Pure.
    """

    # Optional operator protocol: a callback may expose ``.batched``
    # (whole-population implementation, used instead of vmap — lets the
    # default point mutation run as an iota-compare mask instead of a
    # per-row scatter) and ``.rand_cols`` (how many uniforms per individual
    # it consumes, so the rand block can be (P, 3) instead of (P, L)).
    cross_batched = getattr(crossover_fn, "batched", None)
    cross_cols = getattr(crossover_fn, "rand_cols", None)
    mut_batched = getattr(mutate_fn, "batched", None)
    mut_cols = getattr(mutate_fn, "rand_cols", None)

    def breed(genomes: jax.Array, scores: jax.Array, key: jax.Array):
        P, L = genomes.shape
        k_sel, k_cross, k_mut = jax.random.split(key, 3)
        p1_idx, p2_idx = select_parent_pairs(
            k_sel, scores, P, k=tournament_size,
            kind=selection_kind, param=selection_param,
        )
        p1 = jnp.take(genomes, p1_idx, axis=0)
        p2 = jnp.take(genomes, p2_idx, axis=0)

        rand_c = jax.random.uniform(
            k_cross, (P, cross_cols or L), dtype=jnp.float32
        )
        if cross_batched is not None:
            children = cross_batched(p1, p2, rand_c)
        else:
            children = jax.vmap(crossover_fn)(p1, p2, rand_c)

        rand_m = jax.random.uniform(k_mut, (P, mut_cols or L), dtype=jnp.float32)
        if mut_batched is not None:
            nxt = mut_batched(children, rand_m)
        else:
            nxt = jax.vmap(mutate_fn)(children, rand_m)

        if elitism > 0:
            _, elite_idx = jax.lax.top_k(scores, elitism)
            nxt = nxt.at[:elitism].set(jnp.take(genomes, elite_idx, axis=0))

        return nxt.astype(genomes.dtype)

    return breed


def make_param_breed(
    crossover_fn: Callable,
    mutate_kind: str,
    *,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    elitism: int = 0,
) -> Callable:
    """:func:`make_breed` with the mutation rate/sigma as RUNTIME inputs.

    The serving mega-run packs requests with distinct mutation rates
    into one compiled program, so the operator parameters cannot be
    baked in the way :func:`make_breed` bakes them. ``mutate_kind``
    names a builtin kind ("point" / "gaussian" / "swap"); the returned
    ``breed(genomes, scores, key, mparams)`` reads ``rate = mparams[0,
    0]`` and ``sigma = mparams[0, 1]`` — the engine's ``(1, 2)`` f32
    mparams layout, the same runtime input the fused Pallas kernel
    takes. For equal parameter values the traced computation is
    identical to :func:`make_breed`'s (same ops, same PRNG consumption),
    so results are bit-identical to a baked-parameter breed — the
    property the serving bit-exactness tests assert.

    ``mutate_kind`` may also be a CALLABLE operator carrying a
    ``param_batched(genomes, rand, rate, sigma)`` attribute — the GP
    structural mutations (``gp/operators.py``) ship one, so GP runs
    batch-serve through the same mega-run machinery as every vector
    workload (ISSUE 11).

    The returned callable carries ``takes_params = True`` (the marker
    the island epochs already dispatch on) and ``default_params``.
    """
    from libpga_tpu.ops import mutate as _m

    batched_kinds = {
        "point": (_m.point_mutate_batched, 3),
        "gaussian": (_m.gaussian_mutate, None),
        "swap": (_m.swap_mutate_batched, 3),
    }
    if callable(mutate_kind):
        mut_batched = getattr(mutate_kind, "param_batched", None)
        if mut_batched is None:
            raise ValueError(
                "callable mutate kinds must carry .param_batched"
                "(genomes, rand, rate, sigma) — see gp/operators.py"
            )
        mut_cols = getattr(mutate_kind, "rand_cols", None)
    elif mutate_kind not in batched_kinds:
        raise ValueError(
            f"unknown mutate kind {mutate_kind!r}; "
            f"available: {sorted(batched_kinds)}"
        )
    else:
        mut_batched, mut_cols = batched_kinds[mutate_kind]
    cross_batched = getattr(crossover_fn, "batched", None)
    cross_cols = getattr(crossover_fn, "rand_cols", None)

    def breed(genomes, scores, key, mparams):
        P, L = genomes.shape
        rate = mparams[0, 0]
        k_sel, k_cross, k_mut = jax.random.split(key, 3)
        p1_idx, p2_idx = select_parent_pairs(
            k_sel, scores, P, k=tournament_size,
            kind=selection_kind, param=selection_param,
        )
        p1 = jnp.take(genomes, p1_idx, axis=0)
        p2 = jnp.take(genomes, p2_idx, axis=0)

        rand_c = jax.random.uniform(
            k_cross, (P, cross_cols or L), dtype=jnp.float32
        )
        if cross_batched is not None:
            children = cross_batched(p1, p2, rand_c)
        else:
            children = jax.vmap(crossover_fn)(p1, p2, rand_c)

        rand_m = jax.random.uniform(
            k_mut, (P, mut_cols or L), dtype=jnp.float32
        )
        if callable(mutate_kind):
            nxt = mut_batched(children, rand_m, rate, mparams[0, 1])
        elif mutate_kind == "gaussian":
            nxt = mut_batched(children, rand_m, rate, mparams[0, 1])
        else:
            nxt = mut_batched(children, rand_m, rate)

        if elitism > 0:
            _, elite_idx = jax.lax.top_k(scores, elitism)
            nxt = nxt.at[:elitism].set(jnp.take(genomes, elite_idx, axis=0))

        return nxt.astype(genomes.dtype)

    breed.takes_params = True
    breed.default_params = jnp.asarray([[0.01, 0.0]], dtype=jnp.float32)
    breed.mutate_kind = mutate_kind
    return breed


def make_step(
    obj: Callable,
    crossover_fn: Callable,
    mutate_fn: Callable,
    *,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    elitism: int = 0,
) -> Callable[[jax.Array, jax.Array], Tuple[jax.Array, jax.Array]]:
    """One full generation: ``step(genomes, key[, scores]) -> (next, next_scores)``.

    The returned scores always describe the returned genomes. Selection
    reads the CURRENT generation's fitness: pass it as ``scores`` to
    avoid re-evaluating (a loop threads the returned scores back in —
    one evaluation per generation); when omitted it is computed here.
    """
    breed = make_breed(
        crossover_fn, mutate_fn, tournament_size=tournament_size,
        selection_kind=selection_kind, selection_param=selection_param,
        elitism=elitism,
    )

    def step(genomes: jax.Array, key: jax.Array, scores: jax.Array = None):
        if scores is None:
            scores = evaluate(obj, genomes)
        nxt = breed(genomes, scores, key)
        return nxt, evaluate(obj, nxt)

    return step
