"""Mutation operators.

Per-individual pure functions ``(genome, rand) -> genome`` with ``rand`` an
``(L,)`` uniform [0,1) vector — the functional equivalent of the reference
callback ``void (*mutate_f)(gene*, float* rand, unsigned)``
(``include/pga.h:47``). Functional (returns a new genome) rather than
in-place; XLA aliases the buffers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def point_mutate(genome: jax.Array, rand: jax.Array, rate: float = 0.01) -> jax.Array:
    """With probability ``rate``, set one random gene to a random value.

    Semantics of the reference default ``__default_mutate``
    (``src/pga.cu:127-133``): fires when ``rand[1] <= rate``; target position
    ``floor(rand[0]*L)``; new value ``rand[2]``. This consumption pattern is
    why the reference requires ``genome_len >= 4``.
    """
    L = genome.shape[0]
    pos = jnp.clip(jnp.floor(rand[0] * L).astype(jnp.int32), 0, L - 1)
    fire = rand[1] <= rate
    mutated = genome.at[pos].set(rand[2].astype(genome.dtype))
    return jax.lax.select(fire, mutated, genome)


def make_point_mutate(rate: float = 0.01):
    """Bind a rate into the standard ``(genome, rand)`` signature."""
    return partial(point_mutate, rate=rate)


def gaussian_mutate(
    genome: jax.Array,
    rand: jax.Array,
    rate: float = 0.1,
    sigma: float = 0.1,
) -> jax.Array:
    """Per-gene Gaussian perturbation (real-coded GAs, e.g. Rastrigin).

    Each gene independently mutates with probability ``rate`` by adding
    N(0, sigma²) noise, clipped back to [0, 1). Needs three uniforms per
    gene (gate, radius, angle); rather than widening the rand slice, the
    extra streams are derived by integer bit-mixing the first (cheap,
    stateless, in-register). The gate is the raw ``rand`` value — exact
    rate — and MUST be a different stream than the Box-Muller angle, or the
    noise sign becomes correlated with firing (a gate of ``u2 < rate`` with
    rate ≤ 0.25 would make every applied mutation positive).
    """
    bits = (rand * jnp.float32(2**24)).astype(jnp.uint32)
    m1 = bits * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9)
    m2 = m1 * jnp.uint32(2246822519) + jnp.uint32(0x85EBCA6B)
    u1 = (m1 & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    u2 = (m2 & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    u1 = jnp.clip(u1, 1e-7, 1.0 - 1e-7)
    # Box-Muller
    normal = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * jnp.pi * u2)
    fire = rand < rate
    out = jnp.where(fire, genome + sigma * normal.astype(genome.dtype), genome)
    return jnp.clip(out, 0.0, 1.0 - 1e-7)


def make_gaussian_mutate(rate: float = 0.1, sigma: float = 0.1):
    return partial(gaussian_mutate, rate=rate, sigma=sigma)


def swap_mutate(genome: jax.Array, rand: jax.Array, rate: float = 0.5) -> jax.Array:
    """Swap two random positions with probability ``rate`` (permutation GAs)."""
    L = genome.shape[0]
    i = jnp.clip(jnp.floor(rand[0] * L).astype(jnp.int32), 0, L - 1)
    j = jnp.clip(jnp.floor(rand[1] * L).astype(jnp.int32), 0, L - 1)
    fire = rand[2] <= rate
    gi, gj = genome[i], genome[j]
    swapped = genome.at[i].set(gj).at[j].set(gi)
    return jax.lax.select(fire, swapped, genome)


def make_swap_mutate(rate: float = 0.5):
    return partial(swap_mutate, rate=rate)
