"""Mutation operators.

Per-individual pure functions ``(genome, rand) -> genome`` with ``rand`` an
``(L,)`` uniform [0,1) vector — the functional equivalent of the reference
callback ``void (*mutate_f)(gene*, float* rand, unsigned)``
(``include/pga.h:47``). Functional (returns a new genome) rather than
in-place; XLA aliases the buffers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def point_mutate(genome: jax.Array, rand: jax.Array, rate: float = 0.01) -> jax.Array:
    """With probability ``rate``, set one random gene to a random value.

    Semantics of the reference default ``__default_mutate``
    (``src/pga.cu:127-133``): fires when ``rand[1]`` is below ``rate``;
    target position ``floor(rand[0]*L)``; new value ``rand[2]``. This
    consumption pattern is why the reference requires ``genome_len >= 4``.
    The gate is strict ``<`` (the reference's ``<=`` differs only on a
    measure-zero event for rates in (0,1)) so rate=0 disables mutation.
    """
    L = genome.shape[0]
    pos = jnp.clip(jnp.floor(rand[0] * L).astype(jnp.int32), 0, L - 1)
    fire = rand[1] < rate
    mutated = genome.at[pos].set(rand[2].astype(genome.dtype))
    return jax.lax.select(fire, mutated, genome)


def point_mutate_batched(
    genomes: jax.Array, rand: jax.Array, rate: float = 0.01
) -> jax.Array:
    """Population-batched point mutation without a scatter.

    Same semantics as :func:`point_mutate` (rand columns 0..2 = position /
    gate / value) but expressed as an iota-compare mask over the whole
    ``(P, L)`` matrix — a pure elementwise program. On TPU this is ~10×
    faster than the vmap'd per-row ``at[pos].set`` scatter at 1M-population
    scale (measured: 30 ms → 2.8 ms per generation at 1M×100).
    """
    L = genomes.shape[1]
    pos = jnp.clip(jnp.floor(rand[:, 0] * L).astype(jnp.int32), 0, L - 1)
    fire = rand[:, 1] < rate
    hit = (jnp.arange(L, dtype=jnp.int32)[None, :] == pos[:, None]) & fire[:, None]
    return jnp.where(hit, rand[:, 2:3].astype(genomes.dtype), genomes)


def make_point_mutate(rate: float = 0.01):
    """Bind a rate into the standard ``(genome, rand)`` signature.

    The returned callable carries two optional-protocol attributes the
    engine's breed step exploits when present (see
    :func:`libpga_tpu.ops.step.make_breed`):

    - ``batched``: ``(genomes (P,L), rand (P, rand_cols)) -> genomes`` —
      whole-population implementation used instead of ``vmap``.
    - ``rand_cols``: how many uniforms per individual the operator actually
      consumes (the default mutate reads only rand[0..2], reference
      ``pga.cu:127-133``), so the engine can generate a ``(P, 3)`` random
      block instead of ``(P, L)``.
    """
    fn = partial(point_mutate, rate=rate)

    def mut(genome, rand):
        return fn(genome, rand)

    mut.func = point_mutate  # identity marker for default-operator checks
    mut.batched = partial(point_mutate_batched, rate=rate)
    mut.rand_cols = 3
    mut.rate = rate  # inspected by the engine's Pallas fast-path gate
    return mut


def gaussian_mutate(
    genome: jax.Array,
    rand: jax.Array,
    rate: float = 0.1,
    sigma: float = 0.1,
) -> jax.Array:
    """Per-gene Gaussian perturbation (real-coded GAs, e.g. Rastrigin).

    Each gene independently mutates with probability ``rate`` by adding
    N(0, sigma²) noise, clipped back to [0, 1). Needs three uniforms per
    gene (gate, radius, angle); rather than widening the rand slice, the
    extra streams are derived by integer bit-mixing the first (cheap,
    stateless, in-register). The gate is the raw ``rand`` value — exact
    rate — and MUST be a different stream than the Box-Muller angle, or the
    noise sign becomes correlated with firing (a gate of ``u2 < rate`` with
    rate ≤ 0.25 would make every applied mutation positive).
    """
    bits = (rand * jnp.float32(2**24)).astype(jnp.uint32)
    m1 = bits * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9)
    m2 = m1 * jnp.uint32(2246822519) + jnp.uint32(0x85EBCA6B)
    u1 = (m1 & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    u2 = (m2 & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    u1 = jnp.clip(u1, 1e-7, 1.0 - 1e-7)
    # Box-Muller
    normal = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * jnp.pi * u2)
    fire = rand < rate
    out = jnp.where(fire, genome + sigma * normal.astype(genome.dtype), genome)
    return jnp.clip(out, 0.0, 1.0 - 1e-7)


def make_gaussian_mutate(rate: float = 0.1, sigma: float = 0.1):
    fn = partial(gaussian_mutate, rate=rate, sigma=sigma)

    def mut(genome, rand):
        return fn(genome, rand)

    mut.func = gaussian_mutate
    # Already elementwise — the batched form is the same computation.
    mut.batched = partial(gaussian_mutate, rate=rate, sigma=sigma)
    # Inspected by the engine's Pallas fast path (runtime mutation params).
    mut.rate = rate
    mut.sigma = sigma
    return mut


def swap_mutate(genome: jax.Array, rand: jax.Array, rate: float = 0.5) -> jax.Array:
    """Swap two random positions with probability ``rate`` (permutation GAs)."""
    L = genome.shape[0]
    i = jnp.clip(jnp.floor(rand[0] * L).astype(jnp.int32), 0, L - 1)
    j = jnp.clip(jnp.floor(rand[1] * L).astype(jnp.int32), 0, L - 1)
    fire = rand[2] < rate
    gi, gj = genome[i], genome[j]
    swapped = genome.at[i].set(gj).at[j].set(gi)
    return jax.lax.select(fire, swapped, genome)


def swap_mutate_batched(
    genomes: jax.Array, rand: jax.Array, rate: float = 0.5
) -> jax.Array:
    """Population-batched swap mutation via two iota-compare masks
    (scatter-free; same semantics as :func:`swap_mutate`)."""
    L = genomes.shape[1]
    i = jnp.clip(jnp.floor(rand[:, 0] * L).astype(jnp.int32), 0, L - 1)
    j = jnp.clip(jnp.floor(rand[:, 1] * L).astype(jnp.int32), 0, L - 1)
    fire = (rand[:, 2] < rate)[:, None]
    cols = jnp.arange(L, dtype=jnp.int32)[None, :]
    gi = jnp.take_along_axis(genomes, i[:, None], axis=1)
    gj = jnp.take_along_axis(genomes, j[:, None], axis=1)
    out = jnp.where((cols == i[:, None]) & fire, gj, genomes)
    return jnp.where((cols == j[:, None]) & fire, gi, out)


def make_swap_mutate(rate: float = 0.5):
    fn = partial(swap_mutate, rate=rate)

    def mut(genome, rand):
        return fn(genome, rand)

    mut.func = swap_mutate
    mut.batched = partial(swap_mutate_batched, rate=rate)
    mut.rand_cols = 3
    # Inspected by the engine's Pallas fast path (runtime mutation params).
    mut.rate = rate
    return mut
