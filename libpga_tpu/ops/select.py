"""Parent selection.

Reference: size-2 tournament — draw two random indices from the population,
keep the higher-scored (``src/pga.cu:278-292``); two tournaments select the
two parents of each child (``pga.cu:306-307``). Here the tournament is a
batched gather + argmax over a ``(num, k)`` index matrix, k configurable.

The reference draws tournament indices from the same uniform pool that the
crossover mask later re-reads, so selection and crossover randomness overlap
(survey §2.2). That aliasing is a bug, not a feature — here every consumer
gets an independent PRNG stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tournament_select(
    key: jax.Array,
    scores: jax.Array,
    num: int,
    k: int = 2,
) -> jax.Array:
    """Run ``num`` independent k-way tournaments.

    Args:
      key: PRNG key.
      scores: ``(pop,)`` fitness values, higher better.
      num: number of winners to select.
      k: tournament size (reference: 2).

    Returns:
      ``(num,)`` int32 indices of winners into the population.
    """
    pop = scores.shape[0]
    if k == 2:
        # Branchless pairwise form: two flat index vectors + a where on the
        # gathered scores. Avoids the 2-D gather + argmax + take_along_axis
        # chain, which is ~2× slower on TPU at 1M-population scale
        # (measured 68 ms → 34 ms per generation). Tie goes to the first
        # candidate, matching the argmax path and the reference's strict
        # '>' comparison (``pga.cu:286``).
        k1, k2 = jax.random.split(key)
        i1 = jax.random.randint(k1, (num,), 0, pop, dtype=jnp.int32)
        i2 = jax.random.randint(k2, (num,), 0, pop, dtype=jnp.int32)
        return jnp.where(scores[i1] >= scores[i2], i1, i2)
    idx = jax.random.randint(key, (num, k), 0, pop, dtype=jnp.int32)
    cand = scores[idx]  # (num, k) gather
    win = jnp.argmax(cand, axis=-1)  # ties -> lowest slot, matches strict '>'
    return jnp.take_along_axis(idx, win[:, None], axis=-1)[:, 0]


def select_parent_pairs(
    key: jax.Array,
    scores: jax.Array,
    num_children: int,
    k: int = 2,
) -> tuple[jax.Array, jax.Array]:
    """Two tournaments per child → (p1_idx, p2_idx), each ``(num_children,)``."""
    winners = tournament_select(key, scores, num_children * 2, k=k)
    return winners[:num_children], winners[num_children:]
