"""Parent selection.

Reference: size-2 tournament — draw two random indices from the population,
keep the higher-scored (``src/pga.cu:278-292``); two tournaments select the
two parents of each child (``pga.cu:306-307``). Here the tournament is a
batched gather + argmax over a ``(num, k)`` index matrix, k configurable.

The reference draws tournament indices from the same uniform pool that the
crossover mask later re-reads, so selection and crossover randomness overlap
(survey §2.2). That aliasing is a bug, not a feature — here every consumer
gets an independent PRNG stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tournament_select(
    key: jax.Array,
    scores: jax.Array,
    num: int,
    k: int = 2,
) -> jax.Array:
    """Run ``num`` independent k-way tournaments.

    Args:
      key: PRNG key.
      scores: ``(pop,)`` fitness values, higher better.
      num: number of winners to select.
      k: tournament size (reference: 2).

    Returns:
      ``(num,)`` int32 indices of winners into the population.
    """
    pop = scores.shape[0]
    if k == 2:
        # Branchless pairwise form: two flat index vectors + a where on the
        # gathered scores. Avoids the 2-D gather + argmax + take_along_axis
        # chain, which is ~2× slower on TPU at 1M-population scale
        # (measured 68 ms → 34 ms per generation). Tie goes to the first
        # candidate, matching the argmax path and the reference's strict
        # '>' comparison (``pga.cu:286``).
        k1, k2 = jax.random.split(key)
        i1 = jax.random.randint(k1, (num,), 0, pop, dtype=jnp.int32)
        i2 = jax.random.randint(k2, (num,), 0, pop, dtype=jnp.int32)
        return jnp.where(scores[i1] >= scores[i2], i1, i2)
    idx = jax.random.randint(key, (num, k), 0, pop, dtype=jnp.int32)
    cand = scores[idx]  # (num, k) gather
    win = jnp.argmax(cand, axis=-1)  # ties -> lowest slot, matches strict '>'
    return jnp.take_along_axis(idx, win[:, None], axis=-1)[:, 0]


SELECTION_KINDS = ("tournament", "truncation", "linear_rank")


def resolve_selection(kind: str, param: float | None) -> float | None:
    """Default and validate a selection strategy's parameter — the ONE
    place defaults/ranges live, shared by the XLA operators here and the
    fused Pallas kernel (``ops/pallas_step.py``), so the two paths can
    never drift. Returns the resolved param (None for tournament);
    raises ValueError for an unknown kind or out-of-range param."""
    if kind == "tournament":
        return None
    if kind == "truncation":
        param = 0.5 if param is None else param
        if not 0.0 < param <= 1.0:
            raise ValueError(f"truncation tau must be in (0, 1], got {param}")
        return param
    if kind == "linear_rank":
        param = 2.0 if param is None else param
        if not 1.0 < param <= 2.0:
            raise ValueError(
                f"linear ranking pressure must be in (1, 2], got {param}"
            )
        return param
    raise ValueError(
        f"unknown selection kind {kind!r}; one of {SELECTION_KINDS}"
    )


def rank_fraction_icdf(kind: str, param: float, u: jax.Array) -> jax.Array:
    """Map uniform draws ``u`` to winner rank FRACTIONS x in [0, 1) —
    the strategy's inverse CDF, shared verbatim by the XLA operators
    below and the fused Pallas kernel (``ops/pallas_step.py``) so the
    two paths sample provably identical distributions. Tournament is
    not here: the kernel specializes its k cases (sqrt chains), and the
    XLA tournament samples candidate indices directly."""
    if kind == "truncation":
        return u * jnp.float32(param)
    if kind == "linear_rank":
        s = jnp.float32(param)
        # Clamp the radicand: for s just under 2 and u within a few ulps
        # of 1, s²-4(s-1)u can round fractionally negative in f32 and
        # sqrt(NaN) would poison the winner rank (a NaN rank matches no
        # one-hot row in the kernel and breeds an all-zero child). When
        # the clamp fires the quotient is s/(2(s-1)), fractionally above
        # 1; at u≈0, sqrt(s²) can round a ulp above s, going fractionally
        # negative. Pin to [0, 1) so the documented contract holds at the
        # source. (Consumers still need their rank clamps: x·V can round
        # UP to V in f32 even for x < 1, e.g. (1-2^-24)·1024.)
        x = (
            s - jnp.sqrt(jnp.maximum(s * s - 4.0 * (s - 1.0) * u, 0.0))
        ) / (2.0 * (s - 1.0))
        return jnp.clip(x, 0.0, jnp.float32(1.0 - 2.0**-24))
    raise ValueError(f"no rank-fraction ICDF for selection kind {kind!r}")


def _rank_order(scores: jax.Array, key: jax.Array) -> jax.Array:
    """Row indices sorted best-first (rank r → row). Score ties break in
    a fresh uniform random order per call — matching the fused kernel's
    per-generation tie shuffle. A stable index tie-break would make
    rank-cutoff strategies (truncation) permanently exclude the
    high-index half of a tie block: on a flat fitness plateau only the
    first ``tau·pop`` ROWS would ever breed."""
    tb = jax.random.bits(key, scores.shape)
    iota = jnp.arange(scores.shape[0], dtype=jnp.int32)
    _, _, order = jax.lax.sort((-scores, tb, iota), num_keys=2)
    return order


def truncation_select(
    key: jax.Array,
    scores: jax.Array,
    num: int,
    tau: float,
) -> jax.Array:
    """``num`` parents drawn uniformly from the top ``tau`` fraction.

    Classic (μ, λ)-style truncation: every individual ranked in the top
    ``ceil(tau·pop)`` is equally likely, everyone else never selected.
    Not in the reference (its selection enum is a single-member
    placeholder, ``pga.h:37-42``) — this completes that declared
    surface. Selection runs in rank space exactly like the fused
    kernel's inverse-CDF sampler (``ops/pallas_step.py``).
    """
    pop = scores.shape[0]
    tau = resolve_selection("truncation", tau)
    k_tie, k_u = jax.random.split(key)
    order = _rank_order(scores, k_tie)
    u = jax.random.uniform(k_u, (num,))
    x = rank_fraction_icdf("truncation", tau, u)
    r = jnp.clip((x * pop).astype(jnp.int32), 0, pop - 1)
    return order[r]


def linear_rank_select(
    key: jax.Array,
    scores: jax.Array,
    num: int,
    pressure: float,
) -> jax.Array:
    """Linear ranking selection with pressure ``s`` in (1, 2].

    The best rank is selected ``s`` times as often as the average and
    the worst ``2-s`` times; the rank-fraction density is
    ``f(x) = s - 2(s-1)x`` with inverse CDF
    ``x = (s - sqrt(s² - 4(s-1)u)) / (2(s-1))``. At s=2 the selection
    intensity equals tournament-2 (E[winner] = 2/3 quantile on uniform
    scores); s→1 approaches uniform selection.
    """
    pop = scores.shape[0]
    pressure = resolve_selection("linear_rank", pressure)
    k_tie, k_u = jax.random.split(key)
    order = _rank_order(scores, k_tie)
    u = jax.random.uniform(k_u, (num,))
    x = rank_fraction_icdf("linear_rank", pressure, u)
    r = jnp.clip((x * pop).astype(jnp.int32), 0, pop - 1)
    return order[r]


def select_parent_pairs(
    key: jax.Array,
    scores: jax.Array,
    num_children: int,
    k: int = 2,
    kind: str = "tournament",
    param: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Two independent selections per child → (p1_idx, p2_idx), each
    ``(num_children,)``. ``kind`` picks the strategy: "tournament"
    (k-way, the reference's only implemented strategy), "truncation"
    (param = top fraction τ, default 0.5), or "linear_rank" (param =
    pressure s, default 2.0)."""
    if kind == "tournament":
        winners = tournament_select(key, scores, num_children * 2, k=k)
    elif kind == "truncation":
        winners = truncation_select(key, scores, num_children * 2, param)
    elif kind == "linear_rank":
        winners = linear_rank_select(key, scores, num_children * 2, param)
    else:
        resolve_selection(kind, param)  # raises with the canonical message
        raise AssertionError("unreachable")
    return winners[:num_children], winners[num_children:]
