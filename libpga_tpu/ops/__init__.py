"""GA operators — the TPU-native equivalents of the reference's CUDA kernels.

Reference kernel → op mapping (all in reference ``src/pga.cu``):

- ``__g_evaluate`` (pga.cu:250-262)        → :func:`evaluate.evaluate`
- ``tournament_selection`` (pga.cu:280-292)→ :func:`select.tournament_select`
- ``__g_crossover`` (pga.cu:294-317)       → :func:`crossover` ops + step fusion
- ``__default_crossover`` (pga.cu:135-143) → :func:`crossover.uniform_crossover`
- ``__g_mutate`` (pga.cu:333-347)          → :func:`mutate` ops + step fusion
- ``__default_mutate`` (pga.cu:127-133)    → :func:`mutate.point_mutate`
- whole-generation loop (pga.cu:376-391)   → :func:`step.make_step` (single
  fused XLA program per generation instead of ~3×(pop/512) launches)
"""

from libpga_tpu.ops.evaluate import evaluate
from libpga_tpu.ops.select import (
    linear_rank_select,
    tournament_select,
    truncation_select,
)
from libpga_tpu.ops.crossover import (
    uniform_crossover,
    one_point_crossover,
    arithmetic_crossover,
    order_preserving_crossover,
)
from libpga_tpu.ops.mutate import point_mutate, gaussian_mutate, swap_mutate
from libpga_tpu.ops.topk import best_index, top_k_genomes
from libpga_tpu.ops.step import make_step

__all__ = [
    "evaluate",
    "tournament_select",
    "truncation_select",
    "linear_rank_select",
    "uniform_crossover",
    "one_point_crossover",
    "arithmetic_crossover",
    "order_preserving_crossover",
    "point_mutate",
    "gaussian_mutate",
    "swap_mutate",
    "best_index",
    "top_k_genomes",
    "make_step",
]
