"""Fused Pallas generation step for the default operators.

Placeholder for the Pallas-kernel fast path (survey §7 step 4): a fused
tournament-select + uniform-crossover + point-mutate kernel with in-kernel
PRNG (``pltpu.prng_random_bits``), avoiding the HBM materialization of the
``(pop, genome_len)`` random pools the XLA path generates.

``make_pallas_run`` returns ``None`` until the kernel lands; the engine
falls back to the XLA-fused path.
"""

from __future__ import annotations

from typing import Callable, Optional


def make_pallas_run(
    obj: Callable, *, tournament_size: int = 2, mutation_rate: float = 0.01
) -> Optional[Callable]:
    return None
