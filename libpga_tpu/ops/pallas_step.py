"""Fused Pallas generation step — the TPU fast path.

One kernel = one whole generation of breeding: k-way tournament selection
(k ≤ 16; default 2), uniform crossover, and point or gaussian mutation,
fused over a VMEM-resident deme of the population — plus optional
in-kernel evaluation and elitism. This is the TPU answer to the reference's hot loop, which
issues ceil(pop/512) chunked launches per operator with a full device sync
after each (``/root/reference/src/pga.cu:62-77,269``): here the entire
population breeds in one pass over HBM with zero intermediate HBM traffic.

Why not XLA alone? The naive formulation is random-access bound: tournament
score lookups and parent row gathers are scalar/row gathers that XLA lowers
at ~10 ns per access (measured ~60 ms per generation at 1M×100 on v5e).
This kernel removes all HBM random access:

- **Demes**: the population is processed in blocks ("demes") of ``K``
  rows that live entirely in VMEM. Selection happens *within* a deme, so
  every random access is on-chip.
- **Selection in rank space**: each deme's rows are ranked outside the
  kernel (one two-key sort per generation — score first, then a fresh
  random word so ties shuffle uniformly; NaNs last among real rows,
  pads strictly last), and the k-way tournament winner is *sampled
  directly in rank space* — the winner's rank is the minimum of k
  i.i.d. uniform candidate ranks, whose inverse CDF is
  ``floor(V·(1-(1-u)^{1/k}))``. The winner-SCORE distribution is exact
  (``P(rank=r) = ((V-r)^k - (V-r-1)^k)/V^k``, identical to drawing k
  candidates and keeping the best score), and within a score-tie block
  the per-generation random tie order makes each row's expected
  selection mass exactly uniform (draws within one generation share the
  realized order — the only deviation from fully independent candidate
  draws). No per-candidate score lookups, no winner fold, and the cost
  is independent of k. The winner's parent row is then gathered by a
  one-hot matmul (``onehot @ genomes``), which the MXU executes at full
  tilt. Gene matrices multiply as a bf16 hi/lo split (``g ≈ hi + lo``),
  giving ~1e-5 absolute accuracy on [0,1) genes — far below mutation
  noise — at 2× bf16 FLOPs instead of slow f32 MXU.
- **In-kernel PRNG**: ``pltpu.prng_random_bits`` generates tournament
  indices, crossover masks, and mutation draws in registers, so no
  ``(P, L)`` random pool ever touches HBM (the reference materializes
  exactly such a pool per generation, ``pga.cu:99-105``).
- **Free global mixing**: each deme's children are written through the
  output ``BlockSpec`` index map into a ``(K, G, L)`` layout; a free
  row-major reshape back to ``(P, L)`` interleaves all demes (a riffle
  shuffle), so deme membership changes every generation and selection is
  panmictic over a few-generation horizon.

Semantics note: selection is a tournament *within the current deme* (a
random cohort of ``K`` that reshuffles every generation), not i.i.d. over
the full population. Selection intensity is identical to the panmictic
tournament; only opponent locality differs, and the per-generation
riffle shuffle randomizes it. Measured equivalence
(``tools/selection_equivalence.py``, BASELINE.md): selection intensity
within 0.6% of the panmictic XLA path at every deme size, takeover time
within 1.5%, OneMax generations-to-99%-optimum within 0.5%. The
exact-panmictic path remains available via the XLA breed step
(``use_pallas=False``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

LANE = 128


def _valid_deme(k: int) -> bool:
    """Power of two in [128, 1024]: K=128 is the smallest MXU-efficient
    tile; above 1024 the one-hot matmul FLOPs dominate; tiny demes
    collapse tournament-2 toward cloning and produce sub-tile shapes."""
    return bool(k) and not (k & (k - 1)) and 128 <= k <= 1024


def _scoped_vmem_bytes(K: int, D: int, Lp: int, gene_bytes: int) -> int:
    """Conservative model of the kernel's scoped-VMEM stack for one grid
    step, calibrated against hardware compiles (Mosaic's scoped limit is
    16 MiB): genome in+out blocks (D·K·Lp each), the selection one-hots
    (two bf16 K×K planes plus an f32 temp's worth of headroom), and one
    deme's row intermediates (f32 parents/child, bf16 hi/lo for f32
    genes, the crossover mask). Measured anchors (with the former
    in-kernel rank cube, which this model retains as headroom): f32
    K=1024 D=1 at Lp=128 compiles, D=4 OOMs at 18.26M reported; bf16
    K=256 Lp=2048 D=2 compiles, K=512 Lp=2048 fails (row term alone
    16.8M)."""
    blocks = 2 * D * K * Lp * gene_bytes
    cubes = K * K * (4 + 2 + 2)
    rows = K * Lp * (3 * 4 + 4 + (4 if gene_bytes == 4 else 0))
    return blocks + cubes + rows


_SCOPED_VMEM_LIMIT = 14_500_000  # of the 16 MiB scoped stack; f32 K=1024
# D=4 at Lp=128 models 15.2M and OOMs on hardware, D=2 models 13.1M and runs

# Mosaic double-buffers the pipelined genome in+out blocks, so their raw
# bytes are bounded separately from the additive stack model. Anchors:
# f32 K=256 D=16 at Lp=128 compiles (8.4M doubled), D=32 OOMs (16.8M);
# bf16 K=256 D=32 compiles (8.4M doubled).
_BLOCK_BYTES_LIMIT = 8_650_000


def _blocks_fit(K: int, D: int, Lp: int, gene_bytes: int) -> bool:
    return (
        4 * D * K * Lp * gene_bytes <= _BLOCK_BYTES_LIMIT
        and _scoped_vmem_bytes(K, D, Lp, gene_bytes) <= _SCOPED_VMEM_LIMIT
    )


def _pick_deme_size(
    pop_size: int,
    preferred: int,
    genome_lanes: int = LANE,
    gene_bytes: int = 4,
):
    """Deme size for a population: exact divisors first (zero padding),
    then a padded fit — the kernel pads the population up to the next
    deme multiple and masks the pad rows out of selection.

    ``genome_lanes`` (the lane-padded genome length) bounds the deme via
    the scoped-VMEM model (``_scoped_vmem_bytes`` at D=1) — e.g. K=512
    at Lp=2048 needs ~23 MB and fails to compile, K=256 fits (measured).
    Genomes too long for even K=128 fall back to the XLA path.

    Padded fits must keep the short tail deme healthy: a tail of
    ``tail = P - (G-1)K`` valid rows breeds K children from only
    ``tail`` candidates, so tails under K/4 rows (degenerate case: a
    single row cloning itself into ~1/G of the population with zero
    fitness pressure) are rejected. Among healthy fits, wastes up to
    12.5% of the population are treated as equivalent (per-deme
    overheads outweigh small waste: K=128's minimal padding at 40,000
    measured 27% slower than K=256's 192 pad rows) and the caller's
    configured size, then the larger deme, is preferred; beyond that
    the least-waste fit wins. None (→ XLA path) for populations under
    one 128-row tile or with only degenerate-tail fits."""
    def fits(k: int) -> bool:
        return _blocks_fit(k, 1, genome_lanes, gene_bytes)

    if _valid_deme(preferred) and fits(preferred) and pop_size % preferred == 0:
        return preferred
    for k in (1024, 512, 256, 128):
        if fits(k) and pop_size % k == 0:
            return k
    if pop_size < 128:
        return None
    best = None
    for k in (1024, 512, 256, 128):
        if k > pop_size or not fits(k):
            continue
        g = -(-pop_size // k)
        tail = pop_size - (g - 1) * k
        if tail < max(k // 4, 2):
            continue
        waste = g * k - pop_size
        rank = (
            waste if waste > pop_size // 8 else 0,
            0 if k == preferred else 1,
            -k,
        )
        if best is None or rank < best[0]:
            best = (rank, k)
    return best[1] if best else None


def auto_deme_size(gene_dtype) -> int:
    """Measured per-dtype deme sweet spot at 1M×100 (see BASELINE.md):
    bf16's single selection matmul makes the larger deme worthwhile.
    Single source of truth — bench.py derives its FLOPs model from this."""
    return 512 if gene_dtype == jnp.bfloat16 else 256


def _carry_elites(g_prev, s_prev, g2, s2, elitism: int):
    """Carry the top-e of the previous generation into rows 0..e-1 of the
    new one, scores included — the same slots the XLA breed uses
    (``ops/step.py``). Works on padded arrays: pad rows carry -inf
    scores, so they can never be selected as elites, and rows 0..e-1 are
    always real rows. The single definition serves both the fused breed
    and the non-fused run loop so the two paths cannot drift."""
    top_s, top_i = jax.lax.top_k(s_prev, elitism)
    elites = jnp.take(g_prev, top_i, axis=0).astype(g2.dtype)
    g2 = jax.lax.dynamic_update_slice(g2, elites, (0, 0))
    s2 = jax.lax.dynamic_update_slice(s2, top_s, (0,))
    return g2, s2


def _supported() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except ImportError:
        return False
    return True


def _breed_kernel(
    seed_ref,
    mparams_ref,
    scores_ref,
    genomes_ref,
    *rest,
    K,
    D,
    L,
    Lp,
    tk=2,
    sel="tournament",
    sel_param=None,
    crossover="uniform",
    mutate="point",
    obj=None,
    obj_pad_ok=False,
    n_consts=0,
    bf16_genes=False,
    P=None,
    ablate=(),
):
    """One grid step = ``D`` consecutive demes: select parents, crossover,
    mutate — and, when ``obj`` is given, evaluate the children in-kernel
    (skipping a whole extra HBM pass per generation). All VMEM/register
    work; the per-deme loop unrolls at trace time.

    Why group demes: each deme's children land in output column g of a
    ``(K, G/D, D, Lp)`` layout, so a row's writes for one grid step are
    ``D·Lp`` contiguous values instead of ``Lp`` — D× fewer, larger HBM
    bursts for the riffle shuffle (whose strided writes grew per-row cost
    ~25% from 64k to 1M population at D=1).

    ``mparams_ref`` is a (1, 2) f32 SMEM block carrying the mutation
    operator's runtime parameters ([rate, _] for point mutation,
    [rate, sigma] for gaussian) — runtime scalars so an annealing
    schedule (e.g. Rastrigin's shrinking sigma) reuses one compilation
    instead of recompiling per phase.

    ``rest`` holds, in order: ``n_consts`` objective-constant input refs
    (problem data like the NK table — Pallas forbids captured array
    constants, so fused objectives declare them via
    ``kernel_rowwise_consts`` and receive them as call arguments), the
    genome output ref, and (when ``obj`` is set) the score output ref."""
    import jax.lax as lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    const_refs = rest[:n_consts]
    out_ref = rest[n_consts]

    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0, 0] ^ (i * jnp.int32(-1640531527)))  # golden-ratio mix

    # NOTE on shapes: Mosaic only supports minor-dim insertion/transpose
    # for 32-bit types, so every bool/bf16 value here is built directly in
    # its final 2-D/3-D orientation; only f32/i32 get transposed.
    s_all = scores_ref[:]   # (1, D, K) f32 — per-deme ranks (see below)
    g_all = genomes_ref[:]  # (D*K, Lp)

    # uint32 -> f32 isn't a supported Mosaic cast; >>8 leaves 24 bits, so
    # bitcast to i32 before the float convert.
    def uniform(shape):
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
        return pltpu.bitcast(bits >> 8, jnp.int32).astype(
            jnp.float32
        ) * jnp.float32(2**-24)

    rate = mparams_ref[0, 0]

    if crossover == "uniform" and "no_cross" not in ablate:
        # Crossover coin flips need ONE bit per gene, not a 32-bit draw:
        # a single (K, Lp) PRNG tile per grid step serves every deme in
        # the group — deme d reads bit d of each word (distinct bits of
        # one generator call are independent streams), cutting mask PRNG
        # volume D× (the mask draw measured ~1.3 ms/gen of the 1M×100
        # generation at one-draw-per-deme).
        mask_words = pltpu.bitcast(pltpu.prng_random_bits((K, Lp)), jnp.uint32)

    if mutate == "gaussian" and Lp > L:
        # Keep pad lanes untouched by gaussian noise so the pads-stay-
        # zero invariant holds for every mutation kind (pad_ok fused
        # objectives rely on it; point/swap positions are < L already).
        lane_ok = lax.broadcasted_iota(jnp.int32, (K, Lp), 1) < L

    for d in range(D):
        g = g_all[d * K : (d + 1) * K, :]  # (K, Lp)

        # ---- rank-space tournament selection --------------------------
        if "sel_const" in ablate:
            # Ablation harness (tools/ablate_kernel.py): identity
            # selection isolates the sampling + one-hot cost from the
            # parent matmuls.
            oh1 = oh2 = (
                lax.broadcasted_iota(jnp.int32, (K, K), 0)
                == lax.broadcasted_iota(jnp.int32, (K, K), 1)
            ).astype(jnp.bfloat16)
        else:
            # ``scores_ref`` carries each row's PRE-COMPUTED in-deme
            # rank (0 = best; strict total order, score ties broken by a
            # fresh random word per generation, NaNs last among real
            # rows) — the caller derives them from the
            # scores with one stable double-argsort per generation
            # (``breed_padded``), which costs ~0.8 ms/gen at 1M×100 and
            # replaces what used to be a K×K compare+reduce cube per
            # deme in here (~1–2 ms/gen, growing linearly with K).
            R = s_all[0, d : d + 1, :]  # (1, K) f32 ranks

            # The k-way tournament winner is the candidate with the
            # minimum rank; for k i.i.d. uniform candidate draws over V
            # valid rows that minimum has inverse CDF
            # rank = floor(V·(1-(1-u)^{1/k})):
            # P(rank=r) = ((V-r)^k - (V-r-1)^k)/V^k, exactly the
            # distribution of drawing k candidates and keeping the best
            # score. One uniform per parent replaces 2k candidate draws
            # + 2k score lookups, at k-independent cost. Power-of-two k
            # uses repeated sqrt; other k the exp/log form.
            if P is None or P % K == 0:
                Vf = jnp.float32(K)
            else:
                # padded population: the last deme holds V = P - deme·K
                # < K real rows (pads beyond them, carrying -inf
                # scores). Ranks 0..V-1 are exactly the real rows — the
                # pads carry the maximal 0xFFFFFFFF tie key while real
                # rows' random tie words are shifted into [0, 2^31), so
                # even a -inf-scored real row sorts strictly before
                # every pad — and sampling rank < V means a pad row can
                # never be selected.
                deme = i * D + d
                Vf = jnp.maximum(
                    jnp.minimum(jnp.int32(K), jnp.int32(P) - deme * K), 1
                ).astype(jnp.float32)

            u_t = uniform((2, K)).T  # (K, 2): one winner draw per parent
            if sel != "tournament":
                # Truncation / linear ranking: the SAME inverse-CDF
                # helper the XLA operators use (ops/select.py), so the
                # two paths sample provably identical distributions.
                # The cohort argument for panmictic equivalence applies
                # identically (see module docstring).
                from libpga_tpu.ops.select import rank_fraction_icdf

                x = rank_fraction_icdf(sel, sel_param, u_t)
            elif tk == 1:
                x = u_t
            elif tk & (tk - 1) == 0:
                t = 1.0 - u_t
                for _ in range(tk.bit_length() - 1):
                    t = jnp.sqrt(t)
                x = 1.0 - t
            else:
                x = 1.0 - jnp.exp(jnp.log(1.0 - u_t) * jnp.float32(1.0 / tk))
            # Two-sided clamp: floor can graze V at f32 precision (x·V
            # rounding up), and linear_rank's x can go fractionally
            # NEGATIVE at u≈0 if the VPU's sqrt(s²-4(s-1)u) rounds a ulp
            # above s — wr=-1 would match no rank and breed a zero row.
            wr = jnp.clip(jnp.floor(x * Vf), 0.0, Vf - 1.0)  # (K, 2) ranks

            # Winner one-hots by rank equality: ranks are distinct
            # integers 0..K-1 (exact in f32), so each row of the compare
            # is an exact one-hot over the deme's source rows.
            oh1 = (R == wr[:, 0:1]).astype(jnp.bfloat16)
            oh2 = (R == wr[:, 1:2]).astype(jnp.bfloat16)

        # ---- parent rows via one-hot matmul ---------------------------
        # (named gather_rows, NOT "sel": rebinding the ``sel`` strategy
        # param here would silently turn every deme after the first back
        # into a tournament — caught by the hardware truncation check.)
        if bf16_genes:
            # bf16 genomes are selected exactly by a single bf16 matmul
            # (0/1 selector rows; f32 accumulation) — half the FLOPs and
            # HBM traffic of the f32 hi/lo path.
            def gather_rows(oh_w):
                return jnp.dot(oh_w, g, preferred_element_type=jnp.float32)

        else:
            # f32 genomes: bf16 hi/lo split, ~1e-5 absolute gene accuracy.
            g_hi = g.astype(jnp.bfloat16)
            g_lo = (g - g_hi.astype(jnp.float32)).astype(jnp.bfloat16)

            def gather_rows(oh_w):
                hi = jnp.dot(oh_w, g_hi, preferred_element_type=jnp.float32)
                lo = jnp.dot(oh_w, g_lo, preferred_element_type=jnp.float32)
                return hi + lo

        if "no_matmul" in ablate:
            p1 = p2 = g.astype(jnp.float32)
        else:
            p1 = gather_rows(oh1)  # (K, Lp) f32
            p2 = gather_rows(oh2)

        if "no_cross" in ablate:
            child = p1
        elif crossover == "uniform":
            # ---- uniform crossover: per-gene coin flip (pga.cu:135-143)
            child = jnp.where(
                ((mask_words >> d) & jnp.uint32(1)) == 0, p1, p2
            )
        elif crossover == "order":
            # ---- order-preserving crossover (reference TSP driver,
            # test3/test.cu:48-64): walk gene positions left to right,
            # take p1's gene if its decoded city is unvisited, else
            # p2's, else the raw random value. Inherently sequential in
            # L, but each step is a handful of (Lp, K) VPU ops on
            # VMEM-resident data — unrolled at trace time, zero HBM
            # traffic — unlike the XLA scan path whose per-step launch
            # overhead dominates large populations (ops/crossover.py).
            # Transposed (gene-major) layout: a step's slice is then a
            # static SUBLANE row, and the visited set indexes cities on
            # sublanes.
            p1t = p1.T  # (Lp, K) f32 — 32-bit transpose is supported
            p2t = p2.T
            c1t = jnp.clip(jnp.floor(p1t * L), 0, L - 1).astype(jnp.int32)
            c2t = jnp.clip(jnp.floor(p2t * L), 0, L - 1).astype(jnp.int32)
            randt = uniform((Lp, K))
            sub = lax.broadcasted_iota(jnp.int32, (Lp, K), 0)
            visited = jnp.zeros((Lp, K), dtype=jnp.bool_)
            childt = jnp.zeros((Lp, K), dtype=jnp.float32)
            for l in range(L):
                g1l, c1l = p1t[l : l + 1, :], c1t[l : l + 1, :]
                g2l, c2l = p2t[l : l + 1, :], c2t[l : l + 1, :]
                seen1 = jnp.any(
                    visited & (sub == c1l), axis=0, keepdims=True
                )
                seen2 = jnp.any(
                    visited & (sub == c2l), axis=0, keepdims=True
                )
                take1 = ~seen1
                take2 = seen1 & ~seen2
                gene = jnp.where(
                    take1, g1l, jnp.where(take2, g2l, randt[l : l + 1, :])
                )
                mark_city = jnp.where(take1, c1l, c2l)
                visited = visited | ((sub == mark_city) & (take1 | take2))
                childt = jnp.where(sub == l, gene, childt)
            child = childt.T  # (K, Lp); pad columns are 0
        else:
            raise ValueError(f"unknown crossover kind {crossover!r}")

        # ---- mutation -------------------------------------------------
        if "no_mut" in ablate:
            pass
        elif mutate == "point":
            # Point mutation (pga.cu:127-133): one random gene per firing
            # row.
            u_t = uniform((4, K)).T  # (K, 4) f32
            pos = jnp.floor(u_t[:, 0:1] * L).astype(jnp.int32)  # in [0, L)
            cols = lax.broadcasted_iota(jnp.int32, (K, Lp), 1)
            # Strict '<' so rate=0 disables mutation exactly (the
            # reference's ``rand[1] <= chance`` gate, pga.cu:128, differs
            # only on a measure-zero event for rate in (0,1)).
            hit = (cols == pos) & (u_t[:, 1:2] < rate)
            child = jnp.where(hit, u_t[:, 2:3], child)
        elif mutate == "gaussian":
            # Per-gene Gaussian perturbation (ops/mutate.gaussian_mutate
            # semantics): each gene independently fires with probability
            # ``rate`` and receives N(0, sigma^2) noise, clipped to
            # [0, 1). Box-Muller from two independent in-kernel uniform
            # draws; the gate draw is a third stream, so noise sign stays
            # independent of firing (see the XLA operator's docstring).
            sigma = mparams_ref[0, 1]
            gate = uniform((K, Lp))
            u1 = jnp.clip(uniform((K, Lp)), 1e-7, 1.0 - 1e-7)
            u2 = uniform((K, Lp))
            normal = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(
                2.0 * jnp.float32(math.pi) * u2
            )
            mutated = jnp.clip(child + sigma * normal, 0.0, 1.0 - 1e-7)
            fire = gate < rate
            if Lp > L:
                fire = fire & lane_ok
            child = jnp.where(fire, mutated, child)
        elif mutate == "swap":
            # Swap two random positions with probability ``rate``
            # (ops/mutate.swap_mutate semantics — permutation GAs).
            # Scatter-free: two lane one-hots select/exchange the genes.
            u_t = uniform((4, K)).T  # (K, 4) f32
            pi = jnp.floor(u_t[:, 0:1] * L).astype(jnp.int32)
            pj = jnp.floor(u_t[:, 1:2] * L).astype(jnp.int32)
            fire = u_t[:, 2:3] < rate
            cols = lax.broadcasted_iota(jnp.int32, (K, Lp), 1)
            ohi = cols == pi
            ohj = cols == pj
            gi = jnp.sum(jnp.where(ohi, child, 0.0), axis=1, keepdims=True)
            gj = jnp.sum(jnp.where(ohj, child, 0.0), axis=1, keepdims=True)
            child = jnp.where(ohi & fire, gj, child)
            child = jnp.where(ohj & fire, gi, child)
        else:
            raise ValueError(f"unknown mutate kind {mutate!r}")

        # Write deme d into output column d of the group: the row-major
        # reshape of (K, G/D, D, Lp) interleaves all demes (row index
        # r·G + i·D + d — the same riffle as the D=1 layout).
        out_dtype = jnp.bfloat16 if bf16_genes else jnp.float32
        child = child.astype(out_dtype)
        if "no_riffle" in ablate:
            out_ref[d * K : (d + 1) * K, :] = child
        else:
            out_ref[:, 0, d, :] = child
        if bf16_genes:
            # Score the STORED genes: evaluating the pre-rounding f32
            # child would return scores the written bf16 genomes don't
            # achieve.
            child = child.astype(jnp.float32)

        if obj is not None:
            # Fused evaluation: score the children while they're in VMEM,
            # skipping the separate per-generation evaluation pass over
            # HBM. ``obj`` here is the objective's ROWWISE form
            # ((K, L) -> (K,) with axis=1 reductions): a per-genome fn
            # under jax.vmap unrolls into K scalar reductions in Mosaic
            # (~100× slower, measured). Objectives whose reductions are
            # invariant to zero pad lanes declare ``pad_ok`` and receive
            # the full lane-aligned (K, Lp) child — the (K, L) slice is
            # a misaligned relayout that measured ~1 ms/gen at 1M×100.
            # Scores write as ONE contiguous (1,1,K) row per deme —
            # routing them through the genome output's column mapping
            # would mean a K-element strided scatter per deme, which
            # costs ~12 ms/gen at 1M pop (measured); the caller instead
            # applies a cheap (G,K) transpose to match the
            # riffle-shuffled genome row order.
            child_scores = obj(
                child if obj_pad_ok else child[:, :L],
                *[r[:] for r in const_refs],
            ).astype(jnp.float32)
            rest[n_consts + 1][0:1, d : d + 1, :] = child_scores.reshape(
                1, 1, K
            )


def make_pallas_breed(
    pop_size: int,
    genome_len: int,
    *,
    deme_size: Optional[int] = None,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    mutation_rate: float = 0.01,
    mutation_sigma: float = 0.0,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    elitism: int = 0,
    fused_obj: Optional[Callable] = None,
    fused_consts: tuple = (),
    gene_dtype=jnp.float32,
    _demes_per_step: Optional[int] = None,
    _ablate: tuple = (),
) -> Optional[Callable]:
    """Build the fused breed: ``(genomes (P,L), scores (P,), key[, mparams])
    -> next_genomes (P, L)`` — or, with ``fused_obj``, ``-> (next_genomes,
    next_scores)`` with evaluation done inside the kernel. ``gene_dtype``
    bfloat16 selects parents with a single exact bf16 matmul (half the
    FLOPs/traffic of the f32 hi/lo path) at bf16 gene resolution.

    ``mutate_kind`` selects the in-kernel mutation ("point" or
    "gaussian"); its parameters are RUNTIME inputs — pass ``mparams``
    (shape (1, 2) f32: [rate, sigma]) per call to anneal without
    recompiling, or omit it to use the construction-time defaults.

    ``elitism`` > 0 (fused only): the top-e of the incoming generation
    overwrite rows 0..e-1 of the outgoing one, with their scores — the
    same slots the XLA breed uses (``ops/step.py``).

    Populations that no deme size divides exactly are padded internally
    to the next deme multiple: pad rows are excluded from tournaments
    in-kernel (see ``_breed_kernel``) and tail children carry -inf fused
    scores, so the padded rows are inert — the caller still sees exactly
    ``(P, L)``. Returns None when unsupported (population under one deme
    tile, an unsupported dtype, or elitism without fused scores)."""
    if not _supported():
        return None
    if gene_dtype not in (jnp.float32, jnp.bfloat16):
        return None
    if crossover_kind not in ("uniform", "order"):
        return None
    if mutate_kind not in ("point", "gaussian", "swap"):
        return None
    if crossover_kind == "order" and gene_dtype != jnp.float32:
        # Permutation genomes decode cities as floor(g*L); bf16 gene
        # resolution (~0.004 near 1.0) would corrupt decodes wholesale.
        return None
    if crossover_kind == "order" and genome_len > 256:
        # The order crossover unrolls L trace-time steps; beyond a few
        # hundred the Mosaic program size balloons (only L≈100, the
        # reference driver's scale, is measured). Longer permutations
        # fall back to the XLA scan path.
        return None
    if not (1 <= tournament_size <= 16):
        # Documented engine contract (k beyond 16 is a configuration
        # smell — selection pressure ~k/(k+1) saturates). Rank-space
        # sampling makes the in-kernel cost k-independent, so the cap is
        # a contract bound, not a resource one.
        return None
    # Selection strategies beyond the reference's single-member enum
    # (``pga.h:37-42``): each is one inverse-CDF line in rank space.
    # Defaults/ranges live in ONE place (ops/select.resolve_selection,
    # shared with the XLA path) so the two paths cannot drift; invalid
    # kinds/params raise rather than silently falling back.
    from libpga_tpu.ops.select import resolve_selection

    selection_param = resolve_selection(selection_kind, selection_param)
    if elitism > 0 and fused_obj is None:
        # The epilogue needs next-generation scores; without fused
        # evaluation the caller (engine run loop) applies elitism itself.
        return None
    bf16_genes = gene_dtype == jnp.bfloat16
    if not deme_size:
        deme_size = auto_deme_size(gene_dtype)
    P, L = pop_size, genome_len
    Lp = math.ceil(L / LANE) * LANE

    # Rank-space selection holds one (K, K) rank cube regardless of k,
    # so the deme size no longer shrinks with tournament size.
    gene_bytes = 2 if bf16_genes else 4
    K = _pick_deme_size(P, deme_size, genome_lanes=Lp, gene_bytes=gene_bytes)
    if K is None:
        return None
    G = math.ceil(P / K)
    Pp = G * K  # padded row count; == P for exact-divisor populations
    # Demes per grid step: larger groups write D·Lp-contiguous bursts
    # through the riffle layout (see _breed_kernel) — the riffle's
    # strided HBM writes are a top non-matmul cost at D=1 (512-byte
    # bursts for f32 at Lp=128). Candidates must divide G and keep the
    # whole grid step within the scoped-VMEM model (long genomes that
    # compile at D=1 must not start failing grouped; K=1024 at D≥2
    # OOMs the 16 MiB scoped limit — measured).
    d_candidates = [
        d for d in (32, 16, 8, 4, 2, 1)
        if G % d == 0 and _blocks_fit(K, d, Lp, gene_bytes)
    ] or [1]
    if crossover_kind == "order":
        # The order crossover unrolls L trace-time steps per deme; D>1
        # would multiply compile size for no burst-write benefit (the
        # permutation path is compute-, not write-bound).
        D = 1
    elif _demes_per_step:
        # round an explicit request down to the largest valid candidate
        D = next((d for d in d_candidates if d <= _demes_per_step), 1)
    elif bf16_genes:
        # Measured sweet spots at 1M×100 (tools/sweep_kernel.py, round
        # 3): bf16 peaks at D=4 (K=512: 159 gens/sec vs 156-158 at
        # D∈{2,8}); f32 keeps gaining through D=16 (K=256: 134 vs 133 at
        # D=8, 124 at D=4) — its 4-byte rows need bigger bursts before
        # the riffle's strided writes stop hurting.
        D = next((d for d in d_candidates if d <= 4), 1)
    else:
        D = next((d for d in d_candidates if d <= 16), 1)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Objective constants (problem data) become real kernel inputs:
    # Pallas rejects captured array constants. Stored 2-D, replicated to
    # every grid step (index map pinned to the origin).
    consts = tuple(jnp.atleast_2d(jnp.asarray(c)) for c in fused_consts)
    if fused_obj is None:
        consts = ()

    kernel = partial(
        _breed_kernel,
        K=K,
        D=D,
        L=L,
        Lp=Lp,
        tk=tournament_size,
        sel=selection_kind,
        sel_param=selection_param,
        crossover=crossover_kind,
        mutate=mutate_kind,
        obj=fused_obj,
        obj_pad_ok=bool(getattr(fused_obj, "pad_ok", False)),
        n_consts=len(consts),
        bf16_genes=bf16_genes,
        P=P,
        ablate=tuple(_ablate),
    )

    if "no_riffle" in _ablate:
        # Ablation: contiguous deme-major writes, no inter-deme mixing —
        # measures the riffle layout's strided-write cost.
        out_specs = [pl.BlockSpec((D * K, Lp), lambda i: (i, 0))]
        out_shape = [jax.ShapeDtypeStruct((Pp, Lp), gene_dtype)]
    else:
        out_specs = [pl.BlockSpec((K, 1, D, Lp), lambda i: (0, i, 0, 0))]
        out_shape = [jax.ShapeDtypeStruct((K, G // D, D, Lp), gene_dtype)]
    if fused_obj is not None:
        # (G//D, D, K) score array tiled on its LAST TWO dims (D, K): the
        # former (G, 1, K) layout's middle singleton was sublane-padded
        # 1→8 by Mosaic tiling, making every score write move 8× the
        # bytes. (A flat (G, K) array with (D, K) blocks would be ideal
        # but Pallas requires block dims divisible by (8, 128) unless
        # they equal the array dims — D=4 would be rejected.)
        out_specs.append(pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((G // D, D, K), jnp.float32))

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i: (0,) * c.ndim)

    call = pl.pallas_call(
        kernel,
        grid=(G // D,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)),
            pl.BlockSpec((D * K, Lp), lambda i: (i, 0)),
        ] + [_const_spec(c) for c in consts],
        out_specs=out_specs if fused_obj is not None else out_specs[0],
        out_shape=out_shape if fused_obj is not None else out_shape[0],
    )

    default_params = jnp.asarray(
        [[mutation_rate, mutation_sigma]], dtype=jnp.float32
    )

    def compute_ranks(scores, k_tie):
        """In-deme ranks (0 = best) for ``scores (..., Pp)`` →
        ``(..., G//D, D, K)`` f32, via ONE two-key sort flattened over
        every leading dim (an island runner passes (I, Pp) so the sort
        runs at (I·G, K) — a per-island vmapped sort measured ~3.4 ms
        per 8×131k generation vs ~0.9 flattened). Keys, in order:

        1. negated scores, with NaN pinned to -inf first so NaN rows
           rank last among real rows instead of after the pads (XLA's
           sort order puts NaN above +inf);
        2. a fresh random word per row, so SCORE TIES are broken in a
           new uniform random order every generation — each tied row's
           expected selection mass is then exactly uniform over the tie
           block (an index tie-break would systematically favor
           low-index rows of wide tie blocks, e.g. onemax_bits with its
           L+1 distinct score levels). Pad rows get the maximal tie key
           (real rows' keys are shifted into [0, 2^31)), so they still
           sort strictly after every real row and sampling rank < V can
           never select one.
        """
        lead = scores.shape[:-1]
        N = math.prod(lead) if lead else 1
        if "no_rank_sort" in _ablate:
            # Ablation harness only: raw scores where ranks belong —
            # selection semantics are garbage but the cost shape is
            # right, isolating the sort+argsort cost.
            return scores.reshape(*lead, G // D, D, K).astype(jnp.float32)
        s_real = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
        neg = -s_real.reshape(N * G, K).astype(jnp.float32)
        tb = jax.lax.shift_right_logical(
            jax.random.bits(k_tie, (N, Pp)), jnp.uint32(1)
        )
        if Pp != P:
            tb = jnp.where(
                jnp.arange(Pp, dtype=jnp.int32)[None, :] < P,
                tb,
                jnp.uint32(0xFFFFFFFF),
            )
        row_iota = jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[None, :], (N * G, K)
        )
        _, _, order = jax.lax.sort(
            (neg, tb.reshape(N * G, K), row_iota), dimension=1, num_keys=2
        )
        ranks = jnp.argsort(order, axis=1, stable=True).astype(jnp.float32)
        return ranks.reshape(*lead, G // D, D, K)

    def padded_ranks(gp, scores, ranks, key, mparams=None):
        """``breed_padded`` with the deme ranks precomputed (see
        ``compute_ranks``): island runners hoist the rank sort above
        their per-island vmap and call this per island. With ranks from
        ``compute_ranks(scores, k_tie)`` where ``(_, k_tie) =
        split(key)``, this returns exactly what ``breed_padded(gp,
        scores, key)`` would. ``scores`` are still needed for the
        elitism epilogue (elites carry from the PREVIOUS generation)."""
        if mparams is None:
            mparams = default_params
        k_seed, _ = jax.random.split(key)
        seed = jax.random.randint(
            k_seed, (1, 1), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max,
            dtype=jnp.int32,
        )
        out = call(seed, mparams, ranks, gp, *consts)
        if fused_obj is not None:
            genomes, child_scores = out
            # Genome row order after reshape is (child r)·G + (deme i);
            # kernel scores come out deme-major (G, K) — transpose to match.
            if "no_riffle" in _ablate or "no_score_t" in _ablate:
                s2 = child_scores.reshape(Pp)
            else:
                s2 = child_scores.reshape(G, K).T.reshape(Pp)
            if Pp != P:
                s2 = jnp.where(
                    jnp.arange(Pp, dtype=jnp.int32) < P, s2, -jnp.inf
                )
            g2 = genomes.reshape(Pp, Lp)
            if elitism > 0:
                g2, s2 = _carry_elites(gp, scores, g2, s2, elitism)
            return g2, s2
        return out.reshape(Pp, Lp)

    def breed_padded(gp, scores, key, mparams=None):
        """(Pp, Lp)-padded variant for loops that keep the pad resident.
        Takes/returns genomes (Pp, Lp) and scores (Pp,); when fused, tail
        child scores (rows >= P) come back masked to -inf so loop
        reductions and target checks never see a discarded child."""
        _, k_tie = jax.random.split(key)
        ranks = compute_ranks(scores, k_tie)
        return padded_ranks(gp, scores, ranks, key, mparams)

    def breed(genomes, scores, key, mparams=None):
        gp = genomes.astype(gene_dtype)
        if Lp != L or Pp != P:
            gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
        if Pp != P:
            scores = jnp.pad(scores, (0, Pp - P), constant_values=-jnp.inf)
        out = breed_padded(gp, scores, key, mparams)
        if fused_obj is not None:
            g2, s2 = out
            return g2[:P, :L], s2[:P]
        return out[:P, :L]

    breed.padded = breed_padded
    breed.padded_ranks = padded_ranks
    breed.compute_ranks = compute_ranks
    breed.Lp = Lp
    breed.Pp = Pp
    breed.K = K
    breed.D = D  # actual demes-per-step (an explicit request may round down)
    breed.fused = fused_obj is not None
    breed.gene_dtype = gene_dtype
    breed.takes_params = True
    breed.default_params = default_params
    breed.elitism = elitism
    breed.crossover_kind = crossover_kind
    return breed


def make_pallas_run(
    obj: Callable,
    *,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    mutation_rate: float = 0.01,
    mutation_sigma: float = 0.0,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    elitism: int = 0,
    deme_size: Optional[int] = None,
    donate: bool = True,
    gene_dtype=jnp.float32,
) -> Optional[Callable]:
    """Build a per-shape factory for the fused run loop used by ``PGA.run``:
    ``build(pop_size, genome_len)`` returns a jitted
    ``(genomes, key, n, target, mparams) -> (genomes, scores, gens)`` with
    the same contract as the XLA path in ``engine._compiled_run`` (plus
    the runtime mutation-params input — see ``make_pallas_breed``), or
    None when unsupported (non-TPU backend, tournament size out of the
    kernel's 1..16 range, or per-shape inside the factory) — the engine
    then falls back to the XLA path."""
    if not _supported():
        return None
    # The Mosaic kernel only lowers on TPU; an explicit use_pallas=True on
    # CPU/GPU must fall back, not crash at trace time. (make_pallas_breed
    # itself stays platform-agnostic so force_tpu_interpret_mode tests can
    # call it on CPU.)
    import jax as _jax

    if _jax.default_backend() != "tpu":
        return None

    from libpga_tpu.ops.evaluate import evaluate as _evaluate

    # Objectives carrying a ``kernel_rowwise`` batched form evaluate
    # INSIDE the breed kernel (children are scored while still in VMEM),
    # eliminating the separate per-generation evaluation pass over HBM
    # (~2 ms/gen at 1M×100; see BASELINE.md). The attribute is an explicit
    # opt-in set only on builtins verified to lower under Mosaic. Problem
    # data the rowwise form needs (e.g. the NK table) is declared via
    # ``kernel_rowwise_consts`` and becomes extra kernel inputs.
    fused_obj = getattr(obj, "kernel_rowwise", None)
    fused_consts = tuple(getattr(obj, "kernel_rowwise_consts", ()))

    def build(pop_size: int, genome_len: int):
        breed = make_pallas_breed(
            pop_size, genome_len,
            deme_size=deme_size, tournament_size=tournament_size,
            selection_kind=selection_kind,
            selection_param=selection_param,
            mutation_rate=mutation_rate,
            mutation_sigma=mutation_sigma,
            crossover_kind=crossover_kind, mutate_kind=mutate_kind,
            elitism=elitism if fused_obj is not None else 0,
            fused_obj=fused_obj, fused_consts=fused_consts,
            gene_dtype=gene_dtype,
        )
        if breed is None:
            return None

        P, L, Pp, Lp = pop_size, genome_len, breed.Pp, breed.Lp

        def masked_tail(s):
            """Scores for pad rows pinned to -inf: they must never win the
            target check or surface from the final population."""
            if Pp == P:
                return s
            return jnp.where(jnp.arange(Pp, dtype=jnp.int32) < P, s, -jnp.inf)

        def run_loop(genomes, key, n, target, mparams):
            # Pad once; the loop carries the deme-aligned (Pp, Lp) matrix.
            # Evaluation reads the [:P, :L] view (the slice fuses into the
            # objective's reduction — nothing materializes).
            gp = genomes.astype(gene_dtype)
            if Lp != L or Pp != P:
                gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
            scores0 = masked_tail(
                jnp.pad(_evaluate(obj, gp[:P, :L]), (0, Pp - P))
            )

            def cond(carry):
                g, s, k, gen = carry
                return jnp.logical_and(gen < n, jnp.max(s) < target)

            def body(carry):
                g, s, k, gen = carry
                k, sub = jax.random.split(k)
                if breed.fused:
                    # tail already -inf; elitism applied inside breed
                    g2, s2 = breed.padded(g, s, sub, mparams)
                else:
                    g2 = breed.padded(g, s, sub, mparams)
                    s2 = masked_tail(jnp.pad(
                        _evaluate(obj, g2[:P, :L]), (0, Pp - P)
                    ))
                    if elitism > 0:
                        g2, s2 = _carry_elites(g, s, g2, s2, elitism)
                return (g2, s2, k, gen + 1)

            init = (gp, scores0, key, jnp.int32(0))
            g, s, k, gens = jax.lax.while_loop(cond, body, init)
            return g[:P, :L], s[:P], gens

        return jax.jit(run_loop, donate_argnums=(0,) if donate else ())

    return build
