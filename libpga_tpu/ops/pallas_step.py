"""Fused Pallas generation step — the TPU fast path.

One kernel = one whole generation of breeding: k-way tournament selection
(k ≤ 16; default 2), uniform crossover, and point or gaussian mutation,
fused over a VMEM-resident deme of the population — plus optional
in-kernel evaluation and elitism. This is the TPU answer to the reference's hot loop, which
issues ceil(pop/512) chunked launches per operator with a full device sync
after each (``/root/reference/src/pga.cu:62-77,269``): here the entire
population breeds in one pass over HBM with zero intermediate HBM traffic.

Why not XLA alone? The naive formulation is random-access bound: tournament
score lookups and parent row gathers are scalar/row gathers that XLA lowers
at ~10 ns per access (measured ~60 ms per generation at 1M×100 on v5e).
This kernel removes all HBM random access:

- **Demes**: the population is processed in blocks ("demes") of ``K``
  rows that live entirely in VMEM. Selection happens *within* a deme, so
  every random access is on-chip.
- **Selection in rank space**: each deme's rows are ranked outside the
  kernel (one two-key sort per generation — score first, then a fresh
  random word so ties shuffle uniformly; NaNs last among real rows,
  pads strictly last), and the k-way tournament winner is *sampled
  directly in rank space* — the winner's rank is the minimum of k
  i.i.d. uniform candidate ranks, whose inverse CDF is
  ``floor(V·(1-(1-u)^{1/k}))``. The winner-SCORE distribution is exact
  (``P(rank=r) = ((V-r)^k - (V-r-1)^k)/V^k``, identical to drawing k
  candidates and keeping the best score), and within a score-tie block
  the per-generation random tie order makes each row's expected
  selection mass exactly uniform (draws within one generation share the
  realized order — the only deviation from fully independent candidate
  draws). No per-candidate score lookups, no winner fold, and the cost
  is independent of k. The winner's parent row is then gathered by a
  one-hot matmul (``onehot @ genomes``), which the MXU executes at full
  tilt. Gene matrices multiply as a bf16 hi/lo split (``g ≈ hi + lo``),
  giving ~1e-5 absolute accuracy on [0,1) genes — far below mutation
  noise — at 2× bf16 FLOPs instead of slow f32 MXU.
- **In-kernel PRNG**: ``pltpu.prng_random_bits`` generates tournament
  indices, crossover masks, and mutation draws in registers, so no
  ``(P, L)`` random pool ever touches HBM (the reference materializes
  exactly such a pool per generation, ``pga.cu:99-105``).
- **Free global mixing, in place**: deme membership changes every
  generation. On the fused default this is the ALIAS-COMPATIBLE
  PING-PONG layout (see the layout-algebra block below): children are
  written in place over the rows their grid step read
  (``input_output_aliases`` — no staged output buffer, no strided
  riffle writes) and the reshuffle comes from alternating two row
  groupings by generation parity. Elsewhere the riffle layout remains:
  children written through the output ``BlockSpec`` index map into a
  ``(K, G, L)`` layout whose free row-major reshape back to ``(P, L)``
  interleaves all demes. Either way selection is panmictic over a
  few-generation horizon.

Semantics note: selection is a tournament *within the current deme* (a
random cohort of ``K`` that reshuffles every generation), not i.i.d. over
the full population. Selection intensity is identical to the panmictic
tournament; only opponent locality differs, and the per-generation
riffle shuffle randomizes it. Measured equivalence
(``tools/selection_equivalence.py``, BASELINE.md): selection intensity
within 0.6% of the panmictic XLA path at every deme size, takeover time
within 1.5%, OneMax generations-to-99%-optimum within 0.5%. The
exact-panmictic path remains available via the XLA breed step
(``use_pallas=False``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from libpga_tpu.robustness import faults as _faults

LANE = 128

# Every ablation flag the kernel factories understand, each consumed by
# tools/ablate_kernel.py or tools/ablate_floor.py. A typo'd flag used to
# be silently ignored — the variant would measure the FULL kernel and the
# attribution table would carry a wrong number — so unknown names now
# raise at build time (see _validate_ablate).
_VALID_ABLATE = frozenset({
    "copy_only",       # pure-copy kernel (floor harness)
    "no_riffle",       # contiguous deme-major output layout
    "alias_io",        # in-place output over the input buffer
    "serial_grid",     # "arbitrary" grid dimension semantics
    "no_rank_sort",    # skip the host-side rank sort (copy variants)
    "no_score_t",      # skip the score transpose in padded_ranks
    "scatter_scores",  # pre-round-5 per-deme score stores
    "sel_const",       # identity selection (no sampling/one-hot build)
    "no_matmul",       # skip the parent-gather matmul
    "no_cross",        # skip crossover
    "no_mut",          # skip mutation
    "no_freeze",       # multigen: disable the target-freeze predicate
    "no_rank_cube",    # multigen: identity in-kernel ranks
})

# Flags that change the OUTPUT LAYOUT itself; the ping-pong layout has
# its own addressing, so these only combine with the riffle layout.
_LAYOUT_ABLATE = frozenset({
    "copy_only", "no_riffle", "alias_io", "no_score_t", "scatter_scores",
})


def _validate_ablate(ablate) -> tuple:
    """Reject unknown ablation-flag names at build time. A silently
    ignored typo (e.g. ``"no_rifle"``) makes the harness measure the
    full kernel where a component was meant to be removed."""
    ablate = tuple(ablate)
    unknown = sorted(set(ablate) - _VALID_ABLATE)
    if unknown:
        raise ValueError(
            f"unknown ablation flag(s) {unknown}; valid flags are "
            f"{sorted(_VALID_ABLATE)}"
        )
    return ablate


# ---------------------------------------------------------------------
# Ping-pong layout algebra (the alias-compatible replacement for the
# riffle shuffle — ISSUE 3 tentpole).
#
# The riffle layout scatters each grid step's children across every
# other step's read rows, which is exactly why in-place output aliasing
# was gated to the non-shippable ``no_riffle`` ablation. The ping-pong
# scheme instead uses a GENERATION-PARITY PAIR of row groupings in which
# every grid step writes only the rows it reads — so
# ``input_output_aliases`` is sound by construction — while deme-cohort
# membership still reshuffles across generations:
#
# - parity 0 ("even" generations): grid step i owns the CONSECUTIVE row
#   slab [i*W, (i+1)*W) (W = demes_per_step * K rows);
# - parity 1 ("odd" generations): the population is viewed as
#   (A, S, q, Lp) with A = W/q chunks of q rows and S grid steps, and
#   step i owns the STRIDED comb {a*S*q + i*q + o : a < A, o < q} —
#   A chunks of q consecutive rows at stride S*q.
#
# q is the dtype's native sublane tile (8 rows f32, 16 bf16), the
# finest granularity a BlockSpec can address. Both groupings partition
# the Pp rows into S groups of W rows; within a group the kernel breeds
# D READ demes of K consecutive group-local rows each.
#
# CRUCIALLY, a generation READS layout A but WRITES layout B (within
# the same rows — the aliasing license is row-SET equality per step,
# not per row): deme d's children are written INTERLEAVED across the
# whole group — child chunk u of deme d lands at group chunk
# ``u*D + d`` (a single middle-axis store on a (T, D, q, Lp)-factored
# block, the same proven pattern as the riffle kernel's
# ``out_ref[:, 0, d, :]``). Without this cross-deme write scatter the
# scheme provably fragments: read==write per DEME makes each deme's
# rows a closed set under one parity, and the two parities' closures
# leave disconnected super-block islands (a cohort-dynamics simulation
# shows takeover never completing — see tools/selection_equivalence.py
# --simulate, which guards this exact property). With the interleave,
# one parity-0 + parity-1 pair spreads any lineage across the full row
# range (the parity-1 comb's interleaved writes span all of [0, Pp)),
# and the cohort graph mixes in a handful of generations — the
# deme-cohort reshuffle property the riffle provided, now at in-place
# write cost.
#
# ``pingpong_admissible`` still hard-gates on A >= S (W**2 >= Pp*q):
# below it the parity-1 comb of one group covers too few distinct
# even-group residues and middle index "bits" are never regrouped
# (provably disconnected for power-of-two shapes even with the write
# interleave at D = 1).
#
# ONE LEVEL UP (ISSUE 7): the same algebra extends over POPULATION
# SHARDS — ``parallel/shard_pop.py`` runs this kernel unchanged on
# each shard's local (P/S, L) block (every function below is already
# parameterized by the per-shard population, and a shard only ever
# writes its own rows, so the aliasing license is untouched), and the
# odd-parity comb STRIDE becomes a cross-shard ``ppermute``: the
# stride-S row comb of fresh children hops the shard ring each
# generation with the same u·D+d cross-chunk interleave. The comb
# property is load-bearing at that level too: a CONTIGUOUS migrating
# slab starves the parity-0 groups that don't intersect it (simulated
# deme-path takeover ~3× slower — the shard-level rerun of exactly
# the closed-super-block failure described above), while the stride-S
# comb touches every group. tools/selection_equivalence.py --simulate
# --pop-shards S guards the composition.
# ---------------------------------------------------------------------


def pingpong_quantum(gene_dtype) -> int:
    """Chunk granularity of the parity-1 comb: the dtype's native
    sublane tile (the finest row block a BlockSpec may address)."""
    return 16 if gene_dtype == jnp.bfloat16 else 8


def pingpong_admissible(W: int, Pp: int, q: int) -> bool:
    """True when the parity pair fully mixes: ``A >= S`` (with A = W/q
    chunks per group and S = Pp/W groups), i.e. every even group spans
    every odd group and vice versa. Below that threshold the two static
    partitions provably leave disconnected row components (for
    power-of-two shapes the middle index bits are never regrouped), so
    the layout must not ship."""
    if W <= 0 or W % q or Pp % W:
        return False
    return (W // q) >= (Pp // W)


def pingpong_group_rows(parity: int, i: int, *, W: int, S: int, q: int):
    """Physical rows grid step ``i`` both READS and WRITES under the
    given parity — the single source of truth for the layout algebra,
    mirrored by the BlockSpec index maps and pinned against the kernels
    by the structural tests (tests/test_pingpong.py)."""
    import numpy as np

    if parity == 0:
        return np.arange(i * W, (i + 1) * W, dtype=np.int64)
    A = W // q
    a = np.arange(A, dtype=np.int64)[:, None]
    o = np.arange(q, dtype=np.int64)[None, :]
    return (a * (S * q) + i * q + o).reshape(-1)


def pingpong_perm(parity: int, Pp: int, W: int, q: int):
    """READ-cohort-order -> physical-row permutation: entry ``g*W + x``
    is the physical row of group ``g``'s local row ``x`` (local rows in
    group-chunk order; read deme d = local rows [d*K, (d+1)*K)). Parity
    0 is the identity; parity 1 is the strided comb."""
    import numpy as np

    S = Pp // W
    return np.concatenate([
        pingpong_group_rows(parity, i, W=W, S=S, q=q) for i in range(S)
    ])


def pingpong_child_rows(
    parity: int, Pp: int, K: int, q: int, D: int, B: int = 1
):
    """WRITE placement: entry ``g*W + dd*K + k`` is the physical row
    where group ``g``'s deme ``dd``'s child ``k`` lands. Within each
    sub-block of D demes, child chunk ``u`` of deme ``d`` is written to
    sub-block chunk ``u*D + d`` — the cross-deme interleave that makes
    the parity pair mix (see the layout-algebra block above). The row
    SET per group equals ``pingpong_group_rows`` (the aliasing
    license); only the within-group placement differs from read
    order."""
    import numpy as np

    W = B * D * K
    S = Pp // W
    T = K // q
    rows = np.empty(Pp, np.int64)
    for g in range(S):
        grp = pingpong_group_rows(parity, g, W=W, S=S, q=q)
        for b in range(B):
            for d in range(D):
                dd = b * D + d
                u = np.arange(T)[:, None]
                o = np.arange(q)[None, :]
                m = b * D * T + u * D + d      # sub-block interleave
                local = (m * q + o).reshape(-1)
                rows[g * W + dd * K : g * W + (dd + 1) * K] = grp[local]
    return rows


def _valid_deme(k: int) -> bool:
    """Power of two in [128, 1024]: K=128 is the smallest MXU-efficient
    tile; above 1024 the one-hot matmul FLOPs dominate; tiny demes
    collapse tournament-2 toward cloning and produce sub-tile shapes."""
    return bool(k) and not (k & (k - 1)) and 128 <= k <= 1024


def _scoped_vmem_bytes(K: int, D: int, Lp: int, gene_bytes: int) -> int:
    """Conservative model of the kernel's scoped-VMEM stack for one grid
    step, calibrated against hardware compiles (Mosaic's scoped limit is
    16 MiB): genome in+out blocks (D·K·Lp each), the selection one-hots
    (two bf16 K×K planes plus an f32 temp's worth of headroom), and one
    deme's row intermediates (f32 parents/child, bf16 hi/lo for f32
    genes, the crossover mask). Measured anchors (with the former
    in-kernel rank cube, which this model retains as headroom): f32
    K=1024 D=1 at Lp=128 compiles, D=4 OOMs at 18.26M reported; bf16
    K=256 Lp=2048 D=2 compiles, K=512 Lp=2048 fails (row term alone
    16.8M)."""
    blocks = 2 * D * K * Lp * gene_bytes
    cubes = K * K * (4 + 2 + 2)
    rows = K * Lp * (3 * 4 + 4 + (4 if gene_bytes == 4 else 0))
    return blocks + cubes + rows


_SCOPED_VMEM_LIMIT = 14_500_000  # of the 16 MiB scoped stack; f32 K=1024
# D=4 at Lp=128 models 15.2M and OOMs on hardware, D=2 models 13.1M and runs

# Mosaic double-buffers the pipelined genome in+out blocks, so their raw
# bytes are bounded separately from the additive stack model. Anchors:
# f32 K=256 D=16 at Lp=128 compiles (8.4M doubled), D=32 OOMs (16.8M);
# bf16 K=256 D=32 compiles (8.4M doubled).
_BLOCK_BYTES_LIMIT = 8_650_000


def _blocks_fit(
    K: int, D: int, Lp: int, gene_bytes: int, extra_scoped: int = 0
) -> bool:
    """``extra_scoped``: additional scoped-VMEM bytes the kernel variant
    carries (e.g. the order-crossover walk's scratch planes) — counted
    against the SAME budget as the base model, so every admission path
    (deme pick, D-candidate scan) sees the true total."""
    return (
        4 * D * K * Lp * gene_bytes <= _BLOCK_BYTES_LIMIT
        and _scoped_vmem_bytes(K, D, Lp, gene_bytes) + extra_scoped
        <= _SCOPED_VMEM_LIMIT
    )


def _pick_deme_size(
    pop_size: int,
    preferred: int,
    genome_lanes: int = LANE,
    gene_bytes: int = 4,
    fits=None,
):
    """Deme size for a population: exact divisors first (zero padding),
    then a padded fit — the kernel pads the population up to the next
    deme multiple and masks the pad rows out of selection.

    ``genome_lanes`` (the lane-padded genome length) bounds the deme via
    the scoped-VMEM model (``_scoped_vmem_bytes`` at D=1) — e.g. K=512
    at Lp=2048 needs ~23 MB and fails to compile, K=256 fits (measured).
    Genomes too long for even K=128 fall back to the XLA path.

    Padded fits must keep the short tail deme healthy: a tail of
    ``tail = P - (G-1)K`` valid rows breeds K children from only
    ``tail`` candidates, so tails under K/4 rows (degenerate case: a
    single row cloning itself into ~1/G of the population with zero
    fitness pressure) are rejected. Among healthy fits, wastes up to
    12.5% of the population are treated as equivalent (per-deme
    overheads outweigh small waste: K=128's minimal padding at 40,000
    measured 27% slower than K=256's 192 pad rows) and the caller's
    configured size, then the larger deme, is preferred; beyond that
    the least-waste fit wins. None (→ XLA path) for populations under
    one 128-row tile or with only degenerate-tail fits.

    ``fits``: the caller's VMEM admission predicate ``fits(k) -> bool``
    (default: the one-generation model at D=1). Callers with extra
    per-kernel VMEM (multigen scratch, order-walk planes) pass their own
    so the deme pick retries SMALLER sizes when the extras don't fit at
    the preferred one."""
    if fits is None:
        def fits(k: int) -> bool:
            return _blocks_fit(k, 1, genome_lanes, gene_bytes)

    if _valid_deme(preferred) and fits(preferred) and pop_size % preferred == 0:
        return preferred
    for k in (1024, 512, 256, 128):
        if fits(k) and pop_size % k == 0:
            return k
    if pop_size < 128:
        return None
    best = None
    for k in (1024, 512, 256, 128):
        if k > pop_size or not fits(k):
            continue
        g = -(-pop_size // k)
        tail = pop_size - (g - 1) * k
        if tail < max(k // 4, 2):
            continue
        waste = g * k - pop_size
        rank = (
            waste if waste > pop_size // 8 else 0,
            0 if k == preferred else 1,
            -k,
        )
        if best is None or rank < best[0]:
            best = (rank, k)
    return best[1] if best else None


def auto_deme_size(gene_dtype, const_carrying: bool = False) -> int:
    """Measured per-dtype deme sweet spot (see BASELINE.md round 5).

    K=512 by default since round 5: batching the fused-eval score
    stores shifted the f32 trade-off — at 1M×100 OneMax K=512 D=8 beat
    the round-4 default K=256 D=16 174.5 vs 167.6 median (4/5
    interleaved rounds, where the pre-batching kernel measured the
    opposite ordering), and the trap shape agrees (160.9 vs 147.6).
    EXCEPTION: f32 objectives whose fused evaluation carries kernel
    constants (``const_carrying`` — the NK-class table lookups) keep
    K=256: the NK-4M interleave shows 256/16 at 31.8 vs 512/8 at 28.3.
    Single source of truth — bench.py derives its FLOPs model from this."""
    if const_carrying and gene_dtype != jnp.bfloat16:
        return 256
    return 512


def _carry_elites(g_prev, s_prev, g2, s2, elitism: int):
    """Carry the top-e of the previous generation into rows 0..e-1 of the
    new one, scores included — the same slots the XLA breed uses
    (``ops/step.py``). Works on padded arrays: pad rows carry -inf
    scores, so they can never be selected as elites, and rows 0..e-1 are
    always real rows. The single definition serves both the fused breed
    and the non-fused run loop so the two paths cannot drift."""
    top_s, top_i = jax.lax.top_k(s_prev, elitism)
    elites = jnp.take(g_prev, top_i, axis=0).astype(g2.dtype)
    g2 = jax.lax.dynamic_update_slice(g2, elites, (0, 0))
    s2 = jax.lax.dynamic_update_slice(s2, top_s, (0,))
    return g2, s2


def _order_scratch_shapes(K: int, L: int, Lp: int):
    """VMEM scratch for the order-crossover walk (see _deme_child): two
    gene-major parent planes, their city-decode planes, the gene-major
    child (prefilled with the random-fallback genes), and the
    visited-city bitmask (ceil(L/32) i32 words per column,
    sublane-padded to 8)."""
    from jax.experimental.pallas import tpu as pltpu

    Wp = max(8, math.ceil(math.ceil(L / 32) / 8) * 8)
    return [
        pltpu.VMEM((Lp, K), jnp.float32),
        pltpu.VMEM((Lp, K), jnp.float32),
        pltpu.VMEM((Lp, K), jnp.int32),
        pltpu.VMEM((Lp, K), jnp.int32),
        pltpu.VMEM((Lp, K), jnp.float32),
        pltpu.VMEM((Wp, K), jnp.int32),
    ]


def _supported() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except ImportError:
        return False
    return True


def _grid_compiler_params(ablate=()):
    """Grid dimension declared PARALLEL: every grid step reads only its
    own deme-group block and writes only its own output blocks (the
    per-step PRNG reseed is index-keyed, and all scratch is written
    before read within a step), so Mosaic may overlap step i's output
    DMA with step i+1's compute instead of enforcing sequential
    semantics. The ``serial_grid`` ablation flag restores the default
    "arbitrary" semantics so tools/ablate_floor.py can measure the
    difference."""
    from jax.experimental.pallas import tpu as pltpu

    params_cls = getattr(pltpu, "TPUCompilerParams", None) or getattr(
        pltpu, "CompilerParams"
    )
    sem = "arbitrary" if "serial_grid" in ablate else "parallel"
    return params_cls(dimension_semantics=(sem,))


def _deme_child(
    g,
    R,
    Vf,
    uniform,
    mask_words,
    d,
    *,
    K,
    L,
    Lp,
    tk,
    sel,
    sel_param,
    crossover,
    mutate,
    rate,
    sigma,
    lane_ok,
    bf16_genes,
    elite_rows=0,
    order_refs=None,
    cross_consts=(),
    mut_consts=(),
    ablate=(),
):
    """Breed one deme's K children: rank-space selection + crossover +
    mutation, all on VMEM values. The SINGLE definition of in-kernel
    breeding, shared by the one-generation kernel (``_breed_kernel``,
    ranks precomputed outside) and the multi-generation kernel
    (``_multigen_kernel``, ranks computed in-kernel per sub-generation)
    so the two cannot drift.

    ``crossover`` / ``mutate`` are either builtin kind names or the
    CALLABLE rowwise forms of expression operators
    (``ops/breed_expr.py``) — a custom C/Python breeding operator
    evaluated on the VMEM-resident parents at device speed, the kernel
    analog of the reference's ``__device__`` callback pointers
    (``pga.h:47-48``). ``cross_consts``/``mut_consts`` carry their
    registered constants (already lane-padded kernel inputs).

    Args: ``g`` (K, Lp) genomes in their STORED dtype; ``R`` (1, K) f32
    in-deme ranks (0 = best, strict total order, pads ranked >= V);
    ``Vf`` f32 valid-row count; ``uniform(shape)`` the kernel's PRNG
    draw; ``mask_words`` the (K, Lp) crossover-mask PRNG tile shared by
    the deme group (deme ``d`` reads bit d), or None for non-uniform
    crossover; ``rate``/``sigma`` runtime mutation params.

    ``order_refs`` (order crossover only): the six VMEM scratch refs of
    ``_order_scratch_shapes`` — gene-major parent/city planes, the
    gene-major child, and the per-column visited-city bitmask — declared
    by the owning pallas_call and reused across demes/sub-generations.

    ``elite_rows`` > 0 turns rows 0..e-1 into verbatim copies of the
    deme's rank-0..e-1 rows: both winner ranks are forced to the row
    index, the crossover output of those rows is overwritten with the
    gathered parent (uniform crossover of identical parents is already
    the identity, but order crossover is NOT — duplicate-city decodes
    regenerate random genes), and mutation is gated off. Per-deme elites
    preserve the global top-e: each global top-j row (j <= e) is within
    the top-e of its own deme. Returns the child block (K, Lp) f32.
    """
    import jax.lax as lax

    # ---- rank-space tournament selection --------------------------
    if "sel_const" in ablate:
        # Ablation harness (tools/ablate_kernel.py): identity
        # selection isolates the sampling + one-hot cost from the
        # parent matmuls.
        oh = (
            lax.broadcasted_iota(jnp.int32, (2 * K, K), 0) % K
            == lax.broadcasted_iota(jnp.int32, (2 * K, K), 1)
        ).astype(jnp.bfloat16)
    else:
        u_t = uniform((2, K)).T  # (K, 2): one winner draw per parent
        if sel != "tournament":
            # Truncation / linear ranking: the SAME inverse-CDF
            # helper the XLA operators use (ops/select.py), so the
            # two paths sample provably identical distributions.
            # The cohort argument for panmictic equivalence applies
            # identically (see module docstring).
            from libpga_tpu.ops.select import rank_fraction_icdf

            x = rank_fraction_icdf(sel, sel_param, u_t)
        elif tk == 1:
            x = u_t
        elif tk & (tk - 1) == 0:
            # The k-way tournament winner is the candidate with the
            # minimum rank; for k i.i.d. uniform candidate draws over V
            # valid rows that minimum has inverse CDF
            # rank = floor(V·(1-(1-u)^{1/k})):
            # P(rank=r) = ((V-r)^k - (V-r-1)^k)/V^k, exactly the
            # distribution of drawing k candidates and keeping the best
            # score. One uniform per parent replaces 2k candidate draws
            # + 2k score lookups, at k-independent cost. Power-of-two k
            # uses repeated sqrt; other k the exp/log form.
            t = 1.0 - u_t
            for _ in range(tk.bit_length() - 1):
                t = jnp.sqrt(t)
            x = 1.0 - t
        else:
            x = 1.0 - jnp.exp(jnp.log(1.0 - u_t) * jnp.float32(1.0 / tk))
        # Two-sided clamp: floor can graze V at f32 precision (x·V
        # rounding up), and linear_rank's x can go fractionally
        # NEGATIVE at u≈0 if the VPU's sqrt(s²-4(s-1)u) rounds a ulp
        # above s — wr=-1 would match no rank and breed a zero row.
        wr = jnp.clip(jnp.floor(x * Vf), 0.0, Vf - 1.0)  # (K, 2) ranks

        if elite_rows:
            # Rows 0..e-1 reproduce the deme's best e rows verbatim:
            # both winner ranks are forced to the row index, the
            # crossover OUTPUT of those rows is overwritten with the
            # gathered parent below (order crossover is NOT the
            # identity on identical parents — duplicate city decodes
            # regenerate random genes), and mutation is gated off.
            # min() guards a tail deme with fewer than e valid rows.
            row_col = lax.broadcasted_iota(jnp.int32, (K, 1), 0)
            forced = jnp.minimum(row_col.astype(jnp.float32), Vf - 1.0)
            wr = jnp.where(row_col < elite_rows, forced, wr)

        # Winner one-hots by rank equality: ranks are distinct
        # integers 0..K-1 (exact in f32), so each row of the compare
        # is an exact one-hot over the deme's source rows; the two
        # parents' one-hots stack into the (2K, K) selector the single
        # selection matmul below consumes. (A direct (2K, 1)-rank
        # compare would save the concat, but Mosaic can't lower the
        # (K, 2) -> (2K, 1) reshape.)
        oh = jnp.concatenate(
            [
                (R == wr[:, 0:1]).astype(jnp.bfloat16),
                (R == wr[:, 1:2]).astype(jnp.bfloat16),
            ],
            axis=0,
        )  # (2K, K)

    # ---- parent rows via ONE one-hot matmul -----------------------
    # Both parents' one-hots stack into a (2K, K) selector so the MXU
    # runs a single large matmul instead of 2 (bf16) or 4 (f32) K-sized
    # ones — measured ~1.5× faster at K=256 (small matmuls leave the
    # systolic array underfed; the bf16 K=512 path's efficiency was the
    # tell). For f32 genes the bf16 hi/lo split halves concatenate on
    # the LANE axis, so all four products land in one
    # (2K, K)@(K, 2Lp) op and two adds reassemble ~1e-5-accurate rows.
    if "no_matmul" in ablate:
        p1 = p2 = g.astype(jnp.float32)
    else:
        if bf16_genes:
            # bf16 genomes are selected exactly (0/1 selector rows; f32
            # accumulation) — half the FLOPs and HBM traffic of f32.
            pp = jnp.dot(oh, g, preferred_element_type=jnp.float32)
            p1, p2 = pp[:K, :], pp[K:, :]
        else:
            g_hi = g.astype(jnp.bfloat16)
            g_lo = (g - g_hi.astype(jnp.float32)).astype(jnp.bfloat16)
            g_cat = jnp.concatenate([g_hi, g_lo], axis=1)  # (K, 2Lp)
            pp = jnp.dot(oh, g_cat, preferred_element_type=jnp.float32)
            p1 = pp[:K, :Lp] + pp[:K, Lp:]
            p2 = pp[K:, :Lp] + pp[K:, Lp:]

    pad_lane = None
    if Lp > L:
        pad_lane = lax.broadcasted_iota(jnp.int32, (K, Lp), 1) < L

    def _breeding_draws(uses):
        """The expression operators' random inputs, drawn ONLY for the
        streams the compiled expression references (``rows.uses`` — a
        (K, Lp) PRNG tile per unused stream is real per-generation cost
        at scale): per-gene streams get pad lanes zeroed so ``r``-
        derived values cannot leak into pad genes before the output
        mask; ``q``/``q2`` share one per-row draw."""
        zero = jnp.float32(0.0)

        def gene_stream():
            s = uniform((K, Lp))
            return jnp.where(pad_lane, s, 0.0) if pad_lane is not None else s

        r = gene_stream() if "r" in uses else zero
        r2 = gene_stream() if "r2" in uses else zero
        if uses & {"q", "q2"}:
            qq = uniform((2, K)).T  # (K, 2)
            q, q2 = qq[:, 0:1], qq[:, 1:2]
        else:
            q = q2 = zero
        return r, r2, q, q2

    if "no_cross" in ablate:
        child = p1
    elif callable(crossover):
        # Expression crossover (ops/breed_expr.py): evaluate the
        # compiled rowwise form on the freshly gathered parents, in
        # VMEM — the device-speed custom-crossover path. The rowwise
        # form clips into the gene domain; pad lanes are re-zeroed
        # (an expression like ``1 - p1`` would otherwise write pads).
        r, r2, q, q2 = _breeding_draws(
            getattr(crossover, "uses", frozenset({"r", "r2", "q", "q2"}))
        )
        child = crossover(p1, p2, r, r2, q, q2, *cross_consts, true_len=L)
        if pad_lane is not None:
            child = jnp.where(pad_lane, child, 0.0)
    elif crossover == "uniform":
        # ---- uniform crossover: per-gene coin flip (pga.cu:135-143)
        child = jnp.where(
            ((mask_words >> d) & jnp.uint32(1)) == 0, p1, p2
        )
    elif crossover == "order":
        # ---- order-preserving crossover (reference TSP driver,
        # test3/test.cu:48-64): walk gene positions left to right,
        # take p1's gene if its decoded city is unvisited, else
        # p2's, else a fresh random value. Inherently sequential in
        # L, but it runs as an in-kernel ``fori_loop`` over VMEM
        # scratch (``order_refs``) in gene-major layout:
        #
        # - a step reads/writes ONE sublane row via a dynamic ref
        #   slice — O(K) per step, where the former trace-time unroll
        #   (and the XLA scan path, ops/crossover.py) spent a full
        #   (Lp, K) select per step just to address position l;
        # - the visited set is a per-column CITY BITMASK, ceil(L/32)
        #   i32 words on sublanes, so each membership test reduces
        #   over ~L/32 sublanes instead of Lp — together ~30× less
        #   work per step at L=1000, and the runtime loop keeps the
        #   Mosaic program size L-independent (the unroll capped
        #   genome_len at 256; this path lowers for any L the VMEM
        #   model admits).
        from jax.experimental import pallas as pl

        p1t_ref, p2t_ref, c1t_ref, c2t_ref, child_ref, vis_ref = order_refs
        Wp = vis_ref.shape[0]
        p1t = p1.T  # (Lp, K) f32 — 32-bit transpose is supported
        p2t = p2.T
        p1t_ref[:] = p1t
        p2t_ref[:] = p2t
        # Hoisted out of the walk: city decodes as whole planes, and the
        # random-fallback genes prefilled into the child (a step only
        # overwrites its row when a parent gene is taken; pad rows
        # l >= L are never visited and stay 0 via the lane mask).
        c1t_ref[:] = jnp.clip(jnp.floor(p1t * L), 0, L - 1).astype(jnp.int32)
        c2t_ref[:] = jnp.clip(jnp.floor(p2t * L), 0, L - 1).astype(jnp.int32)
        rows_ok = lax.broadcasted_iota(jnp.int32, (Lp, K), 0) < L
        child_ref[:] = jnp.where(rows_ok, uniform((Lp, K)), 0.0)
        vis_ref[:] = jnp.zeros((Wp, K), jnp.int32)
        wiota = lax.broadcasted_iota(jnp.int32, (Wp, K), 0)

        def order_step(l):
            p1l = p1t_ref[pl.ds(l, 1), :]  # (1, K)
            p2l = p2t_ref[pl.ds(l, 1), :]
            c1 = c1t_ref[pl.ds(l, 1), :]
            c2 = c2t_ref[pl.ds(l, 1), :]
            vis = vis_ref[:]
            w1, b1 = c1 >> 5, jnp.int32(1) << (c1 & 31)
            w2, b2 = c2 >> 5, jnp.int32(1) << (c2 & 31)
            seen1 = jnp.any(
                (wiota == w1) & ((vis & b1) != 0), axis=0, keepdims=True
            )
            seen2 = jnp.any(
                (wiota == w2) & ((vis & b2) != 0), axis=0, keepdims=True
            )
            take1 = ~seen1
            take2 = seen1 & ~seen2
            gene = jnp.where(
                take1, p1l, jnp.where(take2, p2l, child_ref[pl.ds(l, 1), :])
            )
            mw = jnp.where(take1, w1, w2)
            mb = jnp.where(take1, b1, b2)
            vis_ref[:] = vis | jnp.where(
                (wiota == mw) & (take1 | take2), mb, 0
            )
            child_ref[pl.ds(l, 1), :] = gene

        # Partial unroll by hand (Mosaic's fori_loop supports only full
        # or no unroll): U walk steps per loop iteration cut the
        # per-iteration loop overhead ~U×; the L % U tail runs at static
        # trace-time offsets.
        U = 8
        if L >= 2 * U:

            def order_block(i, carry):
                for j in range(U):
                    order_step(i * U + j)
                return carry

            lax.fori_loop(0, L // U, order_block, jnp.int32(0))
        for l in range(L - (L % U if L >= 2 * U else L), L):
            order_step(l)
        child = child_ref[:].T  # (K, Lp)
    else:
        raise ValueError(f"unknown crossover kind {crossover!r}")

    if elite_rows:
        elite_col = (
            lax.broadcasted_iota(jnp.int32, (K, 1), 0) >= elite_rows
        )  # True where mutation may fire
        if "no_matmul" not in ablate and "sel_const" not in ablate:
            # Elite rows become the gathered parent VERBATIM: uniform
            # crossover of identical parents is already the identity,
            # but order crossover regenerates random genes at
            # duplicate-city positions even for p1 == p2.
            child = jnp.where(elite_col, child, p1)

    # ---- mutation -------------------------------------------------
    if "no_mut" in ablate:
        pass
    elif callable(mutate):
        # Expression mutation: same device-speed path; ``rate``/``sigma``
        # arrive as the kernel's runtime mparams, so annealing schedules
        # share this compilation exactly like the builtin kinds. Elite
        # rows keep the unmutated child.
        r, r2, q, q2 = _breeding_draws(
            getattr(mutate, "uses", frozenset({"r", "r2", "q", "q2"}))
        )
        mutated = mutate(
            child, r, r2, q, q2, rate, sigma, *mut_consts, true_len=L
        )
        if pad_lane is not None:
            mutated = jnp.where(pad_lane, mutated, 0.0)
        child = jnp.where(elite_col, mutated, child) if elite_rows else mutated
    elif mutate == "point":
        # Point mutation (pga.cu:127-133): one random gene per firing
        # row.
        u_t = uniform((4, K)).T  # (K, 4) f32
        pos = jnp.floor(u_t[:, 0:1] * L).astype(jnp.int32)  # in [0, L)
        cols = lax.broadcasted_iota(jnp.int32, (K, Lp), 1)
        # Strict '<' so rate=0 disables mutation exactly (the
        # reference's ``rand[1] <= chance`` gate, pga.cu:128, differs
        # only on a measure-zero event for rate in (0,1)).
        hit = (cols == pos) & (u_t[:, 1:2] < rate)
        if elite_rows:
            hit = hit & elite_col
        child = jnp.where(hit, u_t[:, 2:3], child)
    elif mutate == "gaussian":
        # Per-gene Gaussian perturbation (ops/mutate.gaussian_mutate
        # semantics): each gene independently fires with probability
        # ``rate`` and receives N(0, sigma^2) noise, clipped to
        # [0, 1). Box-Muller from two independent in-kernel uniform
        # draws; the gate draw is a third stream, so noise sign stays
        # independent of firing (see the XLA operator's docstring).
        gate = uniform((K, Lp))
        u1 = jnp.clip(uniform((K, Lp)), 1e-7, 1.0 - 1e-7)
        u2 = uniform((K, Lp))
        normal = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(
            2.0 * jnp.float32(math.pi) * u2
        )
        mutated = jnp.clip(child + sigma * normal, 0.0, 1.0 - 1e-7)
        fire = gate < rate
        if lane_ok is not None:
            fire = fire & lane_ok
        if elite_rows:
            fire = fire & elite_col
        child = jnp.where(fire, mutated, child)
    elif mutate == "swap":
        # Swap two random positions with probability ``rate``
        # (ops/mutate.swap_mutate semantics — permutation GAs).
        # Scatter-free: two lane one-hots select/exchange the genes.
        u_t = uniform((4, K)).T  # (K, 4) f32
        pi = jnp.floor(u_t[:, 0:1] * L).astype(jnp.int32)
        pj = jnp.floor(u_t[:, 1:2] * L).astype(jnp.int32)
        fire = u_t[:, 2:3] < rate
        if elite_rows:
            fire = fire & elite_col
        cols = lax.broadcasted_iota(jnp.int32, (K, Lp), 1)
        ohi = cols == pi
        ohj = cols == pj
        gi = jnp.sum(jnp.where(ohi, child, 0.0), axis=1, keepdims=True)
        gj = jnp.sum(jnp.where(ohj, child, 0.0), axis=1, keepdims=True)
        child = jnp.where(ohi & fire, gj, child)
        child = jnp.where(ohj & fire, gi, child)
    else:
        raise ValueError(f"unknown mutate kind {mutate!r}")
    return child


def _tsp_eval_gene_major(child, tableT, order_refs, *, K, L, C, penalty):
    """Score one deme's TSP children INSIDE the kernel, gene-major —
    the long-genome evaluation path (round-4 weakness 3: the XLA
    one-hot gather's (P·L, C) materialization is HBM-bound and
    dominated end-to-end 1,000-city generations).

    Coordinates come from a FACTORIZED one-hot gather: city c = 32a+b.
    Eight gene rows batch into ONE (128, A)@(A, 8K) bf16 matmul over
    their a-digit one-hots; ``tableT`` (``make_tsp_coords``
    ``duplicate_mode="genes"``) is the HI/LO bf16 split of the
    coordinates, b-digit on sublanes and a-digit on lanes — rows 0..31
    x_hi, 32..63 y_hi, 64..95 x_lo, 96..127 y_lo — because the MXU runs
    matmul OPERANDS at bf16 precision (raw f32 coordinates measured
    ±141 on a 1,000-city tour; exact 0/1 one-hots times hi+lo with f32
    accumulation recover them to ~1e-3). Each row then pays a
    32-sublane b-digit select summing the matching hi and lo planes.
    Everything stays in (sublane, K-lane) orientation —
    no per-step transposes, no per-step matmul dispatch (a first cut
    with a per-row (K, A) matmul + 4 relayout transposes per step
    measured SLOWER than the XLA gather end-to-end: 31 vs 51 gens/sec
    at 8,192×1,000). Work per gene position is O(K·(A/8 + 32)) versus
    the O(K·C) of a C-wide masked accumulation. Duplicate GENES are
    counted with the order-crossover walk's own machinery: a
    ceil(L/32)-word per-column city bitmask (``vis_ref``), one
    membership test + one insert per step — which is why this evaluator
    pairs with ``crossover_kind="order"`` (the scratch planes are
    already declared and free after the walk). Returns the (1, K)
    score row: −(open-path length + penalty·dups).
    """
    import jax.lax as lax
    from jax.experimental import pallas as pl

    _, _, c1t_ref, _, _, vis_ref = order_refs
    Wp = vis_ref.shape[0]
    childT = child.T  # (Lp, K) f32 — one 32-bit transpose per deme
    # Decode in [0, L) (the objective's contract); the coordinate
    # lookup clamps to the table separately below.
    c1t_ref[:] = jnp.clip(jnp.floor(childT * L), 0, L - 1).astype(jnp.int32)
    vis_ref[:] = jnp.zeros((Wp, K), jnp.int32)
    wiota = lax.broadcasted_iota(jnp.int32, (Wp, K), 0)
    b_iota = lax.broadcasted_iota(jnp.int32, (32, K), 0)
    A = tableT.shape[1]
    # hi rows are bf16 round-trips (exact); lo rows are f32 residuals
    # whose own bf16 rounding is ~2^-8 of an already-2^-8-scale value —
    # the composition recovers f32 coordinates to ~1e-3.
    tab_bf16 = tableT.astype(jnp.bfloat16)
    U = 8

    a_iota = lax.broadcasted_iota(jnp.int32, (A, K), 0)

    def eval_batch(i, l0, n_rows, carry):
        """Score gene rows l0..l0+n_rows-1 (n_rows <= U, static):
        ``i`` is the traced block index (tail calls pass the static
        global row instead). Per-row (A, K) a-digit one-hots are built
        FIRST and then lane-concatenated — concatenating the raw (1, K)
        row slices does not lower (their sublane offsets differ:
        Mosaic 'offset mismatch on non-concat dimension'); the compare
        outputs are full (A, K) tiles with canonical layout."""
        rows = []
        for u in range(n_rows):
            c_row = c1t_ref[pl.ds(l0 + u, 1), :]  # (1, K)
            cg = jnp.minimum(c_row, C - 1)
            rows.append((c_row, cg & 31,
                         (a_iota == (cg >> 5)).astype(jnp.float32)))
        oh_a = (
            jnp.concatenate([oh for _, _, oh in rows], axis=1)
            if n_rows > 1 else rows[0][2]
        )  # (A, n_rows*K)
        # bf16 operands: the one-hot is exact 0/1 and the table is the
        # hi/lo coordinate split, so f32-accumulated selection recovers
        # f32 coordinates (the MXU runs matmuls at bf16 operand
        # precision — raw f32 here measured ±141 on a 1,000-city tour).
        M = jnp.dot(
            tab_bf16, oh_a.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )  # (128, n_rows*K): x_hi/y_hi/x_lo/y_lo blocks per gene row
        xp, yp, total, dups = carry
        for u in range(n_rows):
            c_row, b_row, _ = rows[u]
            mxy = M[:, u * K : (u + 1) * K]  # K-aligned lane slice
            sel = b_iota == b_row
            x = jnp.sum(
                jnp.where(sel, mxy[0:32, :] + mxy[64:96, :], 0.0),
                axis=0, keepdims=True,
            )
            y = jnp.sum(
                jnp.where(sel, mxy[32:64, :] + mxy[96:128, :], 0.0),
                axis=0, keepdims=True,
            )
            d = jnp.sqrt(
                (x - xp) * (x - xp) + (y - yp) * (y - yp)
                + jnp.float32(1e-12)
            )
            step = (i * U + u) if i is not None else (l0 + u)
            if isinstance(step, int):
                if step > 0:
                    total = total + d
            else:
                total = total + jnp.where(step > 0, d, 0.0)
            # duplicate-gene count via the walk's city bitmask
            w = c_row >> 5
            bitv = jnp.int32(1) << (c_row & 31)
            vis = vis_ref[:]
            seen = jnp.any(
                (wiota == w) & ((vis & bitv) != 0), axis=0, keepdims=True
            )
            dups = dups + seen.astype(jnp.float32)
            vis_ref[:] = vis | jnp.where(wiota == w, bitv, 0)
            xp, yp = x, y
        return xp, yp, total, dups

    zero = jnp.zeros((1, K), jnp.float32)
    carry = (zero, zero, zero, zero)
    if L >= U:  # tail stays < U rows — eval_batch's design width
        carry = lax.fori_loop(
            0,
            L // U,
            lambda i, c: eval_batch(i, i * U, U, c),
            carry,
        )
    tail0 = L - L % U if L >= U else 0
    if tail0 < L:
        carry = eval_batch(None, tail0, L - tail0, carry)
    _, _, total, dups = carry
    return -(total + jnp.float32(penalty) * dups)  # (1, K)


def _breed_kernel(
    seed_ref,
    mparams_ref,
    scores_ref,
    genomes_ref,
    *rest,
    K,
    D,
    L,
    Lp,
    tk=2,
    sel="tournament",
    sel_param=None,
    crossover="uniform",
    mutate="point",
    obj=None,
    obj_pad_ok=False,
    tsp=None,
    n_consts=0,
    n_cross=0,
    n_mut=0,
    bf16_genes=False,
    P=None,
    ablate=(),
):
    """One grid step = ``D`` consecutive demes: select parents, crossover,
    mutate — and, when ``obj`` is given, evaluate the children in-kernel
    (skipping a whole extra HBM pass per generation). All VMEM/register
    work; the per-deme loop unrolls at trace time.

    Why group demes: each deme's children land in output column g of a
    ``(K, G/D, D, Lp)`` layout, so a row's writes for one grid step are
    ``D·Lp`` contiguous values instead of ``Lp`` — D× fewer, larger HBM
    bursts for the riffle shuffle (whose strided writes grew per-row cost
    ~25% from 64k to 1M population at D=1).

    ``mparams_ref`` is a (1, 2) f32 SMEM block carrying the mutation
    operator's runtime parameters ([rate, _] for point mutation,
    [rate, sigma] for gaussian) — runtime scalars so an annealing
    schedule (e.g. Rastrigin's shrinking sigma) reuses one compilation
    instead of recompiling per phase.

    ``rest`` holds, in order: ``n_consts`` objective-constant input refs
    (problem data like the NK table — Pallas forbids captured array
    constants, so fused objectives declare them via
    ``kernel_rowwise_consts`` and receive them as call arguments),
    ``n_cross`` + ``n_mut`` expression-breeding constant refs, the
    genome output ref, and (when ``obj`` or ``tsp`` is set) the score
    output ref."""
    import jax.lax as lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    const_refs = rest[:n_consts]
    cross_consts = tuple(r[:] for r in rest[n_consts : n_consts + n_cross])
    mut_consts = tuple(
        r[:] for r in rest[n_consts + n_cross : n_consts + n_cross + n_mut]
    )
    base = n_consts + n_cross + n_mut
    out_ref = rest[base]
    order_refs = rest[-6:] if crossover == "order" else None

    i = pl.program_id(0)
    if "copy_only" in ablate:
        # Floor-attribution harness (tools/ablate_floor.py): a PURE COPY
        # at the production kernel's exact grid/BlockSpec layout — no
        # PRNG, no selection, no breeding. Genomes pass through to the
        # output mapping (riffled or contiguous per the other flags) and,
        # when a score output exists, the ranks input stands in for the
        # scores so the batched (1, D, K) store cost is included. What
        # remains is exactly the memory system + grid machinery: HBM
        # read/write, the output layout's write pattern, and per-step
        # Mosaic overheads.
        g_all = genomes_ref[:]
        score_rows = []
        for d in range(D):
            child = g_all[d * K : (d + 1) * K, :]
            if "no_riffle" in ablate:
                out_ref[d * K : (d + 1) * K, :] = child
            else:
                out_ref[:, 0, d, :] = child
            if obj is not None or tsp is not None:
                score_rows.append(
                    scores_ref[0:1, d : d + 1, :].astype(jnp.float32)
                )
        if score_rows:
            rest[base + 1][:] = (
                jnp.concatenate(score_rows, axis=1)
                if D > 1 else score_rows[0]
            )
        return
    pltpu.prng_seed(seed_ref[0, 0] ^ (i * jnp.int32(-1640531527)))  # golden-ratio mix

    # NOTE on shapes: Mosaic only supports minor-dim insertion/transpose
    # for 32-bit types, so every bool/bf16 value here is built directly in
    # its final 2-D/3-D orientation; only f32/i32 get transposed.
    s_all = scores_ref[:]   # (1, D, K) f32 — per-deme ranks (see below)
    g_all = genomes_ref[:]  # (D*K, Lp)

    # uint32 -> f32 isn't a supported Mosaic cast; >>8 leaves 24 bits, so
    # bitcast to i32 before the float convert.
    def uniform(shape):
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
        return pltpu.bitcast(bits >> 8, jnp.int32).astype(
            jnp.float32
        ) * jnp.float32(2**-24)

    rate = mparams_ref[0, 0]
    sigma = mparams_ref[0, 1]

    mask_words = None
    if crossover == "uniform" and "no_cross" not in ablate:
        # Crossover coin flips need ONE bit per gene, not a 32-bit draw:
        # a single (K, Lp) PRNG tile per grid step serves every deme in
        # the group — deme d reads bit d of each word (distinct bits of
        # one generator call are independent streams), cutting mask PRNG
        # volume D× (the mask draw measured ~1.3 ms/gen of the 1M×100
        # generation at one-draw-per-deme).
        mask_words = pltpu.bitcast(pltpu.prng_random_bits((K, Lp)), jnp.uint32)

    lane_ok = None
    if mutate == "gaussian" and Lp > L:
        # Keep pad lanes untouched by gaussian noise so the pads-stay-
        # zero invariant holds for every mutation kind (pad_ok fused
        # objectives rely on it; point/swap positions are < L already).
        lane_ok = lax.broadcasted_iota(jnp.int32, (K, Lp), 1) < L

    score_rows = []
    for d in range(D):
        g = g_all[d * K : (d + 1) * K, :]  # (K, Lp)

        # ``scores_ref`` carries each row's PRE-COMPUTED in-deme rank
        # (0 = best; strict total order, score ties broken by a fresh
        # random word per generation, NaNs last among real rows) — the
        # caller derives them from the scores with one stable
        # double-argsort per generation (``breed_padded``), which costs
        # ~0.8 ms/gen at 1M×100 (the multi-generation kernel instead
        # ranks in-kernel, see ``_kernel_ranks``).
        R = s_all[0, d : d + 1, :]  # (1, K) f32 ranks

        if P is None or P % K == 0:
            Vf = jnp.float32(K)
        else:
            # padded population: the last deme holds V = P - deme·K
            # < K real rows (pads beyond them, carrying -inf
            # scores). Ranks 0..V-1 are exactly the real rows — the
            # pads carry the maximal 0xFFFFFFFF tie key while real
            # rows' random tie words are shifted into [0, 2^31), so
            # even a -inf-scored real row sorts strictly before
            # every pad — and sampling rank < V means a pad row can
            # never be selected.
            deme = i * D + d
            Vf = jnp.maximum(
                jnp.minimum(jnp.int32(K), jnp.int32(P) - deme * K), 1
            ).astype(jnp.float32)

        child = _deme_child(
            g, R, Vf, uniform, mask_words, d,
            K=K, L=L, Lp=Lp, tk=tk, sel=sel, sel_param=sel_param,
            crossover=crossover, mutate=mutate, rate=rate, sigma=sigma,
            lane_ok=lane_ok, bf16_genes=bf16_genes, order_refs=order_refs,
            cross_consts=cross_consts, mut_consts=mut_consts,
            ablate=ablate,
        )

        # Write deme d into output column d of the group: the row-major
        # reshape of (K, G/D, D, Lp) interleaves all demes (row index
        # r·G + i·D + d — the same riffle as the D=1 layout).
        out_dtype = jnp.bfloat16 if bf16_genes else jnp.float32
        child = child.astype(out_dtype)
        if "no_riffle" in ablate:
            out_ref[d * K : (d + 1) * K, :] = child
        else:
            out_ref[:, 0, d, :] = child
        if bf16_genes:
            # Score the STORED genes: evaluating the pre-rounding f32
            # child would return scores the written bf16 genomes don't
            # achieve.
            child = child.astype(jnp.float32)

        if obj is not None:
            # Fused evaluation: score the children while they're in VMEM,
            # skipping the separate per-generation evaluation pass over
            # HBM. ``obj`` here is the objective's ROWWISE form
            # ((K, L) -> (K,) with axis=1 reductions): a per-genome fn
            # under jax.vmap unrolls into K scalar reductions in Mosaic
            # (~100× slower, measured). Objectives whose reductions are
            # invariant to zero pad lanes declare ``pad_ok`` and receive
            # the full lane-aligned (K, Lp) child — the (K, L) slice is
            # a misaligned relayout that measured ~1 ms/gen at 1M×100.
            # Scores collect into score_rows and store as ONE
            # contiguous (1, D, K) block after the deme loop (see
            # below); routing them through the genome output's column
            # mapping would mean a K-element strided scatter per deme,
            # which costs ~12 ms/gen at 1M pop (measured) — the caller
            # instead applies a cheap (G,K) transpose to match the
            # riffle-shuffled genome row order.
            child_scores = obj(
                child if obj_pad_ok else child[:, :L],
                *[r[:] for r in const_refs],
            ).astype(jnp.float32)
            srow = child_scores.reshape(1, 1, K)
        elif tsp is not None:
            # Gene-major fused TSP scoring (long-genome path): reuses
            # the order walk's scratch planes, free after breeding.
            srow = _tsp_eval_gene_major(
                child, const_refs[0][:], order_refs,
                K=K, L=L, C=tsp["C"], penalty=tsp["penalty"],
            ).reshape(1, 1, K)
        else:
            continue
        if "scatter_scores" in ablate:  # ablation: the pre-round-5 path
            rest[base + 1][0:1, d : d + 1, :] = srow
        else:
            score_rows.append(srow)
    if score_rows:
        # ONE (1, D, K) score store per grid step instead of D separate
        # (1, 1, K) stores interleaved with the genome writes (round-5
        # 5-round interleaved A/B at 1M×100: f32 medians 167.9 vs 143.0
        # (+17%), bf16 198.5 vs 170.0 (+17%), consistent every round —
        # the per-deme stores were breaking the genome writes'
        # pipelining).
        rest[base + 1][:] = (
            jnp.concatenate(score_rows, axis=1) if D > 1 else score_rows[0]
        )


def _pp_breed_kernel(
    seed_ref,
    mparams_ref,
    scores_ref,
    genomes_ref,
    *rest,
    parity,
    K,
    D,
    B,
    S,
    q,
    L,
    Lp,
    tk=2,
    sel="tournament",
    sel_param=None,
    crossover="uniform",
    mutate="point",
    obj=None,
    obj_pad_ok=False,
    n_consts=0,
    n_cross=0,
    n_mut=0,
    bf16_genes=False,
    padded=False,
    ablate=(),
):
    """One grid step of the PING-PONG layout: breed ``B * D`` demes and
    write every child IN PLACE over the group's own rows (the in/out
    BlockSpecs are identical, licensing ``input_output_aliases``). The
    genome arrays arrive as the parity's chunk view with an explicit
    deme-interleave axis — parity 0 ``(S, T, D, q, Lp)`` blocks
    ``(1, T, D, q, Lp)``, parity 1 ``(T, D, S, q, Lp)`` blocks
    ``(T, D, 1, q, Lp)`` — whose group-local flat row order is
    IDENTICAL (group-chunk-major), so the breeding core is
    parity-independent; only the ref indexing differs.

    READ layout A, WRITE layout B (the mixing crux — see the module's
    layout-algebra block): deme d READS the contiguous group-local rows
    [d*K, (d+1)*K) (a flat slice of the loaded block) but its children
    are WRITTEN interleaved across the whole sub-block via the middle
    D axis (``out[.., :, d, :, :] = child``) — child chunk u lands at
    group chunk ``u*D + d``. Same row set per step (aliasing stays
    sound); the cross-deme scatter is what lets the parity pair mix
    lineages across the whole population instead of fragmenting into
    closed super-blocks.

    ``B`` > 1 is the SUB-BLOCK PIPELINE: the genome arrays stay in HBM
    (``memory_space=ANY``) and the kernel streams ``B`` sub-blocks of
    ``D`` demes through a manually double-buffered VMEM scratch pair
    (async copy in / breed / async copy out), so one grid step serves
    ``B`` times the demes at the same scoped-VMEM footprint — the grid
    shrinks ``B``x, directly attacking the per-grid-step dispatch floor
    the round-6 D-sweep isolated. Ranks and scores stay on ordinary
    pipelined BlockSpecs (they are K-lane rows, ~KB per step).

    ``rest`` holds, in order: the alive-mask input ref when ``padded``
    (see below), ``n_consts`` objective-constant refs, expression
    crossover/mutation constant refs, the genome output ref, the score
    output ref when fused, and for ``B`` > 1 the four scratch refs
    (in-buffer, out-buffer, in-sems, out-sems).

    Padded populations: under parity 1 the pad rows (physical row >= P)
    scatter through the comb instead of pooling at each deme's tail, so
    the positional ``V = P - deme*K`` count of the riffle kernel is
    wrong here. The caller instead passes a static per-parity ALIVE
    mask (S, B*D, K) f32 (1 = real row); the deme's valid count is its
    lane sum, and the host-side rank sort already places pad rows at
    ranks >= V (their tie keys are pinned maximal), so sampling
    ``rank < V`` can never select a pad row in either parity.
    """
    import jax.lax as lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    fused = obj is not None
    idx = 1 if padded else 0
    alive_ref = rest[0] if padded else None
    const_refs = rest[idx : idx + n_consts]
    cross_consts = tuple(
        r[:] for r in rest[idx + n_consts : idx + n_consts + n_cross]
    )
    mut_consts = tuple(
        r[:]
        for r in rest[
            idx + n_consts + n_cross : idx + n_consts + n_cross + n_mut
        ]
    )
    base = idx + n_consts + n_cross + n_mut
    g_out = rest[base]
    s_out = rest[base + 1] if fused else None

    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0, 0] ^ (i * jnp.int32(-1640531527)))

    def uniform(shape):
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
        return pltpu.bitcast(bits >> 8, jnp.int32).astype(
            jnp.float32
        ) * jnp.float32(2**-24)

    rate = mparams_ref[0, 0]
    sigma = mparams_ref[0, 1]
    ranks_all = scores_ref[:]  # (1, B*D, K) f32 in-deme ranks
    alive_all = alive_ref[:] if padded else None  # (1, B*D, K) f32

    lane_ok = None
    if mutate == "gaussian" and Lp > L:
        lane_ok = lax.broadcasted_iota(jnp.int32, (K, Lp), 1) < L

    out_dtype = jnp.bfloat16 if bf16_genes else jnp.float32
    T = K // q           # chunks per deme

    if B > 1:
        gin_buf, gout_buf, sem_in, sem_out = rest[-4:]

        # Arrays are the 6-D sub-block views — parity 0
        # (S, B, T, D, q, Lp), parity 1 (B, T, D, S, q, Lp) — so one
        # integer-indexed slab per (step, sub-block) matches the
        # (T, D, q, Lp) scratch shape exactly.
        def in_copy(b, slot):
            if parity == 0:
                src = genomes_ref.at[i, b]
            else:
                src = genomes_ref.at[b, :, :, i]
            return pltpu.make_async_copy(
                src, gin_buf.at[slot], sem_in.at[slot]
            )

        def out_copy(b, slot):
            if parity == 0:
                dst = g_out.at[i, b]
            else:
                dst = g_out.at[b, :, :, i]
            return pltpu.make_async_copy(
                gout_buf.at[slot], dst, sem_out.at[slot]
            )

        in_copy(0, 0).start()

    score_rows = []
    for b in range(B):
        slot = b % 2
        if B > 1:
            # Double buffer: start sub-block b+1's inbound DMA before
            # waiting on b's, and reclaim the outbound buffer written
            # two iterations ago before overwriting it.
            if b + 1 < B:
                in_copy(b + 1, (b + 1) % 2).start()
            in_copy(b, slot).wait()
            if b >= 2:
                out_copy(b - 2, slot).wait()
            g_sub = gin_buf[slot].reshape(D * K, Lp)
        else:
            g_sub = genomes_ref[:].reshape(D * K, Lp)

        mask_words = None
        if crossover == "uniform" and "no_cross" not in ablate:
            # One (K, Lp) PRNG tile per sub-block serves its D demes
            # via distinct word bits (same trick as _breed_kernel).
            mask_words = pltpu.bitcast(
                pltpu.prng_random_bits((K, Lp)), jnp.uint32
            )

        for d in range(D):
            dd = b * D + d  # deme slot within the whole grid step
            g = g_sub[d * K : (d + 1) * K, :]
            R = ranks_all[0, dd : dd + 1, :]  # (1, K)
            if padded:
                av = alive_all[0, dd : dd + 1, :]  # (1, K)
                # A parity-1 cohort can in principle be all pads; the
                # max() keeps the sampling denominator sane (its
                # children are pad rows the caller masks to -inf).
                Vf = jnp.maximum(
                    jnp.sum(av, axis=1, keepdims=True), 1.0
                )  # (1, 1)
            else:
                Vf = jnp.float32(K)

            child = _deme_child(
                g, R, Vf, uniform, mask_words, d,
                K=K, L=L, Lp=Lp, tk=tk, sel=sel, sel_param=sel_param,
                crossover=crossover, mutate=mutate, rate=rate, sigma=sigma,
                lane_ok=lane_ok, bf16_genes=bf16_genes,
                cross_consts=cross_consts, mut_consts=mut_consts,
                ablate=ablate,
            )
            child = child.astype(out_dtype)
            # The cross-deme write interleave: child chunk u of deme d
            # lands at sub-block chunk u*D + d — one middle-axis store
            # (the riffle kernel's proven out_ref[:, 0, d, :] pattern).
            blk = child.reshape(T, q, Lp)
            if B > 1:
                gout_buf[slot, :, d, :, :] = blk
            elif parity == 0:
                g_out[0, :, d, :, :] = blk
            else:
                g_out[:, d, 0, :, :] = blk
            if fused:
                if bf16_genes:
                    child = child.astype(jnp.float32)
                child_scores = obj(
                    child if obj_pad_ok else child[:, :L],
                    *[r[:] for r in const_refs],
                ).astype(jnp.float32)
                score_rows.append(child_scores.reshape(1, 1, K))
        if B > 1:
            out_copy(b, slot).start()

    if B > 1:
        # Drain the last two outbound DMAs (earlier ones were waited in
        # the loop when their buffer slot was reclaimed).
        for b in range(max(B - 2, 0), B):
            out_copy(b, b % 2).wait()

    if score_rows:
        # ONE (1, B*D, K) score store per grid step (the round-5
        # batched-store lesson carries over from the riffle kernel).
        s_out[:] = (
            jnp.concatenate(score_rows, axis=1)
            if len(score_rows) > 1 else score_rows[0]
        )


def _kernel_ranks(s, tie_bits, v_i32, K, padded=True, alive=None):
    """In-deme ranks (1, K) f32 computed INSIDE the kernel from raw
    scores — the multi-generation kernel's replacement for the caller's
    ``compute_ranks`` sort (sub-generations 2..T have no HBM round trip
    where a host-side sort could run).

    Same total order as ``compute_ranks``: descending score; NaN pinned
    to -inf first; score ties broken by a fresh random word per
    sub-generation (``tie_bits``), made strictly distinct by splicing
    the lane index into the word's low 10 bits (K <= 1024 — a bare
    32-bit tie word collides between some pair of rows every ~2³²/K²
    draws, and two rows sharing a rank would breed a summed two-row
    genome); pad lanes (>= ``v_i32``) get keys above every real row's
    (real keys < 2^30, pads >= 0x7FFFFC00), so rank(pad) >= V always.

    Cost: one (K, K) compare cube + sublane reduce per deme per
    sub-generation — all VPU, no MXU — versus the host sort's ~0.9 ms
    per 1M×100 generation plus its HBM score round trip.

    ``alive`` (ping-pong layouts): a (1, K) f32 mask of real rows
    replacing the positional ``v_i32`` tail — under the parity-1 comb,
    pad rows scatter through a cohort instead of pooling at its end.
    """
    import jax.lax as lax

    from jax.experimental.pallas import tpu as pltpu

    lane = lax.broadcasted_iota(jnp.int32, (1, K), 1)
    # Dead slots (rows >= V) are excluded POSITIONALLY, whatever score
    # they carry — within a launch the dead rows of the tail deme are
    # its last K-V rows, exactly as the caller's positional tail mask
    # declares them between launches (children are exchangeable; each
    # generation re-picks which K-V die). ``padded`` False (exact-
    # divisor population, V == K statically) skips both dead-slot
    # passes.
    dead = jnp.isnan(s)
    if alive is not None:
        dead = dead | (alive == 0.0)
    elif padded:
        dead = dead | (lane >= v_i32)
    s = jnp.where(dead, -jnp.inf, s)  # (1, K) f32
    t = pltpu.bitcast(
        lax.shift_right_logical(tie_bits, jnp.uint32(2)), jnp.int32
    )
    t = (t & jnp.int32(-1024)) | lane
    if alive is not None:
        t = jnp.where(alive > 0.0, t, jnp.int32(0x7FFFFC00) | lane)
    elif padded:
        t = jnp.where(lane < v_i32, t, jnp.int32(0x7FFFFC00) | lane)
    # better[i, j]: row i strictly precedes row j in the sort order.
    # (A select-on-bool where-form won't lower in Mosaic.) The column
    # reduce runs as a (1,K)@(K,K) matmul — 0/1 bf16 entries sum
    # exactly in f32 accumulation (K <= 1024 < 2^24) and the MXU does
    # it in a sliver of its idle time while the VPU owns the cube.
    better = (s.T > s) | ((s.T == s) & (t.T < t))  # (K, K)
    return jnp.dot(
        jnp.ones((1, K), dtype=jnp.bfloat16),
        better.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )


def _multigen_kernel(
    seed_ref,
    mparams_ref,
    steps_ref,
    target_ref,
    scores_ref,
    genomes_ref,
    *rest,
    K,
    D,
    L,
    Lp,
    tk=2,
    sel="tournament",
    sel_param=None,
    crossover="uniform",
    mutate="point",
    obj=None,
    obj_pad_ok=False,
    n_consts=0,
    n_cross=0,
    n_mut=0,
    bf16_genes=False,
    P=None,
    elitism=0,
    ablate=(),
    layout="riffle",
    parity=0,
    q=8,
):
    """Breed ``steps_ref`` consecutive generations with the deme group
    resident in VMEM scratch — one HBM read + one HBM write of the
    population per ``steps`` generations instead of per generation,
    amortizing the IO+grid floor (~46% of f32 generation time at 1M×100,
    BASELINE.md ablation) across the whole launch.

    Differences from the one-generation ``_breed_kernel``:

    - ``scores_ref`` carries raw SCORES, not precomputed ranks; each
      sub-generation ranks its demes in-kernel (``_kernel_ranks``).
    - ``steps_ref`` (SMEM i32) is a RUNTIME trip count — one compiled
      kernel serves any chunk size, including the ``n % T`` remainder.
    - ``target_ref`` (SMEM f32) freezes the whole deme group once its
      best score reaches the target: a target-satisfying individual
      bred mid-launch is never bred away (the group stops, other groups
      continue to their own ``steps``), preserving the run loop's
      early-termination guarantee at launch granularity. +inf = never.
    - ``elitism`` is applied PER DEME by ``_deme_child`` every
      sub-generation (rows 0..e-1 clone the deme's best e). This
      preserves the global top-e — each global top-j row (j <= e) is in
      the top-e of its own deme — while keeping G·e elites total
      instead of e (~0.8% of a 1M population at e=2, K=256).
    - Demes stay fixed for the whole launch (the riffle reshuffle
      happens at launch boundaries), so the panmictic mixing horizon
      grows from 1 to ``steps`` generations — measured equivalence in
      BASELINE.md covers the shipped default.

    ``layout`` "pingpong": the genome in/out refs are the parity's 4-D
    chunk view of the SAME aliased flat buffer (see _pp_breed_kernel —
    group-local row order is identical for both parities) and the
    launch writes the whole group back IN PLACE; the inter-group
    reshuffle comes from the run loop alternating launch parity. On a
    padded population the positional tail masks are replaced by the
    static per-parity alive-mask input (``rest[0]``).
    """
    import jax.lax as lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    pp = layout == "pingpong"
    pp_padded = pp and P is not None and P % K != 0
    idx = 1 if pp_padded else 0
    alive_ref = rest[0] if pp_padded else None
    const_refs = rest[idx : idx + n_consts]
    cross_consts = tuple(
        r[:] for r in rest[idx + n_consts : idx + n_consts + n_cross]
    )
    mut_consts = tuple(
        r[:]
        for r in rest[
            idx + n_consts + n_cross : idx + n_consts + n_cross + n_mut
        ]
    )
    base = idx + n_consts + n_cross + n_mut
    g_out = rest[base]
    s_out = rest[base + 1]
    g_scr = rest[base + 2]
    s_scr = rest[base + 3]
    order_refs = rest[-6:] if crossover == "order" else None

    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0, 0] ^ (i * jnp.int32(-1640531527)))

    def uniform(shape):
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
        return pltpu.bitcast(bits >> 8, jnp.int32).astype(
            jnp.float32
        ) * jnp.float32(2**-24)

    rate = mparams_ref[0, 0]
    sigma = mparams_ref[0, 1]
    tgt = target_ref[0, 0]

    if pp:
        g_scr[:] = genomes_ref[:].reshape(D * K, Lp)
    else:
        g_scr[:] = genomes_ref[:]
    s_scr[:] = scores_ref[:]

    lane_ok = None
    if mutate == "gaussian" and Lp > L:
        lane_ok = lax.broadcasted_iota(jnp.int32, (K, Lp), 1) < L

    alive_all = alive_ref[:] if pp_padded else None  # (1, D, K) f32

    def valid_rows(d):
        if pp_padded:
            # Pad rows scatter through the parity-1 comb; the static
            # mask's lane sum is the deme's real-row count.
            return jnp.maximum(
                jnp.sum(alive_all[0, d : d + 1, :], axis=1, keepdims=True),
                1.0,
            )
        if P is None or P % K == 0:
            return jnp.int32(K)
        deme = i * D + d
        return jnp.maximum(
            jnp.minimum(jnp.int32(K), jnp.int32(P) - deme * K), 1
        )

    out_dtype = jnp.bfloat16 if bf16_genes else jnp.float32

    # ``s_scr`` carries every child's TRUE score (the caller applies the
    # positional dead-row mask after the riffle, same as the
    # one-generation path); in-kernel, dead tail-deme slots are excluded
    # positionally inside _kernel_ranks and via this mask for the
    # target-freeze check.
    if pp_padded:
        alive = alive_all > 0.0
    elif P is not None and P % K != 0:
        lane3 = lax.broadcasted_iota(jnp.int32, (1, D, K), 2)
        deme3 = lax.broadcasted_iota(jnp.int32, (1, D, K), 1) + i * D
        v3 = jnp.clip(jnp.int32(P) - deme3 * K, 1, jnp.int32(K))
        alive = lane3 < v3
    else:
        alive = None

    def sub_gen(t, carry):
        del t

        # BRANCHLESS freeze: once the group's best (over alive rows)
        # reaches the target, every write below becomes a keep-old
        # select. A (1, 1)-vector predicate instead of a pl.when scalar:
        # the scalar-condition branch measured ~0.5 ms/gen of pipeline
        # stall at 1M×100; the vector selects cost ~nothing and also
        # keep the PRNG stream advance identical whether or not a group
        # is frozen.
        if "no_freeze" in ablate:
            frozen = None
        else:
            s_all = s_scr[:]
            if alive is not None:
                s_all = jnp.where(alive, s_all, -jnp.inf)
            frozen = (
                jnp.max(s_all, axis=(0, 1, 2), keepdims=True) >= tgt
            ).reshape(1, 1)

        mask_words = None
        if crossover == "uniform" and "no_cross" not in ablate:
            mask_words = pltpu.bitcast(
                pltpu.prng_random_bits((K, Lp)), jnp.uint32
            )
        tie_bits = pltpu.bitcast(
            pltpu.prng_random_bits((D, K)), jnp.uint32
        )
        for d in range(D):
            v = valid_rows(d)
            g_store = g_scr[d * K : (d + 1) * K, :]  # stored gene dtype
            if "no_rank_cube" in ablate:
                # Ablation harness: identity "ranks" — selection
                # semantics are garbage but the cost shape isolates
                # the in-kernel rank cube.
                R = lax.broadcasted_iota(
                    jnp.int32, (1, K), 1
                ).astype(jnp.float32)
            else:
                R = _kernel_ranks(
                    s_scr[0:1, d, :], tie_bits[d : d + 1, :], v, K,
                    padded=P is not None and P % K != 0,
                    alive=(
                        alive_all[0, d : d + 1, :] if pp_padded else None
                    ),
                )
            vf = v if pp_padded else v.astype(jnp.float32)
            child = _deme_child(
                g_store, R, vf, uniform, mask_words, d,
                K=K, L=L, Lp=Lp, tk=tk, sel=sel, sel_param=sel_param,
                crossover=crossover, mutate=mutate, rate=rate,
                sigma=sigma, lane_ok=lane_ok, bf16_genes=bf16_genes,
                elite_rows=elitism, order_refs=order_refs,
                cross_consts=cross_consts, mut_consts=mut_consts,
                ablate=ablate,
            )
            child = child.astype(out_dtype)
            if frozen is not None:
                child = jnp.where(frozen, g_store, child)
            g_scr[d * K : (d + 1) * K, :] = child
            if bf16_genes:
                # Score the STORED genes (see _breed_kernel).
                child = child.astype(jnp.float32)
            cs = obj(
                child if obj_pad_ok else child[:, :L],
                *[r[:] for r in const_refs],
            ).astype(jnp.float32).reshape(1, 1, K)
            if frozen is not None:
                cs = jnp.where(
                    frozen.reshape(1, 1, 1), s_scr[0:1, d : d + 1, :], cs
                )
            s_scr[0:1, d : d + 1, :] = cs
        return carry

    lax.fori_loop(0, steps_ref[0, 0], sub_gen, jnp.int32(0))

    if pp:
        # In-place group writeback through the parity's interleave
        # view (same rows the step read — the aliasing license): deme
        # d's rows land at group chunks {u*D + d}, the launch-boundary
        # reshuffle of the ping-pong scheme.
        T = K // q
        for d in range(D):
            blk = g_scr[d * K : (d + 1) * K, :].reshape(T, q, Lp)
            if parity == 0:
                g_out[0, :, d, :, :] = blk
            else:
                g_out[:, d, 0, :, :] = blk
    else:
        for d in range(D):
            g_out[:, 0, d, :] = g_scr[d * K : (d + 1) * K, :]
    s_out[:] = s_scr[:]


def _kernel_shape(
    pop_size,
    genome_len,
    deme_size,
    tournament_size,
    selection_kind,
    selection_param,
    crossover_kind,
    mutate_kind,
    gene_dtype,
    *,
    blocks_fit,
    d_pool,
    d_default,
    demes_per_step,
    const_carrying=False,
):
    """Admission gates + shape resolution shared by the one-generation
    and multi-generation kernel factories — ONE copy so the two paths
    can never accept different configurations. Returns
    ``(K, G, D, Pp, Lp, resolved_selection_param, d_candidates)`` —
    ``d_candidates`` being every VMEM-admissible demes-per-step value
    (descending; the ping-pong layout resolver may bump D within it) —
    or None to decline:

    - supported gene dtype (f32/bf16), crossover/mutate kind;
    - order crossover: f32 genes only (bf16 resolution ~0.004 near 1.0
      corrupts ``floor(g*L)`` city decodes), D pinned to 1, and the
      walk's VMEM scratch (``_order_scratch_shapes``) counted against
      the scoped budget — any L whose scratch fits lowers (the walk is
      a runtime ``fori_loop``; it no longer unrolls trace-time steps,
      so the former ``genome_len <= 256`` cap is gone);
    - tournament size 1..16 (documented engine contract — selection
      pressure ~k/(k+1) saturates; rank-space sampling makes the
      in-kernel cost k-independent, so the cap is contractual);
    - selection kind/param validated by the ONE resolver the XLA path
      uses (``ops/select.resolve_selection``) — invalid raises;
    - deme size via ``_pick_deme_size`` under the caller's VMEM model
      (``blocks_fit``), demes-per-step from ``d_pool`` capped at
      ``d_default`` (or the caller's explicit ``demes_per_step``,
      rounded down to a valid candidate).
    """
    if not _supported():
        return None
    if gene_dtype not in (jnp.float32, jnp.bfloat16):
        return None
    # Callable kinds are expression breeding operators (the rowwise
    # forms of ops/breed_expr.py) — evaluated in-kernel like the
    # builtin kinds.
    if not callable(crossover_kind) and crossover_kind not in (
        "uniform", "order",
    ):
        return None
    if not callable(mutate_kind) and mutate_kind not in (
        "point", "gaussian", "swap",
    ):
        return None
    if crossover_kind == "order" and gene_dtype != jnp.float32:
        return None
    if not (1 <= tournament_size <= 16):
        return None
    from libpga_tpu.ops.select import resolve_selection

    selection_param = resolve_selection(selection_kind, selection_param)
    if not deme_size:
        deme_size = auto_deme_size(gene_dtype, const_carrying)
    Lp = math.ceil(genome_len / LANE) * LANE
    gene_bytes = 2 if gene_dtype == jnp.bfloat16 else 4

    def extra_scoped(k: int) -> int:
        # The order walk's VMEM scratch counts against the same scoped
        # budget as the caller's own model — threaded through every
        # admission check (deme pick included, so long genomes retry
        # smaller K instead of silently dropping to the XLA path).
        if crossover_kind != "order":
            return 0
        return sum(
            math.prod(s.shape) * 4
            for s in _order_scratch_shapes(k, genome_len, Lp)
        )

    def fit(k: int, d: int) -> bool:
        return blocks_fit(k, d, Lp, gene_bytes, extra_scoped(k))

    K = _pick_deme_size(
        pop_size, deme_size, genome_lanes=Lp, gene_bytes=gene_bytes,
        fits=lambda k: fit(k, 1),
    )
    if K is None:
        return None
    G = math.ceil(pop_size / K)
    d_candidates = [
        d for d in d_pool if G % d == 0 and fit(K, d)
    ] or [1]
    if crossover_kind == "order":
        D = 1
    elif demes_per_step:
        D = next((d for d in d_candidates if d <= demes_per_step), 1)
    else:
        D = next((d for d in d_candidates if d <= d_default), 1)
    return K, G, D, G * K, Lp, selection_param, tuple(d_candidates)


def _breeding_kind(kind, L: int, Lp: int):
    """Normalize a crossover/mutate kind for the kernel: a builtin name
    passes through with no constants; an expression operator
    (``ops/breed_expr.py``) contributes its compiled rowwise form plus
    its registered constants as lane-padded kernel inputs (vector
    constants pair with the gene axis, so they pad to Lp exactly like
    the genomes they broadcast against)."""
    if not callable(kind):
        return kind, ()
    rows = getattr(kind, "kernel_rows", None)
    if rows is None:
        raise ValueError(
            "callable breeding kinds must be expression operators "
            "carrying .kernel_rows (ops/breed_expr.py)"
        )
    pin = getattr(kind, "pinned_genome_len", None)
    if pin and pin != L:
        raise ValueError(
            f"breeding expression uses length-{pin} vector constants "
            f"but the population genome length is {L}"
        )
    consts = []
    for c in getattr(kind, "kernel_consts", ()) or ():
        a = jnp.atleast_2d(jnp.asarray(c, jnp.float32))
        if a.shape[-1] == L and Lp != L:
            a = jnp.pad(a, ((0, 0), (0, Lp - L)))
        consts.append(a)
    return rows, tuple(consts)


def _resolve_layout(
    layout,
    *,
    K,
    G,
    D,
    Pp,
    q,
    d_candidates,
    subblock,
    fused,
    crossover_kind,
    ablate,
    multigen=False,
    padded_elitism=False,
    d_pinned=False,
):
    """Resolve the output-layout request to ``("riffle", D, 1)`` or
    ``("pingpong", D', B)``.

    ``layout`` None is AUTO: the ping-pong in-place layout is the
    SHIPPED DEFAULT for the fused f32/bf16 paths (ISSUE 3) whenever its
    mixing gate admits — bumping demes-per-step to the smallest
    VMEM-admissible candidate that satisfies ``pingpong_admissible``
    (in-place writes have no riffle-stride downside, so a larger D only
    cuts grid steps) — and falls back to the riffle otherwise. An
    EXPLICIT ``"pingpong"`` raises when inadmissible instead of
    degrading silently (a benchmark variant must not quietly measure
    the other layout). Riffle-only conditions: order crossover (D
    pinned to 1 never passes the mixing gate at scale, and the TSP
    scorer shares its scratch), any layout-affecting ablation flag
    (the floor instruments are riffle-calibrated), and per-deme
    elitism on a padded multigen population (a pad row can occupy a
    parity-1 cohort's elite slot).
    """
    B = int(subblock or 1)
    if B < 1:
        raise ValueError(f"subblock depth must be >= 1, got {subblock}")
    if layout not in (None, "riffle", "pingpong"):
        raise ValueError(
            f"unknown layout {layout!r}: expected 'riffle' or 'pingpong'"
        )
    explicit = layout == "pingpong"
    blockers = []
    if crossover_kind == "order":
        blockers.append("order crossover is riffle-only")
    if set(ablate) & _LAYOUT_ABLATE:
        blockers.append(
            f"layout ablation flags {sorted(set(ablate) & _LAYOUT_ABLATE)}"
            " are riffle instruments"
        )
    if multigen and B > 1:
        blockers.append(
            "sub-block pipelining streams demes through VMEM, which the"
            " multi-generation kernel's resident scratch precludes"
        )
    if padded_elitism:
        blockers.append(
            "per-deme elitism on a padded population would write elites"
            " into pad rows under parity 1"
        )
    want = explicit or (layout is None and fused)
    if layout == "riffle" or not want:
        return "riffle", D, 1
    if blockers:
        if explicit:
            raise ValueError(
                "layout='pingpong' is not available here: "
                + "; ".join(blockers)
            )
        return "riffle", D, 1
    # Smallest admissible D' >= the measured default (candidates are
    # descending). An EXPLICITLY pinned demes-per-step is never bumped
    # — a sweep point must measure the D it asked for — so it either
    # passes the gate itself or the ping-pong layout is off the table.
    pool = [D] if d_pinned else sorted(d for d in d_candidates if d >= D)
    for d2 in pool:
        if G % (B * d2) == 0 and pingpong_admissible(B * d2 * K, Pp, q):
            return "pingpong", d2, B
    if explicit:
        raise ValueError(
            "layout='pingpong' requested but no VMEM-admissible"
            f" demes-per-step satisfies the mixing gate (K={K}, G={G},"
            f" subblock={B}, candidates={pool}):"
            " the parity pair would leave disconnected row components"
            " (pingpong_admissible)"
        )
    return "riffle", D, 1


# One-generation kernel demes-per-step policy — shared by the factory
# (make_pallas_breed) and the dry-run resolver (kernel_plan) so the
# tuning space can never describe a D the kernel wouldn't pick.
ONE_GEN_D_POOL = (32, 16, 8, 4, 2, 1)


def one_gen_d_default(gene_dtype, const_carrying: bool = False) -> int:
    """Measured demes-per-step default of the one-generation kernel
    (see the d_pool comment in make_pallas_breed): bf16 keeps D=4 at
    K=512; f32 moved to D=8 — except const-carrying fused objectives
    (NK-class), which measured fastest at the old K=256 D=16."""
    if gene_dtype == jnp.bfloat16:
        return 4
    return 16 if const_carrying else 8


def kernel_plan(
    pop_size: int,
    genome_len: int,
    *,
    deme_size: Optional[int] = None,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    gene_dtype=jnp.float32,
    demes_per_step: Optional[int] = None,
    layout: Optional[str] = None,
    subblock: Optional[int] = None,
    fused: bool = True,
    const_carrying: bool = False,
) -> Optional[dict]:
    """DRY-RUN shape + layout resolution: exactly what
    :func:`make_pallas_breed` would build for these knobs, WITHOUT
    compiling anything — the admissibility oracle of the tuning config
    space (``libpga_tpu/tuning/space.py``), so an invalid configuration
    is rejected before a kernel is ever built.

    Runs the same ``_kernel_shape`` gates (dtype/kind support, VMEM
    budget model, deme divisibility/padding policy, demes-per-step
    candidates) and the same ``_resolve_layout`` (ping-pong mixing gate,
    sub-block divisibility) as the factory, with the factory's own
    ``d_pool``/``d_default`` — ONE copy, so the plan and the built
    kernel can never disagree. Returns ``None`` where the factory would
    decline, raises ``ValueError`` exactly where it would raise (an
    explicit inadmissible ping-pong request), and otherwise a dict with
    the resolved ``deme_size``/``demes_per_step``/``layout``/
    ``subblock``, the padded ``Pp``/``Lp``, and the per-launch
    ``grid_steps`` count.
    """
    const_obj = bool(const_carrying)
    shape = _kernel_shape(
        pop_size, genome_len, deme_size, tournament_size,
        selection_kind, selection_param, crossover_kind, mutate_kind,
        gene_dtype,
        blocks_fit=_blocks_fit,
        d_pool=ONE_GEN_D_POOL,
        d_default=one_gen_d_default(gene_dtype, const_obj),
        demes_per_step=demes_per_step,
        const_carrying=const_obj,
    )
    if shape is None:
        return None
    K, G, D, Pp, Lp, sel_param, d_cands = shape
    lay, D2, B = _resolve_layout(
        layout,
        K=K, G=G, D=D, Pp=Pp, q=pingpong_quantum(gene_dtype),
        d_candidates=d_cands, subblock=subblock, fused=fused,
        crossover_kind=crossover_kind, ablate=(),
        d_pinned=demes_per_step is not None,
    )
    return {
        "deme_size": K,
        "demes_per_step": D2,
        "layout": lay,
        "subblock": B,
        "Pp": Pp,
        "Lp": Lp,
        "grid_steps": G // (B * D2) if lay == "pingpong" else G // D2,
        "d_candidates": d_cands,
    }


def plan_cost(
    plan: dict,
    *,
    gene_dtype=jnp.float32,
    generations_per_launch: Optional[int] = None,
) -> dict:
    """Analytic per-generation cost of a resolved :func:`kernel_plan`
    (the ISSUE 17 plan→cost hook — ``libpga_tpu/perf/cost.py`` builds
    roofline reports from this, ``bench.single_derived`` its MFU note).

    Lives HERE, next to the shape model it describes, for the same
    reason ``kernel_plan`` does: one copy of the geometry, so the cost
    model can never describe a kernel the factory wouldn't build.

    FLOPs count ONLY the one-hot parent-selection matmuls — per deme
    and generation, ``matmuls`` K×K·K×Lp products at 2 FLOPs/MAC (f32
    genes split into bf16 hi/lo passes, so 4 matmuls; bf16 genes take
    2) — the kernel's only MXU work. Elementwise crossover/mutate/
    objective VPU work is excluded, so fraction-of-peak never
    overstates. HBM bytes are the launch-IO floor (one genome
    read+write and one score read+write per launch, amortized over the
    ``T`` generations a multi-generation launch breeds — the
    ``bench.hbm_bytes_per_gen`` model, on the PADDED shape the kernel
    actually moves). VMEM is the factory's own admission model
    (:func:`_scoped_vmem_bytes`) at the resolved geometry.
    """
    K = int(plan["deme_size"])
    D = int(plan["demes_per_step"])
    Pp = int(plan["Pp"])
    Lp = int(plan["Lp"])
    gene_bytes = 2 if gene_dtype == jnp.bfloat16 else 4
    matmuls = 2 if gene_dtype == jnp.bfloat16 else 4
    T = int(generations_per_launch or multigen_default_t(gene_dtype))
    genome = 2 * Pp * Lp * gene_bytes
    scores = 2 * Pp * 4
    return {
        "flops_per_gen": Pp * K * Lp * 2 * matmuls,
        "hbm_bytes_per_gen": (genome + scores) // T,
        "vmem_bytes": _scoped_vmem_bytes(K, D, Lp, gene_bytes),
        "gene_bytes": gene_bytes,
        "matmuls_per_deme": matmuls,
        "generations_per_launch": T,
    }


def make_pallas_breed(
    pop_size: int,
    genome_len: int,
    *,
    deme_size: Optional[int] = None,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    mutation_rate: float = 0.01,
    mutation_sigma: float = 0.0,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    elitism: int = 0,
    fused_obj: Optional[Callable] = None,
    fused_consts: tuple = (),
    fused_tsp: Optional[dict] = None,
    gene_dtype=jnp.float32,
    _demes_per_step: Optional[int] = None,
    _ablate: tuple = (),
    _layout: Optional[str] = None,
    _subblock: Optional[int] = None,
) -> Optional[Callable]:
    """Build the fused breed: ``(genomes (P,L), scores (P,), key[, mparams])
    -> next_genomes (P, L)`` — or, with ``fused_obj``, ``-> (next_genomes,
    next_scores)`` with evaluation done inside the kernel. ``gene_dtype``
    bfloat16 selects parents with a single exact bf16 matmul (half the
    FLOPs/traffic of the f32 hi/lo path) at bf16 gene resolution.

    ``_layout`` None (auto) ships the alias-compatible PING-PONG layout
    on the fused paths whenever its mixing gate admits (see
    ``_resolve_layout``): children are written IN PLACE over the input
    buffer (``input_output_aliases``), generations alternate between
    two row groupings (the returned breed's ``padded``/``padded_ranks``
    take a ``parity`` argument the run loops toggle), and the riffle's
    staged output buffer plus its strided writes disappear. "riffle"
    and "pingpong" force either layout; ``_subblock`` B > 1 adds the
    manually double-buffered sub-block pipeline (ping-pong only),
    shrinking the grid B-fold at the same scoped-VMEM budget.

    ``fused_tsp`` (an objective's ``kernel_gene_major`` dict) selects
    the gene-major fused TSP scorer instead of a rowwise ``fused_obj``;
    it requires ``crossover_kind="order"`` (whose scratch planes the
    evaluator reuses) and produces fused scores exactly like
    ``fused_obj`` does. With a different crossover (or when a rowwise
    ``fused_obj`` is also present) the request is silently DROPPED and
    an ordinary breed comes back — check ``breed.fused`` before
    expecting a (genomes, scores) pair; None only results when the
    drop leaves ``elitism > 0`` without fused scores.

    ``mutate_kind`` selects the in-kernel mutation ("point" or
    "gaussian"); its parameters are RUNTIME inputs — pass ``mparams``
    (shape (1, 2) f32: [rate, sigma]) per call to anneal without
    recompiling, or omit it to use the construction-time defaults.

    ``elitism`` > 0 (fused only): the top-e of the incoming generation
    overwrite rows 0..e-1 of the outgoing one, with their scores — the
    same slots the XLA breed uses (``ops/step.py``).

    Populations that no deme size divides exactly are padded internally
    to the next deme multiple: pad rows are excluded from tournaments
    in-kernel (see ``_breed_kernel``) and tail children carry -inf fused
    scores, so the padded rows are inert — the caller still sees exactly
    ``(P, L)``. Returns None when unsupported (population under one deme
    tile, an unsupported dtype, or elitism without fused scores)."""
    # Fault-injection site (robustness/faults): a raised fault here
    # travels the exact path a real Mosaic build failure would — the
    # engine's fallback policy decides whether the config degrades to
    # XLA or fails fast. No-op attribute read when no plan is installed.
    if _faults.PLAN is not None:
        _faults.PLAN.fire("kernel.build")
    # const_carrying deliberately EXCLUDES fused_tsp: its coordinate
    # table is a bilinear-matmul operand, not an NK-class
    # masked-accumulation table, and K=512 measured FASTER for the
    # fused TSP at short genomes too (100-city, 4-round interleave:
    # 3316 vs 2817 gens/sec; long genomes fall to K<=256 via the order
    # scratch VMEM gate regardless).
    _ablate = _validate_ablate(_ablate)
    const_obj = fused_obj is not None and bool(fused_consts)
    shape = _kernel_shape(
        pop_size, genome_len, deme_size, tournament_size,
        selection_kind, selection_param, crossover_kind, mutate_kind,
        gene_dtype,
        blocks_fit=_blocks_fit,
        # Demes per grid step: larger groups write D·Lp-contiguous
        # bursts through the riffle layout (see _breed_kernel) — the
        # riffle's strided HBM writes are a top non-matmul cost at D=1
        # (512-byte bursts for f32 at Lp=128). Round-5 sweep under the
        # batched score stores (BASELINE.md): bf16 keeps D=4 at K=512;
        # f32 moved to D=8 at K=512 (the round-3 K=256 D=16 sweet spot
        # predates both the stacked matmul and the batched stores) —
        # EXCEPT const-carrying fused objectives (NK-class), which
        # measured fastest at the old K=256 D=16.
        d_pool=ONE_GEN_D_POOL,
        d_default=one_gen_d_default(gene_dtype, const_obj),
        demes_per_step=_demes_per_step,
        const_carrying=const_obj,
    )
    if shape is None:
        return None
    if fused_tsp is not None and (fused_obj is not None
                                  or crossover_kind != "order"):
        # The gene-major evaluator reuses the order walk's scratch; a
        # rowwise fused objective always wins if both are present.
        fused_tsp = None
    fused = fused_obj is not None or fused_tsp is not None
    if elitism > 0 and not fused:
        # The epilogue needs next-generation scores; without fused
        # evaluation the caller (engine run loop) applies elitism itself.
        return None
    bf16_genes = gene_dtype == jnp.bfloat16
    P, L = pop_size, genome_len
    K, G, D, Pp, Lp, selection_param, d_cands = shape

    layout, D, subblock = _resolve_layout(
        _layout,
        K=K, G=G, D=D, Pp=Pp, q=pingpong_quantum(gene_dtype),
        d_candidates=d_cands, subblock=_subblock, fused=fused,
        crossover_kind=crossover_kind, ablate=_ablate,
        d_pinned=_demes_per_step is not None,
    )
    if layout == "pingpong":
        return _make_pingpong_breed(
            P, L, K, G, D, subblock, Pp, Lp,
            tournament_size=tournament_size,
            selection_kind=selection_kind, selection_param=selection_param,
            mutation_rate=mutation_rate, mutation_sigma=mutation_sigma,
            crossover_kind=crossover_kind, mutate_kind=mutate_kind,
            elitism=elitism, fused_obj=fused_obj, fused_consts=fused_consts,
            gene_dtype=gene_dtype, ablate=_ablate,
        )

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Objective constants (problem data) become real kernel inputs:
    # Pallas rejects captured array constants. Stored 2-D, replicated to
    # every grid step (index map pinned to the origin). The gene-major
    # TSP scorer's packed coordinate table rides the same channel.
    consts = tuple(jnp.atleast_2d(jnp.asarray(c)) for c in fused_consts)
    if fused_obj is None:
        consts = ()
    if fused_tsp is not None:
        consts = (jnp.asarray(fused_tsp["table"], jnp.float32),)
    cross_kind, cross_consts = _breeding_kind(crossover_kind, L, Lp)
    mut_kind, mut_consts = _breeding_kind(mutate_kind, L, Lp)

    kernel = partial(
        _breed_kernel,
        K=K,
        D=D,
        L=L,
        Lp=Lp,
        tk=tournament_size,
        sel=selection_kind,
        sel_param=selection_param,
        crossover=cross_kind,
        mutate=mut_kind,
        obj=fused_obj,
        obj_pad_ok=bool(getattr(fused_obj, "pad_ok", False)),
        tsp=(
            {"C": fused_tsp["C"], "penalty": fused_tsp["penalty"]}
            if fused_tsp is not None else None
        ),
        n_consts=len(consts),
        n_cross=len(cross_consts),
        n_mut=len(mut_consts),
        bf16_genes=bf16_genes,
        P=P,
        ablate=tuple(_ablate),
    )

    if "no_riffle" in _ablate:
        # Ablation: contiguous deme-major writes, no inter-deme mixing —
        # measures the riffle layout's strided-write cost.
        out_specs = [pl.BlockSpec((D * K, Lp), lambda i: (i, 0))]
        out_shape = [jax.ShapeDtypeStruct((Pp, Lp), gene_dtype)]
    else:
        out_specs = [pl.BlockSpec((K, 1, D, Lp), lambda i: (0, i, 0, 0))]
        out_shape = [jax.ShapeDtypeStruct((K, G // D, D, Lp), gene_dtype)]
    if fused:
        # (G//D, D, K) score array tiled on its LAST TWO dims (D, K): the
        # former (G, 1, K) layout's middle singleton was sublane-padded
        # 1→8 by Mosaic tiling, making every score write move 8× the
        # bytes. (A flat (G, K) array with (D, K) blocks would be ideal
        # but Pallas requires block dims divisible by (8, 128) unless
        # they equal the array dims — D=4 would be rejected.)
        out_specs.append(pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((G // D, D, K), jnp.float32))

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i: (0,) * c.ndim)

    aliases = {}
    if "alias_io" in _ablate:
        # Ablation experiment (tools/ablate_floor.py): write children
        # IN PLACE over the incoming genome buffer. Only sound for the
        # contiguous-emit layout, where grid step i reads and writes
        # the SAME (D·K, Lp) row slab — the riffle layout scatters each
        # step's children across every other step's read rows, so
        # aliasing it would corrupt later reads.
        if "no_riffle" not in _ablate:
            raise ValueError("alias_io requires no_riffle (see comment)")
        aliases = {3: 0}  # genomes input -> genome output

    call = pl.pallas_call(
        kernel,
        grid=(G // D,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)),
            pl.BlockSpec((D * K, Lp), lambda i: (i, 0)),
        ] + [_const_spec(c) for c in consts + cross_consts + mut_consts],
        out_specs=out_specs if fused else out_specs[0],
        out_shape=out_shape if fused else out_shape[0],
        scratch_shapes=(
            _order_scratch_shapes(K, L, Lp)
            if crossover_kind == "order" else []
        ),
        input_output_aliases=aliases,
        compiler_params=_grid_compiler_params(_ablate),
    )

    default_params = jnp.asarray(
        [[mutation_rate, mutation_sigma]], dtype=jnp.float32
    )

    def compute_ranks(scores, k_tie, parity=0):
        """In-deme ranks (0 = best) for ``scores (..., Pp)`` →
        ``(..., G//D, D, K)`` f32, via ONE two-key sort flattened over
        every leading dim (an island runner passes (I, Pp) so the sort
        runs at (I·G, K) — a per-island vmapped sort measured ~3.4 ms
        per 8×131k generation vs ~0.9 flattened). Keys, in order:

        1. negated scores, with NaN pinned to -inf first so NaN rows
           rank last among real rows instead of after the pads (XLA's
           sort order puts NaN above +inf);
        2. a fresh random word per row, so SCORE TIES are broken in a
           new uniform random order every generation — each tied row's
           expected selection mass is then exactly uniform over the tie
           block (an index tie-break would systematically favor
           low-index rows of wide tie blocks, e.g. onemax_bits with its
           L+1 distinct score levels). Pad rows get the maximal tie key
           (real rows' keys are shifted into [0, 2^31)), so they still
           sort strictly after every real row and sampling rank < V can
           never select one.

        ``parity`` is accepted for signature parity with the ping-pong
        breed (the riffle's cohorts are parity-independent).
        """
        del parity
        lead = scores.shape[:-1]
        N = math.prod(lead) if lead else 1
        if "no_rank_sort" in _ablate:
            # Ablation harness only: raw scores where ranks belong —
            # selection semantics are garbage but the cost shape is
            # right, isolating the sort+argsort cost.
            return scores.reshape(*lead, G // D, D, K).astype(jnp.float32)
        s_real = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
        neg = -s_real.reshape(N * G, K).astype(jnp.float32)
        tb = jax.lax.shift_right_logical(
            jax.random.bits(k_tie, (N, Pp)), jnp.uint32(1)
        )
        if Pp != P:
            tb = jnp.where(
                jnp.arange(Pp, dtype=jnp.int32)[None, :] < P,
                tb,
                jnp.uint32(0xFFFFFFFF),
            )
        row_iota = jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[None, :], (N * G, K)
        )
        _, _, order = jax.lax.sort(
            (neg, tb.reshape(N * G, K), row_iota), dimension=1, num_keys=2
        )
        ranks = jnp.argsort(order, axis=1, stable=True).astype(jnp.float32)
        return ranks.reshape(*lead, G // D, D, K)

    def padded_ranks(gp, scores, ranks, key, mparams=None, parity=0):
        """``breed_padded`` with the deme ranks precomputed (see
        ``compute_ranks``): island runners hoist the rank sort above
        their per-island vmap and call this per island. With ranks from
        ``compute_ranks(scores, k_tie)`` where ``(_, k_tie) =
        split(key)``, this returns exactly what ``breed_padded(gp,
        scores, key)`` would. ``scores`` are still needed for the
        elitism epilogue (elites carry from the PREVIOUS generation)."""
        del parity  # riffle cohorts are parity-independent
        if mparams is None:
            mparams = default_params
        k_seed, _ = jax.random.split(key)
        seed = jax.random.randint(
            k_seed, (1, 1), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max,
            dtype=jnp.int32,
        )
        out = call(
            seed, mparams, ranks, gp, *consts, *cross_consts, *mut_consts
        )
        if fused:
            genomes, child_scores = out
            # Genome row order after reshape is (child r)·G + (deme i);
            # kernel scores come out deme-major (G, K) — transpose to match.
            if "no_riffle" in _ablate or "no_score_t" in _ablate:
                s2 = child_scores.reshape(Pp)
            else:
                s2 = child_scores.reshape(G, K).T.reshape(Pp)
            if Pp != P:
                s2 = jnp.where(
                    jnp.arange(Pp, dtype=jnp.int32) < P, s2, -jnp.inf
                )
            g2 = genomes.reshape(Pp, Lp)
            if elitism > 0:
                g2, s2 = _carry_elites(gp, scores, g2, s2, elitism)
            return g2, s2
        return out.reshape(Pp, Lp)

    def breed_padded(gp, scores, key, mparams=None, parity=0):
        """(Pp, Lp)-padded variant for loops that keep the pad resident.
        Takes/returns genomes (Pp, Lp) and scores (Pp,); when fused, tail
        child scores (rows >= P) come back masked to -inf so loop
        reductions and target checks never see a discarded child."""
        del parity
        _, k_tie = jax.random.split(key)
        ranks = compute_ranks(scores, k_tie)
        return padded_ranks(gp, scores, ranks, key, mparams)

    def breed(genomes, scores, key, mparams=None, parity=0):
        del parity
        gp = genomes.astype(gene_dtype)
        if Lp != L or Pp != P:
            gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
        if Pp != P:
            scores = jnp.pad(scores, (0, Pp - P), constant_values=-jnp.inf)
        out = breed_padded(gp, scores, key, mparams)
        if fused:
            g2, s2 = out
            return g2[:P, :L], s2[:P]
        return out[:P, :L]

    breed.padded = breed_padded
    breed.padded_ranks = padded_ranks
    breed.compute_ranks = compute_ranks
    breed.Lp = Lp
    breed.Pp = Pp
    breed.K = K
    breed.D = D  # actual demes-per-step (an explicit request may round down)
    breed.fused = fused
    breed.gene_dtype = gene_dtype
    breed.takes_params = True
    breed.default_params = default_params
    breed.elitism = elitism
    breed.crossover_kind = crossover_kind
    breed.layout = "riffle"
    breed.subblock = 1
    breed.parities = 1
    return breed


def _make_pingpong_breed(
    P, L, K, G, D, B, Pp, Lp,
    *,
    tournament_size,
    selection_kind,
    selection_param,
    mutation_rate,
    mutation_sigma,
    crossover_kind,
    mutate_kind,
    elitism,
    fused_obj,
    fused_consts,
    gene_dtype,
    ablate,
):
    """Assemble the ping-pong breed: one ``pl.pallas_call`` per parity
    over the parity's 4-D chunk view of the SAME flat (Pp, Lp) buffer,
    genome input aliased onto the genome output (children land in
    place). ``D`` here is demes per SUB-block; a grid step serves
    ``B * D`` demes (``B`` > 1 streams them through the manual
    double-buffer pipeline of ``_pp_breed_kernel``). See the layout
    algebra block at the top of this module for the mixing argument.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    import numpy as np

    fused = fused_obj is not None
    bf16_genes = gene_dtype == jnp.bfloat16
    q = pingpong_quantum(gene_dtype)
    Dstep = B * D           # demes per grid step
    W = Dstep * K           # rows per grid step
    S = Pp // W             # grid steps
    Ablk = W // q           # chunks per grid step
    padded = Pp != P

    consts = tuple(jnp.atleast_2d(jnp.asarray(c)) for c in fused_consts)
    if fused_obj is None:
        consts = ()
    cross_kind, cross_consts = _breeding_kind(crossover_kind, L, Lp)
    mut_kind, mut_consts = _breeding_kind(mutate_kind, L, Lp)

    # Static per-parity alive masks (padded populations only): 1.0 where
    # the cohort slot holds a real row. Under parity 1 pad rows scatter
    # through the comb, so aliveness is a per-slot property, not a
    # per-deme tail count.
    alive = []
    if padded:
        for parity in (0, 1):
            rows = pingpong_perm(parity, Pp, W, q)  # cohort -> physical
            alive.append(
                jnp.asarray(
                    (rows < P).astype(np.float32).reshape(S, Dstep, K)
                )
            )

    T = K // q  # chunks per deme
    if B > 1:
        # 6-D sub-block views: one integer-indexed (T, D, q, Lp) slab
        # per (step, sub-block) for the manual DMA pipeline.
        view = [(S, B, T, D, q, Lp), (B, T, D, S, q, Lp)]
    else:
        view = [(S, T, D, q, Lp), (T, D, S, q, Lp)]
    gspec = [
        pl.BlockSpec((1, T, D, q, Lp), lambda i: (i, 0, 0, 0, 0)),
        pl.BlockSpec((T, D, 1, q, Lp), lambda i: (0, 0, i, 0, 0)),
    ]

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i: (0,) * c.ndim)

    calls = []
    for parity in (0, 1):
        kernel = partial(
            _pp_breed_kernel,
            parity=parity, K=K, D=D, B=B, S=S, q=q, L=L, Lp=Lp,
            tk=tournament_size, sel=selection_kind, sel_param=selection_param,
            crossover=cross_kind, mutate=mut_kind,
            obj=fused_obj,
            obj_pad_ok=bool(getattr(fused_obj, "pad_ok", False)),
            n_consts=len(consts), n_cross=len(cross_consts),
            n_mut=len(mut_consts), bf16_genes=bf16_genes, padded=padded,
            ablate=tuple(ablate),
        )
        if B > 1:
            # Sub-block pipeline: genomes stay in HBM; the kernel
            # streams (D*K/q, q, Lp) slabs through the scratch pair.
            genome_in = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
            genome_out = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
            scratch = [
                pltpu.VMEM((2, T, D, q, Lp), gene_dtype),
                pltpu.VMEM((2, T, D, q, Lp), gene_dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ]
        else:
            genome_in = gspec[parity]
            genome_out = gspec[parity]
            scratch = []
        in_specs = [
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, Dstep, K), lambda i: (i, 0, 0)),
            genome_in,
        ]
        if padded:
            in_specs.append(pl.BlockSpec((1, Dstep, K), lambda i: (i, 0, 0)))
        in_specs += [_const_spec(c) for c in consts + cross_consts + mut_consts]
        out_specs = [genome_out]
        out_shape = [jax.ShapeDtypeStruct(view[parity], gene_dtype)]
        if fused:
            out_specs.append(pl.BlockSpec((1, Dstep, K), lambda i: (i, 0, 0)))
            out_shape.append(jax.ShapeDtypeStruct((S, Dstep, K), jnp.float32))
        calls.append(
            pl.pallas_call(
                kernel,
                grid=(S,),
                in_specs=in_specs,
                out_specs=out_specs if fused else out_specs[0],
                out_shape=out_shape if fused else out_shape[0],
                scratch_shapes=scratch,
                input_output_aliases={3: 0},
                compiler_params=_grid_compiler_params(ablate),
            )
        )

    default_params = jnp.asarray(
        [[mutation_rate, mutation_sigma]], dtype=jnp.float32
    )

    # Static pad mask in COHORT order per parity (parity 0 is physical
    # order, so the plain arange test suffices there).
    pad_cohort = [None, None]
    if padded:
        pad_cohort[0] = jnp.arange(Pp, dtype=jnp.int32) >= P
        pad_cohort[1] = jnp.asarray(pingpong_perm(1, Pp, W, q) >= P)

    def _to_cohort(scores, parity):
        """Physical-order (..., Pp) scores -> the parity's cohort order
        (group-major, demes of K consecutive slots). Parity 0 is the
        identity; parity 1 swaps the chunk/group axes of the comb view
        — a (Pp,)-sized transpose, ~4 MB at 1M, vs the ~0.5 GB genome
        traffic the in-place layout saves."""
        if parity == 0:
            return scores
        lead = scores.shape[:-1]
        sc = scores.reshape(*lead, Ablk, S, q)
        return jnp.swapaxes(sc, -3, -2).reshape(*lead, -1)

    def _to_physical(scores, parity):
        """Inverse of ``_to_cohort`` (same transpose, axes swapped
        back)."""
        if parity == 0:
            return scores
        lead = scores.shape[:-1]
        sc = scores.reshape(*lead, S, Ablk, q)
        return jnp.swapaxes(sc, -3, -2).reshape(*lead, -1)

    def _child_to_physical(cs, parity):
        """Kernel child scores (S, B*D, K) — written per READ deme — to
        physical row order of the INTERLEAVED child placement (child
        chunk u of deme d lands at sub-block chunk u*D + d: the
        (D, T) -> (T, D) axis swap, then the parity's comb)."""
        local = cs.reshape(S, B, D, T, q).swapaxes(2, 3).reshape(-1)
        return _to_physical(local, parity)

    def compute_ranks(scores, k_tie, parity=0):
        """In-deme ranks for the PARITY'S cohorts, shaped
        ``(..., S, Dstep, K)`` for the kernel's rank input. Same total
        order as the riffle path's ``compute_ranks`` (descending score,
        NaN last among real rows, random tie order, pads strictly
        last); the only difference is which rows form a deme."""
        lead = scores.shape[:-1]
        N = math.prod(lead) if lead else 1
        s_real = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
        s_c = _to_cohort(s_real, parity)
        neg = -s_c.reshape(N * S * Dstep, K).astype(jnp.float32)
        tb = jax.lax.shift_right_logical(
            jax.random.bits(k_tie, (N, Pp)), jnp.uint32(1)
        )
        if padded:
            tb = jnp.where(
                pad_cohort[parity][None, :], jnp.uint32(0xFFFFFFFF), tb
            )
        row_iota = jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[None, :], (N * S * Dstep, K)
        )
        _, _, order = jax.lax.sort(
            (neg, tb.reshape(N * S * Dstep, K), row_iota),
            dimension=1, num_keys=2,
        )
        ranks = jnp.argsort(order, axis=1, stable=True).astype(jnp.float32)
        return ranks.reshape(*lead, S, Dstep, K)

    def padded_ranks(gp, scores, ranks, key, mparams=None, parity=0):
        """One in-place generation at the given parity. ``ranks`` must
        come from ``compute_ranks(scores, k_tie, parity)`` with
        ``(_, k_tie) = split(key)``; genomes and scores are physical
        row order in and out (the cohort permutations are internal)."""
        if mparams is None:
            mparams = default_params
        if elitism > 0:
            # Elites are gathered BEFORE the kernel call: reading the
            # pre-breed buffer afterwards would force XLA to keep a
            # copy alive and defeat the in-place aliasing.
            top_s, top_i = jax.lax.top_k(scores, elitism)
            elite_g = jnp.take(gp, top_i, axis=0)
        k_seed, _ = jax.random.split(key)
        seed = jax.random.randint(
            k_seed, (1, 1), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max,
            dtype=jnp.int32,
        )
        args = [seed, mparams, ranks, gp.reshape(view[parity])]
        if padded:
            args.append(alive[parity])
        out = calls[parity](*args, *consts, *cross_consts, *mut_consts)
        if fused:
            genomes, child_scores = out
            s2 = _child_to_physical(child_scores, parity)
            if padded:
                s2 = jnp.where(
                    jnp.arange(Pp, dtype=jnp.int32) < P, s2, -jnp.inf
                )
            g2 = genomes.reshape(Pp, Lp)
            if elitism > 0:
                g2 = jax.lax.dynamic_update_slice(
                    g2, elite_g.astype(g2.dtype), (0, 0)
                )
                s2 = jax.lax.dynamic_update_slice(s2, top_s, (0,))
            return g2, s2
        return out.reshape(Pp, Lp)

    def breed_padded(gp, scores, key, mparams=None, parity=0):
        _, k_tie = jax.random.split(key)
        ranks = compute_ranks(scores, k_tie, parity)
        return padded_ranks(gp, scores, ranks, key, mparams, parity)

    def breed(genomes, scores, key, mparams=None, parity=0):
        gp = genomes.astype(gene_dtype)
        if Lp != L or Pp != P:
            gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
        if Pp != P:
            scores = jnp.pad(scores, (0, Pp - P), constant_values=-jnp.inf)
        out = breed_padded(gp, scores, key, mparams, parity)
        if fused:
            g2, s2 = out
            return g2[:P, :L], s2[:P]
        return out[:P, :L]

    breed.padded = breed_padded
    breed.padded_ranks = padded_ranks
    breed.compute_ranks = compute_ranks
    breed.Lp = Lp
    breed.Pp = Pp
    breed.K = K
    breed.D = Dstep  # total demes per grid step (dispatch-relevant)
    breed.fused = fused
    breed.gene_dtype = gene_dtype
    breed.takes_params = True
    breed.default_params = default_params
    breed.elitism = elitism
    breed.crossover_kind = crossover_kind
    breed.layout = "pingpong"
    breed.subblock = B
    breed.parities = 2
    breed.grid_steps = S
    return breed


def multigen_default_t(gene_dtype) -> int:
    """Default sub-generations per launch for ``PGA.run``'s fused loop.

    1 for every dtype — measured at 1M×100 OneMax (BASELINE.md round
    4): the single-generation kernel's grid pipeline already hides most
    of the HBM round trip under compute, and the in-kernel rank cube
    costs about what the /T amortization saves. Early same-process
    comparisons suggested +3–6% for f32 at T=8–16, but an INTERLEAVED
    A/B (5 alternating measurement rounds in one process) put the
    medians at T=1 142.6 vs T=8 135.5 gens/sec — the apparent wins were
    within-process drift. T > 1 remains available via
    ``pallas_generations_per_launch`` (note it trades exact
    target-generation reporting and per-generation deme mixing for the
    launch amortization).

    The ISLAND path also defaults to one-generation since round 5: the
    round-4 tie (multigen whole-epoch launches vs per-generation
    launches + hoisted sort, 128.6 vs 132.0) broke once the score
    stores were batched — one-generation 149.2 vs multigen 127.0
    gens/sec, 5/5 interleaved rounds (BASELINE.md round 5;
    ``engine._pallas_island_breed``). An explicit
    ``pallas_generations_per_launch > 1`` still selects the structural
    one-launch-per-migration-interval epoch.
    """
    del gene_dtype
    return 1


def _multigen_blocks_fit(
    K: int, D: int, Lp: int, gene_bytes: int, extra_scoped: int = 0
) -> bool:
    """VMEM gate for the multi-generation kernel: the single-generation
    model plus the genome/score scratch and the in-kernel rank cube
    (plus any variant extra, same contract as ``_blocks_fit``)."""
    scratch = D * K * Lp * gene_bytes + 4 * D * K
    return (
        4 * D * K * Lp * gene_bytes + scratch <= _BLOCK_BYTES_LIMIT
        and _scoped_vmem_bytes(K, D, Lp, gene_bytes)
        + scratch + 8 * K * K + extra_scoped
        <= _SCOPED_VMEM_LIMIT
    )


def make_pallas_multigen(
    pop_size: int,
    genome_len: int,
    *,
    deme_size: Optional[int] = None,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    mutation_rate: float = 0.01,
    mutation_sigma: float = 0.0,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    elitism: int = 0,
    fused_obj: Optional[Callable] = None,
    fused_consts: tuple = (),
    gene_dtype=jnp.float32,
    _demes_per_step: Optional[int] = None,
    _ablate: tuple = (),
    _layout: Optional[str] = None,
    _subblock: Optional[int] = None,
) -> Optional[Callable]:
    """Build the multi-generation fused breed:
    ``(genomes (P, L), scores (P,), key, steps[, mparams, target])
    -> (next_genomes, next_scores)`` breeding ``steps`` (a RUNTIME i32)
    consecutive generations per kernel launch with the deme group held
    in VMEM scratch — see ``_multigen_kernel`` for semantics (in-kernel
    ranking, per-deme elitism, per-group target freeze, launch-boundary
    riffle).

    Requires a fused objective (sub-generations need in-kernel scores);
    returns None otherwise or wherever ``make_pallas_breed`` would
    decline. The same deme-size policy applies; D defaults smaller than
    the one-generation kernel's because scratch shares the VMEM budget.

    ``_layout`` follows ``make_pallas_breed``: the auto default is the
    alias-compatible ping-pong layout (launches write their deme group
    back IN PLACE; the run loop alternates launch parity —
    ``breed.padded(..., parity=p)``). Sub-block pipelining is
    one-generation-only (the multigen kernel's whole point is keeping
    the group VMEM-resident), so ``_subblock`` is ignored here.
    """
    if _faults.PLAN is not None:  # same site as make_pallas_breed
        _faults.PLAN.fire("kernel.build")
    if fused_obj is None:
        return None
    _ablate = _validate_ablate(_ablate)
    shape = _kernel_shape(
        pop_size, genome_len, deme_size, tournament_size,
        selection_kind, selection_param, crossover_kind, mutate_kind,
        gene_dtype,
        blocks_fit=_multigen_blocks_fit,
        # Scratch shares the VMEM budget, so D caps below the
        # one-generation kernel's (measured: larger D gains nothing —
        # the riffle write amortizes /T already). With the round-5
        # K=512 auto default, f32 multigen lands at K=512 D=4 (D=8
        # fails the scratch-sharing VMEM gate) — which IS the round-4
        # multigen sweep's measured f32 sweet spot ("bigger K wins in
        # multigen; K=512 D=4", BASELINE.md round 4).
        d_pool=(16, 8, 4, 2, 1),
        d_default=4 if gene_dtype == jnp.bfloat16 else 8,
        demes_per_step=_demes_per_step,
        const_carrying=bool(fused_consts),
    )
    if shape is None:
        return None
    bf16_genes = gene_dtype == jnp.bfloat16
    P, L = pop_size, genome_len
    K, G, D, Pp, Lp, selection_param, d_cands = shape
    if elitism >= K // 4:
        # Per-deme elitism at this scale would freeze most of each deme.
        return None

    # _subblock is IGNORED here (not an error): the multigen kernel's
    # whole point is a VMEM-resident deme group, which the sub-block
    # streaming pipeline contradicts; the one-generation kernel is the
    # sub-block carrier.
    layout, D, _ = _resolve_layout(
        _layout,
        K=K, G=G, D=D, Pp=Pp, q=pingpong_quantum(gene_dtype),
        d_candidates=d_cands, subblock=None, fused=True,
        crossover_kind=crossover_kind, ablate=_ablate,
        multigen=True,
        padded_elitism=(Pp != P and elitism > 0),
        d_pinned=_demes_per_step is not None,
    )
    pp = layout == "pingpong"
    q = pingpong_quantum(gene_dtype)
    S = G // D
    Ablk = D * K // q

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    import numpy as np

    consts = tuple(jnp.atleast_2d(jnp.asarray(c)) for c in fused_consts)
    cross_kind, cross_consts = _breeding_kind(crossover_kind, L, Lp)
    mut_kind, mut_consts = _breeding_kind(mutate_kind, L, Lp)

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i: (0,) * c.ndim)

    smem = pltpu.SMEM
    pp_padded = pp and Pp != P
    alive = []
    if pp_padded:
        for par in (0, 1):
            rows = pingpong_perm(par, Pp, D * K, q)
            alive.append(
                jnp.asarray(
                    (rows < P).astype(np.float32).reshape(S, D, K)
                )
            )

    T = K // q
    view = [(S, T, D, q, Lp), (T, D, S, q, Lp)]
    gspec = [
        pl.BlockSpec((1, T, D, q, Lp), lambda i: (i, 0, 0, 0, 0)),
        pl.BlockSpec((T, D, 1, q, Lp), lambda i: (0, 0, i, 0, 0)),
    ]

    def build_call(par):
        kernel = partial(
            _multigen_kernel,
            K=K, D=D, L=L, Lp=Lp,
            tk=tournament_size, sel=selection_kind,
            sel_param=selection_param,
            crossover=cross_kind, mutate=mut_kind,
            obj=fused_obj,
            obj_pad_ok=bool(getattr(fused_obj, "pad_ok", False)),
            n_consts=len(consts), n_cross=len(cross_consts),
            n_mut=len(mut_consts), bf16_genes=bf16_genes, P=P,
            elitism=elitism, ablate=tuple(_ablate),
            layout=layout, parity=par, q=q,
        )
        in_specs = [
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=smem),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=smem),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=smem),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=smem),
            pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)),
            gspec[par] if pp else pl.BlockSpec((D * K, Lp), lambda i: (i, 0)),
        ]
        if pp_padded:
            in_specs.append(pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)))
        in_specs += [
            _const_spec(c) for c in consts + cross_consts + mut_consts
        ]
        return pl.pallas_call(
            kernel,
            grid=(S,),
            in_specs=in_specs,
            out_specs=[
                gspec[par] if pp
                else pl.BlockSpec((K, 1, D, Lp), lambda i: (0, i, 0, 0)),
                pl.BlockSpec((1, D, K), lambda i: (i, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(
                    view[par] if pp else (K, S, D, Lp), gene_dtype
                ),
                jax.ShapeDtypeStruct((S, D, K), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((D * K, Lp), gene_dtype),
                pltpu.VMEM((1, D, K), jnp.float32),
            ] + (
                _order_scratch_shapes(K, L, Lp)
                if crossover_kind == "order" else []
            ),
            input_output_aliases={5: 0} if pp else {},
            compiler_params=_grid_compiler_params(_ablate),
        )

    calls = [build_call(0), build_call(1)] if pp else [build_call(0)]

    default_params = jnp.asarray(
        [[mutation_rate, mutation_sigma]], dtype=jnp.float32
    )

    def _to_cohort(s, par):
        if not pp or par == 0:
            return s
        return jnp.swapaxes(s.reshape(Ablk, S, q), 0, 1).reshape(-1)

    def _to_physical(s, par):
        if not pp or par == 0:
            return s
        return jnp.swapaxes(s.reshape(S, Ablk, q), 0, 1).reshape(-1)

    def _child_to_physical(cs, par):
        """Launch-end scores (S, D, K), per resident deme, -> physical
        rows of the interleaved writeback (chunk u of deme d at group
        chunk u*D + d)."""
        local = cs.reshape(S, D, T, q).swapaxes(1, 2).reshape(-1)
        return _to_physical(local, par)

    def breed_padded(gp, scores, key, steps, mparams=None, target=None,
                     parity=0):
        """(Pp, Lp)-padded multi-generation breed. ``steps`` is a
        runtime i32 (0 = identity); pad rows must carry -inf scores on
        entry and do on exit. ``target`` freezes a deme group once its
        best reaches it (None/+inf = never). ``parity`` (ping-pong
        layout only) selects the launch's row grouping — the run loop
        alternates it so demes regroup between launches."""
        if mparams is None:
            mparams = default_params
        if target is None:
            target = jnp.inf
        seed = jax.random.randint(
            key, (1, 1), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max,
            dtype=jnp.int32,
        )
        steps_a = jnp.asarray(steps, dtype=jnp.int32).reshape(1, 1)
        tgt_a = jnp.asarray(target, dtype=jnp.float32).reshape(1, 1)
        s_in = _to_cohort(
            scores.astype(jnp.float32), parity
        ).reshape(S, D, K)
        if pp:
            args = [seed, mparams, steps_a, tgt_a, s_in,
                    gp.reshape(view[parity])]
            if pp_padded:
                args.append(alive[parity])
            genomes, cs = calls[parity](
                *args, *consts, *cross_consts, *mut_consts
            )
            s2 = _child_to_physical(cs, parity)
        else:
            genomes, cs = calls[0](
                seed, mparams, steps_a, tgt_a, s_in, gp,
                *consts, *cross_consts, *mut_consts,
            )
            s2 = cs.reshape(G, K).T.reshape(Pp)
        if Pp != P:
            s2 = jnp.where(jnp.arange(Pp, dtype=jnp.int32) < P, s2, -jnp.inf)
        return genomes.reshape(Pp, Lp), s2

    def breed(genomes, scores, key, steps, mparams=None, target=None,
              parity=0):
        gp = genomes.astype(gene_dtype)
        if Lp != L or Pp != P:
            gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
        if Pp != P:
            scores = jnp.pad(scores, (0, Pp - P), constant_values=-jnp.inf)
        g2, s2 = breed_padded(gp, scores, key, steps, mparams, target,
                              parity)
        return g2[:P, :L], s2[:P]

    breed.padded = breed_padded
    breed.Lp = Lp
    breed.Pp = Pp
    breed.K = K
    breed.D = D
    breed.fused = True
    breed.gene_dtype = gene_dtype
    breed.takes_params = True
    breed.default_params = default_params
    breed.elitism = elitism
    breed.crossover_kind = crossover_kind
    breed.multigen = True
    breed.layout = layout
    breed.subblock = 1
    breed.parities = 2 if pp else 1
    breed.grid_steps = S
    return breed


def _multigen_run_loop(obj, bm, pop_size, genome_len, T, donate,
                       history_gens=None):
    """Jitted run loop over the multi-generation breed ``bm``: launches
    chunks of ``min(T, n - gen)`` sub-generations until ``n`` or the
    target is reached. Same contract as the one-generation loop; the
    generation count still lands exactly on ``n`` (the runtime ``steps``
    input serves the remainder), and a target hit reports at launch
    granularity (its achiever is preserved by the kernel's group
    freeze).

    ``history_gens`` set = telemetry: the loop carries the stats buffer
    and the fn returns it as a trailing output. Rows land at LAUNCH
    granularity — each launch's ``steps`` generation rows are filled
    with the launch-end stats (the kernel keeps demes VMEM-resident
    between sub-generations, so per-sub-generation stats don't exist
    outside the kernel) and the stall counter advances by the whole
    launch width. Disabled path untouched.

    Ping-pong breeds: the carry additionally holds the LAUNCH counter,
    whose parity selects the kernel's row grouping (lax.cond between
    the two aliased pallas calls) — the double-buffer "carry parity" of
    the in-place layout. Riffle breeds carry it too (dead weight of one
    i32) so the two loop shapes stay identical."""
    from libpga_tpu.ops.evaluate import evaluate as _evaluate
    from libpga_tpu.utils import telemetry as _tl

    P, L, Pp, Lp = pop_size, genome_len, bm.Pp, bm.Lp
    pingpong = getattr(bm, "layout", "riffle") == "pingpong"

    def launch(g, s, sub, steps, mparams, target, lc):
        if not pingpong:
            return bm.padded(g, s, sub, steps, mparams, target)
        return jax.lax.cond(
            jnp.equal(lc & 1, 0),
            lambda a: bm.padded(*a, parity=0),
            lambda a: bm.padded(*a, parity=1),
            (g, s, sub, steps, mparams, target),
        )

    def masked_tail(s):
        if Pp == P:
            return s
        return jnp.where(jnp.arange(Pp, dtype=jnp.int32) < P, s, -jnp.inf)

    if history_gens is None:

        def run_loop(genomes, key, n, target, mparams):
            gp = genomes.astype(bm.gene_dtype)
            if Lp != L or Pp != P:
                gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
            scores0 = masked_tail(
                jnp.pad(_evaluate(obj, gp[:P, :L]), (0, Pp - P))
            )

            def cond(carry):
                g, s, k, gen, lc = carry
                return jnp.logical_and(gen < n, jnp.max(s) < target)

            def body(carry):
                g, s, k, gen, lc = carry
                k, sub = jax.random.split(k)
                steps = jnp.minimum(jnp.int32(T), n - gen)
                g2, s2 = launch(g, s, sub, steps, mparams, target, lc)
                return (g2, s2, k, gen + steps, lc + 1)

            init = (gp, scores0, key, jnp.int32(0), jnp.int32(0))
            g, s, k, gens, _ = jax.lax.while_loop(cond, body, init)
            return g[:P, :L], s[:P], gens

    else:

        def run_loop(genomes, key, n, target, mparams):
            gp = genomes.astype(bm.gene_dtype)
            if Lp != L or Pp != P:
                gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
            scores0 = masked_tail(
                jnp.pad(_evaluate(obj, gp[:P, :L]), (0, Pp - P))
            )

            def cond(carry):
                g, s, k, gen, lc, best, stall, buf = carry
                return jnp.logical_and(gen < n, jnp.max(s) < target)

            def body(carry):
                g, s, k, gen, lc, best, stall, buf = carry
                k, sub = jax.random.split(k)
                steps = jnp.minimum(jnp.int32(T), n - gen)
                g2, s2 = launch(g, s, sub, steps, mparams, target, lc)
                # Stats on the live [:P] rows only (the pad tail carries
                # -inf scores / zero genes).
                row, best, stall = _tl.stats_row(
                    g2[:P, :L], s2[:P], best, stall, step=steps
                )
                buf = _tl.fill_rows(buf, gen, gen + steps, row)
                return (g2, s2, k, gen + steps, lc + 1, best, stall, buf)

            init = (
                gp, scores0, key, jnp.int32(0), jnp.int32(0),
                jnp.max(scores0), jnp.int32(0),
                _tl.history_init(history_gens),
            )
            g, s, k, gens, _, _, _, buf = jax.lax.while_loop(
                cond, body, init
            )
            return g[:P, :L], s[:P], gens, buf

    return jax.jit(run_loop, donate_argnums=(0,) if donate else ())


def make_pallas_run(
    obj: Callable,
    *,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    mutation_rate: float = 0.01,
    mutation_sigma: float = 0.0,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    elitism: int = 0,
    deme_size: Optional[int] = None,
    donate: bool = True,
    gene_dtype=jnp.float32,
    generations_per_launch: Optional[int] = None,
    history_gens: Optional[int] = None,
    layout: Optional[str] = None,
    subblock: Optional[int] = None,
) -> Optional[Callable]:
    """Build a per-shape factory for the fused run loop used by ``PGA.run``:
    ``build(pop_size, genome_len)`` returns a jitted
    ``(genomes, key, n, target, mparams) -> (genomes, scores, gens)`` with
    the same contract as the XLA path in ``engine._compiled_run`` (plus
    the runtime mutation-params input — see ``make_pallas_breed``), or
    None when unsupported (non-TPU backend, tournament size out of the
    kernel's 1..16 range, or per-shape inside the factory) — the engine
    then falls back to the XLA path.

    ``history_gens`` set = telemetry: the host-level while_loop around
    the kernel launches carries a ``(history_gens, NUM_STATS)`` stats
    buffer (written from the kernel-returned scores — the kernel itself
    is untouched) and the built fn returns it as a trailing output. The
    disabled loops below are byte-identical to the pre-telemetry code.

    ``generations_per_launch`` (T): generations bred per kernel launch.
    None = auto (``multigen_default_t`` when the objective fuses, else
    1); 1 = the one-generation kernel. T > 1 uses the multi-generation kernel
    (``_multigen_kernel``): the HBM IO floor amortizes /T, the target
    check runs every launch (generations reported in launch-granularity
    chunks; a mid-launch target hit freezes its deme group so the
    achieving individual survives to the returned population), and
    elitism is applied per deme."""
    # Fault-injection site (robustness/faults): fires BEFORE the backend
    # gate so a chaos run on any host exercises the engine's
    # build-failure fallback policy through this real entry point.
    if _faults.PLAN is not None:
        _faults.PLAN.fire("kernel.build")
    if not _supported():
        return None
    # The Mosaic kernel only lowers on TPU; an explicit use_pallas=True on
    # CPU/GPU must fall back, not crash at trace time. (make_pallas_breed
    # itself stays platform-agnostic so force_tpu_interpret_mode tests can
    # call it on CPU.)
    import jax as _jax

    if _jax.default_backend() != "tpu":
        return None

    from libpga_tpu.ops.evaluate import evaluate as _evaluate

    # Objectives carrying a ``kernel_rowwise`` batched form evaluate
    # INSIDE the breed kernel (children are scored while still in VMEM),
    # eliminating the separate per-generation evaluation pass over HBM
    # (~2 ms/gen at 1M×100; see BASELINE.md). The attribute is an explicit
    # opt-in set only on builtins verified to lower under Mosaic. Problem
    # data the rowwise form needs (e.g. the NK table) is declared via
    # ``kernel_rowwise_consts`` and becomes extra kernel inputs.
    fused_obj = getattr(obj, "kernel_rowwise", None)
    fused_consts = tuple(getattr(obj, "kernel_rowwise_consts", ()))
    # Gene-major fused TSP scoring (make_tsp_coords duplicate_mode=
    # "genes"): the long-genome evaluation path; pairs with order
    # crossover (whose scratch it reuses) on f32 genes.
    fused_tsp = None
    if (
        fused_obj is None
        and crossover_kind == "order"
        and gene_dtype == jnp.float32
    ):
        fused_tsp = getattr(obj, "kernel_gene_major", None)
    T = generations_per_launch
    if T is None:
        T = multigen_default_t(gene_dtype) if fused_obj is not None else 1

    def build(pop_size: int, genome_len: int):
        common = dict(
            deme_size=deme_size, tournament_size=tournament_size,
            selection_kind=selection_kind,
            selection_param=selection_param,
            mutation_rate=mutation_rate,
            mutation_sigma=mutation_sigma,
            crossover_kind=crossover_kind, mutate_kind=mutate_kind,
            fused_obj=fused_obj, fused_consts=fused_consts,
            gene_dtype=gene_dtype,
            _layout=layout, _subblock=subblock,
        )
        if T > 1:
            bm = make_pallas_multigen(
                pop_size, genome_len, elitism=elitism, **common
            )
            if bm is not None:
                return _multigen_run_loop(
                    obj, bm, pop_size, genome_len, T, donate,
                    history_gens=history_gens,
                )
            if generations_per_launch is not None:
                # An EXPLICIT T > 1 expresses intent (e.g. a T-sweep
                # benchmark); degrading to the one-generation kernel
                # silently would make every sweep point measure T=1.
                import warnings

                warnings.warn(
                    f"pallas_generations_per_launch={generations_per_launch}"
                    " requested but the multi-generation kernel declined"
                    " (objective not in-kernel fusable, elitism too large"
                    " for the deme, or VMEM misfit) — falling back to the"
                    " one-generation kernel",
                    stacklevel=2,
                )
        breed = make_pallas_breed(
            pop_size, genome_len,
            elitism=elitism if (fused_obj is not None or fused_tsp) else 0,
            fused_tsp=fused_tsp,
            **common,
        )
        if breed is None:
            return None

        P, L, Pp, Lp = pop_size, genome_len, breed.Pp, breed.Lp
        pingpong = getattr(breed, "layout", "riffle") == "pingpong"

        def one_gen(g, s, sub, mparams, gen):
            """One breed at the generation's parity. Ping-pong layouts
            alternate the two aliased kernels via lax.cond (the cond
            predicate is the loop-carried generation counter — the
            'double-buffer carry parity'); riffle breeds dispatch
            directly. Returns (g2, s2) for fused breeds, g2 otherwise."""
            if not pingpong:
                return breed.padded(g, s, sub, mparams)
            return jax.lax.cond(
                jnp.equal(gen & 1, 0),
                lambda a: breed.padded(*a, parity=0),
                lambda a: breed.padded(*a, parity=1),
                (g, s, sub, mparams),
            )

        def masked_tail(s):
            """Scores for pad rows pinned to -inf: they must never win the
            target check or surface from the final population."""
            if Pp == P:
                return s
            return jnp.where(jnp.arange(Pp, dtype=jnp.int32) < P, s, -jnp.inf)

        if history_gens is None:

            def run_loop(genomes, key, n, target, mparams):
                # Pad once; the loop carries the deme-aligned (Pp, Lp)
                # matrix. Evaluation reads the [:P, :L] view (the slice
                # fuses into the objective's reduction — nothing
                # materializes).
                gp = genomes.astype(gene_dtype)
                if Lp != L or Pp != P:
                    gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
                scores0 = masked_tail(
                    jnp.pad(_evaluate(obj, gp[:P, :L]), (0, Pp - P))
                )

                def cond(carry):
                    g, s, k, gen = carry
                    return jnp.logical_and(gen < n, jnp.max(s) < target)

                def body(carry):
                    g, s, k, gen = carry
                    k, sub = jax.random.split(k)
                    if breed.fused:
                        # tail already -inf; elitism applied inside breed
                        g2, s2 = one_gen(g, s, sub, mparams, gen)
                    else:
                        g2 = one_gen(g, s, sub, mparams, gen)
                        s2 = masked_tail(jnp.pad(
                            _evaluate(obj, g2[:P, :L]), (0, Pp - P)
                        ))
                        if elitism > 0:
                            g2, s2 = _carry_elites(g, s, g2, s2, elitism)
                    return (g2, s2, k, gen + 1)

                init = (gp, scores0, key, jnp.int32(0))
                g, s, k, gens = jax.lax.while_loop(cond, body, init)
                return g[:P, :L], s[:P], gens

        else:
            from libpga_tpu.utils import telemetry as _tl

            def run_loop(genomes, key, n, target, mparams):
                gp = genomes.astype(gene_dtype)
                if Lp != L or Pp != P:
                    gp = jnp.pad(gp, ((0, Pp - P), (0, Lp - L)))
                scores0 = masked_tail(
                    jnp.pad(_evaluate(obj, gp[:P, :L]), (0, Pp - P))
                )

                def cond(carry):
                    g, s, k, gen, best, stall, buf = carry
                    return jnp.logical_and(gen < n, jnp.max(s) < target)

                def body(carry):
                    g, s, k, gen, best, stall, buf = carry
                    k, sub = jax.random.split(k)
                    if breed.fused:
                        g2, s2 = one_gen(g, s, sub, mparams, gen)
                    else:
                        g2 = one_gen(g, s, sub, mparams, gen)
                        s2 = masked_tail(jnp.pad(
                            _evaluate(obj, g2[:P, :L]), (0, Pp - P)
                        ))
                        if elitism > 0:
                            g2, s2 = _carry_elites(g, s, g2, s2, elitism)
                    # Stats on the live [:P] rows (pad tail is -inf/0).
                    row, best, stall = _tl.stats_row(
                        g2[:P, :L], s2[:P], best, stall
                    )
                    buf = _tl.write_row(buf, gen, row)
                    return (g2, s2, k, gen + 1, best, stall, buf)

                init = (
                    gp, scores0, key, jnp.int32(0), jnp.max(scores0),
                    jnp.int32(0), _tl.history_init(history_gens),
                )
                g, s, k, gens, _, _, buf = jax.lax.while_loop(
                    cond, body, init
                )
                return g[:P, :L], s[:P], gens, buf

        return jax.jit(run_loop, donate_argnums=(0,) if donate else ())

    return build
