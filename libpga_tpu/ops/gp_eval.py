"""Fused Pallas stack-machine evaluator for GP genomes + its dry-run plan.

The accelerator half of the GP subsystem (``libpga_tpu/gp/``): one
``pallas_call`` scores a whole population block against the whole
sample batch with the value stacks resident in VMEM scratch — the same
kernel shape as the round-4 VMEM-scratch order-crossover walk
(``ops/pallas_step.py``): a bounded ``fori_loop`` over token positions
whose every stack access is an iota-compare mask (no gathers — TPU
gathers neither lower in Mosaic nor pay for themselves at ~10
ns/element).

Grid: one step per ``rows_per_block`` population rows. Per step the
kernel holds in VMEM: the block's decoded opcode/operand matrices
``(R, Tp)``, the variable-major sample matrix ``(Vp, Bp)`` (replicated
— SR batches are small), the target row + sample mask ``(8, Bp)``, the
``(S, R, Bp)`` value-stack scratch, and the ``(R, LANE)`` score block
it writes. The token-step body is LITERALLY the XLA interpreter's
(``gp/interpreter.make_token_step``) — one copy of the semantics, so
the fused and fallback paths cannot drift; ``tools/gp_smoke.py`` gates
their agreement (interpret mode off-TPU) and ``gp/reference.py`` is
the numpy oracle behind both.

:func:`gp_eval_plan` is the DRY-RUN resolution — the admissibility
oracle the tuning config space consumes (``tuning/space.py``,
``gp_stack_depth`` / ``gp_opcode_block`` knobs), mirroring
``pallas_step.kernel_plan``'s contract: ``None`` where the kernel
declines (the XLA interpreter serves), ``ValueError`` exactly where an
explicit knob is invalid, a resolved-plan dict otherwise. Because the
two knobs shape the TRACED program of the XLA path too, distinct
admissible settings are distinct plans even on CPU — the first >1-plan
autotuner space off-chip.

CHIP-ROUND NOTE: like every Mosaic kernel in the tree this round is
CPU-validated through interpret mode only; first-hardware items are
the 3-D ``(S, R, Bp)`` scratch layout, the int32 masked-accumulation
token reads, and (optimize path) the runtime ``fori_loop`` trip bound
read from the per-block max-live-length input — a traced bound lowers
to ``while`` under Mosaic; if the hardware round finds it hostile the
fallback is the static ``T // B`` bound with the same masks.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from libpga_tpu.gp.encoding import (
    DISPATCH_KINDS,
    GPConfig,
    PAD_OP,
    decode_args,
    decode_ops,
)
from libpga_tpu.gp.interpreter import make_token_step
from libpga_tpu.gp.optimize import EvalProgram, optimize_for_eval

LANE = 128
SUBLANE = 8

#: Population rows per grid step, largest-first (the plan walks this
#: pool under the VMEM budget, exactly like the breed kernel's deme
#: pool).
GP_ROW_POOL = (256, 128, 64, 32, 16, 8)

#: Scoped-VMEM budget for one grid step's working set. Conservative —
#: the stack tensor dominates and the budget keeps it well under the
#: ~16 MB/core VMEM alongside the breed kernel's own residency.
GP_VMEM_BUDGET = 4 * 1024 * 1024


def _lanes(n: int) -> int:
    return max(LANE, math.ceil(n / LANE) * LANE)


def _sublanes(n: int) -> int:
    return max(SUBLANE, math.ceil(n / SUBLANE) * SUBLANE)


def gp_eval_plan(
    pop: int,
    gp: GPConfig,
    n_samples: int,
    *,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
) -> Optional[dict]:
    """Dry-run shape resolution of the fused GP evaluator.

    Returns the plan dict (resolved ``stack_depth``/``opcode_block``/
    ``dispatch``, fused-kernel geometry with ``rows_per_block``/
    ``grid``/``vmem_bytes`` — or ``path="xla"`` with
    ``rows_per_block=None`` when no block size fits the budget or
    divides ``pop``), raises ``ValueError`` for an explicitly invalid
    knob (a stack depth below the provable bound, a block that does not
    divide ``max_nodes``, an unknown dispatch kind), and never returns
    a geometry the factory wouldn't build — :func:`make_gp_eval`
    resolves through THIS function.
    """
    if pop < 1 or n_samples < 1:
        return None
    required = gp.required_stack()
    S = int(stack_depth or gp.stack_depth or required)
    if S < required:
        raise ValueError(
            f"gp_stack_depth {S} < required bound {required} (a "
            f"well-formed {gp.max_nodes}-token program can hold "
            f"{required} values)"
        )
    B = int(opcode_block or gp.opcode_block or 1)
    if B < 1 or gp.max_nodes % B:
        raise ValueError(
            f"gp_opcode_block {B} does not divide max_nodes "
            f"{gp.max_nodes}"
        )
    D = dispatch if dispatch is not None else (gp.dispatch or "dense")
    if D not in DISPATCH_KINDS:
        raise ValueError(
            f"gp_dispatch {D!r} not in {tuple(k for k in DISPATCH_KINDS if k)}"
        )
    Bp = _lanes(n_samples)
    Tp = _lanes(gp.max_nodes)
    Vp = _sublanes(gp.n_vars)

    def vmem_bytes(R: int) -> int:
        stack = S * R * Bp * 4
        toks = 2 * R * Tp * 4  # ops (i32) + args (f32)
        samples = Vp * Bp * 4 + SUBLANE * Bp * 4  # xt + y/mask rows
        ctab = SUBLANE * LANE * 4  # constant-table row
        out = R * LANE * 4
        return stack + toks + samples + ctab + out

    rows = next(
        (
            R
            for R in GP_ROW_POOL
            if pop % R == 0 and vmem_bytes(R) <= GP_VMEM_BUDGET
        ),
        None,
    )
    plan = {
        "stack_depth": S,
        "opcode_block": B,
        "dispatch": D,
        "optimize": bool(gp.optimize),
        "batch_lanes": Bp,
        "token_lanes": Tp,
        "rows_per_block": rows,
        "grid": None if rows is None else pop // rows,
        "vmem_bytes": None if rows is None else vmem_bytes(rows),
        "path": "xla" if rows is None else "fused",
    }
    return plan


def gp_plan_cost(
    plan: dict,
    pop: int,
    gp: GPConfig,
    n_samples: int,
    *,
    live_length: Optional[float] = None,
) -> dict:
    """Analytic per-evaluation cost of a resolved :func:`gp_eval_plan`
    (the ISSUE 17 plan→cost hook; ``libpga_tpu/perf/cost.py`` builds the
    GP roofline report from this).

    The mask-only interpreter executes its FULL lattice regardless of
    masks — every executed token step touches the whole ``(S, P, B)``
    value stack (top read, second read, result write: 3 passes at
    compare+select = 2 ops each) and computes candidate ``(P, B)``
    planes for its dispatch lattice (compute + select = 2 ops per
    plane) — so the elementwise count IS the device work, not an upper
    bound:

        ``flops_per_eval = tokens · P · B · (6·S + 2·n_planes)``

    ``tokens`` is the trip count the evaluator actually runs: the
    static ``max_nodes`` cap on the legacy path, the MEASURED mean live
    length (``gp/optimize.mean_live_length``, passed by the caller as
    ``live_length``) when the plan's config optimizes — that is what
    keeps ``pga.program_report`` / ``perf.achieved`` roofline fractions
    honest after compaction + trip reduction. ``n_planes`` is ``n_ops``
    plus one for the optimizer's synthetic ``LIT`` leaf, minus one when
    ``dispatch="blocked"`` fuses the add/sub planes into one.

    ``B`` is the padded ``batch_lanes`` on the fused path (the kernel
    pads samples to the 128 lane); the XLA interpreter runs unpadded,
    so for ``path="xla"`` the same formula over raw ``n_samples`` is
    reported. HBM bytes are the evaluation's irreducible traffic: the
    token stream read (ops i32 + args f32 per padded token — the
    compacted buffer keeps the padded extent, only the loop shortens),
    the sample matrix and targets, and the score write. ``vmem_bytes``
    is the plan's own admission figure (None on the XLA path).
    """
    S = int(plan["stack_depth"])
    fused = plan["path"] == "fused"
    B = int(plan["batch_lanes"]) if fused else int(n_samples)
    Tp = int(plan["token_lanes"]) if fused else int(gp.max_nodes)
    opt = bool(plan.get("optimize", False))
    tokens = (
        float(live_length)
        if (opt and live_length is not None)
        else float(gp.max_nodes)
    )
    n_planes = gp.n_ops + (1 if opt else 0)
    if (
        plan.get("dispatch") == "blocked"
        and "add" in gp.binary
        and "sub" in gp.binary
    ):
        n_planes -= 1
    flops = int(round(tokens * pop * B * (6 * S + 2 * n_planes)))
    hbm = pop * Tp * (4 + 4) + gp.n_vars * B * 4 + B * 4 + pop * 4
    return {
        "flops_per_eval": flops,
        "hbm_bytes_per_eval": hbm,
        "vmem_bytes": plan["vmem_bytes"],
        "batch_lanes": B,
        "path": plan["path"],
        "tokens_per_program": tokens,
    }


def make_gp_eval(
    gp: GPConfig,
    X,
    y,
    *,
    pop: int,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
    optimize: Optional[bool] = None,
) -> Callable:
    """Build the fused evaluator for one population size: ``fn(genomes
    (pop, 2T) | EvalProgram)`` -> ``(pop,)`` float32 ``-RMSE`` scores,
    semantics bit-matching the XLA interpreter path (same token step,
    same sanitization). When the config optimizes (``gp.optimize``, or
    the explicit ``optimize`` override) the build accepts raw genomes
    OR a pre-built :class:`~libpga_tpu.gp.optimize.EvalProgram` (the
    ``prepare_eval`` hook's output), sorts rows by live length so each
    grid block holds like-sized programs, and bounds each block's token
    loop at that block's max live length — a runtime scalar, so trips
    shrink with compaction and nothing recompiles across generations.
    Raises ``ValueError`` where the plan declines — callers
    (``gp/sr.py``) apply the ``PGAConfig.fallback`` stance.
    """
    import numpy as np

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Xa = np.asarray(X, np.float32)
    if Xa.ndim == 1:
        Xa = Xa[:, None]
    ya = np.asarray(y, np.float32).reshape(-1)
    n_samples = Xa.shape[0]
    plan = gp_eval_plan(
        pop, gp, n_samples,
        stack_depth=stack_depth, opcode_block=opcode_block,
        dispatch=dispatch,
    )
    if plan is None or plan["rows_per_block"] is None:
        raise ValueError(
            f"fused GP evaluator declines pop={pop} "
            f"(no admissible rows_per_block in {GP_ROW_POOL})"
        )
    opt_on = bool(gp.optimize if optimize is None else optimize)
    S, B = plan["stack_depth"], plan["opcode_block"]
    R, Bp, Tp = plan["rows_per_block"], plan["batch_lanes"], plan["token_lanes"]
    T = gp.max_nodes
    n_vars = gp.n_vars
    Vp = _sublanes(n_vars)

    xt = np.zeros((Vp, Bp), np.float32)
    xt[:n_vars, :n_samples] = Xa.T
    ym = np.zeros((SUBLANE, Bp), np.float32)
    ym[0, :n_samples] = ya
    ym[1, :n_samples] = 1.0  # sample mask (pad lanes are dead)
    n_consts = max(len(gp.consts), 1)
    if n_consts > LANE:
        raise ValueError(
            f"constant table of {n_consts} entries exceeds the kernel's "
            f"one-lane-row layout ({LANE})"
        )
    ctab = np.zeros((SUBLANE, LANE), np.float32)
    ctab[0, :n_consts] = np.asarray(gp.consts or (0.0,), np.float32)
    xt_j = jnp.asarray(xt)
    ym_j = jnp.asarray(ym)
    ctab_j = jnp.asarray(ctab)
    step = make_token_step(gp, dispatch=plan["dispatch"], lit=opt_on)

    def finish(stack, sp, yrow, mask, out_ref):
        sidx = jax.lax.broadcasted_iota(jnp.int32, (S, R, Bp), 0)
        top = jnp.sum(
            jnp.where(sidx == sp[None, :, None] - 1, stack, 0.0), axis=0
        )
        top = jnp.where(sp[:, None] > 0, top, 0.0)
        err = (top - yrow[None, :]) * mask[None, :]
        mse = jnp.sum(err * err, axis=1) / jnp.sum(mask)
        score = -jnp.sqrt(mse)
        score = jnp.where(jnp.isfinite(score), score, -jnp.float32(jnp.inf))
        out_ref[...] = jnp.broadcast_to(score[:, None], (R, LANE))

    def kernel(ops_ref, args_ref, xt_ref, ym_ref, c_ref, out_ref,
               stack_ref):
        ops_b = ops_ref[...]  # (R, Tp) int32
        args_b = args_ref[...]
        xts = xt_ref[...]
        consts = c_ref[0, :]
        stack_ref[...] = jnp.zeros((S, R, Bp), jnp.float32)
        lane_t = jax.lax.broadcasted_iota(jnp.int32, (R, Tp), 1)

        def body(i, sp):
            stack = stack_ref[...]
            for j in range(B):
                t = i * B + j
                tm = lane_t == t
                op = jnp.sum(jnp.where(tm, ops_b, 0), axis=1)
                arg = jnp.sum(jnp.where(tm, args_b, 0.0), axis=1)
                stack, sp = step(stack, sp, op, arg, xts, consts)
            stack_ref[...] = stack
            return sp

        sp = jax.lax.fori_loop(
            0, T // B, body, jnp.zeros((R,), jnp.int32)
        )
        finish(stack_ref[...], sp, ym_ref[0, :], ym_ref[1, :], out_ref)

    def kernel_opt(ops_ref, args_ref, xt_ref, ym_ref, c_ref, mx_ref,
                   out_ref, stack_ref):
        # Identical walk, but the trip count is the block's max live
        # length (rows are length-sorted, so blocks are homogeneous):
        # tokens past a row's own length are PAD_OP inside the bound
        # and never visited beyond it. Runtime bound -> while loop;
        # see the module CHIP-ROUND NOTE.
        ops_b = ops_ref[...]
        args_b = args_ref[...]
        xts = xt_ref[...]
        consts = c_ref[0, :]
        stack_ref[...] = jnp.zeros((S, R, Bp), jnp.float32)
        lane_t = jax.lax.broadcasted_iota(jnp.int32, (R, Tp), 1)

        def body(i, sp):
            stack = stack_ref[...]
            for j in range(B):
                t = i * B + j
                tm = lane_t == t
                op = jnp.sum(jnp.where(tm, ops_b, 0), axis=1)
                arg = jnp.sum(jnp.where(tm, args_b, 0.0), axis=1)
                stack, sp = step(stack, sp, op, arg, xts, consts)
            stack_ref[...] = stack
            return sp

        nblk = (mx_ref[0, 0] + (B - 1)) // B
        sp = jax.lax.fori_loop(
            0, nblk, body, jnp.zeros((R,), jnp.int32)
        )
        finish(stack_ref[...], sp, ym_ref[0, :], ym_ref[1, :], out_ref)

    grid = plan["grid"]
    tok_specs = [
        pl.BlockSpec((R, Tp), lambda i: (i, 0)),
        pl.BlockSpec((R, Tp), lambda i: (i, 0)),
        pl.BlockSpec((Vp, Bp), lambda i: (0, 0)),
        pl.BlockSpec((SUBLANE, Bp), lambda i: (0, 0)),
        pl.BlockSpec((SUBLANE, LANE), lambda i: (0, 0)),
    ]

    def _pad_tokens(ops, args):
        if Tp != T:
            ops = jnp.pad(ops, ((0, 0), (0, Tp - T)),
                          constant_values=PAD_OP)
            args = jnp.pad(args, ((0, 0), (0, Tp - T)))
        return ops, args

    if opt_on:

        def run(m):
            prog = m if isinstance(m, EvalProgram) else (
                optimize_for_eval(m, gp)
            )
            order = jnp.argsort(prog.length)
            inv = jnp.argsort(order)
            ops, args = _pad_tokens(
                jnp.take(prog.ops, order, axis=0),
                jnp.take(prog.args, order, axis=0),
            )
            blkmax = jnp.max(
                jnp.take(prog.length, order).reshape(grid, R), axis=1
            )
            mx = jnp.broadcast_to(
                blkmax[:, None].astype(jnp.int32), (grid, LANE)
            )
            out = pl.pallas_call(
                kernel_opt,
                grid=(grid,),
                in_specs=tok_specs + [
                    pl.BlockSpec((1, LANE), lambda i: (i, 0)),
                ],
                out_specs=pl.BlockSpec((R, LANE), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((pop, LANE), jnp.float32),
                scratch_shapes=[pltpu.VMEM((S, R, Bp), jnp.float32)],
            )(ops, args, xt_j, ym_j, ctab_j, mx)
            return jnp.take(out[:, 0], inv)

    else:

        def run(genomes):
            ops, args = _pad_tokens(
                decode_ops(genomes, gp), decode_args(genomes, gp)
            )
            out = pl.pallas_call(
                kernel,
                grid=(grid,),
                in_specs=tok_specs,
                out_specs=pl.BlockSpec((R, LANE), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((pop, LANE), jnp.float32),
                scratch_shapes=[pltpu.VMEM((S, R, Bp), jnp.float32)],
            )(ops, args, xt_j, ym_j, ctab_j)
            return out[:, 0]

    run.plan = dict(plan)
    return jax.jit(run)


__all__ = ["LANE", "GP_ROW_POOL", "GP_VMEM_BUDGET", "gp_eval_plan",
           "gp_plan_cost", "make_gp_eval"]
