"""On-device best / top-k extraction.

The reference copies the whole score vector to the host and argmaxes in a C
loop (``src/pga.cu:218-236``), and its top-k variants are NULL-returning
stubs (``pga.cu:238-248``). At 1M+ populations the host round-trip dominates,
so both argmax and top-k run on device here; only the winning genomes cross
to the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def best_index(scores: jax.Array) -> jax.Array:
    """Index of the best (maximal) score. On-device scalar."""
    return jnp.argmax(scores)


@jax.jit
def best_genome(genomes: jax.Array, scores: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(best_genome, best_score) — one gather, fully on device."""
    i = jnp.argmax(scores)
    return genomes[i], scores[i]


def top_k_genomes(
    genomes: jax.Array, scores: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Top-k individuals by score, best first.

    Implements what the reference's ``pga_get_best_top`` promised
    (``include/pga.h:91``) but stubbed to NULL (``pga.cu:238-240``).

    Returns ``(k, L)`` genomes and ``(k,)`` scores.
    """
    top_scores, idx = jax.lax.top_k(scores, k)
    return genomes[idx], top_scores
