"""Device-speed custom CROSSOVER and MUTATION from expressions.

The reference's extension mechanism covers all three GA callbacks at
device speed — ``__device__`` function pointers for the objective,
mutation, and crossover (``/root/reference/include/pga.h:46-48``, install
idiom ``src/pga.cu:157-161``); its flagship TSP driver installs a custom
crossover (``test3/test.cu:48-64,87-91``). Round 4 gave OBJECTIVES their
TPU-native custom path (``objectives/expr.py``); this module closes the
remaining two: a custom breeding operator written as an expression
compiles to the rowwise form the fused Pallas kernel's ``_deme_child``
evaluates on VMEM-resident parents — no ``jax.pure_callback``, no CPU
pin, unlike the host-pointer compatibility path (``capi_bridge.py``).

Variables available to the expressions (everything is per-gene and
broadcasts; ``P`` rows by ``L`` genes):

- crossover: ``p1``, ``p2`` (the selected parents), and
- mutation: ``g`` (the child genome) plus runtime ``rate`` / ``sigma``
  (the engine's mutation parameters — annealing schedules share one
  compilation, like the builtin kinds);
- both: ``r``, ``r2`` (two independent per-gene uniform [0,1) streams),
  ``q``, ``q2`` (two per-ROW uniforms, shape (P, 1) — cut points,
  per-child gates), ``i`` (gene index), ``L``, literals, ``pi``, ``e``,
  and registered scalar/vector constants.

Breeding expressions are strictly PER-GENE: reductions (``sum``,
``mean``, one-argument ``min``/``max``, ``dot``) and the indexed
primitives (``roll``, ``gather``) are rejected at compile time — inside
the kernel the gene axis is lane-padded, so a reduction would silently
include pad lanes. Elementwise ops, comparisons, ``where``, and
two-argument ``min``/``max`` cover the classic operator families:

    # uniform crossover (the library default)
    crossover_from_expression("where(r < 0.5, p1, p2)")
    # one-point crossover via the per-row cut q
    crossover_from_expression("where(i < floor(q * L), p1, p2)")
    # blend crossover with a per-gene mixing weight
    crossover_from_expression("r * p1 + (1 - r) * p2")
    # per-gene reset mutation at the runtime rate
    mutate_from_expression("where(r < rate, r2, g)")
    # creep mutation: +/- sigma steps
    mutate_from_expression(
        "where(r < rate, g + sigma * (2*r2 - 1), g)")

Results are clipped into the gene domain [0, 1) (exactly like the
builtin gaussian mutation), so a custom operator cannot corrupt the
decode invariants the rest of the library relies on.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.objectives.expr import (
    ExpressionError,
    _Parser,
    _emit,
    validate_const,
    walk_ast,
)

_GENE_MAX = 1.0 - 1e-7  # the library-wide open-interval gene ceiling

_CROSS_VARS = ("p1", "p2", "r", "r2", "q", "q2", "i", "L")
_MUT_VARS = ("g", "r", "r2", "q", "q2", "i", "L", "rate", "sigma")


def _forbid_non_elementwise(node) -> None:
    kind = node[0]
    if kind in ("roll", "gather"):
        raise ExpressionError(
            f"{kind}() is not available in breeding expressions — they "
            f"are strictly per-gene (the kernel block is lane-padded)"
        )
    if kind == "call":
        fname, args = node[1], node[2]
        if fname in ("sum", "mean", "dot") or (
            fname in ("min", "max") and len(args) == 1
        ):
            raise ExpressionError(
                f"{fname}() reductions are not available in breeding "
                f"expressions — they are strictly per-gene (the kernel "
                f"block is lane-padded, so a reduction would include "
                f"pad lanes)"
            )


def _compile_breeding(role: str, expr: str, var_names, consts):
    """Parse + validate a breeding expression; returns
    ``(ast, const_names, defaults, pinned_len, cache_key)``. The cache
    key identifies the COMPILED SEMANTICS — role, source, and constant
    values — so the engine can reuse one kernel compilation across
    operator instances (annealing schedules re-creating the same
    expression with new rate/sigma hit the cache; the parameters are
    runtime kernel inputs)."""
    const_vals: Dict[str, np.ndarray] = {
        name: validate_const(
            name, v, allow_2d=False, extra_reserved=var_names
        )
        for name, v in consts.items()
    }

    ast = _Parser(expr, set(const_vals), var_names=var_names).parse()
    used: set = set()
    used_vars: set = set()

    def visit(node):
        _forbid_non_elementwise(node)
        if node[0] == "const":
            used.add(node[1])
        elif node[0] == "var":
            used_vars.add(node[1])

    walk_ast(ast, visit)
    const_vals = {n: a for n, a in const_vals.items() if n in used}
    const_names = sorted(const_vals)
    defaults = tuple(
        jnp.atleast_2d(jnp.asarray(const_vals[n])) for n in const_names
    )
    vec_lens = {a.shape[0] for a in const_vals.values() if a.ndim == 1}
    if len(vec_lens) > 1:
        raise ExpressionError(
            f"vector constants disagree on genome length: {sorted(vec_lens)}"
        )
    pinned = vec_lens.pop() if vec_lens else None
    cache_key = (
        role, expr,
        tuple(
            (n, const_vals[n].shape, const_vals[n].tobytes())
            for n in const_names
        ),
    )
    return ast, const_names, defaults, pinned, cache_key, used_vars


def _derived_streams(r: jax.Array):
    """Three extra uniform streams bit-mixed from the engine's one
    ``(P, L)`` rand block (the ``gaussian_mutate`` trick — cheap,
    stateless, in-register): a second per-gene stream and two per-row
    scalars taken from gene 0's lineage. The fused kernel draws all
    four independently from its own PRNG instead."""
    bits = (r * jnp.float32(2**24)).astype(jnp.uint32)
    m1 = bits * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9)
    r2 = (m1 & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    row = bits[:, 0:1]
    mq = row * jnp.uint32(2246822519) + jnp.uint32(0x85EBCA6B)
    q = (mq & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    mq2 = mq * jnp.uint32(2654435761) + jnp.uint32(0x27220A95)
    q2 = (mq2 & jnp.uint32(0xFFFFFF)).astype(jnp.float32) / jnp.float32(2**24)
    return r2, q, q2


def _probe(rows, n_gene_args: int, n_row_args: int, probe_len: int):
    """Eager shape validation — registration errors surface at the
    factory call (→ -1 through the C ABI), not at first run."""
    gene = jax.ShapeDtypeStruct((2, probe_len), jnp.float32)
    row = jax.ShapeDtypeStruct((2, 1), jnp.float32)
    try:
        jax.eval_shape(
            rows, *([gene] * n_gene_args), *([row] * n_row_args)
        )
    except ExpressionError:
        raise
    except Exception as exc:  # noqa: BLE001 — rewrap with the source
        raise ExpressionError(f"invalid expression: {exc}") from exc


def crossover_from_expression(expr: str, **consts) -> Callable:
    """Compile a crossover expression to the library's operator protocol
    (``(p1, p2, rand) -> child`` with ``.batched``) PLUS the kernel hook
    the fused Pallas path evaluates in VMEM (``.kernel_rows``): the
    TPU-native answer to the reference's ``__device__`` crossover
    pointers (``pga.h:48``; its TSP driver's operator,
    ``test3/test.cu:48-64``, is the motivating workload). See the module
    docstring for the variable set and examples."""
    ast, const_names, defaults, pinned, cache_key, used_vars = (
        _compile_breeding("crossover-expr", expr, _CROSS_VARS, consts)
    )

    def rows(p1, p2, r, r2, q, q2, *cargs, true_len=None):
        env = {
            "p1": p1, "p2": p2, "r": r, "r2": r2, "q": q, "q2": q2,
            "i": jax.lax.broadcasted_iota(jnp.int32, p1.shape, 1).astype(
                jnp.float32
            ),
            "L": jnp.float32(true_len or p1.shape[1]),
            "shape": p1.shape,
            "table_kinds": {},
            "consts": dict(zip(const_names, cargs or defaults)),
        }
        out = jnp.broadcast_to(_emit(ast, env), p1.shape)
        return jnp.clip(out, 0.0, _GENE_MAX)

    _probe(rows, 4, 2, pinned or 8)

    def batched(p1, p2, rand):
        r = rand.astype(jnp.float32)
        r2, q, q2 = _derived_streams(r)
        return rows(
            p1.astype(jnp.float32), p2.astype(jnp.float32), r, r2, q, q2
        ).astype(p1.dtype)

    def op(p1, p2, rand):
        return batched(p1[None, :], p2[None, :], rand[None, :])[0]

    # Which random streams the expression actually references — the
    # kernel draws only those (a full (K, Lp) PRNG tile per unused
    # stream is real per-generation cost at 1M-population scale).
    rows.uses = frozenset(used_vars & {"r", "r2", "q", "q2"})
    op.batched = batched
    op.kernel_rows = rows
    op.kernel_consts = defaults
    op.kernel_cache_key = cache_key
    op.expression = expr
    op.pinned_genome_len = pinned
    op.__doc__ = f"Expression crossover: {expr}"
    return op


def mutate_from_expression(
    expr: str, rate: float = 0.01, sigma: float = 0.0, **consts
) -> Callable:
    """Compile a mutation expression to the operator protocol
    (``(genome, rand) -> genome`` with ``.batched``) plus the
    ``.kernel_rows`` hook — the custom-``__device__``-mutation analog
    (``pga.h:47``). ``rate``/``sigma`` are the values the expression's
    ``rate``/``sigma`` variables take (runtime kernel inputs, so an
    annealing schedule swapping operators reuses one compilation, like
    the builtin kinds)."""
    ast, const_names, defaults, pinned, cache_key, used_vars = (
        _compile_breeding("mutate-expr", expr, _MUT_VARS, consts)
    )

    def rows(g, r, r2, q, q2, rate_v, sigma_v, *cargs, true_len=None):
        env = {
            "g": g, "r": r, "r2": r2, "q": q, "q2": q2,
            "rate": jnp.float32(rate_v), "sigma": jnp.float32(sigma_v),
            "i": jax.lax.broadcasted_iota(jnp.int32, g.shape, 1).astype(
                jnp.float32
            ),
            "L": jnp.float32(true_len or g.shape[1]),
            "shape": g.shape,
            "table_kinds": {},
            "consts": dict(zip(const_names, cargs or defaults)),
        }
        out = jnp.broadcast_to(_emit(ast, env), g.shape)
        return jnp.clip(out, 0.0, _GENE_MAX)

    _probe(
        lambda g, r, r2, q, q2: rows(g, r, r2, q, q2, 0.5, 0.1), 3, 2,
        pinned or 8,
    )

    def batched(g, rand):
        r = rand.astype(jnp.float32)
        r2, q, q2 = _derived_streams(r)
        return rows(
            g.astype(jnp.float32), r, r2, q, q2,
            jnp.float32(rate), jnp.float32(sigma),
        ).astype(g.dtype)

    def op(genome, rand):
        return batched(genome[None, :], rand[None, :])[0]

    rows.uses = frozenset(used_vars & {"r", "r2", "q", "q2"})
    op.batched = batched
    op.kernel_rows = rows
    op.kernel_consts = defaults
    op.kernel_cache_key = cache_key
    op.expression = expr
    op.pinned_genome_len = pinned
    # Inspected by the engine (``_operator_param``): these feed the
    # kernel's runtime mparams, so kernel and XLA paths agree — and
    # they are deliberately NOT part of kernel_cache_key, which is what
    # lets an annealing schedule's re-created operators share one
    # compilation.
    op.rate = rate
    op.sigma = sigma
    op.__doc__ = f"Expression mutation: {expr}"
    return op
