"""Fitness evaluation.

Reference: ``__g_evaluate`` kernel, one thread per individual, optional
shared-memory staging of the genome (``src/pga.cu:250-262``). TPU-natively
this is a ``vmap`` of the user's per-genome objective over the population
axis; XLA tiles it onto the VPU/MXU and fuses it with neighboring ops, so
there is no separate "staging" step to write.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def evaluate(obj: Callable[[jax.Array], jax.Array], genomes: jax.Array) -> jax.Array:
    """Score every individual. Higher is better.

    Args:
      obj: per-individual objective, ``(genome_len,) -> scalar``.
      genomes: ``(pop, genome_len)``.

    Returns:
      ``(pop,)`` float32 scores.
    """
    if genomes.dtype in (jnp.bfloat16, jnp.float16):
        # Score low-precision genes in f32 arithmetic: a bf16 reduction
        # loses ~0.25 absolute resolution at sums near 100, collapsing
        # late-run selection pressure. This matches the fused kernel
        # path, which upcasts the stored bf16 child before scoring.
        genomes = genomes.astype(jnp.float32)
    # An objective carrying a whole-population form evaluates through it
    # directly — e.g. make_tsp's gather-free one-hot matmul (``.rows``)
    # or the Mosaic-safe rowwise form of the fusable builtins (whose
    # const parameters all carry closure defaults, so the bare call is
    # valid outside a kernel).
    rows = getattr(obj, "rows", None) or getattr(obj, "kernel_rowwise", None)
    if rows is not None:
        # Eval-prep hook: an objective may transform the population
        # into a transient eval-only representation first (the GP
        # optimizer's compacted EvalProgram, ``gp/sr.py``). The stored
        # genomes the engine breeds/checkpoints are untouched.
        prep = getattr(obj, "prepare_eval", None)
        scores = rows(genomes if prep is None else prep(genomes))
    else:
        scores = jax.vmap(obj)(genomes)
    return scores.astype(jnp.float32)
