"""Persistent tuning database: best-known kernel configs per signature.

The artifact a chip round produces (``tools/autotune.py``) and every
subsequent run consults: a JSON file mapping **tuning keys** — the
measurement context a result is only valid in — to the **knobs** that
measured fastest there. File conventions match the repo's other durable
state (``utils/checkpoint``, the fleet spool): schema-versioned,
written atomically (temp file + ``os.replace``, so a concurrent reader
or a SIGKILL mid-write can never observe a torn database), and merges
of multiple DB files are ASSOCIATIVE (entry conflicts resolve by a
total order, so merging per-host databases in any grouping yields the
same fleet database).

Key fields (all part of the context the measurement happened in):
``(pop, genome_len, dtype, backend, device_kind, objective class,
operator kinds)``. A DB produced on one device kind never silently
applies to another — lookups from a different backend simply miss.

Failure stances, mirroring ``utils/metrics.merge_snapshots``:

- **torn / partial file** (unparseable JSON, truncated write from a
  non-atomic producer): :func:`merge_files` SKIPS it and reports
  (warning + the returned ``skipped`` list); :func:`TuningDB.load`
  raises :class:`TuningDBError` naming the path.
- **parseable but schema-mismatched**: always a LOUD
  :class:`TuningDBError` — a future schema is not guessed at.

Resolution precedence (:func:`resolve_config_knobs`): an EXPLICIT user
knob on ``PGAConfig`` always beats the DB entry, which beats the
built-in auto default — so a user pinning ``pallas_deme_size=256`` can
never be silently overridden by a stale database.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

#: Environment hook: fleet workers (and any subprocess) inherit the
#: coordinator's tuning database through this variable — the same
#: transport pattern as PGA_FAULT_SPEC (serving/worker.py).
ENV_VAR = "PGA_TUNING_DB"

#: Fields a DB entry may resolve: the engine-appliable PGAConfig knobs
#: (tuning/space.KNOB_TO_CONFIG_FIELD maps space knobs onto these) and
#: the GP evaluator knobs (ISSUE 11 — applied at OBJECTIVE build by
#: ``gp/sr.symbolic_regression``, which consults the active DB itself;
#: ``resolve_config_knobs`` reads them as None off a plain PGAConfig,
#: so vector-genome resolution is untouched).
TUNABLE_FIELDS = (
    "pallas_deme_size", "pallas_layout", "pallas_subblock",
    "gp_stack_depth", "gp_opcode_block", "gp_dispatch",
)


class TuningDBError(RuntimeError):
    """Torn/partial or otherwise unusable tuning-database file."""


class TuningSchemaError(TuningDBError):
    """Parseable database whose schema_version this code does not
    speak — always refused loudly, never skipped."""


def objective_class(obj) -> str:
    """Stable string identity of an objective for the tuning key: its
    builtin-registry name when it has one (so the engine — which holds
    the resolved callable — and the tuner — which may have been handed
    the name — derive the SAME key), else the module-qualified callable
    name. Exotic objectives get a usable — if verbose — class; lookups
    for them just miss until tuned."""
    if isinstance(obj, str):
        return obj
    try:
        from libpga_tpu import objectives as _objectives

        for name in _objectives.names():
            if _objectives.get(name) is obj:
                return name
    except Exception:
        pass
    for attr in ("registry_name", "name", "__name__"):
        v = getattr(obj, attr, None)
        if isinstance(v, str) and v:
            mod = getattr(obj, "__module__", "") or ""
            if attr == "__name__" and mod and not mod.startswith(
                "libpga_tpu.objectives"
            ):
                return f"{mod}.{v}"
            return v
    return type(obj).__name__


def operator_kinds(crossover_kind, mutate_kind) -> str:
    """Stable operator-kind pair string (e.g. ``"uniform+point"``).
    Expression operators key by their compiled cache identity when it
    is a string, else by a generic marker — again, exotic operators
    miss rather than mis-match."""
    def one(kind):
        if isinstance(kind, str):
            return kind
        key = getattr(kind, "kernel_cache_key", None)
        if isinstance(key, str):
            return key
        return f"expr:{getattr(kind, 'role', type(kind).__name__)}"

    return f"{one(crossover_kind)}+{one(mutate_kind)}"


@dataclasses.dataclass(frozen=True)
class TuningKey:
    """The context a tuned config is valid in — every field is part of
    the measurement's identity."""

    pop: int
    genome_len: int
    dtype: str
    backend: str
    device_kind: str
    objective: str
    operators: str

    def as_string(self) -> str:
        return (
            f"pop={self.pop}|len={self.genome_len}|dtype={self.dtype}"
            f"|backend={self.backend}|device={self.device_kind}"
            f"|obj={self.objective}|ops={self.operators}"
        )

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "TuningKey":
        return TuningKey(
            pop=int(d["pop"]), genome_len=int(d["genome_len"]),
            dtype=str(d["dtype"]), backend=str(d["backend"]),
            device_kind=str(d["device_kind"]),
            objective=str(d["objective"]), operators=str(d["operators"]),
        )


def current_key(
    pop: int,
    genome_len: int,
    gene_dtype,
    objective,
    crossover_kind="uniform",
    mutate_kind="point",
) -> TuningKey:
    """The tuning key for a shape on the LIVE backend/device."""
    import jax
    import numpy as np

    try:
        backend = jax.default_backend()
        device_kind = jax.devices()[0].device_kind
    except RuntimeError:
        backend, device_kind = "unknown", "unknown"
    return TuningKey(
        pop=int(pop), genome_len=int(genome_len),
        dtype=np.dtype(gene_dtype).name, backend=str(backend),
        device_kind=str(device_kind),
        objective=objective_class(objective),
        operators=operator_kinds(crossover_kind, mutate_kind),
    )


@dataclasses.dataclass(frozen=True)
class TuningEntry:
    """One tuned result: the knobs that measured best for ``key``, with
    enough provenance to audit the claim (how fast, against what
    default, over how many samples, at what confidence)."""

    key: TuningKey
    knobs: dict                  # PGAConfig field -> value (None = auto)
    plan: dict = dataclasses.field(default_factory=dict)
    gens_per_sec: float = 0.0
    default_gens_per_sec: float = 0.0
    rel_ci: Optional[float] = None
    samples: int = 0
    evaluated: int = 0
    space_size: int = 0
    budget: int = 0
    seed: int = 0
    created: float = 0.0
    note: str = ""

    def __post_init__(self):
        unknown = sorted(set(self.knobs) - set(TUNABLE_FIELDS))
        if unknown:
            raise TuningDBError(
                f"entry knobs {unknown} are not tunable fields "
                f"{list(TUNABLE_FIELDS)}"
            )

    def knobs_tuple(self) -> tuple:
        """Canonical hashable knob form (cache-key ingredient)."""
        return tuple(
            (f, self.knobs.get(f)) for f in TUNABLE_FIELDS
        )

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key.as_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "TuningEntry":
        d = dict(d)
        d["key"] = TuningKey.from_dict(d["key"])
        return TuningEntry(**d)

    def _order(self) -> tuple:
        """Total order for associative merge: faster wins; ties break
        on creation time then the canonical knob string, so ANY merge
        grouping of the same entry set picks the same winner."""
        return (
            self.gens_per_sec, self.created, json.dumps(
                self.knobs, sort_keys=True, default=str
            ),
        )


class TuningDB:
    """In-memory tuning database; thread-safe for the engine/serving
    lookup path (lookups race with a concurrent ``set_tuning_db``)."""

    def __init__(self, entries: Optional[Dict[str, TuningEntry]] = None):
        self.entries: Dict[str, TuningEntry] = dict(entries or {})

    def __len__(self) -> int:
        return len(self.entries)

    def lookup(self, key: TuningKey) -> Optional[TuningEntry]:
        return self.entries.get(key.as_string())

    def add(self, entry: TuningEntry) -> None:
        """Insert, keeping the better entry on conflict (the merge
        order, so add() and merge() agree)."""
        ks = entry.key.as_string()
        cur = self.entries.get(ks)
        if cur is None or entry._order() > cur._order():
            self.entries[ks] = entry

    def merge(self, other: "TuningDB") -> "TuningDB":
        """Associative, commutative merge: the union of entries with
        per-key conflicts resolved by the total order."""
        out = TuningDB(dict(self.entries))
        for e in other.entries.values():
            out.add(e)
        return out

    # ------------------------------------------------------------- file IO

    def to_json(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "entries": {
                k: e.as_dict() for k, e in sorted(self.entries.items())
            },
        }

    @staticmethod
    def from_json(data: dict, path: str = "<memory>") -> "TuningDB":
        if not isinstance(data, dict) or "schema_version" not in data:
            raise TuningDBError(
                f"{path}: not a tuning database (no schema_version)"
            )
        if data["schema_version"] != SCHEMA_VERSION:
            raise TuningSchemaError(
                f"{path}: tuning-db schema_version "
                f"{data['schema_version']!r} != supported "
                f"{SCHEMA_VERSION} — refusing to guess at a different "
                "schema (re-run tools/autotune.py to regenerate)"
            )
        entries = {}
        for k, d in data.get("entries", {}).items():
            try:
                entries[k] = TuningEntry.from_dict(d)
            except (KeyError, TypeError, ValueError) as exc:
                raise TuningDBError(
                    f"{path}: malformed entry {k!r}: {exc}"
                ) from exc
        return TuningDB(entries)

    def save(self, path: str) -> str:
        """Atomic write: temp file in the same directory +
        ``os.replace`` — the checkpoint/spool durability convention. A
        reader concurrent with save() sees either the old complete file
        or the new complete file, never a prefix."""
        final = os.path.abspath(path)
        os.makedirs(os.path.dirname(final) or ".", exist_ok=True)
        tmp = f"{final}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.to_json(), fh, indent=1, default=str)
                fh.write("\n")
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return final

    @staticmethod
    def load(path: str) -> "TuningDB":
        """Load one DB file. Torn/unparseable → :class:`TuningDBError`
        naming the path; schema mismatch → :class:`TuningDBError`
        (loud refusal, see module docstring)."""
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except json.JSONDecodeError as exc:
            raise TuningDBError(
                f"{path}: torn or partial tuning database ({exc})"
            ) from exc
        return TuningDB.from_json(data, path=path)


def merge_files(paths: Sequence[str]) -> Tuple[TuningDB, List[str]]:
    """Merge several DB files into one (associative — any grouping of
    the same files produces the same database). TORN/partial files are
    SKIPPED and reported (warning + returned list); a parseable file
    with a mismatched schema REFUSES loudly; a merely MISSING file is
    silently fine (merging "whatever the hosts have written so far" is
    the normal fleet case, and autotune's first write merges into a
    not-yet-existing path)."""
    out = TuningDB()
    skipped: List[str] = []
    for p in paths:
        try:
            out = out.merge(TuningDB.load(p))
        except TuningSchemaError:
            raise  # loud refusal: a future schema is not guessed at
        except FileNotFoundError:
            continue
        except TuningDBError:
            skipped.append(p)
    if skipped:
        warnings.warn(
            f"tuning merge skipped {len(skipped)} torn/partial file(s): "
            f"{skipped}",
            stacklevel=2,
        )
    return out, skipped


# ------------------------------------------------------- process-global DB

_LOCK = threading.Lock()
_ACTIVE: dict = {"path": None, "db": None, "env_checked": False}


def set_tuning_db(path: Optional[str]) -> Optional["TuningDB"]:
    """Install (or with None/"" clear) the process-global tuning
    database every engine and serving executor consults at kernel
    selection. Loads EAGERLY so a bad path/schema fails here, at the
    operator's hand, not inside a serving warm-up."""
    with _LOCK:
        if not path:
            _ACTIVE.update(path=None, db=None, env_checked=True)
            return None
        db = TuningDB.load(path)
        _ACTIVE.update(path=os.path.abspath(path), db=db,
                       env_checked=True)
        return db


def active_db() -> Optional["TuningDB"]:
    """The installed tuning database, or None. First call falls back to
    the :data:`ENV_VAR` environment hook (how fleet workers inherit the
    coordinator's DB); an unreadable env-provided DB warns once and
    stays off rather than killing a worker at import time."""
    with _LOCK:
        if _ACTIVE["db"] is None and not _ACTIVE["env_checked"]:
            _ACTIVE["env_checked"] = True
            env_path = os.environ.get(ENV_VAR)
            if env_path:
                try:
                    _ACTIVE.update(
                        path=os.path.abspath(env_path),
                        db=TuningDB.load(env_path),
                    )
                except (FileNotFoundError, TuningDBError) as exc:
                    warnings.warn(
                        f"{ENV_VAR}={env_path!r} is unusable "
                        f"({exc}) — running untuned",
                        stacklevel=2,
                    )
        return _ACTIVE["db"]


def active_path() -> Optional[str]:
    with _LOCK:
        return _ACTIVE["path"]


def resolve_config_knobs(
    config, entry: Optional[TuningEntry]
) -> Tuple[dict, Optional[dict]]:
    """Apply the resolution precedence — explicit user knob > DB entry
    > built-in default — to the tunable ``PGAConfig`` fields.

    Returns ``(knobs, provenance)``: ``knobs`` maps every tunable field
    to its EFFECTIVE value (what kernel selection must use), and
    ``provenance`` maps each field to ``"user"``/``"db"``/``"default"``.
    ``provenance`` is None exactly when ``entry`` is None (no DB
    installed, or no entry for this signature) — the untuned path then
    carries literally the config's own values and nothing else, the
    byte-identity guarantee of ``db=None``. A MATCHED entry always
    yields provenance, even when every knob stays at its default (the
    CPU case, where the tuner's never-regress rule records the default
    config): that a database ruled is itself part of a served bucket's
    identity (``serving/cache`` stats, the ``tuned_config`` event).
    """
    knobs, prov = {}, {}
    for field in TUNABLE_FIELDS:
        # GP evaluator fields have no PGAConfig attribute — user
        # precedence for them lives at objective build (gp/sr.py).
        user = getattr(config, field, None)
        if user is not None:
            knobs[field], prov[field] = user, "user"
        elif entry is not None and entry.knobs.get(field) is not None:
            knobs[field], prov[field] = entry.knobs[field], "db"
        else:
            knobs[field], prov[field] = None, "default"
    return knobs, (prov if entry is not None else None)


def entry_created_now() -> float:
    return time.time()


__all__ = [
    "SCHEMA_VERSION",
    "ENV_VAR",
    "TUNABLE_FIELDS",
    "TuningDBError",
    "TuningSchemaError",
    "TuningKey",
    "TuningEntry",
    "TuningDB",
    "current_key",
    "objective_class",
    "operator_kinds",
    "merge_files",
    "set_tuning_db",
    "active_db",
    "active_path",
    "resolve_config_knobs",
]
