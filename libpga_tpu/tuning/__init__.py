"""Self-tuning kernels (ROADMAP item 4, ISSUE 10).

Three pieces:

- :mod:`libpga_tpu.tuning.space` — the single-source kernel config
  space (knob domains + admissibility gates) both sweep tools and the
  autotuner consume;
- :mod:`libpga_tpu.tuning.tuner` — the evolutionary autotuner: the
  library's own PGA over integer-encoded configs with an
  interleaved-medians measurement oracle;
- :mod:`libpga_tpu.tuning.db` — the persistent, schema-versioned,
  atomically-written tuning database the engine and the serving
  warm-up consult at kernel selection (resolution precedence: explicit
  user knob > DB entry > built-in default).

Heavy imports stay lazy (PEP 562): ``import libpga_tpu`` must not pay
for the tuner.
"""

from __future__ import annotations

from libpga_tpu.tuning.db import (  # light, no jax at import
    TuningDB,
    TuningDBError,
    TuningEntry,
    TuningKey,
    TuningSchemaError,
    active_db,
    active_path,
    current_key,
    merge_files,
    resolve_config_knobs,
    set_tuning_db,
)

__all__ = [
    "TuningDB",
    "TuningDBError",
    "TuningSchemaError",
    "TuningEntry",
    "TuningKey",
    "active_db",
    "active_path",
    "current_key",
    "merge_files",
    "resolve_config_knobs",
    "set_tuning_db",
    "autotune",
    "TunerSettings",
    "space",
    "db",
    "tuner",
]


def __getattr__(name):
    if name in ("autotune", "TunerSettings"):
        from libpga_tpu.tuning import tuner as _tuner

        return getattr(_tuner, name)
    if name in ("space", "db", "tuner"):
        import importlib

        return importlib.import_module(f"libpga_tpu.tuning.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
