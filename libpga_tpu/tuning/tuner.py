"""Evolutionary kernel autotuner: the GA tuning the GA.

The cuPilot direction (PAPERS.md, arxiv 2512.16465) pointed at our own
hot path: kernel configurations (``tuning/space.py``) encode as
fixed-width integer genomes — one gene per knob, the gene an index into
that knob's domain — and the library's OWN :class:`~libpga_tpu.engine.PGA`
evolves them, with fitness supplied by a measurement oracle through a
``pure_callback`` whole-population objective (the meta-GA's device
program calls back into the host to time real kernels).

Oracle design, in the order the guarantees matter:

- **measures the real hot path** — each distinct configuration is
  measured by running an actual engine (``PGA.run``) with the knobs
  applied, sampled with the two-length-subtraction estimator inside
  :func:`~libpga_tpu.utils.profiling.interleaved_medians` in its
  repeat-until-confidence mode (``min_rel_ci`` bounded by
  ``max_rounds``), interleaved against the DEFAULT configuration in the
  same wave — so every candidate-vs-default comparison is adjacent and
  decision-grade (this box's ~4% drift floor cannot promote noise);
- **memoized by RESOLVED PLAN, not by genome** — two configurations
  that resolve to the same compiled kernel (``space.resolve``; on a
  CPU backend, where the fused kernel never runs, EVERY configuration
  resolves to the one XLA plan) share one measurement. This is also
  what makes the CPU smoke deterministic: constant fitness → a
  seed-deterministic meta-GA trajectory → a deterministic database;
- **compile-failure → worst fitness, never a crash** — a config whose
  kernel fails to build or dispatch (``fallback="raise"`` inside the
  oracle) records 0.0 gens/sec and the error string; inadmissible
  configurations score below that without ever compiling;
- **never regresses** — the recorded entry is the measured winner only
  if it beats the default's same-wave measurement minus the drift
  floor; otherwise the DEFAULT configuration is recorded (knobs all
  auto), so applying the database can never make a signature slower
  than stock.

Deterministic given a seed: the meta-GA's PRNG chain is the engine's
own seeded chain, waves are ordered, and ties break on a total order.
(The measured NUMBERS still carry timing noise — determinism claims
cover the search trajectory and, through plan memoization, the
recorded knobs wherever plans are discrete, which is what the CI smoke
pins on CPU.)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from libpga_tpu.tuning import db as _db
from libpga_tpu.tuning import space as _space

#: This box's measured cross-round drift (BASELINE.md round 4/5): a
#: candidate must beat the default by more than this to be recorded.
DEFAULT_DRIFT_FLOOR = 0.04


@dataclasses.dataclass(frozen=True)
class TunerSettings:
    """Autotune run parameters (CLI flags of ``tools/autotune.py``).

    ``budget`` counts DISTINCT measured plans (the default config's
    plan included); the meta-GA stops once the budget — or the whole
    admissible plan set, whichever is smaller — is measured, or after
    ``max_generations``. ``wave`` bounds candidate runners alive per
    measurement wave (each runner holds a live population buffer —
    on-device memory, not time, is the binding constraint at 1M-row
    shapes)."""

    budget: int = 16
    seed: int = 0
    ga_population: int = 16
    max_generations: int = 32
    rounds: int = 3
    min_rel_ci: float = 0.05
    max_rounds: int = 9
    measure_lo: int = 3
    measure_hi: int = 9
    measure_tries: int = 2
    drift_floor: float = DEFAULT_DRIFT_FLOOR
    wave: int = 4

    def __post_init__(self):
        if self.budget < 1:
            raise ValueError("budget must be >= 1")
        if self.ga_population < 2:
            raise ValueError("ga_population must be >= 2")
        if self.max_generations < 1:
            raise ValueError("max_generations must be >= 1")
        if not (0 <= self.drift_floor < 1):
            raise ValueError("drift_floor must be in [0, 1)")
        if self.measure_hi <= self.measure_lo:
            raise ValueError("measure_hi must be > measure_lo")
        if self.wave < 1:
            raise ValueError("wave must be >= 1")


_DEFAULT_CONFIG = _space.KernelConfig()  # all knobs auto


def _plan_key(ctx, cfg, pallas_live: bool) -> tuple:
    """Measurement identity of a configuration: the compiled kernel it
    resolves to. Off-TPU every VECTOR-GENOME config resolves to the XLA
    step path — ONE plan — which is both honest (the knobs are no-ops
    there) and what makes the CPU smoke deterministic. GP contexts key
    on the resolved stack-machine geometry instead: the evaluator knobs
    shape the TRACED program on every backend, so the space carries
    real >1-plan structure even on CPU (ISSUE 11)."""
    if ctx.gp_nodes is not None:
        plan = _space.resolve(ctx, cfg)
        if plan is None:
            return ("xla",)
        return (
            "gp", plan["stack_depth"], plan["opcode_block"],
            plan["dispatch"],
        )
    if not pallas_live:
        return ("xla",)
    plan = _space.resolve(ctx, cfg)
    if plan is None:
        return ("xla",)
    return (
        "pallas", plan["deme_size"], plan["demes_per_step"],
        plan["layout"], plan["subblock"],
    )


def _canonical_knobs(plan_key: tuple) -> dict:
    """The knob dict a winning plan records in the database. The XLA
    plan (and the default plan on ties / never-regress) records
    all-auto knobs — applying the entry reproduces the stock config.
    GP plans record the RESOLVED evaluator geometry (applying explicit
    resolved values is the identical traced program)."""
    knobs = {f: None for f in _db.TUNABLE_FIELDS}
    if plan_key[0] == "gp":
        knobs["gp_stack_depth"] = int(plan_key[1])
        knobs["gp_opcode_block"] = int(plan_key[2])
        knobs["gp_dispatch"] = str(plan_key[3])
        return knobs
    if plan_key[0] != "pallas":
        return knobs
    _, K, _D, layout, B = plan_key
    knobs.update(
        pallas_deme_size=int(K),
        pallas_layout=str(layout),
        pallas_subblock=int(B) if B and B > 1 else None,
    )
    return knobs


class MeasurementOracle:
    """Plan-memoized gens/sec oracle over real engine runs."""

    def __init__(
        self,
        ctx: _space.SpaceContext,
        objective,
        settings: TunerSettings,
        use_pallas: Optional[bool] = None,
        crossover_op=None,
        mutate_op=None,
    ):
        self.ctx = ctx
        self.objective = objective
        self.settings = settings
        self.use_pallas = use_pallas
        # The knob set this context searches (GP contexts evolve the
        # evaluator axes, vector contexts the fused-breed axes) and the
        # operators the measurement engines breed with (GP runs must
        # time REAL structural breeding, not uniform crossover over
        # token genes).
        self.knob_names = _space.tuner_knobs_for(ctx)
        self.crossover_op = crossover_op
        self.mutate_op = mutate_op
        from libpga_tpu.config import PGAConfig

        probe = PGAConfig(use_pallas=use_pallas,
                          gene_dtype=ctx.gene_dtype)
        import jax

        self.pallas_live = (
            probe.pallas_enabled() and jax.default_backend() == "tpu"
        )
        #: plan key -> record dict (gens_per_sec, default_gens_per_sec
        #: from the same wave, rel_ci, n, error)
        self.measured: Dict[tuple, dict] = {}
        self.default_key = _plan_key(ctx, _DEFAULT_CONFIG,
                                     self.pallas_live)
        self._inadmissible: Dict[_space.KernelConfig, str] = {}

    # ------------------------------------------------------------ runners

    def _make_runner(self, knobs: dict) -> Callable[[int], None]:
        """A fresh engine with the knobs applied; ``run(n)`` executes n
        generations synchronously. ``fallback="raise"`` so a broken
        lowering surfaces HERE (worst fitness) instead of silently
        measuring the XLA path as if it were the candidate."""
        import jax

        from libpga_tpu.config import PGAConfig
        from libpga_tpu.engine import PGA

        cfg_knobs = {
            k: v for k, v in knobs.items() if k.startswith("pallas_")
        }
        cfg = PGAConfig(
            gene_dtype=self.ctx.gene_dtype,
            use_pallas=self.use_pallas,
            fallback="raise",
            tournament_size=self.ctx.tournament_size,
            selection=self.ctx.selection_kind,
            selection_param=self.ctx.selection_param,
            **cfg_knobs,
        )
        pga = PGA(seed=0, config=cfg)
        obj = self.objective
        if self.ctx.gp_nodes is not None:
            # GP evaluator knobs apply at objective build (user
            # precedence semantics — gp/sr.with_knobs).
            obj = obj.with_knobs(
                stack_depth=knobs.get("gp_stack_depth"),
                opcode_block=knobs.get("gp_opcode_block"),
                dispatch=knobs.get("gp_dispatch"),
            )
        pga.set_objective(obj)
        if self.crossover_op is not None:
            pga.set_crossover(self.crossover_op)
        if self.mutate_op is not None:
            pga.set_mutate(self.mutate_op)
        if self.ctx.gp_nodes is not None:
            from libpga_tpu.gp.encoding import random_population

            pga.install_population(random_population(
                pga.next_key(), self.ctx.pop, self.objective.gp_config
            ))
        else:
            pga.create_population(self.ctx.pop, self.ctx.genome_len)

        def run(n: int) -> None:
            pga.run(int(n))

        run.pga = pga  # keep the engine (and its buffers) alive
        return run

    def _measure_wave(self, new_keys: List[tuple]) -> None:
        """Measure the new plans interleaved WITH the default plan in
        one wave (adjacent samples — the only decision-grade
        comparison on a drifting host), honoring the wave width."""
        from libpga_tpu.utils.profiling import (
            best_ms_per_unit,
            interleaved_medians,
        )

        s = self.settings
        waves = [
            new_keys[i:i + s.wave]
            for i in range(0, len(new_keys), s.wave)
        ] or [[]]  # an empty request still measures the default plan
        for wave_keys in waves:
            chunk = [
                k for k in wave_keys
                if k not in self.measured and k != self.default_key
            ]
            runners, errors = {}, {}
            for key in [self.default_key] + chunk:
                if key in self.measured and key != self.default_key:
                    continue
                try:
                    r = self._make_runner(_canonical_knobs(key))
                    r(2)  # compile + warm outside the timed samples
                    runners[key] = r
                except Exception as exc:  # compile/dispatch failure
                    errors[key] = f"{type(exc).__name__}: {exc}"
            med = interleaved_medians(
                {str(k): r for k, r in runners.items()},
                rounds=s.rounds,
                min_rel_ci=s.min_rel_ci,
                max_rounds=s.max_rounds,
                sample=lambda run: best_ms_per_unit(
                    run, s.measure_lo, s.measure_hi,
                    tries=s.measure_tries,
                ),
            ) if runners else {}
            default_gps = None
            if str(self.default_key) in (med or {}):
                ms = med[str(self.default_key)]
                default_gps = 1000.0 / ms if ms and ms == ms else 0.0
            elif self.default_key in self.measured:
                default_gps = self.measured[self.default_key][
                    "gens_per_sec"
                ]
            for key in runners:
                ms = med[str(key)]
                gps = 1000.0 / ms if ms and ms == ms else 0.0
                rec = {
                    "gens_per_sec": gps,
                    "default_gens_per_sec": default_gps,
                    "rel_ci": med.rel_ci[str(key)],
                    "samples": med.n[str(key)],
                    "error": None,
                }
                if key == self.default_key:
                    rec["default_gens_per_sec"] = gps
                    if key in self.measured:
                        continue  # keep the first default measurement
                self.measured[key] = rec
            for key, err in errors.items():
                # Compile-failure → worst MEASURED fitness, never a
                # crash: the plan is recorded as dead, not retried.
                self.measured[key] = {
                    "gens_per_sec": 0.0,
                    "default_gens_per_sec": default_gps,
                    "rel_ci": None, "samples": 0, "error": err,
                }

    # ------------------------------------------------------------ fitness

    def _decode_keys(self, genomes: np.ndarray) -> List[Optional[tuple]]:
        """Rows -> plan keys (None = inadmissible, rejected before any
        compile)."""
        keys: List[Optional[tuple]] = []
        for row in genomes:
            cfg = _space.config_from_genes(row, self.knob_names)
            if cfg not in self._inadmissible:
                reason = _space.why_inadmissible(self.ctx, cfg)
                self._inadmissible[cfg] = reason or ""
            if self._inadmissible[cfg]:
                keys.append(None)
            else:
                keys.append(_plan_key(self.ctx, cfg, self.pallas_live))
        return keys

    def prepare(self, genomes) -> None:
        """ASK phase, called on the HOST thread between meta-GA
        generations: decode the current meta population, measure every
        not-yet-measured admissible plan it proposes (budget
        permitting). Measurement runs real jitted programs, which a
        jax host callback must never do — hence the ask/measure/tell
        split: the traced objective (:func:`_meta_objective`) only does
        memo LOOKUPS."""
        keys = self._decode_keys(np.asarray(genomes))
        budget_left = self.settings.budget - len(self.measured)
        new = []
        for k in keys:
            if k is None or k in self.measured or k in new:
                continue
            if len(new) >= max(budget_left, 0):
                continue
            new.append(k)
        if new or self.default_key not in self.measured:
            self._measure_wave(new)

    def lookup_host(self, genomes) -> np.ndarray:
        """TELL phase — the pure-numpy host callback behind the
        meta-GA's objective. Inadmissible rows score -1.0 (below any
        measurement, below failed compiles at 0.0) without ever
        compiling; plans beyond the budget (or children bred after the
        last ``prepare``) read 0.0 until the next ask phase measures
        them. No jax calls happen here (callback deadlock hazard)."""
        out = np.empty(len(genomes), np.float32)
        for i, k in enumerate(self._decode_keys(np.asarray(genomes))):
            if k is None:
                out[i] = -1.0
            elif k in self.measured:
                out[i] = self.measured[k]["gens_per_sec"]
            else:
                out[i] = 0.0
        return out

    # ------------------------------------------------------------- verdict

    def winner(self) -> Tuple[tuple, dict]:
        """The recorded plan under the never-regress rule: the fastest
        measured plan if it beats its same-wave default measurement by
        more than the drift floor, else the default plan. Ties break on
        a total order (prefer default, then the smaller plan string) so
        the verdict is deterministic."""
        if self.default_key not in self.measured:
            self._measure_wave([])
        best = max(
            self.measured.items(),
            key=lambda kv: (
                kv[1]["gens_per_sec"],
                kv[0] == self.default_key,
                str(kv[0]),
            ),
        )
        key, rec = best
        if key != self.default_key:
            baseline = rec.get("default_gens_per_sec") or (
                self.measured[self.default_key]["gens_per_sec"]
            )
            floor = baseline * (1.0 - self.settings.drift_floor)
            if rec["gens_per_sec"] <= floor:
                key, rec = self.default_key, self.measured[
                    self.default_key
                ]
        return key, rec


def _meta_objective(oracle: MeasurementOracle):
    """The meta-GA's objective: a whole-population (``.rows``) form
    calling back into the oracle's MEMO (``lookup_host`` — pure numpy,
    never jax; the measurements themselves happen in the ask phase,
    ``oracle.prepare``, between generations). The engine's evaluate
    path uses ``rows`` directly, so one callback scores the whole
    population."""
    import jax
    import jax.numpy as jnp

    def rows(genomes):
        return jax.pure_callback(
            oracle.lookup_host,
            jax.ShapeDtypeStruct((genomes.shape[0],), jnp.float32),
            genomes,
        )

    def obj(genome):
        return rows(genome[None, :])[0]

    obj.rows = rows
    return obj


def autotune(
    pop: int,
    genome_len: int,
    *,
    objective="onemax",
    gene_dtype=None,
    crossover_kind: str = "uniform",
    mutate_kind: str = "point",
    settings: Optional[TunerSettings] = None,
    use_pallas: Optional[bool] = None,
    db_path: Optional[str] = None,
    events=None,
) -> _db.TuningEntry:
    """Tune the kernel config for one signature and (optionally) persist
    the result.

    Runs the library's own PGA over the engine-appliable knob space
    (``space.TUNER_KNOBS``) with the measurement oracle above, applies
    the never-regress rule, and returns the :class:`~libpga_tpu.tuning.db.TuningEntry`.
    With ``db_path`` the entry is MERGED into the file at that path
    (existing entries for other keys survive; a better existing entry
    for the same key survives too — merge order) and written
    atomically.
    """
    import jax.numpy as jnp

    from libpga_tpu.config import PGAConfig
    from libpga_tpu.engine import PGA

    settings = settings or TunerSettings()
    if gene_dtype is None:
        gene_dtype = jnp.float32
    obj = objective
    if isinstance(obj, str):
        from libpga_tpu import objectives

        obj = objectives.get(obj)
    gpc = getattr(obj, "gp_config", None)
    crossover_op = mutate_op = None
    if gpc is not None:
        # GP engine (ISSUE 11): tune the stack-machine evaluator axes,
        # breeding with the real structural operators; the tuning key's
        # operator field is the fixed "gp+gp" marker — the same key
        # gp/sr's own DB lookup derives, so the entry round-trips.
        if genome_len != gpc.genome_len:
            raise ValueError(
                f"genome_len {genome_len} != GP encoding's "
                f"{gpc.genome_len} (2 * max_nodes)"
            )
        if not hasattr(obj, "with_knobs"):
            raise ValueError(
                "GP objectives must carry .with_knobs "
                "(gp/sr.symbolic_regression provides it)"
            )
        from libpga_tpu.gp.operators import (
            make_gp_mutate,
            make_subtree_crossover,
        )

        crossover_kind = mutate_kind = "gp"
        crossover_op = make_subtree_crossover(gpc)
        mutate_op = make_gp_mutate(gpc)
    ctx = _space.SpaceContext(
        pop=pop, genome_len=genome_len, gene_dtype=gene_dtype,
        crossover_kind=crossover_kind, mutate_kind=mutate_kind,
        gp_nodes=None if gpc is None else gpc.max_nodes,
        gp_samples=getattr(obj, "sr_samples", 64),
    )
    oracle = MeasurementOracle(
        ctx, obj, settings, use_pallas=use_pallas,
        crossover_op=crossover_op, mutate_op=mutate_op,
    )
    admissible = _space.grid(ctx, oracle.knob_names)
    distinct_plans = {
        _plan_key(ctx, cfg, oracle.pallas_live) for cfg in admissible
    }
    distinct_plans.add(oracle.default_key)
    budget_eff = min(settings.budget, len(distinct_plans))

    t0 = time.perf_counter()
    # The meta-GA: the library tuning itself. Small population of
    # genome-width gene vectors in [0,1); XLA path (a 16-row population
    # has no business in the fused kernel); generous mutation so a
    # 3-gene genome keeps exploring.
    meta = PGA(
        seed=settings.seed,
        config=PGAConfig(
            use_pallas=False,
            mutation_rate=0.3,
            seed=settings.seed,
        ),
    )
    meta.set_objective(_meta_objective(oracle))
    # The engine's reference-parity floor is 4 genes per genome; pad
    # the knob genome with inert genes (config_from_genes decodes only
    # the first genome_width positions).
    handle = meta.create_population(
        settings.ga_population,
        max(4, _space.genome_width(oracle.knob_names)),
    )
    gens = 0
    while (
        len(oracle.measured) < budget_eff
        and gens < settings.max_generations
    ):
        # Ask/measure/tell: measure the current population's new plans
        # on the host, THEN step the meta-GA one generation — its
        # traced objective reads the memo (children bred this step are
        # measured at the top of the next iteration, before selection
        # ever uses their scores).
        oracle.prepare(np.asarray(meta.population(handle).genomes))
        meta.run(1)
        gens += 1

    key, rec = oracle.winner()
    knobs = _canonical_knobs(key)
    plan = {"path": key[0]}
    if key[0] == "pallas":
        plan.update(
            deme_size=key[1], demes_per_step=key[2], layout=key[3],
            subblock=key[4],
        )
    elif key[0] == "gp":
        plan.update(
            stack_depth=key[1], opcode_block=key[2], dispatch=key[3],
        )
    entry = _db.TuningEntry(
        key=_db.current_key(
            pop, genome_len, gene_dtype, obj, crossover_kind,
            mutate_kind,
        ),
        knobs=knobs,
        plan=plan,
        gens_per_sec=float(rec["gens_per_sec"]),
        default_gens_per_sec=float(
            rec.get("default_gens_per_sec")
            or oracle.measured[oracle.default_key]["gens_per_sec"]
        ),
        rel_ci=rec.get("rel_ci"),
        samples=int(rec.get("samples") or 0),
        evaluated=len(oracle.measured),
        space_size=len(admissible),
        budget=settings.budget,
        seed=settings.seed,
        created=_db.entry_created_now(),
        note=(
            "never-regress: default kept"
            if key == oracle.default_key else ""
        ),
    )
    if events is not None:
        events.emit(
            "tuned_config", population_size=pop, genome_len=genome_len,
            knobs={k: v for k, v in knobs.items()},
            gens_per_sec=entry.gens_per_sec,
            evaluated=entry.evaluated,
        )
    if db_path:
        merged, _ = _db.merge_files([db_path])
        merged.add(entry)
        merged.save(db_path)
    return entry


__all__ = [
    "DEFAULT_DRIFT_FLOOR",
    "TunerSettings",
    "MeasurementOracle",
    "autotune",
]
