"""Single-source definition of the fused-kernel configuration space.

Every chip round so far swept the kernel knobs by hand:
``tools/sweep_kernel.py`` hand-rolled a (dtype, K, D) grid and skipped
inadmissible points by building the kernel and checking what came back;
``tools/ablate_floor.py`` hand-rolled its own D sweep with the same
build-and-check pattern. This module is the ONE definition both tools
(and the evolutionary autotuner, ``tuning/tuner.py``) consume:

- **knob domains** — every tunable axis with its value set. Index 0 of
  every domain is the AUTO value (``None`` — "let the factory pick"),
  so the all-zeros genome is exactly the shipped default configuration.
- **admissibility gates** — :func:`why_inadmissible` runs the factory's
  own dry-run resolution (``ops/pallas_step.kernel_plan``: the
  ``_kernel_shape`` VMEM budget model + deme divisibility and the
  ``_resolve_layout`` ping-pong mixing gate / sub-block divisibility)
  so an invalid configuration is rejected BEFORE anything compiles,
  and the space can never describe a kernel the factory wouldn't
  build.
- **genome codec** — configurations encode as fixed-width integer
  genomes (one gene per knob, the gene value an index into that knob's
  domain), the representation ``tuning/tuner.py`` evolves with the
  library's own ``PGA``.

The ENGINE-APPLICABLE knobs (``TUNER_KNOBS``) are the ones
``PGAConfig`` exposes — ``deme_size``/``layout``/``subblock`` — which
is what the autotuner searches so a tuning-database entry is directly
appliable at kernel selection. The sweep tools additionally iterate
``demes_per_step`` (a factory-internal axis, ``_demes_per_step``) and
``dimension_semantics`` (parallel vs. serial grid, the
``serial_grid`` ablation) via ``SWEEP_KNOBS``.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from libpga_tpu.ops.pallas_step import (  # the single admission source
    LANE,
    _valid_deme,
    kernel_plan,
    pingpong_admissible,
    pingpong_quantum,
)

#: Knob domains. Index 0 is always the AUTO value (factory default), so
#: the zero genome is the shipped default configuration and a decoded
#: index can never be out of range after clipping.
DOMAINS: Dict[str, tuple] = {
    "deme_size": (None, 128, 256, 512, 1024),
    "layout": (None, "riffle", "pingpong"),
    "subblock": (None, 2, 4),
    "demes_per_step": (None, 1, 2, 4, 8, 16, 32),
    "dimension_semantics": ("parallel", "serial"),
    # GP stack-machine evaluator axes (ISSUE 11, ``ops/gp_eval.py``):
    # value-stack depth and tokens-per-loop-iteration. Both shape the
    # TRACED program of the XLA interpreter too, so distinct
    # admissible values are distinct plans even on CPU — the first
    # >1-plan autotuner space off-chip.
    "gp_stack_depth": (None, 8, 16, 32, 64),
    "gp_opcode_block": (None, 1, 2, 4, 8),
    # Token-step dispatch lattice (ISSUE 19): dense = one candidate
    # plane per registered op, blocked = arity-class composite planes
    # (bit-identical results; the plane count is what changes — speed
    # is the measured axis). AUTO is the dense stock path.
    "gp_dispatch": (None, "dense", "blocked"),
}

#: The engine-appliable knobs (PGAConfig fields exist for exactly
#: these) — the vector-genome autotuner's genome, and what a tuning-DB
#: entry records.
TUNER_KNOBS: Tuple[str, ...] = ("deme_size", "layout", "subblock")

#: The GP evaluator knobs (applied at objective build —
#: ``gp/sr.symbolic_regression`` — not through PGAConfig).
GP_KNOBS: Tuple[str, ...] = (
    "gp_stack_depth", "gp_opcode_block", "gp_dispatch",
)

#: The full sweep space (tools/sweep_kernel.py, tools/ablate_floor.py).
SWEEP_KNOBS: Tuple[str, ...] = TUNER_KNOBS + (
    "demes_per_step", "dimension_semantics",
)

#: KernelConfig knob -> PGAConfig field for the engine-appliable subset.
KNOB_TO_CONFIG_FIELD: Dict[str, str] = {
    "deme_size": "pallas_deme_size",
    "layout": "pallas_layout",
    "subblock": "pallas_subblock",
}


@dataclasses.dataclass(frozen=True)
class KernelConfig:
    """One point of the kernel config space. ``None`` anywhere means
    AUTO — defer to the factory default for that knob (the decoded form
    of domain index 0)."""

    deme_size: Optional[int] = None
    layout: Optional[str] = None
    subblock: Optional[int] = None
    demes_per_step: Optional[int] = None
    dimension_semantics: str = "parallel"
    gp_stack_depth: Optional[int] = None
    gp_opcode_block: Optional[int] = None
    gp_dispatch: Optional[str] = None

    def knobs(self, names: Sequence[str] = TUNER_KNOBS) -> dict:
        return {n: getattr(self, n) for n in names}

    def config_fields(self) -> dict:
        """The engine-appliable knobs as ``PGAConfig`` field values."""
        return {
            field: getattr(self, knob)
            for knob, field in KNOB_TO_CONFIG_FIELD.items()
        }


@dataclasses.dataclass(frozen=True)
class SpaceContext:
    """The shape context a config space is defined against — everything
    admissibility depends on besides the knobs themselves. ``dtype`` is
    part of the context (and of the tuning-DB key), not a knob: a tuned
    entry must never silently change the genome representation."""

    pop: int
    genome_len: int
    gene_dtype: object = jnp.float32
    crossover_kind: str = "uniform"
    mutate_kind: str = "point"
    tournament_size: int = 2
    selection_kind: str = "tournament"
    selection_param: Optional[float] = None
    fused: bool = True
    const_carrying: bool = False
    # GP context (ISSUE 11): non-None max_nodes switches the space to
    # the stack-machine EVALUATOR axes (``GP_KNOBS``) — the fused-breed
    # knobs are inert for GP engines (their operators are XLA-path by
    # design) and strictly inadmissible here, so a genome can never
    # claim credit for a knob that changed nothing.
    gp_nodes: Optional[int] = None
    gp_samples: int = 64

    @property
    def genome_lanes(self) -> int:
        return math.ceil(self.genome_len / LANE) * LANE

    @property
    def gene_bytes(self) -> int:
        return 2 if self.gene_dtype == jnp.bfloat16 else 4

    @property
    def quantum(self) -> int:
        return pingpong_quantum(self.gene_dtype)

    def dtype_name(self) -> str:
        import numpy as np

        return np.dtype(self.gene_dtype).name


def tuner_knobs_for(ctx: SpaceContext) -> Tuple[str, ...]:
    """The knob set an autotuner searches in ``ctx``: the
    engine-appliable fused-breed knobs for vector-genome contexts, the
    stack-machine evaluator knobs for GP contexts."""
    return GP_KNOBS if ctx.gp_nodes is not None else TUNER_KNOBS


def _gp_config(ctx: SpaceContext):
    from libpga_tpu.gp.encoding import GPConfig

    return GPConfig(max_nodes=int(ctx.gp_nodes))


def resolve(ctx: SpaceContext, cfg: KernelConfig) -> Optional[dict]:
    """The factory's dry-run resolution of ``cfg`` in ``ctx`` — the
    plan :func:`~libpga_tpu.ops.pallas_step.make_pallas_breed` (or,
    for GP contexts, :func:`~libpga_tpu.ops.gp_eval.gp_eval_plan`)
    would build, or None where it would decline. Raises where the
    factory would (explicit inadmissible ping-pong / explicit invalid
    GP knob)."""
    if ctx.gp_nodes is not None:
        from libpga_tpu.ops.gp_eval import gp_eval_plan

        return gp_eval_plan(
            ctx.pop, _gp_config(ctx), ctx.gp_samples,
            stack_depth=cfg.gp_stack_depth,
            opcode_block=cfg.gp_opcode_block,
            dispatch=cfg.gp_dispatch,
        )
    return kernel_plan(
        ctx.pop, ctx.genome_len,
        deme_size=cfg.deme_size,
        gene_dtype=ctx.gene_dtype,
        demes_per_step=cfg.demes_per_step,
        layout=cfg.layout,
        subblock=cfg.subblock,
        crossover_kind=ctx.crossover_kind,
        mutate_kind=ctx.mutate_kind,
        tournament_size=ctx.tournament_size,
        selection_kind=ctx.selection_kind,
        selection_param=ctx.selection_param,
        fused=ctx.fused,
        const_carrying=ctx.const_carrying,
    )


def why_inadmissible(
    ctx: SpaceContext, cfg: KernelConfig, strict: bool = True
) -> Optional[str]:
    """None when ``cfg`` is admissible in ``ctx``, else a one-line
    reason. ``strict`` additionally rejects configurations the factory
    would accept but SILENTLY ROUND AWAY (a requested deme size or
    demes-per-step the factory replaces, a sub-block request the riffle
    fallback drops) — the sweep tools' "skip duplicates" rule and the
    tuner's "measure what you asked for" rule, now enforced before any
    compile."""
    gp_set = [
        n for n in GP_KNOBS if getattr(cfg, n) is not None
    ]
    if ctx.gp_nodes is None:
        if gp_set:
            return (
                f"{gp_set} are GP evaluator knobs; this context has no "
                "GP encoding (SpaceContext.gp_nodes is None)"
            )
    else:
        inert = [
            n for n in ("deme_size", "layout", "subblock",
                        "demes_per_step")
            if getattr(cfg, n) is not None
        ]
        if cfg.dimension_semantics != "parallel":
            inert.append("dimension_semantics")
        if inert:
            return (
                f"{inert} are fused-breed knobs — inert for GP engines "
                "(XLA-path operators by design); only "
                f"{list(GP_KNOBS)} tune the stack-machine evaluator"
            )
        try:
            plan = resolve(ctx, cfg)
        except ValueError as exc:  # explicit invalid GP knob
            return str(exc)
        if plan is None:
            return "GP evaluator declines this shape"
        return None
    if cfg.deme_size is not None:
        if not _valid_deme(cfg.deme_size):
            return (
                f"deme_size {cfg.deme_size} is not a power of two in "
                "[128, 1024]"
            )
        if strict and ctx.pop % cfg.deme_size:
            return (
                f"deme_size {cfg.deme_size} does not divide pop "
                f"{ctx.pop} (factory would re-pick or pad)"
            )
    if cfg.subblock is not None and cfg.subblock < 1:
        return f"subblock {cfg.subblock} must be >= 1"
    if (
        strict
        and cfg.subblock is not None
        and cfg.subblock > 1
        and cfg.layout == "riffle"
    ):
        return "subblock > 1 is a ping-pong pipeline (riffle drops it)"
    try:
        plan = resolve(ctx, cfg)
    except ValueError as exc:  # explicit ping-pong failing its gate
        return str(exc)
    if plan is None:
        return "factory declines this shape/knob combination"
    if strict:
        for knob, resolved in (
            ("deme_size", plan["deme_size"]),
            ("demes_per_step", plan["demes_per_step"]),
            ("layout", plan["layout"]),
        ):
            asked = getattr(cfg, knob)
            if asked is not None and asked != resolved:
                return (
                    f"{knob}={asked} rounds away (factory resolves "
                    f"{resolved})"
                )
        if (
            cfg.subblock is not None
            and cfg.subblock > 1
            and plan["subblock"] != cfg.subblock
        ):
            return (
                f"subblock={cfg.subblock} rounds away (factory resolves "
                f"{plan['subblock']})"
            )
    return None


def admissible(
    ctx: SpaceContext, cfg: KernelConfig, strict: bool = True
) -> bool:
    return why_inadmissible(ctx, cfg, strict=strict) is None


def grid(
    ctx: SpaceContext,
    knobs: Optional[Sequence[str]] = None,
    strict: bool = True,
    **pins: Iterable,
) -> List[KernelConfig]:
    """Every ADMISSIBLE configuration over the Cartesian product of the
    named knob domains (default: the context's tuner knob set —
    :func:`tuner_knobs_for`). ``pins`` overrides a knob's iterated
    values (e.g. ``layout=("riffle",)`` pins the sweep to one layout);
    a pinned knob need not be in ``knobs``. Inadmissible points are
    filtered here — callers never build a kernel to find out."""
    if knobs is None:
        knobs = tuner_knobs_for(ctx)
    names = list(dict.fromkeys(list(knobs) + list(pins)))
    axes = []
    for name in names:
        if name not in DOMAINS:
            raise ValueError(
                f"unknown knob {name!r}; valid knobs: {sorted(DOMAINS)}"
            )
        axes.append(tuple(pins.get(name, DOMAINS[name])))
    out = []
    for values in itertools.product(*axes):
        cfg = KernelConfig(**dict(zip(names, values)))
        if admissible(ctx, cfg, strict=strict):
            out.append(cfg)
    return out


def space_size(
    ctx: SpaceContext, knobs: Optional[Sequence[str]] = None
) -> int:
    """Number of admissible configurations (``--dry-run`` of the
    autotune CLI)."""
    return len(grid(ctx, knobs))


# ------------------------------------------------------------ genome codec


def genome_width(knobs: Sequence[str] = TUNER_KNOBS) -> int:
    """Fixed genome width: one gene per knob."""
    return len(knobs)


def config_from_indices(
    idx: Sequence[int], knobs: Sequence[str] = TUNER_KNOBS
) -> KernelConfig:
    """Decode a fixed-width integer genome: gene i indexes knob i's
    domain (clipped into range, so any integer decodes)."""
    fields = {}
    for name, i in zip(knobs, idx):
        dom = DOMAINS[name]
        fields[name] = dom[max(0, min(int(i), len(dom) - 1))]
    return KernelConfig(**fields)


def indices_from_config(
    cfg: KernelConfig, knobs: Sequence[str] = TUNER_KNOBS
) -> Tuple[int, ...]:
    return tuple(
        DOMAINS[name].index(getattr(cfg, name)) for name in knobs
    )


def config_from_genes(
    row, knobs: Sequence[str] = TUNER_KNOBS
) -> KernelConfig:
    """Decode one PGA genome row (floats in [0, 1) — the library's gene
    domain for random init and point mutation) into a configuration:
    gene g maps to domain index ``floor(g * |domain|)``, clipped, so
    the decode is total and the all-zeros genome is the default
    config."""
    idx = []
    for name, g in zip(knobs, row):
        dom = DOMAINS[name]
        idx.append(int(float(g) * len(dom)))
    return config_from_indices(idx, knobs)


__all__ = [
    "DOMAINS",
    "TUNER_KNOBS",
    "GP_KNOBS",
    "tuner_knobs_for",
    "SWEEP_KNOBS",
    "KNOB_TO_CONFIG_FIELD",
    "KernelConfig",
    "SpaceContext",
    "resolve",
    "why_inadmissible",
    "admissible",
    "grid",
    "space_size",
    "genome_width",
    "config_from_indices",
    "indices_from_config",
    "config_from_genes",
    "pingpong_admissible",
]
