"""Multi-host (multi-process) initialization.

The reference's README claims "CUDA GPUs+MPI" but contains zero MPI code
(survey §2.3/§2.4). The TPU-native distribution story needs no external
launcher: each host process calls :func:`initialize`, after which
``jax.devices()`` is the global device list, ``default_mesh()`` spans the
pod, and the island runner's collectives ride ICI within a slice and DCN
across slices automatically.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host JAX runtime.

    On TPU pods the arguments are discovered from the environment; pass them
    explicitly only for CPU/GPU test rigs. Idempotent: safe to call when
    already initialized or single-process.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        # Already initialized, or single-process run without coordinator.
        pass


def is_multi_process() -> bool:
    return jax.process_count() > 1


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
