"""Population sharding: ONE panmictic-equivalent population across the
device mesh (ROADMAP item 2, ISSUE 7).

Everything before this module fits a single device's memory — islands
were the only multi-device story, so the largest servable tenant was
capped by single-device HBM. Here the POPULATION AXIS of a single run is
split S ways via ``shard_map``: each shard runs the existing breed
machinery (the fused ping-pong deme kernel on TPU, the XLA breed
elsewhere) over its LOCAL rows, and three cross-shard mechanisms keep
the run globally panmictic-equivalent at a cost of exactly ONE
cross-shard collective pair per generation:

1. **Comb mixing (one ``ppermute``)** — the round-8 ping-pong comb
   algebra extended over shards: the odd-parity comb STRIDE becomes a
   cross-shard permute. Each generation, every shard ships the
   ``mix = P/S²`` children sitting at stride-S row positions (rows
   0, S, 2S, … — a comb across the WHOLE shard, so every deme group
   of the in-shard layout contributes) one hop around the shard ring,
   and the received comb lands CROSS-DEME INTERLEAVED (comb slot
   ``d·C + u`` lands at slot ``u·D + d`` — the same ``u*D+d`` write
   interleave that makes the in-shard parity pair mix, see
   ``ops/pallas_step.py``). The hop is the ring specialization of the
   comb's ``(s+u) mod S`` stride family with a STATIC permutation
   (``ppermute`` perms cannot be traced); because every comb row is a
   fresh child of the WHOLE local shard (local selection is panmictic
   within the shard), one hop per generation spreads any lineage
   across all S shards in at most S generations, and because the comb
   is spread across every deme group, the composition with the
   in-shard ping-pong layout mixes too (a CONTIGUOUS migration slab
   provably does not: at S=4·K=512 it slowed simulated deme-path
   takeover ~3×, caught in-session by the cohort model — the same
   class of bug round 8 caught in the read==write deme layout). The
   lineage-BFS test in ``tests/test_shard_pop.py`` pins connectivity,
   and the cohort-dynamics simulation
   (``tools/selection_equivalence.py --simulate --pop-shards S``)
   measures takeover within 0-1.6% of panmictic (S=2/4/8, BASELINE.md round 12). The read-local/
   write-local alias discipline holds per shard: a shard only ever
   writes its own rows, so ``input_output_aliases`` (and buffer
   donation) still applies per shard.

2. **Global rank thresholds (one ``all_gather`` of S·k scalars)** —
   selection pressure stays globally panmictic-equivalent. Per-shard
   rank-space selection over a mixed shard is selection over an
   exchangeable cohort of the global score distribution — the exact
   argument (and measurement) that already justifies the deme kernel's
   cohort selection one level down (``tools/selection_equivalence.py``,
   BASELINE.md round 8); the comb mixing is what keeps the cohorts
   exchangeable. What cannot be local is the GLOBAL part of the
   algebra: the target/termination check, elitism, and telemetry's
   best. Each generation every shard publishes its local top-k scores
   (k = max(1, elitism)); one ``all_gather`` makes the sorted S·k
   sketch — the global rank thresholds — available everywhere: row 0
   is the global best (the while-loop's termination predicate and the
   stall counter's input), row e-1 is the global elitism threshold
   (each shard re-injects only local parents scoring at or above the
   global e-th best, so exactly the global top-e survive, modulo
   score ties).

3. **Replicated control flow** — every shard derives the same
   ``best``/``gens`` scalars from the same sketch, so all shards take
   the same branch every generation (the islands pmax pattern).

``pop_shards=1`` never reaches this module: the engine routes the
default through the exact pre-sharding path, which therefore lowers to
byte-identical StableHLO (structurally asserted in
``tests/test_shard_pop.py``).

Admissibility: ``P % S == 0`` (equal shards) and ``(P/S) % S == 0``
(the mix slab is a whole number of rows ≥ 1 per hop), i.e. ``S² | P``
— plus, on the TPU deme path, the per-shard population must itself
pass ``pingpong_admissible``. :func:`validate_shards` raises a
ValueError naming the valid shard counts (the round-8 ablate-flag
convention).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libpga_tpu.ops.evaluate import evaluate as _evaluate
from libpga_tpu.parallel.mesh import POP_AXIS, pop_mesh
from libpga_tpu.utils import telemetry as _tl

#: Ablation flags accepted by make_sharded_run (bench component
#: isolation — tools/ablate_floor.py convention: unknown flags raise).
ABLATE_FLAGS = ("sync", "mix")


# ------------------------------------------------------------ admissibility


def admissible_shards(pop_size: int, max_shards: Optional[int] = None):
    """Every shard count S with ``S² | pop_size`` (each shard gets an
    equal P/S rows AND the per-generation mix slab P/S² is a whole
    number of rows), capped at ``max_shards`` (default: the number of
    visible devices)."""
    if max_shards is None:
        max_shards = len(jax.devices())
    return [
        s
        for s in range(1, max_shards + 1)
        if pop_size % (s * s) == 0
    ]


def validate_shards(
    pop_size: int, shards: int, max_shards: Optional[int] = None
) -> None:
    """Raise ValueError (naming the valid values, the round-8
    ablate-flag convention) unless ``shards`` is admissible for this
    population on this host."""
    if max_shards is None:
        max_shards = len(jax.devices())
    valid = admissible_shards(pop_size, max_shards)
    if shards not in valid:
        raise ValueError(
            f"pop_shards={shards} is inadmissible for a population of "
            f"{pop_size} on {max_shards} devices (need S <= devices and "
            f"S^2 | pop so every shard holds pop/S rows and the comb "
            f"mix slab pop/S^2 is whole); valid shard counts: {valid}"
        )


def mix_rows(pop_size: int, shards: int) -> int:
    """Rows each shard ships per generation: one comb stride's worth,
    ``P / S²`` (the whole population circulates the ring every S·S
    generations even without lineage spread; WITH it, one hop per
    generation suffices — see the module docstring)."""
    return (pop_size // shards) // shards


def comb_chunks(mix: int, cap: int = 8) -> int:
    """Sub-chunk count D of the migrating slab — the cross-deme write
    interleave granularity (``u·D + d``). The largest divisor of the
    slab that is <= ``cap`` (8 = the f32 sublane quantum the in-shard
    comb uses); 1 when the slab is a single row."""
    for d in range(min(cap, mix), 0, -1):
        if mix % d == 0:
            return d
    return 1


def comb_interleave_rows(mix: int, D: Optional[int] = None):
    """Where received slab rows land, slab-locally: source row
    ``d·C + u`` (sub-chunk d of D, offset u of C = mix/D) lands at row
    ``u·D + d`` — the transposed cross-deme interleave of the round-8
    comb (``pingpong_child_rows``), one level up. Returns a numpy
    permutation ``dest[src_row] = dest_row``."""
    import numpy as np

    if D is None:
        D = comb_chunks(mix)
    C = mix // D
    d = np.arange(D, dtype=np.int64)[:, None]
    u = np.arange(C, dtype=np.int64)[None, :]
    dest = np.empty(mix, dtype=np.int64)
    dest[(d * C + u).reshape(-1)] = (u * D + d).reshape(-1)
    return dest


def shard_mix_perm(pop_size: int, shards: int):
    """The GLOBAL row permutation one generation's mixing applies —
    the single source of truth the runtime mirrors, pinned by the
    structure tests and driven by the ``--simulate`` cohort model.
    Row ``s·Ps + m·S`` (the stride-S comb) moves to shard
    ``(s+1) mod S`` at comb slot ``inv_interleave(m)``; off-comb rows
    stay. The comb (rather than a contiguous slab) is load-bearing:
    it touches every deme group of the in-shard layout, which is what
    makes the composition with the ping-pong parities mix (see the
    module docstring)."""
    import numpy as np

    S = shards
    Ps = pop_size // S
    mix = mix_rows(pop_size, S)
    ileave = comb_interleave_rows(mix)
    inv = np.argsort(ileave)  # inv[ileave[k]] = k
    dest = np.arange(pop_size, dtype=np.int64)
    m = np.arange(mix)
    for s in range(S):
        nxt = (s + 1) % S
        # runtime: dest comb slot k receives source comb slot
        # ileave[k]; as src -> dest that is slot m -> inv[m].
        dest[s * Ps + m * S] = nxt * Ps + inv[m] * S
    return dest


def _validate_ablate(ablate) -> tuple:
    ablate = tuple(ablate)
    unknown = [a for a in ablate if a not in ABLATE_FLAGS]
    if unknown:
        raise ValueError(
            f"unknown shard ablation flag(s) {unknown}; "
            f"valid: {list(ABLATE_FLAGS)}"
        )
    return ablate


# ---------------------------------------------------------------- run loop


def make_sharded_run(
    obj: Callable,
    local_step: Callable,
    pop_size: int,
    genome_len: int,
    shards: int,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = POP_AXIS,
    elitism: int = 0,
    history_gens: Optional[int] = None,
    donate: bool = True,
    ablate=(),
) -> Callable:
    """Build the sharded fused run loop: ``runner(genomes (P, L), key,
    n, target, mparams) -> (genomes, scores, gens[, history])`` with
    the engine run-loop contract, population rows split ``shards`` ways
    over ``mesh`` (default: :func:`~libpga_tpu.parallel.mesh.pop_mesh`).

    ``local_step(g, s, sub, mparams, gen) -> (g2, s2 | None)`` breeds
    one shard's local block — the XLA breed returns ``(children,
    None)`` (the loop evaluates after mixing); a fused Pallas breed
    returns in-kernel scores and only the migrated slab is re-scored.
    The step must NOT apply elitism itself: the loop applies GLOBAL
    elitism through the gathered rank thresholds (see module
    docstring).

    ``ablate``: bench-only component isolation — ``"sync"`` drops the
    all_gather (termination/elitism degrade to shard-local; measures
    the collective's cost), ``"mix"`` drops the ppermute. Unknown
    flags raise (tools/ablate_floor.py convention).
    """
    validate_shards(pop_size, shards)
    ablate = _validate_ablate(ablate)
    if mesh is None:
        mesh = pop_mesh(shards, axis_name=axis_name)
    S = shards
    Ps = pop_size // S
    mix = mix_rows(pop_size, S)
    if not 0 <= elitism <= Ps:
        raise ValueError(
            f"elitism={elitism} must be in [0, per-shard rows {Ps}]"
        )
    ileave = jnp.asarray(comb_interleave_rows(mix), dtype=jnp.int32)
    perm = [(i, (i + 1) % S) for i in range(S)]
    k_sync = max(1, elitism)
    telemetry = history_gens is not None

    def sync(scores):
        """The one small all-reduce: local top-k -> all_gather -> the
        sorted S·k global rank-threshold sketch (descending; entry 0 is
        the global best, entry e-1 the global elitism threshold)."""
        top = jax.lax.top_k(scores, k_sync)[0]
        if "sync" in ablate:
            return top  # shard-local sketch (bench isolation only)
        gathered = jax.lax.all_gather(top, axis_name)  # (S, k_sync)
        return -jnp.sort(-gathered.reshape(-1))

    def mix_children(g2):
        """One ppermute: ship the stride-S row comb of fresh children
        (rows 0, S, 2S, … — every deme group contributes) one hop
        around the shard ring; the received comb lands cross-deme
        interleaved (``u·D + d``)."""
        if mix == 0 or "mix" in ablate:
            return g2
        g2r = g2.reshape(mix, S, genome_len)  # row k·S + b -> (k, b)
        incoming = jax.lax.ppermute(g2r[:, 0, :], axis_name, perm)
        g2r = g2r.at[:, 0, :].set(incoming[ileave])
        return g2r.reshape(Ps, genome_len)

    def apply_elitism(g, s, g2, s2, sketch):
        """Global elitism via the carried rank thresholds: a local
        parent survives into rows 0..e-1 iff its score reaches the
        global e-th best — so exactly the global top-e survive
        (score ties may keep a few extra copies, never fewer)."""
        if elitism == 0:
            return g2, s2
        thr = sketch[elitism - 1]
        top_s, top_i = jax.lax.top_k(s, elitism)
        keep = top_s >= thr  # (e,)
        elites = jnp.take(g, top_i, axis=0).astype(g2.dtype)
        cur_g = jax.lax.dynamic_slice(
            g2, (0, 0), (elitism, g2.shape[1])
        )
        cur_s = jax.lax.dynamic_slice(s2, (0,), (elitism,))
        g2 = jax.lax.dynamic_update_slice(
            g2, jnp.where(keep[:, None], elites, cur_g), (0, 0)
        )
        s2 = jax.lax.dynamic_update_slice(
            s2, jnp.where(keep, top_s, cur_s), (0,)
        )
        return g2, s2

    def generation(g, s, sub, mparams, gen, sketch):
        """One sharded generation: local breed -> comb ppermute ->
        (re)evaluate -> global elitism -> rank-threshold sync."""
        g2, s2 = local_step(g, s, sub, mparams, gen)
        g2 = mix_children(g2)
        if s2 is None:
            s2 = _evaluate(obj, g2)
        elif mix > 0 and "mix" not in ablate:
            # Fused step scored its own children pre-mix; only the
            # migrated comb rows need re-scoring.
            comb = g2.reshape(mix, S, genome_len)[:, 0, :]
            s2 = (
                s2.reshape(mix, S)
                .at[:, 0]
                .set(_evaluate(obj, comb))
                .reshape(Ps)
            )
        g2, s2 = apply_elitism(g, s, g2, s2, sketch)
        return g2, s2, sync(s2)

    if not telemetry:

        def shard_body(genomes, keys, n, target, mparams):
            key = keys[0]
            scores = _evaluate(obj, genomes)
            sketch0 = sync(scores)

            def cond(c):
                g, s, k, gen, sk = c
                return jnp.logical_and(gen < n, sk[0] < target)

            def body(c):
                g, s, k, gen, sk = c
                k, sub = jax.random.split(k)
                g2, s2, sk2 = generation(g, s, sub, mparams, gen, sk)
                return (g2, s2, k, gen + 1, sk2)

            init = (genomes, scores, key, jnp.int32(0), sketch0)
            g, s, k, gens, _ = jax.lax.while_loop(cond, body, init)
            return g, s, gens

        out_specs = (P(axis_name, None), P(axis_name), P())

    else:

        def shard_body(genomes, keys, n, target, mparams):
            key = keys[0]
            scores = _evaluate(obj, genomes)
            sketch0 = sync(scores)

            def cond(c):
                g, s, k, gen, sk = c[:5]
                return jnp.logical_and(gen < n, sk[0] < target)

            def body(c):
                g, s, k, gen, sk, best, stall, buf = c
                k, sub = jax.random.split(k)
                g2, s2, sk2 = generation(g, s, sub, mparams, gen, sk)
                # Global stats row (pmax/pmean across shards — the
                # islands reduction pattern): every shard writes the
                # identical replicated history buffer.
                row, best, stall = _tl.island_stats_row(
                    g2[None], s2[None], best, stall,
                    axis_name=None if "sync" in ablate else axis_name,
                )
                buf = _tl.write_row(buf, gen, row)
                return (g2, s2, k, gen + 1, sk2, best, stall, buf)

            init = (
                genomes, scores, key, jnp.int32(0), sketch0,
                sketch0[0], jnp.int32(0), _tl.history_init(history_gens),
            )
            out = jax.lax.while_loop(cond, body, init)
            return out[0], out[1], out[3], out[7]

        out_specs = (P(axis_name, None), P(axis_name), P(), P())

    from libpga_tpu.utils.compat import shard_map as _shard_map

    mapped = _shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name), P(), P(), P()),
        out_specs=out_specs,
    )
    jitted = jax.jit(mapped, donate_argnums=(0,) if donate else ())

    def runner(genomes, key, n, target, mparams):
        keys = jax.random.split(key, S)
        return jitted(genomes, keys, n, target, mparams)

    runner.mesh = mesh
    runner.shards = S
    runner.mix = mix
    runner.k_sync = k_sync
    runner.jitted = jitted
    runner.history_gens = history_gens
    return runner
