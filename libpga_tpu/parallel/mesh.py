"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ISLAND_AXIS = "islands"
POP_AXIS = "pop"


def default_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis_name: str = ISLAND_AXIS
) -> Mesh:
    """A 1-D mesh over all (or the given) devices, one island group per core.

    On a multi-host pod every process sees the global device list, so the
    same call yields the global mesh (ICI within a slice, DCN across)."""
    import numpy as np

    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, axis_names=(axis_name,))


def island_sharding(mesh: Mesh, axis_name: str = ISLAND_AXIS) -> NamedSharding:
    """Sharding for a stacked ``(islands, size, genome_len)`` array:
    islands split across the mesh, genomes local to a core."""
    return NamedSharding(mesh, P(axis_name, None, None))


def pop_mesh(
    shards: int,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = POP_AXIS,
) -> Mesh:
    """A 1-D ``shards``-way mesh over the POPULATION axis of one run
    (``parallel/shard_pop.py``): the first ``shards`` devices, one
    population shard per device. Distinct axis name from the island
    mesh so a future 2-D (islands × pop) layout composes."""
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    if shards > len(devs):
        raise ValueError(
            f"pop_shards={shards} exceeds the {len(devs)} available "
            "devices"
        )
    return Mesh(np.asarray(devs[:shards]), axis_names=(axis_name,))


def pop_sharding(mesh: Mesh, axis_name: str = POP_AXIS) -> NamedSharding:
    """Sharding for one ``(pop, genome_len)`` population: rows split
    across the mesh axis, genes local to a device."""
    return NamedSharding(mesh, P(axis_name, None))


def global_max(arr, mesh: Optional[Mesh] = None) -> float:
    """Max of a (possibly multi-host-sharded) array as a host float.

    Plain ``jnp.max`` on a global array with non-addressable shards
    raises; reducing under jit with a replicated output sharding gives
    every process the scalar. Fully addressable arrays take the direct
    path (no host round trip beyond the scalar)."""
    import jax.numpy as jnp

    if getattr(arr, "is_fully_addressable", True) or mesh is None:
        return float(jnp.max(arr))
    return float(
        jax.jit(jnp.max, out_shardings=NamedSharding(mesh, P()))(arr)
    )
