"""Parallelism: island model over a TPU device mesh.

The reference designed — but never implemented — an island model: multiple
populations run in a container, with ``pga_migrate``/``pga_migrate_between``/
``pga_run_islands`` declared in the header (``include/pga.h:108-150``) and
left as empty stubs (``src/pga.cu:368-374,393-395``); its README claims MPI
that does not exist anywhere in the tree.

TPU-natively: islands are a stacked ``(islands, size, genome_len)`` array
sharded island-per-core over a 1-D ``jax.sharding.Mesh`` with ``shard_map``;
ring migration is a ``lax.ppermute`` neighbor exchange that rides ICI
(DCN across hosts via ``jax.distributed``); random-topology migration is an
``all_gather`` of the (small) emigrant sets plus a shared permutation.
"""

from libpga_tpu.parallel.mesh import (
    default_mesh,
    island_sharding,
    pop_mesh,
    pop_sharding,
)
from libpga_tpu.parallel.islands import run_islands_stacked, make_island_epoch
from libpga_tpu.parallel import distributed

__all__ = [
    "default_mesh",
    "island_sharding",
    "pop_mesh",
    "pop_sharding",
    "run_islands_stacked",
    "make_island_epoch",
    "distributed",
]
