"""Island-model execution: epochs of local evolution + migration.

Implements the contract of the reference's never-written
``pga_run_islands(pga, n, m, pct)`` (header spec ``include/pga.h:144-150``:
run for ``n`` generations, migrating the top ``pct`` every ``m``
generations) as one jitted program:

- each island evolves ``m`` generations via ``lax.scan`` of
  breed-then-evaluate, carrying ``(genomes, scores)`` together so the
  carried scores always describe the carried genomes;
- migration selects each island's top-E on device and ships them to the
  next island — ``jnp.roll`` within a core's local islands, ``lax.ppermute``
  across cores (the ICI ring), or ``all_gather`` + shared permutation for
  the random topology; immigrants replace the destination's worst-E, so an
  island's best always survives a migration event;
- the epoch loop is a ``lax.while_loop`` so a single compilation serves any
  (epochs, target); early termination checks the carried scores BEFORE
  breeding again, so the generation that reached the target is the one
  returned. With migration every ``m`` generations, the target check has
  epoch granularity (a transient winner strictly inside an epoch is
  superseded by its offspring, as in any generational GA without elitism).

Runner builders (:func:`build_local_runner`, :func:`build_sharded_runner`)
are deterministic in their arguments so callers (the engine) can cache the
compiled runner across calls.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libpga_tpu.ops.evaluate import evaluate as _evaluate
from libpga_tpu.ops.pallas_step import _carry_elites
from libpga_tpu.utils import telemetry as _tl


def make_island_epoch(
    breed: Callable, obj: Callable, m: int, *, elitism: int = 0
) -> Callable:
    """``(genomes (S,L), scores (S,), key) -> (genomes, scores, key)`` —
    m generations of breed-then-evaluate on one island.

    A breed carrying ``fused=True`` (the Pallas path built with a
    ``fused_obj`` — see :func:`libpga_tpu.ops.pallas_step.make_pallas_breed`)
    supplies the next scores itself and the separate evaluation is
    skipped. For lane-unaligned genome lengths or deme-padded island
    sizes the epoch pads once at entry, scans over the breed's padded
    variant (pad rows carry -inf scores and are inert — see
    ``make_pallas_breed``), and slices once at exit — not once per
    generation.

    ``elitism`` > 0 applies the elite carry HERE, after the separate
    evaluation — for breeds that neither handle elitism internally (the
    XLA breed does) nor score children in-kernel (the fused Pallas breed
    applies its own epilogue). This is what lets a custom, non-rowwise
    objective with elitism keep the Pallas island fast path."""
    fused = getattr(breed, "fused", False)
    padded_fn = getattr(breed, "padded", None)
    Lp = getattr(breed, "Lp", None)
    Pp = getattr(breed, "Pp", None)
    gdtype = getattr(breed, "gene_dtype", None)
    takes_params = getattr(breed, "takes_params", False)

    def epoch(genomes, scores, key, mparams=None):
        S, L = genomes.shape
        pad = padded_fn is not None and (
            (Lp is not None and Lp != L) or (Pp is not None and Pp != S)
        )
        # Cast to the breed's gene dtype (bf16 mode outputs bf16; a f32
        # carry would fail the scan's carry-dtype check).
        g0 = genomes.astype(gdtype or genomes.dtype)
        s0 = scores
        if pad:
            g0 = jnp.pad(g0, ((0, Pp - S), (0, Lp - L)))
            s0 = jnp.pad(scores, (0, Pp - S), constant_values=-jnp.inf)

        def body(carry, _):
            g, s, k = carry
            k, sub = jax.random.split(k)
            step = padded_fn if pad else breed
            args = (g, s, sub) + (
                (mparams,) if takes_params and mparams is not None else ()
            )
            if fused:
                g2, s2 = step(*args)
            else:
                g2 = step(*args)
                s2 = _evaluate(obj, g2[:S, :L] if pad else g2)
                if pad:
                    s2 = jnp.pad(s2, (0, Pp - S), constant_values=-jnp.inf)
                if elitism > 0:
                    g2, s2 = _carry_elites(g, s, g2, s2, elitism)
            return (g2, s2, k), None

        (genomes, scores, key), _ = jax.lax.scan(
            body, (g0, s0, key), None, length=m
        )
        if pad:
            genomes = genomes[:S, :L]
            scores = scores[:S]
        return genomes, scores, key

    return epoch


def make_stacked_pallas_epoch(breed: Callable, m: int) -> Callable:
    """m generations over ALL islands at once for a fused Pallas breed:
    ``(genomes (I,S,L), scores (I,S), keys (I,)[, mparams]) ->
    (genomes, scores, keys)``.

    Per generation, the deme ranks for every island come from ONE
    flattened (I·G, K) two-key sort (``breed.compute_ranks``) and only
    the kernel call is vmapped. Hoisting matters: a per-island vmapped
    sort measured 3.4 ms per 8×131,072 generation vs 0.9 ms flattened —
    it was the island path's largest overhead (see BASELINE.md round 3).
    Serves fused breeds only (they score children in-kernel and apply
    their own elitism epilogue); everything else goes through
    :func:`make_island_epoch` under ``jax.vmap``.

    Ping-pong breeds alternate their two row groupings per generation.
    The parity is STATIC per kernel build, so instead of a traced cond
    the epoch scans generation PAIRS (parity 0 then parity 1) with an
    odd-``m`` tail at parity 0 — gens 0,1,2,... run at parities
    0,1,0,..., exactly like the single-population run loop. Each epoch
    restarts at parity 0; the migration step between epochs mixes
    globally anyway, so the restart costs nothing."""
    Lp, Pp = breed.Lp, breed.Pp
    gdtype = breed.gene_dtype
    takes_params = breed.takes_params
    parities = getattr(breed, "parities", 1)

    def epoch(genomes, scores, keys, mparams=None):
        I, S, L = genomes.shape
        pad = Lp != L or Pp != S
        g0 = genomes.astype(gdtype)
        s0 = scores
        if pad:
            g0 = jnp.pad(g0, ((0, 0), (0, Pp - S), (0, Lp - L)))
            s0 = jnp.pad(
                scores, ((0, 0), (0, Pp - S)), constant_values=-jnp.inf
            )

        def gen_step(carry, parity):
            g, s, ks = carry
            split2 = jax.vmap(jax.random.split)(ks)
            ks2, subs = split2[:, 0], split2[:, 1]
            # One tie-break stream for the whole flattened sort,
            # disjoint from every island's kernel-seed stream (fold_in
            # is a PRF; padded_ranks only consumes split(key)[0]).
            tie_key = jax.random.fold_in(subs[0], 0x72616E6B)
            ranks = breed.compute_ranks(s, tie_key, parity=parity)
            if takes_params and mparams is not None:
                g2, s2 = jax.vmap(
                    lambda gi, si, ri, ki: breed.padded_ranks(
                        gi, si, ri, ki, mparams, parity=parity
                    )
                )(g, s, ranks, subs)
            else:
                g2, s2 = jax.vmap(
                    lambda gi, si, ri, ki: breed.padded_ranks(
                        gi, si, ri, ki, parity=parity
                    )
                )(g, s, ranks, subs)
            return (g2, s2, ks2)

        carry = (g0, s0, keys)
        if parities > 1:
            def pair(carry, _):
                return gen_step(gen_step(carry, 0), 1), None

            carry, _ = jax.lax.scan(pair, carry, None, length=m // 2)
            if m % 2:
                carry = gen_step(carry, 0)
        else:
            def body(carry, _):
                return gen_step(carry, 0), None

            carry, _ = jax.lax.scan(body, carry, None, length=m)
        g, s, ks = carry
        if pad:
            g = g[:, :S, :L]
            s = s[:, :S]
        return g, s, ks

    return epoch


def make_multigen_stacked_epoch(bm: Callable, m: int) -> Callable:
    """m generations over ALL islands for a MULTI-GENERATION fused breed
    (``make_pallas_multigen``): the epoch is a handful of vmapped kernel
    launches — ceil(m / T) per island, each breeding up to T
    sub-generations with demes VMEM-resident and ranks computed
    in-kernel — instead of m per-generation launches with a hoisted
    host-side rank sort (``make_stacked_pallas_epoch``). The round-3
    sort-hoist machinery is unnecessary here: sub-generations rank
    in-kernel, so nothing is left to hoist.

    Signature matches the other stacked epoch:
    ``(genomes (I,S,L), scores (I,S), keys (I,)[, mparams]) ->
    (genomes, scores, keys)``. Elitism runs in-breed (per deme).
    """
    Lp, Pp = bm.Lp, bm.Pp
    gdtype = bm.gene_dtype
    # Whole-epoch launches up to T=8 by default: 8 is the measured
    # convergence-NEUTRAL bound (BASELINE.md multigen table: takeover
    # 67.2 vs 66.6 gens, 64-gen OneMax mean -0.04), while T=16 shows
    # measurable drag (takeover 70.4, mean -0.11). Since round 5 this
    # epoch is OPT-IN only (the one-generation island path measured
    # faster, 149.2 vs 127.0 — BASELINE.md round 5), so T=8 is the cap
    # a bare pallas_generations_per_launch>1 request gets; an explicit
    # value rules exactly (the engine stamps it on the breed as
    # ``epoch_chunk``).
    T = getattr(bm, "epoch_chunk", None) or 8

    def epoch(genomes, scores, keys, mparams=None):
        I, S, L = genomes.shape
        pad = Lp != L or Pp != S
        g = genomes.astype(gdtype)
        s = scores
        if pad:
            g = jnp.pad(g, ((0, 0), (0, Pp - S), (0, Lp - L)))
            s = jnp.pad(s, ((0, 0), (0, Pp - S)), constant_values=-jnp.inf)
        ks = keys
        done = 0
        launch = 0
        while done < m:  # static chunking: m and T are Python ints
            t = min(T, m - done)
            # Ping-pong multigen: launch parity alternates the row
            # grouping (static per launch — the loop is a Python
            # unroll, so no traced cond is needed).
            parity = launch % 2 if getattr(bm, "parities", 1) > 1 else 0
            split2 = jax.vmap(jax.random.split)(ks)
            ks, subs = split2[:, 0], split2[:, 1]
            g, s = jax.vmap(
                lambda gi, si, ki: bm.padded(
                    gi, si, ki, jnp.int32(t), mparams, None, parity
                )
            )(g, s, subs)
            done += t
            launch += 1
        if pad:
            g = g[:, :S, :L]
            s = s[:, :S]
        return g, s, ks

    return epoch


def _use_stacked_epoch(breed, elitism: int) -> bool:
    """Fused Pallas breeds with the rank hooks take the stacked epoch
    (their elitism runs in-breed, so the epoch-level carry must be 0)."""
    return (
        getattr(breed, "fused", False)
        and hasattr(breed, "padded_ranks")
        and elitism == 0
    )


def _make_vepoch(breed, obj, m: int, elitism: int):
    """The epoch actually run over stacked islands — shared by the local
    and sharded runners so the stacked/vmapped selection can never
    diverge between them. Signature either way:
    ``(g (I,S,L), s (I,S), keys (I,)[, mparams]) -> (g, s, keys)``."""
    if getattr(breed, "multigen", False):
        return make_multigen_stacked_epoch(breed, m)
    if _use_stacked_epoch(breed, elitism):
        return make_stacked_pallas_epoch(breed, m)
    epoch = make_island_epoch(breed, obj, m, elitism=elitism)
    if getattr(breed, "takes_params", False):
        return jax.vmap(epoch, in_axes=(0, 0, 0, None))
    return jax.vmap(epoch)


def _select_emigrants(genomes, scores, count):
    """Per-island top-``count``: genomes (I,S,L), scores (I,S) →
    emigrants (I,count,L), escores (I,count)."""
    top_s, top_i = jax.lax.top_k(scores, count)
    em = jnp.take_along_axis(genomes, top_i[..., None], axis=1)
    return em, top_s


def _immigrate(genomes, scores, im_g, im_s):
    """Replace each island's worst-``count`` with the immigrants.
    Batched over the leading island axis."""
    count = im_g.shape[1]
    _, worst_i = jax.lax.top_k(-scores, count)
    genomes = jax.vmap(lambda g, idx, im: g.at[idx].set(im))(
        genomes, worst_i, im_g.astype(genomes.dtype)
    )
    scores = jax.vmap(lambda s, idx, ims: s.at[idx].set(ims))(
        scores, worst_i, im_s
    )
    return genomes, scores


def _shuffled_ring_sources(key, n):
    """Source-island index per destination for a ring over a random island
    order: ``src[order[i+1]] = order[i]``."""
    order = jax.random.permutation(key, n)
    return jnp.zeros((n,), dtype=order.dtype).at[order].set(jnp.roll(order, 1))


def _migrate_local(genomes, scores, key, count, topology):
    """Single-device migration across the leading island axis."""
    I = genomes.shape[0]
    em_g, em_s = _select_emigrants(genomes, scores, count)
    if topology == "ring":
        src = jnp.roll(jnp.arange(I), 1)
    else:  # random: ring over a shuffled island order
        src = _shuffled_ring_sources(key, I)
    return _immigrate(genomes, scores, em_g[src], em_s[src])


def _shard_host_array(arr: jax.Array, sharding: NamedSharding) -> jax.Array:
    """Place a host-replicated array onto a (possibly multi-host) mesh.

    ``jax.make_array_from_callback`` asks each process only for the
    shards it can address, which is the multi-host-safe equivalent of
    ``device_put`` with a NamedSharding (the latter requires every mesh
    device to be addressable by the calling process). Typed PRNG key
    arrays round-trip through their uint32 key data (numpy cannot hold
    the key dtype); the extra trailing data axis is replicated.

    Single-process meshes short-circuit to ``device_put`` — an on-device
    reshard with no host round trip (the callback path would pull the
    whole population to host and back, gigabytes at framework scale)."""
    import numpy as np

    if all(
        d.process_index == jax.process_index()
        for d in sharding.mesh.devices.flat
    ):
        return jax.device_put(arr, sharding)
    if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
        impl = jax.random.key_impl(arr)
        data = np.asarray(jax.random.key_data(arr))
        extra = data.ndim - arr.ndim
        spec = P(*(tuple(sharding.spec) + (None,) * extra))
        data_sharded = jax.make_array_from_callback(
            data.shape,
            NamedSharding(sharding.mesh, spec),
            lambda idx: data[idx],
        )
        return jax.random.wrap_key_data(data_sharded, impl=impl)
    host = np.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


# --------------------------------------------------------------- local path


def build_local_runner(
    breed: Callable, obj: Callable, *, m: int, count: int, topology: str,
    elitism: int = 0, history_gens: Optional[int] = None,
) -> Callable:
    """Single-device (vmapped-islands) epoch loop.

    Returns ``runner(genomes (I,S,L), island_keys (I,), mig_key,
    num_epochs, target) -> (genomes, scores (I,S), epochs_done)``. For a
    breed with runtime mutation params (``breed.takes_params``) the
    runner takes a trailing ``mparams`` argument and sets its own
    ``takes_params`` marker. ``elitism`` is the epoch-level elite carry
    for breeds that don't handle it themselves (see
    :func:`make_island_epoch`).

    ``history_gens`` set = telemetry mode: the loop ADDITIONALLY takes
    ``(gen0, best0, stall0, hist)`` after ``target`` and returns
    ``(genomes, scores, epochs_done, best, stall, hist)``. One GLOBAL
    stats row per migration epoch (interval-end values fill that epoch's
    ``m`` generation rows of the ``(history_gens, NUM_STATS)`` buffer,
    offset by ``gen0``) — written on device inside the loop carry; the
    explicit best/stall threading lets the remainder-generations call
    continue the same buffer and stall counter. The default path below
    is untouched (telemetry off traces to the exact pre-telemetry
    jaxpr).
    """
    takes_params = getattr(breed, "takes_params", False)
    vepoch = _make_vepoch(breed, obj, m, elitism)

    if history_gens is None:

        def loop(genomes, island_keys, mig_key, num_epochs, target,
                 mparams=None):
            scores = jax.vmap(lambda gi: _evaluate(obj, gi))(genomes)

            def cond(c):
                g, s, keys, mk, e = c
                return jnp.logical_and(e < num_epochs, jnp.max(s) < target)

            def body(c):
                g, s, keys, mk, e = c
                if takes_params:
                    g, s, keys = vepoch(g, s, keys, mparams)
                else:
                    g, s, keys = vepoch(g, s, keys)
                if count > 0:
                    mk, sub = jax.random.split(mk)
                    g, s = _migrate_local(g, s, sub, count, topology)
                return (g, s, keys, mk, e + 1)

            init = (genomes, scores, island_keys, mig_key, jnp.int32(0))
            g, s, keys, mk, e = jax.lax.while_loop(cond, body, init)
            return g, s, e

    else:

        def loop(genomes, island_keys, mig_key, num_epochs, target,
                 gen0, best0, stall0, hist, mparams=None):
            scores = jax.vmap(lambda gi: _evaluate(obj, gi))(genomes)

            def cond(c):
                g, s, keys, mk, e, best, stall, buf = c
                return jnp.logical_and(e < num_epochs, jnp.max(s) < target)

            def body(c):
                g, s, keys, mk, e, best, stall, buf = c
                if takes_params:
                    g, s, keys = vepoch(g, s, keys, mparams)
                else:
                    g, s, keys = vepoch(g, s, keys)
                if count > 0:
                    mk, sub = jax.random.split(mk)
                    g, s = _migrate_local(g, s, sub, count, topology)
                row, best, stall = _tl.island_stats_row(
                    g, s, best, stall, step=m
                )
                start = gen0 + e * m
                buf = _tl.fill_rows(buf, start, start + m, row)
                return (g, s, keys, mk, e + 1, best, stall, buf)

            init = (
                genomes, scores, island_keys, mig_key, jnp.int32(0),
                best0, stall0, hist,
            )
            g, s, keys, mk, e, best, stall, buf = jax.lax.while_loop(
                cond, body, init
            )
            return g, s, e, best, stall, buf

    jitted = jax.jit(loop)

    def runner(*args):
        return jitted(*args)

    runner.takes_params = takes_params
    # The untraced loop body, exposed so the serving executor can batch
    # N independent island runs through ONE program (scan/vmap over a
    # leading run axis — see make_batched_island_loop).
    runner.raw = loop
    runner.history_gens = history_gens
    return runner


def make_batched_island_loop(
    breed: Callable, obj: Callable, *, m: int, count: int, topology: str,
    elitism: int = 0, history_gens: Optional[int] = None,
    layout: str = "run_major",
):
    """N independent island runs as ONE program over a leading run axis —
    the island-model face of the serving mega-run (``serving/batch.py``).

    Reuses :func:`build_local_runner`'s exact loop per run, so each
    run's result is bit-identical to a standalone
    :func:`run_islands_stacked` epoch loop with the same keys.
    ``layout``: "run_major" scans runs sequentially (each run's working
    set stays cache-resident — the fast layout on CPU hosts);
    "lockstep" vmaps the loop over the run axis (every run advances one
    epoch per step — the wide layout for accelerators).

    Returns ``mega(genomes (N,I,S,L), island_keys (N,I), mig_keys (N,),
    num_epochs (N,), target (N,)[, telemetry extras][, mparams (N,...)])
    -> stacked per-run results`` (untraced; callers jit with their own
    donation policy).
    """
    runner = build_local_runner(
        breed, obj, m=m, count=count, topology=topology, elitism=elitism,
        history_gens=history_gens,
    )
    loop = runner.raw
    takes_params = runner.takes_params

    if layout == "lockstep":
        mega = jax.vmap(loop)
    elif layout == "run_major":

        def mega(*args):
            def one(carry, xs):
                return carry, loop(*xs)

            _, out = jax.lax.scan(one, 0, args)
            return out

    else:
        raise ValueError(
            f"unknown layout {layout!r}; use 'run_major' or 'lockstep'"
        )
    mega.takes_params = takes_params
    mega.history_gens = history_gens
    return mega


# ------------------------------------------------------------- sharded path


def _migrate_sharded(genomes, scores, key, count, topology, axis_name,
                     n_dev=None):
    """Migration inside shard_map: genomes (I_loc, S, L) per core.

    Ring: emigrants shift one island forward globally — a local roll plus a
    single ppermute of the boundary island's emigrants to the next core
    (pure ICI neighbor traffic). Random: all_gather the (small) emigrant
    sets and index by a shared permutation (identical on every core because
    it derives from the replicated migration key).

    ``n_dev``: the STATIC mesh-axis size (the ppermute ring needs a
    python int); callers inside shard_map pass ``mesh.shape[axis_name]``.
    ``None`` uses ``jax.lax.axis_size``, which only exists on newer JAX.
    """
    i_loc = genomes.shape[0]
    if n_dev is None:
        n_dev = jax.lax.axis_size(axis_name)
    total = i_loc * n_dev
    em_g, em_s = _select_emigrants(genomes, scores, count)

    if topology == "ring":
        perm = [(d, (d + 1) % n_dev) for d in range(n_dev)]
        from_prev_g = jax.lax.ppermute(em_g[i_loc - 1], axis_name, perm)
        from_prev_s = jax.lax.ppermute(em_s[i_loc - 1], axis_name, perm)
        in_g = jnp.roll(em_g, 1, axis=0).at[0].set(from_prev_g)
        in_s = jnp.roll(em_s, 1, axis=0).at[0].set(from_prev_s)
    else:
        all_g = jax.lax.all_gather(em_g, axis_name)  # (D, I_loc, E, L)
        all_s = jax.lax.all_gather(em_s, axis_name)
        all_g = all_g.reshape((total,) + all_g.shape[2:])
        all_s = all_s.reshape((total,) + all_s.shape[2:])
        src = _shuffled_ring_sources(key, total)
        my_first = jax.lax.axis_index(axis_name) * i_loc
        my_src = jax.lax.dynamic_slice_in_dim(src, my_first, i_loc)
        in_g = all_g[my_src]
        in_s = all_s[my_src]

    return _immigrate(genomes, scores, in_g, in_s)


def build_sharded_runner(
    breed: Callable,
    obj: Callable,
    *,
    m: int,
    count: int,
    topology: str,
    mesh: Mesh,
    axis_name: str = "islands",
    elitism: int = 0,
    history_gens: Optional[int] = None,
) -> Callable:
    """shard_map'd epoch loop: islands split over the mesh axis, migration
    over ICI. Same signature as :func:`build_local_runner`'s return
    (including the trailing ``mparams`` for a ``takes_params`` breed —
    replicated across the mesh, and the telemetry extras when
    ``history_gens`` is set: every shard computes the identical global
    stats row via pmax/pmean collectives, so the history buffer stays
    replicated — one all-reduce of five scalars per epoch, not per
    generation)."""
    takes_params = getattr(breed, "takes_params", False)
    # Same flattened-rank-sort hoist as the local runner, applied to
    # each shard's local islands.
    vepoch = _make_vepoch(breed, obj, m, elitism)
    telemetry = history_gens is not None

    def shard_body(genomes, island_keys, mig_key, num_epochs, target,
                   *rest):
        if telemetry:
            gen0, best_t0, stall0, hist = rest[:4]
            rest = rest[4:]
        mparams = rest[0] if rest else None
        # genomes: (I_loc, S, L); island_keys: (I_loc,); mig_key replicated.
        scores = jax.vmap(lambda gi: _evaluate(obj, gi))(genomes)
        best0 = jax.lax.pmax(jnp.max(scores), axis_name)

        def cond(c):
            return jnp.logical_and(c[4] < num_epochs, c[5] < target)

        def body(c):
            g, s, keys, mk, e, best = c[:6]
            if takes_params:
                g, s, keys = vepoch(g, s, keys, mparams)
            else:
                g, s, keys = vepoch(g, s, keys)
            if count > 0:
                mk, sub = jax.random.split(mk)
                g, s = _migrate_sharded(
                    g, s, sub, count, topology, axis_name,
                    n_dev=mesh.shape[axis_name],
                )
            # Global best — every core takes the same branch next epoch.
            # Computed AFTER migration, which only replaces worst-E, so the
            # carried best is still present in some island.
            best = jax.lax.pmax(jnp.max(s), axis_name)
            if not telemetry:
                return (g, s, keys, mk, e + 1, best)
            best_t, stall, buf = c[6:]
            row, best_t, stall = _tl.island_stats_row(
                g, s, best_t, stall, step=m, axis_name=axis_name
            )
            start = gen0 + e * m
            buf = _tl.fill_rows(buf, start, start + m, row)
            return (g, s, keys, mk, e + 1, best, best_t, stall, buf)

        init = (genomes, scores, island_keys, mig_key, jnp.int32(0), best0)
        if telemetry:
            init = init + (best_t0, stall0, hist)
        out = jax.lax.while_loop(cond, body, init)
        if not telemetry:
            return out[0], out[1], out[4]
        return out[0], out[1], out[4], out[6], out[7], out[8]

    from libpga_tpu.utils.compat import shard_map as _shard_map

    base_specs = (P(axis_name, None, None), P(axis_name), P(), P(), P())
    if telemetry:
        base_specs = base_specs + (P(), P(), P(), P())
    out_specs = (P(axis_name, None, None), P(axis_name, None), P())
    if telemetry:
        out_specs = out_specs + (P(), P(), P())
    mapped = _shard_map(
        shard_body,
        mesh=mesh,
        in_specs=base_specs + ((P(),) if takes_params else ()),
        out_specs=out_specs,
    )
    jitted = jax.jit(mapped)

    def runner(*args):
        return jitted(*args)

    runner.takes_params = takes_params
    return runner


def build_runner(
    breed: Callable,
    obj: Callable,
    *,
    m: int,
    count: int,
    topology: str,
    mesh: Optional[Mesh] = None,
    axis_name: str = "islands",
    elitism: int = 0,
    history_gens: Optional[int] = None,
) -> Callable:
    if mesh is None:
        return build_local_runner(
            breed, obj, m=m, count=count, topology=topology, elitism=elitism,
            history_gens=history_gens,
        )
    return build_sharded_runner(
        breed, obj, m=m, count=count, topology=topology, mesh=mesh,
        axis_name=axis_name, elitism=elitism, history_gens=history_gens,
    )


# ------------------------------------------------------------- convenience


def run_islands_stacked(
    step_or_breed,
    obj: Callable,
    stacked: jax.Array,
    key: jax.Array,
    *,
    n: int,
    m: int,
    pct: float,
    target: Optional[float] = None,
    topology: str = "ring",
    mesh: Optional[Mesh] = None,
    axis_name: str = "islands",
    runner_cache: Optional[dict] = None,
    mparams: Optional[jax.Array] = None,
    elitism: int = 0,
    history_gens: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Run the island GA on a stacked ``(I, S, L)`` population array.

    ``step_or_breed`` takes ``(genomes, scores, key)`` (a breed fn from
    :func:`libpga_tpu.ops.step.make_breed`). ``pct`` of the island size is
    the emigrant count (``int(S*pct)``; 0 → no migration). Pass a dict as
    ``runner_cache`` to reuse compiled runners across calls. ``mparams``
    is forwarded to a ``takes_params`` breed (runtime mutation rate/sigma
    — see ``ops/pallas_step.make_pallas_breed``); None uses the breed's
    construction-time defaults. ``elitism`` is the epoch-level elite
    carry for breeds that don't apply it themselves (see
    :func:`make_island_epoch`) — leave 0 for XLA breeds built with
    ``make_breed(..., elitism=...)`` and fused Pallas breeds.

    Returns ``(genomes (I,S,L), scores (I,S), generations_executed)``;
    with ``history_gens`` set, a trailing on-device history buffer
    (``(history_gens, telemetry.NUM_STATS)``, epoch-granularity rows —
    the remainder-generations call continues the same buffer and stall
    counter) making it a 4-tuple.
    """
    I, S, L = stacked.shape
    if m < 1:
        raise ValueError("migration interval m must be >= 1")
    if not (0.0 <= pct <= 1.0):
        raise ValueError("migration pct must be in [0, 1]")
    breed = step_or_breed
    count = int(S * pct)
    epochs, rem = divmod(n, m)
    tgt = jnp.float32(jnp.inf if target is None else target)

    island_keys = jax.random.split(key, I + 1)
    mig_key, island_keys = island_keys[0], island_keys[1:]

    if mesh is not None and I % mesh.devices.size != 0:
        raise ValueError(
            f"islands ({I}) must be a multiple of mesh devices "
            f"({mesh.devices.size})"
        )

    def cached(tag, mm, cc, build):
        if runner_cache is None:
            return build()
        # Role-prefixed namespace: the runner cache is the engine's
        # shared ``_compiled`` dict, so island keys must be structurally
        # disjoint from every other role's keys (see the collision test
        # in tests/test_serving.py).
        ck = (
            "islands/" + tag, mm, cc, topology, mesh, axis_name, breed,
            obj, elitism, history_gens,
        )
        if ck not in runner_cache:
            runner_cache[ck] = build()
        return runner_cache[ck]

    runner = cached(
        "main", m, count,
        lambda: build_runner(
            breed, obj, m=m, count=count, topology=topology, mesh=mesh,
            axis_name=axis_name, elitism=elitism, history_gens=history_gens,
        ),
    )
    if mesh is not None:
        # make_array_from_callback rather than device_put: each process
        # supplies only its addressable shards, so the same code works on
        # a multi-host mesh (device_put rejects shardings with
        # non-addressable devices). Host arrays are identical on every
        # process (same PRNG keys), so the callback slices consistently.
        stacked = _shard_host_array(
            stacked, NamedSharding(mesh, P(axis_name, None, None))
        )
        island_keys = _shard_host_array(
            island_keys, NamedSharding(mesh, P(axis_name))
        )
    if getattr(runner, "takes_params", False):
        if mparams is None:
            mparams = getattr(breed, "default_params", None)
        extra = (mparams,)
    else:
        extra = ()
    if history_gens is not None:
        # best0 = -inf so the first epoch registers as an improvement
        # (stall 0) — the telemetry carry, threaded through both calls.
        tstate = (
            jnp.int32(0), jnp.float32(-jnp.inf), jnp.int32(0),
            _tl.history_init(history_gens),
        )
        genomes, scores, epochs_done, best_t, stall_t, hist = runner(
            stacked, island_keys, mig_key, jnp.int32(epochs), tgt,
            *tstate, *extra,
        )
    else:
        hist = None
        genomes, scores, epochs_done = runner(
            stacked, island_keys, mig_key, jnp.int32(epochs), tgt, *extra
        )
    gens = int(epochs_done) * m

    # Remainder generations (< m) run without a following migration. Only
    # executed when the epoch loop wasn't cut short by the target.
    from libpga_tpu.parallel.mesh import global_max

    if rem > 0 and (target is None or global_max(scores, mesh) < float(tgt)):
        rem_runner = cached(
            "rem", rem, 0,
            lambda: build_runner(
                breed, obj, m=rem, count=0, topology=topology, mesh=mesh,
                axis_name=axis_name, elitism=elitism,
                history_gens=history_gens,
            ),
        )
        rem_keys = jax.random.split(jax.random.fold_in(mig_key, 7), I)
        if mesh is not None:
            rem_keys = _shard_host_array(
                rem_keys, NamedSharding(mesh, P(axis_name))
            )
        rem_args = (
            genomes, rem_keys, jax.random.fold_in(mig_key, 11),
            jnp.int32(1), jnp.float32(jnp.inf),
        )
        if history_gens is not None:
            genomes, scores, _, best_t, stall_t, hist = rem_runner(
                *rem_args, jnp.int32(gens), best_t, stall_t, hist, *extra
            )
        else:
            genomes, scores, _ = rem_runner(*rem_args, *extra)
        gens += rem
    if history_gens is not None:
        return genomes, scores, gens, hist
    return genomes, scores, gens
