"""Benchmark history database (ISSUE 17, leg 3).

Fifteen rounds of ``BENCH_r*.json`` artifacts exist as loose files with
three different shapes; this module gives them (and every future bench
run) ONE durable home: a schema-versioned, append-only store of
:class:`PerfSample` records keyed by ``(backend, device_kind, shape,
arm)``, so the repo's performance trajectory is machine-queryable and
the regression sentinel (``perf/detect.py``, ``tools/perf_gate.py``)
has a baseline to compare against.

File conventions match the repo's other durable state (``tuning/db.py``,
``utils/checkpoint``, the fleet spool):

- schema-versioned, refused LOUDLY on mismatch (:class:`PerfSchemaError`
  — a future schema is not guessed at);
- written atomically (temp file + ``os.replace`` — a concurrent reader
  or SIGKILL mid-write can never observe a torn database);
- merges are ASSOCIATIVE and COMMUTATIVE: samples carry a full identity
  (key, metric, round, run id, source) and merge is set-union with
  per-identity conflicts resolved by a total order — merging per-host
  histories in any grouping yields the same database;
- :func:`merge_files` SKIPS torn/partial files and reports (warning +
  returned ``skipped`` list); :meth:`PerfHistory.load` raises
  :class:`PerfHistoryError` naming the path.

The artifact normalizer (:meth:`PerfHistory.ingest_artifact`) speaks
every historical generation: the r01–r06 wrapper shape (``{"cmd", "n",
"parsed", ...}``), the r07–r08 provenance-stamped nested shape, and the
r09+ flat-key shape — plus the schema-2 artifacts ``bench.provenance``
now stamps with ``git_rev``/``run_id``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

#: Largest bench schema whose artifacts the normalizer understands.
#: (0 = the pre-provenance r01–r06 wrapper shape.)
MAX_ARTIFACT_SCHEMA = 2


class PerfHistoryError(RuntimeError):
    """Torn/partial or otherwise unusable perf-history file."""


class PerfSchemaError(PerfHistoryError):
    """Parseable history whose schema_version this code does not speak
    — always refused loudly, never skipped."""


@dataclasses.dataclass(frozen=True)
class PerfKey:
    """The measurement context a sample is only comparable within.
    ``shape`` is the workload geometry (``"1048576x100"``-style when
    derivable, else the arm's flagship-shape marker ``"default"``);
    ``arm`` the bench arm family (``single``/``serving``/``fleet``/
    ``gp``/...)."""

    backend: str
    device_kind: str
    shape: str
    arm: str

    def as_string(self) -> str:
        return (
            f"backend={self.backend}|device={self.device_kind}"
            f"|shape={self.shape}|arm={self.arm}"
        )

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "PerfKey":
        return PerfKey(
            backend=str(d["backend"]), device_kind=str(d["device_kind"]),
            shape=str(d["shape"]), arm=str(d["arm"]),
        )


@dataclasses.dataclass(frozen=True)
class PerfSample:
    """One measured number with enough provenance to audit and order it.

    ``round`` is the BENCH round the sample came from (0 = not a
    numbered artifact, e.g. a gate measurement), ``run_id`` the
    monotonic id ``bench.provenance`` stamps from schema 2 on (0 for
    older artifacts), ``source`` the artifact filename or producing
    tool. Identity is ``(key, metric, round, run_id, source)`` — the
    append-only set the merge unions."""

    key: PerfKey
    metric: str
    value: float
    unit: str = ""
    round: int = 0
    run_id: int = 0
    git_rev: str = ""
    source: str = ""
    artifact_schema: int = 0
    note: str = ""

    def ident(self) -> str:
        return (
            f"{self.key.as_string()}|metric={self.metric}"
            f"|round={self.round}|run={self.run_id}|src={self.source}"
        )

    def _order(self) -> tuple:
        """Total order for same-identity conflicts (two producers
        writing the same identity with different payloads): newer run
        wins, ties break on the value then the serialized payload — so
        ANY merge grouping picks the same winner."""
        return (
            self.run_id, self.round, self.value,
            json.dumps(self.as_dict(), sort_keys=True, default=str),
        )

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key.as_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "PerfSample":
        d = dict(d)
        d["key"] = PerfKey.from_dict(d["key"])
        return PerfSample(**d)


def new_run_id() -> int:
    """Monotonic run-id provenance for bench artifacts: wall-clock
    nanoseconds at stamp time — strictly increasing across a host's
    bench runs (the ingestion order the history's total order uses),
    unique enough to identify a run without coordination."""
    return time.time_ns()


def git_rev(cwd: Optional[str] = None) -> str:
    """Current git revision for artifact provenance, or ``"unknown"``
    (never raises — provenance must not break a bench run)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=cwd,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:
        return "unknown"


# --------------------------------------------------- artifact normalizer

#: Bench-arm families: a flat artifact's top-level ``metric`` name (or a
#: numeric key's prefix) maps onto the arm that produced it.
_ARM_PREFIXES = (
    "serving", "supervised", "fleet", "autotuned", "gp", "streaming",
    "sharded", "tenant", "fairness", "elastic", "session",
)

_SHAPE_RE = re.compile(r"(\d+)x(\d+)")
_ROUND_RE = re.compile(r"r(\d+)")


def _arm_of(metric: str) -> str:
    for p in _ARM_PREFIXES:
        if metric.startswith(p):
            return p
    return "single"


def _shape_of(metric: str) -> str:
    m = _SHAPE_RE.search(metric)
    if m:
        return m.group(0)
    if "1M" in metric:
        return "1048576x100"  # the flagship single-arm shape (bench.POP)
    return "default"


def _pick_primary(top_metric: str, flat: dict) -> str:
    """Pick the artifact's headline metric.

    r09+ artifacts stamp ``metric`` with a shape suffix
    (``sharded_gens_per_sec_65536x64``) while the flat keys omit it, so
    an exact match is tried first, then the suffix-stripped name. Older
    artifacts carry no top-level metric at all; prefer a throughput
    series over the alphabetical accident (``genome_len``).
    """
    if top_metric in flat:
        return top_metric
    stripped = _SHAPE_RE.sub("", top_metric).rstrip("_")
    if stripped in flat:
        return stripped
    if stripped:
        pref = sorted((k for k in flat if k.startswith(stripped)),
                      key=lambda k: ("iqr" in k, k))
        if pref:
            return pref[0]
    for pat in ("generations_per_sec", "gens_per_sec", "runs_per_sec",
                "per_sec"):
        hits = sorted(k for k in flat if pat in k and "iqr" not in k)
        if hits:
            return hits[0]
    return sorted(flat)[0] if flat else ""


def _flatten(prefix: str, obj, out: dict) -> None:
    if isinstance(obj, bool):
        return
    if isinstance(obj, (int, float)):
        out[prefix] = float(obj)
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)


class PerfHistory:
    """In-memory perf history; thread-safe for concurrent ingest vs.
    series reads (the gate's measurement thread pattern)."""

    def __init__(self, samples: Optional[Dict[str, PerfSample]] = None):
        self.samples: Dict[str, PerfSample] = dict(samples or {})
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.samples)

    def add(self, sample: PerfSample) -> None:
        """Insert, keeping the total-order winner on identity conflict
        (so add() and merge() agree)."""
        with self._lock:
            ident = sample.ident()
            cur = self.samples.get(ident)
            if cur is None or sample._order() > cur._order():
                self.samples[ident] = sample

    def merge(self, other: "PerfHistory") -> "PerfHistory":
        """Associative, commutative merge: identity-set union with
        per-identity conflicts resolved by the total order."""
        out = PerfHistory(dict(self.samples))
        for s in other.samples.values():
            out.add(s)
        return out

    def series(
        self, key: PerfKey, metric: str
    ) -> List[PerfSample]:
        """All samples for one ``(key, metric)``, in trajectory order
        (round, then run id) — the regression detector's baseline."""
        ks = key.as_string()
        with self._lock:
            got = [
                s for s in self.samples.values()
                if s.key.as_string() == ks and s.metric == metric
            ]
        return sorted(got, key=lambda s: (s.round, s.run_id, s.source))

    def keys(self) -> List[PerfKey]:
        seen: Dict[str, PerfKey] = {}
        with self._lock:
            for s in self.samples.values():
                seen.setdefault(s.key.as_string(), s.key)
        return [seen[k] for k in sorted(seen)]

    # --------------------------------------------------------- ingestion

    def ingest_artifact(
        self, art: dict, source: str = "<memory>"
    ) -> List[PerfSample]:
        """Normalize one bench artifact (any historical generation)
        into samples and add them. Returns what was added.

        Raises :class:`PerfHistoryError` for an artifact claiming a
        bench schema NEWER than this code understands (the loud-refusal
        stance); everything else degrades gracefully — unknown keys are
        just extra metrics, non-numeric leaves are skipped.
        """
        if not isinstance(art, dict):
            raise PerfHistoryError(f"{source}: artifact is not an object")
        schema = art.get("schema_version", 0)
        if not isinstance(schema, int) or schema > MAX_ARTIFACT_SCHEMA:
            raise PerfHistoryError(
                f"{source}: bench artifact schema_version {schema!r} is "
                f"newer than supported {MAX_ARTIFACT_SCHEMA} — update "
                "libpga_tpu/perf/history.py before ingesting"
            )
        m = _ROUND_RE.search(os.path.basename(source))
        rnd = int(m.group(1)) if m else 0
        # r01–r06 stamped no provenance: those runs predate the ISSUE 3
        # stamp, so backend/device are recorded as unstamped rather
        # than guessed at.
        backend = str(art.get("backend", "unstamped"))
        device = str(art.get("device_kind", "unstamped"))
        run_id = int(art.get("run_id", 0))
        rev = str(art.get("git_rev", ""))
        top_metric = str(art.get("metric", ""))

        flat: dict = {}
        if "parsed" in art and isinstance(art["parsed"], dict):
            parsed = art["parsed"]
            if "value" in parsed and isinstance(
                parsed.get("value"), (int, float)
            ):
                # r01–r06: one primary number + derived extras.
                name = str(parsed.get("metric", "value"))
                flat[name] = float(parsed["value"])
                top_metric = top_metric or name
                for k, v in parsed.items():
                    if k in ("metric", "value", "unit"):
                        continue
                    _flatten(f"{name}.{k}", v, flat)
            else:
                # r07–r08: nested per-config sub-dicts.
                top_metric = top_metric or str(parsed.get("metric", ""))
                _flatten("", parsed, flat)
        for k, v in art.items():
            if k in (
                "schema_version", "run_id", "rc", "n", "parsed", "cmd",
                "tail", "compilation_cache_entries",
            ):
                continue
            _flatten(k, v, flat)

        added: List[PerfSample] = []
        primary = _pick_primary(top_metric, flat)
        for name, value in sorted(flat.items()):
            arm = _arm_of(top_metric or name)
            key = PerfKey(
                backend=backend, device_kind=device,
                shape=_shape_of(f"{top_metric} {name}"), arm=arm,
            )
            s = PerfSample(
                key=key, metric=name, value=value, round=rnd,
                run_id=run_id, git_rev=rev,
                source=os.path.basename(source), artifact_schema=schema,
                note="primary" if name == primary else "",
            )
            self.add(s)
            added.append(s)
        return added

    def ingest_file(self, path: str) -> List[PerfSample]:
        """Ingest one artifact file. Torn/unparseable →
        :class:`PerfHistoryError` naming the path (backfill callers
        skip-and-report, mirroring :func:`merge_files`)."""
        try:
            with open(path, encoding="utf-8") as fh:
                art = json.load(fh)
        except json.JSONDecodeError as exc:
            raise PerfHistoryError(
                f"{path}: torn or partial bench artifact ({exc})"
            ) from exc
        return self.ingest_artifact(art, source=path)

    # ----------------------------------------------------------- file IO

    def to_json(self) -> dict:
        with self._lock:
            return {
                "schema_version": SCHEMA_VERSION,
                "samples": [
                    self.samples[k].as_dict()
                    for k in sorted(self.samples)
                ],
            }

    @staticmethod
    def from_json(data: dict, path: str = "<memory>") -> "PerfHistory":
        if not isinstance(data, dict) or "schema_version" not in data:
            raise PerfHistoryError(
                f"{path}: not a perf history (no schema_version)"
            )
        if data["schema_version"] != SCHEMA_VERSION:
            raise PerfSchemaError(
                f"{path}: perf-history schema_version "
                f"{data['schema_version']!r} != supported "
                f"{SCHEMA_VERSION} — refusing to guess at a different "
                "schema (re-run tools/perf_report.py --backfill)"
            )
        out = PerfHistory()
        for d in data.get("samples", ()):
            try:
                out.add(PerfSample.from_dict(d))
            except (KeyError, TypeError, ValueError) as exc:
                raise PerfHistoryError(
                    f"{path}: malformed sample {d!r}: {exc}"
                ) from exc
        return out

    def save(self, path: str) -> str:
        """Atomic write: temp file in the same directory +
        ``os.replace`` — the checkpoint/spool/tuning-DB durability
        convention (and the ``spool-atomic-write`` lint rule)."""
        final = os.path.abspath(path)
        os.makedirs(os.path.dirname(final) or ".", exist_ok=True)
        tmp = f"{final}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.to_json(), fh, indent=1, default=str)
                fh.write("\n")
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return final

    @staticmethod
    def load(path: str) -> "PerfHistory":
        """Load one history file. Torn/unparseable →
        :class:`PerfHistoryError` naming the path; schema mismatch →
        :class:`PerfSchemaError` (loud refusal)."""
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except json.JSONDecodeError as exc:
            raise PerfHistoryError(
                f"{path}: torn or partial perf history ({exc})"
            ) from exc
        return PerfHistory.from_json(data, path=path)


def merge_files(paths: Sequence[str]) -> Tuple[PerfHistory, List[str]]:
    """Merge several history files (associative — any grouping of the
    same files yields the same database). Torn/partial files are
    SKIPPED and reported; a parseable file with a mismatched schema
    refuses loudly; a merely missing file is silently fine."""
    out = PerfHistory()
    skipped: List[str] = []
    for p in paths:
        try:
            out = out.merge(PerfHistory.load(p))
        except PerfSchemaError:
            raise  # loud refusal: a future schema is not guessed at
        except FileNotFoundError:
            continue
        except PerfHistoryError:
            skipped.append(p)
    if skipped:
        warnings.warn(
            f"perf-history merge skipped {len(skipped)} torn/partial "
            f"file(s): {skipped}",
            stacklevel=2,
        )
    return out, skipped


__all__ = [
    "SCHEMA_VERSION",
    "MAX_ARTIFACT_SCHEMA",
    "PerfHistoryError",
    "PerfSchemaError",
    "PerfKey",
    "PerfSample",
    "PerfHistory",
    "merge_files",
    "new_run_id",
    "git_rev",
]
