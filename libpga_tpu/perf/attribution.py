"""Per-stage device-time attribution (ISSUE 17, leg 2).

The round-7 ``pga/<stage>`` trace spans (``utils/telemetry.span``) gave
profiles a readable per-stage timeline; as of this round every span
ALSO feeds its host-side duration into the metrics registry as a
``perf.stage_ms{stage=}`` histogram (``utils/metrics.observe_stage_ms``)
— so the BENCH_r13 "evaluator = 94% of a GP generation" number is now a
standing query over the registry instead of a one-off profile read.

This module is the query side: :func:`stage_breakdown` folds the
``perf.stage_ms`` series of a registry snapshot into total
milliseconds and shares per stage, and :func:`stage_shares` maps the
engine's stage names onto the report buckets (breed/eval/selection/
collective/host) a generation decomposes into.

Host-level semantics, inherited from ``span``: a stage's time is the
time its DISPATCH held the host, so under the fused run loop the whole
generation lands in ``run`` (one dispatch), while the step-by-step API
(``evaluate``/``select_breed``/``mutate``/``swap``) and the island/
sharded runners decompose. That is the honest accounting off-device;
on-chip decomposition of the fused kernel comes from the profiler
trace the spans annotate.
"""

from __future__ import annotations

from typing import Dict, Optional

#: Engine stage → report bucket. ``select_breed`` covers both the
#: selection matmuls and the crossover (one fused dispatch);
#: ``migrate`` is the collective bucket (ring ppermute / shard sync);
#: ``checkpoint`` is host I/O.
STAGE_BUCKETS = {
    "run": "run",
    "run_islands": "run",
    "evaluate": "eval",
    "select_breed": "breed",
    "mutate": "breed",
    "swap": "breed",
    "migrate": "collective",
    "checkpoint": "host",
}


def stage_breakdown(snapshot: Optional[dict] = None) -> Dict[str, dict]:
    """Fold a registry snapshot's ``perf.stage_ms`` histograms into
    ``{stage: {"ms": total, "count": n, "share": fraction}}``. With no
    snapshot given, reads the live process registry."""
    if snapshot is None:
        from libpga_tpu.utils import metrics as _metrics

        snapshot = _metrics.REGISTRY.snapshot()
    out: Dict[str, dict] = {}
    for rec in snapshot.get("histograms", ()):
        if rec.get("name") != "perf.stage_ms":
            continue
        stage = dict(rec.get("labels") or {}).get("stage", "?")
        cur = out.setdefault(stage, {"ms": 0.0, "count": 0})
        cur["ms"] += float(rec.get("sum", 0.0))
        cur["count"] += int(rec.get("count", 0))
    total = sum(v["ms"] for v in out.values())
    for v in out.values():
        v["share"] = (v["ms"] / total) if total > 0 else 0.0
    return out


def stage_shares(snapshot: Optional[dict] = None) -> Dict[str, float]:
    """The generation-decomposition view: per-bucket (breed/eval/
    collective/host/run) share of attributed stage time. Stages outside
    :data:`STAGE_BUCKETS` fold into ``host`` (they held the host)."""
    shares: Dict[str, float] = {}
    for stage, rec in stage_breakdown(snapshot).items():
        bucket = STAGE_BUCKETS.get(stage, "host")
        shares[bucket] = shares.get(bucket, 0.0) + rec["share"]
    return shares


__all__ = ["STAGE_BUCKETS", "stage_breakdown", "stage_shares"]
