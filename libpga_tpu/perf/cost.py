"""Analytic cost model + roofline program reports (ISSUE 17, leg 1).

A *program report* answers, for one resolved program shape, the
questions a chip round keeps re-deriving by hand: how many FLOPs and
HBM bytes does a generation cost, what VMEM does the kernel hold, which
roof (compute or bandwidth) bounds it, and — paired with a measured
gens/sec — what fraction of that roof the program achieves. Everything
derives from the DRY-RUN plan resolvers (``ops/pallas_step.kernel_plan``
/ ``ops/gp_eval.gp_eval_plan``) through their colocated cost hooks
(``plan_cost`` / ``gp_plan_cost``), so reports need **no hardware**: a
CPU session can predict the chip's roofline for any shape, and the
model can never describe a kernel the factory wouldn't build.

Reports are keyed exactly like the tuning database
(``tuning/db.TuningKey``: pop, len, dtype, backend, device_kind,
objective class, operator kinds) — a report and a tuning entry for the
same signature describe the same program.

The FLOPs model counts only the selection matmuls (the kernel's MXU
work) and the HBM model is the launch-IO floor — both deliberately
UNDERCOUNT, so achieved-fraction-of-roofline never flatters (the same
stance as ``bench.hbm_bytes_per_gen``, which this module now backs).
"""

from __future__ import annotations

from typing import Optional

#: Per-chip peaks (FLOP/s at the matmul dtype the kernel feeds the MXU
#: — bf16 on every current path — and HBM bytes/s). Keyed by JAX
#: ``device_kind`` strings; unknown kinds (and CPU hosts predicting for
#: the chip) fall back to :data:`DEFAULT_DEVICE` — the repo's measured
#: chip (BASELINE.md) — with ``peaks_assumed=True`` stamped on the
#: report so a number computed off-device can't masquerade as
#: calibrated.
DEVICE_PEAKS = {
    "TPU v5e": (197e12, 819e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v4 lite": (137e12, 614e9),
}
DEFAULT_DEVICE = "TPU v5e"


def device_peaks(device_kind: Optional[str]) -> tuple:
    """``(peak_flops, peak_hbm_bytes_per_sec, assumed)`` for a device
    kind; ``assumed`` is True when the kind missed the table and the
    default chip's peaks were substituted."""
    if device_kind in DEVICE_PEAKS:
        return DEVICE_PEAKS[device_kind] + (False,)
    return DEVICE_PEAKS[DEFAULT_DEVICE] + (True,)


def roofline(
    flops_per_gen: int,
    hbm_bytes_per_gen: int,
    device_kind: Optional[str] = None,
) -> dict:
    """Roofline bound for one generation's cost: the attainable
    gens/sec under each roof, their min, and which roof binds.
    ``arithmetic_intensity`` (FLOPs/byte) against the chip's ridge
    point (peak_flops/peak_bw) tells the same story in roofline-plot
    coordinates."""
    peak_f, peak_b, assumed = device_peaks(device_kind)
    compute_gps = peak_f / flops_per_gen if flops_per_gen else float("inf")
    memory_gps = peak_b / hbm_bytes_per_gen if hbm_bytes_per_gen else float(
        "inf"
    )
    bound_gps = min(compute_gps, memory_gps)
    return {
        "roofline_gens_per_sec": bound_gps,
        "bound": "compute" if compute_gps <= memory_gps else "memory",
        "compute_bound_gens_per_sec": compute_gps,
        "memory_bound_gens_per_sec": memory_gps,
        "arithmetic_intensity": (
            flops_per_gen / hbm_bytes_per_gen if hbm_bytes_per_gen else None
        ),
        "ridge_intensity": peak_f / peak_b,
        "peak_flops": peak_f,
        "peak_hbm_bytes_per_sec": peak_b,
        "peaks_device": device_kind if not assumed else DEFAULT_DEVICE,
        "peaks_assumed": assumed,
    }


def breed_report(
    pop: int,
    genome_len: int,
    *,
    gene_dtype=None,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
    crossover_kind="uniform",
    mutate_kind="point",
    deme_size: Optional[int] = None,
    demes_per_step: Optional[int] = None,
    layout: Optional[str] = None,
    subblock: Optional[int] = None,
    generations_per_launch: Optional[int] = None,
    const_carrying: bool = False,
    device_kind: Optional[str] = None,
) -> dict:
    """Program report for one breeding shape.

    Resolves the FUSED plan via ``kernel_plan`` (the factory's own
    dry-run oracle — works on any backend) and attaches per-generation
    FLOPs/bytes/VMEM plus the roofline bound. Where the factory would
    decline the shape (``path="xla"``), the report still renders —
    with ``plan=None`` and no roofline, because the XLA step path has
    no closed-form cost model — so callers can always key and log it.
    """
    import jax.numpy as jnp
    import numpy as np

    from libpga_tpu.ops.pallas_step import kernel_plan, plan_cost

    gene_dtype = jnp.float32 if gene_dtype is None else gene_dtype
    try:
        plan = kernel_plan(
            pop, genome_len,
            deme_size=deme_size,
            tournament_size=tournament_size,
            selection_kind=selection_kind,
            selection_param=selection_param,
            crossover_kind=crossover_kind,
            mutate_kind=mutate_kind,
            gene_dtype=gene_dtype,
            demes_per_step=demes_per_step,
            layout=layout,
            subblock=subblock,
            const_carrying=const_carrying,
        )
    except (ValueError, TypeError):
        # Exotic operator objects / inadmissible explicit knobs: report
        # the XLA path rather than refusing to report at all.
        plan = None
    report = {
        "report": "breed",
        "pop": int(pop),
        "genome_len": int(genome_len),
        "dtype": np.dtype(gene_dtype).name,
        "path": "fused" if plan is not None else "xla",
        "plan": plan,
    }
    if plan is not None:
        cost = plan_cost(
            plan, gene_dtype=gene_dtype,
            generations_per_launch=generations_per_launch,
        )
        report.update(cost)
        report.update(roofline(
            cost["flops_per_gen"], cost["hbm_bytes_per_gen"], device_kind,
        ))
    return report


def gp_report(
    pop: int,
    gp,
    n_samples: int,
    *,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
    live_length: Optional[float] = None,
    device_kind: Optional[str] = None,
) -> dict:
    """Program report for one GP-evaluation shape (``gp`` is a
    ``gp/encoding.GPConfig``). One *evaluation* of the whole population
    is the GP analog of a generation, so the roofline fields read in
    the same units (evals/sec ≡ gens/sec). ``live_length`` is the
    measured mean post-compaction live length of the population being
    reported (``gp/optimize.mean_live_length``) — with it, an
    optimizing config's FLOPs price the trips the evaluator actually
    runs instead of the ``max_nodes`` cap (ISSUE 19), keeping
    ``achieved()`` roofline fractions honest on the fast path."""
    from libpga_tpu.ops.gp_eval import gp_eval_plan, gp_plan_cost

    plan = gp_eval_plan(
        pop, gp, n_samples,
        stack_depth=stack_depth, opcode_block=opcode_block,
        dispatch=dispatch,
    )
    report = {
        "report": "gp_eval",
        "pop": int(pop),
        "max_nodes": int(gp.max_nodes),
        "n_samples": int(n_samples),
        "path": plan["path"] if plan is not None else "xla",
        "plan": plan,
    }
    if plan is not None:
        cost = gp_plan_cost(
            plan, pop, gp, n_samples, live_length=live_length,
        )
        report["flops_per_gen"] = cost["flops_per_eval"]
        report["hbm_bytes_per_gen"] = cost["hbm_bytes_per_eval"]
        report["vmem_bytes"] = cost["vmem_bytes"]
        report["batch_lanes"] = cost["batch_lanes"]
        report["tokens_per_program"] = cost["tokens_per_program"]
        report.update(roofline(
            cost["flops_per_eval"], cost["hbm_bytes_per_eval"], device_kind,
        ))
    return report


def achieved(report: dict, measured_gens_per_sec: float) -> dict:
    """Pair a report with a measured gens/sec: achieved FLOP/s and HBM
    bytes/s, their fractions of the chip peaks, and the
    fraction-of-roofline (the number that replaces the ad-hoc
    ``selection_matmul_mfu`` note in bench artifacts — against the
    BINDING roof, so 1.0 means "at the model's limit" whichever roof
    that is)."""
    gps = float(measured_gens_per_sec)
    out = {"measured_gens_per_sec": gps}
    if report.get("flops_per_gen") is None:
        return out
    achieved_flops = gps * report["flops_per_gen"]
    achieved_hbm = gps * report["hbm_bytes_per_gen"]
    out.update(
        achieved_flops=achieved_flops,
        achieved_hbm_bytes_per_sec=achieved_hbm,
        flops_frac_of_peak=achieved_flops / report["peak_flops"],
        hbm_frac_of_peak=achieved_hbm / report["peak_hbm_bytes_per_sec"],
        roofline_frac=gps / report["roofline_gens_per_sec"],
    )
    return out


__all__ = [
    "DEVICE_PEAKS",
    "DEFAULT_DEVICE",
    "device_peaks",
    "roofline",
    "breed_report",
    "gp_report",
    "achieved",
]
