"""Performance observatory (ISSUE 17): analytic roofline-attributed
program reports (``cost``), per-stage device-time attribution
(``attribution``), and the append-only benchmark history with its
drift-floor-aware regression sentinel (``history`` / ``detect``).

Entry points: ``PGA.program_report`` (engine), ``tools/perf_report.py``
(history backfill/table), ``tools/perf_gate.py`` (the ci.sh stage-17
regression gate)."""

from libpga_tpu.perf.attribution import (  # noqa: F401
    STAGE_BUCKETS,
    stage_breakdown,
    stage_shares,
)
from libpga_tpu.perf.cost import (  # noqa: F401
    DEFAULT_DEVICE,
    DEVICE_PEAKS,
    achieved,
    breed_report,
    device_peaks,
    gp_report,
    roofline,
)
from libpga_tpu.perf.detect import (  # noqa: F401
    CROSS_PROCESS_FLOOR,
    DRIFT_FLOOR,
    MIN_SAMPLES,
    Verdict,
    detect,
)
from libpga_tpu.perf.history import (  # noqa: F401
    MAX_ARTIFACT_SCHEMA,
    PerfHistory,
    PerfHistoryError,
    PerfKey,
    PerfSample,
    PerfSchemaError,
    git_rev,
    merge_files,
    new_run_id,
)

SCHEMA_VERSION = 1  # re-exported history schema (perf/history.py)

__all__ = [
    "STAGE_BUCKETS", "stage_breakdown", "stage_shares",
    "DEFAULT_DEVICE", "DEVICE_PEAKS", "achieved", "breed_report",
    "device_peaks", "gp_report", "roofline",
    "CROSS_PROCESS_FLOOR", "DRIFT_FLOOR", "MIN_SAMPLES", "Verdict",
    "detect",
    "MAX_ARTIFACT_SCHEMA", "PerfHistory", "PerfHistoryError", "PerfKey",
    "PerfSample", "PerfSchemaError", "git_rev", "merge_files",
    "new_run_id",
]
