"""Drift-floor-aware regression detection (ISSUE 17, leg 3).

The measurement doctrine this encodes is BASELINE.md's, learned the
hard way over four bench rounds: same-process interleaved medians drift
~4% on the CPU gate host (and up to ±15% ACROSS processes on the
tunneled chip), so a "regression" smaller than the relevant floor is
noise, and the noise of the baseline itself (its IQR) must widen the
bar further. The math is ``utils/profiling``'s — the same
``_median``/``_rel_ci`` (half-IQR over median) the interleaved-medians
verdict protocol uses — so the sentinel and the bench speak one
statistics dialect.

A verdict only says REGRESSED when the current measurement falls below
``median(baseline) · (1 − max(drift_floor, 2·rel_ci(baseline)))`` with
at least ``min_samples`` finite baseline points — otherwise it reports
the (named) reason it abstained, because a gate that fails on noise
trains people to ignore it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

#: Same-process interleaved drift floor on the CPU gate host
#: (BASELINE.md: ~4%). Effects below this are indistinguishable from
#: run-to-run noise even under the interleaved protocol.
DRIFT_FLOOR = 0.04

#: Cross-process floor (BASELINE.md: ±15% across processes on the
#: tunneled chip) — the right bar when the baseline was recorded by a
#: DIFFERENT process/host than the current measurement, which is
#: exactly the committed-history case ``tools/perf_gate.py`` gates.
CROSS_PROCESS_FLOOR = 0.15

#: How many finite baseline samples a verdict needs before it may
#: accuse: below this, ``_rel_ci`` is infinite/degenerate and the
#: verdict abstains as "baselining".
MIN_SAMPLES = 3


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One regression check's full reasoning — every number a human
    needs to audit the accusation (or the abstention)."""

    metric: str
    current: float
    baseline_median: Optional[float]
    rel_ci: Optional[float]
    threshold: Optional[float]
    ratio: Optional[float]
    n_baseline: int
    regressed: bool
    reason: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def detect(
    baseline: Sequence[float],
    current: float,
    *,
    metric: str = "gens_per_sec",
    drift_floor: float = DRIFT_FLOOR,
    min_samples: int = MIN_SAMPLES,
    higher_is_better: bool = True,
) -> Verdict:
    """Judge ``current`` against a baseline trajectory.

    NaN/inf baseline points are dropped (a torn artifact or a failed
    round must not poison the median — the IQR-window edge case the
    tests pin). The bar is ``max(drift_floor, 2·rel_ci)``: the floor
    covers environment drift the baseline can't see, the doubled
    half-IQR covers the baseline's own spread (±rel_ci is the band one
    median wanders in; 2× keeps a one-sided excursion from accusing).
    """
    from libpga_tpu.utils.profiling import _median, _rel_ci

    kept = sorted(
        float(x) for x in baseline if not (math.isnan(x) or math.isinf(x))
    )
    cur = float(current)
    if math.isnan(cur) or math.isinf(cur):
        return Verdict(
            metric=metric, current=cur, baseline_median=None, rel_ci=None,
            threshold=None, ratio=None, n_baseline=len(kept),
            regressed=False, reason="current measurement is not finite",
        )
    if len(kept) < max(min_samples, 2):
        return Verdict(
            metric=metric, current=cur, baseline_median=None, rel_ci=None,
            threshold=None, ratio=None, n_baseline=len(kept),
            regressed=False,
            reason=f"baselining ({len(kept)} finite samples < "
                   f"{max(min_samples, 2)})",
        )
    med = _median(kept)
    rci = _rel_ci(kept)
    if med <= 0 or math.isinf(rci):
        return Verdict(
            metric=metric, current=cur, baseline_median=med, rel_ci=None,
            threshold=None, ratio=None, n_baseline=len(kept),
            regressed=False, reason="degenerate baseline (median <= 0)",
        )
    threshold = max(float(drift_floor), 2.0 * rci)
    ratio = cur / med
    if higher_is_better:
        regressed = ratio < 1.0 - threshold
    else:
        regressed = ratio > 1.0 + threshold
    if regressed:
        reason = (
            f"{metric}: {cur:.4g} vs baseline median {med:.4g} "
            f"(ratio {ratio:.3f}) breaches the "
            f"{threshold:.1%} bar (floor {drift_floor:.0%}, "
            f"2x rel_ci {2 * rci:.1%}, n={len(kept)})"
        )
    else:
        reason = (
            f"within the {threshold:.1%} bar "
            f"(ratio {ratio:.3f}, n={len(kept)})"
        )
    return Verdict(
        metric=metric, current=cur, baseline_median=med, rel_ci=rci,
        threshold=threshold, ratio=ratio, n_baseline=len(kept),
        regressed=regressed, reason=reason,
    )


__all__ = ["DRIFT_FLOOR", "CROSS_PROCESS_FLOOR", "MIN_SAMPLES",
           "Verdict", "detect"]
