"""Tenant identity (ISSUE 14).

The north star is a service handling millions of users, and every
fairness/quota/admission policy (ROADMAP item 1) presupposes that each
unit of work — a serving ticket, a fleet batch, a streaming session —
knows which TENANT it belongs to. This module is the single source for
that identity: one validated, label-safe string that rides every
observability surface (metric labels, trace-span attributes, event
records, result metas, suspended-session sidecars) without ever
entering a traced program — attribution is host-side by construction,
so the tenant-on and tenant-off paths lower byte-identical StableHLO
(pinned via ``analysis.fingerprint`` in ``tests/test_tenancy.py``).

Rules:

- ``None`` means "no tenant stated" and resolves to :data:`ANON` — the
  default tenant every pre-tenancy caller lands in, so enabling
  attribution never changes behavior, only labeling;
- explicit ids must be Prometheus-label-safe (``[A-Za-z0-9_.-]``, 1-64
  chars, not starting with a dot or dash) — anything else raises at the
  API boundary rather than poisoning an exposition downstream;
- ids beginning with ``_`` are RESERVED for the library (the metrics
  registry's cardinality-overflow bucket is ``_overflow``).
"""

from __future__ import annotations

import re
from typing import Optional

#: The default tenant: work submitted without an identity.
ANON = "anon"

#: The registry's label-cardinality overflow bucket (a reserved id —
#: clients can never submit as it, so an ``_overflow`` label value in an
#: exposition is always the guard speaking, never a tenant).
OVERFLOW = "_overflow"

_TENANT_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.-]{0,63}$")


def validate_tenant(tenant: Optional[str]) -> str:
    """Resolve and validate a tenant id at an API boundary.

    ``None`` → :data:`ANON`. Explicit ids must match the label-safe
    charset and must not use the reserved ``_``-prefix; violations
    raise ``ValueError`` naming the rule, so a misbehaving client is
    rejected at submit time instead of corrupting the exposition."""
    if tenant is None:
        return ANON
    tenant = str(tenant)
    if not _TENANT_RE.match(tenant):
        raise ValueError(
            f"invalid tenant id {tenant!r}: must be 1-64 chars of "
            "[A-Za-z0-9_.-] starting with a letter, digit or underscore"
        )
    if tenant.startswith("_"):
        raise ValueError(
            f"invalid tenant id {tenant!r}: the '_' prefix is reserved "
            "for library-internal label values (e.g. the cardinality "
            "overflow bucket)"
        )
    return tenant
