"""Runtime validation mode — the TPU stand-in for a device sanitizer.

The reference's only correctness net is ``CUDA_CALL`` exit-on-error
(``/root/reference/src/pga.cu:24-31``); CUDA users reach for
compute-sanitizer when device code misbehaves. There is no sanitizer to
point at a Mosaic kernel, so this module provides the equivalent
observability the survey's aux-subsystem inventory calls for (§5 "race
detection / sanitizers"): with ``PGAConfig(validate=True)`` the engine
cross-checks every state-installing operation against the invariants
the kernels promise, on REAL outputs, using the independent XLA
evaluation path as the oracle:

- **gene domain**: genomes finite and inside [0, 1) — point/gaussian
  mutation clip there, uniform/order crossover only move parent genes;
  a value outside means PRNG/selection/layout corruption;
- **score consistency**: stored scores must equal the objective
  re-evaluated on the stored genomes through the XLA path (``evaluate``
  with the plain rowwise/per-genome form) — catching fused-kernel score
  drift, riffle-layout mismatches between the genome and score outputs,
  and stale-score bugs, the exact class of defect a miscompiled kernel
  produces;
- **shape/size**: population dimensions unchanged by breeding.

Checks run on host after the jitted step completes (validation mode is
a debug tool; it adds a device→host copy + one XLA evaluation per
checked operation and is OFF by default). On a multi-process mesh the
engine validates only populations fully addressable from this process
(every process runs the same engine calls, so each validates its own).
Failures raise :class:`ValidationError` naming the operation and the
first offending population — instead of the silently-wrong populations
a corrupted kernel would otherwise evolve for hours.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np


class ValidationError(AssertionError):
    """An engine-state invariant failed under ``PGAConfig(validate=True)``."""


def check_population(
    obj: Optional[Callable],
    genomes,
    scores,
    *,
    where: str,
    index: int = 0,
    atol: float = 5e-2,
) -> None:
    """Validate one population's invariants; raise ValidationError.

    ``scores`` may be None (not yet evaluated — e.g. right after
    ``swap_generations``, whose -inf reset is deliberate). ``atol`` is
    absolute score tolerance: fused evaluation accumulates in f32 but
    bf16 genes and the hi/lo selection split mean reductions can differ
    from the XLA oracle by ~1e-2 at 100-gene sums.
    """
    g = np.asarray(genomes, dtype=np.float32)
    if not np.isfinite(g).all():
        raise ValidationError(
            f"{where}: population {index} genomes contain "
            f"{np.count_nonzero(~np.isfinite(g))} non-finite genes"
        )
    lo, hi = float(g.min(initial=0.0)), float(g.max(initial=0.0))
    if lo < 0.0 or hi > 1.0:
        raise ValidationError(
            f"{where}: population {index} genes outside [0, 1): "
            f"min {lo}, max {hi}"
        )
    if scores is None or obj is None:
        return
    s = np.asarray(scores, dtype=np.float32)
    if s.shape != (g.shape[0],):
        raise ValidationError(
            f"{where}: population {index} scores shape {s.shape} != "
            f"({g.shape[0]},)"
        )
    if np.isnan(s).any():
        raise ValidationError(
            f"{where}: population {index} scores contain NaN"
        )
    live = np.isfinite(s)
    if not live.any():
        return  # all -inf: not yet evaluated (staged swap)
    from libpga_tpu.ops.evaluate import evaluate as _evaluate

    oracle = np.asarray(_evaluate(obj, jnp.asarray(g[live])))
    drift = np.abs(oracle - s[live])
    worst = float(drift.max(initial=0.0))
    if worst > atol:
        k = int(drift.argmax())
        raise ValidationError(
            f"{where}: population {index} scores drifted from the XLA "
            f"oracle (worst |Δ| {worst:.4g} at live row {k}: stored "
            f"{s[live][k]:.6g}, re-evaluated {oracle[k]:.6g}) — fused "
            "kernel scores inconsistent with stored genomes"
        )
