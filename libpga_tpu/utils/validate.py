"""Runtime validation mode — the TPU stand-in for a device sanitizer.

The reference's only correctness net is ``CUDA_CALL`` exit-on-error
(``/root/reference/src/pga.cu:24-31``); CUDA users reach for
compute-sanitizer when device code misbehaves. There is no sanitizer to
point at a Mosaic kernel, so this module provides the equivalent
observability the survey's aux-subsystem inventory calls for (§5 "race
detection / sanitizers"): with ``PGAConfig(validate=True)`` the engine
cross-checks every state-installing operation against the invariants
the kernels promise, on REAL outputs, using the independent XLA
evaluation path as the oracle:

- **gene domain**: genomes finite and inside [0, 1) — point/gaussian
  mutation clip there, uniform/order crossover only move parent genes;
  a value outside means PRNG/selection/layout corruption;
- **score consistency**: stored scores must equal the objective
  re-evaluated on the stored genomes through the XLA path (``evaluate``
  with the plain rowwise/per-genome form) — catching fused-kernel score
  drift, riffle-layout mismatches between the genome and score outputs,
  and stale-score bugs, the exact class of defect a miscompiled kernel
  produces;
- **shape/size**: population dimensions unchanged by breeding.

Checks run on host after the jitted step completes (validation mode is
a debug tool; it adds a device→host copy + one XLA evaluation per
checked operation and is OFF by default). On a multi-process mesh the
engine validates only populations fully addressable from this process
(every process runs the same engine calls, so each validates its own).
Failures raise :class:`ValidationError` naming the operation and the
first offending population — instead of the silently-wrong populations
a corrupted kernel would otherwise evolve for hours.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np


class ValidationError(AssertionError):
    """An engine-state invariant failed under ``PGAConfig(validate=True)``."""


def check_population(
    obj: Optional[Callable],
    genomes,
    scores,
    *,
    where: str,
    index: int = 0,
    atol: Optional[float] = None,
    rtol: Optional[float] = None,
) -> None:
    """Validate one population's invariants; raise ValidationError.

    ``scores`` may be None (not yet evaluated — e.g. right after
    ``swap_generations``, whose -inf reset is deliberate; the all--inf
    case is likewise skipped, but a PARTIAL non-finite score pattern is
    itself a failure — that is what a stale/overflowed row looks like).
    Score drift is judged against ``atol + rtol·|oracle|``. The default
    tolerance is DTYPE-AWARE in BOTH terms: bf16 genes drift absolutely
    (~1e-2 at 100-gene sums — each gene carries ~2^-9 rounding) and
    relatively at large magnitudes, so they keep atol 5e-2 / rtol 1e-3;
    f32 genomes share the oracle's exact inputs and differ only by f32
    summation order (~sqrt(n)·eps relative ≈ 1e-6 at n=100, and the
    fused one-hot TSP matmul's documented divergence is ≤1.3e-7
    relative), so they get atol 1e-3 / rtol 1e-5 — a 0.01-magnitude
    fused-score error on an f32 OneMax population (a real-bug size for
    a 100-gene sum whose ULP is ~1e-5, oracle magnitude ~50) is caught,
    not absorbed by the relative band.
    """
    raw_dtype = str(getattr(genomes, "dtype", ""))
    if atol is None:
        atol = 5e-2 if raw_dtype == "bfloat16" else 1e-3
    if rtol is None:
        rtol = 1e-3 if raw_dtype == "bfloat16" else 1e-5
    g = np.asarray(genomes, dtype=np.float32)
    if not np.isfinite(g).all():
        raise ValidationError(
            f"{where}: population {index} genomes contain "
            f"{np.count_nonzero(~np.isfinite(g))} non-finite genes"
        )
    if g.size == 0:
        raise ValidationError(f"{where}: population {index} is empty")
    lo, hi = float(g.min()), float(g.max())
    # Operators keep f32 genes strictly below 1 (gaussian clips to
    # 1 - 1e-7; exactly 1.0 would decode city/index L, out of range) —
    # but the bf16 gene cast legitimately rounds values >= 1 - 2^-9 up
    # to exactly 1.0, so the strict bound applies to f32 genomes only.
    too_high = hi > 1.0 if raw_dtype == "bfloat16" else hi >= 1.0
    if lo < 0.0 or too_high:
        raise ValidationError(
            f"{where}: population {index} genes outside [0, 1): "
            f"min {lo}, max {hi}"
        )
    if scores is None:
        return
    s = np.asarray(scores, dtype=np.float32)
    if s.shape != (g.shape[0],):
        raise ValidationError(
            f"{where}: population {index} scores shape {s.shape} != "
            f"({g.shape[0]},)"
        )
    if np.isnan(s).any():
        raise ValidationError(
            f"{where}: population {index} scores contain NaN"
        )
    finite = np.isfinite(s)
    if not finite.any():
        return  # all -inf: not yet evaluated (staged swap)
    if obj is None:
        return
    from libpga_tpu.ops.evaluate import evaluate as _evaluate

    oracle = np.asarray(_evaluate(obj, jnp.asarray(g)))
    # Non-finite stored scores must match the oracle EXACTLY: a
    # hard-constraint objective legitimately returns -inf for
    # infeasible rows (and re-evaluates to the same -inf); a stale or
    # overflowed row does not.
    nf = ~finite
    if nf.any() and not np.array_equal(s[nf], oracle[nf]):
        bad = np.flatnonzero(nf & (s != oracle))
        raise ValidationError(
            f"{where}: population {index} has {bad.size} non-finite "
            f"scores the objective does not reproduce (first at row "
            f"{bad[0]}: stored {s[bad[0]]}, re-evaluated "
            f"{oracle[bad[0]]}) — stale or overflowed rows"
        )
    tol = atol + rtol * np.abs(oracle[finite])
    drift = np.abs(oracle[finite] - s[finite])
    if (drift > tol).any():
        k = int((drift - tol).argmax())
        raise ValidationError(
            f"{where}: population {index} scores drifted from the XLA "
            f"oracle (worst |Δ| {drift[k]:.4g} at finite row {k}: stored "
            f"{s[finite][k]:.6g}, re-evaluated {oracle[finite][k]:.6g}) — "
            "fused kernel scores inconsistent with stored genomes"
        )
