"""In-run telemetry: on-device history, named trace spans, event log.

The reference's entire observability story is one ``printf`` of the best
score inside ``pga_get_best`` (``src/pga.cu:230``); before this module the
port only recorded whole-run wall time (``utils/metrics.py``), so a fused
``lax.while_loop`` run was a black box between launch and return. Three
layers fix that:

1. **On-device per-generation history** — the fused run loops (engine XLA
   path, Pallas one-generation and multi-generation paths, both island
   runners) carry a preallocated ``(max_gens, NUM_STATS)`` float32 buffer
   through the loop carry and write one row per generation (per launch on
   the multi-generation kernel, per migration epoch on the island
   runners — each row of the coarser granularities holds the interval-end
   values) with ``dynamic_update_slice`` / a masked fill. No host round
   trip happens inside the loop; the buffer comes back with the final
   population. Columns: ``HISTORY_COLUMNS`` = best / mean / std fitness,
   a genome-diversity proxy (mean per-gene variance over a bounded row
   sample, :data:`DIVERSITY_SAMPLE_ROWS`), and a stall counter
   (generations since the best score last improved). Enabled by
   ``PGAConfig(telemetry=TelemetryConfig(...))``; when disabled the run
   loops trace to the exact pre-telemetry jaxpr (zero-cost off —
   structurally asserted in ``tests/test_telemetry.py``).

2. **Named trace spans** — :func:`span` wraps every engine stage
   (evaluate, select+breed, mutate, swap, migrate, checkpoint, the fused
   run loops) in ``jax.profiler.TraceAnnotation`` so a
   ``profiling.trace()`` capture shows a readable per-stage host timeline
   instead of anonymous fusions. ``tools/trace_smoke.py`` captures a
   trace and asserts the spans exist.

3. **Structured event log** — :class:`EventLog` appends schema-versioned
   JSONL records (run start/end, compiled-function builds, migration,
   islands epochs, checkpoint saves, validation failures, stall alerts)
   driven off the engine's :class:`~libpga_tpu.utils.metrics.Metrics`
   listener registry plus direct engine hook points. The schema is
   validated by :func:`validate_log` (used by ``tools/ci.sh``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from typing import Dict, List, Optional

import numpy as np

# ----------------------------------------------------------------- schema

#: Per-generation statistics recorded in the on-device history buffer,
#: in column order. ``stall`` is stored as float32 like the rest (one
#: homogeneous buffer keeps the loop carry a single array).
HISTORY_COLUMNS = ("best", "mean", "std", "diversity", "stall")
NUM_STATS = len(HISTORY_COLUMNS)

#: Row cap for the genome-diversity proxy: per-gene variance over at most
#: this many leading rows. A full-population variance would re-read the
#: whole genome matrix every generation (~0.5 ms at 1M×100 f32 — alone
#: most of the <2% overhead budget); a bounded sample keeps the proxy
#: O(1) in population size while staying representative (rows are
#: shuffled every generation on the Pallas path and unordered on the XLA
#: path).
DIVERSITY_SAMPLE_ROWS = 4096

#: JSONL event-log schema version. Bump on any breaking field change.
EVENT_SCHEMA_VERSION = 1

#: Required extra fields per known event kind (beyond the base keys
#: ``schema``/``ts``/``event`` every record carries). Unknown event kinds
#: are allowed — forward compatibility — but must carry the base keys.
EVENT_FIELDS: Dict[str, tuple] = {
    "run_start": ("population_size", "genome_len", "n"),
    "run_end": ("generations", "seconds", "best"),
    "islands_start": ("islands", "n", "m", "pct"),
    "islands_end": ("generations", "seconds", "best"),
    "run_record": ("generations", "population_size", "seconds"),
    "compile": ("what",),
    "batch_admit": ("bucket",),
    "batch_launch": ("bucket", "batch_size"),
    "migration": ("pct",),
    "checkpoint_save": ("path",),
    "validation_failure": ("where", "error"),
    "stall_alert": ("stalled_gens",),
    # Robustness layer (ISSUE 5): injected faults, graceful kernel
    # degradation, supervised/serving retries, poisoned-request routing.
    "fault_injected": ("site", "kind"),
    "degraded": ("what", "error"),
    "retry": ("attempt", "error"),
    "dead_letter": ("bucket", "error"),
    # Serving observability (ISSUE 6): per-ticket latency accounting,
    # SLO breaches, exported metric snapshots, flight-recorder dumps.
    # Population sharding (ISSUE 7): one record per sharded run naming
    # the per-generation cross-shard collective pair's geometry (S-way
    # mesh, S·k-scalar rank-threshold gather, comb-slab ppermute rows).
    "shard_sync": ("shards", "topk", "mix_rows"),
    "ticket_done": ("bucket", "queue_wait_ms", "execute_ms", "e2e_ms"),
    "slo_violation": ("what", "value_ms", "limit_ms"),
    "metrics_snapshot": ("metrics",),
    "flight_dump": ("reason", "records"),
    # Serving fleet (ISSUE 8): coordinator-side worker lifecycle +
    # lease accounting (worker_spawn/death/exit, lease_requeue) and
    # worker-side spool protocol records (lease_claim, worker_drain).
    # Fleet-level dead-lettering reuses the "dead_letter" kind with the
    # batch id as the bucket. ``worker_death``/``lease_requeue`` are
    # the records tools/chaos_smoke.py's fleet stage schema-checks.
    "worker_spawn": ("worker", "pid"),
    "worker_exit": ("worker",),
    "worker_death": ("worker",),
    "worker_drain": ("worker",),
    "lease_claim": ("worker", "batch"),
    "lease_requeue": ("batch", "worker"),
    # Fleet observability (ISSUE 9): cross-process trace spans riding
    # the spool (``trace_span`` records are BOTH the span-log line
    # format and flight-dump embeds), the coordinator's end-to-end
    # ticket verdict with its cross-process breakdown, and straggler
    # detection over the merged per-worker metric snapshots.
    "trace_span": ("span", "t0", "t1"),
    "fleet_ticket_done": ("trace_id", "e2e_ms"),
    "straggler_alert": ("worker", "p95_ms", "fleet_p95_ms"),
    # Self-tuning kernels (ISSUE 10): one record per (shape, resolved
    # knobs) naming the tuning-DB resolution a kernel selection or a
    # serving warm-up applied — the provenance trail of "which config
    # did this signature actually compile".
    "tuned_config": ("population_size", "genome_len", "knobs"),
    # Genetic programming (ISSUE 11): one record per run evolving a
    # GP objective (``gp/sr.py``), naming the postfix encoding — the
    # observability anchor for SR-as-a-service traffic.
    # ISSUE 19 adds the eval fast-path provenance: whether the run's
    # evaluator compacts programs before scoring and which token-step
    # dispatch lattice it resolved.
    "gp_run": (
        "population_size", "max_nodes", "n_ops", "n_vars",
        "optimize", "dispatch",
    ),
    # Streaming evolution service (ISSUE 12): session lifecycle —
    # tenant open, external-evaluation folds at generation boundaries
    # (``where`` names the boundary: step / ask / group_step),
    # suspend-to-spool and resume-from-spool.
    "session_open": ("session", "population_size", "genome_len"),
    "session_fold": ("session", "folded"),
    "session_suspend": ("session", "path"),
    "session_resume": ("session", "path"),
    # Co-batched PBT (ISSUE 12): one record per exploit/explore pass of
    # a SessionGroup — epoch index, how many sessions copied a
    # partner's parameters, and the group's best at the boundary.
    # (Registered by the round-18 lint sweep: the emit site shipped in
    # round 17 without a schema entry — exactly the bug class
    # ``event-kind-registered`` exists for.)
    "pbt_epoch": ("epoch", "exploited", "best"),
    # Tenant-attributed observability (ISSUE 14): one record the first
    # time a tenant id is admitted at a surface (``where`` names it:
    # serving_queue / fleet / session), the multi-window error-budget
    # burn-rate alert (transition-edge, per tenant), and the streaming
    # session lifecycle span — the ``trace_span`` shape carrying the
    # session id, emitted by EvolutionSession's anchored-clock
    # lifecycle trace (open/ask/tell/step/suspend/resume, telescoping
    # so they tile the session's lifetime).
    "tenant_admit": ("tenant", "where"),
    "slo_burn": ("tenant", "fast_burn", "slow_burn"),
    "session_span": ("session", "span", "t0", "t1"),
    # Elastic fleet (ISSUE 15): the scheduling layer's verdicts — a
    # per-tenant quota shed (deterministic QuotaExceeded at submit), a
    # priority preemption (coordinator marks a lower-priority
    # supervised batch; the worker drains it at a chunk boundary and
    # the high-priority batch takes the slot), the autoscaler's
    # spawn/retire decisions (retire always drains, never kills), and
    # one record per scheduler pass that released batches to the spool
    # (deficit-round-robin order; ``queued`` is the fair backlog still
    # held back by the release window).
    "quota_reject": ("tenant", "outstanding", "limit"),
    "preempt": ("batch", "by", "worker"),
    "autoscale_up": ("workers", "reason"),
    "autoscale_down": ("workers", "reason"),
    "sched_round": ("batches", "queued"),
    # Performance observatory (ISSUE 17): one ``perf_report`` per
    # roofline-attributed program report (``PGA.program_report`` /
    # ``perf/cost.py``) — the tuning-DB-style key, the resolved path
    # (fused/xla), and the analytic roofline bound (None on the XLA
    # path, which has no closed-form cost model); one
    # ``perf_regression`` per confirmed regression verdict from the
    # continuous-bench gate (``tools/perf_gate.py`` — always paired
    # with a flight dump carrying the full verdict context).
    "perf_report": ("key", "path", "roofline_gens_per_sec"),
    "perf_regression": ("metric", "current", "baseline", "threshold"),
    # Shared-memory ticket ring (ISSUE 18, ``serving/shm_ring.py``):
    # one ``ring_attach`` per participant that mapped the ring (role =
    # coordinator/worker; the coordinator's also reports whether it
    # replaced a stale predecessor's ring), one ``ring_degraded`` per
    # participant that dropped to pure-spool coordination (torn/CRC
    # failures, attach failure, or an injected ``ring.publish`` fault)
    # — degradation is an event precisely because behavior stays
    # bit-identical and would otherwise be invisible.
    "ring_attach": ("role", "path", "stale_replaced"),
    "ring_degraded": ("role", "reason"),
    # HA coordinator (ISSUE 20, ``serving/ha.py``): one ``leader_elect``
    # per won election (epoch is the fence generation; ``takeover`` is
    # True when the win seized a stale predecessor's lease), one
    # ``leader_fence`` per rejected lower-epoch write (a zombie
    # leader's late batch/ring artifact — ``what`` names the artifact
    # kind, ``epoch``/``fence`` the stale and current generations),
    # one ``coordinator_failover`` per completed takeover rebuild
    # (journaled tickets re-admitted, in-flight batches adopted), and
    # one ``intake_journal_replay`` per journal replay scan (idempotent:
    # ``admitted`` counts first-sightings only, ``skipped`` the
    # already-seen/already-resulted entries).
    "leader_elect": ("epoch", "takeover"),
    "leader_fence": ("what", "epoch", "fence"),
    "coordinator_failover": ("epoch", "readmitted", "adopted"),
    "intake_journal_replay": ("epoch", "admitted", "skipped"),
}


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Telemetry settings for a solver (``PGAConfig(telemetry=...)``).

    Attributes:
      history_gens: row capacity of the on-device history buffer. Runs
        longer than this keep overwriting the LAST row (so it always
        holds the latest generation's stats) and
        :attr:`History.truncated` is set. 0 disables the history carry
        (events/spans only).
      events_path: JSONL event-log path; None disables the event log.
      stall_alert_gens: emit a ``stall_alert`` event after a run whose
        final stall counter (generations since the best score improved)
        is >= this. 0 disables.
    """

    history_gens: int = 1024
    events_path: Optional[str] = None
    stall_alert_gens: int = 0

    def __post_init__(self):
        if self.history_gens < 0:
            raise ValueError("history_gens must be >= 0")
        if self.stall_alert_gens < 0:
            raise ValueError("stall_alert_gens must be >= 0")


# ------------------------------------------------- device-side primitives
#
# These run INSIDE jitted run loops: pure jnp, no host effects. They are
# the one implementation shared by the engine's XLA while_loop, the
# Pallas one-generation and multi-generation run loops, and both island
# runners, so the recorded semantics cannot drift between paths.


def history_init(max_gens: int):
    """Fresh history buffer: NaN rows mark never-written generations."""
    import jax.numpy as jnp

    return jnp.full((max_gens, NUM_STATS), jnp.nan, dtype=jnp.float32)


def stats_row(genomes, scores, best_prev, stall_prev, step=1):
    """One history row from a (P, L) population.

    Returns ``(row (NUM_STATS,), best_next, stall_next)`` where the carry
    scalars are the running best (f32) and the stall counter (int32).
    ``step`` is the number of generations this row accounts for (1 on
    per-generation paths; the launch/epoch width on chunked paths, where
    the stall counter must advance by the whole interval).
    """
    import jax.numpy as jnp

    best = jnp.max(scores)
    mean = jnp.mean(scores)
    std = jnp.std(scores)
    sample = genomes[: min(genomes.shape[0], DIVERSITY_SAMPLE_ROWS)]
    diversity = jnp.mean(jnp.var(sample.astype(jnp.float32), axis=0))
    improved = best > best_prev
    stall = jnp.where(improved, jnp.zeros_like(stall_prev), stall_prev + step)
    row = jnp.stack([best, mean, std, diversity, stall.astype(jnp.float32)])
    return row, jnp.maximum(best, best_prev), stall


def island_stats_row(genomes, scores, best_prev, stall_prev, step=1,
                     axis_name=None):
    """One GLOBAL history row from stacked islands: genomes (I, S, L),
    scores (I, S). Diversity is the mean over islands of the
    within-island per-gene variance (the island-local quantity migration
    acts on), rows capped per island as in :func:`stats_row`.

    ``axis_name`` set = inside ``shard_map``: moments combine across the
    mesh axis with pmax/pmean (equal island sizes per shard, so the mean
    of local means IS the global mean), and every shard computes the
    identical row — required for the replicated history out_spec.
    """
    import jax
    import jax.numpy as jnp

    sample = genomes[:, : min(genomes.shape[1], DIVERSITY_SAMPLE_ROWS)]
    local_div = jnp.mean(jnp.var(sample.astype(jnp.float32), axis=1))
    if axis_name is None:
        best = jnp.max(scores)
        mean = jnp.mean(scores)
        meansq = jnp.mean(scores * scores)
        diversity = local_div
    else:
        best = jax.lax.pmax(jnp.max(scores), axis_name)
        mean = jax.lax.pmean(jnp.mean(scores), axis_name)
        meansq = jax.lax.pmean(jnp.mean(scores * scores), axis_name)
        diversity = jax.lax.pmean(local_div, axis_name)
    std = jnp.sqrt(jnp.maximum(meansq - mean * mean, 0.0))
    improved = best > best_prev
    stall = jnp.where(improved, jnp.zeros_like(stall_prev), stall_prev + step)
    row = jnp.stack([best, mean, std, diversity, stall.astype(jnp.float32)])
    return row, jnp.maximum(best, best_prev), stall


def write_row(buf, gen, row):
    """Write ``row`` at row index ``gen`` (one ``dynamic_update_slice``,
    no host round trip). DUS clamps the start index, so generations past
    the buffer capacity keep overwriting the LAST row — it always holds
    the latest stats; :class:`History` reports the truncation."""
    import jax
    import jax.numpy as jnp

    return jax.lax.dynamic_update_slice(
        buf, row[None, :], (jnp.asarray(gen, jnp.int32), jnp.int32(0))
    )


def fill_rows(buf, start, stop, row):
    """Write ``row`` into rows [start, stop) — the chunked-granularity
    write for multi-generation launches and island epochs, where one
    device step accounts for several generations. A masked select over
    the (small) buffer rather than a dynamic slice: the chunk width is a
    traced value, which ``dynamic_update_slice`` cannot express. The
    start clamps to the last row like :func:`write_row`, so a run past
    the buffer capacity keeps the final row current."""
    import jax.numpy as jnp

    idx = jnp.arange(buf.shape[0], dtype=jnp.int32)
    mask = (idx >= jnp.minimum(start, buf.shape[0] - 1)) & (idx < stop)
    return jnp.where(mask[:, None], row[None, :], buf)


# ------------------------------------------------------ host-side history


class History:
    """Host-side view of one run's recorded history.

    Rows cover the generations actually executed (``len(history)`` =
    ``min(generations, capacity)``); column properties return 1-D numpy
    arrays. Row ``i`` describes the population AFTER generation ``i+1``
    completed (chunked paths: after the interval containing it — every
    row of an interval holds the interval-end values).
    """

    columns = HISTORY_COLUMNS

    def __init__(self, buffer, generations: int):
        buffer = np.asarray(buffer, dtype=np.float32)
        if buffer.ndim != 2 or buffer.shape[1] != NUM_STATS:
            raise ValueError(
                f"history buffer must be (gens, {NUM_STATS}); "
                f"got {buffer.shape}"
            )
        self.capacity = buffer.shape[0]
        self.generations = int(generations)
        self.truncated = self.generations > self.capacity
        self._rows = buffer[: min(self.generations, self.capacity)]

    def __len__(self) -> int:
        return self._rows.shape[0]

    def _col(self, name: str) -> np.ndarray:
        return self._rows[:, HISTORY_COLUMNS.index(name)]

    @property
    def best(self) -> np.ndarray:
        return self._col("best")

    @property
    def mean(self) -> np.ndarray:
        return self._col("mean")

    @property
    def std(self) -> np.ndarray:
        return self._col("std")

    @property
    def diversity(self) -> np.ndarray:
        return self._col("diversity")

    @property
    def stall(self) -> np.ndarray:
        return self._col("stall").astype(np.int32)

    def as_dict(self) -> Dict[str, np.ndarray]:
        return {name: self._col(name) for name in HISTORY_COLUMNS}

    def __repr__(self) -> str:
        if len(self) == 0:
            return "History(empty)"
        return (
            f"History({len(self)} gens, best {self.best[-1]:.4g}, "
            f"stall {int(self.stall[-1])}"
            + (", truncated" if self.truncated else "")
            + ")"
        )


# ----------------------------------------------------------- trace spans

#: Canonical engine-stage span names (without the "pga/" prefix).
#: tools/trace_smoke.py asserts these appear in a captured trace.
SPAN_STAGES = (
    "run", "run_islands", "evaluate", "select_breed", "mutate", "swap",
    "migrate", "checkpoint",
)
SPAN_PREFIX = "pga/"


@contextlib.contextmanager
def span(stage: str):
    """Named trace span around an engine stage: shows up as
    ``pga/<stage>`` in ``jax.profiler`` captures (TensorBoard/Perfetto),
    turning the host timeline into a readable per-stage view. Host-level
    only — it wraps the dispatch, never the traced computation, so it
    cannot perturb any jaxpr. No-ops (cheaply) when no profiler is
    attached; degrades to a plain passthrough if the profiler API is
    unavailable.

    Every span additionally feeds its host-side duration into the
    metrics registry as a ``perf.stage_ms{stage=}`` histogram (ISSUE
    17 per-stage attribution — ``perf/attribution.stage_breakdown``
    folds these into per-stage shares), so a generation's breakdown is
    a standing registry query, not a one-off profile read. The timer is
    host wall time around the DISPATCH — the same host-level contract
    as the annotation itself."""
    t0 = time.perf_counter()
    try:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(SPAN_PREFIX + stage)
        except Exception:  # profiler unavailable — never block the run
            ann = None
        if ann is not None:
            with ann:
                yield
        else:
            yield
    finally:
        from libpga_tpu.utils.metrics import observe_stage_ms

        observe_stage_ms(stage, (time.perf_counter() - t0) * 1e3)


# ------------------------------------------------------------- event log


class EventLog:
    """Append-only JSONL event emitter with a versioned record schema.

    Every record carries ``schema`` (int), ``ts`` (epoch seconds) and
    ``event`` (str) plus event-specific fields (see
    :data:`EVENT_FIELDS`). Lines are flushed per emit so a crashed run
    leaves a readable log (the same durability stance as
    ``utils/checkpoint``). Listener-registry integration:
    :meth:`attach` subscribes to a :class:`~libpga_tpu.utils.metrics.Metrics`
    registry and emits a ``run_record`` per completed run.
    """

    def __init__(self, path: str, *, clock=time.time):
        self.path = path
        self._clock = clock
        self._fh = open(path, "a", encoding="utf-8")
        self._detach = None

    def emit(self, event: str, **fields) -> dict:
        rec = {
            "schema": EVENT_SCHEMA_VERSION,
            "ts": float(self._clock()),
            "event": str(event),
        }
        rec.update(fields)
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        return rec

    def attach(self, metrics) -> None:
        """Emit a ``run_record`` for every run the Metrics registry sees."""
        def on_run(rec):
            self.emit(
                "run_record",
                generations=rec.generations,
                population_size=rec.population_size,
                seconds=rec.seconds,
                generations_per_sec=rec.generations_per_sec,
            )

        metrics.add_listener(on_run)
        self._detach = lambda: metrics.remove_listener(on_run)

    def close(self) -> None:
        if self._detach is not None:
            self._detach()
            self._detach = None
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ------------------------------------------------- cross-process tracing
#
# The fleet's span log (ISSUE 9). A ticket's life crosses at least two
# processes (coordinator intake -> spool wait -> worker claim/execute/
# publish -> coordinator readback), so span timestamps must compose
# across processes WITHOUT trusting wall-clock sync mid-run: every
# process anchors its monotonic clock to wall time ONCE at import
# (:data:`_MONO_ANCHOR`) and stamps spans as anchor + monotonic delta.
# Within a process, span deltas are exactly monotonic deltas (immune to
# NTP steps); across processes on one host, anchors agree to the
# clock's accuracy at process start — and the assembled breakdown
# TELESCOPES (each span's end is the next span's start), so the sum of
# a ticket's spans equals its end-to-end wall time regardless of
# per-process anchor offsets.
#
# On-disk format: span logs are JSONL files of ``trace_span`` event
# records (``traces/<batch>.trace.jsonl`` in a fleet spool), appended
# with O_APPEND single writes so concurrent writers (two workers racing
# a requeue) interleave whole lines. :func:`read_trace` tolerates a
# torn LAST line (a writer killed mid-append) but REFUSES records from
# another schema version — a mixed-version fleet fails loudly instead
# of silently mis-composing spans (the same stance as
# ``HistogramSnapshot.merge``'s bounds-mismatch refusal).

#: Version of the on-disk span-log record layout. Bump on any breaking
#: change to the trace_span field set; readers refuse other versions.
TRACE_SCHEMA_VERSION = 1

#: Wall-clock anchor of this process's monotonic clock, captured once
#: at import. ``anchored_wall()`` timestamps derived from it are
#: comparable across the processes of one host without trusting
#: wall-clock stability DURING the run.
_MONO_ANCHOR = time.time() - time.monotonic()


def anchored_wall(mono: Optional[float] = None) -> float:
    """Wall-clock seconds derived from the monotonic clock and this
    process's import-time anchor. Pass a ``time.monotonic()`` reading
    to convert it; default is "now"."""
    return _MONO_ANCHOR + (time.monotonic() if mono is None else mono)


def new_trace_id() -> str:
    """A fresh trace id for one fleet ticket (random hex — ids must not
    collide across coordinators sharing a spool)."""
    import os

    return os.urandom(6).hex()


def trace_span_record(
    span: str, t0: float, t1: float, **attrs
) -> dict:
    """One span-log record: a schema-valid ``trace_span`` event naming
    the span, its anchored-wall [t0, t1] bounds, the writing process,
    and any attribution (tid/trace_id/batch/worker/role). ``t0 == t1``
    records are point events (requeue, claim markers)."""
    import os

    rec = {
        "schema": EVENT_SCHEMA_VERSION,
        "ts": float(time.time()),
        "event": "trace_span",
        "trace_schema": TRACE_SCHEMA_VERSION,
        "span": str(span),
        "t0": float(t0),
        "t1": float(t1),
        "pid": os.getpid(),
    }
    rec.update(attrs)
    return rec


def span_ms(rec: dict) -> float:
    """A span record's duration in milliseconds (clamped at 0)."""
    return max((float(rec["t1"]) - float(rec["t0"])) * 1e3, 0.0)


def append_trace(path: str, rec: dict) -> None:
    """Append one record to a span-log file. One ``write`` call in
    append mode — concurrent appenders (racing workers) interleave
    whole lines, and a killed writer tears at most the final line
    (which :func:`read_trace` tolerates). Never raises: the span log
    is observability, not correctness."""
    try:
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        pass


def read_trace(path: str) -> List[dict]:
    """Parse a span-log file. A torn LAST line (writer killed
    mid-append) is dropped silently; a torn middle line or a record
    carrying a different ``trace_schema`` raises ValueError — the
    mixed-version refusal path. Missing file reads as empty."""
    records: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return records
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn tail: the writer died mid-append
            raise ValueError(f"{path}:{i + 1}: torn span-log line")
        ver = rec.get("trace_schema", TRACE_SCHEMA_VERSION)
        if ver != TRACE_SCHEMA_VERSION:
            raise ValueError(
                f"{path}:{i + 1}: span-log schema {ver} != supported "
                f"{TRACE_SCHEMA_VERSION} — refusing to compose spans "
                "across fleet versions"
            )
        records.append(rec)
    return records


# -------------------------------------------------------- flight recorder


class FlightRecorder:
    """Bounded ring buffer of recent observability events — the
    post-mortem "what were the last N things this process did" record
    (ISSUE 6). Every ``_emit`` site in the serving queue, executor,
    engine, and supervisor also notes its event here (independent of
    whether a JSONL event log is configured), so when something
    dead-letters, degrades, or a supervised run aborts, the trigger
    site calls :meth:`dump` and the recent launch/fault/retry context
    lands on disk as a schema-valid JSONL file, terminated by a
    ``metrics_snapshot`` record carrying the live
    :data:`~libpga_tpu.utils.metrics.REGISTRY` state and a
    ``flight_dump`` trailer naming the dump reason.

    Thread-safe; ``capacity`` bounds memory (each record is one small
    dict). Dumps go to ``dump_dir`` (default: ``$PGA_FLIGHT_DIR`` or
    the system temp dir) as ``pga-flight-<pid>-<seq>-<reason>.jsonl``.
    """

    def __init__(
        self,
        capacity: int = 256,
        dump_dir: Optional[str] = None,
        *,
        clock=time.time,
    ):
        import collections
        import threading

        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.dump_dir = dump_dir
        #: Optional fleet-worker attribution (ISSUE 8): when a process
        #: is a fleet worker, ``serving/worker.py`` sets this so every
        #: dump trailer names the worker that wrote it. The ``pid`` is
        #: stamped regardless — a fleet post-mortem over a shared dump
        #: directory needs to attribute dumps to processes either way.
        self.worker_id: Optional[str] = None
        self._clock = clock
        self._ring = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps: List[str] = []

    def note(self, event: str, fields: Optional[dict] = None, **kw) -> dict:
        rec = {
            "schema": EVENT_SCHEMA_VERSION,
            "ts": float(self._clock()),
            "event": str(event),
        }
        if fields:
            rec.update(fields)
        if kw:
            rec.update(kw)
        with self._lock:
            self._ring.append(rec)
        return rec

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def _default_path(self, reason: str) -> str:
        import os
        import tempfile

        base = self.dump_dir or os.environ.get(
            "PGA_FLIGHT_DIR"
        ) or tempfile.gettempdir()
        os.makedirs(base, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)
        return os.path.join(
            base, f"pga-flight-{os.getpid()}-{self._seq}-{safe}.jsonl"
        )

    def dump(
        self,
        path: Optional[str] = None,
        reason: str = "manual",
        extra: Optional[List[dict]] = None,
    ) -> Optional[str]:
        """Write the ring (oldest first) + any ``extra`` records (e.g.
        a quarantined batch's span log — ISSUE 9) + a
        ``metrics_snapshot`` + a ``flight_dump`` trailer as schema-valid
        JSONL; returns the path (None when the write failed). Never
        raises out of a trigger site — the flight recorder is the
        diagnostic of last resort, and a failing dump must not mask the
        failure being recorded (it warns instead)."""
        import warnings

        with self._lock:
            recs = list(self._ring)
            self._seq += 1
        if extra:
            recs = recs + list(extra)
        try:
            if path is None:
                path = self._default_path(reason)
            from libpga_tpu.utils import metrics as _metrics

            snap_rec = {
                "schema": EVENT_SCHEMA_VERSION,
                "ts": float(self._clock()),
                "event": "metrics_snapshot",
                "metrics": _metrics.REGISTRY.snapshot(),
            }
            import os as _os

            trailer = {
                "schema": EVENT_SCHEMA_VERSION,
                "ts": float(self._clock()),
                "event": "flight_dump",
                "reason": str(reason),
                "records": len(recs),
                # Attribution for fleet post-mortems (ISSUE 8): which
                # process (and, when set, which fleet worker) wrote
                # this dump. Optional fields — validate_log stays green
                # on pre-fleet dumps, which simply lack them.
                "pid": _os.getpid(),
            }
            if self.worker_id is not None:
                trailer["worker"] = str(self.worker_id)
            # Not the spool discipline, deliberately: flight dumps are
            # the diagnostic of last resort, written into a dump/temp
            # directory (never the spool) while the process may already
            # be dying — one direct write maximizes the chance ANY
            # context survives, and a torn tail is acceptable in a
            # post-mortem artifact (validate_log flags it).
            with open(path, "w", encoding="utf-8") as fh:  # pga-lint: disable=spool-atomic-write
                for rec in recs + [snap_rec, trailer]:
                    fh.write(json.dumps(rec, default=str) + "\n")
        except Exception as e:
            warnings.warn(
                f"flight-recorder dump to {path!r} failed: {e!r}",
                stacklevel=2,
            )
            return None
        self.dumps.append(path)
        del self.dumps[:-32]  # keep the tail; paths, not contents
        return path


#: The process-wide flight recorder every instrumented subsystem feeds.
FLIGHT = FlightRecorder()


def flight() -> FlightRecorder:
    return FLIGHT


def flight_note(event: str, fields: Optional[dict] = None) -> None:
    """Feed one event into the global flight recorder (the tee every
    subsystem ``_emit`` helper calls). Never raises — recording is
    strictly best-effort."""
    try:
        FLIGHT.note(event, fields)
    except Exception:
        pass


def flight_dump(reason: str) -> Optional[str]:
    """Trigger an automatic post-mortem dump (dead letters, degradation,
    supervisor aborts). Returns the path, or None if dumping failed."""
    try:
        return FLIGHT.dump(reason=reason)
    except Exception:
        return None


def validate_event(rec: dict) -> None:
    """Raise ValueError unless ``rec`` is a well-formed event record."""
    for key, typ in (("schema", int), ("ts", (int, float)), ("event", str)):
        if key not in rec:
            raise ValueError(f"event record missing required key {key!r}: {rec}")
        if not isinstance(rec[key], typ):
            raise ValueError(f"event key {key!r} has wrong type: {rec}")
    if rec["schema"] != EVENT_SCHEMA_VERSION:
        raise ValueError(
            f"unsupported event schema {rec['schema']} "
            f"(expected {EVENT_SCHEMA_VERSION})"
        )
    required = EVENT_FIELDS.get(rec["event"], ())
    missing = [f for f in required if f not in rec]
    if missing:
        raise ValueError(
            f"event {rec['event']!r} missing fields {missing}: {rec}"
        )


def validate_log(path: str) -> List[dict]:
    """Parse + schema-validate a JSONL event log; returns the records.
    Raises ValueError on the first malformed line (with its number)."""
    records = []
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not valid JSON: {e}")
            try:
                validate_event(rec)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: {e}")
            records.append(rec)
    return records
