from libpga_tpu.utils.metrics import Metrics
from libpga_tpu.utils import checkpoint
from libpga_tpu.utils import profiling

__all__ = ["Metrics", "checkpoint", "profiling"]
