from libpga_tpu.utils.metrics import Metrics
from libpga_tpu.utils import checkpoint

__all__ = ["Metrics", "checkpoint"]
