from libpga_tpu.utils.metrics import Metrics
from libpga_tpu.utils import checkpoint
from libpga_tpu.utils import profiling
from libpga_tpu.utils import telemetry
from libpga_tpu.utils.telemetry import TelemetryConfig, History

__all__ = [
    "Metrics", "checkpoint", "profiling", "telemetry", "TelemetryConfig",
    "History",
]
