"""Version compatibility helpers.

The library tracks current JAX, but several deployment surfaces (the
test harness, the multi-process smokes, the multichip dryrun) must also
run on older installs — the container this grows in ships JAX 0.4.37.
Each helper degrades to the era-appropriate mechanism instead of
raising ``Unrecognized config option`` / ``AttributeError`` at import.
"""

from __future__ import annotations

import os

import jax


def force_cpu_device_count(n: int) -> None:
    """Make the CPU platform present ``n`` devices.

    Newer JAX exposes this as the ``jax_num_cpu_devices`` config option;
    older versions only honor the ``--xla_force_host_platform_device_count``
    XLA flag, which must be in the environment BEFORE the backend
    initializes. Both are applied (the flag is inert once a backend
    exists, the config option raises on old JAX if called directly), so
    callers just invoke this before their first device query.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", n)


def install_pallas_interpret_compat() -> None:
    """Version-gate ``pltpu.force_tpu_interpret_mode`` for old JAX.

    The fused-vs-XLA agreement gates (tests/conftest.py for the test
    harness, ``tools/gp_smoke.py`` for CI) run the Mosaic kernels on
    CPU via ``pltpu.force_tpu_interpret_mode``, which the installed JAX
    0.4.37 predates. The shim reproduces the two properties those gates
    rely on: every ``pl.pallas_call`` built inside the context runs
    with ``interpret=True``, and the Mosaic-only PRNG primitives
    execute on CPU with the documented interpret-mode semantics
    (``prng_random_bits`` yields all-zero bits, ``prng_seed`` is a
    no-op). On newer JAX the real context manager is used untouched.
    Idempotent.
    """
    from jax.experimental.pallas import tpu as pltpu

    if hasattr(pltpu, "force_tpu_interpret_mode"):
        return
    import contextlib

    import jax.numpy as jnp
    from jax.interpreters import mlir
    from jax._src.pallas.mosaic import primitives as _mp
    from jax.experimental import pallas as pl

    mlir.register_lowering(
        _mp.prng_seed_p,
        mlir.lower_fun(lambda *seeds: [], multiple_results=True),
        "cpu",
    )
    mlir.register_lowering(
        _mp.prng_random_bits_p,
        mlir.lower_fun(
            lambda *, shape: jnp.zeros(shape, jnp.int32),
            multiple_results=False,
        ),
        "cpu",
    )

    _real_call = pl.pallas_call

    @contextlib.contextmanager
    def force_tpu_interpret_mode():
        def interpret_call(*args, **kwargs):
            kwargs["interpret"] = True
            return _real_call(*args, **kwargs)

        pl.pallas_call = interpret_call
        try:
            yield
        finally:
            pl.pallas_call = _real_call

    pltpu.force_tpu_interpret_mode = force_tpu_interpret_mode


def shard_map(fn, *, mesh, in_specs, out_specs, check=False):
    """``jax.shard_map`` with the pre-0.5 fallback.

    New JAX hosts ``shard_map`` at the top level with the replication
    check named ``check_vma``; 0.4.x keeps it in ``jax.experimental``
    with ``check_rep``. Call sites that need replication checking pass
    ``check=True``; the library's runners disable it (their bodies mix
    per-shard and replicated values deliberately).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )
