"""Run metrics and telemetry.

The reference's entire observability story is one ``printf`` of the best
score inside ``pga_get_best`` (``src/pga.cu:230``). Here every fused run
records generation counts and wall time, exposing generations/sec — the
framework's headline metric — plus an optional callback hook for loggers.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class RunRecord:
    generations: int
    population_size: int
    seconds: float
    timestamp: float

    @property
    def generations_per_sec(self) -> float:
        """Generations per wall second; 0.0 when no time elapsed (a
        sub-resolution timer must read as "no rate", not inf — inf
        poisons any aggregate a consumer computes over records)."""
        return self.generations / self.seconds if self.seconds > 0 else 0.0


class Counters:
    """Named monotonically-increasing counters with listener fan-out —
    the metrics primitive behind the serving compile-cache's
    hit/miss/evict accounting (``serving/cache.py``). Deliberately
    minimal: ``bump`` increments, ``snapshot`` returns a plain dict (so
    a consumer can diff two snapshots without holding a reference into
    live state), and listeners registered with :meth:`add_listener` see
    ``(name, value)`` per bump under the same isolation contract as
    :class:`Metrics` run listeners."""

    def __init__(self):
        self._counts: dict = {}
        self._listeners: List[Callable[[str, int], None]] = []
        self._lock = threading.Lock()
        self._warned_listeners: set = set()

    def add_listener(self, fn: Callable[[str, int], None]) -> Callable:
        self._listeners.append(fn)
        return fn

    def remove_listener(self, fn: Callable[[str, int], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass
        self._warned_listeners.discard(id(fn))

    def bump(self, name: str, by: int = 1) -> int:
        # Read-modify-write under a lock: the serving flusher thread and
        # submitter threads bump the same cache counters concurrently —
        # an unlocked += loses increments exactly when the accounting is
        # most interesting (bursts).
        with self._lock:
            value = self._counts.get(name, 0) + by
            self._counts[name] = value
        # Listener isolation (same contract as Metrics.record_run): one
        # bad listener must never break cache/queue accounting. Called
        # OUTSIDE the lock — a listener that bumps back would deadlock.
        # One warning PER FAILING LISTENER, not per bump: counters fire
        # on hot serving paths, and a broken dashboard hook repeating
        # its warning thousands of times buries every other diagnostic.
        for fn in list(self._listeners):
            try:
                fn(name, value)
            except Exception as e:
                if id(fn) not in self._warned_listeners:
                    self._warned_listeners.add(id(fn))
                    warnings.warn(
                        f"counter listener {fn!r} raised {e!r} — ignored "
                        "(further failures of this listener are silent)",
                        stacklevel=2,
                    )
        return value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


class Metrics:
    """Accumulates per-run statistics for a PGA instance.

    Listeners: multiple independent consumers (loggers, checkpointers)
    register with :meth:`add_listener` / :meth:`remove_listener` — a
    single overwritable callback slot forces consumers to hand-roll
    wrap-and-restore chains that break when tear-down order differs from
    set-up order. ``on_run`` remains as a simple extra slot for ad-hoc
    use.
    """

    def __init__(self):
        self.runs: List[RunRecord] = []
        self.on_run: Optional[Callable[[RunRecord], None]] = None
        self._listeners: List[Callable[[RunRecord], None]] = []

    def add_listener(self, fn: Callable[[RunRecord], None]) -> Callable:
        self._listeners.append(fn)
        return fn

    def remove_listener(self, fn: Callable[[RunRecord], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def record_run(self, generations: int, population_size: int, seconds: float):
        rec = RunRecord(
            generations=generations,
            population_size=population_size,
            seconds=seconds,
            timestamp=time.time(),
        )
        self.runs.append(rec)
        # Listener isolation: observers must never abort the run they
        # observe (a raising logger used to propagate out of PGA.run
        # AFTER the run completed, losing the result). Each consumer is
        # isolated; failures surface as warnings and the listener stays
        # registered (a transient failure shouldn't silently end a
        # checkpointer's subscription).
        for fn in list(self._listeners) + (
            [self.on_run] if self.on_run is not None else []
        ):
            try:
                fn(rec)
            except Exception as e:
                warnings.warn(
                    f"metrics listener {fn!r} raised {e!r} — ignored "
                    "(listeners must not abort the run)",
                    stacklevel=2,
                )
        return rec

    @property
    def total_generations(self) -> int:
        return sum(r.generations for r in self.runs)

    @property
    def total_seconds(self) -> float:
        return sum(r.seconds for r in self.runs)

    @property
    def generations_per_sec(self) -> float:
        s = self.total_seconds
        return self.total_generations / s if s > 0 else 0.0


# ======================================================================
# Serving-grade metrics registry (ISSUE 6)
#
# Host-side only, by construction: nothing below ever appears inside a
# traced program — instrumented code paths observe wall-clock spans and
# queue states around device dispatches, so the metrics-disabled /
# metrics-enabled distinction cannot perturb a jaxpr (the StableHLO
# byte-identity gates never see this layer).
# ======================================================================


def log_bounds(
    lo: float = 0.01, hi: float = 1e6, per_decade: int = 5
) -> Tuple[float, ...]:
    """Fixed log-spaced histogram bucket upper bounds covering
    [lo, hi]. ``per_decade`` buckets per factor of 10 bounds the
    worst-case percentile interpolation error at a factor of
    ``10**(1/per_decade)`` (~58% at the default 5) while keeping the
    bucket count small enough to snapshot/merge cheaply. The default
    span (0.01..1e6, read as milliseconds: 10µs .. ~17min) covers every
    latency this library serves."""
    if lo <= 0 or hi <= lo:
        raise ValueError("need 0 < lo < hi")
    if per_decade < 1:
        raise ValueError("per_decade must be >= 1")
    step = 10.0 ** (1.0 / per_decade)
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * step)
    # Exact decade boundaries drift under repeated float multiply;
    # round to a stable short decimal so identical parameters always
    # produce identical (mergeable) bounds.
    return tuple(float(f"{b:.6g}") for b in out)


#: The registry's default bucket layout — one shared shape so every
#: histogram snapshot in a process (and across processes of one fleet)
#: merges with every other.
DEFAULT_BOUNDS = log_bounds()


@dataclasses.dataclass(frozen=True)
class HistogramSnapshot:
    """An immutable, mergeable view of a histogram's state.

    ``counts`` has ``len(bounds) + 1`` entries — the last is the
    overflow bucket (> bounds[-1]). Merging requires identical bounds;
    merge is associative and commutative (counts add, min/max fold), so
    per-worker snapshots can be combined in any tree order — the
    property a fleet-level aggregator needs.
    """

    bounds: Tuple[float, ...]
    counts: Tuple[int, ...]
    sum: float = 0.0
    min: float = math.inf
    max: float = -math.inf

    @property
    def count(self) -> int:
        return sum(self.counts)

    def merge(self, other: "HistogramSnapshot") -> "HistogramSnapshot":
        if self.bounds != other.bounds:
            raise ValueError(
                "cannot merge histograms with different bounds "
                f"({len(self.bounds)} vs {len(other.bounds)} buckets)"
            )
        return HistogramSnapshot(
            bounds=self.bounds,
            counts=tuple(
                a + b for a, b in zip(self.counts, other.counts)
            ),
            sum=self.sum + other.sum,
            min=min(self.min, other.min),
            max=max(self.max, other.max),
        )

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (0..100) by linear interpolation
        inside the containing bucket. Accuracy is bounded by the bucket
        width; exact at the recorded min/max. NaN when empty."""
        if not (0.0 <= q <= 100.0):
            raise ValueError("q must be in [0, 100]")
        total = self.count
        if total == 0:
            return math.nan
        rank = (q / 100.0) * total
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else min(
                    self.min, self.bounds[0]
                )
                hi = (
                    self.bounds[i] if i < len(self.bounds) else self.max
                )
                lo = max(lo, self.min)
                hi = min(hi, self.max) if hi >= lo else lo
                frac = (rank - cum) / c
                return min(max(lo + frac * (hi - lo), self.min), self.max)
            cum += c
        return self.max  # numeric slack: rank fell off the end

    @property
    def p50(self) -> float:
        return self.percentile(50.0)

    @property
    def p95(self) -> float:
        return self.percentile(95.0)

    @property
    def p99(self) -> float:
        return self.percentile(99.0)

    def as_dict(self) -> dict:
        """JSON-able form (the snapshot-exporter record)."""
        empty = self.count == 0
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.sum,
            "min": None if empty else self.min,
            "max": None if empty else self.max,
            "p50": None if empty else self.p50,
            "p95": None if empty else self.p95,
            "p99": None if empty else self.p99,
        }

    @staticmethod
    def from_dict(d: dict) -> "HistogramSnapshot":
        empty = sum(d["counts"]) == 0
        return HistogramSnapshot(
            bounds=tuple(d["bounds"]),
            counts=tuple(d["counts"]),
            sum=float(d["sum"]),
            min=math.inf if empty else float(d["min"]),
            max=-math.inf if empty else float(d["max"]),
        )


class Histogram:
    """Thread-safe fixed-bound histogram (log-spaced by default).

    ``observe`` is O(log buckets); reads go through :meth:`snapshot`
    (an immutable, mergeable value — see :class:`HistogramSnapshot`).
    Convenience percentile properties read a fresh snapshot.
    """

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        bounds = tuple(bounds) if bounds is not None else DEFAULT_BOUNDS
        if len(bounds) < 1 or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError("bounds must be strictly increasing")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        if math.isnan(value):
            return  # a NaN sample would poison sum/percentiles
        import bisect

        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    def snapshot(self) -> HistogramSnapshot:
        with self._lock:
            return HistogramSnapshot(
                bounds=self.bounds,
                counts=tuple(self._counts),
                sum=self._sum,
                min=self._min,
                max=self._max,
            )

    @property
    def count(self) -> int:
        return self.snapshot().count

    @property
    def sum(self) -> float:
        return self.snapshot().sum

    def percentile(self, q: float) -> float:
        return self.snapshot().percentile(q)

    @property
    def p50(self) -> float:
        return self.percentile(50.0)

    @property
    def p95(self) -> float:
        return self.percentile(95.0)

    @property
    def p99(self) -> float:
        return self.percentile(99.0)


class Gauge:
    """Thread-safe point-in-time value (queue depth, cache entries)."""

    def __init__(self, value: float = 0.0):
        self._value = float(value)
        self._lock = threading.Lock()

    def set(self, value: float) -> float:
        with self._lock:
            self._value = float(value)
            return self._value

    def add(self, delta: float) -> float:
        with self._lock:
            self._value += float(delta)
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter:
    """Thread-safe monotonically-increasing scalar (the registry's
    per-series counter; :class:`Counters` remains the multi-name set
    used by the compile cache)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def bump(self, by: int = 1) -> int:
        if by < 0:
            raise ValueError("counters only increase; use a Gauge")
        with self._lock:
            self._value += by
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _series_name(name: str, labels: tuple) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Process-global home for counters, gauges, and histograms.

    ``counter(name, **labels)`` / ``gauge(...)`` / ``histogram(...)``
    return the live (shared, thread-safe) instrument for that series,
    creating it on first use — instrumentation sites never need setup
    order. Snapshots are plain JSON-able dicts; ``to_prometheus()``
    renders the text exposition format. A name maps to exactly one
    instrument kind (a ``gauge("x")`` after ``counter("x")`` raises —
    silent kind confusion corrupts dashboards).

    **Label-cardinality guard** (ISSUE 14): per-tenant attribution means
    label values now arrive from CLIENTS, and one misbehaving client
    cycling tenant ids would otherwise mint unbounded metric series —
    blowing up every snapshot, flush, and exposition in the process.
    The registry therefore caps the distinct values of each label NAME
    at :attr:`label_cardinality_limit`; past the cap, new values route
    into one shared ``_overflow`` series (existing values keep their
    own), a warning fires ONCE per label name, and the overflow count
    is exported as ``registry.label_overflow{label=...}`` gauges so
    dashboards (and ``tools/metrics_dump.py --check``, which flags
    ``_overflow`` label values) can see the guard engaged.
    """

    SNAPSHOT_SCHEMA = 1

    #: Distinct values allowed per label name before new values fold
    #: into the ``_overflow`` bucket. Class-level so a serving host can
    #: raise it deliberately; the default comfortably covers workers,
    #: buckets, and a healthy tenant population.
    label_cardinality_limit = 64

    #: The shared overflow label value (``tenancy.OVERFLOW`` — reserved,
    #: so a client can never legitimately collide with it).
    OVERFLOW_VALUE = "_overflow"

    def __init__(self):
        self._series: Dict[tuple, object] = {}
        self._kinds: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._label_values: Dict[str, set] = {}
        self._label_overflow: Dict[str, int] = {}
        self._card_warned: set = set()

    def _guard_labels(self, labels: dict) -> Tuple[dict, List[str]]:
        """Apply the cardinality cap. Returns the (possibly rewritten)
        labels and the label names that newly overflowed — warnings
        fire OUTSIDE the lock."""
        if not labels:
            return labels, []
        limit = self.label_cardinality_limit
        out = None
        newly = []
        with self._lock:
            for k, v in labels.items():
                v = str(v)
                seen = self._label_values.setdefault(k, set())
                if v in seen:
                    continue
                if len(seen) < limit or v == self.OVERFLOW_VALUE:
                    seen.add(v)
                    continue
                self._label_overflow[k] = (
                    self._label_overflow.get(k, 0) + 1
                )
                if out is None:
                    out = dict(labels)
                out[k] = self.OVERFLOW_VALUE
                if k not in self._card_warned:
                    self._card_warned.add(k)
                    newly.append(k)
        return (labels if out is None else out), newly

    def _get(self, kind: str, name: str, labels: dict, make):
        labels, newly = self._guard_labels(labels)
        with self._lock:
            key = (name, _labels_key(labels))
            prev = self._kinds.get(name)
            if prev is not None and prev != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {prev}, "
                    f"cannot re-register as {kind}"
                )
            got = self._series.get(key)
            if got is None:
                self._kinds[name] = kind
                got = self._series[key] = make()
        for label in newly:
            warnings.warn(
                f"metric label {label!r} exceeded "
                f"{self.label_cardinality_limit} distinct values — new "
                f"values now share the {self.OVERFLOW_VALUE!r} series "
                "(one warning per label; see "
                "MetricsRegistry.label_cardinality_limit)",
                stacklevel=3,
            )
        return got

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(
        self, name: str, bounds: Optional[Sequence[float]] = None,
        **labels,
    ) -> Histogram:
        return self._get(
            "histogram", name, labels, lambda: Histogram(bounds)
        )

    def reset(self) -> None:
        """Drop every series (tests; a fresh server start)."""
        with self._lock:
            self._series.clear()
            self._kinds.clear()
            self._label_values.clear()
            self._label_overflow.clear()
            self._card_warned.clear()

    def label_overflow(self) -> Dict[str, int]:
        """Label names whose distinct-value count exceeded the guard,
        mapped to how many values were folded into ``_overflow``."""
        with self._lock:
            return dict(self._label_overflow)

    # ------------------------------------------------------------ export

    def snapshot(self) -> dict:
        """JSON-able registry state: one record per series, grouped by
        instrument kind. The histogram records embed the full mergeable
        state (bounds + counts) plus derived p50/p95/p99."""
        with self._lock:
            items = list(self._series.items())
        out = {
            "schema": self.SNAPSHOT_SCHEMA,
            "ts": time.time(),
            "counters": [],
            "gauges": [],
            "histograms": [],
        }
        for (name, labels), series in sorted(
            items, key=lambda kv: kv[0]
        ):
            rec = {"name": name, "labels": dict(labels)}
            if isinstance(series, Counter):
                rec["value"] = series.value
                out["counters"].append(rec)
            elif isinstance(series, Gauge):
                rec["value"] = series.value
                out["gauges"].append(rec)
            else:
                rec.update(series.snapshot().as_dict())
                out["histograms"].append(rec)
        # Cardinality-guard visibility: one synthetic gauge per
        # overflowed label name (built here, not via gauge() — the
        # guard must never be able to mint series of its own).
        for label, count in sorted(self.label_overflow().items()):
            out["gauges"].append({
                "name": "registry.label_overflow",
                "labels": {"label": label},
                "value": float(count),
            })
        return out

    def to_prometheus(self, prefix: str = "pga_") -> str:
        return prometheus_text(self.snapshot(), prefix=prefix)


def merge_snapshots(
    parts: Sequence[Tuple[str, dict]], proc_label: str = "proc"
) -> dict:
    """Merge per-process :meth:`MetricsRegistry.snapshot` dicts into ONE
    fleet-wide snapshot (ISSUE 9 — the coordinator's aggregation of
    worker registry flushes).

    ``parts`` is ``[(proc_name, snapshot), ...]`` — one entry per
    process, names unique (the fleet uses worker ids plus
    ``"coordinator"``). Every series gains a ``proc`` label naming its
    origin (the per-worker labels the merged Prometheus exposition
    carries), and histograms ADDITIONALLY fold into one aggregate
    series per (name, original labels) without the ``proc`` label via
    :meth:`HistogramSnapshot.merge` — associative and commutative, so
    the merge order cannot change the fleet percentiles, and a bounds
    mismatch (a worker built on different bucket parameters) raises
    rather than silently mis-merging. A snapshot from another
    ``SNAPSHOT_SCHEMA`` version is refused the same way: loudly.
    """
    merged: dict = {
        "schema": MetricsRegistry.SNAPSHOT_SCHEMA,
        "ts": 0.0,
        "merged_from": [],
        "counters": [],
        "gauges": [],
        "histograms": [],
    }
    agg: Dict[Tuple[str, tuple], HistogramSnapshot] = {}
    seen: set = set()
    for proc, snap in parts:
        if proc in seen:
            raise ValueError(f"duplicate process name {proc!r} in merge")
        seen.add(proc)
        if not isinstance(snap, dict) or snap.get("schema") != (
            MetricsRegistry.SNAPSHOT_SCHEMA
        ):
            raise ValueError(
                f"snapshot from {proc!r} has schema "
                f"{None if not isinstance(snap, dict) else snap.get('schema')!r}"
                f" != supported {MetricsRegistry.SNAPSHOT_SCHEMA} — "
                "refusing to merge across registry versions"
            )
        merged["ts"] = max(merged["ts"], float(snap.get("ts", 0.0)))
        merged["merged_from"].append(str(proc))
        for kind in ("counters", "gauges", "histograms"):
            for rec in snap.get(kind, ()):
                labeled = dict(rec)
                labeled["labels"] = {
                    **rec.get("labels", {}), proc_label: str(proc)
                }
                merged[kind].append(labeled)
                if kind == "histograms":
                    key = (rec["name"], _labels_key(rec.get("labels", {})))
                    h = HistogramSnapshot.from_dict(rec)
                    prev = agg.get(key)
                    agg[key] = h if prev is None else prev.merge(h)
    for (name, labels), h in sorted(agg.items()):
        merged["histograms"].append(
            {"name": name, "labels": dict(labels), **h.as_dict()}
        )
    return merged


# ------------------------------------------------ SLO burn rate (ISSUE 14)


class BurnRateMonitor:
    """Multi-window error-budget burn-rate tracking, per tenant.

    The SRE alerting shape: each completed request either met its
    latency objective or violated it; the ERROR BUDGET says a
    ``budget`` fraction of requests may violate; the BURN RATE over a
    window is ``observed_violation_rate / budget`` (1.0 = burning the
    budget exactly as fast as allowed). An alert requires BOTH a fast
    window (catches a sharp regression quickly) and a slow window
    (confirms it is sustained, not one spike) over ``threshold`` — the
    classic multi-window rule, which is simultaneously fast to fire
    and slow to flap.

    Host-side and allocation-bounded: one deque of (monotonic stamp,
    violated) pairs per tenant, pruned past the slow window on every
    touch. ``record`` is what instrumented readback paths call;
    ``check`` returns TRANSITION-EDGE alerts (a tenant alerts once per
    excursion, and recovery re-arms it) so callers can emit one
    ``slo_burn`` event per incident instead of one per scan.
    """

    def __init__(
        self,
        budget: float = 0.01,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        threshold: float = 10.0,
        min_samples: int = 1,
        *,
        clock=time.monotonic,
    ):
        if not (0.0 < budget <= 1.0):
            raise ValueError("budget must be in (0, 1]")
        if not (0.0 < fast_window_s <= slow_window_s):
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if threshold <= 0:
            raise ValueError("threshold must be > 0")
        if min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        self.budget = float(budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self._clock = clock
        self._events: Dict[str, object] = {}  # tenant -> deque
        self._alerting: set = set()
        self._mon_lock = threading.Lock()

    def record(self, tenant: str, violated: bool) -> None:
        import collections

        now = self._clock()
        with self._mon_lock:
            dq = self._events.get(tenant)
            if dq is None:
                dq = self._events[tenant] = collections.deque()
            dq.append((now, bool(violated)))
            cutoff = now - self.slow_window_s
            while dq and dq[0][0] < cutoff:
                dq.popleft()

    def _window_rate(self, dq, now: float, window: float):
        total = bad = 0
        cutoff = now - window
        for t, violated in dq:
            if t >= cutoff:
                total += 1
                bad += violated
        return (0.0 if total == 0 else bad / total), total

    def burn(self, tenant: str) -> dict:
        """Current burn state for one tenant: fast/slow burn rates
        (violation rate over the window divided by the budget) and the
        sample counts behind them."""
        now = self._clock()
        with self._mon_lock:
            dq = list(self._events.get(tenant, ()))
        fast_rate, n_fast = self._window_rate(dq, now, self.fast_window_s)
        slow_rate, n_slow = self._window_rate(dq, now, self.slow_window_s)
        return {
            "tenant": tenant,
            "fast_burn": fast_rate / self.budget,
            "slow_burn": slow_rate / self.budget,
            "fast_samples": n_fast,
            "slow_samples": n_slow,
        }

    def tenants(self) -> List[str]:
        with self._mon_lock:
            return sorted(self._events)

    def alerting(self, tenant: str) -> bool:
        with self._mon_lock:
            return tenant in self._alerting

    def check(self) -> List[dict]:
        """Scan every recorded tenant; returns the NEW alerts (burn
        over ``threshold`` in BOTH windows with at least
        ``min_samples`` slow-window observations, transition-edge).
        Tenants back under threshold silently re-arm."""
        alerts: List[dict] = []
        for tenant in self.tenants():
            b = self.burn(tenant)
            hot = (
                b["fast_burn"] >= self.threshold
                and b["slow_burn"] >= self.threshold
                and b["slow_samples"] >= self.min_samples
            )
            with self._mon_lock:
                if hot and tenant not in self._alerting:
                    self._alerting.add(tenant)
                    alerts.append({
                        **b,
                        "budget": self.budget,
                        "threshold": self.threshold,
                    })
                elif not hot:
                    self._alerting.discard(tenant)
        return alerts


#: The process-wide registry every instrumented subsystem shares.
#: Tests that assert exact series contents should construct their own
#: MetricsRegistry (RunQueue and friends accept one) or reset this.
REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return REGISTRY


def observe_stage_ms(stage: str, ms: float) -> None:
    """Feed one engine-stage duration into the process registry's
    ``perf.stage_ms{stage=}`` histogram — the ISSUE 17 per-stage
    attribution series (``telemetry.span`` calls this around every
    ``pga/<stage>`` dispatch; ``perf/attribution.stage_breakdown``
    reads it back as per-stage shares). Never raises: attribution is
    observability, not control flow."""
    try:
        REGISTRY.histogram("perf.stage_ms", stage=stage).observe(ms)
    except Exception:
        pass


# ------------------------------------------------- Prometheus exposition


def _prom_name(name: str, prefix: str) -> str:
    out = "".join(
        c if c.isalnum() or c == "_" else "_" for c in prefix + name
    )
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _prom_labels(labels: dict, extra: str = "") -> str:
    parts = [
        f'{k}="{_prom_escape(v)}"' for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _prom_float(v: float) -> str:
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return repr(float(v))


def prometheus_text(snapshot: dict, prefix: str = "pga_") -> str:
    """Render a :meth:`MetricsRegistry.snapshot` dict in the Prometheus
    text exposition format (the ``tools/metrics_dump.py`` writer).
    Works from a snapshot — not the live registry — so a collector can
    re-render persisted or merged snapshots from other processes."""
    lines: List[str] = []
    typed: set = set()

    def header(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for rec in snapshot.get("counters", ()):
        name = _prom_name(rec["name"], prefix)
        header(name, "counter")
        lines.append(
            f"{name}{_prom_labels(rec['labels'])} {int(rec['value'])}"
        )
    for rec in snapshot.get("gauges", ()):
        name = _prom_name(rec["name"], prefix)
        header(name, "gauge")
        lines.append(
            f"{name}{_prom_labels(rec['labels'])} "
            f"{_prom_float(rec['value'])}"
        )
    for rec in snapshot.get("histograms", ()):
        name = _prom_name(rec["name"], prefix)
        header(name, "histogram")
        cum = 0
        for bound, cnt in zip(rec["bounds"], rec["counts"]):
            cum += cnt
            le = _prom_labels(rec["labels"], f'le="{_prom_float(bound)}"')
            lines.append(f"{name}_bucket{le} {cum}")
        cum += rec["counts"][len(rec["bounds"])]
        le = _prom_labels(rec["labels"], 'le="+Inf"')
        lines.append(f"{name}_bucket{le} {cum}")
        labels = _prom_labels(rec["labels"])
        lines.append(f"{name}_sum{labels} {_prom_float(rec['sum'])}")
        lines.append(f"{name}_count{labels} {rec['count']}")
    return "\n".join(lines) + "\n"


def lint_prometheus(text: str) -> List[str]:
    """Line-format lint of a Prometheus text exposition (the
    ``tools/metrics_dump.py --check`` gate). Returns a list of problem
    strings (empty = clean). Checks per-line syntax, histogram bucket
    cumulativity, the ``+Inf`` bucket, ``_count`` consistency, and —
    ISSUE 14 — label-value hygiene: values must be printable ASCII
    after unescaping (a control character or non-ASCII byte in a label
    is a scrape-breaking writer bug), and an ``_overflow`` label value
    is flagged because it means the registry's cardinality guard
    engaged — some client minted more distinct values of that label
    than :attr:`MetricsRegistry.label_cardinality_limit` allows."""
    import re

    errors: List[str] = []
    name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
    label_re = re.compile(
        r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    )
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)(\s+\d+)?$"
    )
    buckets: Dict[tuple, List[Tuple[float, float]]] = {}
    counts: Dict[tuple, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("TYPE", "HELP"):
                errors.append(f"line {lineno}: malformed comment: {line!r}")
            elif not name_re.fullmatch(parts[2]):
                errors.append(
                    f"line {lineno}: bad metric name {parts[2]!r}"
                )
            elif parts[1] == "TYPE" and (
                len(parts) < 4
                or parts[3]
                not in ("counter", "gauge", "histogram", "summary",
                        "untyped")
            ):
                errors.append(f"line {lineno}: bad TYPE: {line!r}")
            continue
        m = sample_re.match(line)
        if m is None:
            errors.append(f"line {lineno}: not a sample line: {line!r}")
            continue
        name, labelstr, value = m.group(1), m.group(2), m.group(3)
        labels = {}
        if labelstr:
            body = labelstr[1:-1].strip()
            if body:
                pos = 0
                ok = True
                while pos < len(body):
                    lm = label_re.match(body, pos)
                    if lm is None:
                        ok = False
                        break
                    k, v = lm.group(0).split("=", 1)
                    labels[k] = v[1:-1]
                    pos = lm.end()
                    if pos < len(body):
                        if body[pos] != ",":
                            ok = False
                            break
                        pos += 1
                if not ok:
                    errors.append(
                        f"line {lineno}: bad label syntax: {labelstr!r}"
                    )
                    continue
        for lk, lv in labels.items():
            raw = (
                lv.replace("\\\\", "\\").replace('\\"', '"')
                .replace("\\n", "\n")
            )
            if any(c < " " or c > "~" for c in raw):
                errors.append(
                    f"line {lineno}: label {lk}={lv!r} is not "
                    "prometheus-safe (control or non-ASCII character)"
                )
            elif lk != "le" and raw == MetricsRegistry.OVERFLOW_VALUE:
                errors.append(
                    f"line {lineno}: label {lk}=\"_overflow\" — the "
                    "registry's label-cardinality guard engaged (a "
                    "client exceeded the distinct-value cap for this "
                    "label)"
                )
        try:
            fval = float(value)
        except ValueError:
            if value not in ("NaN", "+Inf", "-Inf"):
                errors.append(
                    f"line {lineno}: bad sample value {value!r}"
                )
                continue
            fval = float(value.replace("Inf", "inf"))
        if name.endswith("_bucket") and "le" in labels:
            base = name[: -len("_bucket")]
            rest = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            le = (
                math.inf if labels["le"] == "+Inf"
                else float(labels["le"])
            )
            buckets.setdefault((base, rest), []).append((le, fval))
        elif name.endswith("_count"):
            base = name[: -len("_count")]
            counts[(base, _labels_key(labels))] = fval
    for (base, rest), series in buckets.items():
        series.sort(key=lambda p: p[0])
        if series[-1][0] != math.inf:
            errors.append(f"histogram {base}: missing le=\"+Inf\" bucket")
        prev = -math.inf
        for le, v in series:
            if v < prev:
                errors.append(
                    f"histogram {base}: bucket counts not cumulative "
                    f"at le={le}"
                )
                break
            prev = v
        total = counts.get((base, tuple(rest)))
        if (
            total is not None
            and series[-1][0] == math.inf
            and series[-1][1] != total
        ):
            errors.append(
                f"histogram {base}: +Inf bucket {series[-1][1]} != "
                f"_count {total}"
            )
    return errors
