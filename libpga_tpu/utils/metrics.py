"""Run metrics and telemetry.

The reference's entire observability story is one ``printf`` of the best
score inside ``pga_get_best`` (``src/pga.cu:230``). Here every fused run
records generation counts and wall time, exposing generations/sec — the
framework's headline metric — plus an optional callback hook for loggers.
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from typing import Callable, List, Optional


@dataclasses.dataclass
class RunRecord:
    generations: int
    population_size: int
    seconds: float
    timestamp: float

    @property
    def generations_per_sec(self) -> float:
        """Generations per wall second; 0.0 when no time elapsed (a
        sub-resolution timer must read as "no rate", not inf — inf
        poisons any aggregate a consumer computes over records)."""
        return self.generations / self.seconds if self.seconds > 0 else 0.0


class Counters:
    """Named monotonically-increasing counters with listener fan-out —
    the metrics primitive behind the serving compile-cache's
    hit/miss/evict accounting (``serving/cache.py``). Deliberately
    minimal: ``bump`` increments, ``snapshot`` returns a plain dict (so
    a consumer can diff two snapshots without holding a reference into
    live state), and listeners registered with :meth:`add_listener` see
    ``(name, value)`` per bump under the same isolation contract as
    :class:`Metrics` run listeners."""

    def __init__(self):
        self._counts: dict = {}
        self._listeners: List[Callable[[str, int], None]] = []

    def add_listener(self, fn: Callable[[str, int], None]) -> Callable:
        self._listeners.append(fn)
        return fn

    def remove_listener(self, fn: Callable[[str, int], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def bump(self, name: str, by: int = 1) -> int:
        value = self._counts.get(name, 0) + by
        self._counts[name] = value
        for fn in list(self._listeners):
            try:
                fn(name, value)
            except Exception as e:
                warnings.warn(
                    f"counter listener {fn!r} raised {e!r} — ignored",
                    stacklevel=2,
                )
        return value

    def get(self, name: str) -> int:
        return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        return dict(self._counts)

    def reset(self) -> None:
        self._counts.clear()


class Metrics:
    """Accumulates per-run statistics for a PGA instance.

    Listeners: multiple independent consumers (loggers, checkpointers)
    register with :meth:`add_listener` / :meth:`remove_listener` — a
    single overwritable callback slot forces consumers to hand-roll
    wrap-and-restore chains that break when tear-down order differs from
    set-up order. ``on_run`` remains as a simple extra slot for ad-hoc
    use.
    """

    def __init__(self):
        self.runs: List[RunRecord] = []
        self.on_run: Optional[Callable[[RunRecord], None]] = None
        self._listeners: List[Callable[[RunRecord], None]] = []

    def add_listener(self, fn: Callable[[RunRecord], None]) -> Callable:
        self._listeners.append(fn)
        return fn

    def remove_listener(self, fn: Callable[[RunRecord], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def record_run(self, generations: int, population_size: int, seconds: float):
        rec = RunRecord(
            generations=generations,
            population_size=population_size,
            seconds=seconds,
            timestamp=time.time(),
        )
        self.runs.append(rec)
        # Listener isolation: observers must never abort the run they
        # observe (a raising logger used to propagate out of PGA.run
        # AFTER the run completed, losing the result). Each consumer is
        # isolated; failures surface as warnings and the listener stays
        # registered (a transient failure shouldn't silently end a
        # checkpointer's subscription).
        for fn in list(self._listeners) + (
            [self.on_run] if self.on_run is not None else []
        ):
            try:
                fn(rec)
            except Exception as e:
                warnings.warn(
                    f"metrics listener {fn!r} raised {e!r} — ignored "
                    "(listeners must not abort the run)",
                    stacklevel=2,
                )
        return rec

    @property
    def total_generations(self) -> int:
        return sum(r.generations for r in self.runs)

    @property
    def total_seconds(self) -> float:
        return sum(r.seconds for r in self.runs)

    @property
    def generations_per_sec(self) -> float:
        s = self.total_seconds
        return self.total_generations / s if s > 0 else 0.0
