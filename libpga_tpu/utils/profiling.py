"""Profiling hooks.

The reference has no tracing at all — its only possible timing is external
``nvprof`` (survey §5). Here two layers:

- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard/Perfetto trace of everything run inside (kernel timings,
  HBM usage, fusion boundaries).
- :func:`timed_runs` — lightweight generations/sec reporting built on the
  engine's :class:`~libpga_tpu.utils.metrics.Metrics`, no profiler
  overhead; suitable for always-on logging.
"""

from __future__ import annotations

import contextlib
import os
from typing import Callable, Optional

import jax


def enable_compilation_cache(path: str = "~/.cache/libpga_tpu_xla") -> None:
    """Persist XLA/Mosaic compilations across processes.

    The island runners' fused kernels take tens of seconds to compile on
    TPU; with this cache enabled a restarted job (or a benchmark rerun)
    loads them in milliseconds instead. Safe to call repeatedly; call it
    before the first compilation to benefit that compilation.
    """
    path = os.path.expanduser(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace of the enclosed block::

        with profiling.trace("/tmp/pga-trace"):
            pga.run(100)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def timed_runs(pga, log: Optional[Callable[[str], None]] = print):
    """Log generations/sec for every ``run``/``run_islands`` completed
    inside the block, via the engine's metrics callback::

        with profiling.timed_runs(pga):
            pga.run(1000)   # -> "run: 1000 gens @ 83.1 gens/sec (pop 1048576)"
    """
    def on_run(rec):
        if log is not None:
            log(
                f"run: {rec.generations} gens @ "
                f"{rec.generations_per_sec:.1f} gens/sec "
                f"(pop {rec.population_size})"
            )

    pga.metrics.add_listener(on_run)
    try:
        yield pga.metrics
    finally:
        pga.metrics.remove_listener(on_run)
