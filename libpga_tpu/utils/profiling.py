"""Profiling hooks.

The reference has no tracing at all — its only possible timing is external
``nvprof`` (survey §5). Here two layers:

- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard/Perfetto trace of everything run inside (kernel timings,
  HBM usage, fusion boundaries).
- :func:`timed_runs` — lightweight generations/sec reporting built on the
  engine's :class:`~libpga_tpu.utils.metrics.Metrics`, no profiler
  overhead; suitable for always-on logging.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Dict, Optional

import jax


def enable_compilation_cache(path: str = "~/.cache/libpga_tpu_xla") -> None:
    """Persist XLA/Mosaic compilations across processes.

    The island runners' fused kernels take tens of seconds to compile on
    TPU; with this cache enabled a restarted job (or a benchmark rerun)
    loads them in milliseconds instead. Safe to call repeatedly; call it
    before the first compilation to benefit that compilation.

    TPU sessions only. Do NOT enable on the CPU backend of this jaxlib
    (0.4.37): executing a cache-DESERIALIZED executable with donated
    buffers corrupts the runtime heap — donation-heavy
    checkpoint/restore loops (the robustness supervisor's workload)
    segfault or silently corrupt results (found by
    ``tools/chaos_smoke.py``; see the gate in ``tools/ci.sh``). CPU
    compiles are cheap enough that the cache buys nothing there anyway.
    """
    path = os.path.expanduser(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace of the enclosed block::

        with profiling.trace("/tmp/pga-trace"):
            pga.run(100)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    with jax.profiler.trace(log_dir):
        yield


def best_ms_per_unit(
    run: Callable[[int], None],
    lo: int = 30,
    hi: int = 90,
    tries: int = 2,
    units_per_call: int = 1,
) -> float:
    """ms per unit of work via two-length subtraction of per-length
    minima — the estimator bench.py and the ablation harnesses share.

    ``run(n)`` executes n calls and blocks until ready. The difference
    ``min(t(hi)) − min(t(lo))`` cancels warm-up/compile/dispatch
    constants, and taking per-length minima FIRST keeps the estimate
    bounded by true hardware time (a max over per-try deltas would
    select the try where noise shrank the difference).
    ``units_per_call`` scales a call that performs several units (e.g. a
    multi-generation launch breeding T generations). NaN when the
    subtraction is degenerate — the drop marker
    :func:`interleaved_medians` COUNTS AND REPORTS (``.dropped``), so a
    published median always states the n it actually rests on.
    """
    t_lo, t_hi = [], []
    for _ in range(tries):
        t0 = time.perf_counter()
        run(lo)
        t_lo.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run(hi)
        t_hi.append(time.perf_counter() - t0)
    delta = min(t_hi) - min(t_lo)
    units = (hi - lo) * units_per_call
    return 1000.0 * delta / units if delta > 0 else float("nan")


class InterleavedMedians(dict):
    """``{runner: median}`` plus the sample accounting a decision-grade
    median must state: ``.n[runner]`` = samples the median rests on,
    ``.dropped[runner]`` = degenerate (NaN) samples excluded,
    ``.rel_ci[runner]`` = the relative spread proxy (half-IQR over
    median) of the kept samples, ``.rounds`` = interleaved rounds
    actually executed (>= the requested count under the
    repeat-until-confidence mode). Plain-dict compatible, so existing
    callers are unaffected."""

    def __init__(self):
        super().__init__()
        self.n: Dict[str, int] = {}
        self.dropped: Dict[str, int] = {}
        self.rel_ci: Dict[str, float] = {}
        self.rounds: int = 0


def _median(kept) -> float:
    mid = len(kept) // 2
    return (
        kept[mid] if len(kept) % 2 else 0.5 * (kept[mid - 1] + kept[mid])
    )


def _rel_ci(kept) -> float:
    """Relative confidence proxy of a kept-sample list: half the
    interquartile range over the median (a robust coefficient of
    spread). ``inf`` below 2 samples (one sample carries no spread
    information — the repeat mode must keep going), 0.0 for identical
    samples."""
    if len(kept) < 2:
        return float("inf")
    med = _median(kept)
    if med == 0:
        return 0.0 if kept[0] == kept[-1] else float("inf")
    q1 = kept[max(0, (len(kept) - 1) // 4)]
    q3 = kept[min(len(kept) - 1, (3 * (len(kept) - 1) + 3) // 4)]
    return abs(0.5 * (q3 - q1) / med)


def interleaved_medians(
    runners: Dict[str, Callable[[int], None]],
    rounds: int = 5,
    sample: Optional[Callable[[Callable], float]] = None,
    min_rel_ci: Optional[float] = None,
    max_rounds: Optional[int] = None,
) -> "InterleavedMedians":
    """Per-runner MEDIAN of ``sample`` over ``rounds`` interleaved
    rounds with a fixed per-round ordering.

    The round-4/5 measurement lesson (BASELINE.md): on the tunneled
    bench chip, sequential same-process figures minutes apart drift more
    than the effects under comparison — only interleaved A/Bs are
    decision-grade. This is that protocol as a reusable primitive;
    ``sample`` defaults to :func:`best_ms_per_unit`. NaN samples
    (degenerate subtractions) are excluded from the median — and
    COUNTED: the result's ``.n``/``.dropped`` attributes state each
    runner's surviving/excluded sample counts, and any drop emits a
    warning (a median over 2 of 5 rounds is a much weaker claim than
    the number alone suggests; silently shrinking n hid that).

    **Repeat-until-confidence** (the autotuner oracle's mode,
    ISSUE 10): with ``min_rel_ci`` set, after the initial ``rounds``
    the protocol keeps appending FULL interleaved rounds until every
    runner's relative spread proxy (half-IQR / median of its kept
    samples, ``.rel_ci``) is at or under ``min_rel_ci`` — bounded by
    ``max_rounds`` total rounds (default ``3 * rounds``), so a noisy
    host terminates with an honest wide CI instead of looping forever.
    Interaction with the ``.n``/``.dropped`` accounting: both count
    over ALL executed rounds (``.rounds`` of them), so ``n + dropped ==
    rounds_executed`` per runner — extension rounds tighten the median
    AND grow the stated n, never silently. A runner whose samples are
    all degenerate keeps ``rel_ci = inf`` and stops extending only at
    ``max_rounds``.
    """
    import warnings

    if sample is None:
        sample = best_ms_per_unit
    if min_rel_ci is not None and min_rel_ci < 0:
        raise ValueError("min_rel_ci must be >= 0")
    if max_rounds is None:
        max_rounds = rounds if min_rel_ci is None else 3 * rounds
    if max_rounds < rounds:
        raise ValueError("max_rounds must be >= rounds")
    samples: Dict[str, list] = {name: [] for name in runners}

    def one_round():
        for name, run in runners.items():
            samples[name].append(sample(run))

    def kept(name):
        return sorted(x for x in samples[name] if x == x)

    done = 0
    for _ in range(rounds):
        one_round()
        done += 1
    if min_rel_ci is not None:
        while done < max_rounds and any(
            _rel_ci(kept(name)) > min_rel_ci for name in runners
        ):
            one_round()
            done += 1

    out = InterleavedMedians()
    out.rounds = done
    for name, xs in samples.items():
        k = kept(name)
        out.n[name] = len(k)
        out.dropped[name] = len(xs) - len(k)
        out.rel_ci[name] = _rel_ci(k)
        if out.dropped[name]:
            warnings.warn(
                f"interleaved_medians: runner {name!r} median rests on "
                f"n={len(k)} of {len(xs)} rounds "
                f"({out.dropped[name]} degenerate sample(s) dropped)",
                stacklevel=2,
            )
        out[name] = _median(k) if k else float("nan")
    return out


@contextlib.contextmanager
def timed_runs(pga, log: Optional[Callable[[str], None]] = print):
    """Log generations/sec for every ``run``/``run_islands`` completed
    inside the block, via the engine's metrics callback::

        with profiling.timed_runs(pga):
            pga.run(1000)   # -> "run: 1000 gens @ 83.1 gens/sec (pop 1048576)"
    """
    def on_run(rec):
        if log is not None:
            log(
                f"run: {rec.generations} gens @ "
                f"{rec.generations_per_sec:.1f} gens/sec "
                f"(pop {rec.population_size})"
            )

    pga.metrics.add_listener(on_run)
    try:
        yield pga.metrics
    finally:
        pga.metrics.remove_listener(on_run)
