"""Profiling hooks.

The reference has no tracing at all — its only possible timing is external
``nvprof`` (survey §5). Here two layers:

- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard/Perfetto trace of everything run inside (kernel timings,
  HBM usage, fusion boundaries).
- :func:`timed_runs` — lightweight generations/sec reporting built on the
  engine's :class:`~libpga_tpu.utils.metrics.Metrics`, no profiler
  overhead; suitable for always-on logging.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Dict, Optional

import jax


def enable_compilation_cache(path: str = "~/.cache/libpga_tpu_xla") -> None:
    """Persist XLA/Mosaic compilations across processes.

    The island runners' fused kernels take tens of seconds to compile on
    TPU; with this cache enabled a restarted job (or a benchmark rerun)
    loads them in milliseconds instead. Safe to call repeatedly; call it
    before the first compilation to benefit that compilation.
    """
    path = os.path.expanduser(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace of the enclosed block::

        with profiling.trace("/tmp/pga-trace"):
            pga.run(100)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    with jax.profiler.trace(log_dir):
        yield


def best_ms_per_unit(
    run: Callable[[int], None],
    lo: int = 30,
    hi: int = 90,
    tries: int = 2,
    units_per_call: int = 1,
) -> float:
    """ms per unit of work via two-length subtraction of per-length
    minima — the estimator bench.py and the ablation harnesses share.

    ``run(n)`` executes n calls and blocks until ready. The difference
    ``min(t(hi)) − min(t(lo))`` cancels warm-up/compile/dispatch
    constants, and taking per-length minima FIRST keeps the estimate
    bounded by true hardware time (a max over per-try deltas would
    select the try where noise shrank the difference).
    ``units_per_call`` scales a call that performs several units (e.g. a
    multi-generation launch breeding T generations). NaN when the
    subtraction is degenerate.
    """
    t_lo, t_hi = [], []
    for _ in range(tries):
        t0 = time.perf_counter()
        run(lo)
        t_lo.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run(hi)
        t_hi.append(time.perf_counter() - t0)
    delta = min(t_hi) - min(t_lo)
    units = (hi - lo) * units_per_call
    return 1000.0 * delta / units if delta > 0 else float("nan")


def interleaved_medians(
    runners: Dict[str, Callable[[int], None]],
    rounds: int = 5,
    sample: Optional[Callable[[Callable], float]] = None,
) -> Dict[str, float]:
    """Per-runner MEDIAN of ``sample`` over ``rounds`` interleaved
    rounds with a fixed per-round ordering.

    The round-4/5 measurement lesson (BASELINE.md): on the tunneled
    bench chip, sequential same-process figures minutes apart drift more
    than the effects under comparison — only interleaved A/Bs are
    decision-grade. This is that protocol as a reusable primitive;
    ``sample`` defaults to :func:`best_ms_per_unit`. NaN samples
    (degenerate subtractions) are dropped from the median.
    """
    if sample is None:
        sample = best_ms_per_unit
    samples: Dict[str, list] = {name: [] for name in runners}
    for _ in range(rounds):
        for name, run in runners.items():
            samples[name].append(sample(run))
    out = {}
    for name, xs in samples.items():
        xs = sorted(x for x in xs if x == x)
        if not xs:
            out[name] = float("nan")
            continue
        mid = len(xs) // 2
        out[name] = (
            xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])
        )
    return out


@contextlib.contextmanager
def timed_runs(pga, log: Optional[Callable[[str], None]] = print):
    """Log generations/sec for every ``run``/``run_islands`` completed
    inside the block, via the engine's metrics callback::

        with profiling.timed_runs(pga):
            pga.run(1000)   # -> "run: 1000 gens @ 83.1 gens/sec (pop 1048576)"
    """
    def on_run(rec):
        if log is not None:
            log(
                f"run: {rec.generations} gens @ "
                f"{rec.generations_per_sec:.1f} gens/sec "
                f"(pop {rec.population_size})"
            )

    pga.metrics.add_listener(on_run)
    try:
        yield pga.metrics
    finally:
        pga.metrics.remove_listener(on_run)
