"""Population checkpoint / resume.

The reference has no serialization at all — the only state extraction is the
host copy of one winning genome in ``pga_get_best`` (``src/pga.cu:218-236``).
Here whole solver states (all populations + PRNG key) round-trip through a
single ``.npz`` file, so long island runs can resume after preemption.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:
    from libpga_tpu.engine import PGA

FORMAT_VERSION = 1


def save(pga: "PGA", path: str) -> None:
    """Serialize all populations and the PRNG state to ``path`` (.npz)."""
    arrays = {
        "__version__": np.asarray(FORMAT_VERSION),
        "__num_populations__": np.asarray(len(pga.populations)),
        "__key__": np.asarray(jax.random.key_data(pga._key)),
    }
    for i, pop in enumerate(pga.populations):
        arrays[f"genomes_{i}"] = np.asarray(pop.genomes)
        arrays[f"scores_{i}"] = np.asarray(pop.scores)
    np.savez(path, **arrays)


def restore(pga: "PGA", path: str) -> None:
    """Load populations and PRNG state saved by :func:`save` into ``pga``.

    Replaces any populations already in the engine.
    """
    from libpga_tpu.population import Population

    with np.load(path) as data:
        version = int(data["__version__"])
        if version != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        n = int(data["__num_populations__"])
        pga._key = jax.random.wrap_key_data(jnp.asarray(data["__key__"]))
        pga._populations = [
            Population(
                genomes=jnp.asarray(data[f"genomes_{i}"]),
                scores=jnp.asarray(data[f"scores_{i}"]),
            )
            for i in range(n)
        ]
        pga._staged = [None] * n
