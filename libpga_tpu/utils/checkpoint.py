"""Population checkpoint / resume.

The reference has no serialization at all — the only state extraction is the
host copy of one winning genome in ``pga_get_best`` (``src/pga.cu:218-236``).
Here whole solver states (all populations + PRNG key) round-trip through a
single ``.npz`` file, so long island runs can resume after preemption.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:
    from libpga_tpu.engine import PGA

FORMAT_VERSION = 2


def _encode(arr: np.ndarray):
    """npz-safe encoding: ml_dtypes bfloat16 has no npy representation
    (np.savez writes it as raw void '|V2' that jnp.asarray cannot read
    back), so non-npy dtypes are stored as their uint bit patterns with
    the true dtype name recorded alongside."""
    if arr.dtype.kind == "V" or arr.dtype.name == "bfloat16":
        return arr.view(np.uint16 if arr.dtype.itemsize == 2 else np.uint8), arr.dtype.name
    return arr, ""


def _decode(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    if not dtype_name:
        return arr
    import ml_dtypes

    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name)))


def save(pga: "PGA", path: str) -> None:
    """Serialize all populations and the PRNG state to ``path`` (.npz)."""
    arrays = {
        "__version__": np.asarray(FORMAT_VERSION),
        "__num_populations__": np.asarray(len(pga.populations)),
        "__key__": np.asarray(jax.random.key_data(pga._key)),
    }
    for i, pop in enumerate(pga.populations):
        genomes, dtype_name = _encode(np.asarray(pop.genomes))
        arrays[f"genomes_{i}"] = genomes
        arrays[f"genomes_dtype_{i}"] = np.asarray(dtype_name)
        arrays[f"scores_{i}"] = np.asarray(pop.scores)
    np.savez(path, **arrays)


class AutoCheckpointer:
    """Periodic checkpointing for long / preemptible runs.

    Hooks the engine's metrics callback and saves the full solver state
    every ``every_generations`` completed generations::

        ckpt = AutoCheckpointer(pga, "state.npz", every_generations=1000)
        for _ in range(100):
            pga.run_islands(500, 50, 0.05)
        ckpt.close()

    On restart, ``checkpoint.restore(pga, "state.npz")`` resumes from the
    last save (populations + PRNG stream). The reference has no recovery
    story at all — any CUDA error exits the process (``pga.cu:31``).
    """

    def __init__(self, pga: "PGA", path: str, every_generations: int = 1000):
        self._pga = pga
        self._path = path
        self._every = every_generations
        self._since_save = 0
        pga.metrics.add_listener(self._on_run)

    def _on_run(self, rec):
        self._since_save += rec.generations
        if self._since_save >= self._every:
            save(self._pga, self._path)
            self._since_save = 0

    def close(self, final_save: bool = True):
        if final_save:
            save(self._pga, self._path)
        self._pga.metrics.remove_listener(self._on_run)


def restore(pga: "PGA", path: str) -> None:
    """Load populations and PRNG state saved by :func:`save` into ``pga``.

    Replaces any populations already in the engine.
    """
    from libpga_tpu.population import Population

    with np.load(path) as data:
        version = int(data["__version__"])
        if version not in (1, FORMAT_VERSION):
            raise ValueError(f"unsupported checkpoint version {version}")
        n = int(data["__num_populations__"])
        pga._key = jax.random.wrap_key_data(jnp.asarray(data["__key__"]))

        def genomes(i):
            g = data[f"genomes_{i}"]
            if version >= 2:
                g = _decode(g, str(data[f"genomes_dtype_{i}"]))
            return jnp.asarray(g)

        pga._populations = [
            Population(
                genomes=genomes(i),
                scores=jnp.asarray(data[f"scores_{i}"]),
            )
            for i in range(n)
        ]
        pga._staged = [None] * n
