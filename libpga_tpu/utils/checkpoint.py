"""Population checkpoint / resume.

The reference has no serialization at all — the only state extraction is the
host copy of one winning genome in ``pga_get_best`` (``src/pga.cu:218-236``).
Here whole solver states (all populations + PRNG key) round-trip through
``.npz`` files, so long island runs can resume after preemption.

Multi-host safety: on a multi-process mesh a population's device buffers
may live entirely on another host — ``np.asarray`` on such an array
raises. ``save`` therefore writes only the ADDRESSABLE shards of each
array, one ``<path>.proc<k>.npz`` file per process (all processes must
call it — it is a collective); ``restore`` merges every process file it
finds (shared filesystem, the norm for pod jobs) back into full host
arrays. Single-process solvers keep the flat single-file format.

Population sharding (ISSUE 7): a POPULATION-SHARDED solver
(``PGAConfig(pop_shards=S)``, ``parallel/shard_pop.py``) checkpoints
through these same paths as ONE LOGICAL ``(pop, genome_len)`` array —
single-process saves gather the addressable shards transparently, and
multi-process saves reuse the per-shard offset format above. The shard
count is a RESTORE-TIME choice, not a checkpoint property: the engine
re-places the restored array onto whatever mesh its current
``pop_shards`` demands at the next sharded run, so save@shards=4 →
restore@shards=2 needs no conversion (``tools/resize_smoke.py``'s
pop-shard leg proves the round trip).
"""

from __future__ import annotations

import glob
import os
import re
import zlib
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.robustness import faults as _faults

if TYPE_CHECKING:
    from libpga_tpu.engine import PGA

FORMAT_VERSION = 2  # single-file format
SHARD_FORMAT_VERSION = 3  # per-process shard format
_PROC_RE = re.compile(r"\.proc(\d+)\.npz$")  # shard-file suffix, save+restore


class CheckpointError(ValueError):
    """A checkpoint could not be restored (or written): version
    mismatch, missing/extra shard files, a truncated or corrupted file,
    or a per-array CRC mismatch. Carries the offending ``path`` so an
    operator knows WHICH file to repair — a ``ValueError`` subclass, so
    callers matching the historical error surface keep working."""

    def __init__(self, message: str, path: Optional[str] = None):
        self.path = path
        super().__init__(
            message if path is None else f"{message} [checkpoint: {path}]"
        )


def _crc32(arr: np.ndarray) -> np.uint32:
    """Per-array integrity word stored alongside each data array: CRC32
    of the raw little-endian bytes. Cheap relative to the npz deflate,
    and catches the silent-corruption class (bit flips, short writes
    inside an otherwise readable zip) that the container CRC alone
    cannot attribute to an array."""
    return np.uint32(zlib.crc32(np.ascontiguousarray(arr).tobytes()))


def _verify_crc(data, key: str, path: Optional[str]) -> np.ndarray:
    """Return ``data[key]``, verifying its recorded CRC when present
    (checkpoints written before the integrity manifest lack the crc
    keys and restore unverified, as before)."""
    try:
        arr = data[key]
    except KeyError:
        raise CheckpointError(f"checkpoint is missing array {key!r}", path)
    crc_key = f"{key}_crc32"
    if crc_key in data:
        stored = int(data[crc_key])
        actual = int(_crc32(arr))
        if stored != actual:
            raise CheckpointError(
                f"checkpoint array {key!r} is corrupted: stored crc32 "
                f"{stored:#010x} != computed {actual:#010x}",
                path,
            )
    return arr


def _np_load(path: str):
    """np.load that maps container-level corruption (truncated file,
    bad zip, unreadable header) to :class:`CheckpointError` naming the
    file, instead of a raw zipfile/OS error mid-restore."""
    try:
        return np.load(path)
    except Exception as e:
        raise CheckpointError(
            f"checkpoint file is unreadable ({type(e).__name__}: {e})", path
        )


def _encode(arr: np.ndarray):
    """npz-safe encoding: ml_dtypes bfloat16 has no npy representation
    (np.savez writes it as raw void '|V2' that jnp.asarray cannot read
    back), so non-npy dtypes are stored as their uint bit patterns with
    the true dtype name recorded alongside."""
    if arr.dtype.kind == "V" or arr.dtype.name == "bfloat16":
        return arr.view(np.uint16 if arr.dtype.itemsize == 2 else np.uint8), arr.dtype.name
    return arr, ""


def _decode(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    if not dtype_name:
        return arr
    import ml_dtypes

    return arr.view(np.dtype(getattr(ml_dtypes, dtype_name)))


def _addressable_shards(arr) -> List[Tuple[Tuple[int, ...], np.ndarray]]:
    """(start_offsets, data) for every shard this process can read.

    A plain numpy/host array is one full shard; a jax.Array contributes
    its addressable shards only (possibly none, when the whole array
    lives on another host's devices)."""
    if not isinstance(arr, jax.Array):
        a = np.asarray(arr)
        return [((0,) * a.ndim, a)]
    out = []
    seen = set()
    for s in arr.addressable_shards:
        starts = tuple(
            0 if sl.start is None else int(sl.start) for sl in s.index
        )
        if starts in seen:  # replicated shard — one copy is enough
            continue
        seen.add(starts)
        out.append((starts, np.asarray(s.data)))
    return out


def _atomic_savez(path: str, arrays: Dict[str, np.ndarray]) -> str:
    """np.savez via temp-file + os.replace: a preemption mid-write must
    never truncate the previous good checkpoint (the exact scenario this
    module exists for). Returns the final filename actually written
    (np.savez's ``.npz``-appending naming is preserved)."""
    final = path if path.endswith(".npz") else path + ".npz"
    # Sweep tmps of THIS final name from earlier hard-killed saves. Only
    # our own target's tmps: peers' in-flight tmps have different finals,
    # so a collective save can't race itself here.
    for orphan in glob.glob(f"{glob.escape(final)}.*.tmp.npz"):
        os.remove(orphan)
    tmp = f"{final}.{os.getpid()}.tmp.npz"  # .npz suffix: stop savez renaming
    try:
        np.savez(tmp, **arrays)
        # Fault-injection site (robustness/faults): firing BETWEEN the
        # temp write and the atomic rename is the kill-mid-checkpoint
        # point — the previous good checkpoint must survive (the finally
        # sweeps the temp), which tools/chaos_smoke.py proves.
        if _faults.PLAN is not None:
            _faults.PLAN.fire("checkpoint.save")
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return final


def _pack_array(arrays: Dict[str, np.ndarray], name: str, arr) -> None:
    """Store an array's addressable shards under ``name`` in ``arrays``."""
    shape = tuple(getattr(arr, "shape", np.shape(arr)))
    arrays[f"{name}_shape"] = np.asarray(shape, dtype=np.int64)
    for j, (starts, data) in enumerate(_addressable_shards(arr)):
        enc, dtype_name = _encode(data)
        arrays[f"{name}_shard{j}"] = enc
        arrays[f"{name}_shard{j}_crc32"] = _crc32(enc)
        arrays[f"{name}_shard{j}_dtype"] = np.asarray(dtype_name)
        arrays[f"{name}_shard{j}_start"] = np.asarray(starts, dtype=np.int64)


def _merge_array(files: List, name: str, paths: Optional[List[str]] = None):
    """Reassemble a full host array for ``name`` from all process files.
    ``paths`` (aligned with ``files``) names the offending file in
    integrity errors; each shard's recorded CRC is verified on read."""
    shape = dtype = None
    pieces = []
    for idx_f, data in enumerate(files):
        path = paths[idx_f] if paths else None
        if f"{name}_shape" not in data:
            continue
        shape = tuple(int(x) for x in data[f"{name}_shape"])
        j = 0
        while f"{name}_shard{j}" in data:
            piece = _decode(
                _verify_crc(data, f"{name}_shard{j}", path),
                str(data[f"{name}_shard{j}_dtype"]),
            )
            starts = tuple(int(x) for x in data[f"{name}_shard{j}_start"])
            pieces.append((starts, piece))
            dtype = piece.dtype
            j += 1
    if shape is None:
        raise CheckpointError(
            f"checkpoint is missing array {name!r}",
            paths[0] if paths else None,
        )
    full = np.zeros(shape, dtype=dtype)
    covered = np.zeros(shape, dtype=bool) if pieces else None
    for starts, piece in pieces:
        idx = tuple(
            slice(st, st + dim) for st, dim in zip(starts, piece.shape)
        )
        full[idx] = piece
        covered[idx] = True
    if covered is None or not covered.all():
        raise CheckpointError(
            f"checkpoint shards for {name!r} do not cover the full array "
            "(missing a process file?)",
            paths[0] if paths else None,
        )
    return full


def save(pga: "PGA", path: str) -> None:
    """Serialize all populations and the PRNG state.

    Single-process: one ``path`` .npz file. Multi-process (after
    ``jax.distributed.initialize``): a COLLECTIVE — every process writes
    ``<path>.proc<k>.npz`` with its addressable shards; no process ever
    touches a non-addressable buffer.
    """
    from libpga_tpu.utils import telemetry as _tl

    with _tl.span("checkpoint"):
        _save(pga, path)
    emit = getattr(pga, "_emit", None)
    if emit is not None:
        emit("checkpoint_save", path=path, seq=getattr(pga, "_ckpt_seq", 0))


def _save(pga: "PGA", path: str) -> None:
    # Monotonic per-solver save sequence: every process runs the same
    # engine calls, so the counter is identical across the fleet — at
    # restore it catches a checkpoint torn by preemption mid-save (one
    # process wrote generation N's shards, another still has N-1's).
    seq = getattr(pga, "_ckpt_seq", 0) + 1
    pga._ckpt_seq = seq

    if jax.process_count() > 1:
        if jax.process_index() == 0 and os.path.exists(path):
            # A stale single-process file at `path` would shadow the
            # shard set at restore time — remove it. Stale .proc<k>
            # files from an earlier WIDER run are deliberately left in
            # place: restore() reads only the file set the checkpoint
            # declares, and deleting them before this save's shard set
            # is durably written would destroy the only restorable
            # checkpoint if preemption hits mid-save.
            os.remove(path)
        arrays = {
            "__version__": np.asarray(SHARD_FORMAT_VERSION),
            "__num_populations__": np.asarray(len(pga.populations)),
            "__num_processes__": np.asarray(jax.process_count()),
            "__save_seq__": np.asarray(seq),
            "__key__": np.asarray(jax.random.key_data(pga._key)),
        }
        for i, pop in enumerate(pga.populations):
            _pack_array(arrays, f"genomes_{i}", pop.genomes)
            _pack_array(arrays, f"scores_{i}", pop.scores)
        _atomic_savez(f"{path}.proc{jax.process_index()}.npz", arrays)
        return

    arrays = {
        "__version__": np.asarray(FORMAT_VERSION),
        "__num_populations__": np.asarray(len(pga.populations)),
        "__key__": np.asarray(jax.random.key_data(pga._key)),
    }
    for i, pop in enumerate(pga.populations):
        genomes, dtype_name = _encode(np.asarray(pop.genomes))
        scores = np.asarray(pop.scores)
        arrays[f"genomes_{i}"] = genomes
        arrays[f"genomes_{i}_crc32"] = _crc32(genomes)
        arrays[f"genomes_dtype_{i}"] = np.asarray(dtype_name)
        arrays[f"scores_{i}"] = scores
        arrays[f"scores_{i}_crc32"] = _crc32(scores)
    _atomic_savez(path, arrays)
    # Only now is it safe to drop a previous run's shard set (see shadow
    # note above): restore() prefers the single file, and deleting the
    # shards BEFORE the new file durably exists would leave nothing
    # restorable if preemption hit mid-save.
    for stale in glob.glob(f"{path}.proc*.npz"):
        os.remove(stale)


class AutoCheckpointer:
    """Periodic checkpointing for long / preemptible runs.

    Hooks the engine's metrics callback and saves the full solver state
    every ``every_generations`` completed generations::

        ckpt = AutoCheckpointer(pga, "state.npz", every_generations=1000)
        for _ in range(100):
            pga.run_islands(500, 50, 0.05)
        ckpt.close()

    On restart, ``checkpoint.restore(pga, "state.npz")`` resumes from the
    last save (populations + PRNG stream). Multi-host safe: every process
    runs the same engine calls, so the metrics listener fires on all of
    them in lockstep and :func:`save`'s collective contract holds. The
    reference has no recovery story at all — any CUDA error exits the
    process (``pga.cu:31``).
    """

    def __init__(self, pga: "PGA", path: str, every_generations: int = 1000):
        self._pga = pga
        self._path = path
        self._every = every_generations
        self._since_save = 0
        pga.metrics.add_listener(self._on_run)

    def _on_run(self, rec):
        self._since_save += rec.generations
        if self._since_save >= self._every:
            save(self._pga, self._path)
            self._since_save = 0

    def close(self, final_save: bool = True):
        if final_save:
            save(self._pga, self._path)
        self._pga.metrics.remove_listener(self._on_run)


def restore(pga: "PGA", path: str) -> None:
    """Load populations and PRNG state saved by :func:`save` into ``pga``.

    Replaces any populations already in the engine. Accepts both the
    single-file format and the per-process shard format (all
    ``<path>.proc*.npz`` files are merged; on a multi-host job the
    filesystem must be shared, and the caller should barrier after
    ``save`` before restoring — e.g.
    ``jax.experimental.multihost_utils.sync_global_devices``).
    """
    from libpga_tpu.population import Population

    # Fault-injection site (robustness/faults): a raise here is a
    # restore-time I/O failure on the real path.
    if _faults.PLAN is not None:
        _faults.PLAN.fire("checkpoint.restore")

    if os.path.exists(path):
        _restore_single(pga, path)
        return

    by_idx = {}
    for f in glob.glob(f"{path}.proc*.npz"):
        m = _PROC_RE.search(f)
        if m:
            by_idx[int(m.group(1))] = f
    if 0 not in by_idx:
        raise FileNotFoundError(f"no checkpoint at {path} (or {path}.proc*.npz)")
    with _np_load(by_idx[0]) as head:
        version = int(head["__version__"])
        if version != SHARD_FORMAT_VERSION:
            raise CheckpointError(
                f"unsupported shard-checkpoint version {version}", by_idx[0]
            )
        expect = int(head["__num_processes__"])
    # Read exactly the file set the checkpoint declares: stale .proc<k>
    # leftovers with k >= expect (older, wider run) are ignored rather
    # than failing the count/seq consistency checks.
    missing = [k for k in range(expect) if k not in by_idx]
    if missing:
        raise CheckpointError(
            f"checkpoint written by {expect} processes is missing process "
            f"files {missing}",
            f"{path}.proc{missing[0]}.npz",
        )
    proc_paths = [by_idx[k] for k in range(expect)]
    datas = [_np_load(p) for p in proc_paths]
    try:
        n = int(datas[0]["__num_populations__"])
        seqs = {int(d["__save_seq__"]) for d in datas}
        if len(seqs) != 1:
            raise CheckpointError(
                f"inconsistent checkpoint: process files carry save "
                f"sequences {sorted(seqs)} (torn by preemption mid-save?)",
                path,
            )
        pga._key = jax.random.wrap_key_data(jnp.asarray(datas[0]["__key__"]))
        pga._populations = [
            Population(
                genomes=jnp.asarray(
                    _merge_array(datas, f"genomes_{i}", proc_paths)
                ),
                scores=jnp.asarray(
                    _merge_array(datas, f"scores_{i}", proc_paths)
                ),
            )
            for i in range(n)
        ]
        pga._staged = [None] * n
        pga._history = [None] * n
    finally:
        for d in datas:
            d.close()


def _restore_single(pga: "PGA", path: str) -> None:
    from libpga_tpu.population import Population

    with _np_load(path) as data:
        version = int(data["__version__"])
        if version not in (1, FORMAT_VERSION):
            raise CheckpointError(
                f"unsupported checkpoint version {version}", path
            )
        n = int(data["__num_populations__"])
        pga._key = jax.random.wrap_key_data(jnp.asarray(data["__key__"]))

        def genomes(i):
            g = _verify_crc(data, f"genomes_{i}", path)
            if version >= 2:
                g = _decode(g, str(data[f"genomes_dtype_{i}"]))
            return jnp.asarray(g)

        pga._populations = [
            Population(
                genomes=genomes(i),
                scores=jnp.asarray(_verify_crc(data, f"scores_{i}", path)),
            )
            for i in range(n)
        ]
        pga._staged = [None] * n
        pga._history = [None] * n
