"""PGA solver engine.

The Python-native equivalent of the reference's ``pga_t`` instance
(``src/pga.cu:48-56``): owns populations, the three user callbacks, the PRNG
stream, and the run loops. Everything device-side happens inside jitted
programs; the host only orchestrates.

Reference lifecycle parity:

- ``pga_init``/``pga_deinit``       → ``PGA()`` constructor / GC (nothing to
  free manually; JAX owns device buffers).
- ``pga_create_population``         → :meth:`PGA.create_population`.
- ``pga_set_*_function``            → :meth:`set_objective` /
  :meth:`set_mutate` / :meth:`set_crossover` (plain Python callables replace
  ``__device__`` fn pointers + ``cudaMemcpyFromSymbol``, ``pga.cu:157-161``).
- ``pga_run``                       → :meth:`run` — including the
  objective-value early termination the reference header promises
  (``pga.h:141``) but never implements.
- ``pga_get_best(_top)(_all)``      → :meth:`get_best` etc. — including the
  three NULL-stub variants (``pga.cu:238-248``), implemented on device.
- ``pga_evaluate/crossover/mutate/swap_generations`` → same-named methods
  operating on an explicit staged next-generation, for drivers that want
  the step-by-step API (the fused :meth:`run` path does not use staging).
- ``pga_run_islands``/``pga_migrate*`` → :meth:`run_islands` /
  :meth:`migrate` / :meth:`migrate_between` (stubs in the reference,
  ``pga.cu:368-374,393-395``; implemented here per the header spec).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.config import PGAConfig
from libpga_tpu.population import Population, create_population
from libpga_tpu.ops.evaluate import evaluate as _evaluate
from libpga_tpu.ops.select import select_parent_pairs
from libpga_tpu.ops.crossover import uniform_crossover
from libpga_tpu.ops.mutate import make_point_mutate
from libpga_tpu.ops.step import make_breed
from libpga_tpu.ops.topk import top_k_genomes
from libpga_tpu.utils.metrics import Metrics
from libpga_tpu.utils import telemetry as _tl
from libpga_tpu.robustness import faults as _faults


# Cache marker: the Pallas factory declined this (shape, kind) — skip
# re-probing it, but dispatch through the operator-instance-keyed XLA
# cache (the XLA fn bakes the operator in, so it must never be reused
# across operator swaps via a kind-only key).
_XLA_FALLBACK = object()


def _kind_key(kind):
    """Cache identity of a breeding kind: builtin names key by
    themselves; expression operators key by their COMPILED SEMANTICS
    (role, source, constant values — ``kernel_cache_key``), so an
    annealing schedule re-creating the same expression with new
    rate/sigma reuses the compiled kernel (the parameters are runtime
    inputs of the compiled fn, passed per call)."""
    return getattr(kind, "kernel_cache_key", kind)


def fold_injection(genomes, scores, inj_genomes, inj_scores, inj_n):
    """Fold externally evaluated candidates into a population at a
    generation boundary (the streaming ask/tell protocol, ISSUE 12):
    the first ``inj_n`` of the ``K`` injection slots replace the
    current WORST-scoring rows, and their TOLD fitnesses override the
    internal evaluation for the next selection (offspring are re-scored
    by the internal objective as usual). Pure jnp — runs inside the
    jitted run loops. With ``inj_n == 0`` the scatter writes back the
    values it read, so the folded state is value-identical to the
    unfolded one (the group-stepping no-op guarantee,
    tests/test_streaming.py)."""
    K = inj_genomes.shape[0]
    mask = jnp.arange(K) < inj_n
    worst = jnp.argsort(scores)[:K]
    cur_g = jnp.take(genomes, worst, axis=0)
    cur_s = jnp.take(scores, worst)
    new_g = jnp.where(mask[:, None], inj_genomes.astype(genomes.dtype), cur_g)
    new_s = jnp.where(mask, inj_scores, cur_s)
    return genomes.at[worst].set(new_g), scores.at[worst].set(new_s)


def make_run_loop(
    obj, breed, history_gens: Optional[int] = None,
    inject_slots: Optional[int] = None,
):
    """Build the fused single-run loop — the one implementation shared by
    the engine's XLA path and the serving mega-run executor
    (``serving/batch.py``), so their semantics cannot drift.

    ``breed`` takes ``(genomes, scores, key, mparams)``; operators with
    baked-in parameters simply ignore ``mparams`` (the engine wraps its
    3-arg breed), while the serving executor passes a runtime-parameter
    breed (``ops/step.make_param_breed``) so distinct mutation rates can
    share one compilation.

    Returns ``run_loop(genomes, key, n, target, mparams) ->
    (genomes, scores, gens_done[, history])``. The loop carries
    ``(genomes, scores)`` together and checks the target against the
    carried scores BEFORE breeding again, so the generation that reaches
    the target is the one returned — its offspring never overwrite it.
    With ``history_gens`` set the loop additionally carries the
    ``(history_gens, NUM_STATS)`` stats buffer + running best/stall
    scalars and returns a trailing history array; the disabled path
    traces to the exact pre-telemetry jaxpr (structurally asserted in
    tests/test_telemetry.py).

    ``inject_slots`` (ISSUE 12) grows the loop an INJECTION SLOT for
    the streaming ask/tell protocol: the returned loop takes three
    trailing inputs ``(inj_genomes (K, L), inj_scores (K,), inj_n)``
    and folds the first ``inj_n`` externally evaluated candidates over
    the worst rows at the generation boundary BEFORE the first breed
    (:func:`fold_injection`) — told fitnesses seed the next selection;
    every later generation re-scores through the internal objective.
    ``None`` (the default, every pre-streaming caller) leaves the code
    below untouched — the no-injection path traces to the exact
    pre-streaming jaxpr, which is what makes a ``step()``-only
    streaming session bit-identical to ``PGA.run``.
    """
    if inject_slots is not None:
        if history_gens is None:

            def run_loop(genomes, key, n, target, mparams,
                         inj_genomes, inj_scores, inj_n):
                scores0 = _evaluate(obj, genomes)
                genomes, scores0 = fold_injection(
                    genomes, scores0, inj_genomes, inj_scores, inj_n
                )

                def cond(carry):
                    g, s, k, gen = carry
                    return jnp.logical_and(gen < n, jnp.max(s) < target)

                def body(carry):
                    g, s, k, gen = carry
                    k, sub = jax.random.split(k)
                    g2 = breed(g, s, sub, mparams)
                    s2 = _evaluate(obj, g2)
                    return (g2, s2, k, gen + 1)

                init = (genomes, scores0, key, jnp.int32(0))
                g, s, k, gens_done = jax.lax.while_loop(cond, body, init)
                return g, s, gens_done

        else:

            def run_loop(genomes, key, n, target, mparams,
                         inj_genomes, inj_scores, inj_n):
                scores0 = _evaluate(obj, genomes)
                genomes, scores0 = fold_injection(
                    genomes, scores0, inj_genomes, inj_scores, inj_n
                )

                def cond(carry):
                    g, s, k, gen, best, stall, buf = carry
                    return jnp.logical_and(gen < n, jnp.max(s) < target)

                def body(carry):
                    g, s, k, gen, best, stall, buf = carry
                    k, sub = jax.random.split(k)
                    with jax.named_scope("pga/select_breed"):
                        g2 = breed(g, s, sub, mparams)
                    with jax.named_scope("pga/evaluate"):
                        s2 = _evaluate(obj, g2)
                    with jax.named_scope("pga/telemetry"):
                        row, best, stall = _tl.stats_row(g2, s2, best, stall)
                        buf = _tl.write_row(buf, gen, row)
                    return (g2, s2, k, gen + 1, best, stall, buf)

                init = (
                    genomes, scores0, key, jnp.int32(0),
                    jnp.max(scores0), jnp.int32(0),
                    _tl.history_init(history_gens),
                )
                g, s, k, gens_done, _, _, buf = jax.lax.while_loop(
                    cond, body, init
                )
                return g, s, gens_done, buf

        return run_loop

    if history_gens is None:

        def run_loop(genomes, key, n, target, mparams):
            scores0 = _evaluate(obj, genomes)

            def cond(carry):
                g, s, k, gen = carry
                return jnp.logical_and(gen < n, jnp.max(s) < target)

            def body(carry):
                g, s, k, gen = carry
                k, sub = jax.random.split(k)
                g2 = breed(g, s, sub, mparams)
                s2 = _evaluate(obj, g2)
                return (g2, s2, k, gen + 1)

            init = (genomes, scores0, key, jnp.int32(0))
            g, s, k, gens_done = jax.lax.while_loop(cond, body, init)
            return g, s, gens_done

    else:

        def run_loop(genomes, key, n, target, mparams):
            scores0 = _evaluate(obj, genomes)

            def cond(carry):
                g, s, k, gen, best, stall, buf = carry
                return jnp.logical_and(gen < n, jnp.max(s) < target)

            def body(carry):
                g, s, k, gen, best, stall, buf = carry
                k, sub = jax.random.split(k)
                with jax.named_scope("pga/select_breed"):
                    g2 = breed(g, s, sub, mparams)
                with jax.named_scope("pga/evaluate"):
                    s2 = _evaluate(obj, g2)
                with jax.named_scope("pga/telemetry"):
                    row, best, stall = _tl.stats_row(g2, s2, best, stall)
                    buf = _tl.write_row(buf, gen, row)
                return (g2, s2, k, gen + 1, best, stall, buf)

            init = (
                genomes, scores0, key, jnp.int32(0),
                jnp.max(scores0), jnp.int32(0),
                _tl.history_init(history_gens),
            )
            g, s, k, gens_done, _, _, buf = jax.lax.while_loop(
                cond, body, init
            )
            return g, s, gens_done, buf

    return run_loop


@dataclasses.dataclass(frozen=True)
class PopulationHandle:
    """Opaque handle to a population owned by a :class:`PGA` instance.

    Plays the role of the reference's ``population_t*`` (``pga.h:27``) —
    state lives in the engine; the handle survives functional updates.
    """

    index: int


class PGA:
    """A genetic-algorithm solver instance.

    Example::

        pga = PGA(seed=0)
        pop = pga.create_population(40_000, 100)
        pga.set_objective(lambda g: jnp.sum(g))
        pga.run(100)
        best = pga.get_best(pop)
    """

    def __init__(self, seed: Optional[int] = None, config: Optional[PGAConfig] = None):
        self.config = config or PGAConfig()
        if seed is None:
            seed = self.config.seed
        if seed is None:
            # Reference seeds cuRAND with time(NULL) (pga.cu:154); we use
            # fresh OS entropy when no seed is given.
            seed = int.from_bytes(__import__("os").urandom(4), "little")
        self._key = jax.random.key(seed)
        self._populations: List[Population] = []
        # Staged next generations for the step-by-step operator API — the
        # functional stand-in for the reference's current/next double buffer.
        self._staged: List[Optional[jax.Array]] = []
        self._objective: Optional[Callable] = None
        self._crossover: Callable = uniform_crossover
        self._mutate: Callable = make_point_mutate(self.config.mutation_rate)
        self._compiled: Dict[tuple, Callable] = {}
        self.metrics = Metrics()
        # Per-population History of the most recent telemetry-enabled
        # run (run_islands stores the shared global history in every
        # participating slot); None when telemetry is off.
        self._history: List[Optional[_tl.History]] = []
        self._events: Optional[_tl.EventLog] = None
        # One degradation warning per distinct cause (graceful kernel
        # fallback, config.fallback == "xla").
        self._degraded_warned: set = set()
        # One tuned_config event per (shape, resolved knobs) — the
        # tuning-DB resolution provenance record (ISSUE 10).
        self._tuned_emitted: set = set()

    # ------------------------------------------------------------------ RNG

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    # ----------------------------------------------------------- populations

    def create_population(
        self, size: int, genome_len: int, init: str = "random"
    ) -> PopulationHandle:
        limit = self.config.max_populations
        if limit is not None and len(self._populations) >= limit:
            raise RuntimeError(f"max_populations={limit} reached")
        pop = create_population(
            self.next_key(), size, genome_len, init=init, dtype=self.config.gene_dtype
        )
        self._populations.append(pop)
        self._staged.append(None)
        self._history.append(None)
        return PopulationHandle(len(self._populations) - 1)

    def install_population(self, genomes) -> PopulationHandle:
        """Install an EXPLICIT genome matrix as a new population (scores
        read ``-inf`` until the first evaluation, the
        ``swap_generations`` stance). The init path for representations
        whose valid genomes are not uniform noise — e.g. postfix GP
        programs (``gp.random_population``), warm starts, transfer
        seeding. Does not consume PRNG state."""
        genomes = jnp.asarray(genomes, dtype=self.config.gene_dtype)
        if genomes.ndim != 2:
            raise ValueError(
                f"install_population needs a (size, genome_len) matrix; "
                f"got shape {genomes.shape}"
            )
        limit = self.config.max_populations
        if limit is not None and len(self._populations) >= limit:
            raise RuntimeError(f"max_populations={limit} reached")
        scores = jnp.full((genomes.shape[0],), -jnp.inf, dtype=jnp.float32)
        self._populations.append(Population(genomes=genomes, scores=scores))
        self._staged.append(None)
        self._history.append(None)
        return PopulationHandle(len(self._populations) - 1)

    def population(self, handle: PopulationHandle) -> Population:
        return self._populations[handle.index]

    @property
    def populations(self) -> List[Population]:
        return list(self._populations)

    @property
    def num_populations(self) -> int:
        return len(self._populations)

    def _handles(self) -> List[PopulationHandle]:
        return [PopulationHandle(i) for i in range(len(self._populations))]

    # -------------------------------------------------------------- telemetry

    def _history_gens(self) -> Optional[int]:
        """History-buffer capacity, or None when the history carry is off
        (no telemetry config, or history_gens == 0)."""
        t = self.config.telemetry
        return t.history_gens if t is not None and t.history_gens > 0 else None

    def history(self, handle: PopulationHandle) -> Optional[_tl.History]:
        """Per-generation history of the population's most recent
        telemetry-enabled ``run``/``run_islands`` (columns
        ``telemetry.HISTORY_COLUMNS``: best/mean/std fitness, diversity
        proxy, stall counter), or None. Recorded ON DEVICE inside the
        fused loop — no host round trip per generation; granularity is
        per generation on the default paths, per launch on an explicit
        multi-generation kernel, per migration epoch on the island
        runners."""
        return self._history[handle.index]

    def _event_log(self) -> Optional[_tl.EventLog]:
        t = self.config.telemetry
        if t is None or not t.events_path:
            return None
        if self._events is None or self._events.path != t.events_path:
            if self._events is not None:
                self._events.close()
            self._events = _tl.EventLog(t.events_path)
            # run_record events ride the existing Metrics listener
            # registry — the same channel loggers/checkpointers use.
            self._events.attach(self.metrics)
        return self._events

    def _emit(self, event: str, **fields) -> None:
        _tl.flight_note(event, fields)  # post-mortem ring, always on
        log = self._event_log()
        if log is not None:
            log.emit(event, **fields)

    def _emit_gp_run(self, population_size: int) -> None:
        """One ``gp_run`` record per run whose objective is a GP
        objective family member (``gp/sr.py`` stamps ``gp_config``):
        the encoding the run is evolving under — the observability
        anchor for SR-as-a-service traffic (tools/gp_smoke.py gates
        the schema)."""
        gpc = getattr(self._objective, "gp_config", None)
        if gpc is None:
            return
        self._emit(
            "gp_run",
            population_size=population_size,
            max_nodes=gpc.max_nodes,
            n_ops=gpc.n_ops,
            n_vars=gpc.n_vars,
            optimize=bool(gpc.optimize),
            dispatch=gpc.dispatch or "dense",
        )

    def _check_stall_alert(self, hist: Optional[_tl.History]) -> None:
        t = self.config.telemetry
        if (
            t is not None and t.stall_alert_gens > 0 and hist is not None
            and len(hist) > 0 and int(hist.stall[-1]) >= t.stall_alert_gens
        ):
            self._emit(
                "stall_alert",
                stalled_gens=int(hist.stall[-1]),
                best=float(hist.best[-1]),
            )

    def program_report(
        self,
        handle: PopulationHandle,
        measured_gens_per_sec: Optional[float] = None,
    ) -> dict:
        """Roofline-attributed program report for this population's
        resolved program (ISSUE 17): per-generation FLOPs, HBM bytes,
        VMEM footprint, and the analytic roofline bound, derived from
        the dry-run plan resolvers — so it works on ANY backend,
        predicting the chip. Keyed exactly like the tuning database
        (``report["key"]``), resolved at the engine's own knob
        precedence (user > tuning DB > default), and emitted as one
        ``perf_report`` event. GP objectives (``gp/sr.py``) report
        their evaluator's cost instead of the breed kernel's.

        ``measured_gens_per_sec`` (e.g. from a bench round) adds the
        achieved-fraction-of-roofline fields (``perf/cost.achieved``)
        — the systematic replacement for the ad-hoc
        ``selection_matmul_mfu`` note in older bench artifacts.
        """
        from libpga_tpu import perf as _perf
        from libpga_tpu.tuning import db as _tdb

        pop = self._populations[handle.index]
        size, genome_len = int(pop.size), int(pop.genome_len)
        obj = self._objective
        key = _tdb.current_key(
            size, genome_len, self.config.gene_dtype,
            obj if obj is not None else "<unset>",
            _kind_key(self._crossover_kind()),
            _kind_key(self._mutate_kind()),
        )
        try:
            device_kind = getattr(
                jax.devices()[0], "device_kind", None
            )
        except RuntimeError:
            device_kind = None
        gpc = getattr(obj, "gp_config", None)
        if gpc is not None:
            # The SR objective stamps the evaluator knobs it was built
            # at (gp/sr.py: user > tuning DB > auto, resolved at build).
            ka = tuple(getattr(obj, "knob_args", ()) or ())
            gp_sd, gp_ob, gp_disp = (ka + (None, None, None))[:3]
            live = None
            if gpc.optimize:
                # Measured mean post-compaction live length of THIS
                # population — what the fast path's trips actually are.
                from libpga_tpu.gp.optimize import mean_live_length

                live = mean_live_length(pop.genomes, gpc)
            report = _perf.gp_report(
                size, gpc,
                int(getattr(obj, "sr_samples", 0)) or 64,
                stack_depth=gp_sd, opcode_block=gp_ob,
                dispatch=gp_disp, live_length=live,
                device_kind=device_kind,
            )
            report["dispatch_path"] = report["path"]
            if live is not None:
                report["live_length_mean"] = live
        else:
            deme, layout, subblock, _ = self._resolved_pallas_knobs(
                size, genome_len
            )
            ck = self._crossover_kind()
            mk = self._mutate_kind()
            report = _perf.breed_report(
                size, genome_len,
                gene_dtype=self.config.gene_dtype,
                tournament_size=self.config.tournament_size,
                selection_kind=self.config.selection,
                selection_param=self.config.selection_param,
                crossover_kind=ck if ck is not None else "uniform",
                mutate_kind=mk if mk is not None else "point",
                deme_size=deme, layout=layout, subblock=subblock,
                generations_per_launch=(
                    self.config.pallas_generations_per_launch
                ),
                const_carrying=bool(
                    tuple(getattr(obj, "kernel_rowwise_consts", ()))
                ),
                device_kind=device_kind,
            )
            # The analytic fields predict the FUSED kernel wherever the
            # plan resolves; dispatch_path records what THIS backend
            # would actually run (the XLA step path off-TPU).
            report["dispatch_path"] = (
                report["path"] if self._pallas_gate() else "xla"
            )
        report["key"] = key.as_string()
        if measured_gens_per_sec is not None:
            report.update(_perf.achieved(report, measured_gens_per_sec))
        self._emit(
            "perf_report",
            key=report["key"],
            path=report["path"],
            roofline_gens_per_sec=report.get("roofline_gens_per_sec"),
            bound=report.get("bound"),
            dispatch_path=report["dispatch_path"],
        )
        return report

    # ------------------------------------------------------------- callbacks

    def set_objective(self, fn) -> None:
        """Set the fitness function: ``(genome,) -> scalar``, higher better.

        Accepts a callable or the name of a builtin objective from
        :mod:`libpga_tpu.objectives`.
        """
        if isinstance(fn, str):
            from libpga_tpu import objectives

            fn = objectives.get(fn)
        self._objective = fn
        self._compiled.clear()

    def set_mutate(self, fn: Optional[Callable]) -> None:
        """Set the mutation ``(genome, rand) -> genome``; None → default
        point mutation (reference semantics, ``pga.cu:127-133``)."""
        self._mutate = fn if fn is not None else make_point_mutate(
            self.config.mutation_rate
        )
        self._compiled.clear()

    def set_crossover(self, fn: Optional[Callable]) -> None:
        """Set the crossover ``(p1, p2, rand) -> child``; None → default
        uniform crossover (reference semantics, ``pga.cu:135-143``)."""
        self._crossover = fn if fn is not None else uniform_crossover
        self._compiled.clear()

    def _require_objective(self) -> Callable:
        if self._objective is None:
            raise RuntimeError(
                "no objective set — call set_objective() before evaluating"
            )
        return self._objective

    def _validate(
        self, where: str, indices=None, staged: bool = False,
        oracle: bool = True,
    ):
        """Runtime validation mode (``config.validate`` — see
        ``utils/validate``): check the named populations' invariants
        against the XLA oracle after a state-installing operation.
        ``staged`` checks the staged next generation's gene domain
        instead (it has no scores yet). ``oracle=False`` skips the
        score re-evaluation — used after :meth:`evaluate`, whose scores
        COME from the oracle path (comparing it to itself can catch
        nothing and would double the op's cost)."""
        if not self.config.validate:
            return
        from libpga_tpu.utils.validate import check_population as _check

        def check_population(*args, **kw):
            # Event-log hook: a validation failure is exactly the kind of
            # in-run anomaly the structured log exists to capture.
            try:
                _check(*args, **kw)
            except Exception as e:
                self._emit(
                    "validation_failure", where=where,
                    index=kw.get("index"), error=str(e),
                )
                raise

        if indices is None:
            indices = range(len(self._populations))

        def addressable(arr):
            # On a multi-process mesh a population may live entirely on
            # another host — np.asarray on it raises. Validate what
            # this process can see; peers validate their own shards
            # (every process runs the same engine calls).
            return not isinstance(arr, jax.Array) or arr.is_fully_addressable

        for i in indices:
            if staged:
                if self._staged[i] is not None and addressable(
                    self._staged[i]
                ):
                    check_population(
                        None, self._staged[i], None, where=where, index=i
                    )
                continue
            pop = self._populations[i]
            if not (
                addressable(pop.genomes) and addressable(pop.scores)
            ):
                continue
            check_population(
                self._objective if oracle else None,
                pop.genomes, pop.scores, where=where, index=i,
            )

    # --------------------------------------------------------- fused run loop

    def _breed_fn(self) -> Callable:
        """Cached breed (select+crossover+mutate) for the current callbacks."""
        cache_key = (
            "engine/breed", self._crossover, self._mutate,
            self.config.tournament_size, self.config.elitism,
            self.config.selection, self.config.selection_param,
        )
        fn = self._compiled.get(cache_key)
        if fn is None:
            fn = make_breed(
                self._crossover,
                self._mutate,
                tournament_size=self.config.tournament_size,
                selection_kind=self.config.selection,
                selection_param=self.config.selection_param,
                elitism=self.config.elitism,
            )
            self._compiled[cache_key] = fn
        return fn

    def _compiled_run(self, size: int, genome_len: int) -> Callable:
        """One compiled while_loop serving every (n, target) for this shape.

        The loop carries ``(genomes, scores)`` together and checks the
        target against the carried scores BEFORE breeding again, so the
        generation that reaches the target is the one returned — its
        offspring never overwrite it.

        Returns ``fn(genomes, key, n, target, mparams)``. On the Pallas
        path ``mparams`` is the runtime mutation-parameter input (so
        annealing schedules share one compilation — the cache key holds
        the mutation KIND, not the operator instance); the XLA path bakes
        the operator in and ignores it.

        Telemetry (``config.telemetry`` with history_gens > 0): the loop
        additionally carries the (history_gens, NUM_STATS) stats buffer +
        running best/stall scalars and the fn returns a trailing history
        array. The DISABLED path is the exact code below, untouched — it
        traces to the same jaxpr as before telemetry existed
        (structurally asserted in tests/test_telemetry.py).
        """
        return self._compiled_run_meta(size, genome_len)[0]

    def _degrade(self, what: str, error: BaseException, **fields) -> None:
        """Record a graceful kernel degradation (policy "xla"): one-time
        warning per cause + a ``degraded`` telemetry event + an
        automatic flight-recorder dump (the degradation's recent
        context — launches, faults, retries — is exactly what the
        post-mortem needs). The caller has already decided to fall
        back."""
        self._emit("degraded", what=what, error=str(error), **fields)
        _tl.flight_dump("degraded")
        cause = (what, type(error).__name__)
        if cause in self._degraded_warned:
            return
        self._degraded_warned.add(cause)
        import warnings

        warnings.warn(
            f"fused Pallas {what} failed ({type(error).__name__}: {error})"
            " — degrading this config to the XLA step path"
            " (PGAConfig(fallback='raise') to fail fast instead)",
            stacklevel=4,
        )

    def _resolved_pallas_knobs(self, size: int, genome_len: int) -> tuple:
        """Kernel-knob resolution for one breeding shape under the
        precedence **explicit user knob > tuning-DB entry > built-in
        default** (ISSUE 10): returns ``(deme_size, layout, subblock,
        provenance)``.

        With no tuning database installed (``tuning.set_tuning_db`` /
        ``PGA_TUNING_DB``), or no entry for this signature, the
        returned values are LITERALLY the config's own fields and
        provenance is None — the traced program is byte-identical to
        the pre-tuning code. A matched entry resolves only the knobs
        the user left on auto, emits one ``tuned_config`` event per
        (shape, knobs), and joins the compiled-program cache keys so a
        database swap re-keys cleanly."""
        from libpga_tpu.tuning import db as _tdb

        tdb = _tdb.active_db()
        entry = None
        if tdb is not None and self._objective is not None:
            entry = tdb.lookup(_tdb.current_key(
                size, genome_len, self.config.gene_dtype,
                self._objective,
                _kind_key(self._crossover_kind()),
                _kind_key(self._mutate_kind()),
            ))
        knobs, prov = _tdb.resolve_config_knobs(self.config, entry)
        resolved = (
            knobs["pallas_deme_size"], knobs["pallas_layout"],
            knobs["pallas_subblock"],
        )
        if prov is not None:
            mark = (size, genome_len, resolved)
            if mark not in self._tuned_emitted:
                self._tuned_emitted.add(mark)
                self._emit(
                    "tuned_config", population_size=size,
                    genome_len=genome_len, knobs=dict(knobs),
                    provenance=dict(prov), db=_tdb.active_path(),
                )
        return resolved + (prov,)

    def _compiled_run_meta(
        self, size: int, genome_len: int
    ) -> Tuple[Callable, Optional[tuple]]:
        """(compiled run fn, pallas cache key or None). The key is
        non-None exactly when the returned fn is the fused Pallas path —
        ``run()`` uses it to retire the entry and re-dispatch on the XLA
        path when a first dispatch fails under ``fallback="xla"``."""
        obj = self._require_objective()
        hist_gens = self._history_gens()
        pallas_kind = self._mutate_kind() if self._pallas_gate() else None
        if pallas_kind is None:
            self._warn_xla_fallback()
        if pallas_kind is not None:
            deme, layout, subblock, _ = self._resolved_pallas_knobs(
                size, genome_len
            )
            # Keyed by mutation KIND: rate/sigma are runtime inputs of the
            # compiled fn. A declined shape caches the _XLA_FALLBACK
            # sentinel — NOT the XLA fn itself, which bakes the operator
            # instance in and must stay keyed by it below. The RESOLVED
            # knobs (not the raw config fields) key the entry, so
            # installing a different tuning DB re-compiles instead of
            # reusing a stale kernel.
            pkey = (
                "engine/run-pallas", size, genome_len, obj,
                _kind_key(pallas_kind),
                _kind_key(self._crossover_kind()), self.config.elitism,
                self.config.tournament_size, self.config.selection,
                self.config.selection_param,
                self.config.pallas_generations_per_launch,
                deme, layout, subblock,
                hist_gens,
            )
            cached = self._compiled.get(pkey)
            if cached is None:
                from libpga_tpu.ops.pallas_step import make_pallas_run

                self._emit(
                    "compile", what="run_pallas", population_size=size,
                    genome_len=genome_len,
                )
                try:
                    cached = self._build_pallas_run(
                        make_pallas_run, obj, pallas_kind, size,
                        genome_len, hist_gens,
                    )
                except Exception as e:
                    # Graceful degradation: an unvalidated Mosaic
                    # lowering (or an injected kernel.build fault) must
                    # never take down the process under the default
                    # policy — the config drops to the XLA step path.
                    if self.config.fallback == "raise":
                        raise
                    self._degrade(
                        "kernel build", e, population_size=size,
                        genome_len=genome_len,
                    )
                    cached = _XLA_FALLBACK
                self._compiled[pkey] = cached
            if cached is not _XLA_FALLBACK:
                return cached, pkey

        cache_key = (
            "engine/run-xla", size, genome_len, obj, self._crossover,
            self._mutate,
            self.config.tournament_size, self.config.elitism,
            self.config.selection, self.config.selection_param,
            hist_gens,
        )
        fn = self._compiled.get(cache_key)
        if fn is not None:
            return fn, None
        self._emit(
            "compile", what="run_xla", population_size=size,
            genome_len=genome_len,
        )

        breed3 = self._breed_fn()

        def breed(g, s, k, mparams):
            # Operator parameters are baked into the engine's breed; the
            # runtime mparams input exists for the Pallas and serving
            # paths and is simply unused here.
            return breed3(g, s, k)

        run_loop = make_run_loop(obj, breed, hist_gens)
        donate = (0,) if self.config.donate_buffers else ()
        fn = jax.jit(run_loop, donate_argnums=donate)
        self._compiled[cache_key] = fn
        return fn, None

    def _build_pallas_run(
        self, make_pallas_run, obj, pallas_kind, size, genome_len,
        hist_gens,
    ):
        """Build the fused run fn for one shape, or ``_XLA_FALLBACK``
        when the factory declines. Raises when the build itself fails —
        the caller applies the ``config.fallback`` policy. Kernel knobs
        are the TUNED resolution (user > DB > default) for this shape —
        with no DB these are exactly the config fields."""
        deme, layout, subblock, _ = self._resolved_pallas_knobs(
            size, genome_len
        )
        factory = make_pallas_run(
            obj,
            tournament_size=self.config.tournament_size,
            selection_kind=self.config.selection,
            selection_param=self.config.selection_param,
            # Defaults for callers that pass no runtime params;
            # the engine always passes self._mutate_params().
            mutation_rate=self._mutation_rate(),
            mutation_sigma=self._operator_param("sigma", 0.0),
            crossover_kind=self._crossover_kind(),
            mutate_kind=pallas_kind,
            elitism=self.config.elitism,
            deme_size=deme,
            donate=self.config.donate_buffers,
            gene_dtype=self.config.gene_dtype,
            generations_per_launch=(
                self.config.pallas_generations_per_launch
            ),
            history_gens=hist_gens,
            layout=layout,
            subblock=subblock,
        )
        pallas_fn = factory(size, genome_len) if factory else None
        return pallas_fn if pallas_fn is not None else _XLA_FALLBACK

    def _mutate_kind(self):
        """Kernel-implementable mutation kind of the active operator, or
        None. Builtin operators map to their kind NAME (rate/sigma are
        runtime inputs, so e.g. an annealing schedule swapping
        ``make_gaussian_mutate(rate, sigma)`` per phase reuses one
        compilation); an EXPRESSION operator
        (``ops/breed_expr.mutate_from_expression``) is itself the kind —
        its compiled rowwise form evaluates inside the kernel, and the
        operator instance keys the compiled fast path."""
        from libpga_tpu.ops import mutate as _m

        func = getattr(self._mutate, "func", None)
        if func is _m.point_mutate:
            return "point"
        if func is _m.gaussian_mutate:
            return "gaussian"
        if func is _m.swap_mutate:
            return "swap"
        if getattr(self._mutate, "kernel_rows", None) is not None:
            return self._mutate
        return None

    # Fused expression equivalents of the builtin crossovers that have
    # no named in-kernel kind. one_point: the builtin draws its cut from
    # rand[0]; the expression draws it from the per-row stream q — a
    # different PRNG stream but the identical cut distribution
    # (uniform over gene positions). arithmetic: per-gene convex blend
    # with a fresh uniform weight, exactly the builtin's semantics (the
    # expression path's [0, 1) output clip is a no-op on convex blends
    # of in-domain genes). Compiled once per engine instance and cached
    # under the module-level expression cache key, so every engine maps
    # these builtins to ONE kernel compilation.
    _CROSSOVER_EXPRS = {
        "one_point": "where(i < floor(q * L), p1, p2)",
        "arithmetic": "r * p1 + (1 - r) * p2",
    }

    def _crossover_expr_equivalent(self, name: str):
        cache_key = ("engine/crossover-expr-builtin", name)
        op = self._compiled.get(cache_key)
        if op is None:
            from libpga_tpu.ops.breed_expr import crossover_from_expression

            op = crossover_from_expression(self._CROSSOVER_EXPRS[name])
            self._compiled[cache_key] = op
        return op

    def _crossover_kind(self):
        """Kernel-implementable crossover kind of the active operator:
        uniform (the reference default), order-preserving (the
        reference TSP driver's custom crossover, in-kernel as an
        unrolled VMEM visited-table walk), an expression operator
        (``ops/breed_expr.crossover_from_expression``) evaluated
        in-kernel — or, for the builtin one-point/arithmetic operators,
        their fused expression equivalents (they used to return None
        here, silently dropping the whole run to the ~10× slower XLA
        path)."""
        from libpga_tpu.ops import crossover as _c

        if self._crossover is _c.uniform_crossover:
            return "uniform"
        if self._crossover is _c.order_preserving_crossover:
            return "order"
        if self._crossover is _c.one_point_crossover:
            return self._crossover_expr_equivalent("one_point")
        if self._crossover is _c.arithmetic_crossover:
            return self._crossover_expr_equivalent("arithmetic")
        if getattr(self._crossover, "kernel_rows", None) is not None:
            return self._crossover
        return None

    def _operator_param(self, name: str, default: float) -> float:
        v = getattr(self._mutate, name, None)
        if v is None:
            v = getattr(self._mutate, "keywords", {}).get(name)
        if v is None:
            # A bare ``partial(gaussian_mutate)`` executes at the
            # operator's own signature defaults — the kernel must match
            # those, not a literal copy that can drift out of sync.
            func = getattr(self._mutate, "func", None)
            if func is not None:
                import inspect

                p = inspect.signature(func).parameters.get(name)
                if p is not None and p.default is not inspect.Parameter.empty:
                    return p.default
        return default if v is None else v

    def _mutate_params(self) -> jax.Array:
        """(1, 2) f32 [rate, sigma] runtime input for the Pallas kernel."""
        kind = self._mutate_kind()
        if kind == "gaussian":
            rate = self._operator_param("rate", 0.1)
            sigma = self._operator_param("sigma", 0.1)
        elif callable(kind):
            # Expression mutation: the factory stamps the values its
            # ``rate``/``sigma`` variables were declared with, so the
            # kernel and XLA paths agree.
            rate = self._operator_param("rate", self.config.mutation_rate)
            sigma = self._operator_param("sigma", 0.0)
        else:
            rate, sigma = self._mutation_rate(), 0.0
        return jnp.asarray([[rate, sigma]], dtype=jnp.float32)

    def _mutation_rate(self) -> float:
        """The rate bound into the active mutate operator. A raw
        ``partial(point_mutate, rate=r)`` passes the default-operator gate
        but lacks the ``.rate`` attribute ``make_point_mutate`` sets — read
        its ``keywords`` so the kernel runs at r, not the config default.
        When no rate is discoverable at all (bare ``partial(point_mutate)``)
        the operator executes at its own signature default, so that — not
        the config value — is what the kernel must match."""
        return self._operator_param("rate", self.config.mutation_rate)

    def _pallas_gate(self) -> bool:
        """Single source of truth for Pallas fast-path eligibility, shared
        by the single-population run loop and the island runner. The
        kernel implements uniform or order-preserving crossover with
        point, gaussian, or swap mutation, k-way tournaments (k ≤ 16),
        elitism (fused objectives), and f32/bf16 genes (order crossover:
        f32 only — make_pallas_breed declines bf16), and requires a real
        TPU."""
        return (
            self.config.pallas_enabled()
            and self._crossover_kind() is not None
            and self._mutate_kind() is not None
            and 1 <= self.config.tournament_size <= 16
            and self.config.gene_dtype in (jnp.float32, jnp.bfloat16)
            and self._pallas_backend_ok()
        )

    def _pallas_backend_ok(self) -> bool:
        """The Mosaic kernel only lowers on a real TPU backend."""
        import jax as _jax

        return _jax.default_backend() == "tpu"

    def _warn_xla_fallback(self) -> None:
        """Documented fallback warning: the run COULD take the fused
        Pallas path (config + backend allow it) but the active
        crossover/mutation operator has no in-kernel form, so the whole
        run drops to the XLA operator path — ~10× slower at headline
        scale (BASELINE.md). Builtin operator kinds and expression
        operators (``ops/breed_expr``) run in-kernel; an opaque Python
        callable cannot. One warning per distinct cause; the fallback
        itself is still taken (the result is correct, just slow)."""
        if not (self.config.pallas_enabled() and self._pallas_backend_ok()):
            return
        missing = [
            name
            for name, kind, op in (
                ("crossover", self._crossover_kind(), self._crossover),
                ("mutation", self._mutate_kind(), self._mutate),
            )
            # xla_only operators (the GP structural operators,
            # gp/operators.py) are LEGITIMATELY kernel-less — their
            # fused half is the evaluator, not the breed — so the
            # "you forgot an in-kernel form" warning stays quiet.
            if kind is None and not getattr(op, "xla_only", False)
        ]
        if not missing:
            return
        import warnings

        warnings.warn(
            f"custom {' and '.join(missing)} operator(s) have no "
            "in-kernel form — this run falls back to the XLA operator "
            "path (~10x slower at 1M scale). Use a builtin operator, "
            "or compile the operator with "
            "ops.breed_expr.crossover_from_expression / "
            "mutate_from_expression to keep the fused Pallas path.",
            stacklevel=3,
        )

    def _pallas_island_breed(self, island_size: int, genome_len: int):
        """Fused Pallas breed for one island, or None if ineligible.

        The returned callable is vmapped across islands by the runner, so
        the kernel's deme shuffle stays island-local and island semantics
        hold. Mutation rate/sigma are runtime inputs of the breed (the
        runner passes the engine's current ``_mutate_params()``), so the
        cache key carries only the mutation KIND."""
        if not self._pallas_gate():
            return None
        obj = self._require_objective()
        fused = getattr(obj, "kernel_rowwise", None)
        from libpga_tpu.ops.pallas_step import (
            make_pallas_breed,
            make_pallas_multigen,
        )

        # The tuned resolution keys on the ISLAND size — the shape the
        # kernel actually breeds (a DB tuned at the full-population
        # shape deliberately misses here).
        deme, layout, subblock, _ = self._resolved_pallas_knobs(
            island_size, genome_len
        )
        # Cached: runner caching downstream keys on the breed's identity,
        # so rebuilding it per call would defeat compilation reuse.
        cache_key = (
            "engine/island-breed", island_size, genome_len, obj, fused,
            _kind_key(self._crossover_kind()),
            _kind_key(self._mutate_kind()),
            self.config.elitism, self.config.tournament_size,
            self.config.selection, self.config.selection_param,
            self.config.pallas_generations_per_launch,
            deme, layout, subblock,
        )
        if cache_key in self._compiled:
            return self._compiled[cache_key]
        # One-generation island epoch by DEFAULT for both dtypes since
        # round 5: the round-4 f32 tie (multigen whole-interval launches
        # vs per-generation launches + hoisted sort, medians 128.6 vs
        # 132.0) flipped decisively once the one-generation kernel's
        # score stores were batched — 5-round interleaved A/B: one-gen
        # 149.2 vs multigen 127.0 gens/sec on the 8×131k bench shape,
        # 5/5 rounds (BASELINE.md round 5; bf16 already measured faster
        # one-generation in round 4). An explicit config value rules
        # either way (1 = one-generation, >1 = multigen epoch chunk
        # cap — the structural one-launch-per-interval option remains).
        T_cfg = self.config.pallas_generations_per_launch
        use_island_multigen = T_cfg is not None and T_cfg > 1
        if use_island_multigen and fused is None:
            # Same contract as make_pallas_run: an explicitly requested
            # T > 1 must not degrade silently, including for objectives
            # without an in-kernel form.
            import warnings

            warnings.warn(
                "pallas_generations_per_launch="
                f"{self.config.pallas_generations_per_launch} requested"
                " but the objective has no in-kernel (kernel_rowwise)"
                " form — islands fall back to the one-generation path",
                stacklevel=3,
            )
        if use_island_multigen and fused is not None:
            try:
                bm = make_pallas_multigen(
                    island_size,
                    genome_len,
                    deme_size=deme,
                    tournament_size=self.config.tournament_size,
                    selection_kind=self.config.selection,
                    selection_param=self.config.selection_param,
                    mutation_rate=self._mutation_rate(),
                    mutation_sigma=self._operator_param("sigma", 0.0),
                    crossover_kind=self._crossover_kind(),
                    mutate_kind=self._mutate_kind(),
                    elitism=self.config.elitism,
                    fused_obj=fused,
                    fused_consts=tuple(
                        getattr(obj, "kernel_rowwise_consts", ())
                    ),
                    gene_dtype=self.config.gene_dtype,
                    _layout=layout,
                )
            except Exception as e:
                if self.config.fallback == "raise":
                    raise
                self._degrade(
                    "island multigen kernel build", e,
                    island_size=island_size, genome_len=genome_len,
                )
                bm = None
            if bm is not None:
                # An explicit config value bounds the island epoch's
                # per-launch generation count too (None → the island
                # default, see islands.make_multigen_stacked_epoch).
                bm.epoch_chunk = self.config.pallas_generations_per_launch
                self._compiled[cache_key] = bm
                return bm
            if self.config.pallas_generations_per_launch is not None:
                # Same contract as make_pallas_run: an explicit T > 1
                # must not degrade silently (a T-sweep over islands
                # would measure T=1 at every point).
                import warnings

                warnings.warn(
                    "pallas_generations_per_launch="
                    f"{self.config.pallas_generations_per_launch} requested"
                    " but the island multi-generation kernel declined —"
                    " falling back to the one-generation island path",
                    stacklevel=3,
                )
        try:
            pb = make_pallas_breed(
                island_size,
                genome_len,
                deme_size=deme,
                tournament_size=self.config.tournament_size,
                selection_kind=self.config.selection,
                selection_param=self.config.selection_param,
                mutation_rate=self._mutation_rate(),
                mutation_sigma=self._operator_param("sigma", 0.0),
                crossover_kind=self._crossover_kind(),
                mutate_kind=self._mutate_kind(),
                # Without fused scores the kernel can't carry elites itself;
                # the island epoch applies them after its separate evaluation
                # (run_islands passes the epoch-level elitism).
                elitism=self.config.elitism if fused is not None else 0,
                fused_obj=fused,
                fused_consts=tuple(getattr(obj, "kernel_rowwise_consts", ())),
                gene_dtype=self.config.gene_dtype,
                _layout=layout,
                _subblock=subblock,
            )
        except Exception as e:
            # Degrade THIS config to the XLA island breed (caller falls
            # back on the cached None) instead of killing the run.
            if self.config.fallback == "raise":
                raise
            self._degrade(
                "island kernel build", e, island_size=island_size,
                genome_len=genome_len,
            )
            pb = None
        self._compiled[cache_key] = pb
        return pb

    # ----------------------------------------------------- injection (ask/tell)

    def _compiled_run_inject(self, size: int, genome_len: int, K: int):
        """Compiled XLA run loop WITH the ``inject_slots=K`` boundary
        fold (ISSUE 12) — the program a streaming session's fold-step
        dispatches. Cached per (shape, K, operators) exactly like the
        plain XLA run; the fused Pallas path has no injection slot, so
        a folding run always takes this program (the fold itself is one
        argsort + scatter — negligible next to a generation)."""
        obj = self._require_objective()
        hist_gens = self._history_gens()
        cache_key = (
            "engine/run-xla-inject", K, size, genome_len, obj,
            self._crossover, self._mutate,
            self.config.tournament_size, self.config.elitism,
            self.config.selection, self.config.selection_param,
            hist_gens,
        )
        fn = self._compiled.get(cache_key)
        if fn is not None:
            return fn
        self._emit(
            "compile", what="run_xla_inject", population_size=size,
            genome_len=genome_len, inject_slots=K,
        )
        breed3 = self._breed_fn()

        def breed(g, s, k, mparams):
            return breed3(g, s, k)

        run_loop = make_run_loop(obj, breed, hist_gens, inject_slots=K)
        donate = (0,) if self.config.donate_buffers else ()
        fn = jax.jit(run_loop, donate_argnums=donate)
        self._compiled[cache_key] = fn
        return fn

    def _prepare_inject(self, pop: Population, inject) -> tuple:
        """Validate and normalize a ``run(inject=...)`` payload:
        ``(genomes (m, L) f32-host, scores (m,) f32, m)``."""
        inj_g, inj_s = inject
        inj_g = np.asarray(inj_g, dtype=np.float32)
        inj_s = np.asarray(inj_s, dtype=np.float32).reshape(-1)
        if inj_g.ndim != 2 or inj_g.shape[1] != pop.genome_len:
            raise ValueError(
                f"inject genomes {inj_g.shape} incompatible with "
                f"genome_len {pop.genome_len}"
            )
        if inj_g.shape[0] != inj_s.shape[0]:
            raise ValueError(
                f"inject carries {inj_g.shape[0]} genomes but "
                f"{inj_s.shape[0]} fitnesses"
            )
        if inj_g.shape[0] > pop.size:
            raise ValueError(
                f"cannot fold {inj_g.shape[0]} candidates into a "
                f"population of {pop.size}"
            )
        return inj_g, inj_s, inj_g.shape[0]

    @staticmethod
    def _inject_slot_width(m: int, size: int) -> int:
        """Slot count the fold program compiles at: next power of two
        >= m, capped at the population size — so repeated folds of
        varying widths reuse a handful of compiled programs."""
        K = 1
        while K < m:
            K *= 2
        return min(K, size)

    def run(
        self,
        n: int,
        target: Optional[float] = None,
        population: Optional[PopulationHandle] = None,
        inject=None,
    ) -> int:
        """Run the standard GA for up to ``n`` generations.

        Operates on the first population by default (reference ``pga_run``
        touches ``populations[0]`` only, ``pga.cu:382-386``). Stops early as
        soon as a generation's best score reaches ``target`` — the behavior
        promised by ``pga.h:137-143`` and missing from the reference
        implementation.

        ``inject`` (ISSUE 12): an optional ``(genomes (m, L), fitnesses
        (m,))`` pair of EXTERNALLY evaluated candidates folded in at the
        generation boundary before the first breed — they replace the
        current worst rows and their told fitnesses seed the next
        selection (see :func:`fold_injection`). ``None`` (every
        pre-streaming caller) leaves the run paths byte-identical to the
        pre-injection code. On a POPULATION-SHARDED run the fold happens
        host-side before dispatch and the told fitnesses are re-scored
        by the internal objective (the sharded loop evaluates its own
        scores inside ``shard_map``).

        Returns the number of generations actually executed. Without a
        target this is exactly ``n``; with one, the default
        (one-generation kernel) reports the exact reaching generation.
        An EXPLICIT ``config.pallas_generations_per_launch`` > 1 runs
        the multi-generation kernel, which checks the target once per
        launch — the count on early exit is then a multiple of T (up to
        T-1 high) and a mid-launch achiever is preserved by the
        kernel's group freeze.
        """
        if self.config.pop_shards > 1:
            # Giant populations (ROADMAP 2): the population axis splits
            # across the device mesh. pop_shards=1 (the default) never
            # reaches the sharded path — the code below is byte-for-byte
            # the pre-sharding run loop (tests/test_shard_pop.py pins
            # its StableHLO).
            if inject is not None:
                self._fold_host(population or PopulationHandle(0), inject)
            return self._run_sharded(n, target, population)
        handle = population or PopulationHandle(0)
        pop = self._populations[handle.index]
        inject_extra = ()
        if inject is not None:
            inj_g, inj_s, m = self._prepare_inject(pop, inject)
            K = self._inject_slot_width(m, pop.size)
            pad = K - m
            if pad:
                inj_g = np.concatenate(
                    [inj_g, np.zeros((pad, pop.genome_len), np.float32)]
                )
                inj_s = np.concatenate(
                    [inj_s, np.full(pad, -np.inf, np.float32)]
                )
            fn = self._compiled_run_inject(pop.size, pop.genome_len, K)
            pallas_key = None
            inject_extra = (
                jnp.asarray(inj_g), jnp.asarray(inj_s), jnp.int32(m),
            )
        else:
            fn, pallas_key = self._compiled_run_meta(
                pop.size, pop.genome_len
            )
        tgt = jnp.float32(jnp.inf if target is None else target)
        self._emit(
            "run_start", population_size=pop.size,
            genome_len=pop.genome_len, n=int(n),
            target=None if target is None else float(target),
            **({"injected": int(inject_extra[2])} if inject_extra else {}),
        )
        self._emit_gp_run(pop.size)
        # Fault-injection site "objective.eval" (robustness/faults):
        # kind "raise" propagates from here — BEFORE the key is consumed
        # or any buffer donated, so a supervised retry replays the exact
        # state; kind "nan" flags a NaN storm applied to the produced
        # scores below. Disabled path: one attribute read.
        nan_storm = (
            _faults.PLAN is not None and _faults.PLAN.fire("objective.eval")
        )
        t0 = time.perf_counter()
        args = (
            pop.genomes, self.next_key(), jnp.int32(n), tgt,
            self._mutate_params(),
        ) + inject_extra
        with _tl.span("run"):
            try:
                out = fn(*args)
            except Exception as e:
                # Graceful degradation on FIRST DISPATCH of a fused
                # Pallas program (an unvalidated Mosaic lowering can
                # fail at execute, not only at build): retire the cache
                # entry and re-dispatch the same inputs on the XLA path.
                if pallas_key is None or self.config.fallback == "raise":
                    raise
                if (
                    isinstance(pop.genomes, jax.Array)
                    and pop.genomes.is_deleted()
                ):
                    raise  # the failed dispatch consumed the donation
                self._degrade(
                    "kernel dispatch", e, population_size=pop.size,
                    genome_len=pop.genome_len,
                )
                self._compiled[pallas_key] = _XLA_FALLBACK
                fn, _ = self._compiled_run_meta(pop.size, pop.genome_len)
                out = fn(*args)
        genomes, scores, gens_done = out[:3]
        if nan_storm:
            scores = jnp.full_like(scores, jnp.nan)
        gens = int(gens_done)
        # Install the new population BEFORE notifying metrics listeners:
        # the old genome buffer was donated to the jit and is dead, and
        # listeners (e.g. AutoCheckpointer) read solver state.
        self._populations[handle.index] = Population(genomes=genomes, scores=scores)
        self._staged[handle.index] = None
        hist = None
        if len(out) > 3:  # telemetry history rode the loop carry
            hist = _tl.History(out[3], gens)
        # history() always describes the population's MOST RECENT run: a
        # telemetry-off run clears any stale buffer from an earlier one.
        self._history[handle.index] = hist
        self._validate("run", [handle.index])
        seconds = time.perf_counter() - t0
        self.metrics.record_run(gens, pop.size, seconds)
        if self._event_log() is not None:
            self._emit(
                "run_end", generations=gens, seconds=seconds,
                best=float(jnp.max(scores)),
            )
        self._check_stall_alert(hist)
        return gens

    # ------------------------------------------------- sharded population run

    def _sharded_local_step(self, shard_size: int, genome_len: int):
        """The per-shard breeding step of the sharded run loop:
        ``(g, s, sub, mparams, gen) -> (g2, s2 | None)``. On TPU a
        per-shard fused ping-pong breed (parity alternated on the
        generation counter, exactly like the single-device run loop);
        everywhere else the XLA breed built WITHOUT elitism — the
        sharded loop applies GLOBAL elitism through the gathered rank
        thresholds (``parallel/shard_pop.py``), so the local step must
        not also carry local elites."""
        if self._pallas_gate():
            pallas_kind = self._mutate_kind()
            obj = self._require_objective()
            fused = getattr(obj, "kernel_rowwise", None)
            if fused is not None:
                from libpga_tpu.ops.pallas_step import make_pallas_breed

                # Tuned resolution at the SHARD shape — the block the
                # per-shard kernel actually breeds.
                deme, layout, subblock, _ = self._resolved_pallas_knobs(
                    shard_size, genome_len
                )
                try:
                    breed = make_pallas_breed(
                        shard_size, genome_len,
                        deme_size=deme,
                        tournament_size=self.config.tournament_size,
                        selection_kind=self.config.selection,
                        selection_param=self.config.selection_param,
                        mutation_rate=self._mutation_rate(),
                        mutation_sigma=self._operator_param("sigma", 0.0),
                        crossover_kind=self._crossover_kind(),
                        mutate_kind=pallas_kind,
                        elitism=0,  # global elitism lives in the loop
                        fused_obj=fused,
                        fused_consts=tuple(
                            getattr(obj, "kernel_rowwise_consts", ())
                        ),
                        gene_dtype=self.config.gene_dtype,
                        _layout=layout,
                        _subblock=subblock,
                    )
                except Exception as e:
                    if self.config.fallback == "raise":
                        raise
                    self._degrade(
                        "sharded kernel build", e, shard_size=shard_size,
                        genome_len=genome_len,
                    )
                    breed = None
                # Per-shard padding inside shard_map would re-pad every
                # generation — only the exact-fit kernel rides the
                # sharded loop; padded shapes take the XLA local step.
                if (
                    breed is not None
                    and getattr(breed, "fused", False)
                    and breed.Pp == shard_size and breed.Lp == genome_len
                ):
                    parities = getattr(breed, "parities", 1)

                    def local_step(g, s, sub, mparams, gen):
                        if parities > 1:
                            return jax.lax.cond(
                                jnp.equal(gen & 1, 0),
                                lambda a: breed.padded(*a, parity=0),
                                lambda a: breed.padded(*a, parity=1),
                                (g, s, sub, mparams),
                            )
                        return breed.padded(g, s, sub, mparams)

                    return local_step

        breed0 = make_breed(
            self._crossover,
            self._mutate,
            tournament_size=self.config.tournament_size,
            selection_kind=self.config.selection,
            selection_param=self.config.selection_param,
            elitism=0,  # global elitism lives in the sharded loop
        )

        def local_step(g, s, sub, mparams, gen):
            del mparams, gen  # engine operators bake their parameters
            return breed0(g, s, sub), None

        return local_step

    def _compiled_sharded_run(self, size: int, genome_len: int):
        """Cached sharded run loop for one shape (``pop_shards`` > 1):
        the shard_map program of ``parallel/shard_pop.make_sharded_run``
        over this engine's operators. Raises ValueError (naming the
        valid shard counts) for an inadmissible ``pop_shards``."""
        from libpga_tpu.parallel import shard_pop as _sp

        obj = self._require_objective()
        S = self.config.pop_shards
        _sp.validate_shards(size, S)
        hist_gens = self._history_gens()
        # The per-shard kernel's knobs resolve at the shard shape
        # (tuning DB included) — key the sharded program on them.
        shard_knobs = self._resolved_pallas_knobs(size // S, genome_len)[:3]
        cache_key = (
            "engine/run-sharded", S, size, genome_len, obj,
            self._crossover, self._mutate,
            self.config.tournament_size, self.config.elitism,
            self.config.selection, self.config.selection_param,
            shard_knobs,
            hist_gens,
        )
        fn = self._compiled.get(cache_key)
        if fn is None:
            self._emit(
                "compile", what="run_sharded", population_size=size,
                genome_len=genome_len, pop_shards=S,
            )
            fn = _sp.make_sharded_run(
                obj,
                self._sharded_local_step(size // S, genome_len),
                size,
                genome_len,
                S,
                elitism=self.config.elitism,
                history_gens=hist_gens,
                donate=self.config.donate_buffers,
            )
            self._compiled[cache_key] = fn
        return fn

    def _fold_host(self, handle: PopulationHandle, inject) -> None:
        """Host-side injection fold for paths whose compiled loop has no
        injection slot (the sharded run): replace the current worst rows
        with the told candidates BEFORE dispatch. The told fitnesses are
        stored on the installed population but the sharded loop
        re-evaluates its own scores inside ``shard_map``, so they steer
        survival only through the genomes themselves — documented in the
        streaming README section."""
        pop = self._populations[handle.index]
        inj_g, inj_s, m = self._prepare_inject(pop, inject)
        scores = np.array(pop.scores, dtype=np.float32)
        if not np.isfinite(scores).any():
            # Never-evaluated population (-inf scores): any m rows are
            # "the worst"; take the leading ones deterministically.
            worst = np.arange(m)
        else:
            worst = np.argsort(scores)[:m]
        genomes = np.asarray(pop.genomes).copy()
        genomes[worst] = inj_g.astype(genomes.dtype)
        scores[worst] = inj_s
        self._populations[handle.index] = Population(
            genomes=jnp.asarray(genomes, dtype=self.config.gene_dtype),
            scores=jnp.asarray(scores),
        )
        self._staged[handle.index] = None

    def _run_sharded(
        self, n: int, target: Optional[float],
        population: Optional[PopulationHandle],
    ) -> int:
        """``run()`` with the population axis sharded S ways (see
        ``parallel/shard_pop.py``). Same contract and side effects as
        the unsharded path: installs the bred population (as ONE
        logical global array, rows sharded over the mesh), records
        telemetry history, fires the same events plus one
        ``shard_sync`` describing the per-generation collective pair."""
        handle = population or PopulationHandle(0)
        pop = self._populations[handle.index]
        fn = self._compiled_sharded_run(pop.size, pop.genome_len)
        tgt = jnp.float32(jnp.inf if target is None else target)
        self._emit(
            "run_start", population_size=pop.size,
            genome_len=pop.genome_len, n=int(n),
            target=None if target is None else float(target),
            pop_shards=fn.shards,
        )
        self._emit_gp_run(pop.size)
        self._emit(
            "shard_sync", shards=fn.shards, topk=fn.k_sync,
            mix_rows=fn.mix,
        )
        # Same "objective.eval" fault site as the unsharded run (see
        # there): raise fires before any key consumption or donation.
        nan_storm = (
            _faults.PLAN is not None and _faults.PLAN.fire("objective.eval")
        )
        t0 = time.perf_counter()
        from libpga_tpu.parallel.islands import _shard_host_array
        from libpga_tpu.parallel.mesh import pop_sharding

        genomes = _shard_host_array(pop.genomes, pop_sharding(fn.mesh))
        args = (
            genomes, self.next_key(), jnp.int32(n), tgt,
            self._mutate_params(),
        )
        with _tl.span("run"):
            out = fn(*args)
        genomes, scores, gens_done = out[:3]
        if nan_storm:
            scores = jnp.full_like(scores, jnp.nan)
        gens = int(gens_done)
        self._populations[handle.index] = Population(
            genomes=genomes, scores=scores
        )
        self._staged[handle.index] = None
        hist = None
        if len(out) > 3:
            hist = _tl.History(out[3], gens)
        self._history[handle.index] = hist
        self._validate("run", [handle.index])
        seconds = time.perf_counter() - t0
        self.metrics.record_run(gens, pop.size, seconds)
        if self._event_log() is not None:
            from libpga_tpu.parallel.mesh import global_max

            self._emit(
                "run_end", generations=gens, seconds=seconds,
                best=float(global_max(scores, fn.mesh)),
            )
        self._check_stall_alert(hist)
        return gens

    # ------------------------------------------------- step-by-step operators

    def evaluate(self, handle: PopulationHandle) -> None:
        """Score the current generation (reference ``pga_evaluate``)."""
        pop = self._populations[handle.index]
        with _tl.span("evaluate"):
            scores = self._jitted_evaluate()(pop.genomes)
        self._populations[handle.index] = dataclasses.replace(pop, scores=scores)
        self._validate("evaluate", [handle.index], oracle=False)

    def evaluate_all(self) -> None:
        for h in self._handles():
            self.evaluate(h)

    def _jitted_evaluate(self):
        cache_key = ("engine/eval", self._objective)
        fn = self._compiled.get(cache_key)
        if fn is None:
            obj = self._require_objective()
            fn = jax.jit(lambda g: _evaluate(obj, g))
            self._compiled[cache_key] = fn
        return fn

    def crossover(self, handle: PopulationHandle, selection: str = "tournament") -> None:
        """Select parents from the current generation and stage children as
        the next generation (reference ``pga_crossover``).

        The reference accepts-and-ignores its selection-type argument
        (``pga.cu:329``, single-member placeholder enum); here a
        NON-tournament value ("truncation" / "linear_rank") switches the
        solver's strategy at its default parameter — the same contract
        as the C ABI's ``pga_crossover`` — while "tournament" (the value
        reference-style callers pass on every call) is inert so it never
        clobbers a strategy chosen via ``config.selection``. Set
        ``PGAConfig(selection=..., selection_param=...)`` for an
        explicit τ/pressure."""
        if selection != "tournament" and selection != self.config.selection:
            from libpga_tpu.ops.select import resolve_selection

            resolve_selection(selection, None)  # validate before mutating
            self.config = dataclasses.replace(
                self.config, selection=selection, selection_param=None
            )
        pop = self._populations[handle.index]
        fn = self._compiled_op("crossover")
        with _tl.span("select_breed"):
            self._staged[handle.index] = fn(
                pop.genomes, pop.scores, self.next_key()
            )
        self._validate("crossover", [handle.index], staged=True)

    def crossover_all(self, selection: str = "tournament") -> None:
        for h in self._handles():
            self.crossover(h, selection)

    def _compiled_op(self, which: str):
        cache_key = (
            "engine/op", which, self._crossover, self._mutate,
            self.config.tournament_size, self.config.selection,
            self.config.selection_param,
        )
        fn = self._compiled.get(cache_key)
        if fn is not None:
            return fn
        if which == "crossover":
            cross = self._crossover
            k = self.config.tournament_size
            batched = getattr(cross, "batched", None)
            cols = getattr(cross, "rand_cols", None)

            sel_kind = self.config.selection
            sel_param = self.config.selection_param

            def op(genomes, scores, key):
                P, L = genomes.shape
                k_sel, k_c = jax.random.split(key)
                i1, i2 = select_parent_pairs(
                    k_sel, scores, P, k=k, kind=sel_kind, param=sel_param
                )
                p1 = jnp.take(genomes, i1, axis=0)
                p2 = jnp.take(genomes, i2, axis=0)
                rand = jax.random.uniform(k_c, (P, cols or L), dtype=jnp.float32)
                out = (
                    batched(p1, p2, rand)
                    if batched is not None
                    else jax.vmap(cross)(p1, p2, rand)
                )
                return out.astype(genomes.dtype)

        elif which == "mutate":
            mut = self._mutate
            batched = getattr(mut, "batched", None)
            cols = getattr(mut, "rand_cols", None)

            def op(genomes, key):
                P, L = genomes.shape
                rand = jax.random.uniform(key, (P, cols or L), dtype=jnp.float32)
                out = (
                    batched(genomes, rand)
                    if batched is not None
                    else jax.vmap(mut)(genomes, rand)
                )
                return out.astype(genomes.dtype)

        else:
            raise ValueError(which)
        # The staged next generation is double-buffer state: mutate()
        # replaces it wholesale, so the incoming buffer is dead on
        # return and XLA may update it in place — the same donation the
        # fused run loop applies to the genome carry. crossover() can't
        # donate: its input is the live current generation.
        donate = (
            (0,) if which == "mutate" and self.config.donate_buffers else ()
        )
        fn = jax.jit(op, donate_argnums=donate)
        self._compiled[cache_key] = fn
        return fn

    def mutate(self, handle: PopulationHandle) -> None:
        """Mutate the staged next generation in place (reference
        ``pga_mutate`` operates on ``next_gen``, ``pga.cu:349-354``)."""
        staged = self._staged[handle.index]
        if staged is None:
            raise RuntimeError("no staged generation — call crossover() first")
        with _tl.span("mutate"):
            self._staged[handle.index] = self._compiled_op("mutate")(
                staged, self.next_key()
            )
        self._validate("mutate", [handle.index], staged=True)

    def mutate_all(self) -> None:
        for h in self._handles():
            self.mutate(h)

    def swap_generations(self, handle: PopulationHandle) -> None:
        """Promote the staged next generation to current (reference
        ``pga_swap_generations`` pointer swap, ``pga.cu:362-366``).

        Deliberate divergence (documented in ``capi/pga.h``): the
        swapped-in population's scores read -inf until the next
        :meth:`evaluate`, where the reference's pointer swap leaves the
        previous generation's stale scores readable. Stale scores are
        wrong for the new genomes either way; -inf makes that visible
        instead of plausible-looking."""
        staged = self._staged[handle.index]
        if staged is None:
            raise RuntimeError("no staged generation — call crossover() first")
        pop = self._populations[handle.index]
        with _tl.span("swap"):
            self._populations[handle.index] = Population(
                genomes=staged,
                scores=jnp.full((pop.size,), -jnp.inf, dtype=jnp.float32),
            )
            self._staged[handle.index] = None

    def fill_random_values(self, handle: PopulationHandle) -> None:
        """Advance the PRNG stream (reference ``pga_fill_random_values``
        refills the cuRAND pool, ``pga.cu:99-105``; with threaded keys the
        analog is burning a key)."""
        del handle
        self.next_key()

    # -------------------------------------------------------- best extraction

    def get_best(self, handle: PopulationHandle) -> np.ndarray:
        """Best genome of one population (reference ``pga_get_best``,
        ``pga.cu:218-236`` — but argmax on device, not host)."""
        genomes, _ = self.get_best_with_score(handle)
        return genomes

    def get_best_with_score(
        self, handle: PopulationHandle
    ) -> Tuple[np.ndarray, float]:
        pop = self._populations[handle.index]
        g, s = top_k_genomes(pop.genomes, pop.scores, 1)
        return np.asarray(g[0]), float(s[0])

    def get_best_top(self, handle: PopulationHandle, k: int) -> np.ndarray:
        """Top-k genomes, best first — implements the reference's NULL stub
        ``pga_get_best_top`` (``pga.cu:238-240``) per its header contract.
        ``k`` is clamped to the population size."""
        pop = self._populations[handle.index]
        g, _ = top_k_genomes(pop.genomes, pop.scores, min(k, pop.size))
        return np.asarray(g)

    def get_best_all(self) -> np.ndarray:
        """Best genome across all populations (stub ``pga_get_best_all``,
        ``pga.cu:242-244``, implemented)."""
        best_g, best_s = None, -float("inf")
        for h in self._handles():
            g, s = self.get_best_with_score(h)
            if s > best_s:
                best_g, best_s = g, s
        if best_g is None:
            raise RuntimeError("no populations")
        return best_g

    def get_best_top_all(self, k: int) -> np.ndarray:
        """Global top-k across all populations (stub ``pga_get_best_top_all``,
        ``pga.cu:246-248``, implemented). Per-population top-k on device,
        then a k-way merge of the small candidate set."""
        cands_g, cands_s = [], []
        for h in self._handles():
            pop = self._populations[h.index]
            kk = min(k, pop.size)
            g, s = top_k_genomes(pop.genomes, pop.scores, kk)
            cands_g.append(np.asarray(g))
            cands_s.append(np.asarray(s))
        genome_lens = {g.shape[1] for g in cands_g}
        if len(genome_lens) != 1:
            raise ValueError("get_best_top_all requires equal genome_len across populations")
        all_g = np.concatenate(cands_g)
        all_s = np.concatenate(cands_s)
        order = np.argsort(-all_s)[:k]
        return all_g[order]

    # ------------------------------------------------------------- migration

    def migrate(self, pct: float) -> None:
        """Randomly migrate the top ``pct`` between populations (reference
        header spec ``pga.h:108-111``; empty stub ``pga.cu:368-370``).

        Ring over a random island order: every population sends its
        pre-migration top ``pct`` to its successor in a shuffled order,
        replacing the destination's worst individuals. Emigrants are
        snapshotted before any immigration so one migrate() event moves
        each individual at most one hop (same semantics as the sharded
        island runner).
        """
        if not (0.0 <= pct <= 1.0):
            raise ValueError("migration pct must be in [0, 1]")
        n = len(self._populations)
        if n < 2:
            return
        self._emit("migration", pct=float(pct), populations=n)
        with _tl.span("migrate"):
            emigrants = {}
            for i, pop in enumerate(self._populations):
                count = int(pop.size * pct)
                if count > 0:
                    emigrants[i] = top_k_genomes(pop.genomes, pop.scores, count)
            order = np.asarray(
                jax.random.permutation(self.next_key(), jnp.arange(n))
            )
            for i in range(n):
                src, dst = int(order[i]), int(order[(i + 1) % n])
                if src in emigrants:
                    self._immigrate_into(dst, *emigrants[src])

    def migrate_between(
        self, src: PopulationHandle, dst: PopulationHandle, pct: float
    ) -> None:
        """Copy the top ``pct`` of ``src`` over the worst of ``dst``
        (reference header spec ``pga.h:112-115``; empty stub
        ``pga.cu:372-374``). Requires both populations evaluated.
        ``pct`` small enough to round to 0 emigrants → no-op."""
        if not (0.0 <= pct <= 1.0):
            raise ValueError("migration pct must be in [0, 1]")
        spop = self._populations[src.index]
        count = int(min(spop.size, self._populations[dst.index].size) * pct)
        if count == 0:
            return
        emigrants, escores = top_k_genomes(spop.genomes, spop.scores, count)
        self._immigrate_into(dst.index, emigrants, escores)

    def _immigrate_into(self, dst_index: int, emigrants, escores) -> None:
        from libpga_tpu.parallel.islands import _immigrate

        dpop = self._populations[dst_index]
        if emigrants.shape[1] != dpop.genome_len:
            raise ValueError("migration requires equal genome_len")
        new_g, new_s = _immigrate(
            dpop.genomes[None], dpop.scores[None], emigrants[None], escores[None]
        )
        self._populations[dst_index] = Population(genomes=new_g[0], scores=new_s[0])

    # --------------------------------------------------------------- islands

    def run_islands(
        self,
        n: int,
        m: int,
        pct: float,
        target: Optional[float] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
    ) -> int:
        """Island GA over ALL populations: ``n`` generations total, ring/random
        migration of the top ``pct`` every ``m`` generations (reference header
        spec ``pga.h:144-150``; empty stub ``pga.cu:393-395``).

        Homogeneous populations run as a stacked ``(islands, size, L)`` batch
        — vmapped on one device, or sharded island-per-core via ``shard_map``
        when a ``mesh`` is provided. Returns generations executed.
        """
        from libpga_tpu.parallel.islands import run_islands_stacked

        if not self._populations:
            raise RuntimeError("no populations")
        sizes = {(p.size, p.genome_len) for p in self._populations}
        if len(sizes) != 1:
            return self._run_islands_hetero(n, m, pct, target)
        stacked = jnp.stack([p.genomes for p in self._populations])
        S, L = stacked.shape[1], stacked.shape[2]
        breed = self._pallas_island_breed(S, L) or self._breed_fn()
        # Epoch-level elite carry: only for a Pallas breed whose kernel
        # couldn't apply it (non-fused objective). The XLA breed and the
        # fused kernel both handle elitism themselves.
        epoch_elitism = (
            self.config.elitism
            if getattr(breed, "padded", None) is not None
            and not getattr(breed, "fused", False)
            else 0
        )
        hist_gens = self._history_gens()
        self._emit(
            "islands_start", islands=len(self._populations), n=int(n),
            m=int(m), pct=float(pct),
        )
        # Same "objective.eval" fault site as run() (see there): raise
        # fires before any key consumption; nan poisons the installed
        # scores below.
        nan_storm = (
            _faults.PLAN is not None and _faults.PLAN.fire("objective.eval")
        )
        t0 = time.perf_counter()
        with _tl.span("run_islands"):
            out = run_islands_stacked(
                breed,
                self._require_objective(),
                stacked,
                self.next_key(),
                n=n,
                m=m,
                pct=pct,
                target=target,
                topology=self.config.migration_topology,
                mesh=mesh,
                runner_cache=self._compiled,
                mparams=self._mutate_params(),
                elitism=epoch_elitism,
                history_gens=hist_gens,
            )
        genomes, scores, gens = out[:3]
        if nan_storm:
            scores = jnp.full_like(scores, jnp.nan)
        for i in range(len(self._populations)):
            # genomes[i] on a jax.Array stays on device (no host round trip).
            self._populations[i] = Population(
                genomes=genomes[i], scores=scores[i]
            )
            self._staged[i] = None
        hist = None
        if hist_gens is not None:
            # One GLOBAL history (stats across all islands) shared by
            # every participating population's slot.
            hist = _tl.History(out[3], gens)
        # Most-recent-run semantics, as in run(): telemetry-off islands
        # clear any stale per-population buffers.
        for i in range(len(self._populations)):
            self._history[i] = hist
        self._validate("run_islands")
        # Metrics listeners run after the state swap (see run()).
        seconds = time.perf_counter() - t0
        self.metrics.record_run(
            gens, sum(p.size for p in self._populations), seconds
        )
        if self._event_log() is not None:
            from libpga_tpu.parallel.mesh import global_max

            self._emit(
                "islands_end", generations=gens, seconds=seconds,
                best=float(global_max(scores, mesh)),
            )
        self._check_stall_alert(hist)
        return gens

    def _run_islands_hetero(
        self, n: int, m: int, pct: float, target: Optional[float]
    ) -> int:
        """Fallback for heterogeneous population shapes: sequential epochs
        with host-orchestrated migration (still jitted per population).
        Returns the maximum generation count any population executed."""
        gens = 0
        while gens < n:
            chunk = min(m, n - gens)
            executed = [
                self.run(chunk, target=target, population=h)
                for h in self._handles()
            ]
            gens += max(executed)
            if target is not None:
                best = max(
                    self.get_best_with_score(h)[1] for h in self._handles()
                )
                if best >= target:
                    break
            if gens < n:
                self.migrate(pct)
        return gens
