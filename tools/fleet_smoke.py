#!/usr/bin/env python
"""Fleet smoke: the ISSUE 8 acceptance matrix on 8 worker processes.

CI stage 9 (``tools/ci.sh``). Four gates against a REAL cross-process
fleet (``serving/fleet.py`` coordinator + ``serving/worker.py``
processes) on the CPU backend:

1. **kill-one-worker bit-identity** — an 8-worker fleet serves a spread
   of plain tickets while one worker SIGKILLs itself mid-batch; the
   dead worker's lease is recovered, its batch re-runs on a survivor,
   and EVERY ticket's result is bit-identical to an uninterrupted
   same-seed single-process ``PGA.run``;
2. **drain/resume bit-identity** — a supervised ticket is SIGTERM-
   drained mid-run (checkpoint at a chunk boundary through the atomic
   checkpoint + sidecar machinery), the fleet restarts, and the
   resumed run finishes bit-identical to an uninterrupted same-seed
   supervised run at the same cadence;
3. **dead-letter quarantine** — a batch that costs
   ``max_worker_deaths`` DISTINCT workers their lease is quarantined
   into ``dead/`` with a schema-valid flight-recorder dump (worker/pid
   attribution in the trailer) and its ticket fails with
   ``FleetDeadLetter`` instead of being retried forever;
4. **per-worker metrics lint** — the coordinator's per-worker gauges
   and lease counters, plus one worker's exit-time exposition from the
   spool, pass ``tools/metrics_dump.py --check`` (Prometheus
   line-format lint).

Exit 0 with one line per gate; nonzero on the first failure.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from libpga_tpu import PGA, PGAConfig  # noqa: E402
from libpga_tpu.config import FleetConfig  # noqa: E402
from libpga_tpu.robustness.supervisor import supervised_run  # noqa: E402
from libpga_tpu.serving.fleet import (  # noqa: E402
    Fleet,
    FleetDeadLetter,
    FleetTicket,
)
from libpga_tpu.utils import metrics as _metrics  # noqa: E402
from libpga_tpu.utils import telemetry as _tl  # noqa: E402

POP, LEN, GENS = 256, 32, 6
WORKERS = 8
CFG = PGAConfig(use_pallas=False)
TOOLS = os.path.dirname(os.path.abspath(__file__))


def check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"fleet {name}: {status}{' — ' + detail if detail else ''}")
    if not ok:
        sys.exit(f"fleet smoke failed at {name}")


def engine_ref(seed, n):
    pga = PGA(seed=seed, config=CFG)
    pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    pga.run(n)
    return np.array(pga._populations[0].genomes, copy=True)


def stage_kill_one_worker(tmp):
    fleet = Fleet(
        os.path.join(tmp, "kill"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=WORKERS, max_batch=2, max_wait_ms=5,
            lease_timeout_s=6.0, heartbeat_s=0.3, poll_s=0.05,
        ),
    )
    # Worker 0 SIGKILLs itself at the start of its first batch — a real
    # kill -9 mid-batch on the 8-process matrix.
    fleet.start(worker_env={0: {"PGA_WORKER_CHAOS": "sigkill@execute:1"}})
    seeds = list(range(100, 100 + 2 * WORKERS))
    handles = [
        fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=GENS, seed=s))
        for s in seeds
    ]
    results = [h.result(timeout=600) for h in handles]
    workers_used = sorted({r.worker for r in results})
    mismatches = [
        s for s, r in zip(seeds, results)
        if not np.array_equal(r.genomes, engine_ref(s, GENS))
    ]
    # ISSUE 9 acceptance on the same run: every completed ticket's
    # cross-process span breakdown TILES — its five spans sum to >=95%
    # of its measured end-to-end wall time — and the requeued batch's
    # trace shows BOTH attempts (two claims around a requeue record).
    bad_cov, requeued_traced = [], 0
    for s, h in zip(seeds, handles):
        lat = h.latency()
        spans = [lat[f"{k}_ms"] for k in
                 ("intake", "spool_wait", "execute", "publish", "readback")]
        if any(v is None for v in spans) or (
            sum(spans) < 0.95 * lat["e2e_ms"]
        ):
            bad_cov.append((s, lat))
        kinds = [r["span"] for r in h.trace()]
        if kinds.count("claim") >= 2 and "requeue" in kinds:
            requeued_traced += 1
    fleet.close()
    check(
        "kill-one-worker",
        not mismatches and fleet.worker_deaths == 1
        and not bad_cov and requeued_traced >= 1,
        f"{len(seeds)} tickets on {WORKERS} workers "
        f"({len(workers_used)} served), 1 killed, "
        f"{fleet.requeues} requeue(s), all bit-identical; spans tile "
        f">=95% e2e on all, {requeued_traced} trace(s) show both "
        "attempts",
    )
    return fleet


def stage_drain_resume(tmp):
    N, K = 24, 4
    fleet = Fleet(
        os.path.join(tmp, "drain"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=2, max_batch=1, max_wait_ms=0,
            lease_timeout_s=6.0, heartbeat_s=0.3, poll_s=0.05,
        ),
    )
    fleet.start()
    h = fleet.submit(FleetTicket(
        size=POP, genome_len=LEN, n=N, seed=77, checkpoint_every=K,
    ))
    fleet.flush()
    sidecar = fleet.spool.ckpt_path(h.tid) + ".meta.json"
    deadline = time.monotonic() + 300
    while True:
        try:
            with open(sidecar) as fh:
                if 0 < json.load(fh)["generations"] < N:
                    break
        except (OSError, json.JSONDecodeError, KeyError):
            pass
        if time.monotonic() > deadline:
            check("drain-resume", False, "no mid-run checkpoint appeared")
        time.sleep(0.02)
    drained = fleet.drain()
    fleet.start()  # fresh workers resume from the durable checkpoint
    res = h.result(timeout=600)
    fleet.close()
    ref = PGA(seed=77, config=CFG)
    ref.create_population(POP, LEN)
    ref.set_objective("onemax")
    report = supervised_run(
        ref, N, checkpoint_path=os.path.join(tmp, "drain-ref.npz"),
        checkpoint_every=K,
    )
    ok = (
        res.generations == N
        and np.array_equal(
            res.genomes, np.array(ref._populations[0].genomes)
        )
        and res.best_score == report.best_score
    )
    check(
        "drain-resume", ok,
        f"drained {drained} worker(s) mid-run, resumed, bit-identical "
        f"at cadence {K}",
    )


def stage_quarantine(tmp):
    K = 2
    fleet = Fleet(
        os.path.join(tmp, "dl"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=2, max_batch=1, max_wait_ms=0,
            lease_timeout_s=6.0, heartbeat_s=0.3, poll_s=0.05,
            max_worker_deaths=K,
        ),
    )
    chaos = {"PGA_WORKER_CHAOS": "sigkill@execute:1"}
    fleet.start(worker_env={0: chaos, 1: chaos})
    h = fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=GENS, seed=5))
    fleet.flush()
    dead_lettered = False
    try:
        h.result(timeout=600)
    except FleetDeadLetter:
        dead_lettered = True
    dump_ok = trace_ok = False
    if fleet.quarantined:
        dump = fleet.spool.path(
            "dead", f"{fleet.quarantined[0]}.flight.jsonl"
        )
        records = _tl.validate_log(dump)  # schema gate
        trailer = records[-1]
        dump_ok = (
            trailer["event"] == "flight_dump"
            and trailer["reason"] == "fleet_dead_letter"
            and trailer.get("pid") == os.getpid()
        )
        # ISSUE 9: the dump embeds the dead batch's span log (both
        # killed workers' claims), and so does the dead batch file.
        spans = [r for r in records if r["event"] == "trace_span"]
        dead_batch = json.load(open(
            fleet.spool.path("dead", fleet.quarantined[0])
        ))
        trace_ok = (
            sum(1 for r in spans if r["span"] == "claim") >= K
            and len(dead_batch.get("trace_log", [])) >= K
        )
    fleet.close()
    check(
        "dead-letter-quarantine",
        dead_lettered and len(fleet.quarantined) == 1 and dump_ok
        and trace_ok,
        f"quarantined after {K} distinct worker deaths, flight dump "
        "schema-valid with pid attribution + embedded span log",
    )


def stage_metrics_lint(tmp):
    # Coordinator-side: the per-worker gauges/counters the stages above
    # populated, exported from the live registry.
    coord = os.path.join(tmp, "coordinator.prom")
    with open(coord, "w", encoding="utf-8") as fh:
        fh.write(_metrics.prometheus_text(_metrics.REGISTRY.snapshot()))
    text = open(coord).read()
    for needle in ("pga_fleet_worker_up", "pga_fleet_lease_requeues",
                   "pga_fleet_worker_deaths"):
        if needle not in text:
            check("metrics-lint", False, f"missing series {needle}")
    # Worker-side: every worker wrote its own exposition on exit.
    worker_proms = []
    for sub in ("kill", "drain", "dl"):
        logs = os.path.join(tmp, sub, "logs")
        worker_proms += [
            os.path.join(logs, f) for f in sorted(os.listdir(logs))
            if f.endswith(".prom")
        ]
    if not worker_proms:
        check("metrics-lint", False, "no worker .prom files in the spool")
    # MERGED fleet exposition (ISSUE 9): the kill stage's spool carries
    # every process's metric flush (8 workers + coordinator); the merge
    # must lint clean and label every series with its origin process.
    from libpga_tpu.serving.fleet import Spool, merge_spool_metrics

    merged = merge_spool_metrics(Spool(os.path.join(tmp, "kill")))
    merged_prom = os.path.join(tmp, "merged.prom")
    with open(merged_prom, "w", encoding="utf-8") as fh:
        fh.write(_metrics.prometheus_text(merged))
    text = open(merged_prom).read()
    procs = {
        p for p in merged["merged_from"] if p.startswith("w")
    }
    if len(procs) < WORKERS or "coordinator" not in merged["merged_from"]:
        check(
            "metrics-lint", False,
            f"merged exposition covers {sorted(merged['merged_from'])}, "
            f"expected {WORKERS} workers + coordinator",
        )
    # w0 may have died before any non-empty flush (its startup snapshot
    # has no series yet) — require the label on ANY worker + the
    # coordinator, not on the deliberately-killed one.
    if 'proc="w' not in text or 'proc="coordinator"' not in text:
        check("metrics-lint", False, "merged exposition lacks proc labels")
    for path in [coord, worker_proms[0], merged_prom]:
        proc = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "metrics_dump.py"),
             "--check", path],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            check(
                "metrics-lint", False,
                f"{path}: {proc.stdout.strip()} {proc.stderr.strip()}",
            )
    check(
        "metrics-lint", True,
        f"coordinator + {len(worker_proms)} worker expositions + merged "
        f"fleet exposition ({len(merged['merged_from'])} procs), "
        "prometheus lint clean",
    )


def main():
    with tempfile.TemporaryDirectory(prefix="pga-fleet-smoke-") as tmp:
        stage_kill_one_worker(tmp)
        stage_drain_resume(tmp)
        stage_quarantine(tmp)
        stage_metrics_lint(tmp)
    print(
        f"fleet smoke: {WORKERS}-process matrix — kill/drain/quarantine "
        "recovered bit-identical, metrics lint clean"
    )


if __name__ == "__main__":
    main()
