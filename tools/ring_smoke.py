#!/usr/bin/env python
"""Ring smoke: the ISSUE 18 fast-path acceptance matrix, CI stage 18.

Four gates against a REAL multi-process fleet on the CPU backend:

1. **ring on/off bit-identity** — the same ticket spread served by a
   4-worker fleet with the shared-memory ticket ring enabled and again
   with it disabled (pure-spool polling) produces bit-identical
   genomes; the ring run's spool carries ``ring_attach`` events and
   live ring wake/heartbeat counters, and the pure-spool run never
   creates a ring file.
2. **degradation** — a coordinator whose very first ring write faults
   (injected ``ring.publish``) emits a schema-valid ``ring_degraded``
   event and still serves every ticket bit-identically via the spool.
3. **ring metrics lint** — the ``fleet.ring.*`` counters populated by
   gate 1 export through ``tools/metrics_dump.py --check`` (Prometheus
   line-format lint).
4. **fleet_top ring health** — the console renders the ring line from
   the spool+ring alone: ``live`` against the running fleet's spool,
   ``absent`` for the pure-spool one.

Exit 0 with one line per gate; nonzero on the first failure.
"""

import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from libpga_tpu import PGA, PGAConfig  # noqa: E402
from libpga_tpu.config import FleetConfig  # noqa: E402
from libpga_tpu.robustness import faults  # noqa: E402
from libpga_tpu.serving.fleet import Fleet, FleetTicket, fleet_status  # noqa: E402
from libpga_tpu.serving.shm_ring import RING_FILENAME, ShmRing  # noqa: E402
from libpga_tpu.utils import metrics as _metrics  # noqa: E402
from libpga_tpu.utils import telemetry as _tl  # noqa: E402

POP, LEN, GENS = 256, 32, 5
WORKERS = 4
CFG = PGAConfig(use_pallas=False)
TOOLS = os.path.dirname(os.path.abspath(__file__))
SEEDS = list(range(300, 300 + 2 * WORKERS))


def check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"ring {name}: {status}{' — ' + detail if detail else ''}")
    if not ok:
        sys.exit(f"ring smoke failed at {name}")


def serve(tmp, sub, ring, events=None, n_workers=WORKERS):
    """One fleet pass over the standard ticket spread; returns
    ``(genome arrays by seed, fleet, spool dir)`` with the fleet still
    open so callers can inspect live state before closing it."""
    spool = os.path.join(tmp, sub)
    fleet = Fleet(
        spool, "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=n_workers, max_batch=2, max_wait_ms=5,
            lease_timeout_s=6.0, heartbeat_s=0.3, poll_s=0.05, ring=ring,
        ),
        events=events,
    )
    fleet.start()
    handles = [
        fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=GENS, seed=s))
        for s in SEEDS
    ]
    results = {
        s: np.asarray(h.result(timeout=600).genomes)
        for s, h in zip(SEEDS, handles)
    }
    return results, fleet, spool


def stage_bit_identity(tmp):
    ring_res, ring_fleet, ring_spool = serve(
        tmp, "ring-on", ring=True,
        events=_tl.EventLog(os.path.join(tmp, "ring-on-events.jsonl")),
    )
    # Inspect the live ring before close() unlinks it.
    live = fleet_status(ring_spool)
    ring_live = live["ring"]
    st = ring_fleet.status()["coordinator"]
    ring_fleet.close()
    ring_fleet.events.close()

    spool_res, spool_fleet, spool_spool = serve(tmp, "ring-off", ring=False)
    spool_fleet.close()

    mismatches = [
        s for s in SEEDS if not np.array_equal(ring_res[s], spool_res[s])
    ]
    records = _tl.validate_log(os.path.join(tmp, "ring-on-events.jsonl"))
    kinds = [r["event"] for r in records]
    ok = (
        not mismatches
        and st["ring_attached"]
        and ring_live.get("present") and ring_live.get("coordinator_alive")
        and ring_live.get("workers_bound", 0) >= 1
        and "ring_attach" in kinds and "ring_degraded" not in kinds
        and not os.path.exists(os.path.join(spool_spool, RING_FILENAME))
    )
    check(
        "on-off-bit-identity", ok,
        f"{len(SEEDS)} tickets x {WORKERS} workers, ring head="
        f"{ring_live.get('head')}, {ring_live.get('workers_bound')} "
        "slots bound, results bit-identical to pure-spool",
    )
    return ring_spool


def stage_degradation(tmp):
    events = _tl.EventLog(os.path.join(tmp, "degrade-events.jsonl"))
    # times=None: every coordinator ring write faults, so the very
    # first advertise (or depth store) forces pure-spool degradation.
    with faults.active(
        faults.FaultPlan("ring.publish", probability=1.0, times=None)
    ):
        results, fleet, _ = serve(
            tmp, "degrade", ring=True, events=events, n_workers=2
        )
        degraded = not fleet.status()["coordinator"]["ring_attached"]
        fleet.close()
    events.close()
    refs = {}
    for s in SEEDS:
        pga = PGA(seed=s, config=CFG)
        pga.create_population(POP, LEN)
        pga.set_objective("onemax")
        pga.run(GENS)
        refs[s] = np.array(pga._populations[0].genomes, copy=True)
    mismatches = [
        s for s in SEEDS if not np.array_equal(results[s], refs[s])
    ]
    records = _tl.validate_log(os.path.join(tmp, "degrade-events.jsonl"))
    degrade_recs = [r for r in records if r["event"] == "ring_degraded"]
    ok = (
        degraded and not mismatches and degrade_recs
        and degrade_recs[0]["role"] == "coordinator"
    )
    check(
        "degradation", ok,
        "coordinator ring writes faulted, degraded to pure-spool, "
        f"{len(SEEDS)} tickets bit-identical to single-process refs",
    )


def stage_metrics_lint(tmp):
    snap = _metrics.REGISTRY.snapshot()
    names = {c["name"] for c in snap.get("counters", ())}
    wanted = {"fleet.ring.wakes", "fleet.ring.fallback_scans",
              "fleet.ring.degraded"}
    missing = wanted - names
    if missing:
        check("metrics-lint", False, f"missing ring series {missing}")
    prom = os.path.join(tmp, "ring.prom")
    with open(prom, "w", encoding="utf-8") as fh:
        fh.write(_metrics.prometheus_text(snap))
    text = open(prom).read()
    if "pga_fleet_ring_wakes" not in text:
        check("metrics-lint", False, "ring counters absent from exposition")
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "metrics_dump.py"),
         "--check", prom],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if proc.returncode != 0:
        check("metrics-lint", False,
              f"{proc.stdout.strip()} {proc.stderr.strip()}")
    check("metrics-lint", True,
          "fleet.ring.* counters present, prometheus lint clean")


def stage_fleet_top(tmp, ring_spool):
    from tools.fleet_top import render

    # Post-mortem of the ring-on spool: the coordinator closed cleanly,
    # unlinking its ring — the console must render "absent" (pure-spool
    # coordination), never crash.
    post = render(fleet_status(ring_spool))
    if "ring: absent" not in post:
        check("fleet-top", False, f"post-mortem ring line wrong:\n{post}")
    # Live fleet: the ring line must read from the spool+ring alone.
    fleet = Fleet(
        os.path.join(tmp, "top"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=5,
            lease_timeout_s=6.0, heartbeat_s=0.3, poll_s=0.05,
        ),
    )
    fleet.start()
    h = fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=2, seed=1))
    h.result(timeout=600)
    live = render(fleet_status(os.path.join(tmp, "top")))
    fleet.close()
    ok = "ring: live" in live and "workers_bound=" in live
    check("fleet-top", ok,
          "ring health rendered from spool+ring alone (live + absent)")


def main():
    with tempfile.TemporaryDirectory(prefix="pga-ring-smoke-") as tmp:
        ring_spool = stage_bit_identity(tmp)
        stage_degradation(tmp)
        stage_metrics_lint(tmp)
        stage_fleet_top(tmp, ring_spool)
    print(
        f"ring smoke: {WORKERS}-process fleet — ring on/off bit-identical, "
        "degradation clean, metrics + console gates pass"
    )


if __name__ == "__main__":
    main()
