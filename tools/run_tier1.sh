#!/usr/bin/env bash
# Tier-1 verification entry point — the exact ROADMAP.md command, make-free.
#
#   tools/run_tier1.sh            # run tier-1 (CPU, not-slow, 870 s budget)
#
# Prints DOTS_PASSED=<count> at the end (the driver's pass metric) and
# exits with pytest's status. Log lands in /tmp/_t1.log.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
