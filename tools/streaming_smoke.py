#!/usr/bin/env python
"""CI smoke for the streaming evolution service (ISSUE 12) — ci.sh
stage 13.

Five gates, all CPU-runnable:

1. **step-only byte-identity** — an EvolutionSession that is only ever
   step()ped produces the bit-identical final population AND telemetry
   history to a same-seed PGA.run;
2. **suspend/resume bit-identity** — suspend at a generation boundary,
   resume into a fresh engine (the simulated different process), and
   the continued trajectory is bit-identical to the uninterrupted one;
3. **warm pool: 0 compiles** — after a session of a signature has run,
   a second tenant acquired from the pool executes its first ask and
   step WITHOUT building a single new program (asserted via the
   engine's compiled-program table and the pool counters), and the
   measured warm first-ask latency beats the cold one;
4. **ask/tell external-fitness loop** — a session driven ONLY by
   external evaluations (tell) recovers a hidden target;
5. **event schema** — session_open / session_fold / session_suspend
   (and session_resume) records validate against EVENT_FIELDS.
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from libpga_tpu import PGA, PGAConfig, TelemetryConfig  # noqa: E402
from libpga_tpu.streaming import (  # noqa: E402
    EnginePool,
    EvolutionSession,
    SessionStore,
)
from libpga_tpu.utils import telemetry as T  # noqa: E402
from libpga_tpu.utils.metrics import Counters  # noqa: E402


def fail(msg: str) -> None:
    print(f"FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="pga-streaming-smoke-")
    events_path = os.path.join(tmp, "events.jsonl")
    cfg = PGAConfig(
        use_pallas=False,
        telemetry=TelemetryConfig(history_gens=64, events_path=events_path),
    )

    # -------------------------------------------- 1. step-only identity
    session = EvolutionSession("onemax", 512, 32, seed=7, config=cfg)
    session.step(10)
    ref = PGA(seed=7, config=cfg)
    href = ref.create_population(512, 32)
    ref.set_objective("onemax")
    ref.run(10)
    a, b = session.population(), ref.population(href)
    if not (
        np.array_equal(np.asarray(a.genomes), np.asarray(b.genomes))
        and np.array_equal(np.asarray(a.scores), np.asarray(b.scores))
    ):
        fail("step()-only session diverged from same-seed PGA.run")
    if not np.array_equal(session.history._rows, ref.history(href)._rows):
        fail("step()-only session telemetry history diverged")
    print("streaming smoke: step-only byte-identity OK (512x32, 10 gens)")

    # ------------------------------------------ 2. suspend/resume
    store = SessionStore(os.path.join(tmp, "sessions"))
    store.suspend(session)
    resumed = store.resume(session.sid, objective="onemax", config=cfg)
    session.step(5)
    resumed.step(5)
    a, b = session.population(), resumed.population()
    if not np.array_equal(np.asarray(a.genomes), np.asarray(b.genomes)):
        fail("suspend->resume trajectory diverged")
    if not np.array_equal(session.history._rows, resumed.history._rows):
        fail("suspend->resume telemetry history diverged")
    print(
        "streaming smoke: suspend/resume bit-identity OK "
        f"(resumed @gen {resumed.gens_done - 5}, stepped 5 more)"
    )

    # ------------------------------------------ 3. warm pool, 0 compiles
    pool = EnginePool(config=cfg, counters=Counters())
    t0 = time.perf_counter()
    cold = pool.acquire("sphere", 256, 24, seed=1)
    cold.ask(8)
    cold.step(1)
    cold_ms = (time.perf_counter() - t0) * 1e3
    eng = cold.pga
    programs_before = len(eng._compiled)
    pool.release(cold)
    t0 = time.perf_counter()
    warm = pool.acquire("sphere", 256, 24, seed=2)
    warm.ask(8)
    warm.step(1)
    warm_ms = (time.perf_counter() - t0) * 1e3
    if warm.pga is not eng:
        fail("pool did not reuse the warm engine")
    if len(eng._compiled) != programs_before:
        fail(
            f"warm acquire built {len(eng._compiled) - programs_before} "
            "new programs (expected 0)"
        )
    stats = pool.stats()
    if stats.get("hits") != 1 or stats.get("misses") != 1:
        fail(f"unexpected pool counters: {stats}")
    if warm_ms >= cold_ms:
        fail(
            f"warm first-ask {warm_ms:.1f} ms not faster than cold "
            f"{cold_ms:.1f} ms"
        )
    print(
        "streaming smoke: warm pool OK — 0 compiles on the hit path, "
        f"first ask+step cold {cold_ms:.1f} ms vs warm {warm_ms:.1f} ms "
        f"({cold_ms / warm_ms:.1f}x)"
    )

    # --------------------------- 4. external-fitness (ask/tell only) loop
    rng = np.random.default_rng(0)
    target = rng.uniform(0.2, 0.8, size=12).astype(np.float32)
    ext = EvolutionSession("sphere", 128, 12, seed=3, config=cfg)

    def external_fitness(genomes: np.ndarray) -> np.ndarray:
        return -np.sum((genomes - target) ** 2, axis=1)

    first = ext.ask(16)
    ext.tell(first, external_fitness(first))
    start_best = float(external_fitness(first).max())
    best = start_best
    for _ in range(80):
        cand = ext.ask(16)
        fit = external_fitness(cand)
        ext.tell(cand, fit)
        best = max(best, float(fit.max()))
    if not (best > start_best and best > -0.15):
        fail(
            f"external-fitness loop did not recover the target "
            f"(start {start_best:.4f}, best {best:.4f})"
        )
    print(
        "streaming smoke: ask/tell external-fitness loop OK "
        f"(best distance^2 {-best:.4f} from {-start_best:.4f})"
    )

    # ----------------------------------------------- 5. event schema
    for s in (session, resumed, ext, warm):
        log = s.pga._events
        if log is not None:
            log.close()
    records = T.validate_log(events_path)
    kinds = {r["event"] for r in records}
    need = {"session_open", "session_fold", "session_suspend",
            "session_resume"}
    missing = need - kinds
    if missing:
        fail(f"event log missing kinds: {sorted(missing)}")
    print(
        f"streaming smoke: {len(records)} schema-valid events, kinds "
        f"include {sorted(need)}"
    )
    print("streaming smoke: all gates passed")


if __name__ == "__main__":
    main()
