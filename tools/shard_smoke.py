"""4-shard CPU smoke (ci.sh stage 8, ISSUE 7).

Forces a 4-device CPU platform (``jax_num_cpu_devices`` /
``xla_force_host_platform_device_count``, the way the island smokes
already do), then proves the sharded run loop end to end:

1. **Bit-identical final best** — a rank-selection (truncation) OneMax
   config run to its optimum at ``pop_shards=4`` reaches the
   bit-identical final best (f32-exact score AND an optimal phenotype)
   as the ``pop_shards=1`` same-seed run: sharded mixing and the
   global rank thresholds must not break convergence.
2. **Collective cost model** — the compiled 4-shard while body carries
   exactly ONE ppermute + ONE all_gather per generation.
3. **shard_sync telemetry** — the sharded run emits a schema-valid
   ``shard_sync`` event (validated against utils/telemetry's
   versioned EVENT_FIELDS schema, like every other ci event gate).

Run directly: ``python tools/shard_smoke.py`` (CPU). Exit 0 and
"SHARD SMOKE: PASS" = all three gates held.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

from libpga_tpu.utils.compat import force_cpu_device_count  # noqa: E402

force_cpu_device_count(4)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

POP, LENGTH, CAP = 256, 32, 400


def _solve(shards, events_path=None):
    from libpga_tpu import PGA, PGAConfig, TelemetryConfig

    tel = (
        None if events_path is None
        else TelemetryConfig(history_gens=8, events_path=events_path)
    )
    pga = PGA(
        seed=7,
        config=PGAConfig(
            pop_shards=shards, use_pallas=False, selection="truncation",
            mutation_rate=0.05, elitism=2, telemetry=tel,
        ),
    )
    h = pga.create_population(POP, LENGTH)
    pga.set_objective("onemax_bits")
    gens = pga.run(CAP, target=float(LENGTH))
    genome, score = pga.get_best_with_score(h)
    return pga, h, gens, genome, np.float32(score)


def main() -> int:
    import tempfile

    assert len(jax.devices()) >= 4, f"only {len(jax.devices())} devices"

    # Gate 1: bit-identical final best, 1 vs 4 shards, same seed.
    _, _, gens1, g1, s1 = _solve(1)
    events = tempfile.mktemp(suffix=".jsonl", prefix="pga-shard-smoke-")
    pga4, h4, gens4, g4, s4 = _solve(4, events_path=events)
    assert gens1 < CAP and gens4 < CAP, (gens1, gens4)
    assert s1.tobytes() == s4.tobytes(), f"best diverged: {s1} vs {s4}"
    assert (g1 >= 0.5).all() and (g4 >= 0.5).all(), "non-optimal best"
    print(
        f"bit-identity OK: shards=1 hit {s1} in {gens1} gens, "
        f"shards=4 hit {s4} in {gens4} gens"
    )

    # Gate 2: exactly one cross-shard collective pair per generation.
    from jax.core import ClosedJaxpr, Jaxpr

    fn = pga4._compiled_sharded_run(POP, LENGTH)
    keys = jax.random.split(jax.random.key(0), 4)
    args = (
        pga4.population(h4).genomes, keys, jnp.int32(3),
        jnp.float32(jnp.inf), pga4._mutate_params(),
    )
    jaxpr = jax.make_jaxpr(lambda *a: fn.jitted(*a))(*args)

    def walk(jxp, counts):
        for eqn in jxp.eqns:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for vv in vals:
                    if isinstance(vv, ClosedJaxpr):
                        walk(vv.jaxpr, counts)
                    elif isinstance(vv, Jaxpr):
                        walk(vv, counts)
        return counts

    def find_while(jxp):
        for eqn in jxp.eqns:
            if eqn.primitive.name == "while":
                return eqn
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for vv in vals:
                    sub = (
                        vv.jaxpr if isinstance(vv, ClosedJaxpr)
                        else vv if isinstance(vv, Jaxpr) else None
                    )
                    if sub is not None:
                        found = find_while(sub)
                        if found is not None:
                            return found
        return None

    body = find_while(jaxpr.jaxpr).params["body_jaxpr"].jaxpr
    counts = walk(body, {})
    pp, ag = counts.get("ppermute", 0), counts.get("all_gather", 0)
    assert (pp, ag) == (1, 1), f"collective pair broken: {counts}"
    print(f"collective pair OK: 1 ppermute + 1 all_gather per generation")

    # Gate 3: schema-valid shard_sync telemetry.
    from libpga_tpu.utils import telemetry

    records = telemetry.validate_log(events)  # raises on violation
    sync = [r for r in records if r["event"] == "shard_sync"]
    assert sync, f"no shard_sync event in {[r['event'] for r in records]}"
    assert sync[0]["shards"] == 4 and sync[0]["mix_rows"] == POP // 16
    print(
        f"shard_sync OK: {len(records)} schema-valid events, "
        f"sync geometry {sync[0]['shards']}x top-{sync[0]['topk']}, "
        f"{sync[0]['mix_rows']}-row comb slab"
    )

    print("SHARD SMOKE: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
