#!/usr/bin/env python
"""Live fleet console (ISSUE 9): render one fleet spool's state.

Reads the SPOOL ALONE (``serving/fleet.fleet_status``) — batch queue
depths, per-worker lease age / liveness / health / throughput, and the
merged cross-process latency percentiles from the per-process metric
flushes — so it works against a live fleet from any terminal AND as a
post-mortem of a crashed one (the spool of a dead fleet renders the
same way; worker liveness then reads "dead").

    JAX_PLATFORMS=cpu python tools/fleet_top.py --spool DIR            # once
    JAX_PLATFORMS=cpu python tools/fleet_top.py --spool DIR --watch    # top-style
    JAX_PLATFORMS=cpu python tools/fleet_top.py --spool DIR --json     # raw dict
    JAX_PLATFORMS=cpu python tools/fleet_top.py --spool DIR --tenants  # per-tenant

``--tenants`` (ISSUE 14) renders the per-tenant view — queue depth
(pending/claimed tickets from the batch files themselves), completions
and dead letters, e2e/spool-wait percentiles from the merged
tenant-labeled histograms, and the SLO burn-rate gauges — all
reconstructed from the spool alone, live or post-mortem.

Exit 0 on a renderable spool (even an empty one); nonzero only when
the spool's on-disk snapshots are from an incompatible schema version
(the fail-loudly path) or the spool path is unusable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _fmt_ms(v) -> str:
    if v is None:
        return "-"
    return f"{v:,.0f}" if v >= 100 else f"{v:.1f}"


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    if v < 120:
        return f"{v:.1f}s"
    return f"{v / 60:.1f}m"


def _worker_state(w: dict, stale_after_s: float) -> str:
    if w["alive"] is False:
        return "dead"
    if w["health"] is not None and w["health"] < 1.0:
        return "STRAGGLER"
    if w["flush_age_s"] > stale_after_s:
        return "stale"
    return "up"


def _ring_line(ring) -> str:
    """Ring fast-path health (ISSUE 18), from the spool+ring alone: a
    dead-coordinator ring reads ``STALE`` (the next coordinator will
    rebuild it), a torn one ``TORN`` (readers are on spool fallback)."""
    if not ring or not ring.get("present"):
        return "ring: absent (pure-spool coordination)"
    if ring.get("torn"):
        state = "TORN"
    elif not ring.get("coordinator_alive"):
        state = "STALE (coordinator dead)"
    else:
        state = "live"
    head = ring.get("head", "-")
    depth = ring.get("pending_depth", "-")
    return (
        f"ring: {state}  coordinator pid={ring.get('pid', '?')}"
        f"  head={head}  advertised_depth={depth}"
        f"  workers_bound={ring.get('workers_bound', 0)}"
    )


def _leader_line(ld) -> str:
    """Coordinator-HA leadership (ISSUE 20), from the spool alone:
    leader pid + liveness, fence epoch, lease age, hot-standby count
    and the last failover time — the post-mortem of a murdered leader
    reads ``DEAD`` with the standby count that should have taken
    over."""
    if not ld or not ld.get("enabled"):
        return "leadership: single coordinator (HA off)"
    pid = ld.get("leader_pid")
    alive = ld.get("leader_alive")
    state = "?" if alive is None else ("up" if alive else "DEAD")
    last = ld.get("last_failover_ts")
    last_s = (
        "never" if not last
        else f"{_fmt_s(max(time.time() - last, 0.0))} ago"
    )
    return (
        f"leadership: leader pid={pid if pid is not None else '?'} ({state})"
        f"  epoch={ld.get('epoch', 0)}"
        f"  lease_age={_fmt_s(ld.get('lease_age_s'))}"
        f"  standbys={ld.get('standbys', 0)}"
        f"  last_failover={last_s}"
    )


def render(status: dict, stale_after_s: float = 10.0) -> str:
    """One screenful of fleet state from a ``fleet_status`` dict —
    pure string building, no I/O (testable against synthetic spools)."""
    q = status["queue"]
    c = status["counters"]
    lines = [
        f"fleet spool {status['spool']}",
        (
            f"queue: pending={len(q['pending_batches'])} batches "
            f"({sum(b['tickets'] for b in q['pending_batches'])} tickets)"
            f"  claimed={len(q['claimed_batches'])}"
            f"  dead={len(q['dead_batches'])}"
            f"  results={q['results']}"
        ),
        (
            f"counters: completed={c['tickets_completed']}"
            f"  worker_deaths={c['worker_deaths']}"
            f"  lease_requeues={c['lease_requeues']}"
            f"  straggler_alerts={c['straggler_alerts']}"
            f"  dead_letters={c['dead_letters']}"
        ),
        _ring_line(status.get("ring")),
        _leader_line(status.get("leadership")),
    ]
    lines.append(
        f"{'worker':<8}{'pid':>8}  {'state':<10}{'flush':>7}"
        f"  {'lease(age)':<26}{'batches':>8}{'tickets':>8}"
        f"  {'exec p50/p95 ms':>16}"
    )
    for w in sorted(status["workers"], key=lambda w: w["worker"]):
        lease = "-"
        if w["lease"] is not None:
            lease = f"{w['lease'][:18]} ({_fmt_s(w['lease_age_s'])})"
        ex = (
            "-" if not w["execute_count"]
            else f"{_fmt_ms(w['execute_p50_ms'])}/{_fmt_ms(w['execute_p95_ms'])}"
        )
        lines.append(
            f"{w['worker']:<8}{str(w['pid'] or '?'):>8}"
            f"  {_worker_state(w, stale_after_s):<10}"
            f"{_fmt_s(w['flush_age_s']):>7}  {lease:<26}"
            f"{str(w['batches_done'] if w['batches_done'] is not None else '-'):>8}"
            f"{w['tickets_published']:>8}  {ex:>16}"
        )
    if not status["workers"]:
        lines.append("  (no worker metric flushes in this spool)")
    lat = status["latency"]
    if lat:
        parts = []
        for key in ("e2e", "spool_wait", "execute"):
            rec = lat.get(key)
            if rec:
                parts.append(
                    f"{key} p50={_fmt_ms(rec['p50_ms'])}"
                    f" p95={_fmt_ms(rec['p95_ms'])}"
                    f" p99={_fmt_ms(rec['p99_ms'])} (n={rec['count']})"
                )
        lines.append("latency ms (merged): " + "   ".join(parts))
    else:
        lines.append("latency: (no traced tickets recorded yet)")
    for b in q["pending_batches"][:8]:
        lines.append(
            f"  pending {b['batch']}: {b['tickets']} tickets, "
            f"age {_fmt_s(b['age_s'])}, attempts {b['attempts']}"
        )
    for b in q["dead_batches"][:8]:
        lines.append(f"  DEAD {b}")
    if status.get("metrics_skipped_files"):
        lines.append(
            f"  note: skipped unreadable metric files "
            f"{status['metrics_skipped_files']}"
        )
    return "\n".join(lines) + "\n"


def render_tenants(status: dict) -> str:
    """The per-tenant screenful (``--tenants``) from a ``fleet_status``
    dict — pure string building, like :func:`render`."""
    tenants = status.get("tenants", {})
    lines = [f"fleet spool {status['spool']} — tenants"]
    if not tenants:
        lines.append("  (no tenant-attributed work in this spool)")
        return "\n".join(lines) + "\n"
    lines.append(
        f"{'tenant':<16}{'pend':>6}{'clmd':>6}{'done':>7}{'dead':>6}"
        f"  {'e2e p50/p99 ms':>16}  {'wait p99':>9}"
        f"  {'burn f/s':>12}{'alerts':>7}"
    )
    for tenant in sorted(tenants):
        t = tenants[tenant]
        e2e = t.get("e2e")
        wait = t.get("spool_wait")
        burn = t.get("burn") or {}
        burn_s = (
            "-" if not burn else
            f"{burn.get('fast', 0):.1f}/{burn.get('slow', 0):.1f}"
        )
        lines.append(
            f"{tenant:<16}{t.get('pending', 0):>6}{t.get('claimed', 0):>6}"
            f"{t.get('completed', 0):>7}{t.get('dead_letters', 0):>6}"
            f"  {'-' if not e2e else _fmt_ms(e2e['p50_ms']) + '/' + _fmt_ms(e2e['p99_ms']):>16}"
            f"  {'-' if not wait else _fmt_ms(wait['p99_ms']):>9}"
            f"  {burn_s:>12}{t.get('burn_alerts', 0):>7}"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--spool", required=True, help="fleet spool directory")
    ap.add_argument("--watch", action="store_true",
                    help="redraw every --interval seconds until ^C")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--json", action="store_true",
                    help="print the raw status dict instead of the table")
    ap.add_argument("--tenants", action="store_true",
                    help="render the per-tenant depth/latency/burn view")
    args = ap.parse_args(argv)

    from libpga_tpu.serving.fleet import fleet_status

    while True:
        try:
            status = fleet_status(args.spool)
        except ValueError as e:
            print(f"fleet_top: {e}", file=sys.stderr)
            return 1
        if args.json:
            out = json.dumps(status, indent=2, sort_keys=True, default=str)
        elif args.tenants:
            out = render_tenants(status)
        else:
            out = render(status)
        if args.watch:
            os.system("clear" if os.name == "posix" else "cls")
        print(out, end="" if out.endswith("\n") else "\n")
        if not args.watch:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
