"""Two-process distributed smoke test (CPU-simulated multi-host).

The reference claims "CUDA GPUs+MPI" but contains zero MPI code (survey
§2.3) — this drives the multi-host path that replaces it: each process
calls ``jax.distributed.initialize`` against a shared coordinator, sees
the GLOBAL device list, builds the global mesh, and runs the sharded
island GA with ``ppermute`` ring migration across processes. No mpirun —
the processes coordinate through JAX's own distributed runtime.

Run directly (spawns its own workers):  python tools/multihost_smoke.py
Exit code 0 = both workers agree on a converged global best.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_PROCESSES = 2
DEVICES_PER_PROCESS = 4
COORD = "127.0.0.1:12421"


def worker(process_id: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from libpga_tpu.utils.compat import force_cpu_device_count

    force_cpu_device_count(DEVICES_PER_PROCESS)

    from libpga_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=COORD,
        num_processes=NUM_PROCESSES,
        process_id=process_id,
    )
    info = distributed.process_info()
    assert info["global_devices"] == NUM_PROCESSES * DEVICES_PER_PROCESS, info

    import jax.numpy as jnp
    from libpga_tpu.objectives import onemax
    from libpga_tpu.ops.crossover import uniform_crossover
    from libpga_tpu.ops.mutate import make_point_mutate
    from libpga_tpu.ops.step import make_breed
    from libpga_tpu.parallel.islands import run_islands_stacked
    from libpga_tpu.parallel.mesh import default_mesh

    mesh = default_mesh()  # spans all 8 global devices
    islands, size, length = 8, 256, 16
    breed = make_breed(uniform_crossover, make_point_mutate(0.05))
    stacked = jax.random.uniform(
        jax.random.key(0), (islands, size, length), dtype=jnp.float32
    )
    # n=32 with m=5 leaves a 2-generation remainder, exercising the
    # multi-host global-best reduction in the remainder branch too.
    genomes, scores, gens = run_islands_stacked(
        breed, onemax, stacked, jax.random.key(1),
        n=32, m=5, pct=0.1, mesh=mesh, target=float(length) + 1.0,
    )
    from libpga_tpu.parallel.mesh import global_max

    best = global_max(scores, mesh)
    print(f"[proc {process_id}] gens={gens} global best={best:.3f}", flush=True)
    assert gens == 32
    assert best > 12.0, f"no convergence: {best}"

    # --- engine path + multi-host checkpointing -------------------------
    # Drive the same workload through the PGA engine with an
    # AutoCheckpointer attached: after run_islands the engine's
    # populations are slices of the mesh-sharded result, so roughly half
    # of them are NON-addressable from each process — save() must write
    # per-process shard files without ever touching a remote buffer.
    from jax.experimental import multihost_utils

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.parallel.mesh import ISLAND_AXIS  # noqa: F401
    from libpga_tpu.utils import checkpoint
    from libpga_tpu.utils.checkpoint import AutoCheckpointer

    ckpt_path = os.environ["PGA_SMOKE_CKPT"]
    pga = PGA(seed=5, config=PGAConfig(mutation_rate=0.05))
    for _ in range(islands):
        pga.create_population(size, length)
    pga.set_objective("onemax")
    ckpt = AutoCheckpointer(pga, ckpt_path, every_generations=10)
    gens2 = pga.run_islands(20, 5, 0.1, mesh=mesh)
    assert gens2 == 20
    best_before = max(
        global_max(p.scores, mesh) for p in pga.populations
    )
    ckpt.close()  # collective: every process writes its shard file
    multihost_utils.sync_global_devices("pga-smoke-ckpt-saved")

    fresh = PGA(seed=999)
    checkpoint.restore(fresh, ckpt_path)
    assert fresh.num_populations == islands
    best_after = max(float(jnp.max(p.scores)) for p in fresh.populations)
    print(
        f"[proc {process_id}] checkpoint best {best_before:.3f} -> "
        f"restored {best_after:.3f}",
        flush=True,
    )
    assert abs(best_after - best_before) < 1e-5


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]))
        return 0

    # jax.distributed.initialize must run before any backend touch; drop
    # env triggers (e.g. an accelerator plugin loaded from sitecustomize)
    # that would initialize backends at interpreter start.
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("PALLAS_AXON") and not k.startswith("TPU_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    import tempfile

    ckpt_dir = tempfile.mkdtemp(prefix="pga_smoke_ckpt_")
    env["PGA_SMOKE_CKPT"] = os.path.join(ckpt_dir, "state.npz")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", str(i)],
            env=env,
        )
        for i in range(NUM_PROCESSES)
    ]
    rc = 0
    try:
        for p in procs:
            p.wait(timeout=420)
            rc |= p.returncode
    except subprocess.TimeoutExpired:
        # A hung worker (e.g. stale coordinator port) must not orphan the
        # others — they would pin the port and hang every future run.
        rc = 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    print("MULTIHOST SMOKE:", "PASS" if rc == 0 else "FAIL")
    return rc


if __name__ == "__main__":
    sys.exit(main())
