#!/usr/bin/env python
"""Coordinator-HA failover smoke (ISSUE 20) — ci.sh stage.

Two REAL coordinator processes (``python -m
libpga_tpu.serving.coordinator``) against one spool, real workers,
real ``kill -9`` — the chaos-style acceptance of ROADMAP item 2(a):

1. **Live failover, mid-burst**: clients submit through the durable
   intake journal (``SpoolClient``); the leader is SIGKILLed while the
   burst is in flight; the hot standby must seize the lease within the
   lease-timeout discipline (settle time asserted and reported), adopt
   the spool, replay the journal, and finish EVERY ticket
   bit-identical to a same-seed standalone engine run. Nothing is
   resubmitted.
2. **Post-failover intake**: fresh submissions (two tenants) after the
   failover complete bit-identical too — the journal + DRR quota
   accounting survived the leader change (asserted from the new
   leader's own metrics flush).
3. **Kill-point chaos matrix**: four more fleets, each killing the
   leader at a DIFFERENT protocol point via ``PGA_COORD_CHAOS``
   (mid-batch-formation, mid-requeue — compounded with a worker death,
   mid-ring-write, mid-autoscale). Every round must fail over and
   deliver all results bit-identical.
4. The merged spool metrics exposition lints clean
   (``metrics_dump.py --check``) and ``fleet_top.py`` renders the
   leadership line post-mortem.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

TOOLS = os.path.dirname(os.path.abspath(__file__))

POP, LEN, GENS = 128, 16, 4
LEASE_S = 2.0
HEARTBEAT_S = 0.4
#: Settle-time ceiling: lease timeout + generous CI slack (the lease
#: must EXPIRE before a standby may seize — sub-lease settles would
#: mean an unsafe early seizure, so only the upper bound is asserted).
SETTLE_CEILING_S = LEASE_S + 8.0


def _fail(stage: str, msg: str, logs=()):
    for path in logs:
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as fh:
                tail = fh.readlines()[-25:]
            print(f"--- {path} ---\n{''.join(tail)}", file=sys.stderr)
        except OSError:
            pass
    print(f"FAIL [{stage}] {msg}", file=sys.stderr)
    sys.exit(1)


def _ok(stage: str, msg: str):
    print(f"ok   [{stage}] {msg}")


def main() -> int:
    import numpy as np

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.serving import ha as H
    from libpga_tpu.serving.fleet import (
        FleetTicket, Spool, load_spool_metrics, merge_spool_metrics,
    )
    from libpga_tpu.utils import metrics as M

    cfg = PGAConfig(use_pallas=False)
    _refs: dict = {}

    def ref_genomes(seed: int):
        if seed not in _refs:
            ref = PGA(seed=seed, config=cfg)
            ref.create_population(POP, LEN)
            ref.set_objective("onemax")
            ref.run(GENS)
            _refs[seed] = np.array(ref._populations[0].genomes)
        return _refs[seed]

    def lease_pid(spool_dir):
        rec = Spool.read_json(
            os.path.join(spool_dir, H.COORD_DIR, H.LEASE_NAME)
        )
        return None if rec is None else rec.get("pid")

    def fence_epoch(spool_dir) -> int:
        rec = Spool.read_json(
            os.path.join(spool_dir, H.COORD_DIR, H.FENCE_NAME)
        )
        try:
            return 0 if rec is None else int(rec.get("epoch", 0))
        except (TypeError, ValueError):
            return 0

    def wait_for(pred, timeout, what, logs=()):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        _fail("wait", f"timed out after {timeout}s waiting for {what}",
              logs)

    def spawn_coord(spool, name, tmp, *, n_workers, extra=(),
                    env_extra=None):
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env.update(env_extra or {})
        log = open(os.path.join(tmp, f"coord_{name}.log"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "libpga_tpu.serving.coordinator",
             "--spool", spool, "--coordinators", "2",
             "--n-workers", str(n_workers), "--max-batch", "2",
             "--max-wait-ms", "5", "--lease-timeout-s", str(LEASE_S),
             "--heartbeat-s", str(HEARTBEAT_S), "--poll-s", "0.05",
             "--metrics-flush-s", "0.4", *extra],
            env=env, stdout=log, stderr=log,
        )
        proc._log_path = log.name  # type: ignore[attr-defined]
        log.close()
        return proc

    def spool_pids(spool):
        """Every pid that ever flushed metrics into this spool."""
        pids = set()
        try:
            payloads, _ = load_spool_metrics(Spool(spool))
        except (ValueError, OSError):
            payloads = []
        for p in payloads:
            pid = p.get("pid")
            if isinstance(pid, int) and pid > 0 and pid != os.getpid():
                pids.add(pid)
        return pids

    def sweep(spool, coords):
        """Graceful coordinator shutdown, then SIGKILL any stragglers
        (orphaned workers of a murdered leader included)."""
        for c in coords:
            if c.poll() is None:
                c.send_signal(signal.SIGTERM)
        for c in coords:
            try:
                c.wait(timeout=30)
            except subprocess.TimeoutExpired:
                c.kill()
                c.wait(timeout=10)
        for pid in spool_pids(spool):
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    top = tempfile.mkdtemp(prefix="pga-ha-smoke-")

    # ---- Stage 1+2: live failover mid-burst + post-failover intake --
    spool = os.path.join(top, "main")
    a = spawn_coord(spool, "a", top, n_workers=4)
    logs = [a._log_path]
    coords = [a]
    try:
        wait_for(lambda: lease_pid(spool) == a.pid, 60,
                 "coordinator A to win the initial election", logs)
        b = spawn_coord(spool, "b", top, n_workers=4)
        coords.append(b)
        logs.append(b._log_path)

        sc = H.SpoolClient(spool)
        tids = [
            sc.submit(
                FleetTicket(size=POP, genome_len=LEN, n=GENS,
                            seed=60_000 + i),
                tenant=f"t{i % 2}",
            )
            for i in range(12)
        ]
        # Mid-burst: wait until the fleet is demonstrably serving
        # (some results durable, workers warm) but the burst is NOT
        # done, then murder the leader.
        wait_for(
            lambda: sum(sc.poll(t) for t in tids) >= 2, 300,
            "first results before the kill", logs,
        )
        if lease_pid(spool) != a.pid:
            _fail("failover", "leadership moved before the kill", logs)
        epoch_before = fence_epoch(spool)
        t0 = time.monotonic()
        os.kill(a.pid, signal.SIGKILL)
        a.wait(timeout=30)
        wait_for(lambda: fence_epoch(spool) > epoch_before,
                 SETTLE_CEILING_S + 5,
                 "the standby to seize the lease", logs)
        settle = time.monotonic() - t0
        if settle > SETTLE_CEILING_S:
            _fail("failover",
                  f"settle {settle:.2f}s exceeds ceiling "
                  f"{SETTLE_CEILING_S}s", logs)
        if lease_pid(spool) != b.pid:
            _fail("failover", "lease holder is not coordinator B", logs)
        _ok("failover",
            f"leader SIGKILLed mid-burst; standby seized epoch "
            f"{fence_epoch(spool)} in {settle:.2f}s "
            f"(lease timeout {LEASE_S}s)")

        for i, tid in enumerate(tids):
            res = sc.result(tid, timeout=600)
            if not np.array_equal(res.genomes, ref_genomes(60_000 + i)):
                _fail("bits", f"ticket {tid} diverged from the "
                      "same-seed engine run", logs)
        _ok("bits", f"all {len(tids)} pre-kill tickets completed "
            "bit-identical across the failover (zero resubmits)")

        # Post-failover intake: the journal + tenant accounting are
        # live under the new leader.
        post = [
            sc.submit(
                FleetTicket(size=POP, genome_len=LEN, n=GENS,
                            seed=61_000 + i),
                tenant=f"t{i % 2}",
            )
            for i in range(4)
        ]
        for i, tid in enumerate(post):
            res = sc.result(tid, timeout=600)
            if not np.array_equal(res.genomes, ref_genomes(61_000 + i)):
                _fail("bits", f"post-failover ticket {tid} diverged",
                      logs)

        def leader_tenants():
            try:
                payloads, _ = load_spool_metrics(Spool(spool))
            except (ValueError, OSError):
                return set()
            for p in payloads:
                if (p.get("pid") == b.pid
                        and str(p.get("proc", "")).startswith(
                            "coordinator")):
                    return {
                        rec.get("labels", {}).get("tenant")
                        for rec in p.get("snapshot", {}).get(
                            "counters", [])
                        if rec.get("name") == "fleet.tenant.submissions"
                    }
            return set()

        wait_for(lambda: {"t0", "t1"} <= leader_tenants(), 30,
                 "the new leader's per-tenant DRR accounting flush",
                 logs)
        _ok("intake", "4 post-failover submissions bit-identical; new "
            "leader's flush carries both tenants' quota accounting "
            "(rebuilt from the journal)")

        # Merged exposition lints clean with every proc labeled.
        merged = merge_spool_metrics(Spool(spool))
        prom = os.path.join(top, "merged.prom")
        with open(prom, "w", encoding="utf-8") as fh:
            fh.write(M.prometheus_text(merged))
        n_coord = sum(
            1 for p in merged["merged_from"]
            if p.startswith("coordinator")
        )
        if n_coord < 2:
            _fail("lint", f"merged exposition covers "
                  f"{sorted(merged['merged_from'])}, expected both "
                  "coordinators", logs)
        lint = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "metrics_dump.py"),
             "--check", prom],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if lint.returncode != 0:
            _fail("lint", f"{lint.stdout.strip()} {lint.stderr.strip()}",
                  logs)
        _ok("lint", f"merged exposition "
            f"({len(merged['merged_from'])} procs, both coordinators) "
            "prometheus-lint clean")

        topout = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "fleet_top.py"),
             "--spool", spool],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if topout.returncode != 0 or "leadership:" not in topout.stdout:
            _fail("top", "fleet_top did not render the leadership "
                  f"line: {topout.stdout!r} {topout.stderr!r}", logs)
        _ok("top", "fleet_top renders leadership "
            + next(l for l in topout.stdout.splitlines()
                   if l.startswith("leadership:")).removeprefix(
                       "leadership:").strip())
    finally:
        sweep(spool, coords)

    # ---- Stage 3: kill-point chaos matrix ---------------------------
    # Each round: fresh spool, leader A armed with a PGA_COORD_CHAOS
    # kill point, standby B clean. A must die BY THE CHAOS (asserted),
    # B must take over, and every ticket must come back bit-identical.
    rounds = [
        ("batch_form", {"PGA_COORD_CHAOS": "sigkill@batch_form:2"}, ()),
        # Compound: every one of A's workers dies on its first execute
        # (inherited env), so the leader is requeueing a dead worker's
        # batch when the requeue kill point fires.
        ("requeue", {"PGA_COORD_CHAOS": "sigkill@requeue:1",
                     "PGA_WORKER_CHAOS": "sigkill@execute:1"}, ()),
        ("ring_write", {"PGA_COORD_CHAOS": "sigkill@ring_write:2"}, ()),
        ("autoscale", {"PGA_COORD_CHAOS": "sigkill@autoscale:20"},
         ("--autoscale",)),
    ]
    for rnd, (site, chaos_env, extra) in enumerate(rounds):
        spool = os.path.join(top, f"chaos_{site}")
        a = spawn_coord(spool, f"{site}_a", top, n_workers=2,
                        extra=extra, env_extra=chaos_env)
        coords = [a]
        logs = [a._log_path]
        try:
            wait_for(lambda: lease_pid(spool) == a.pid, 60,
                     f"[{site}] A to lead", logs)
            b = spawn_coord(spool, f"{site}_b", top, n_workers=2,
                            extra=extra)
            coords.append(b)
            logs.append(b._log_path)
            epoch_before = fence_epoch(spool)
            sc = H.SpoolClient(spool)
            seeds = [70_000 + 100 * rnd + i for i in range(4)]
            tids = [
                sc.submit(FleetTicket(size=POP, genome_len=LEN,
                                      n=GENS, seed=s))
                for s in seeds
            ]
            try:
                a.wait(timeout=240)
            except subprocess.TimeoutExpired:
                _fail(site, "chaos kill point never fired (leader "
                      "still alive)", logs)
            if a.returncode != -signal.SIGKILL:
                _fail(site, f"leader exited {a.returncode}, expected "
                      "SIGKILL from the chaos plan", logs)
            wait_for(lambda: fence_epoch(spool) > epoch_before,
                     SETTLE_CEILING_S + 5,
                     f"[{site}] failover after the chaos kill", logs)
            for s, tid in zip(seeds, tids):
                res = sc.result(tid, timeout=600)
                if not np.array_equal(res.genomes, ref_genomes(s)):
                    _fail(site, f"ticket {tid} diverged after the "
                          f"{site} kill", logs)
            _ok(site, f"leader SIGKILLed mid-{site}; epoch "
                f"{fence_epoch(spool)} took over, all "
                f"{len(tids)} tickets bit-identical")
        finally:
            sweep(spool, coords)

    print("ha smoke: all stages passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
