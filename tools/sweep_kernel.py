"""One-off (K, D) sweep of the fused breed kernel at 1M×100 OneMax.

Usage: python tools/sweep_kernel.py [--quick]
Prints gens/sec for each (dtype, K, D) combination using bench.py's
two-length subtraction estimator. Used to re-pick auto_deme_size and the
demes-per-step default after kernel changes; results land in BASELINE.md.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from libpga_tpu.objectives import onemax
from libpga_tpu.ops.pallas_step import make_pallas_breed

POP = 1 << 20
L = 100


def make_loop(breed):
    """One jitted program running n fused breed steps (matching the
    engine's while_loop structure — per-call dispatch over the tunnel
    would otherwise dominate the timing)."""

    def body(_, carry):
        g, s, key = carry
        key, sub = jax.random.split(key)
        g, s = breed.padded(g, s, sub)
        return g, s, key

    def loop(gp, sp, n):
        g, s, _ = jax.lax.fori_loop(0, n, body, (gp, sp, jax.random.key(0)))
        return g, s

    return jax.jit(loop)


def best_gps(fn, lo=30, hi=90, tries=3):
    t_lo, t_hi = [], []
    for _ in range(tries):
        t0 = time.perf_counter(); fn(lo); t_lo.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); fn(hi); t_hi.append(time.perf_counter() - t0)
    delta = min(t_hi) - min(t_lo)
    return (hi - lo) / delta if delta > 0 else float("nan")


def main():
    assert jax.default_backend() == "tpu", "sweep needs the real chip"
    quick = "--quick" in sys.argv
    # The (dtype, K, D) grid comes from the SINGLE config-space source
    # (tuning/space.py): inadmissible and silently-rounding points are
    # rejected there, before anything compiles — this tool no longer
    # hand-rolls the grid or rediscovers admissibility by building.
    from libpga_tpu.tuning import space

    combos = []
    for dt in (jnp.float32, jnp.bfloat16):
        ctx = space.SpaceContext(POP, L, dt)
        for cfg in space.grid(
            ctx,
            ("deme_size", "demes_per_step"),
            deme_size=(128, 256, 512, 1024),
            demes_per_step=(1, 2, 4, 8),
            # Riffle pinned: the ping-pong mixing gate admits only some
            # (K, D) points, which would silently mix layouts across
            # the sweep; the layout A/B lives in tools/ablate_floor.py.
            layout=("riffle",),
        ):
            combos.append((dt, cfg.deme_size, cfg.demes_per_step))
    for dt, K, D in combos:
        breed = make_pallas_breed(
            POP, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
            gene_dtype=dt, _demes_per_step=D,
            _layout="riffle",
        )
        assert breed is not None and breed.K == K and breed.D == D, (
            "space.grid admitted a config the factory resolves "
            f"differently: K={K} D={D} -> "
            f"{None if breed is None else (breed.K, breed.D)}"
        )
        gp = jax.random.uniform(jax.random.key(1), (breed.Pp, breed.Lp)).astype(dt)
        sp = jnp.sum(gp[:, :L].astype(jnp.float32), axis=1)
        loop = make_loop(breed)

        def run(n, gp=gp, sp=sp, loop=loop):
            jax.block_until_ready(loop(gp, sp, n))

        try:
            run(5)  # compile + warm
        except Exception as e:
            name = "bf16" if dt == jnp.bfloat16 else "f32"
            print(f"{name} K={K:4d} D={D}  FAILED: {str(e)[:90]}", flush=True)
            continue
        gps = best_gps(run, lo=20 if quick else 30, hi=60 if quick else 90,
                       tries=2 if quick else 3)
        name = "bf16" if dt == jnp.bfloat16 else "f32"
        print(f"{name} K={K:4d} D={D}  {gps:8.2f} gens/sec", flush=True)


if __name__ == "__main__":
    main()
