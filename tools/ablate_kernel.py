"""Stage ablation of the fused breed kernel at 1M×100 OneMax.

Usage: python tools/ablate_kernel.py [f32|bf16] [K] [D]
Measures gens/sec with kernel stages disabled one at a time (the
``_ablate`` hook in make_pallas_breed), so per-stage cost falls out by
subtraction:

  full            — the production kernel (fused evaluation on)
  no_eval         — fused evaluation off          -> eval cost
  no_mut          — mutation off                  -> mutation cost
  no_cross        — crossover mask+select off     -> crossover PRNG cost
  sel_const       — identity selection            -> rank cube + sampling
  no_matmul       — parent matmuls bypassed       -> MXU cost
  floor           — all of the above off          -> HBM IO + grid floor

Feeds BASELINE.md's per-stage table after kernel changes.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from libpga_tpu.objectives import onemax
from libpga_tpu.ops.pallas_step import make_pallas_breed

POP = 1 << 20
L = 100


def make_loop(breed):
    def body(_, carry):
        g, s, key = carry
        key, sub = jax.random.split(key)
        out = breed.padded(g, s, sub)
        g, s = out if breed.fused else (out, s)
        return g, s, key

    def loop(gp, sp, n):
        g, s, _ = jax.lax.fori_loop(0, n, body, (gp, sp, jax.random.key(0)))
        return g, s

    return jax.jit(loop)


def best_gps(fn, lo=30, hi=90, tries=3):
    t_lo, t_hi = [], []
    for _ in range(tries):
        t0 = time.perf_counter(); fn(lo); t_lo.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); fn(hi); t_hi.append(time.perf_counter() - t0)
    delta = min(t_hi) - min(t_lo)
    return (hi - lo) / delta if delta > 0 else float("nan")


def measure(dt, K, D, ablate, fused=True):
    breed = make_pallas_breed(
        POP, L, deme_size=K,
        fused_obj=onemax.kernel_rowwise if fused else None,
        gene_dtype=dt, _demes_per_step=D, _ablate=ablate,
        # Riffle pinned: stage deltas must all share ONE output layout
        # (some ablation flags are riffle-only, and the fused default
        # is now the ping-pong layout — its A/B lives in
        # tools/ablate_floor.py, not in this stage harness).
        _layout="riffle",
    )
    assert breed is not None and breed.K == K and breed.D == D, (K, D)
    gp = jax.random.uniform(jax.random.key(1), (breed.Pp, breed.Lp)).astype(dt)
    sp = jnp.sum(gp[:, :L].astype(jnp.float32), axis=1)
    loop = make_loop(breed)

    def run(n):
        jax.block_until_ready(loop(gp, sp, n))

    run(5)
    return best_gps(run)


def main():
    assert jax.default_backend() == "tpu"
    dt = jnp.bfloat16 if "bf16" in sys.argv[1:2] else jnp.float32
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    D = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    name = "bf16" if dt == jnp.bfloat16 else "f32"
    variants = [
        ("full", (), True),
        ("no_eval", (), False),
        ("no_mut", ("no_mut",), True),
        ("no_cross", ("no_cross",), True),
        ("sel_const", ("sel_const",), True),
        ("no_matmul", ("no_matmul",), True),
        ("floor", ("sel_const", "no_matmul", "no_cross", "no_mut"), False),
    ]
    base = None
    for label, abl, fused in variants:
        gps = measure(dt, K, D, abl, fused)
        ms = 1000.0 / gps
        if label == "full":
            base = ms
            print(f"{name} K={K} D={D} {label:10s} {gps:7.2f} gps  {ms:6.3f} ms/gen",
                  flush=True)
        else:
            print(f"{name} K={K} D={D} {label:10s} {gps:7.2f} gps  {ms:6.3f} ms/gen"
                  f"  (stage ≈ {base - ms:+6.3f} ms)", flush=True)


if __name__ == "__main__":
    main()
