"""Floor-attribution ablation harness for the fused generation step.

Usage: python tools/ablate_floor.py [f32|bf16] [--k 512] [--d 8]
           [--pop 1048576] [--len 100] [--rounds 5] [--dsweep] [--tsweep]
           [--json PATH]

The round-5 verdict left 58% of the f32 generation (4.33 of 7.445
ms/gen at K=512) in an unattributed "compute-removed floor":
``tools/ablate_kernel.py`` can subtract the breeding *stages* (matmul,
eval, selection, mutation) but everything the stages sit on — HBM
traffic, per-grid-step Mosaic machinery, the riffle layout's strided
writes, the score stores, the host rank sort — was one opaque number.
This tool partitions that number into NAMED components, each with a
measurement method, so BASELINE.md can carry an attribution table and
future rounds know which lever is real:

  floor            all breeding compute ablated (the round-5 variant:
                   sel_const + no_matmul + no_cross + no_mut, no fused
                   eval) — the quantity being partitioned
  copy_riffle      PURE-COPY kernel at the identical grid/BlockSpec
                   layout (``copy_only`` ablation): HBM read+write +
                   grid machinery + riffle writes, nothing else
  copy_contig      the same copy with contiguous deme-major output
                   (``no_riffle``) — the riffle stride cost by delta
  copy_alias       contiguous copy writing IN PLACE over the input
                   buffer (``alias_io`` + ``input_output_aliases``) —
                   the output-allocation headroom by delta
  copy_riffle_score  copy + the batched (1, D, K) score stores — the
                   score-write cost by delta (part of the FULL step,
                   not of the fused=False floor)
  rank_sort        ``compute_ranks`` (two-key sort + argsort) isolated
  full / full_serial / full_nodonate   the production step, and A/Bs
                   for the parallel grid dimension_semantics and jit
                   buffer donation
  full_riffle      the production step pinned to the pre-ISSUE-3
                   riffle layout (comparable to rounds <= 7 numbers)
  pingpong_alias   the production step on the shipped alias-compatible
                   ping-pong layout: in-place children via
                   input_output_aliases, parity-alternating kernels —
                   the riffle_stride + alias_headroom levers SHIPPED
  subblock         ping-pong + the manually double-buffered sub-block
                   pipeline (--subblock-b groups per grid step): the
                   grid_steps lever shipped — G/(B*D) dispatches
  --dsweep         copy_riffle at every admissible D (fixed K): fits
                   t(D) = a + b·(G/D), attributing per-grid-step
                   dispatch overhead from the slope
  --tsweep         the multi-generation kernel at T in {1,2,4,8}:
                   per-launch dispatch amortization

All variants are measured INTERLEAVED over ``--rounds`` rounds with a
fixed per-round ordering (the round-4/5 lesson: on the tunneled chip
only interleaved A/Bs are decision-grade), each sample a two-length
subtraction of per-length minima; medians are reported. The partition
itself (``partition_floor``) is pure arithmetic over the measured
medians and is unit-tested on CPU (tests/test_ablate_floor.py); the
kernel variants also run under interpret mode there, pinning the
copy kernel's identity property.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


# The floor variant of ablate_kernel.py / BASELINE.md round 5: every
# removable breeding stage off, fused evaluation off.
FLOOR_ABLATE = ("sel_const", "no_matmul", "no_cross", "no_mut")
# Copy variants skip the host rank sort too — the kernel ignores the
# ranks input, so sorting it would time the sort into the copy.
COPY = ("copy_only", "no_rank_sort")


def build_variant(
    name, dt, K, D, pop, L, ablate=(), fused=True, donate=True,
    interpret_ok=False, layout=None, subblock=None,
):
    """Build ``(loop, gp, sp)`` for one ablation variant: a jitted
    fori_loop driving ``breed.padded`` n times, plus the padded inputs.
    Mirrors tools/ablate_kernel.py's loop so numbers stay comparable.

    ``layout``/``subblock`` select the output layout (ISSUE 3 levers):
    a ping-pong breed's loop body alternates the generation parity via
    lax.cond exactly like the shipped run loop, so its timing includes
    the real dispatch pattern (two alternating aliased kernels), not a
    single-parity approximation."""
    from libpga_tpu.objectives import onemax
    from libpga_tpu.ops.pallas_step import make_pallas_breed

    breed = make_pallas_breed(
        pop, L, deme_size=K,
        fused_obj=onemax.kernel_rowwise if fused else None,
        gene_dtype=dt, _demes_per_step=D, _ablate=tuple(ablate),
        _layout=layout, _subblock=subblock,
    )
    if breed is None:
        return None
    if not interpret_ok:
        assert breed.K == K, (name, breed.K)
        if layout is None:
            assert breed.D == D, (name, breed.D)

    pingpong = getattr(breed, "layout", "riffle") == "pingpong"

    def body(i, carry):
        g, s, key = carry
        key, sub = jax.random.split(key)
        if pingpong:
            out = jax.lax.cond(
                jnp.equal(i & 1, 0),
                lambda a: breed.padded(*a, parity=0),
                lambda a: breed.padded(*a, parity=1),
                (g, s, sub),
            )
        else:
            out = breed.padded(g, s, sub)
        g, s = out if breed.fused else (out, s)
        return g, s, key

    def loop(gp, sp, n):
        g, s, _ = jax.lax.fori_loop(0, n, body, (gp, sp, jax.random.key(0)))
        return g, s

    gp = jax.random.uniform(
        jax.random.key(1), (breed.Pp, breed.Lp)
    ).astype(dt)
    sp = jnp.sum(gp[:, :L].astype(jnp.float32), axis=1)
    jitted = jax.jit(loop, donate_argnums=(0,) if donate else ())

    def run(n):
        # Donation consumes gp on the first call; feed a fresh copy so
        # every sample runs the identical program.
        jax.block_until_ready(jitted(gp + 0, sp, n))

    run.breed = breed
    return run


def build_rank_sort(dt, K, D, pop, L):
    """Isolated ``compute_ranks`` timing: the host-side two-key sort the
    one-generation path runs per generation, looped n times with the
    rank output folded back into the scores so the loop cannot be
    collapsed."""
    from libpga_tpu.ops.pallas_step import make_pallas_breed
    from libpga_tpu.objectives import onemax

    breed = make_pallas_breed(
        pop, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
        gene_dtype=dt, _demes_per_step=D,
    )
    if breed is None:
        return None
    Pp = breed.Pp

    def body(_, carry):
        s, key = carry
        key, k_tie = jax.random.split(key)
        ranks = breed.compute_ranks(s, k_tie)
        return s + 1e-6 * ranks.reshape(Pp), key

    def loop(sp, n):
        s, _ = jax.lax.fori_loop(0, n, body, (sp, jax.random.key(0)))
        return s

    jitted = jax.jit(loop)
    sp = jax.random.uniform(jax.random.key(2), (Pp,), jnp.float32)

    def run(n):
        jax.block_until_ready(jitted(sp, n))

    return run


def build_tsweep_variant(dt, K, pop, L, T):
    """Multi-generation kernel at launch depth T: per-launch dispatch
    amortizes /T, so t(T) against 1/T yields the per-launch overhead."""
    from libpga_tpu.objectives import onemax
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    bm = make_pallas_multigen(
        pop, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
        gene_dtype=dt,
    )
    if bm is None:
        return None

    def body(_, carry):
        g, s, key = carry
        key, sub = jax.random.split(key)
        g, s = bm.padded(g, s, sub, jnp.int32(T))
        return g, s, key

    def loop(gp, sp, n):
        g, s, _ = jax.lax.fori_loop(0, n, body, (gp, sp, jax.random.key(0)))
        return g, s

    jitted = jax.jit(loop)
    gp = jax.random.uniform(jax.random.key(1), (bm.Pp, bm.Lp)).astype(dt)
    sp = jnp.sum(gp[:, :L].astype(jnp.float32), axis=1)

    def run(n):
        # n LAUNCHES of T sub-generations each: per-generation figures
        # divide by T (handled by the caller via gens_per_call).
        jax.block_until_ready(jitted(gp, sp, n))

    run.gens_per_call = T
    return run


def measure_interleaved(runners: dict, rounds: int, lo=30, hi=90) -> dict:
    """{name: median ms/gen} over ``rounds`` interleaved rounds with a
    fixed per-round ordering — the measurement protocol now lives in
    ``utils/profiling`` (the only decision-grade protocol on the
    tunneled chip; BASELINE.md round 4)."""
    from libpga_tpu.utils.profiling import (
        best_ms_per_unit,
        interleaved_medians,
    )

    return interleaved_medians(
        runners,
        rounds,
        sample=lambda run: best_ms_per_unit(
            run, lo, hi, units_per_call=getattr(run, "gens_per_call", 1)
        ),
    )


def fit_dispatch_slope(dsweep_ms: dict, G: int):
    """Least-squares fit t(D) = a + b·(G/D) over the copy-kernel D sweep.
    Returns (a_ms, b_ms_per_step): ``b`` is the marginal cost of one
    grid step at fixed total HBM traffic — per-step dispatch/sync
    machinery, the component the VMEM model caps from below (K·D rows
    per step bound the minimum step count)."""
    pts = [(G / d, ms) for d, ms in sorted(dsweep_ms.items()) if ms == ms]
    if len(pts) < 2:
        return None, None
    n = len(pts)
    sx = sum(x for x, _ in pts); sy = sum(y for _, y in pts)
    sxx = sum(x * x for x, _ in pts); sxy = sum(x * y for x, y in pts)
    denom = n * sxx - sx * sx
    if denom == 0:
        return None, None
    b = (n * sxy - sx * sy) / denom
    a = (sy - b * sx) / n
    return a, b


def partition_floor(ms: dict, *, steps_bench=None, dispatch_per_step=None):
    """Partition the measured floor into named components (pure
    arithmetic — unit-tested on CPU). ``ms`` carries the medians for
    ``floor``, ``copy_riffle``, ``copy_contig``, ``copy_alias`` and
    ``rank_sort`` (missing keys degrade gracefully: the affected deltas
    fold into their parent component). Returns ``(components,
    coverage)``: an ordered list of ``(name, ms, method)`` that sums to
    ``floor`` EXACTLY by construction, and the fraction of the floor
    attributed by direct measurement (everything except the
    by-subtraction scaffold residual)."""
    floor = ms["floor"]
    copy_riffle = ms.get("copy_riffle")
    copy_contig = ms.get("copy_contig", copy_riffle)
    copy_alias = ms.get("copy_alias", copy_contig)
    rank_sort = ms.get("rank_sort", 0.0)

    comps = []
    base = copy_alias
    if steps_bench and dispatch_per_step and dispatch_per_step > 0:
        grid = min(dispatch_per_step * steps_bench, base)
        comps.append((
            "grid_steps", grid,
            f"D-sweep slope: {dispatch_per_step*1000:.2f} us/step x "
            f"{steps_bench} steps",
        ))
        base = base - grid
    comps.append((
        "hbm_copy", base,
        "aliased contiguous pure-copy kernel at the identical grid"
        + (" (minus grid_steps)" if len(comps) else ""),
    ))
    if copy_contig is not None and copy_alias is not None:
        comps.append((
            "alias_headroom", copy_contig - copy_alias,
            "contiguous copy minus in-place (input_output_aliases) copy",
        ))
    if copy_riffle is not None and copy_contig is not None:
        comps.append((
            "riffle_stride", copy_riffle - copy_contig,
            "riffle-layout copy minus contiguous copy",
        ))
    comps.append((
        "rank_sort", rank_sort, "compute_ranks looped in isolation",
    ))
    attributed = sum(c[1] for c in comps)
    comps.append((
        "kernel_scaffold", floor - attributed,
        "subtraction: floor minus all directly measured components "
        "(PRNG seeding, sel_const scaffolding, casts, unmodeled "
        "per-step overhead)",
    ))
    coverage = attributed / floor if floor else float("nan")
    return comps, coverage


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("dtype", nargs="?", default="f32", choices=["f32", "bf16"])
    ap.add_argument("--k", type=int, default=512)
    ap.add_argument("--d", type=int, default=None)
    ap.add_argument("--pop", type=int, default=1 << 20)
    ap.add_argument("--len", type=int, default=100, dest="length")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--dsweep", action="store_true")
    ap.add_argument("--tsweep", action="store_true")
    ap.add_argument(
        "--subblock-b", type=int, default=2, dest="subblock_b",
        help="sub-blocks per grid step for the 'subblock' variant "
        "(grid shrinks this many x; 2 and 4 are the shapes the model "
        "tests pin)",
    )
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    assert jax.default_backend() == "tpu", (
        "the floor is a hardware quantity — run this on TPU "
        "(the CPU-side partition arithmetic is covered by "
        "tests/test_ablate_floor.py)"
    )
    dt = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    K, pop, L = args.k, args.pop, args.length
    D = args.d
    if D is None:
        D = 4 if dt == jnp.bfloat16 else 8

    # Admissibility comes from the SINGLE config-space source
    # (tuning/space.py) — an inadmissible --k/--d aborts here with the
    # gate's reason, before any kernel compiles (this tool used to
    # discover it mid-run from factory asserts).
    from libpga_tpu.tuning import space as _space

    ctx = _space.SpaceContext(pop, L, dt)
    reason = _space.why_inadmissible(ctx, _space.KernelConfig(
        deme_size=K, demes_per_step=D, layout="riffle",
    ))
    if reason:
        raise SystemExit(f"inadmissible --k {K} --d {D}: {reason}")

    mk = lambda name, **kw: build_variant(name, dt, K, D, pop, L, **kw)

    def mk_pp(name, **kw):
        # The ping-pong levers can be inadmissible at swept shapes
        # (mixing gate / divisibility): drop the variant rather than
        # abort the whole attribution run.
        try:
            return build_variant(name, dt, K, D, pop, L, **kw)
        except ValueError as exc:
            print(f"# {name}: skipped ({exc})", flush=True)
            return None

    runners = {
        "full": mk("full"),
        # The shipped-default A/B pair (ISSUE 3): the riffle layout the
        # rounds <= 7 numbers measured, vs the alias-compatible
        # ping-pong layout (in-place children, parity-alternating
        # kernels), vs ping-pong + the sub-block pipeline collapsing
        # the grid a further --subblock-b x.
        "full_riffle": mk("full_riffle", layout="riffle"),
        "pingpong_alias": mk_pp("pingpong_alias", layout="pingpong"),
        "subblock": mk_pp(
            "subblock", layout="pingpong", subblock=args.subblock_b
        ),
        "full_serial": mk("full_serial", ablate=("serial_grid",)),
        "full_nodonate": mk("full_nodonate", donate=False),
        "floor": mk("floor", ablate=FLOOR_ABLATE, fused=False),
        "copy_riffle_score": mk("copy_riffle_score", ablate=COPY),
        "copy_riffle": mk("copy_riffle", ablate=COPY, fused=False),
        "copy_contig": mk(
            "copy_contig", ablate=COPY + ("no_riffle",), fused=False
        ),
        "copy_alias": mk(
            "copy_alias", ablate=COPY + ("no_riffle", "alias_io"),
            fused=False,
        ),
        "rank_sort": build_rank_sort(dt, K, D, pop, L),
    }
    runners = {n: r for n, r in runners.items() if r is not None}
    for r in runners.values():
        r(3)  # compile before the interleave
    med = measure_interleaved(runners, args.rounds)

    G = -(-pop // K)
    dsweep_ms, a_ms, b_ms = {}, None, None
    if args.dsweep:
        # The admissible D values at this K come from the config space
        # (one source with sweep_kernel.py and the autotuner) — the old
        # build-and-check loop compiled kernels just to discover that a
        # point rounds away.
        d_values = [
            c.demes_per_step
            for c in _space.grid(
                ctx, ("demes_per_step",),
                deme_size=(K,), layout=("riffle",),
                demes_per_step=(1, 2, 4, 8, 16, 32),
            )
        ]
        dr = {}
        for d in d_values:
            v = build_variant(
                f"copy_riffle_d{d}", dt, K, d, pop, L, ablate=COPY,
                fused=False, interpret_ok=True,
            )
            assert v is not None and v.breed.K == K and v.breed.D == d, (
                f"space admitted D={d} at K={K} but the factory "
                "resolved differently"
            )
            v(3)
            dr[d] = v
        sw = measure_interleaved(
            {f"d{d}": r for d, r in dr.items()}, args.rounds
        )
        dsweep_ms = {d: sw[f"d{d}"] for d in dr}
        a_ms, b_ms = fit_dispatch_slope(dsweep_ms, G)

    tsweep_ms = {}
    if args.tsweep:
        tr = {}
        for t in (1, 2, 4, 8):
            v = build_tsweep_variant(dt, K, pop, L, t)
            if v is not None:
                v(3)
                tr[t] = v
        sw = measure_interleaved(
            {f"t{t}": r for t, r in tr.items()}, args.rounds, lo=10, hi=30
        )
        tsweep_ms = {t: sw[f"t{t}"] for t in tr}

    comps, coverage = partition_floor(
        med, steps_bench=G // D, dispatch_per_step=b_ms,
    )

    name = args.dtype
    print(f"# floor attribution — {name} K={K} D={D} pop={pop} L={L} "
          f"({args.rounds} interleaved rounds, median ms/gen)")
    for label in runners:
        print(f"{name} {label:18s} {med[label]:8.3f} ms/gen")
    if "copy_riffle_score" in med and "copy_riffle" in med:
        print(f"{name} {'score_store':18s} "
              f"{med['copy_riffle_score'] - med['copy_riffle']:8.3f} ms "
              f"(copy_riffle_score - copy_riffle; part of full, not floor)")
    print(f"\n# partition of floor = {med['floor']:.3f} ms "
          f"(coverage {coverage:.1%} directly measured)")
    for comp, v, method in comps:
        print(f"  {comp:16s} {v:8.3f} ms  [{method}]")
    if dsweep_ms:
        print(f"\n# D sweep (copy_riffle, K={K}): "
              + ", ".join(f"D={d}: {v:.3f}" for d, v in dsweep_ms.items()))
        if b_ms is not None:
            print(f"  fit t = {a_ms:.3f} + {b_ms*1000:.2f} us * (G/D)")
    if tsweep_ms:
        print("\n# T sweep (multigen): "
              + ", ".join(f"T={t}: {v:.3f} ms/gen"
                          for t, v in tsweep_ms.items()))

    out = {
        "dtype": name, "K": K, "D": D, "pop": pop, "genome_len": L,
        "rounds": args.rounds,
        "subblock_b": args.subblock_b,
        # dispatch-count bookkeeping for the layout variants: the
        # quantity the grid_steps lever moves
        "layout_variants": {
            n: {
                "layout": r.breed.layout,
                "demes_per_step": r.breed.D,
                "grid_steps": getattr(
                    r.breed, "grid_steps", G // r.breed.D
                ),
            }
            for n, r in runners.items()
            if hasattr(r, "breed")
            and n in ("full", "full_riffle", "pingpong_alias", "subblock")
        },
        "medians_ms_per_gen": {k: round(v, 4) for k, v in med.items()},
        "floor_partition": [
            {"component": c, "ms": round(v, 4), "method": m}
            for c, v, m in comps
        ],
        "coverage": round(coverage, 4),
        "dsweep_ms": {str(d): round(v, 4) for d, v in dsweep_ms.items()},
        "dispatch_us_per_step": (
            round(b_ms * 1000, 3) if b_ms is not None else None
        ),
        "tsweep_ms": {str(t): round(v, 4) for t, v in tsweep_ms.items()},
    }
    line = json.dumps(out)
    print("\n" + line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
