#!/usr/bin/env python
"""Telemetry-overhead A/B: interleaved medians, telemetry off vs on.

Measures the cost of the on-device history carry with the same
decision-grade protocol as the bench (``utils/profiling.
interleaved_medians`` — round-4/5 lesson: only interleaved A/Bs beat
chip/process drift). Two identical solvers, one with
``TelemetryConfig(history_gens=...)``, sampled alternately; prints one
JSON line with both medians, the overhead percentage, and the n each
median rests on.

On a TPU run the default shape is the 1M×100 bench headline; on CPU
(no chip this round) pass a feasible shape, e.g.::

    JAX_PLATFORMS=cpu python tools/telemetry_overhead.py \
        --pop 16384 --len 64 --lo 10 --hi 30 --rounds 5

The acceptance bar (ISSUE 2): overhead < 2% at the bench shape.
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys

sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def make_runner(pop: int, genome_len: int, telemetry_gens: int, seed: int):
    import jax

    from libpga_tpu import PGA, PGAConfig, TelemetryConfig

    tel = (
        TelemetryConfig(history_gens=telemetry_gens)
        if telemetry_gens else None
    )
    pga = PGA(seed=seed, config=PGAConfig(telemetry=tel))
    pga.create_population(pop, genome_len)
    pga.set_objective("onemax")
    pga.run(3)  # compile + warm
    jax.block_until_ready(pga.populations[0].genomes)
    return lambda n: pga.run(n)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pop", type=int, default=1 << 20)
    ap.add_argument("--len", type=int, default=100, dest="genome_len")
    ap.add_argument("--lo", type=int, default=50)
    ap.add_argument("--hi", type=int, default=150)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument(
        "--history-gens", type=int, default=0,
        help="history capacity for the ON solver (default: hi + 8)",
    )
    args = ap.parse_args()
    hist_gens = args.history_gens or args.hi + 8

    import functools

    import jax

    from libpga_tpu.utils.profiling import best_ms_per_unit, interleaved_medians

    runners = {
        "telemetry_off": make_runner(args.pop, args.genome_len, 0, seed=42),
        "telemetry_on": make_runner(
            args.pop, args.genome_len, hist_gens, seed=42
        ),
    }
    sample = functools.partial(best_ms_per_unit, lo=args.lo, hi=args.hi)
    med = interleaved_medians(runners, rounds=args.rounds, sample=sample)
    off, on = med["telemetry_off"], med["telemetry_on"]
    overhead = (on - off) / off * 100.0 if off == off and off > 0 else None
    out = {
        "metric": "telemetry_overhead_pct",
        "value": None if overhead is None else round(overhead, 2),
        "backend": jax.default_backend(),
        "pop": args.pop,
        "genome_len": args.genome_len,
        "history_gens": hist_gens,
        "interleaved_rounds": args.rounds,
        "ms_per_gen_off_median": None if off != off else round(off, 4),
        "ms_per_gen_on_median": None if on != on else round(on, 4),
        "n": med.n,
        "dropped": med.dropped,
        "protocol": (
            f"interleaved_medians over {args.rounds} rounds of "
            f"best_ms_per_unit(lo={args.lo}, hi={args.hi})"
        ),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
