#!/usr/bin/env python
"""Tenant-attributed observability smoke (ISSUE 14) — ci.sh stage 15.

Two tenants through a real 4-worker fleet, end to end:

1. **Attribution + burn-rate**: a ``steady`` tenant submits light
   tickets under a lenient latency objective; a ``bursty`` tenant
   submits heavy tickets under a tight per-tenant override. The bursty
   tenant must trip its multi-window burn-rate alert (``slo_burn``
   event + ``fleet.check_slo(tenant=...)`` violation) while the steady
   tenant stays green — per-tenant SLOs isolating tenants is the whole
   point of the layer.
2. **Spool-only reconstruction**: after the fleet is CLOSED, per-tenant
   p99 latency, queue depth, and burn gauges must be reconstructible
   from the spool alone (``fleet_status``; ``tools/fleet_top.py
   --tenants`` renders it), and the merged per-tenant Prometheus
   exposition must pass ``tools/metrics_dump.py --check``.
3. **Session lifecycle tracing**: one streaming session per tenant —
   open → ask → tell → step → suspend → resume → step — must carry a
   schema-valid span log tiling ≥95% of the session's lifetime across
   the suspend/resume re-hosting.
4. **Zero-compile attribution**: two tenants of one shape share one
   compiled program — the tenant id is host-side labeling only.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from libpga_tpu import PGAConfig
    from libpga_tpu.config import BurnRateConfig, FleetConfig, SLOConfig
    from libpga_tpu.serving.fleet import Fleet, FleetTicket, fleet_status
    from libpga_tpu.utils import metrics as M
    from libpga_tpu.utils import telemetry as T

    tmp = tempfile.mkdtemp(prefix="pga-tenant-smoke-")
    spool = os.path.join(tmp, "spool")
    events_path = os.path.join(tmp, "events.jsonl")
    log = T.EventLog(events_path)

    # Per-tenant SLOs: the steady tenant's objective is unreachable
    # (never violates); the bursty tenant's is far below its heavy
    # tickets' real latency (every completion violates) — so its burn
    # rate is deterministically over threshold while the steady
    # tenant's budget never burns, regardless of this host's drift.
    def burn(objective_ms: float) -> BurnRateConfig:
        return BurnRateConfig(
            objective_ms=objective_ms, budget=0.25, fast_window_s=60.0,
            slow_window_s=120.0, threshold=2.0, min_samples=3,
        )

    slo = SLOConfig(
        burn=burn(1e9),
        tenants={"bursty": SLOConfig(burn=burn(5.0))},
    )
    fleet = Fleet(
        spool, "onemax", config=PGAConfig(use_pallas=False),
        fleet=FleetConfig(
            n_workers=4, max_batch=2, max_wait_ms=5, lease_timeout_s=15.0,
            heartbeat_s=0.3, poll_s=0.05, metrics_flush_s=0.3,
        ),
        events=log, slo=slo,
    )
    fleet.start()
    handles = []
    for i in range(4):
        handles.append(fleet.submit(FleetTicket(
            size=256, genome_len=16, n=2, seed=i, tenant="steady",
        )))
        handles.append(fleet.submit(FleetTicket(
            size=256, genome_len=16, n=40, seed=100 + i, tenant="bursty",
        )))
    for h in handles:
        h.result(timeout=600)

    bursty = fleet.check_slo(tenant="bursty")
    if not any(v["what"] == "fleet_tenant_burn_rate" for v in bursty):
        sys.exit(f"bursty tenant did not trip its burn-rate alert: {bursty}")
    steady = fleet.check_slo(tenant="steady")
    if steady:
        sys.exit(f"steady tenant flagged despite lenient SLO: {steady}")

    # Per-ticket traces carry the tenant.
    for h in handles[:2]:
        for rec in h.trace():
            T.validate_event(rec)
        if not any(r.get("tenant") for r in h.trace()):
            sys.exit("ticket trace lost its tenant attribution")

    merged = fleet.merged_snapshot()
    prom = M.prometheus_text(merged)
    if 'tenant="steady"' not in prom or 'tenant="bursty"' not in prom:
        sys.exit("merged exposition lacks per-tenant series")
    prom_path = os.path.join(tmp, "merged.prom")
    with open(prom_path, "w") as fh:
        fh.write(prom)
    fleet.flush_metrics()
    fleet.close()
    log.close()

    # Event schema: tenant_admit for both tenants, slo_burn ONLY for
    # the bursty one.
    records = T.validate_log(events_path)
    admits = {r["tenant"] for r in records if r["event"] == "tenant_admit"}
    if admits != {"steady", "bursty"}:
        sys.exit(f"tenant_admit events wrong: {admits}")
    burn_tenants = {r["tenant"] for r in records if r["event"] == "slo_burn"}
    if burn_tenants != {"bursty"}:
        sys.exit(f"slo_burn fired for the wrong tenants: {burn_tenants}")

    # Spool-only post-mortem: the fleet is closed; per-tenant p99,
    # depth, and burn must come back from the files alone.
    st = fleet_status(spool)
    tenants = st.get("tenants", {})
    for tenant in ("steady", "bursty"):
        rec = tenants.get(tenant)
        if rec is None:
            sys.exit(f"dead-spool status lost tenant {tenant}")
        if rec["completed"] != 4:
            sys.exit(f"{tenant}: completed {rec['completed']} != 4")
        if not rec["e2e"] or rec["e2e"]["p99_ms"] is None:
            sys.exit(f"{tenant}: no e2e percentiles from the spool")
        if "pending" not in rec or "claimed" not in rec:
            sys.exit(f"{tenant}: no queue-depth fields from the spool")
    if tenants["bursty"]["burn"].get("fast", 0.0) < 2.0:
        sys.exit(f"bursty burn gauge not reconstructed: {tenants['bursty']}")
    if tenants["steady"]["burn"].get("fast", 1.0) != 0.0:
        sys.exit(f"steady tenant burning: {tenants['steady']}")

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "tools/metrics_dump.py", "--check", prom_path],
        capture_output=True, text=True, env=env,
    )
    if proc.returncode != 0:
        sys.exit(f"merged exposition lint failed:\n{proc.stdout}\n"
                 f"{proc.stderr}")
    proc = subprocess.run(
        [sys.executable, "tools/fleet_top.py", "--spool", spool,
         "--tenants"],
        capture_output=True, text=True, env=env,
    )
    if proc.returncode != 0 or "bursty" not in proc.stdout:
        sys.exit(f"fleet_top --tenants failed:\n{proc.stdout}\n"
                 f"{proc.stderr}")

    # Session lifecycle tracing: one session per tenant, spans tile
    # >=95% across a suspend/resume re-hosting.
    import numpy as np

    from libpga_tpu.streaming import EnginePool, EvolutionSession

    pool = EnginePool(config=PGAConfig(use_pallas=False))
    for tenant in ("steady", "bursty"):
        s = pool.acquire("onemax", 256, 16, seed=5, tenant=tenant)
        s.ask(4)
        s.tell(np.zeros((1, 16), np.float32), np.array([1.0], np.float32))
        s.step(2)
        path = os.path.join(tmp, f"{tenant}.ckpt.npz")
        s.suspend(path)
        pool.release(s)
        back = EvolutionSession.resume(
            path, config=PGAConfig(use_pallas=False)
        )
        back.step(2)
        for rec in back.trace():
            T.validate_event(rec)
            if rec.get("tenant") != tenant:
                sys.exit(f"session span lost tenant: {rec}")
        cov = back.trace_coverage()
        if cov < 0.95:
            sys.exit(f"{tenant}: session spans tile {cov:.3f} < 0.95")
        spans = [r["span"] for r in back.trace()]
        if spans[:1] != ["open"] or "resume" not in spans:
            sys.exit(f"{tenant}: span sequence wrong: {spans}")

    # Zero-compile attribution: two tenants of one shape share one
    # compiled mega-run program.
    from libpga_tpu import ServingConfig
    from libpga_tpu.serving import COUNTERS, BatchedRuns, RunQueue, RunRequest

    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))
    before = COUNTERS.snapshot().get("builds", 0)
    with RunQueue(
        ex, serving=ServingConfig(max_batch=2, max_wait_ms=0)
    ) as q:
        ta = q.submit(RunRequest(size=128, genome_len=8, n=2, seed=1),
                      tenant="steady")
        tb = q.submit(RunRequest(size=128, genome_len=8, n=2, seed=2),
                      tenant="bursty")
        q.drain()
        ta.result(timeout=300)
        tb.result(timeout=300)
    builds = COUNTERS.snapshot().get("builds", 0) - before
    if builds != 1:
        sys.exit(f"two tenants of one shape built {builds} programs != 1")

    print(
        "tenant smoke OK: 8 tickets / 2 tenants through a 4-worker "
        "fleet, bursty burn-rate alert fired (steady green), "
        "per-tenant p99/depth/burn reconstructed from the dead spool, "
        "merged exposition linted, session spans tiled >=95% across "
        "resume, 1 compile for 2 tenants"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
