#!/usr/bin/env python
"""Standalone serving throughput A/B (ISSUE 4 satellite).

Measures end-to-end request service rate for N concurrent OneMax runs
three ways, interleaved per round (the decision-grade protocol from
``utils/profiling.interleaved_medians``'s docstring):

  batched     — one mega-run through serving.BatchedRuns (warm bucket;
                rates are runtime inputs, so the sweep shares one
                compiled program);
  seq_fresh   — a fresh PGA instance per request (per-engine compile
                caches: the pipeline ISSUE 4 exists to kill);
  seq_warm    — one persistent engine re-running ONE fixed config warm
                (the no-sweep charitable baseline: zero recompiles).

The request stream is a mutation-rate sweep: each request carries a
distinct (seed, rate). The engine bakes the rate into its compiled
program, so the sequential arms recompile per request — exactly the
cost the shared runtime-input program eliminates.

Prints one JSON line. Run on any backend:

    JAX_PLATFORMS=cpu python tools/serving_throughput.py
    python tools/serving_throughput.py --pop 16384 --len 100 --gens 10 \
        --batch 32 --rounds 5
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pop", type=int, default=16384)
    ap.add_argument("--len", dest="genome_len", type=int, default=100)
    ap.add_argument("--gens", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument(
        "--seq-count", type=int, default=3,
        help="fresh-engine requests timed per round",
    )
    ap.add_argument(
        "--layout", default=None, choices=[None, "run_major", "lockstep"],
        help="mega-run layout (default: ServingConfig auto)",
    )
    args = ap.parse_args()

    import jax

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.serving import COUNTERS, BatchedRuns, RunRequest

    from libpga_tpu.ops.mutate import make_point_mutate

    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))

    def sweep(n_reqs, base):
        return [
            (base + i, 0.005 + 2e-5 * (base % 7919) + 0.002 * i)
            for i in range(n_reqs)
        ]

    def serve_batched(base_seed):
        results = ex.run(
            [
                RunRequest(
                    size=args.pop, genome_len=args.genome_len,
                    n=args.gens, seed=seed, mutation_rate=rate,
                )
                for seed, rate in sweep(args.batch, base_seed)
            ],
            layout=args.layout,
        )
        for r in results:
            r.block()

    def serve_fresh(base_seed):
        for seed, rate in sweep(args.seq_count, base_seed):
            pga = PGA(seed=seed, config=PGAConfig(use_pallas=False))
            pga.create_population(args.pop, args.genome_len)
            pga.set_objective("onemax")
            pga.set_mutate(make_point_mutate(rate))
            pga.run(args.gens)

    warm = PGA(seed=1, config=PGAConfig(use_pallas=False))
    warm.create_population(args.pop, args.genome_len)
    warm.set_objective("onemax")

    serve_batched(10_000)  # compile the bucket (amortized warm-up)
    warm.run(args.gens)

    samples = {"batched": [], "seq_fresh": [], "seq_warm": []}
    speedups = []
    for rnd in range(args.rounds):
        base = 20_000 + 1_000 * rnd
        t0 = time.perf_counter()
        serve_batched(base)
        samples["batched"].append(
            args.batch / (time.perf_counter() - t0)
        )
        t0 = time.perf_counter()
        serve_fresh(base)
        samples["seq_fresh"].append(
            args.seq_count / (time.perf_counter() - t0)
        )
        t0 = time.perf_counter()
        warm.run(args.gens)
        samples["seq_warm"].append(1 / (time.perf_counter() - t0))
        speedups.append(samples["batched"][-1] / samples["seq_fresh"][-1])

    med = {k: statistics.median(v) for k, v in samples.items()}
    print(
        json.dumps(
            {
                "backend": jax.default_backend(),
                "pop": args.pop,
                "genome_len": args.genome_len,
                "gens_per_request": args.gens,
                "batch": args.batch,
                "rounds": args.rounds,
                "batched_runs_per_sec": round(med["batched"], 3),
                "seq_fresh_runs_per_sec": round(med["seq_fresh"], 3),
                "seq_warm_runs_per_sec": round(med["seq_warm"], 3),
                "speedup_vs_fresh_median": round(
                    statistics.median(speedups), 2
                ),
                "speedup_vs_warm": round(
                    med["batched"] / med["seq_warm"], 2
                ),
                "cache_counters": {
                    k: v
                    for k, v in COUNTERS.snapshot().items()
                    if k in ("hits", "misses", "builds", "evictions")
                },
            }
        )
    )


if __name__ == "__main__":
    main()
