#!/usr/bin/env python
"""Standalone serving throughput A/B (ISSUE 4 satellite).

Measures end-to-end request service rate for N concurrent OneMax runs
three ways, interleaved per round (the decision-grade protocol from
``utils/profiling.interleaved_medians``'s docstring):

  batched     — one mega-run through serving.BatchedRuns (warm bucket;
                rates are runtime inputs, so the sweep shares one
                compiled program);
  seq_fresh   — a fresh PGA instance per request (per-engine compile
                caches: the pipeline ISSUE 4 exists to kill);
  seq_warm    — one persistent engine re-running ONE fixed config warm
                (the no-sweep charitable baseline: zero recompiles).

The request stream is a mutation-rate sweep: each request carries a
distinct (seed, rate). The engine bakes the rate into its compiled
program, so the sequential arms recompile per request — exactly the
cost the shared runtime-input program eliminates.

Prints one JSON line. Run on any backend:

    JAX_PLATFORMS=cpu python tools/serving_throughput.py
    python tools/serving_throughput.py --pop 16384 --len 100 --gens 10 \
        --batch 32 --rounds 5
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pop", type=int, default=16384)
    ap.add_argument("--len", dest="genome_len", type=int, default=100)
    ap.add_argument("--gens", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument(
        "--seq-count", type=int, default=3,
        help="fresh-engine requests timed per round",
    )
    ap.add_argument(
        "--layout", default=None, choices=[None, "run_major", "lockstep"],
        help="mega-run layout (default: ServingConfig auto)",
    )
    ap.add_argument(
        "--slo", action="store_true",
        help="gate the run on the latency SLO: exit 1 when the "
             "per-ticket p99 or queue-wait objective is breached "
             "(ISSUE 6 — the CI/SLO entry point)",
    )
    ap.add_argument(
        "--slo-p99-ms", type=float, default=5000.0,
        help="aggregate objective: p99 end-to-end ticket latency (ms)",
    )
    ap.add_argument(
        "--slo-queue-wait-ms", type=float, default=1000.0,
        help="per-ticket objective: max queue wait (ms)",
    )
    args = ap.parse_args()

    import jax

    from libpga_tpu import PGA, PGAConfig, ServingConfig, SLOConfig
    from libpga_tpu.serving import (
        COUNTERS, BatchedRuns, RunQueue, RunRequest,
    )
    from libpga_tpu.utils import metrics as _metrics

    from libpga_tpu.ops.mutate import make_point_mutate

    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))

    def sweep(n_reqs, base):
        return [
            (base + i, 0.005 + 2e-5 * (base % 7919) + 0.002 * i)
            for i in range(n_reqs)
        ]

    def serve_batched(base_seed):
        results = ex.run(
            [
                RunRequest(
                    size=args.pop, genome_len=args.genome_len,
                    n=args.gens, seed=seed, mutation_rate=rate,
                )
                for seed, rate in sweep(args.batch, base_seed)
            ],
            layout=args.layout,
        )
        for r in results:
            r.block()

    def serve_fresh(base_seed):
        for seed, rate in sweep(args.seq_count, base_seed):
            pga = PGA(seed=seed, config=PGAConfig(use_pallas=False))
            pga.create_population(args.pop, args.genome_len)
            pga.set_objective("onemax")
            pga.set_mutate(make_point_mutate(rate))
            pga.run(args.gens)

    warm = PGA(seed=1, config=PGAConfig(use_pallas=False))
    warm.create_population(args.pop, args.genome_len)
    warm.set_objective("onemax")

    serve_batched(10_000)  # compile the bucket (amortized warm-up)
    warm.run(args.gens)

    samples = {"batched": [], "seq_fresh": [], "seq_warm": []}
    speedups = []
    for rnd in range(args.rounds):
        base = 20_000 + 1_000 * rnd
        t0 = time.perf_counter()
        serve_batched(base)
        samples["batched"].append(
            args.batch / (time.perf_counter() - t0)
        )
        t0 = time.perf_counter()
        serve_fresh(base)
        samples["seq_fresh"].append(
            args.seq_count / (time.perf_counter() - t0)
        )
        t0 = time.perf_counter()
        warm.run(args.gens)
        samples["seq_warm"].append(1 / (time.perf_counter() - t0))
        speedups.append(samples["batched"][-1] / samples["seq_fresh"][-1])

    # ------------------------------------------------- latency round
    # One batch through the async queue: tickets carry the full
    # submit -> admit -> launch -> complete -> readback breakdown; a
    # PRIVATE registry so the percentiles describe exactly this round.
    reg = _metrics.MetricsRegistry()
    slo = SLOConfig(
        p99_latency_ms=args.slo_p99_ms,
        max_queue_wait_ms=args.slo_queue_wait_ms,
        min_samples=min(args.batch, 20),
    )
    queue = RunQueue(
        ex,
        serving=ServingConfig(max_batch=args.batch, max_wait_ms=0),
        slo=slo,
        registry=reg,
    )
    tickets = [
        queue.submit(RunRequest(
            size=args.pop, genome_len=args.genome_len, n=args.gens,
            seed=seed, mutation_rate=rate,
        ))
        for seed, rate in sweep(args.batch, 90_000)
    ]
    queue.drain()
    for t in tickets:
        t.result(timeout=600)
    e2e = reg.histogram("serving.ticket.e2e_ms").snapshot()
    qwait = reg.histogram("serving.ticket.queue_wait_ms").snapshot()
    violations = queue.check_slo()
    per_ticket_violations = int(
        reg.counter("serving.slo_violations").value
    ) - len(violations)
    queue.close()

    med = {k: statistics.median(v) for k, v in samples.items()}
    print(
        json.dumps(
            {
                "backend": jax.default_backend(),
                "pop": args.pop,
                "genome_len": args.genome_len,
                "gens_per_request": args.gens,
                "batch": args.batch,
                "rounds": args.rounds,
                "batched_runs_per_sec": round(med["batched"], 3),
                "seq_fresh_runs_per_sec": round(med["seq_fresh"], 3),
                "seq_warm_runs_per_sec": round(med["seq_warm"], 3),
                "speedup_vs_fresh_median": round(
                    statistics.median(speedups), 2
                ),
                "speedup_vs_warm": round(
                    med["batched"] / med["seq_warm"], 2
                ),
                "latency_p50_ms": round(e2e.p50, 3),
                "latency_p99_ms": round(e2e.p99, 3),
                "queue_wait_p50_ms": round(qwait.p50, 3),
                "queue_wait_p99_ms": round(qwait.p99, 3),
                "slo_checked": bool(args.slo),
                "slo_p99_limit_ms": args.slo_p99_ms,
                "slo_queue_wait_limit_ms": args.slo_queue_wait_ms,
                "slo_violations": violations,
                "slo_per_ticket_violations": per_ticket_violations,
                "cache_counters": {
                    k: v
                    for k, v in COUNTERS.snapshot().items()
                    if k in ("hits", "misses", "builds", "evictions")
                },
            }
        )
    )
    if args.slo and (violations or per_ticket_violations):
        print(
            f"SLO BREACHED: {len(violations)} aggregate + "
            f"{per_ticket_violations} per-ticket violations "
            f"(p99 {e2e.p99:.1f}ms vs {args.slo_p99_ms}ms, "
            f"queue-wait p99 {qwait.p99:.1f}ms vs "
            f"{args.slo_queue_wait_ms}ms)",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
