"""Evolutionary kernel autotuner CLI (ROADMAP item 4, ISSUE 10).

Runs the library's own GA over the fused-kernel config space
(``libpga_tpu/tuning``) for one shape signature and merges the winning
configuration into a persistent tuning database that the engine and
the serving AOT warm-up consult at kernel selection. A chip round
becomes::

    python tools/autotune.py --shape 1048576x100 --dtype f32 \
        --budget 16 --db tuning.json --seed 0
    git add tuning.json            # commit the round's verdicts

    # every subsequent run / serving fleet:
    PGA_TUNING_DB=tuning.json python serve.py ...

``--dry-run`` prints the admissible space size (and the distinct
compiled-plan count) without measuring anything. Guarantees (see
tuning/tuner.py): measured interleaved against the default config with
repeat-until-confidence, compile-failure scores worst instead of
crashing, and the recorded entry NEVER regresses the default by more
than the drift floor. On a CPU backend every config resolves to the
one XLA plan, so the produced database is deterministic for a fixed
seed/budget — the CI smoke (tools/autotune_smoke.py) pins that.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_shape(s: str):
    try:
        pop, length = s.lower().split("x")
        return int(pop), int(length)
    except Exception:
        raise argparse.ArgumentTypeError(
            f"--shape wants POPxLEN (e.g. 1048576x100), got {s!r}"
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="evolutionary kernel autotuner"
    )
    ap.add_argument("--shape", type=parse_shape, required=True,
                    help="POPxLEN, e.g. 1048576x100")
    ap.add_argument("--dtype", choices=["f32", "bf16"], default="f32")
    ap.add_argument("--objective", default="onemax",
                    help="builtin objective name (tools surface; the "
                    "Python API takes any objective)")
    ap.add_argument("--budget", type=int, default=16,
                    help="distinct kernel configurations to measure")
    ap.add_argument("--db", default=None,
                    help="tuning database path (merged + written "
                    "atomically; omit to print the entry only)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rounds", type=int, default=3,
                    help="initial interleaved measurement rounds")
    ap.add_argument("--min-rel-ci", type=float, default=0.05,
                    dest="min_rel_ci",
                    help="repeat-until-confidence target (half-IQR / "
                    "median) for the oracle's medians")
    ap.add_argument("--max-rounds", type=int, default=9,
                    dest="max_rounds")
    ap.add_argument("--ga-pop", type=int, default=16, dest="ga_pop")
    ap.add_argument("--max-generations", type=int, default=32,
                    dest="max_generations")
    ap.add_argument("--measure-lo", type=int, default=3,
                    dest="measure_lo")
    ap.add_argument("--measure-hi", type=int, default=9,
                    dest="measure_hi")
    ap.add_argument("--drift-floor", type=float, default=None,
                    dest="drift_floor",
                    help="never-regress margin (default: the tuner's "
                    "measured-host default)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the admissible space size and exit")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from libpga_tpu.tuning import space, tuner

    pop, length = args.shape
    dt = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    ctx = space.SpaceContext(pop, length, dt)

    if args.dry_run:
        cfgs = space.grid(ctx, space.TUNER_KNOBS)
        import jax

        from libpga_tpu.config import PGAConfig
        from libpga_tpu.tuning.tuner import _plan_key

        pallas_live = (
            PGAConfig(gene_dtype=dt).pallas_enabled()
            and jax.default_backend() == "tpu"
        )
        plans = {_plan_key(ctx, c, pallas_live) for c in cfgs}
        print(json.dumps({
            "shape": f"{pop}x{length}", "dtype": args.dtype,
            "admissible_configs": len(cfgs),
            "distinct_plans": len(plans),
            "pallas_live": pallas_live,
            "knobs": list(space.TUNER_KNOBS),
        }))
        return 0

    kw = dict(
        budget=args.budget, seed=args.seed, ga_population=args.ga_pop,
        max_generations=args.max_generations, rounds=args.rounds,
        min_rel_ci=args.min_rel_ci, max_rounds=args.max_rounds,
        measure_lo=args.measure_lo, measure_hi=args.measure_hi,
    )
    if args.drift_floor is not None:
        kw["drift_floor"] = args.drift_floor
    settings = tuner.TunerSettings(**kw)
    entry = tuner.autotune(
        pop, length, objective=args.objective, gene_dtype=dt,
        settings=settings, db_path=args.db,
    )
    out = entry.as_dict()
    out["db"] = os.path.abspath(args.db) if args.db else None
    print(json.dumps(out, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
