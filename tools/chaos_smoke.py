#!/usr/bin/env python
"""Chaos smoke: the ISSUE 5 fault matrix, CPU-runnable, CI stage 6.

Each scenario installs a deterministic fault plan (``robustness/faults``),
exercises a real entry point, and asserts BOTH halves of the robustness
contract:

1. **recovery** — the run completes (retry, resume, degrade, or isolate
   per the scenario) instead of dying;
2. **bit-identity** — the recovered run's final best genome/score is
   bit-identical to the fault-free same-seed run with the same cadence
   (rollback replays the engine key chain), or, for the poisoned-request
   scenario, every innocent co-batched ticket matches its fault-free
   result while the poisoned one dead-letters.

Matrix:
  compile-fault     injected kernel.build failure → engine degrades the
                    config to the XLA path (fallback="xla"), results
                    equal the plain XLA run; serving.compile failure →
                    queue isolation requeues and every ticket completes
  objective-raise   supervised_run retries after an injected objective
                    exception; final state bit-identical to fault-free
  nan-storm         supervised_run detects NaN scores, rolls back,
                    retries; bit-identical to fault-free
  checkpoint-kill   an injected failure between the checkpoint temp
                    write and the atomic rename: the previous checkpoint
                    survives, the supervised run retries the chunk+save
                    and still ends bit-identical; a run killed outright
                    resumes from the last durable checkpoint
  flusher-death     the serving queue's background flusher thread dies;
                    the next submit resurrects it and all tickets land
  dead-letter       one statically poisoned request inside a mega-batch
                    dead-letters with its diagnosis; all co-batched
                    tickets complete bit-identically
  fleet             ISSUE 8, three sub-scenarios against a real
                    cross-process fleet (serving/fleet.py):
                    (a) SIGKILL a worker mid-batch — lease recovered,
                    batch re-run bit-identical on the survivor;
                    (b) SIGSTOP a worker (preemption pause) — its lease
                    EXPIRES under a live process, the batch requeues,
                    results bit-identical;
                    (c) kill a worker mid-drain-checkpoint (injected
                    checkpoint.save fault with no retries) — the
                    previous durable checkpoint survives the torn save
                    and a fresh worker resumes to bit-identical bits;
                    all three leave schema-valid worker_death /
                    lease_requeue events in the coordinator log.

Exit 0 with a one-line summary per scenario; nonzero on first failure.
"""

import dataclasses
import os
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from libpga_tpu import PGA, PGAConfig, ServingConfig  # noqa: E402
from libpga_tpu.robustness import faults  # noqa: E402
from libpga_tpu.robustness.supervisor import (  # noqa: E402
    RetryPolicy,
    supervised_run,
)
from libpga_tpu.serving import (  # noqa: E402
    BatchedRuns,
    RunQueue,
    RunRequest,
)

SEED = 11
POP, LEN, GENS, EVERY = 128, 16, 8, 2
_NOSLEEP = lambda s: None  # noqa: E731 — backoff sleeps add nothing here


def fresh_engine(seed=SEED):
    pga = PGA(seed=seed, config=PGAConfig(use_pallas=False))
    pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    return pga


def genomes_of(pga):
    # explicit host copy — never a zero-copy view of a donatable buffer
    return np.array(pga._populations[0].genomes, copy=True)


def faultfree_supervised(tmp):
    """The reference trajectory every recovery must match bit-exactly."""
    pga = fresh_engine()
    report = supervised_run(
        pga, GENS, checkpoint_path=os.path.join(tmp, "ref.npz"),
        checkpoint_every=EVERY, sleep=_NOSLEEP,
    )
    return genomes_of(pga), report.best_score


def check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"chaos {name}: {status}{' — ' + detail if detail else ''}")
    if not ok:
        sys.exit(f"chaos matrix failed at {name}")


def scenario_compile_fault(tmp, ref_g, ref_best):
    # Engine half: a kernel-build failure degrades the config to the
    # XLA path instead of killing the run (fallback="xla" default).
    xla = fresh_engine()
    xla.run(GENS)
    pga = PGA(seed=SEED, config=PGAConfig(use_pallas=True))
    pga._pallas_backend_ok = lambda: True  # reach the build on CPU
    pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    with faults.active(
        faults.FaultPlan("kernel.build", times=None, probability=1.0)
    ) as reg:
        pga.run(GENS)
        assert reg.injected, "kernel.build site never fired"
    engine_ok = np.array_equal(genomes_of(pga), genomes_of(xla))

    # Serving half: a mega-run compile failure is isolated — the queue
    # requeues the co-batched requests and every ticket completes.
    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))
    q = RunQueue(ex, serving=ServingConfig(max_batch=2, max_wait_ms=0))
    with faults.active(faults.FaultPlan("serving.compile", at_call_n=1)):
        tickets = [
            q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=s))
            for s in (1, 2)
        ]
        results = [t.result(timeout=120) for t in tickets]
    q.close()
    ref = BatchedRuns("onemax", config=PGAConfig(use_pallas=False)).run(
        [RunRequest(size=POP, genome_len=LEN, n=3, seed=s) for s in (1, 2)]
    )
    serving_ok = all(
        np.array_equal(np.asarray(a.genomes), np.asarray(b.genomes))
        for a, b in zip(results, ref)
    ) and q.requeues == 1 and not q.dead_letters
    check(
        "compile-fault", engine_ok and serving_ok,
        f"engine degraded bit-identical={engine_ok}, "
        f"serving requeued+bit-identical={serving_ok}",
    )


def scenario_objective_raise(tmp, ref_g, ref_best):
    pga = fresh_engine()
    with faults.active(faults.FaultPlan("objective.eval", at_call_n=2)):
        report = supervised_run(
            pga, GENS, checkpoint_path=os.path.join(tmp, "oraise.npz"),
            checkpoint_every=EVERY, retry=RetryPolicy(max_retries=2),
            sleep=_NOSLEEP,
        )
    ok = (
        report.retries == 1
        and np.array_equal(genomes_of(pga), ref_g)
        and report.best_score == ref_best
    )
    check("objective-raise", ok, f"retries={report.retries}, bit-identical")


def scenario_nan_storm(tmp, ref_g, ref_best):
    pga = fresh_engine()
    with faults.active(
        faults.FaultPlan("objective.eval", kind="nan", at_call_n=2)
    ):
        report = supervised_run(
            pga, GENS, checkpoint_path=os.path.join(tmp, "nan.npz"),
            checkpoint_every=EVERY, retry=RetryPolicy(max_retries=2),
            sleep=_NOSLEEP,
        )
    ok = (
        report.retries == 1
        and "NaNStorm" in "".join(report.errors)
        and np.array_equal(genomes_of(pga), ref_g)
        and report.best_score == ref_best
    )
    check("nan-storm", ok, f"retries={report.retries}, bit-identical")


def scenario_checkpoint_kill(tmp, ref_g, ref_best):
    # Half 1: a save that dies mid-write is retried (chunk replays
    # deterministically) and the final state is still bit-identical.
    path = os.path.join(tmp, "ckill.npz")
    pga = fresh_engine()
    with faults.active(faults.FaultPlan("checkpoint.save", at_call_n=2)):
        report = supervised_run(
            pga, GENS, checkpoint_path=path, checkpoint_every=EVERY,
            retry=RetryPolicy(max_retries=2), sleep=_NOSLEEP,
        )
    retried_ok = report.retries == 1 and np.array_equal(
        genomes_of(pga), ref_g
    )

    # Half 2: a run killed outright mid-way resumes from the last
    # durable checkpoint in a fresh engine, bit-identical at the end.
    path2 = os.path.join(tmp, "ckill2.npz")
    died = fresh_engine()
    try:
        with faults.active(faults.FaultPlan("objective.eval", at_call_n=3)):
            supervised_run(
                died, GENS, checkpoint_path=path2, checkpoint_every=EVERY,
                retry=RetryPolicy(max_retries=0), sleep=_NOSLEEP,
            )
        raise AssertionError("worker was supposed to die")
    except faults.InjectedFault:
        pass
    resumed = PGA(seed=999, config=PGAConfig(use_pallas=False))
    resumed.set_objective("onemax")  # state comes from the checkpoint
    report2 = supervised_run(
        resumed, GENS, checkpoint_path=path2, checkpoint_every=EVERY,
        resume=True, sleep=_NOSLEEP,
    )
    resume_ok = (
        report2.restored
        and report2.generations == GENS
        and np.array_equal(genomes_of(resumed), ref_g)
        and report2.best_score == ref_best
    )
    check(
        "checkpoint-kill", retried_ok and resume_ok,
        f"save-retry bit-identical={retried_ok}, "
        f"resume bit-identical={resume_ok}",
    )


def scenario_flusher_death(tmp, ref_g, ref_best):
    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))
    q = RunQueue(ex, serving=ServingConfig(max_batch=32, max_wait_ms=15.0))
    with faults.active(faults.FaultPlan("serving.flusher", at_call_n=1)):
        t1 = q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=1))
        deadline = time.monotonic() + 10
        while q._flusher.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        died = not q._flusher.is_alive()
        # the next submit resurrects the flusher, which then launches
        # both tickets off the max_wait_ms clock
        t2 = q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=2))
        deadline = time.monotonic() + 30
        while not (t1.poll() and t2.poll()):
            if time.monotonic() > deadline:
                check("flusher-death", False, "tickets never completed")
            time.sleep(0.01)
        r1, r2 = t1.result(timeout=60), t2.result(timeout=60)
    q.close()
    ref = BatchedRuns("onemax", config=PGAConfig(use_pallas=False)).run(
        [RunRequest(size=POP, genome_len=LEN, n=3, seed=s) for s in (1, 2)]
    )
    ok = died and all(
        np.array_equal(np.asarray(a.genomes), np.asarray(b.genomes))
        for a, b in zip((r1, r2), ref)
    )
    check("flusher-death", ok, f"died={died}, resurrected, bit-identical")


def scenario_dead_letter(tmp, ref_g, ref_best):
    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))
    q = RunQueue(ex, serving=ServingConfig(max_batch=4, max_wait_ms=0))
    good = [RunRequest(size=POP, genome_len=LEN, n=3, seed=s) for s in (1, 2, 3)]
    poisoned = RunRequest(
        size=POP, genome_len=LEN, n=3, seed=9,
        genomes=np.zeros((POP, LEN + 1), np.float32),  # wrong shape
    )
    tickets = [q.submit(good[0]), q.submit(poisoned), q.submit(good[1]),
               q.submit(good[2])]
    poisoned_raised = False
    try:
        tickets[1].result(timeout=60)
    except ValueError:
        poisoned_raised = True
    survivors = [tickets[0].result(timeout=60), tickets[2].result(timeout=60),
                 tickets[3].result(timeout=60)]
    q.close()
    ref = BatchedRuns("onemax", config=PGAConfig(use_pallas=False)).run(good)
    ok = (
        poisoned_raised
        and len(q.dead_letters) == 1
        and q.dead_letters[0].request is poisoned
        and all(
            np.array_equal(np.asarray(a.genomes), np.asarray(b.genomes))
            for a, b in zip(survivors, ref)
        )
    )
    check(
        "dead-letter", ok,
        "poisoned ticket dead-lettered, 3 co-batched tickets bit-identical",
    )


def scenario_fleet(tmp, ref_g, ref_best, ring=True):
    """ISSUE 8: the single-process fault matrix lifted to a real
    cross-process fleet — SIGKILL mid-batch, SIGSTOP lease expiry, and
    a worker killed mid-checkpoint-write (injected checkpoint.save
    fault, no retries) recovering via resume-from-durable-checkpoint.
    Every recovery must land bit-identical and the coordinator log must
    carry schema-valid worker_death / lease_requeue events.

    Runs TWICE (ISSUE 18): once on the shared-memory ring fast path and
    once pure-spool — chaos recovery must be bit-identical either way
    (the ring is an accelerator, never part of correctness)."""
    from libpga_tpu.config import FleetConfig
    from libpga_tpu.serving.fleet import Fleet, FleetTicket
    from libpga_tpu.utils import telemetry as _tl

    mode = "ring" if ring else "spool"
    events_path = os.path.join(tmp, f"fleet-events-{mode}.jsonl")
    log = _tl.EventLog(events_path)
    fcfg = FleetConfig(
        n_workers=2, max_batch=2, max_wait_ms=5, lease_timeout_s=2.0,
        heartbeat_s=0.2, poll_s=0.05, ring=ring,
    )
    cfg = PGAConfig(use_pallas=False)

    # (a) SIGKILL mid-batch: the doomed worker is spawned ALONE so it
    # deterministically claims the batch and kills ITSELF (real
    # kill -9) at the start of its first execution; the survivor is
    # spawned only after the death is recorded and re-runs the batch.
    # (With both workers racing one batch, the healthy one could claim
    # first and the chaos would silently test nothing.)
    kcfg = dataclasses.replace(fcfg, n_workers=1)
    f = Fleet(os.path.join(tmp, f"fleet-kill-{mode}"), "onemax", config=cfg,
              fleet=kcfg, events=log)
    f.start(worker_env={0: {"PGA_WORKER_CHAOS": "sigkill@execute:1"}})
    handles = [
        f.submit(FleetTicket(size=POP, genome_len=LEN, n=GENS, seed=s))
        for s in (21, 22)
    ]
    deadline = time.monotonic() + 60
    while f.worker_deaths < 1:
        if time.monotonic() > deadline:
            check("fleet-sigkill", False, "chaos worker never died")
        time.sleep(0.02)
    f.start()  # the survivor
    results = [h.result(timeout=300) for h in handles]
    refs = []
    for s in (21, 22):
        pga = fresh_engine(seed=s)
        pga.run(GENS)
        refs.append(genomes_of(pga))
    kill_ok = (
        f.worker_deaths == 1 and f.requeues >= 1
        and all(np.array_equal(np.asarray(r.genomes), g)
                for r, g in zip(results, refs))
    )
    f.close()
    check(f"fleet-sigkill[{mode}]", kill_ok,
          "worker killed -9 mid-batch, requeued, bit-identical")

    # (b) SIGSTOP (simulated preemption pause): the lone worker claims,
    # freezes, its lease expires under a LIVE process; a late-spawned
    # survivor re-runs the batch.
    f = Fleet(os.path.join(tmp, f"fleet-stop-{mode}"), "onemax", config=cfg,
              fleet=FleetConfig(
                  n_workers=1, max_batch=1, max_wait_ms=0,
                  lease_timeout_s=1.0, heartbeat_s=0.2, poll_s=0.05,
                  ring=ring,
              ), events=log)
    f.start(worker_env={0: {"PGA_WORKER_CHAOS": "sigstop@execute:1"}})
    h = f.submit(FleetTicket(size=POP, genome_len=LEN, n=GENS, seed=23))
    f.flush()
    deadline = time.monotonic() + 60
    while not os.listdir(f.spool.path("leases")):
        if time.monotonic() > deadline:
            check(f"fleet-sigstop[{mode}]", False, "worker never claimed")
        time.sleep(0.02)
    f.start()  # the survivor
    r = h.result(timeout=300)
    pga = fresh_engine(seed=23)
    pga.run(GENS)
    stop_ok = f.requeues >= 1 and np.array_equal(
        np.asarray(r.genomes), genomes_of(pga)
    )
    for p in f._workers.values():  # wake the paused worker for teardown
        if p.poll() is None:
            os.kill(p.pid, signal.SIGCONT)
    f.close()
    check(f"fleet-sigstop[{mode}]", stop_ok,
          "lease expired under paused worker, requeued, bit-identical")

    # (c) worker killed MID-CHECKPOINT-WRITE: the injected
    # checkpoint.save fault fires between the temp write and the atomic
    # rename of the chunk-2 save, with max_retries=0 — the worker dies,
    # the chunk-1 checkpoint survives the torn save, and a fresh worker
    # RESUMES from it, bit-identical to the fault-free supervised run.
    f = Fleet(os.path.join(tmp, f"fleet-ckpt-{mode}"), "onemax", config=cfg,
              fleet=FleetConfig(
                  n_workers=1, max_batch=1, max_wait_ms=0,
                  lease_timeout_s=5.0, heartbeat_s=0.2, poll_s=0.05,
                  ring=ring,
              ), events=log)
    f.start(worker_env={0: {
        "PGA_FAULT_SPEC":
            '{"site": "checkpoint.save", "at_call_n": 2}',
    }})
    h = f.submit(FleetTicket(
        size=POP, genome_len=LEN, n=GENS, seed=SEED,
        checkpoint_every=EVERY, max_retries=0,
    ))
    f.flush()
    deadline = time.monotonic() + 120
    while f.worker_deaths == 0:
        if time.monotonic() > deadline:
            check(f"fleet-ckpt-kill[{mode}]", False,
                  "worker never died mid-save")
        time.sleep(0.02)
    meta = None
    try:
        with open(f.spool.ckpt_path(h.tid) + ".meta.json") as fh:
            import json as _json

            meta = _json.load(fh)
    except OSError:
        pass
    f.start()  # fault-free worker resumes from the durable checkpoint
    r = h.result(timeout=300)
    ckpt_ok = (
        meta is not None and meta["generations"] == EVERY  # chunk 1 held
        and np.array_equal(np.asarray(r.genomes), ref_g)
        and r.best_score == ref_best
    )
    f.close()
    check(f"fleet-ckpt-kill[{mode}]", ckpt_ok,
          "died mid-checkpoint-write, resumed from durable chunk, "
          "bit-identical")

    log.close()
    records = _tl.validate_log(events_path)  # schema gate
    kinds = [rec["event"] for rec in records]
    fleet_ok = (
        kinds.count("worker_death") >= 2  # (a) + (c)
        and "lease_requeue" in kinds and "worker_spawn" in kinds
    )
    if ring:
        # The fast path was actually ON: every coordinator (and each
        # surviving worker) must have attached its ring.
        fleet_ok = fleet_ok and "ring_attach" in kinds
    check(f"fleet-events[{mode}]", fleet_ok,
          f"{len(records)} schema-valid records, "
          f"{kinds.count('worker_death')} worker_death, "
          f"{kinds.count('lease_requeue')} lease_requeue")


def main():
    # The flusher-death scenario kills a thread by design; keep its
    # traceback out of the smoke's output.
    threading.excepthook = lambda args: None
    with tempfile.TemporaryDirectory(prefix="pga-chaos-") as tmp:
        # Route flight-recorder dumps into the matrix's own tempdir so
        # the post-mortem gate below inspects THIS run's dumps.
        from libpga_tpu.utils import telemetry as _tl

        _tl.FLIGHT = _tl.FlightRecorder(dump_dir=tmp)
        ref_g, ref_best = faultfree_supervised(tmp)
        for scenario in (
            scenario_compile_fault,
            scenario_objective_raise,
            scenario_nan_storm,
            scenario_checkpoint_kill,
            scenario_flusher_death,
            scenario_dead_letter,
            scenario_fleet,
        ):
            scenario(tmp, ref_g, ref_best)
        # ISSUE 18: the same fleet fault matrix, pure-spool — recovery
        # must be bit-identical with the ring fast path off.
        scenario_fleet(tmp, ref_g, ref_best, ring=False)
        # ISSUE 6 acceptance: a chaos run must leave a flight-recorder
        # dump (the dead-letter scenario triggers one) whose every
        # record validates against the versioned event schema, with the
        # metric context + trailer present.
        assert _tl.FLIGHT.dumps, "chaos matrix produced no flight dump"
        records = _tl.validate_log(_tl.FLIGHT.dumps[-1])
        kinds = [r["event"] for r in records]
        assert "dead_letter" in kinds, kinds
        assert "metrics_snapshot" in kinds and kinds[-1] == "flight_dump"
    assert faults.PLAN is None, "a scenario leaked an installed fault plan"
    print(
        "chaos matrix: all scenarios recovered, bit-identical; "
        f"flight dump schema-valid ({len(records)} records)"
    )


if __name__ == "__main__":
    main()
