"""Rastrigin-30D annealed benchmark (BASELINE.json config 2).

8 islands × 16,384 individuals × 30 genes, elitism 2, ring migration of
the top 5% every 20 generations, Gaussian mutation annealed over 5
phases (sigma 0.05 → 0.001, rate 0.15), 400 generations per phase =
2,000 total — the exact scenario BASELINE.md's round-1 row measured at
~96 s wall on the XLA path.

The Pallas fast path takes mutation rate/sigma as RUNTIME inputs, so all
5 phases reuse one compilation; the XLA path re-jits per phase (each
``make_gaussian_mutate`` instance is a new trace constant).

Run: python tools/bench_rastrigin.py [--xla]
Prints one JSON line with wall time (including compiles), generations/sec
steady-state, and solution quality.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.objectives import rastrigin
from libpga_tpu.ops.mutate import make_gaussian_mutate

ISLANDS = 8
ISLAND_SIZE = 16_384
GENES = 30
PHASES = [(0.15, 0.05), (0.15, 0.02), (0.15, 0.008), (0.15, 0.003), (0.15, 0.001)]
GENS_PER_PHASE = 400


def main() -> None:
    use_pallas = "--xla" not in sys.argv
    if "--no-cache" not in sys.argv:
        from libpga_tpu.utils.profiling import enable_compilation_cache

        enable_compilation_cache()
    config = PGAConfig(elitism=2, use_pallas=use_pallas)
    pga = PGA(seed=11, config=config)
    for _ in range(ISLANDS):
        pga.create_population(ISLAND_SIZE, GENES)
    pga.set_objective("rastrigin")

    t0 = time.perf_counter()
    for rate, sigma in PHASES:
        pga.set_mutate(make_gaussian_mutate(rate=rate, sigma=sigma))
        pga.run_islands(GENS_PER_PHASE, 20, 0.05)
    wall = time.perf_counter() - t0

    # steady-state rate at the final phase settings (post-compile)
    t0 = time.perf_counter()
    pga.run_islands(100, 20, 0.05)
    steady = 100 / (time.perf_counter() - t0)

    best = pga.get_best_all()
    best_val = float(rastrigin(best))
    print(json.dumps({
        "path": "pallas" if use_pallas else "xla",
        "wall_s_2000gens_incl_compiles": round(wall, 2),
        "steady_gens_per_sec": round(steady, 1),
        "best_rastrigin": round(best_val, 4),
        "genes_at_half": round(float(np.abs(np.asarray(best) - 0.5).mean()), 4),
    }))


if __name__ == "__main__":
    main()
