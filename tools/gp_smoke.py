#!/usr/bin/env python
"""CI smoke for the GP subsystem (ISSUE 11) — tools/ci.sh stage 12.

Four gates, all CPU (no chip needed):

1. well-formedness machinery: random-grown programs are strictly
   well-formed and the structural operators preserve that for a
   randomized batch of pairs (the property the full test suite proves
   across encodings — this is the fast canary);
2. fused-vs-XLA evaluator agreement: the Pallas VMEM-stack kernel
   (interpret mode off-TPU) scores a population within float tolerance
   of the XLA interpreter, at the default AND a non-default
   (gp_stack_depth, gp_opcode_block) plan;
3. deterministic exact recovery: a seed-pinned symbolic-regression run
   evolves the known target expression ``x0*x0 + x1`` to EXACT zero
   RMSE, and a second identical run reproduces the best genome
   BIT-IDENTICALLY (same generation count, same bytes, same decoded
   expression);
4. the ``gp_run`` event kind is emitted once per GP run and validates
   against the versioned EVENT_FIELDS schema.

Exits nonzero on the first failing gate.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from libpga_tpu import PGA, GPConfig, PGAConfig, TelemetryConfig
    from libpga_tpu.gp import encoding as enc
    from libpga_tpu.gp import operators as gpo
    from libpga_tpu.gp.interpreter import make_eval_rows
    from libpga_tpu.gp.sr import make_dataset, symbolic_regression
    from libpga_tpu.utils import telemetry
    from libpga_tpu.utils.compat import install_pallas_interpret_compat

    gp = GPConfig(
        max_nodes=8, n_vars=2, consts=(1.0, 2.0), unary=("neg",),
        binary=("add", "sub", "mul"),
    )
    X, y = make_dataset(
        lambda a, b: a * a + b, n_samples=32, n_vars=2, seed=0
    )

    # -- gate 1: well-formedness by construction + operator closure
    pop = enc.random_population(jax.random.key(1), 128, gp)
    arr = np.asarray(pop)
    if not all(enc.is_well_formed(r, gp) for r in arr):
        return fail("random-grown programs are not all well-formed")
    xo = gpo.make_subtree_crossover(gp)
    mut = gpo.make_gp_mutate(gp, 0.7, 0.7)
    perm = jax.random.permutation(jax.random.key(2), 128)
    kids = xo.batched(
        pop, pop[perm], jax.random.uniform(jax.random.key(3), (128, 2))
    )
    kids = mut.batched(
        kids, jax.random.uniform(jax.random.key(4), (128, mut.rand_cols))
    )
    kids = np.asarray(kids)
    bad = sum(not enc.is_well_formed(r, gp) for r in kids)
    if bad:
        return fail(f"{bad}/128 bred children are not well-formed")
    if max(enc.program_length(r, gp) for r in kids) > gp.max_nodes:
        return fail("breeding exceeded the token capacity")
    print("gp smoke: well-formedness + operator closure OK (128 pairs)")

    # -- gate 2: fused kernel (interpret mode) vs XLA interpreter
    install_pallas_interpret_compat()
    from jax.experimental.pallas import tpu as pltpu

    from libpga_tpu.ops.gp_eval import make_gp_eval

    want = np.asarray(make_eval_rows(gp, X, y)(pop))
    with pltpu.force_tpu_interpret_mode():
        for kw in ({}, {"stack_depth": 32, "opcode_block": 4}):
            got = np.asarray(make_gp_eval(gp, X, y, pop=128, **kw)(pop))
            if not np.allclose(want, got, rtol=1e-5, atol=1e-5):
                return fail(
                    f"fused evaluator disagrees with the XLA "
                    f"interpreter at {kw or 'default knobs'}: "
                    f"max |diff| = {np.max(np.abs(want - got))}"
                )
    print("gp smoke: fused-vs-XLA evaluator agreement OK (2 plans)")

    # -- gates 3+4: deterministic exact recovery + gp_run schema
    def solve():
        path = tempfile.mktemp(suffix=".jsonl", prefix="pga-gp-smoke-")
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False, selection="truncation", elitism=2,
            telemetry=TelemetryConfig(history_gens=16, events_path=path),
        ))
        pga.set_objective(symbolic_regression(X, y, gp=gp))
        pga.set_crossover(gpo.make_subtree_crossover(gp))
        pga.set_mutate(gpo.make_gp_mutate(gp, 0.4, 0.6))
        h = pga.install_population(
            enc.random_population(jax.random.key(0), 64, gp)
        )
        gens = pga.run(80, target=0.0)
        best, score = pga.get_best_with_score(h)
        return gens, best, np.float32(score), path

    gens1, best1, s1, path1 = solve()
    if not (gens1 < 80 and s1 == np.float32(0.0)):
        return fail(
            f"SR run failed to recover the target exactly "
            f"(gens={gens1}, score={s1})"
        )
    expr = enc.decode_expression(best1, gp)
    gens2, best2, s2, _ = solve()
    if gens2 != gens1 or best1.tobytes() != best2.tobytes():
        return fail(
            f"SR recovery is not bit-deterministic: gens {gens1} vs "
            f"{gens2}, genomes equal={np.array_equal(best1, best2)}"
        )
    if enc.decode_expression(best2, gp) != expr:
        return fail("decoded expressions diverge across identical runs")
    print(
        f"gp smoke: deterministic exact recovery OK "
        f"({gens1} generations, best = {expr})"
    )

    records = telemetry.validate_log(path1)  # raises on schema breaks
    gp_runs = [r for r in records if r["event"] == "gp_run"]
    if len(gp_runs) != 1:
        return fail(f"expected exactly 1 gp_run event, got {len(gp_runs)}")
    rec = gp_runs[0]
    if rec["max_nodes"] != gp.max_nodes or rec["n_ops"] != gp.n_ops:
        return fail(f"gp_run record carries wrong encoding: {rec}")
    print(
        f"gp smoke: gp_run event schema OK "
        f"({len(records)} schema-valid records)"
    )
    return 0


def fail(msg: str) -> int:
    print(f"gp smoke FAILED: {msg}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
