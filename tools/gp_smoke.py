#!/usr/bin/env python
"""CI smoke for the GP subsystem (ISSUE 11) — tools/ci.sh stage 12.

Five gates, all CPU (no chip needed):

1. well-formedness machinery: random-grown programs are strictly
   well-formed and the structural operators preserve that for a
   randomized batch of pairs (the property the full test suite proves
   across encodings — this is the fast canary);
2. fused-vs-XLA evaluator agreement: the Pallas VMEM-stack kernel
   (interpret mode off-TPU) scores a population within float tolerance
   of the XLA interpreter, at the default AND a non-default
   (gp_stack_depth, gp_opcode_block) plan;
3. the eval-time optimizer (ISSUE 19): ``GPConfig(optimize=False)``
   lowers StableHLO BYTE-IDENTICAL (``analysis.fingerprint``) to the
   bare pre-optimizer evaluation pipeline — the escape hatch really is
   the old program — while optimizer-on scores a random population
   bit-equal to optimizer-off (fold/DCE/compact change the work, never
   the answer); prints the compaction-stats line;
4. deterministic exact recovery: a seed-pinned symbolic-regression run
   (optimizer ON, the default) evolves the known target expression
   ``x0*x0 + x1`` to EXACT zero RMSE, a second identical run
   reproduces the best genome BIT-IDENTICALLY (same generation count,
   same bytes, same decoded expression), and an optimizer-OFF twin
   also reaches exact zero. The twin's trajectory is NOT required to
   be bit-identical: XLA re-emits the sample-axis RMSE reduce
   per enclosing program (the unoptimized path already differs
   eager-vs-jit by 1 ulp), so cross-program equality is gate 3's
   same-context bit-equality, while THIS gate proves the outcome —
   both evaluators drive evolution to the same exact solution;
5. the ``gp_run`` event kind is emitted once per GP run and validates
   against the versioned EVENT_FIELDS schema (now carrying the
   optimize/dispatch provenance).

Exits nonzero on the first failing gate.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from libpga_tpu import PGA, GPConfig, PGAConfig, TelemetryConfig
    from libpga_tpu.gp import encoding as enc
    from libpga_tpu.gp import operators as gpo
    from libpga_tpu.gp.interpreter import make_eval_rows
    from libpga_tpu.gp.sr import make_dataset, symbolic_regression
    from libpga_tpu.utils import telemetry
    from libpga_tpu.utils.compat import install_pallas_interpret_compat

    gp = GPConfig(
        max_nodes=8, n_vars=2, consts=(1.0, 2.0), unary=("neg",),
        binary=("add", "sub", "mul"),
    )
    X, y = make_dataset(
        lambda a, b: a * a + b, n_samples=32, n_vars=2, seed=0
    )

    # -- gate 1: well-formedness by construction + operator closure
    pop = enc.random_population(jax.random.key(1), 128, gp)
    arr = np.asarray(pop)
    if not all(enc.is_well_formed(r, gp) for r in arr):
        return fail("random-grown programs are not all well-formed")
    xo = gpo.make_subtree_crossover(gp)
    mut = gpo.make_gp_mutate(gp, 0.7, 0.7)
    perm = jax.random.permutation(jax.random.key(2), 128)
    kids = xo.batched(
        pop, pop[perm], jax.random.uniform(jax.random.key(3), (128, 2))
    )
    kids = mut.batched(
        kids, jax.random.uniform(jax.random.key(4), (128, mut.rand_cols))
    )
    kids = np.asarray(kids)
    bad = sum(not enc.is_well_formed(r, gp) for r in kids)
    if bad:
        return fail(f"{bad}/128 bred children are not well-formed")
    if max(enc.program_length(r, gp) for r in kids) > gp.max_nodes:
        return fail("breeding exceeded the token capacity")
    print("gp smoke: well-formedness + operator closure OK (128 pairs)")

    # -- gate 2: fused kernel (interpret mode) vs XLA interpreter
    install_pallas_interpret_compat()
    from jax.experimental.pallas import tpu as pltpu

    from libpga_tpu.ops.gp_eval import make_gp_eval

    want = np.asarray(make_eval_rows(gp, X, y)(pop))
    with pltpu.force_tpu_interpret_mode():
        for kw in ({}, {"stack_depth": 32, "opcode_block": 4}):
            got = np.asarray(make_gp_eval(gp, X, y, pop=128, **kw)(pop))
            if not np.allclose(want, got, rtol=1e-5, atol=1e-5):
                return fail(
                    f"fused evaluator disagrees with the XLA "
                    f"interpreter at {kw or 'default knobs'}: "
                    f"max |diff| = {np.max(np.abs(want - got))}"
                )
    print("gp smoke: fused-vs-XLA evaluator agreement OK (2 plans)")

    # -- gate 3: optimizer byte-identity + bit-equality (ISSUE 19)
    import jax.numpy as jnp

    from libpga_tpu.analysis.ir_audit import fingerprint
    from libpga_tpu.gp.interpreter import stack_predict
    from libpga_tpu.gp.optimize import compaction_stats

    gp_off = GPConfig(
        max_nodes=8, n_vars=2, consts=(1.0, 2.0), unary=("neg",),
        binary=("add", "sub", "mul"), optimize=False,
    )
    xt = np.ascontiguousarray(np.asarray(X, np.float32).T)
    ya = np.asarray(y, np.float32).reshape(-1)

    def legacy_rows(m):
        # The pre-optimizer evaluation pipeline, verbatim: dense
        # stack_predict + RMSE + sanitize. optimize=False must lower
        # to EXACTLY this program or the escape hatch has drifted.
        preds = stack_predict(m, xt, gp_off)
        err = preds - ya[None, :]
        score = -jnp.sqrt(jnp.mean(err * err, axis=1))
        return jnp.where(jnp.isfinite(score), score, -jnp.inf).astype(
            jnp.float32
        )

    shape = jax.ShapeDtypeStruct((128, gp.genome_len), jnp.float32)
    fp_off = fingerprint(make_eval_rows(gp_off, X, y), shape)
    fp_legacy = fingerprint(legacy_rows, shape)
    if fp_off != fp_legacy:
        return fail(
            f"GPConfig(optimize=False) is not byte-identical to the "
            f"pre-optimizer pipeline ({fp_off[:12]} != {fp_legacy[:12]})"
        )
    s_on = np.asarray(make_eval_rows(gp, X, y)(pop))
    s_off = np.asarray(make_eval_rows(gp_off, X, y)(pop))
    if not np.array_equal(
        s_on.view(np.int32), s_off.view(np.int32)
    ):
        return fail("optimizer-on scores are not bit-equal to off")
    st = compaction_stats(pop, gp)
    print(
        f"gp smoke: optimizer byte-identity + bit-equality OK "
        f"(fingerprint {fp_off[:12]}); compaction: mean live "
        f"{st['mean_live_before']:.2f} -> {st['mean_live_after']:.2f} "
        f"({st['removed_frac']:.0%} removed, max {st['max_live_after']}"
        f"/{st['max_nodes']})"
    )

    # -- gates 4+5: deterministic exact recovery + gp_run schema
    def solve(gp_cfg=gp):
        path = tempfile.mktemp(suffix=".jsonl", prefix="pga-gp-smoke-")
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False, selection="truncation", elitism=2,
            telemetry=TelemetryConfig(history_gens=16, events_path=path),
        ))
        pga.set_objective(symbolic_regression(X, y, gp=gp_cfg))
        pga.set_crossover(gpo.make_subtree_crossover(gp_cfg))
        pga.set_mutate(gpo.make_gp_mutate(gp_cfg, 0.4, 0.6))
        h = pga.install_population(
            enc.random_population(jax.random.key(0), 128, gp_cfg)
        )
        gens = pga.run(80, target=0.0)
        best, score = pga.get_best_with_score(h)
        return gens, best, np.float32(score), path

    gens1, best1, s1, path1 = solve()
    if not (gens1 < 80 and s1 == np.float32(0.0)):
        return fail(
            f"SR run failed to recover the target exactly "
            f"(gens={gens1}, score={s1})"
        )
    expr = enc.decode_expression(best1, gp)
    gens2, best2, s2, _ = solve()
    if gens2 != gens1 or best1.tobytes() != best2.tobytes():
        return fail(
            f"SR recovery is not bit-deterministic: gens {gens1} vs "
            f"{gens2}, genomes equal={np.array_equal(best1, best2)}"
        )
    if enc.decode_expression(best2, gp) != expr:
        return fail("decoded expressions diverge across identical runs")
    gens3, best3, s3, _ = solve(gp_off)
    if not (gens3 < 80 and s3 == np.float32(0.0)):
        return fail(
            f"optimizer-off twin failed to recover the target exactly "
            f"(gens={gens3}, score={s3})"
        )
    print(
        f"gp smoke: deterministic exact recovery OK "
        f"({gens1} generations, best = {expr}; optimizer-off twin "
        f"exact in {gens3})"
    )

    records = telemetry.validate_log(path1)  # raises on schema breaks
    gp_runs = [r for r in records if r["event"] == "gp_run"]
    if len(gp_runs) != 1:
        return fail(f"expected exactly 1 gp_run event, got {len(gp_runs)}")
    rec = gp_runs[0]
    if rec["max_nodes"] != gp.max_nodes or rec["n_ops"] != gp.n_ops:
        return fail(f"gp_run record carries wrong encoding: {rec}")
    print(
        f"gp smoke: gp_run event schema OK "
        f"({len(records)} schema-valid records)"
    )
    return 0


def fail(msg: str) -> int:
    print(f"gp smoke FAILED: {msg}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
