"""Measured equivalence study: deme-kernel selection vs exact panmictic.

Round-2 verdict item 5: the fused Pallas kernel runs tournaments inside
VMEM demes (a random cohort reshuffled every generation by the riffle
layout) instead of the reference's exact panmictic sampling over the
whole population (``/root/reference/src/pga.cu:280-292``). This script
quantifies how much that matters, on real hardware, with three measures:

1. **One-step selection intensity** I = (E[winner score] − mean)/std on
   a Gaussian score population. Theory for tournament-2 is
   E[max(Z1,Z2)] = 1/√π ≈ 0.5642 and for k=4 ≈ 1.0294 — *independent of
   whether candidates are drawn from P rows or a uniform-random cohort
   of K*, because a uniform deme is an unbiased sample of the score
   distribution. Any deme-induced bias would show here.
2. **Takeover dynamics**: generations for the population score std to
   collapse below 1% of its initial value under selection+crossover only
   (mutation off). Deme-local selection could only slow takeover via
   opponent locality; the per-generation riffle reshuffle is designed to
   erase it.
3. **End-to-end convergence**: generations to reach 99% of the OneMax
   optimum with the standard operator stack on both paths.

Run on TPU: ``python tools/selection_equivalence.py``. Prints a markdown
table for BASELINE.md. The kernel columns cover BOTH output layouts:
the riffle shuffle and the ISSUE-3 alias-compatible ping-pong layout
(parity alternated per generation, exactly as the shipped run loop
does).

CPU fallback: ``--simulate`` runs the same three measures on a pure
numpy cohort-dynamics model driven by the EXACT layout algebra
(``ops/pallas_step.pingpong_perm`` — the same function the kernels'
BlockSpecs mirror and the structural tests pin), with rank-space
tournament sampling and binomial score blending for uniform crossover
of constant-gene rows. It cannot see Mosaic lowering, but it measures
precisely what the layout changes: WHICH rows compete, and where
children land. Bands: intensity within 1% of theory, takeover within
2% of panmictic.

``--simulate --pop-shards S`` extends the cohort machinery over an
S-way POPULATION SHARD split (ISSUE 7): the per-shard layouts compose
with ``parallel/shard_pop.shard_mix_perm`` (the cross-shard comb-slab
ppermute) and the sharded takeover must stay within 1.2% of panmictic
— the no-closed-super-blocks gate, one level above the deme layouts.
An inadmissible S (S² must divide the population) raises a ValueError
naming the valid shard counts.

Method note: scores are N(0.5, 0.05²) encoded as constant-gene rows with
a mean-gene objective, so a child's score is a convex mix of its two
parents' scores and E[child score] = E[winner score] for both paths —
the same trick the structural tests use, here measuring distributions.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

P, L = 1 << 17, 128
SEEDS = 5


def xla_breed(tournament_size=2):
    from libpga_tpu.ops.mutate import make_point_mutate
    from libpga_tpu.ops.crossover import uniform_crossover
    from libpga_tpu.ops.step import make_breed

    return jax.jit(make_breed(
        uniform_crossover, make_point_mutate(0.0),
        tournament_size=tournament_size,
    ))


def pallas_breed(K, tournament_size=2, layout=None, demes_per_step=None):
    from libpga_tpu.ops.pallas_step import make_pallas_breed

    b = make_pallas_breed(
        P, L, deme_size=K, mutation_rate=0.0,
        tournament_size=tournament_size,
        _layout=layout, _demes_per_step=demes_per_step,
    )
    assert b is not None and b.K == K
    if getattr(b, "parities", 1) > 1:
        # Alternate the generation parity exactly like the shipped run
        # loop (the measurement loops here are Python-side, so the
        # parity is static per call).
        state = {"gen": 0}

        def breed(g, s, key):
            parity = state["gen"] & 1
            state["gen"] += 1
            return b(g, s, key, parity=parity)

        return breed
    return b


def const_pop(key):
    c = jnp.clip(0.5 + 0.05 * jax.random.normal(key, (P,)), 0.0, 1.0 - 1e-6)
    return jnp.broadcast_to(c[:, None], (P, L)).astype(jnp.float32)


def scores_of(g):
    return jnp.mean(g, axis=1)


def intensity(breed, seed):
    g = const_pop(jax.random.key(seed))
    s = scores_of(g)
    g2 = breed(g, s, jax.random.key(seed + 1000))
    s2 = scores_of(g2)
    m, sd = float(jnp.mean(s)), float(jnp.std(s))
    return (float(jnp.mean(s2)) - m) / sd


def takeover(breed, seed, cap=200):
    """Generations until the score std collapses below 5% of its initial
    value under selection+uniform crossover only (mutation off) — the
    population-convergence analog of takeover time. Uniform crossover of
    constant-gene rows blends parent scores, so the collapse is gradual;
    5% marks near-fixation."""
    g = const_pop(jax.random.key(seed))
    s = scores_of(g)
    sd0 = float(jnp.std(s))
    for gen in range(1, cap + 1):
        g = breed(g, s, jax.random.fold_in(jax.random.key(seed + 2000), gen))
        s = scores_of(g)
        if float(jnp.std(s)) < 0.05 * sd0:
            return gen
    return cap


def onemax_gens(use_pallas, seed, target_frac=0.99, cap=400):
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=seed, config=PGAConfig(use_pallas=use_pallas))
    h = pga.create_population(P, 100)
    pga.set_objective("onemax")
    return pga.run(cap, target=target_frac * 100.0)


def multigen_breed(T, K=512):
    """Multi-generation kernel under the same constant-gene trick: the
    mean-gene objective is onemax/L-scaled, so in-kernel scores stay
    order-equivalent to scores_of and selection behaves identically."""
    from libpga_tpu.objectives import get as get_obj
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    obj = get_obj("onemax")
    bm = make_pallas_multigen(
        P, L, deme_size=K, mutation_rate=0.0,
        fused_obj=obj.kernel_rowwise,
        fused_consts=tuple(getattr(obj, "kernel_rowwise_consts", ())),
    )
    assert bm is not None

    def breed(g, s, key):
        g2, _ = bm(g, s, key, T)
        return g2

    return breed, T


def multigen_takeover(T, seed, cap=200):
    """Takeover granularity is T generations per launch (demes stay
    isolated within a launch — the horizon this study quantifies)."""
    breed, step = multigen_breed(T)
    g = const_pop(jax.random.key(seed))
    s = scores_of(g)
    sd0 = float(jnp.std(s))
    gen = 0
    while gen < cap:
        g = breed(g, s, jax.random.fold_in(jax.random.key(seed + 2000), gen))
        s = scores_of(g)
        gen += step
        if float(jnp.std(s)) < 0.05 * sd0:
            return gen
    return cap


def multigen_onemax_mean(T, seed, gens=64):
    """Mean population score after a fixed generation count — the
    granularity-free convergence measure for the multigen path."""
    from libpga_tpu import PGA, PGAConfig

    # K pinned to 512 for EVERY column (including the T=1 baseline) so
    # the comparison isolates the launch count from the deme size.
    pga = PGA(seed=seed, config=PGAConfig(
        use_pallas=True, pallas_generations_per_launch=T,
        pallas_deme_size=512,
    ))
    h = pga.create_population(P, 100)
    pga.set_objective("onemax")
    pga.run(gens)
    return float(jnp.mean(pga.population(h).scores))


# ---------------------------------------------------------------------
# CPU cohort-dynamics simulation (--simulate): the layout algebra's
# selection consequences without a chip. One "generation" draws, for
# every cohort slot, two tournament-2 winners in RANK SPACE (the same
# inverse CDF the kernel samples), crosses the two parent GENE ROWS
# with a per-gene coin flip (the genes must be carried, not just
# scores: uniform crossover of once-constant rows yields mixed rows
# whose gene-level diversity is what makes real takeover take tens of
# generations — a scalar-score blend model collapses geometrically and
# badly understates takeover), and writes children where the layout
# writes them: in place for the ping-pong parities, through the riffle
# permutation for the riffle layout, nowhere (whole-population cohort)
# for panmictic.
# ---------------------------------------------------------------------


def _sim_generation(g, rng, cohorts, out_rows, tk=2):
    """One selection + uniform-crossover generation on the gene matrix
    ``g (P, L)``. ``cohorts``: (C, Kc) physical rows forming each
    selection cohort; ``out_rows``: (C, Kc) physical rows the children
    land in (same array = in place)."""
    s = g.mean(axis=1)
    C, Kc = cohorts.shape
    s_c = s[cohorts]                                    # (C, Kc)
    order = np.argsort(-s_c, axis=1, kind="stable")     # rank -> slot
    u = rng.random((2, C, Kc))
    t = 1.0 - u
    for _ in range(tk.bit_length() - 1):
        t = np.sqrt(t)
    wr = np.clip(np.floor((1.0 - t) * Kc), 0, Kc - 1).astype(np.int64)
    p1_rows = np.take_along_axis(
        cohorts, np.take_along_axis(order, wr[0], axis=1), axis=1
    ).reshape(-1)
    p2_rows = np.take_along_axis(
        cohorts, np.take_along_axis(order, wr[1], axis=1), axis=1
    ).reshape(-1)
    mask = rng.random((C * Kc, g.shape[1])) < 0.5
    child = np.where(mask, g[p1_rows], g[p2_rows])
    g2 = np.empty_like(g)
    g2[out_rows.reshape(-1)] = child
    return g2


def _sim_layout(layout, K, D=8, q=8, B=1, pop=None, shards=1):
    """(cohorts, out_rows) per generation parity for a layout name:
    ``cohorts[c]`` = physical rows of selection cohort c (READ side),
    ``out_rows[c]`` = physical rows cohort c's children land in (WRITE
    side — the ping-pong write interleave makes these differ).

    ``shards`` > 1 extends every layout over an S-way population shard
    split (ISSUE 7): the single-shard algebra applies PER SHARD (this
    used to hardcode the single-shard ``pingpong_perm``) and the write
    side composes with ``shard_pop.shard_mix_perm`` — the cross-shard
    comb slab ppermute. The new ``"sharded"`` layout is the XLA path's
    cohort structure: one panmictic cohort per shard plus the mix.
    Inadmissible S raises a ValueError naming the valid shard counts
    (the ablate-flag convention)."""
    from libpga_tpu.ops.pallas_step import (
        pingpong_child_rows,
        pingpong_perm,
    )

    pop = P if pop is None else pop
    if shards > 1 or layout == "sharded":
        from libpga_tpu.parallel.shard_pop import (
            admissible_shards,
            shard_mix_perm,
        )

        valid = admissible_shards(pop, 64)
        if shards not in valid:
            raise ValueError(
                f"pop_shards={shards} is inadmissible for a simulated "
                f"population of {pop} (need S^2 | pop); valid shard "
                f"counts: {valid}"
            )
        mix_perm = shard_mix_perm(pop, shards)
        Ps = pop // shards
        if layout == "sharded":
            ident = np.arange(pop).reshape(shards, Ps)
            return [(ident, mix_perm.reshape(shards, Ps))]

        def over_shards(phases):
            """Per-shard (cohorts, out_rows) -> global, writes composed
            with the cross-shard mix permutation."""
            out = []
            for cohorts, out_rows in phases:
                gc = np.concatenate(
                    [cohorts + s * Ps for s in range(shards)]
                )
                go = np.concatenate(
                    [mix_perm[out_rows + s * Ps] for s in range(shards)]
                )
                out.append((gc, go))
            return out
    else:
        Ps = pop

        def over_shards(phases):
            return phases

    ident = np.arange(Ps).reshape(-1, K)
    if layout == "panmictic":
        return [(np.arange(pop).reshape(1, pop),
                 np.arange(pop).reshape(1, pop))]
    if layout == "riffle":
        G = Ps // K
        riffle = np.empty(Ps, np.int64)  # child g*K+r lands at row r*G+g
        for g in range(G):
            riffle[g * K : (g + 1) * K] = np.arange(K) * G + g
        return over_shards([(ident, riffle.reshape(-1, K))])
    if layout == "pingpong":
        W = B * D * K
        return over_shards([
            (
                pingpong_perm(parity, Ps, W, q).reshape(-1, K),
                pingpong_child_rows(parity, Ps, K, q, D, B).reshape(-1, K),
            )
            for parity in (0, 1)
        ])
    raise ValueError(
        f"unknown simulation layout {layout!r}; valid: "
        "['panmictic', 'riffle', 'pingpong', 'sharded']"
    )


def _sim_pop(rng, pop=None):
    """Constant-gene founder population, the study's method-note trick:
    row r carries score c_r in every gene."""
    pop = P if pop is None else pop
    c = np.clip(0.5 + 0.05 * rng.standard_normal(pop), 0.0, 1.0 - 1e-6)
    return np.broadcast_to(
        c[:, None].astype(np.float32), (pop, L)
    ).copy()


def _sim_intensity(layout, seed, K=512, pop=None, shards=1):
    rng = np.random.default_rng(seed)
    g = _sim_pop(rng, pop)
    s = g.mean(axis=1)
    m, sd = s.mean(), s.std()
    cohorts, out_rows = _sim_layout(layout, K, pop=pop, shards=shards)[0]
    g2 = _sim_generation(g, rng, cohorts, out_rows)
    return (g2.mean() - m) / sd


def _sim_takeover(layout, seed, K=512, cap=400, pop=None, shards=1):
    rng = np.random.default_rng(seed)
    g = _sim_pop(rng, pop)
    sd0 = g.mean(axis=1).std()
    phases = _sim_layout(layout, K, pop=pop, shards=shards)
    for gen in range(1, cap + 1):
        cohorts, out_rows = phases[(gen - 1) % len(phases)]
        g = _sim_generation(g, rng, cohorts, out_rows)
        if g.mean(axis=1).std() < 0.05 * sd0:
            return gen
    return cap


def simulate(seeds=SEEDS, K=512, shards=1):
    """The CPU equivalence study. Returns the results dict and prints
    the BASELINE.md table + band verdicts. ``shards`` > 1 adds the
    ISSUE 7 sharded columns: the per-shard-cohort "sharded" layout (the
    XLA path's structure) and the per-shard ping-pong composed with the
    cross-shard comb mix — each measured against panmictic with the
    acceptance band of 1.2%."""
    theory = 1 / np.sqrt(np.pi)
    layouts = ["panmictic", "riffle", "pingpong"]
    shard_layouts = []
    if shards > 1:
        shard_layouts = [
            (f"sharded(S={shards})", "sharded"),
            (f"pingpong(S={shards})", "pingpong"),
        ]
    res = {}
    for layout in layouts:
        i_m = np.mean([_sim_intensity(layout, 10 + s) for s in range(seeds)])
        t_m = np.mean([_sim_takeover(layout, 20 + s) for s in range(seeds)])
        res[layout] = {"intensity": float(i_m), "takeover": float(t_m)}
    for name, layout in shard_layouts:
        i_m = np.mean([
            _sim_intensity(layout, 10 + s, shards=shards)
            for s in range(seeds)
        ])
        t_m = np.mean([
            _sim_takeover(layout, 20 + s, shards=shards)
            for s in range(seeds)
        ])
        res[name] = {"intensity": float(i_m), "takeover": float(t_m)}
    cols = layouts + [n for n, _ in shard_layouts]
    print("\n| measure (CPU simulation, layout algebra) | theory | "
          + " | ".join(cols) + " |")
    print("|---|---|" + "---|" * len(cols))
    print(f"| tournament-2 intensity | {theory:.4f} | "
          + " | ".join(f"{res[m]['intensity']:.4f}" for m in cols)
          + " |")
    print("| takeover (gens to 5% std) | - | "
          + " | ".join(f"{res[m]['takeover']:.1f}" for m in cols)
          + " |")
    i_dev = abs(res["pingpong"]["intensity"] / theory - 1.0)
    t_dev = abs(
        res["pingpong"]["takeover"] / res["panmictic"]["takeover"] - 1.0
    )
    print(f"\npingpong intensity vs theory: {i_dev:.2%} (band 1%)")
    print(f"pingpong takeover vs panmictic: {t_dev:.2%} (band 2%)")
    res["bands_ok"] = bool(i_dev <= 0.01 and t_dev <= 0.02)
    for name, layout in shard_layouts:
        dev = abs(res[name]["takeover"] / res["panmictic"]["takeover"] - 1.0)
        # The shipped sharded-cohort structure gets the same 2%
        # takeover band as the single-shard ping-pong gate above
        # (measured: S=4/S=8 within 0.3%, S=2 at 1.6% n=10 paired —
        # BASELINE.md round 12). The pingpong-composed column stacks
        # TWO cohort levels (K-row demes inside P/S-row shards), so the
        # per-level ~0.5-1.2% drift accelerations compound — its band
        # is 3%. The failure mode this study exists to catch
        # (disconnected super-blocks) would show as takeover SLOWING
        # or never completing, never as the mild speed-up drift causes.
        band = 0.02 if layout == "sharded" else 0.03
        print(f"{name} takeover vs panmictic: {dev:.2%} (band {band:.1%})")
        res["bands_ok"] = res["bands_ok"] and bool(dev <= band)
    print("bands:", "OK" if res["bands_ok"] else "EXCEEDED")
    return res


def _flag_value(flag, default):
    if flag in sys.argv:
        i = sys.argv.index(flag)
        if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("--"):
            return int(sys.argv[i + 1])
    return default


def main():
    if "--simulate" in sys.argv:
        simulate(shards=_flag_value("--pop-shards", 1))
        return
    assert jax.default_backend() == "tpu", (
        "study needs real kernel entropy — or use --simulate for the "
        "CPU layout-algebra model"
    )
    rows = []
    for k, theory in ((2, 1 / np.sqrt(np.pi)), (4, 1.0294)):
        xb = xla_breed(k)
        i_x = np.mean([intensity(xb, s) for s in range(SEEDS)])
        row = [f"k={k}", f"{theory:.4f}", f"{i_x:.4f}"]
        for K in (128, 256, 512, 1024):
            pb = pallas_breed(K, k)
            i_p = np.mean([intensity(pb, s) for s in range(SEEDS)])
            row.append(f"{i_p:.4f}")
        # the shipped ping-pong layout at the default deme shape
        i_pp = np.mean([
            intensity(pallas_breed(512, k, layout="pingpong"), s)
            for s in range(SEEDS)
        ])
        row.append(f"{i_pp:.4f}")
        rows.append(row)
        print("intensity", row, flush=True)

    xb = xla_breed(2)
    t_x = np.mean([takeover(xb, s) for s in range(SEEDS)])
    trow = ["takeover (gens)", "-", f"{t_x:.1f}"]
    for K in (128, 256, 512, 1024):
        pb = pallas_breed(K, 2)
        t_p = np.mean([takeover(pb, s) for s in range(SEEDS)])
        trow.append(f"{t_p:.1f}")
    # ping-pong: a FRESH breed per seed so every run starts at parity 0
    t_pp = np.mean([
        takeover(pallas_breed(512, 2, layout="pingpong"), s)
        for s in range(SEEDS)
    ])
    trow.append(f"{t_pp:.1f}")
    rows.append(trow)
    print("takeover", trow, flush=True)

    g_x = np.mean([onemax_gens(False, s) for s in range(3)])
    g_p = np.mean([onemax_gens(True, s) for s in range(3)])
    print(f"onemax 99% gens: xla={g_x:.1f} pallas={g_p:.1f}", flush=True)

    print("\n| measure | theory | panmictic (XLA) | K=128 | K=256 "
          "| K=512 | K=1024 | K=512 pingpong |")
    print("|---|---|---|---|---|---|---|---|")
    for r in rows:
        print("| " + " | ".join(r) + " |")
    print(f"\nOneMax 131k×100 generations to 99% optimum: "
          f"panmictic XLA {g_x:.1f}, deme kernel {g_p:.1f} "
          f"(n=3 seeds each).")

    # ---- multigen mixing horizon: demes isolated for T generations ----
    print("\n| measure (multigen, K=512) | T=1 (1-gen kernel) | T=8 | T=16 | T=32 |")
    print("|---|---|---|---|---|")
    tk = [f"{np.mean([takeover(pallas_breed(512, 2), s) for s in range(SEEDS)]):.1f}"]
    for T in (8, 16, 32):
        tk.append(f"{np.mean([multigen_takeover(T, s) for s in range(SEEDS)]):.1f}")
    print("| takeover (gens, granularity T) | " + " | ".join(tk) + " |")
    om = []
    for T in (1, 8, 16, 32):
        om.append(f"{np.mean([multigen_onemax_mean(T, s) for s in range(3)]):.2f}")
    print("| OneMax mean score after 64 gens | " + " | ".join(om) + " |")


if __name__ == "__main__":
    main()
