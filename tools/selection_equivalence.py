"""Measured equivalence study: deme-kernel selection vs exact panmictic.

Round-2 verdict item 5: the fused Pallas kernel runs tournaments inside
VMEM demes (a random cohort reshuffled every generation by the riffle
layout) instead of the reference's exact panmictic sampling over the
whole population (``/root/reference/src/pga.cu:280-292``). This script
quantifies how much that matters, on real hardware, with three measures:

1. **One-step selection intensity** I = (E[winner score] − mean)/std on
   a Gaussian score population. Theory for tournament-2 is
   E[max(Z1,Z2)] = 1/√π ≈ 0.5642 and for k=4 ≈ 1.0294 — *independent of
   whether candidates are drawn from P rows or a uniform-random cohort
   of K*, because a uniform deme is an unbiased sample of the score
   distribution. Any deme-induced bias would show here.
2. **Takeover dynamics**: generations for the population score std to
   collapse below 1% of its initial value under selection+crossover only
   (mutation off). Deme-local selection could only slow takeover via
   opponent locality; the per-generation riffle reshuffle is designed to
   erase it.
3. **End-to-end convergence**: generations to reach 99% of the OneMax
   optimum with the standard operator stack on both paths.

Run on TPU: ``python tools/selection_equivalence.py``. Prints a markdown
table for BASELINE.md.

Method note: scores are N(0.5, 0.05²) encoded as constant-gene rows with
a mean-gene objective, so a child's score is a convex mix of its two
parents' scores and E[child score] = E[winner score] for both paths —
the same trick the structural tests use, here measuring distributions.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

P, L = 1 << 17, 128
SEEDS = 5


def xla_breed(tournament_size=2):
    from libpga_tpu.ops.mutate import make_point_mutate
    from libpga_tpu.ops.crossover import uniform_crossover
    from libpga_tpu.ops.step import make_breed

    return jax.jit(make_breed(
        uniform_crossover, make_point_mutate(0.0),
        tournament_size=tournament_size,
    ))


def pallas_breed(K, tournament_size=2):
    from libpga_tpu.ops.pallas_step import make_pallas_breed

    b = make_pallas_breed(
        P, L, deme_size=K, mutation_rate=0.0,
        tournament_size=tournament_size,
    )
    assert b is not None and b.K == K
    return b


def const_pop(key):
    c = jnp.clip(0.5 + 0.05 * jax.random.normal(key, (P,)), 0.0, 1.0 - 1e-6)
    return jnp.broadcast_to(c[:, None], (P, L)).astype(jnp.float32)


def scores_of(g):
    return jnp.mean(g, axis=1)


def intensity(breed, seed):
    g = const_pop(jax.random.key(seed))
    s = scores_of(g)
    g2 = breed(g, s, jax.random.key(seed + 1000))
    s2 = scores_of(g2)
    m, sd = float(jnp.mean(s)), float(jnp.std(s))
    return (float(jnp.mean(s2)) - m) / sd


def takeover(breed, seed, cap=200):
    """Generations until the score std collapses below 5% of its initial
    value under selection+uniform crossover only (mutation off) — the
    population-convergence analog of takeover time. Uniform crossover of
    constant-gene rows blends parent scores, so the collapse is gradual;
    5% marks near-fixation."""
    g = const_pop(jax.random.key(seed))
    s = scores_of(g)
    sd0 = float(jnp.std(s))
    for gen in range(1, cap + 1):
        g = breed(g, s, jax.random.fold_in(jax.random.key(seed + 2000), gen))
        s = scores_of(g)
        if float(jnp.std(s)) < 0.05 * sd0:
            return gen
    return cap


def onemax_gens(use_pallas, seed, target_frac=0.99, cap=400):
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=seed, config=PGAConfig(use_pallas=use_pallas))
    h = pga.create_population(P, 100)
    pga.set_objective("onemax")
    return pga.run(cap, target=target_frac * 100.0)


def multigen_breed(T, K=512):
    """Multi-generation kernel under the same constant-gene trick: the
    mean-gene objective is onemax/L-scaled, so in-kernel scores stay
    order-equivalent to scores_of and selection behaves identically."""
    from libpga_tpu.objectives import get as get_obj
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    obj = get_obj("onemax")
    bm = make_pallas_multigen(
        P, L, deme_size=K, mutation_rate=0.0,
        fused_obj=obj.kernel_rowwise,
        fused_consts=tuple(getattr(obj, "kernel_rowwise_consts", ())),
    )
    assert bm is not None

    def breed(g, s, key):
        g2, _ = bm(g, s, key, T)
        return g2

    return breed, T


def multigen_takeover(T, seed, cap=200):
    """Takeover granularity is T generations per launch (demes stay
    isolated within a launch — the horizon this study quantifies)."""
    breed, step = multigen_breed(T)
    g = const_pop(jax.random.key(seed))
    s = scores_of(g)
    sd0 = float(jnp.std(s))
    gen = 0
    while gen < cap:
        g = breed(g, s, jax.random.fold_in(jax.random.key(seed + 2000), gen))
        s = scores_of(g)
        gen += step
        if float(jnp.std(s)) < 0.05 * sd0:
            return gen
    return cap


def multigen_onemax_mean(T, seed, gens=64):
    """Mean population score after a fixed generation count — the
    granularity-free convergence measure for the multigen path."""
    from libpga_tpu import PGA, PGAConfig

    # K pinned to 512 for EVERY column (including the T=1 baseline) so
    # the comparison isolates the launch count from the deme size.
    pga = PGA(seed=seed, config=PGAConfig(
        use_pallas=True, pallas_generations_per_launch=T,
        pallas_deme_size=512,
    ))
    h = pga.create_population(P, 100)
    pga.set_objective("onemax")
    pga.run(gens)
    return float(jnp.mean(pga.population(h).scores))


def main():
    assert jax.default_backend() == "tpu", "study needs real kernel entropy"
    rows = []
    for k, theory in ((2, 1 / np.sqrt(np.pi)), (4, 1.0294)):
        xb = xla_breed(k)
        i_x = np.mean([intensity(xb, s) for s in range(SEEDS)])
        row = [f"k={k}", f"{theory:.4f}", f"{i_x:.4f}"]
        for K in (128, 256, 512, 1024):
            pb = pallas_breed(K, k)
            i_p = np.mean([intensity(pb, s) for s in range(SEEDS)])
            row.append(f"{i_p:.4f}")
        rows.append(row)
        print("intensity", row, flush=True)

    xb = xla_breed(2)
    t_x = np.mean([takeover(xb, s) for s in range(SEEDS)])
    trow = ["takeover (gens)", "-", f"{t_x:.1f}"]
    for K in (128, 256, 512, 1024):
        pb = pallas_breed(K, 2)
        t_p = np.mean([takeover(pb, s) for s in range(SEEDS)])
        trow.append(f"{t_p:.1f}")
    rows.append(trow)
    print("takeover", trow, flush=True)

    g_x = np.mean([onemax_gens(False, s) for s in range(3)])
    g_p = np.mean([onemax_gens(True, s) for s in range(3)])
    print(f"onemax 99% gens: xla={g_x:.1f} pallas={g_p:.1f}", flush=True)

    print("\n| measure | theory | panmictic (XLA) | K=128 | K=256 | K=512 | K=1024 |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print("| " + " | ".join(r) + " |")
    print(f"\nOneMax 131k×100 generations to 99% optimum: "
          f"panmictic XLA {g_x:.1f}, deme kernel {g_p:.1f} "
          f"(n=3 seeds each).")

    # ---- multigen mixing horizon: demes isolated for T generations ----
    print("\n| measure (multigen, K=512) | T=1 (1-gen kernel) | T=8 | T=16 | T=32 |")
    print("|---|---|---|---|---|")
    tk = [f"{np.mean([takeover(pallas_breed(512, 2), s) for s in range(SEEDS)]):.1f}"]
    for T in (8, 16, 32):
        tk.append(f"{np.mean([multigen_takeover(T, s) for s in range(SEEDS)]):.1f}")
    print("| takeover (gens, granularity T) | " + " | ".join(tk) + " |")
    om = []
    for T in (1, 8, 16, 32):
        om.append(f"{np.mean([multigen_onemax_mean(T, s) for s in range(3)]):.2f}")
    print("| OneMax mean score after 64 gens | " + " | ".join(om) + " |")


if __name__ == "__main__":
    main()
