#!/usr/bin/env python
"""Elastic-fleet fairness + autoscaling smoke (ISSUE 15) — ci.sh
stage 16.

A burst tenant and a steady tenant through a REAL autoscaled fleet,
end to end — the chaos-style acceptance of ROADMAP item 1:

1. **Latency isolation**: the burst tenant floods 24 tickets; the
   steady tenant trickles 8 tickets in while the burst is queued. The
   steady tenant's ``fleet.tenant.spool_wait_ms`` p99 must stay under
   its SLO while the burst tenant demonstrably queues (its own
   spool-wait p99 is worse) — asserted from the EXISTING per-tenant
   histograms, read back from the spool alone after the coordinator's
   final flush (the round-19 observability investment doing the
   acceptance work).
2. **Load-following autoscaler**: worker count must rise above the
   floor under the burst and drain back to ``min_workers`` within the
   cooldown window afterwards — with every result bit-identical to a
   standalone same-seed engine run (the fixed-fleet reference), since
   scale-down drains and never kills.
3. **Admission control**: one submission past the burst tenant's
   ``TenantPolicy.max_pending`` quota sheds deterministically
   (``QuotaExceeded`` + one schema-valid ``quota_reject`` event) and
   leaves the fleet state intact.
4. Every new event kind this round introduced (``sched_round``,
   ``autoscale_up``, ``autoscale_down``, ``quota_reject``) appears in
   the run's event log and the whole log schema-validates.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: The steady tenant's spool-wait SLO for this smoke, generous to this
#: 1-core host's drift but far below what riding behind a 24-ticket
#: burst in FIFO order would cost (the whole burst takes multiple
#: seconds of service time here).
STEADY_SLO_MS = 2000.0


def main() -> int:
    import numpy as np

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.config import (
        AutoscaleConfig,
        FleetConfig,
        TenantPolicy,
    )
    from libpga_tpu.serving.fleet import Fleet, FleetTicket, fleet_status
    from libpga_tpu.serving.scheduler import QuotaExceeded
    from libpga_tpu.utils import metrics as M
    from libpga_tpu.utils import telemetry as T

    tmp = tempfile.mkdtemp(prefix="pga-fairness-smoke-")
    spool = os.path.join(tmp, "spool")
    events_path = os.path.join(tmp, "events.jsonl")
    log = T.EventLog(events_path)
    cfg = PGAConfig(use_pallas=False)
    registry = M.MetricsRegistry()
    POP, LEN, GENS = 128, 16, 4
    BURST_N, STEADY_N = 24, 8

    fleet = Fleet(
        spool, "onemax", config=cfg,
        fleet=FleetConfig(
            n_workers=1, max_batch=2, max_wait_ms=5, poll_s=0.02,
            lease_timeout_s=60.0, heartbeat_s=0.5, sched_lookahead=1,
            tenants={
                "steady": TenantPolicy(weight=2.0),
                "burst": TenantPolicy(
                    weight=1.0, max_pending=BURST_N
                ),
            },
            autoscale=AutoscaleConfig(
                min_workers=1, max_workers=2, target_backlog=1.0,
                up_cooldown_s=0.3, down_cooldown_s=0.5,
                idle_grace_s=0.8, check_s=0.1,
            ),
        ),
        events=log, registry=registry,
    )
    fleet.start()

    # Warm-up: compile the floor worker's mega-run programs at BOTH
    # batch widths this smoke produces (a width-1 steady batch and a
    # width-2 co-batch lower distinct programs), then reset the
    # registry so the timed histograms read steady-state service, not
    # worker boot + first AOT build. The autoscaled workers still come
    # up cold — that cost is execute-side and exactly what a real
    # scale-up pays.
    fleet.submit(FleetTicket(
        size=POP, genome_len=LEN, n=GENS, seed=50_000, tenant="steady",
    )).result(timeout=600)
    warm = [
        fleet.submit(FleetTicket(
            size=POP, genome_len=LEN, n=GENS, seed=50_001 + i,
            tenant=t,
        ))
        for i, t in enumerate(("steady", "burst"))
    ]
    for h in warm:
        h.result(timeout=600)
    registry.reset()

    # Phase 1 — the burst floods, then the steady tenant trickles in
    # WHILE the burst is queued; worker count is sampled throughout.
    burst_handles = [
        fleet.submit(FleetTicket(
            size=POP, genome_len=LEN, n=GENS, seed=60_000 + i,
            tenant="burst",
        ))
        for i in range(BURST_N)
    ]
    # Admission control: the burst tenant is now AT its quota — the
    # next submission sheds deterministically.
    try:
        fleet.submit(FleetTicket(
            size=POP, genome_len=LEN, n=GENS, seed=61_000,
            tenant="burst",
        ))
        sys.exit("quota breach did not shed")
    except QuotaExceeded:
        pass
    peak_workers = len(fleet.workers_alive())
    steady_results = []
    steady_seeds = []
    for i in range(STEADY_N):
        seed = 70_000 + i
        steady_seeds.append(seed)
        h = fleet.submit(FleetTicket(
            size=POP, genome_len=LEN, n=GENS, seed=seed,
            tenant="steady",
        ))
        time.sleep(0.2)
        # Await each steady ticket PROMPTLY (a real latency-sensitive
        # client would): its readback span must measure the fleet, not
        # this driver's patience.
        steady_results.append(h.result(timeout=600))
        peak_workers = max(peak_workers, len(fleet.workers_alive()))
    pending = list(burst_handles)
    while pending:
        pending = [h for h in pending if not h.poll()]
        peak_workers = max(peak_workers, len(fleet.workers_alive()))
        time.sleep(0.05)
    for h in burst_handles:
        h.result(timeout=600)

    # Bit-identity spot check: the elastic fleet changes WHO runs a
    # ticket and WHEN, never its bits.
    ref = PGA(seed=steady_seeds[0], config=cfg)
    ref.create_population(POP, LEN)
    ref.set_objective("onemax")
    ref.run(GENS)
    if not np.array_equal(
        steady_results[0].genomes, np.array(ref._populations[0].genomes)
    ):
        sys.exit("steady result diverged from the same-seed engine run")

    if peak_workers < 2:
        sys.exit(
            f"autoscaler never scaled up under the burst "
            f"(peak {peak_workers})"
        )
    # Scale-down: back to the floor within the cooldown window.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if len(fleet.workers_alive()) == 1:
            break
        time.sleep(0.1)
    else:
        sys.exit(
            f"autoscaler did not drain back to the floor "
            f"(alive {fleet.workers_alive()})"
        )
    if fleet.worker_deaths != 0:
        sys.exit(
            f"scale-down killed instead of draining "
            f"({fleet.worker_deaths} deaths)"
        )

    # Phase 2 — the isolation verdict, FROM THE SPOOL ALONE: flush the
    # coordinator's registry, then reconstruct per-tenant spool-wait
    # percentiles with fleet_status on the directory.
    fleet.flush_metrics()
    st = fleet_status(spool)
    steady = st["tenants"].get("steady", {})
    burst = st["tenants"].get("burst", {})
    s_wait = (steady.get("spool_wait") or {}).get("p99_ms")
    s_e2e = (steady.get("e2e") or {}).get("p50_ms")
    b_e2e = (burst.get("e2e") or {}).get("p50_ms")
    if s_wait is None or s_e2e is None or b_e2e is None:
        sys.exit(f"spool lost the per-tenant histograms: "
                 f"steady={steady} burst={burst}")
    if s_wait > STEADY_SLO_MS:
        sys.exit(
            f"FAIRNESS VIOLATION: steady spool_wait p99 {s_wait:.0f} ms "
            f"> SLO {STEADY_SLO_MS:.0f} ms under a concurrent burst"
        )
    # The burst tenant demonstrably QUEUED: with the fair scheduler
    # holding its backlog in the coordinator (the intake span), its
    # median end-to-end is far above the steady tenant's.
    if not b_e2e > 2.0 * s_e2e:
        sys.exit(
            f"burst tenant did not queue (burst e2e p50 {b_e2e:.0f} ms "
            f"vs steady {s_e2e:.0f} ms) — the smoke lost its load"
        )
    fleet.close()
    log.close()

    # Phase 3 — event-log schema: the round's new kinds all fired.
    records = T.validate_log(events_path)
    kinds = {r["event"] for r in records}
    for kind in ("sched_round", "autoscale_up", "autoscale_down",
                 "quota_reject", "tenant_admit"):
        if kind not in kinds:
            sys.exit(f"event log missing {kind} (got {sorted(kinds)})")
    rejects = [r for r in records if r["event"] == "quota_reject"]
    if len(rejects) != 1 or rejects[0]["tenant"] != "burst":
        sys.exit(f"unexpected quota_reject records: {rejects}")

    print(
        f"fairness smoke OK: steady spool_wait p99 {s_wait:.0f} ms "
        f"(SLO {STEADY_SLO_MS:.0f}), e2e p50 steady {s_e2e:.0f} ms vs "
        f"burst {b_e2e:.0f} ms under a {BURST_N}-ticket burst; workers "
        f"1 -> {peak_workers} -> 1; quota shed deterministic; results "
        f"bit-identical"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
