#!/usr/bin/env bash
# CI entry point: tier-1 tests + the observability gates.
#
#   tools/ci.sh          # CPU: tier-1, trace-span smoke, event-log schema
#
# Three stages, all CPU-runnable (no chip needed):
#   1. tools/run_tier1.sh       — the exact ROADMAP.md tier-1 command;
#   2. tools/trace_smoke.py     — capture a profiler trace, assert every
#                                 pga/<stage> span exists;
#   3. event-log schema check   — run a short telemetry-enabled solve
#                                 emitting a JSONL event log, then
#                                 validate every record against
#                                 utils/telemetry's versioned schema;
#   4. bench provenance gate    — bench.provenance() carries the
#                                 versioned schema fields and the
#                                 newest BENCH_r*.json artifact is
#                                 stamped with them (schema_version,
#                                 backend, device_kind,
#                                 process_state_note — ISSUE 3).
# Exits nonzero on the first failing stage.
set -e
cd "$(dirname "$0")/.."

echo "== ci: tier-1 =="
bash tools/run_tier1.sh

echo "== ci: trace-span smoke =="
JAX_PLATFORMS=cpu python tools/trace_smoke.py

echo "== ci: event-log schema =="
JAX_PLATFORMS=cpu python - <<'PY'
import sys
import tempfile

from libpga_tpu import PGA, PGAConfig, TelemetryConfig
from libpga_tpu.utils import telemetry

path = tempfile.mktemp(suffix=".jsonl", prefix="pga-ci-events-")
pga = PGA(
    seed=0,
    config=PGAConfig(
        telemetry=TelemetryConfig(
            history_gens=32, events_path=path, stall_alert_gens=1000
        )
    ),
)
pga.create_population(256, 16)
pga.create_population(256, 16)
pga.set_objective("onemax")
pga.run(5)
pga.migrate(0.1)
pga.run_islands(4, 2, 0.1)

records = telemetry.validate_log(path)
kinds = {r["event"] for r in records}
need = {"compile", "run_start", "run_record", "run_end", "migration",
        "islands_start", "islands_end"}
missing = need - kinds
if missing:
    sys.exit(f"event log missing kinds: {sorted(missing)} (got {sorted(kinds)})")
print(f"event-log schema OK: {len(records)} records, kinds {sorted(kinds)}")
PY

echo "== ci: bench provenance schema =="
JAX_PLATFORMS=cpu python - <<'PY'
import glob
import json
import re
import sys

import bench

need = {"schema_version", "backend", "device_kind", "process_state_note"}
prov = bench.provenance()
missing = need - set(prov)
if missing:
    sys.exit(f"bench.provenance() missing keys: {sorted(missing)}")

arts = glob.glob("BENCH_r*.json")
latest = max(arts, key=lambda f: int(re.search(r"r(\d+)", f).group(1)))
with open(latest) as f:
    art = json.load(f)
missing = need - set(art)
if missing:
    sys.exit(
        f"{latest} missing provenance keys: {sorted(missing)} — every "
        "artifact from schema_version 1 on must be stamped (ISSUE 3)"
    )
if art["schema_version"] != bench.SCHEMA_VERSION:
    sys.exit(
        f"{latest} schema_version {art['schema_version']} != "
        f"bench.SCHEMA_VERSION {bench.SCHEMA_VERSION}"
    )
print(f"bench provenance OK: {latest} schema_version={art['schema_version']}")
PY
echo "== ci: all stages passed =="
