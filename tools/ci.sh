#!/usr/bin/env bash
# CI entry point: tier-1 tests + the observability gates.
#
#   tools/ci.sh          # CPU: tier-1, trace-span smoke, event-log schema
#
# Three stages, all CPU-runnable (no chip needed):
#   1. tools/run_tier1.sh       — the exact ROADMAP.md tier-1 command;
#   2. tools/trace_smoke.py     — capture a profiler trace, assert every
#                                 pga/<stage> span exists;
#   3. event-log schema check   — run a short telemetry-enabled solve
#                                 emitting a JSONL event log, then
#                                 validate every record against
#                                 utils/telemetry's versioned schema.
# Exits nonzero on the first failing stage.
set -e
cd "$(dirname "$0")/.."

echo "== ci: tier-1 =="
bash tools/run_tier1.sh

echo "== ci: trace-span smoke =="
JAX_PLATFORMS=cpu python tools/trace_smoke.py

echo "== ci: event-log schema =="
JAX_PLATFORMS=cpu python - <<'PY'
import sys
import tempfile

from libpga_tpu import PGA, PGAConfig, TelemetryConfig
from libpga_tpu.utils import telemetry

path = tempfile.mktemp(suffix=".jsonl", prefix="pga-ci-events-")
pga = PGA(
    seed=0,
    config=PGAConfig(
        telemetry=TelemetryConfig(
            history_gens=32, events_path=path, stall_alert_gens=1000
        )
    ),
)
pga.create_population(256, 16)
pga.create_population(256, 16)
pga.set_objective("onemax")
pga.run(5)
pga.migrate(0.1)
pga.run_islands(4, 2, 0.1)

records = telemetry.validate_log(path)
kinds = {r["event"] for r in records}
need = {"compile", "run_start", "run_record", "run_end", "migration",
        "islands_start", "islands_end"}
missing = need - kinds
if missing:
    sys.exit(f"event log missing kinds: {sorted(missing)} (got {sorted(kinds)})")
print(f"event-log schema OK: {len(records)} records, kinds {sorted(kinds)}")
PY
echo "== ci: all stages passed =="
