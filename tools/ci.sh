#!/usr/bin/env bash
# CI entry point: tier-1 tests + the observability gates.
#
#   tools/ci.sh          # CPU: tier-1, trace-span smoke, event-log schema
#
# Three stages, all CPU-runnable (no chip needed):
#   1. tools/run_tier1.sh       — the exact ROADMAP.md tier-1 command;
#   2. tools/trace_smoke.py     — capture a profiler trace, assert every
#                                 pga/<stage> span exists;
#   3. event-log schema check   — run a short telemetry-enabled solve
#                                 emitting a JSONL event log, then
#                                 validate every record against
#                                 utils/telemetry's versioned schema;
#   4. bench provenance gate    — bench.provenance() carries the
#                                 versioned schema fields and the
#                                 newest BENCH_r*.json artifact is
#                                 stamped with them (schema_version,
#                                 backend, device_kind,
#                                 process_state_note — ISSUE 3);
#   5. serving smoke            — 8 mixed-config runs through the async
#                                 submission queue: asserts exactly one
#                                 compile per shape bucket (cache
#                                 counters), bit-parity with pga.run,
#                                 and schema-valid batch_admit /
#                                 batch_launch telemetry (ISSUE 4);
#   6. chaos smoke              — tools/chaos_smoke.py: the ISSUE 5
#                                 fault matrix (injected compile fault,
#                                 objective raise, NaN storm,
#                                 kill-mid-checkpoint, dead flusher,
#                                 poisoned serving request) — every
#                                 fault recovers automatically and the
#                                 recovered run's final best is
#                                 bit-identical to the fault-free
#                                 same-seed run;
#   7. serving observability    — serving bench under --slo, the new
#                                 ISSUE 6 event kinds (ticket_done /
#                                 slo_violation / metrics_snapshot /
#                                 flight_dump) validated against
#                                 EVENT_FIELDS, a forced dead letter's
#                                 flight-recorder dump schema-checked,
#                                 and the Prometheus exposition linted
#                                 (tools/metrics_dump.py --check);
#   8. population-shard smoke   — tools/shard_smoke.py on a 4-device
#                                 CPU platform: a rank-selection config
#                                 at pop_shards=4 reaches the
#                                 bit-identical final best as the
#                                 same-seed pop_shards=1 run, the
#                                 while body carries exactly one
#                                 ppermute + one all_gather per
#                                 generation, and the shard_sync
#                                 telemetry event is schema-valid
#                                 (ISSUE 7);
#   9. serving-fleet smoke      — tools/fleet_smoke.py: the ISSUE 8
#                                 acceptance matrix on 8 real worker
#                                 processes — kill -9 of a worker
#                                 mid-batch and a SIGTERM drain/resume
#                                 cycle both finish bit-identical to
#                                 uninterrupted same-seed
#                                 single-process runs, a batch that
#                                 kills K distinct workers is
#                                 quarantined with a schema-valid
#                                 flight dump, and the per-worker
#                                 Prometheus expositions pass
#                                 tools/metrics_dump.py --check.
#  11. autotune smoke            — tools/autotune_smoke.py (ISSUE 10):
#                                 tools/autotune.py on a tiny CPU
#                                 space deterministically produces a
#                                 schema-valid tuning DB, the recorded
#                                 config never regresses the default,
#                                 a warm serving run compiles exactly
#                                 the DB-resolved config (cache
#                                 provenance + tuned_config event),
#                                 and db=None leaves the traced run
#                                 program byte-identical.
#  13. streaming smoke           — tools/streaming_smoke.py (ISSUE 12):
#                                 a step()-only EvolutionSession is
#                                 bit-identical to same-seed PGA.run
#                                 (population + telemetry history),
#                                 suspend/resume at a generation
#                                 boundary is bit-identical, the warm
#                                 engine pool's hit path compiles 0
#                                 programs (with a measured cold/warm
#                                 first-ask A/B), an ask/tell-only
#                                 external-fitness loop recovers a
#                                 hidden target, and the
#                                 session_open/session_fold/
#                                 session_suspend/session_resume event
#                                 kinds are schema-valid.
#  14. static analysis           — tools/lint_pga.py --all (ISSUE 13):
#                                 the invariant guard — repo-specific
#                                 AST lints (spool-atomic-write,
#                                 event-kind-registered,
#                                 no-wallclock-in-traced,
#                                 lock-guarded-registry; scoped
#                                 suppressions checked for staleness),
#                                 the IR contract audit on the live
#                                 engine's CPU lowerings (fallback and
#                                 telemetry purity via the canonical
#                                 StableHLO fingerprint, buffer
#                                 donation actually aliased, run loops
#                                 callback-free, pop_shards=4 carries
#                                 exactly 1 ppermute + 1 all_gather
#                                 per generation), and the 3-way C-ABI
#                                 cross-check (pga_tpu.h prototypes ↔
#                                 pga_tpu.cc marshal formats ↔
#                                 capi_bridge.py signatures ↔
#                                 test_serving.c symbol coverage,
#                                 retry-once snapshot shapes). Exits
#                                 nonzero with file:line diagnostics.
#  15. tenant smoke              — tools/tenant_smoke.py (ISSUE 14):
#                                 tenant-attributed observability —
#                                 two tenants through a real 4-worker
#                                 fleet: the per-tenant expositions
#                                 lint clean, an injected-slow tenant
#                                 trips its multi-window burn-rate
#                                 alert while the steady tenant stays
#                                 green, per-tenant p99/queue-depth/
#                                 burn are reconstructible from the
#                                 spool alone (fleet_top --tenants,
#                                 dead fleet), streaming session
#                                 lifecycle spans tile >=95% across a
#                                 suspend/resume re-hosting, and two
#                                 tenants of one shape share ONE
#                                 compiled program (attribution is
#                                 host-side only).
#  12. gp smoke                  — tools/gp_smoke.py (ISSUE 11):
#                                 random-grown postfix programs are
#                                 strictly well-formed and the GP
#                                 operators preserve that; the fused
#                                 Pallas stack-machine evaluator
#                                 (interpret mode) agrees with the XLA
#                                 interpreter at two plans; a
#                                 seed-pinned symbolic-regression run
#                                 recovers a known expression to exact
#                                 zero RMSE bit-identically across two
#                                 runs; the gp_run event kind is
#                                 schema-valid.
#  17. perf gate                 — tools/perf_gate.py (ISSUE 17): the
#                                 continuous-bench regression gate.
#                                 --selftest proves the trip wire
#                                 through the REAL estimator: a clean
#                                 baseline is acquitted while an
#                                 injected work-proportional slowdown
#                                 (FaultPlan site bench.measure,
#                                 kind="slow") is convicted, emitting
#                                 a schema-valid perf_regression event
#                                 plus a flight dump; then the clean
#                                 gate measures the fixed workload
#                                 against the committed
#                                 PERF_HISTORY.json baseline at the
#                                 cross-process drift floor and lints
#                                 the perf.* Prometheus series via
#                                 tools/metrics_dump.py --check. Also
#                                 ingests every BENCH_r*.json into a
#                                 scratch history DB (all artifact
#                                 generations must keep parsing).
# Exits nonzero on the first failing stage.
set -e
cd "$(dirname "$0")/.."

# Persistent XLA compilation cache (ISSUE 4 satellite) — TPU sessions
# ONLY. On this jaxlib (0.4.37) CPU backend, executing a
# persistent-cache-DESERIALIZED executable with donated buffers
# corrupts the runtime heap: donation-heavy checkpoint/restore loops
# (the ISSUE 5 supervisor/chaos workloads) segfault or silently
# corrupt results in a majority of runs with the cache on, and are
# rock-solid with it off — while CPU compiles are cheap enough that
# the cache buys nothing here. TPU sessions (tens-of-seconds Mosaic
# compiles, the cache's actual motivation) keep it.
if python -c 'import jax, sys; sys.exit(0 if jax.default_backend() == "tpu" else 1)' 2>/dev/null; then
    export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$HOME/.cache/libpga_tpu_xla}"
    export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-1}"
    mkdir -p "$JAX_COMPILATION_CACHE_DIR"
else
    # An inherited cache dir would re-expose the CPU hazard above.
    unset JAX_COMPILATION_CACHE_DIR
fi

echo "== ci: tier-1 =="
bash tools/run_tier1.sh

echo "== ci: trace-span smoke =="
JAX_PLATFORMS=cpu python tools/trace_smoke.py

echo "== ci: event-log schema =="
JAX_PLATFORMS=cpu python - <<'PY'
import sys
import tempfile

from libpga_tpu import PGA, PGAConfig, TelemetryConfig
from libpga_tpu.utils import telemetry

path = tempfile.mktemp(suffix=".jsonl", prefix="pga-ci-events-")
pga = PGA(
    seed=0,
    config=PGAConfig(
        telemetry=TelemetryConfig(
            history_gens=32, events_path=path, stall_alert_gens=1000
        )
    ),
)
pga.create_population(256, 16)
pga.create_population(256, 16)
pga.set_objective("onemax")
pga.run(5)
pga.migrate(0.1)
pga.run_islands(4, 2, 0.1)

records = telemetry.validate_log(path)
kinds = {r["event"] for r in records}
need = {"compile", "run_start", "run_record", "run_end", "migration",
        "islands_start", "islands_end"}
missing = need - kinds
if missing:
    sys.exit(f"event log missing kinds: {sorted(missing)} (got {sorted(kinds)})")
print(f"event-log schema OK: {len(records)} records, kinds {sorted(kinds)}")
PY

echo "== ci: bench provenance schema =="
JAX_PLATFORMS=cpu python - <<'PY'
import glob
import json
import re
import sys

import bench

need = {"schema_version", "backend", "device_kind", "process_state_note"}
prov = bench.provenance()
missing = need - set(prov)
if missing:
    sys.exit(f"bench.provenance() missing keys: {sorted(missing)}")

arts = glob.glob("BENCH_r*.json")
latest = max(arts, key=lambda f: int(re.search(r"r(\d+)", f).group(1)))
with open(latest) as f:
    art = json.load(f)
missing = need - set(art)
if missing:
    sys.exit(
        f"{latest} missing provenance keys: {sorted(missing)} — every "
        "artifact from schema_version 1 on must be stamped (ISSUE 3)"
    )
# Range, not equality: the newest committed artifact may predate the
# current bench schema (ISSUE 17 bumped it to 2 for git_rev/run_id) —
# old artifacts must keep parsing; only a FUTURE schema is an error.
if not (1 <= art["schema_version"] <= bench.SCHEMA_VERSION):
    sys.exit(
        f"{latest} schema_version {art['schema_version']} outside "
        f"1..bench.SCHEMA_VERSION={bench.SCHEMA_VERSION}"
    )
print(f"bench provenance OK: {latest} schema_version={art['schema_version']}")
PY

echo "== ci: serving smoke =="
JAX_PLATFORMS=cpu python - <<'PY'
import sys
import tempfile

import numpy as np

from libpga_tpu import PGA, PGAConfig, ServingConfig
from libpga_tpu.serving import COUNTERS, BatchedRuns, RunQueue, RunRequest
from libpga_tpu.utils import telemetry

path = tempfile.mktemp(suffix=".jsonl", prefix="pga-ci-serving-")
log = telemetry.EventLog(path)
cfg = PGAConfig(use_pallas=False)
small = BatchedRuns("onemax", config=cfg, events=log)
wide = BatchedRuns("sphere", config=cfg, events=log)
q = RunQueue(
    small, serving=ServingConfig(max_batch=4, max_wait_ms=0), events=log
)

before = COUNTERS.snapshot()
# 8 mixed-config runs: two shape buckets x two objectives-with-shapes,
# distinct seeds/rates/targets inside each bucket.
tickets = []
for i in range(4):
    tickets.append(q.submit(RunRequest(
        size=256, genome_len=16, n=4, seed=i, mutation_rate=0.01 * (i + 1),
    )))
for i in range(4):
    tickets.append(q.submit(RunRequest(
        size=512, genome_len=8, n=4, seed=i,
    ), executor=wide))
q.drain()
results = [t.result(timeout=120) for t in tickets]
q.close()
log.close()

after = COUNTERS.snapshot()
builds = after.get("builds", 0) - before.get("builds", 0)
if builds != 2:
    sys.exit(f"expected exactly 1 compile per bucket (2 total), got {builds}")

# Bit-parity of one batched run against the engine path.
pga = PGA(seed=2, config=cfg)
h = pga.create_population(256, 16)
pga.set_objective("onemax")
from libpga_tpu.ops.mutate import make_point_mutate
pga.set_mutate(make_point_mutate(0.03))
pga.run(4)
if not np.array_equal(
    np.asarray(results[2].genomes), np.asarray(pga.population(h).genomes)
):
    sys.exit("batched run diverged from sequential PGA.run")

records = telemetry.validate_log(path)
kinds = [r["event"] for r in records]
if kinds.count("batch_admit") != 8:
    sys.exit(f"expected 8 batch_admit events, got {kinds.count('batch_admit')}")
if kinds.count("batch_launch") != 2:
    sys.exit(f"expected 2 batch_launch events, got {kinds.count('batch_launch')}")
buckets = {r["bucket"] for r in records if r["event"] == "batch_launch"}
if len(buckets) != 2:
    sys.exit(f"expected 2 distinct buckets, got {buckets}")
print(
    f"serving smoke OK: 8 runs, 2 buckets, {builds} compiles, "
    f"{len(records)} schema-valid events"
)
PY

echo "== ci: chaos smoke =="
JAX_PLATFORMS=cpu python tools/chaos_smoke.py

echo "== ci: serving observability =="
# ISSUE 6, three gates: (a) the serving bench runs under --slo with
# generous objectives (the gate's machinery, not this host's speed, is
# under test); (b) the new event kinds (ticket_done, slo_violation,
# metrics_snapshot, flight_dump) validate against EVENT_FIELDS and a
# forced dead letter produces a schema-valid flight-recorder dump; (c)
# the Prometheus exposition of the live registry passes the
# line-format lint.
JAX_PLATFORMS=cpu python tools/serving_throughput.py --pop 512 --len 32 \
    --gens 4 --batch 8 --rounds 2 --seq-count 1 --slo \
    --slo-p99-ms 120000 --slo-queue-wait-ms 120000 > /dev/null
JAX_PLATFORMS=cpu python - <<'PY'
import sys
import tempfile

import numpy as np

from libpga_tpu import PGAConfig, ServingConfig, SLOConfig
from libpga_tpu.serving import BatchedRuns, RunQueue, RunRequest
from libpga_tpu.utils import telemetry

path = tempfile.mktemp(suffix=".jsonl", prefix="pga-ci-obs-")
log = telemetry.EventLog(path)
ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False), events=log)
q = RunQueue(
    ex, serving=ServingConfig(max_batch=3, max_wait_ms=0), events=log,
    slo=SLOConfig(p99_latency_ms=0.001, max_queue_wait_ms=0.0,
                  min_samples=1),
)
tickets = [
    q.submit(RunRequest(size=256, genome_len=16, n=3, seed=i))
    for i in range(2)
]
poisoned = q.submit(RunRequest(
    size=256, genome_len=16, n=3, seed=9,
    genomes=np.zeros((4, 4), np.float32),
))
q.drain()
for t in tickets:
    t.result(timeout=300)
    tm = t.timing
    if not (tm.submitted <= tm.admitted <= tm.launched
            <= tm.completed <= tm.readback):
        sys.exit(f"non-monotonic ticket lifecycle: {t.latency()}")
try:
    poisoned.result(timeout=300)
    sys.exit("poisoned request did not dead-letter")
except ValueError:
    pass
q.check_slo()
q.close()
log.close()

records = telemetry.validate_log(path)
kinds = {r["event"] for r in records}
need = {"ticket_done", "slo_violation", "dead_letter"}
missing = need - kinds
if missing:
    sys.exit(f"event log missing kinds: {sorted(missing)}")
if not telemetry.FLIGHT.dumps:
    sys.exit("dead letter produced no flight-recorder dump")
dump = telemetry.validate_log(telemetry.FLIGHT.dumps[-1])
dump_kinds = [r["event"] for r in dump]
if "metrics_snapshot" not in dump_kinds or dump_kinds[-1] != "flight_dump":
    sys.exit(f"flight dump malformed: kinds {dump_kinds}")
print(
    f"serving observability OK: {len(records)} events "
    f"({sorted(kinds)}), flight dump {len(dump)} records"
)
PY
JAX_PLATFORMS=cpu python tools/metrics_dump.py --demo --check > /dev/null
echo "prometheus exposition lint OK"

echo "== ci: population-shard smoke =="
JAX_PLATFORMS=cpu python tools/shard_smoke.py

echo "== ci: serving-fleet smoke =="
JAX_PLATFORMS=cpu python tools/fleet_smoke.py

echo "== ci: fleet observability =="
# ISSUE 9, four gates: (a) a fleet run WITH TRACING ON yields a
# cross-process span breakdown on every ticket whose spans tile >=95%
# of e2e; (b) the merged fleet Prometheus exposition (workers +
# coordinator, per-proc labels) passes tools/metrics_dump.py --check;
# (c) the new event kinds (trace_span, fleet_ticket_done,
# straggler_alert) validate against EVENT_FIELDS; (d) tools/fleet_top.py
# renders the DEAD fleet's spool (post-mortem mode) without error.
JAX_PLATFORMS=cpu python - <<'PY'
import json
import os
import subprocess
import sys
import tempfile

from libpga_tpu import PGAConfig
from libpga_tpu.config import FleetConfig
from libpga_tpu.serving.fleet import Fleet, FleetTicket
from libpga_tpu.utils import metrics as M
from libpga_tpu.utils import telemetry as T

tmp = tempfile.mkdtemp(prefix="pga-ci-fleetobs-")
events_path = os.path.join(tmp, "events.jsonl")
log = T.EventLog(events_path)
fleet = Fleet(
    os.path.join(tmp, "spool"), "onemax",
    config=PGAConfig(use_pallas=False),
    fleet=FleetConfig(n_workers=2, max_batch=2, max_wait_ms=5,
                      lease_timeout_s=10.0, heartbeat_s=0.3,
                      poll_s=0.05, metrics_flush_s=0.3),
    events=log,
)
fleet.start()
handles = [
    fleet.submit(FleetTicket(size=256, genome_len=16, n=4, seed=s))
    for s in range(4)
]
for h in handles:
    h.result(timeout=300)
    lat = h.latency()
    spans = [lat[f"{k}_ms"] for k in
             ("intake", "spool_wait", "execute", "publish", "readback")]
    if any(v is None for v in spans):
        sys.exit(f"tracing-on ticket missing spans: {lat}")
    if sum(spans) < 0.95 * lat["e2e_ms"]:
        sys.exit(f"spans cover <95% of e2e: {lat}")
    for rec in h.trace():
        T.validate_event(rec)

merged = fleet.merged_snapshot()
prom_path = os.path.join(tmp, "merged.prom")
with open(prom_path, "w") as fh:
    fh.write(M.prometheus_text(merged))
text = open(prom_path).read()
if 'proc="coordinator"' not in text or 'proc="w0"' not in text:
    sys.exit("merged exposition lacks per-process labels")
fleet.status()  # live-console feed must assemble
fleet.close()
log.close()

records = T.validate_log(events_path)
kinds = {r["event"] for r in records}
if "fleet_ticket_done" not in kinds:
    sys.exit(f"event log missing fleet_ticket_done (got {sorted(kinds)})")
# straggler_alert is hard to provoke on a healthy 2-worker fleet; gate
# its schema contract directly (the detection path is unit-tested).
T.validate_event({
    "schema": T.EVENT_SCHEMA_VERSION, "ts": 0.0,
    "event": "straggler_alert", "worker": "w1", "p95_ms": 100.0,
    "fleet_p95_ms": 10.0,
})

env = {**os.environ, "JAX_PLATFORMS": "cpu"}
for cmd in (
    [sys.executable, "tools/metrics_dump.py", "--check", prom_path],
    [sys.executable, "tools/fleet_top.py",
     "--spool", os.path.join(tmp, "spool")],
):
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env)
    if proc.returncode != 0:
        sys.exit(f"{cmd} failed:\n{proc.stdout}\n{proc.stderr}")
print(
    f"fleet observability OK: {len(handles)} traced tickets tile e2e, "
    f"merged exposition linted ({len(merged['merged_from'])} procs), "
    "dead-fleet fleet_top rendered"
)
PY

echo "== ci: autotune smoke =="
JAX_PLATFORMS=cpu python tools/autotune_smoke.py

echo "== ci: gp smoke =="
JAX_PLATFORMS=cpu python tools/gp_smoke.py

echo "== ci: streaming smoke =="
JAX_PLATFORMS=cpu python tools/streaming_smoke.py

echo "== ci: static analysis =="
JAX_PLATFORMS=cpu python tools/lint_pga.py --all

echo "== ci: tenant smoke =="
JAX_PLATFORMS=cpu python tools/tenant_smoke.py

echo "== ci: fairness smoke =="
JAX_PLATFORMS=cpu python tools/fairness_smoke.py

echo "== ci: perf gate =="
JAX_PLATFORMS=cpu python tools/perf_gate.py --selftest
JAX_PLATFORMS=cpu python tools/perf_gate.py
JAX_PLATFORMS=cpu python tools/perf_report.py --backfill --db "$(mktemp -d)/scratch_history.json"

echo "== ci: ring smoke =="
JAX_PLATFORMS=cpu python tools/ring_smoke.py

echo "== ci: ha smoke =="
JAX_PLATFORMS=cpu python tools/ha_smoke.py

echo "== ci: all stages passed =="
