#!/usr/bin/env python
"""Metrics exporter / linter CLI (ISSUE 6).

Three modes:

  --demo            run a small serving workload, then export the live
                    process registry (default mode when no snapshot is
                    given): ``--format prom`` (default) writes the
                    Prometheus text exposition, ``--format json`` the
                    JSON snapshot.
  --snapshot F      re-render a previously saved JSON snapshot (from
                    ``--format json``, ``pga_metrics_snapshot``, or a
                    flight-recorder ``metrics_snapshot`` record) as
                    Prometheus text — the offline-collector path.
  --check [F]       line-format lint a Prometheus exposition (from a
                    file or stdin with ``-``; with no argument, lints
                    what the current mode would have printed). Exits
                    nonzero listing the problems — the ``tools/ci.sh``
                    gate that keeps ``to_prometheus`` scrape-able.

Examples:

    JAX_PLATFORMS=cpu python tools/metrics_dump.py --demo
    python tools/metrics_dump.py --demo --format json > snap.json
    python tools/metrics_dump.py --snapshot snap.json
    python tools/metrics_dump.py --demo | python tools/metrics_dump.py --check -
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_demo() -> None:
    """A tiny serving workload so the registry has every series kind:
    ticket latency histograms, occupancy, cache gauges, counters."""
    from libpga_tpu import PGAConfig, ServingConfig
    from libpga_tpu.serving import BatchedRuns, RunQueue, RunRequest

    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))
    with RunQueue(
        ex, serving=ServingConfig(max_batch=4, max_wait_ms=0)
    ) as q:
        tickets = [
            q.submit(
                RunRequest(size=256, genome_len=16, n=3, seed=i)
            )
            for i in range(6)
        ]
        q.drain()
        for t in tickets:
            t.result(timeout=300)


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--demo", action="store_true",
                    help="run a small serving workload first")
    ap.add_argument("--snapshot", metavar="F",
                    help="render a saved JSON snapshot instead of the "
                         "live registry")
    ap.add_argument("--format", choices=("prom", "json"), default="prom")
    ap.add_argument("--check", nargs="?", const="", metavar="F",
                    help="lint a Prometheus exposition (file, '-' for "
                         "stdin, or the current output when omitted)")
    args = ap.parse_args()

    from libpga_tpu.utils import metrics as M

    if args.check not in (None, ""):
        text = (
            sys.stdin.read() if args.check == "-"
            else Path(args.check).read_text()
        )
        errors = M.lint_prometheus(text)
        for e in errors:
            print(f"metrics_dump: {e}", file=sys.stderr)
        print(
            f"metrics_dump: {'FAIL' if errors else 'OK'} "
            f"({len(text.splitlines())} lines, {len(errors)} problems)"
        )
        return 1 if errors else 0

    if args.snapshot:
        snap = json.loads(Path(args.snapshot).read_text())
    else:
        if args.demo:
            run_demo()
        snap = M.REGISTRY.snapshot()

    if args.format == "json":
        out = json.dumps(snap, indent=2, sort_keys=True)
    else:
        out = M.prometheus_text(snap)

    if args.check is not None:  # bare --check: lint our own output
        errors = M.lint_prometheus(
            out if args.format == "prom" else M.prometheus_text(snap)
        )
        for e in errors:
            print(f"metrics_dump: {e}", file=sys.stderr)
        if errors:
            return 1

    print(out, end="" if out.endswith("\n") else "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
