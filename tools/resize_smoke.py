"""Job-resize distributed smoke: save at N processes, restore at M.

Preemptible pod jobs come back at whatever size the scheduler grants, so
checkpoint/restore must work ACROSS process counts — the scenario the
per-process shard format (``utils/checkpoint.py``) exists for. Four
stages over a shared checkpoint path, each a separate fleet of workers
on a CPU-simulated multi-host mesh (8 global devices throughout):

1. **4 processes × 2 devices**: island GA through the PGA engine,
   collective shard save (4 ``.proc<k>.npz`` files).
2. **2 processes × 4 devices**: restore the 4-process checkpoint
   (resize DOWN — merge more shard files than running processes),
   verify the global best survived exactly, continue evolving on the
   2-process mesh, save again (2 shard files, at the SAME path — stage
   1's proc2/proc3 files remain on disk, exercising restore's
   declared-file-set rule).
3. **8 processes × 1 device**: restore the 2-process checkpoint
   (resize UP to a FULL fleet — one process per device, more processes
   than shard files), verify, evolve, save (8 shard files).
4. **4 processes × 2 devices**: restore the 8-process checkpoint
   (resize DOWN again — 8 shard files into 4 processes), verify, and
   evolve again.

Stage 5 (**pop-shard leg**, ISSUE 7) resizes the OTHER sharding axis:
a single process with 8 devices runs a POPULATION-SHARDED solver
(``PGAConfig(pop_shards=4)``), checkpoints it — the sharded population
serializes as ONE logical array through the same save path — then
restores into a ``pop_shards=2`` solver and keeps evolving: shard
count, like process count, is a restore-time choice, not a property of
the checkpoint.

Run directly:  python tools/resize_smoke.py
Exit code 0 and "RESIZE SMOKE: PASS" = every stage agreed.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GLOBAL_DEVICES = 8
ISLANDS, SIZE, LENGTH = 8, 256, 16
STAGES = [  # (num_processes, restore_first)
    (4, False),
    (2, True),
    (8, True),
    (4, True),
]


def _free_port() -> int:
    """A port the OS says is free RIGHT NOW. Hard-coded ports collide
    with concurrent smokes or a lingering TIME_WAIT listener; binding 0
    per stage makes the coordinator address collision-free in practice
    (the race between probe-close and coordinator-bind is the standard
    accepted one)."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker(stage: int, process_id: int) -> None:
    num_procs, restoring = STAGES[stage]
    port = int(os.environ["PGA_RESIZE_PORT"])
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from libpga_tpu.utils.compat import force_cpu_device_count

    force_cpu_device_count(GLOBAL_DEVICES // num_procs)

    from libpga_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_procs,
        process_id=process_id,
    )

    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.parallel.mesh import default_mesh, global_max
    from libpga_tpu.utils import checkpoint

    ckpt_path = os.environ["PGA_RESIZE_CKPT"]
    best_file = os.environ["PGA_RESIZE_BEST"]
    mesh = default_mesh()

    pga = PGA(seed=5, config=PGAConfig(mutation_rate=0.05))
    if restoring:
        checkpoint.restore(pga, ckpt_path)
        assert pga.num_populations == ISLANDS, pga.num_populations
        restored_best = max(
            float(jnp.max(p.scores)) for p in pga.populations
        )
        with open(best_file) as f:
            expected = json.load(f)["best"]
        assert abs(restored_best - expected) < 1e-5, (
            f"stage {stage}: restored best {restored_best} != "
            f"saved {expected}"
        )
        print(
            f"[stage {stage} proc {process_id}] restored best "
            f"{restored_best:.3f} across {num_procs} processes",
            flush=True,
        )
    else:
        for _ in range(ISLANDS):
            pga.create_population(SIZE, LENGTH)
    pga.set_objective("onemax")

    gens = pga.run_islands(20 if not restoring else 10, 5, 0.1, mesh=mesh)
    assert gens == (20 if not restoring else 10), gens
    best = max(global_max(p.scores, mesh) for p in pga.populations)
    assert best > 12.0, f"stage {stage}: no convergence ({best})"

    checkpoint.save(pga, ckpt_path)  # collective shard save
    multihost_utils.sync_global_devices(f"resize-smoke-saved-{stage}")
    if process_id == 0:
        with open(best_file, "w") as f:
            json.dump({"best": best, "stage": stage}, f)
    print(
        f"[stage {stage} proc {process_id}] best {best:.3f} "
        f"(saved at {num_procs} processes)",
        flush=True,
    )


def pop_shard_leg() -> None:
    """save@pop_shards=4 → restore@pop_shards=2 (single process, 8
    devices): the population-axis analog of the process-resize stages."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from libpga_tpu.utils.compat import force_cpu_device_count

    force_cpu_device_count(GLOBAL_DEVICES)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.utils import checkpoint

    ckpt_path = os.environ["PGA_RESIZE_CKPT"].replace(
        ".npz", ".popshard.npz"
    )

    def solver(shards):
        pga = PGA(seed=5, config=PGAConfig(
            pop_shards=shards, use_pallas=False, selection="truncation",
            mutation_rate=0.05, elitism=1,
        ))
        pga.set_objective("onemax_bits")
        return pga

    pga = solver(4)
    h = pga.create_population(1024, 32)
    gens = pga.run(15)
    assert gens == 15, gens
    best = float(pga.get_best_with_score(h)[1])
    assert best > 20.0, f"no convergence at shards=4 ({best})"
    checkpoint.save(pga, ckpt_path)

    pga2 = solver(2)
    checkpoint.restore(pga2, ckpt_path)
    h2 = pga2._handles()[0]
    restored = float(pga2.get_best_with_score(h2)[1])
    assert restored == best, f"restore@2 lost the best: {restored} != {best}"
    pga2.run(10)
    after = float(pga2.get_best_with_score(h2)[1])
    assert after >= best, f"evolution at shards=2 regressed: {after} < {best}"
    print(
        f"[pop-shard leg] save@shards=4 best {best:.1f} -> "
        f"restore@shards=2 exact, evolved to {after:.1f}",
        flush=True,
    )


def _run_stage(stage: int, env) -> int:
    num_procs, _ = STAGES[stage]
    env = dict(env, PGA_RESIZE_PORT=str(_free_port()))
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--worker", str(stage), str(i),
            ],
            env=env,
        )
        for i in range(num_procs)
    ]
    rc = 0
    try:
        for p in procs:
            p.wait(timeout=300)
            rc |= p.returncode
    except subprocess.TimeoutExpired:
        rc = 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rc


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]), int(sys.argv[3]))
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "--pop-shard-leg":
        pop_shard_leg()
        return 0

    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("PALLAS_AXON") and not k.startswith("TPU_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    import tempfile

    work = tempfile.mkdtemp(prefix="pga_resize_smoke_")
    env["PGA_RESIZE_CKPT"] = os.path.join(work, "state.npz")
    env["PGA_RESIZE_BEST"] = os.path.join(work, "best.json")

    for stage in range(len(STAGES)):
        rc = _run_stage(stage, env)
        if rc != 0:
            print(f"RESIZE SMOKE: FAIL (stage {stage})")
            return rc
        n, restoring = STAGES[stage]
        print(
            f"stage {stage} ok: {n} processes"
            + (" (restored from previous stage)" if restoring else "")
        )
    # Stage 5: the population-shard resize leg (single process, its own
    # subprocess so the forced device count binds before backend init).
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--pop-shard-leg"],
        env=env, timeout=600,
    )
    if proc.returncode != 0:
        print("RESIZE SMOKE: FAIL (pop-shard leg)")
        return proc.returncode
    print("stage 4 ok: pop-shard leg (save@shards=4 -> restore@shards=2)")
    print("RESIZE SMOKE: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
