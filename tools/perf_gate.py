#!/usr/bin/env python
"""Continuous-bench regression gate (ISSUE 17, ci.sh stage 17).

    JAX_PLATFORMS=cpu python tools/perf_gate.py             # clean gate
    JAX_PLATFORMS=cpu python tools/perf_gate.py --selftest  # prove trip
    JAX_PLATFORMS=cpu python tools/perf_gate.py --record    # add baseline

The gate measures a small fixed workload (OneMax {POP}x{LEN}, XLA
path) through the REAL bench estimator (``bench._sample_gps``: paired
two-length subtraction), compares the median of this run's rounds
against the committed ``PERF_HISTORY.json`` baseline with
``perf.detect`` at the CROSS-PROCESS drift floor (±15%, BASELINE.md
doctrine — committed baselines come from other processes), and exits
nonzero on a confirmed regression after emitting a validated
``perf_regression`` event and a flight-recorder dump. Fewer than 3
finite baseline samples → the detector abstains ("baselining") and the
gate passes. Either way the gate's own run populated ``perf.stage_ms``,
whose Prometheus rendering is then linted via
``tools/metrics_dump.py --check`` — the scrape-ability half of the
observatory contract.

ISSUE 18 adds a second arm: the FLEET ROUND-TRIP gate — a ring-enabled
2-worker fleet serving {FLEET_GATE_REQS} small tickets per round, the
end-to-end coordination rate (submit -> shared-memory wake -> worker
mega-run -> publish -> readback) in runs/sec. Same detector, same
cross-process floor, same ``PERF_HISTORY.json`` DB under
``arm="fleet_gate"`` — a coordination-path regression (e.g. the ring
silently degrading to polling) now trips ci even when the compute
kernels are unchanged.

ISSUE 19 adds a third: the GP EVALUATOR gate — optimizer-ON symbolic
regression at the BENCH_r13 shape ({GP_GATE_POP}x{GP_GATE_NODES}
tokens, {GP_GATE_SAMPLES}-sample fitness) in gens/sec under
``arm="gp_gate"``. The eval-time fold/DCE/compact fast path
(gp/optimize.py) bought a >1.5x whole-generation win; this arm is the
trip wire that keeps it bought.

``--selftest`` proves the trip wire end to end in a temp dir: measure a
clean baseline, re-measure with an injected work-proportional slowdown
(``FaultPlan(site="bench.measure", kind="slow")`` — per-generation
stall, the only shape of slowdown the subtraction estimator cannot
cancel), and require the detector to convict the slowed run and acquit
the clean one. Exits nonzero if either half fails.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_DB = os.path.join(REPO, "PERF_HISTORY.json")
GATE_POP, GATE_LEN = 2048, 64
GATE_METRIC = "gate_gens_per_sec"
GATE_ROUNDS = 4
LO, HI = 20, 60  # two-length subtraction lengths (small: this is a gate)

# Fleet round-trip arm (ISSUE 18): small tickets — the figure is the
# COORDINATION rate, so the compute per ticket is kept near-trivial.
FLEET_GATE_METRIC = "fleet_gate_runs_per_sec"
FLEET_GATE_POP, FLEET_GATE_LEN, FLEET_GATE_GENS = 256, 32, 5
FLEET_GATE_WORKERS = 2
FLEET_GATE_REQS = 4
FLEET_GATE_ROUNDS = 3

# GP evaluator arm (ISSUE 19): the optimizer-ON symbolic-regression
# workload at the BENCH_r13 shape — a regression here means the
# eval-time fold/DCE/compact fast path (gp/optimize.py) or the
# live-length-bounded interpreter lost its win.
GP_GATE_METRIC = "gp_gate_gens_per_sec"
GP_GATE_POP, GP_GATE_NODES, GP_GATE_SAMPLES = 1024, 16, 64
GP_GATE_ROUNDS = 3
GP_LO, GP_HI = 5, 15  # GP generations are ~100x heavier than OneMax's

# Coordinator-failover arm (ISSUE 20): the submit blackout — wall
# seconds from a live leader's last heartbeat to a hot standby holding
# the lease and leading. Lease-timeout dominated (so the figure is
# stable on a contended host), and LOWER IS BETTER — the one arm in
# this gate where a rising number is the regression.
HA_GATE_METRIC = "ha_gate_failover_settle_s"
HA_GATE_ROUNDS = 3
HA_GATE_LEASE_S = 1.5


def _runner():
    """The fixed gate workload: OneMax 2048x64 on the XLA path (the
    path that exists on every backend, so the gate's baseline is
    comparable wherever ci runs)."""
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=7, config=PGAConfig(use_pallas=False))
    h = pga.create_population(GATE_POP, GATE_LEN)
    pga.set_objective("onemax")
    pga.run(5)  # compile + warm
    return pga, h, lambda n: pga.run(n)


def _measure(run, rounds: int = GATE_ROUNDS):
    import bench

    return [bench._sample_gps(run, LO, HI) for _ in range(rounds)]


def _gp_runner():
    """The GP gate workload: optimizer-ON (the default) symbolic
    regression at the BENCH_r13 shape, XLA interpreter path — the
    fast path this gate exists to protect."""
    import jax

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.gp import encoding as genc
    from libpga_tpu.gp import operators as gpo
    from libpga_tpu.gp.sr import make_dataset, symbolic_regression

    gp = genc.GPConfig(max_nodes=GP_GATE_NODES, n_vars=2)
    X, y = make_dataset(
        lambda a, b: a * b + a, n_samples=GP_GATE_SAMPLES, n_vars=2,
        seed=0,
    )
    pga = PGA(seed=7, config=PGAConfig(
        use_pallas=False, selection="truncation", elitism=2,
    ))
    pga.set_objective(symbolic_regression(X, y, gp=gp))
    pga.set_crossover(gpo.make_subtree_crossover(gp))
    pga.set_mutate(gpo.make_gp_mutate(gp))
    pga.install_population(
        genc.random_population(jax.random.key(7), GP_GATE_POP, gp)
    )
    pga.run(3)  # compile + warm
    return lambda n: pga.run(n)


def _gp_measure(rounds: int = GP_GATE_ROUNDS):
    import bench

    run = _gp_runner()
    return [bench._sample_gps(run, GP_LO, GP_HI) for _ in range(rounds)]


def _gate_key(arm: str = "gate", shape: str = None):
    import jax

    from libpga_tpu.perf import PerfKey

    try:
        device = getattr(jax.devices()[0], "device_kind", "unknown")
    except RuntimeError:
        device = "unknown"
    return PerfKey(
        backend=jax.default_backend(), device_kind=str(device),
        shape=shape or f"{GATE_POP}x{GATE_LEN}", arm=arm,
    )


def _fleet_measure(rounds: int = FLEET_GATE_ROUNDS):
    """Runs/sec of whole fleet round trips through a ring-enabled
    2-worker fleet: one warm pass (worker compiles, excluded), then
    ``rounds`` timed serves of FLEET_GATE_REQS tickets each."""
    import shutil
    import time

    from libpga_tpu import PGAConfig
    from libpga_tpu.config import FleetConfig
    from libpga_tpu.serving.fleet import Fleet, FleetTicket

    root = tempfile.mkdtemp(prefix="pga-perf-gate-fleet-")
    fleet = Fleet(
        os.path.join(root, "gate"), "onemax",
        config=PGAConfig(use_pallas=False),
        fleet=FleetConfig(
            n_workers=FLEET_GATE_WORKERS, max_batch=2, max_wait_ms=5,
            lease_timeout_s=30.0, heartbeat_s=0.5, poll_s=0.05,
            ring=True,
        ),
    )
    fleet.start()

    def serve(base):
        handles = [
            fleet.submit(FleetTicket(
                size=FLEET_GATE_POP, genome_len=FLEET_GATE_LEN,
                n=FLEET_GATE_GENS, seed=base + i,
            ))
            for i in range(FLEET_GATE_REQS)
        ]
        fleet.flush()
        for h in handles:
            h.result(timeout=600)

    samples = []
    try:
        serve(10_000)  # warm: each worker compiles its mega-run once
        for rnd in range(rounds):
            t0 = time.perf_counter()
            serve(20_000 + 1_000 * rnd)
            samples.append(FLEET_GATE_REQS / (time.perf_counter() - t0))
    finally:
        fleet.close()
        shutil.rmtree(root, ignore_errors=True)
    return samples


def _ha_measure(rounds: int = HA_GATE_ROUNDS):
    """Seconds of coordinator-failover settle per round: two HA
    candidates on one spool, the leader's monitor (heartbeats) stops
    cold — the in-process SIGKILL analog — and the clock runs until
    the standby seizes the stale lease and leads."""
    import shutil
    import time

    from libpga_tpu import PGAConfig
    from libpga_tpu.config import FleetConfig
    from libpga_tpu.serving.fleet import Fleet
    from libpga_tpu.utils import metrics as M

    cfg = PGAConfig(use_pallas=False)
    samples = []
    for _ in range(rounds):
        root = tempfile.mkdtemp(prefix="pga-perf-gate-ha-")
        fc = dict(
            n_workers=1, max_batch=1, max_wait_ms=2, poll_s=0.05,
            lease_timeout_s=HA_GATE_LEASE_S, heartbeat_s=0.3,
            ring=False, coordinators=2,
        )
        a = Fleet(os.path.join(root, "spool"), "onemax", config=cfg,
                  fleet=FleetConfig(**fc),
                  registry=M.MetricsRegistry())
        b = Fleet(os.path.join(root, "spool"), "onemax", config=cfg,
                  fleet=FleetConfig(**fc),
                  registry=M.MetricsRegistry())
        try:
            a._ensure_monitor()  # leader heartbeats, no worker pool
            b.start()            # standby: election watch only
            time.sleep(2 * fc["heartbeat_s"])
            t0 = time.perf_counter()
            a._stop_monitor.set()
            a._wake.set()
            if a._monitor is not None:
                a._monitor.join(timeout=30)
            while time.perf_counter() - t0 < 60 and not b.is_leader:
                time.sleep(0.01)
            if not b.is_leader:
                samples.append(float("nan"))  # detect() drops it loudly
            else:
                samples.append(time.perf_counter() - t0)
        finally:
            a._closed = True
            b.close()
            shutil.rmtree(root, ignore_errors=True)
    return samples


def _trip(verdict, events_path: str) -> None:
    """A confirmed regression: emit the validated ``perf_regression``
    event and dump the flight recorder — the triage artifact."""
    from libpga_tpu.utils import telemetry as T

    with T.EventLog(events_path) as log:
        rec = log.emit(
            "perf_regression",
            metric=verdict.metric, current=verdict.current,
            baseline=verdict.baseline_median,
            threshold=verdict.threshold,
        )
    T.validate_event(rec)
    T.flight_note("perf_regression", {"metric": verdict.metric,
                                      "ratio": verdict.ratio})
    dump = T.flight_dump("perf_gate regression")
    print(f"perf_gate: REGRESSION {verdict.as_dict()}")
    if dump:
        print(f"perf_gate: flight dump -> {dump}")


def _lint_perf_metrics(tmpdir: str) -> int:
    """Render the live ``perf.*`` series as Prometheus text and lint it
    through the real ``tools/metrics_dump.py --check`` subprocess."""
    from libpga_tpu.utils import metrics as M

    snap = M.REGISTRY.snapshot()
    for kind in ("counters", "gauges", "histograms"):
        snap[kind] = [r for r in snap[kind]
                      if r["name"].startswith("perf.")]
    if not any(snap[k] for k in ("counters", "gauges", "histograms")):
        print("perf_gate: no perf.* series after the gate run — the "
              "span->stage_ms wiring is broken")
        return 1
    prom = os.path.join(tmpdir, "perf_metrics.prom")
    with open(prom, "w", encoding="utf-8") as fh:
        fh.write(M.prometheus_text(snap))
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_dump.py"),
         "--check", prom],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).returncode
    print(f"perf_gate: prometheus lint of perf.* series "
          f"{'clean' if rc == 0 else 'FAILED'}")
    return rc


def run_gate(db_path: str, record: bool) -> int:
    from libpga_tpu.perf import CROSS_PROCESS_FLOOR, PerfHistory, detect
    from libpga_tpu.perf.history import PerfSample, git_rev, new_run_id

    _, _, run = _runner()
    arms = [
        (_gate_key(), GATE_METRIC, _measure(run), "gate", True),
        (
            _gate_key("fleet_gate", f"{FLEET_GATE_POP}x{FLEET_GATE_LEN}"),
            FLEET_GATE_METRIC, _fleet_measure(), "fleet_gate ring=on",
            True,
        ),
        (
            _gate_key(
                "gp_gate", f"{GP_GATE_POP}x{GP_GATE_NODES}nodes"
            ),
            GP_GATE_METRIC, _gp_measure(), "gp_gate optimize=on", True,
        ),
        # ISSUE 20: seconds, not a rate — lower is better here.
        (
            _gate_key("ha_gate", "2coordx1worker"),
            HA_GATE_METRIC, _ha_measure(), "ha_gate coordinators=2",
            False,
        ),
    ]

    hist = (PerfHistory.load(db_path) if os.path.exists(db_path)
            else PerfHistory())
    rev = git_rev()
    verdicts = []
    recorded = 0
    for key, metric, samples, note, higher in arms:
        current = statistics.median(samples)
        print(f"perf_gate: {key.as_string()} {metric} "
              f"median={current:.2f} "
              f"rounds={[round(s, 2) for s in samples]}")
        baseline = [s.value for s in hist.series(key, metric)]
        verdicts.append(detect(baseline, current, metric=metric,
                               drift_floor=CROSS_PROCESS_FLOOR,
                               higher_is_better=higher))
        if record:
            # One run_id per SAMPLE: identity is (key, metric, round,
            # run_id, source), so same-run samples need distinct ids.
            for s in samples:
                hist.add(PerfSample(
                    key=key, metric=metric, value=s,
                    run_id=new_run_id(), git_rev=rev,
                    source="perf_gate", note=note,
                ))
            recorded += len(samples)
    if record:
        hist.save(db_path)
        print(f"perf_gate: recorded {recorded} samples -> {db_path}")

    rc = 0
    with tempfile.TemporaryDirectory() as td:
        for verdict in verdicts:
            if verdict.regressed:
                _trip(verdict, os.path.join(td, "events.jsonl"))
                rc = 1
            else:
                bar = ("none" if verdict.threshold is None
                       else f"{verdict.threshold:.3f}")
                print(f"perf_gate: pass {verdict.metric} "
                      f"({verdict.reason}; "
                      f"baseline n={verdict.n_baseline}, threshold={bar})")
        lint_rc = _lint_perf_metrics(td)
    return rc or lint_rc


def run_selftest() -> int:
    from libpga_tpu.perf import (
        CROSS_PROCESS_FLOOR, PerfHistory, detect,
    )
    from libpga_tpu.perf.history import PerfSample
    from libpga_tpu.robustness import faults
    from libpga_tpu.utils import telemetry as T

    _, _, run = _runner()
    key = _gate_key()
    failures = []
    with tempfile.TemporaryDirectory() as td:
        # Clean baseline through the real estimator, persisted through
        # the real atomic-save/load path.
        clean = _measure(run)
        hist = PerfHistory()
        for i, s in enumerate(clean):
            hist.add(PerfSample(key=key, metric=GATE_METRIC, value=s,
                                run_id=i + 1, source="selftest"))
        db = os.path.join(td, "history.json")
        hist.save(db)
        hist = PerfHistory.load(db)
        baseline = [s.value for s in hist.series(key, GATE_METRIC)]
        clean_med = statistics.median(baseline)

        # Acquit: a fresh clean re-measure must NOT be convicted. The
        # floor here is deliberately looser than the gate's (2x the
        # cross-process floor): this half of the selftest only needs to
        # separate noise from the ~40% injection below, and a tight bar
        # would make the selftest itself the flakiest stage in ci.
        v_clean = detect(baseline, statistics.median(_measure(run, 3)),
                         metric=GATE_METRIC,
                         drift_floor=2 * CROSS_PROCESS_FLOOR)
        print(f"perf_gate selftest: clean verdict {v_clean.as_dict()}")
        if v_clean.regressed:
            failures.append("clean run convicted (estimator noise?)")

        # Convict: inject a ~60% work-proportional slowdown into the
        # timed window and re-measure through the same path.
        plan = faults.FaultPlan(
            site="bench.measure", kind="slow", probability=1.0,
            times=None, param=0.6 / clean_med,
        )
        faults.install(plan)
        try:
            v_slow = detect(baseline, statistics.median(_measure(run, 2)),
                            metric=GATE_METRIC,
                            drift_floor=CROSS_PROCESS_FLOOR)
        finally:
            faults.clear()
        print(f"perf_gate selftest: slowed verdict {v_slow.as_dict()}")
        if not v_slow.regressed:
            failures.append("injected slowdown NOT convicted")
        else:
            _trip(v_slow, os.path.join(td, "events.jsonl"))
            try:
                recs = T.validate_log(os.path.join(td, "events.jsonl"))
                if not any(r["event"] == "perf_regression" for r in recs):
                    failures.append("no perf_regression event emitted")
            except ValueError as exc:
                failures.append(f"perf_regression event invalid: {exc}")

        lint_rc = _lint_perf_metrics(td)
        if lint_rc:
            failures.append("prometheus lint failed")

    if failures:
        print("perf_gate selftest: FAIL — " + "; ".join(failures))
        return 1
    print("perf_gate selftest: ok (clean acquitted, injected slowdown "
          "convicted, event schema-valid, perf.* scrape-able)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--db", default=DEFAULT_DB)
    ap.add_argument("--record", action="store_true",
                    help="append this run's samples to the baseline DB")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the trip wire via an injected slowdown")
    args = ap.parse_args(argv)
    if args.selftest:
        return run_selftest()
    return run_gate(args.db, args.record)


if __name__ == "__main__":
    sys.exit(main())
