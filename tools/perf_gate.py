#!/usr/bin/env python
"""Continuous-bench regression gate (ISSUE 17, ci.sh stage 17).

    JAX_PLATFORMS=cpu python tools/perf_gate.py             # clean gate
    JAX_PLATFORMS=cpu python tools/perf_gate.py --selftest  # prove trip
    JAX_PLATFORMS=cpu python tools/perf_gate.py --record    # add baseline

The gate measures a small fixed workload (OneMax {POP}x{LEN}, XLA
path) through the REAL bench estimator (``bench._sample_gps``: paired
two-length subtraction), compares the median of this run's rounds
against the committed ``PERF_HISTORY.json`` baseline with
``perf.detect`` at the CROSS-PROCESS drift floor (±15%, BASELINE.md
doctrine — committed baselines come from other processes), and exits
nonzero on a confirmed regression after emitting a validated
``perf_regression`` event and a flight-recorder dump. Fewer than 3
finite baseline samples → the detector abstains ("baselining") and the
gate passes. Either way the gate's own run populated ``perf.stage_ms``,
whose Prometheus rendering is then linted via
``tools/metrics_dump.py --check`` — the scrape-ability half of the
observatory contract.

``--selftest`` proves the trip wire end to end in a temp dir: measure a
clean baseline, re-measure with an injected work-proportional slowdown
(``FaultPlan(site="bench.measure", kind="slow")`` — per-generation
stall, the only shape of slowdown the subtraction estimator cannot
cancel), and require the detector to convict the slowed run and acquit
the clean one. Exits nonzero if either half fails.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_DB = os.path.join(REPO, "PERF_HISTORY.json")
GATE_POP, GATE_LEN = 2048, 64
GATE_METRIC = "gate_gens_per_sec"
GATE_ROUNDS = 4
LO, HI = 20, 60  # two-length subtraction lengths (small: this is a gate)


def _runner():
    """The fixed gate workload: OneMax 2048x64 on the XLA path (the
    path that exists on every backend, so the gate's baseline is
    comparable wherever ci runs)."""
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=7, config=PGAConfig(use_pallas=False))
    h = pga.create_population(GATE_POP, GATE_LEN)
    pga.set_objective("onemax")
    pga.run(5)  # compile + warm
    return pga, h, lambda n: pga.run(n)


def _measure(run, rounds: int = GATE_ROUNDS):
    import bench

    return [bench._sample_gps(run, LO, HI) for _ in range(rounds)]


def _gate_key():
    import jax

    from libpga_tpu.perf import PerfKey

    try:
        device = getattr(jax.devices()[0], "device_kind", "unknown")
    except RuntimeError:
        device = "unknown"
    return PerfKey(
        backend=jax.default_backend(), device_kind=str(device),
        shape=f"{GATE_POP}x{GATE_LEN}", arm="gate",
    )


def _trip(verdict, events_path: str) -> None:
    """A confirmed regression: emit the validated ``perf_regression``
    event and dump the flight recorder — the triage artifact."""
    from libpga_tpu.utils import telemetry as T

    with T.EventLog(events_path) as log:
        rec = log.emit(
            "perf_regression",
            metric=verdict.metric, current=verdict.current,
            baseline=verdict.baseline_median,
            threshold=verdict.threshold,
        )
    T.validate_event(rec)
    T.flight_note("perf_regression", {"metric": verdict.metric,
                                      "ratio": verdict.ratio})
    dump = T.flight_dump("perf_gate regression")
    print(f"perf_gate: REGRESSION {verdict.as_dict()}")
    if dump:
        print(f"perf_gate: flight dump -> {dump}")


def _lint_perf_metrics(tmpdir: str) -> int:
    """Render the live ``perf.*`` series as Prometheus text and lint it
    through the real ``tools/metrics_dump.py --check`` subprocess."""
    from libpga_tpu.utils import metrics as M

    snap = M.REGISTRY.snapshot()
    for kind in ("counters", "gauges", "histograms"):
        snap[kind] = [r for r in snap[kind]
                      if r["name"].startswith("perf.")]
    if not any(snap[k] for k in ("counters", "gauges", "histograms")):
        print("perf_gate: no perf.* series after the gate run — the "
              "span->stage_ms wiring is broken")
        return 1
    prom = os.path.join(tmpdir, "perf_metrics.prom")
    with open(prom, "w", encoding="utf-8") as fh:
        fh.write(M.prometheus_text(snap))
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_dump.py"),
         "--check", prom],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).returncode
    print(f"perf_gate: prometheus lint of perf.* series "
          f"{'clean' if rc == 0 else 'FAILED'}")
    return rc


def run_gate(db_path: str, record: bool) -> int:
    from libpga_tpu.perf import CROSS_PROCESS_FLOOR, PerfHistory, detect
    from libpga_tpu.perf.history import PerfSample, git_rev, new_run_id

    _, _, run = _runner()
    samples = _measure(run)
    current = statistics.median(samples)
    key = _gate_key()
    print(f"perf_gate: {key.as_string()} {GATE_METRIC} "
          f"median={current:.2f} rounds={[round(s, 1) for s in samples]}")

    hist = (PerfHistory.load(db_path) if os.path.exists(db_path)
            else PerfHistory())
    baseline = [s.value for s in hist.series(key, GATE_METRIC)]
    verdict = detect(baseline, current, metric=GATE_METRIC,
                     drift_floor=CROSS_PROCESS_FLOOR)

    if record:
        # One run_id per SAMPLE: identity is (key, metric, round,
        # run_id, source), so same-run samples need distinct ids.
        rev = git_rev()
        for s in samples:
            hist.add(PerfSample(
                key=key, metric=GATE_METRIC, value=s,
                run_id=new_run_id(), git_rev=rev, source="perf_gate",
                note="gate",
            ))
        hist.save(db_path)
        print(f"perf_gate: recorded {len(samples)} samples -> {db_path}")

    rc = 0
    with tempfile.TemporaryDirectory() as td:
        if verdict.regressed:
            _trip(verdict, os.path.join(td, "events.jsonl"))
            rc = 1
        else:
            bar = ("none" if verdict.threshold is None
                   else f"{verdict.threshold:.3f}")
            print(f"perf_gate: pass ({verdict.reason}; "
                  f"baseline n={verdict.n_baseline}, threshold={bar})")
        lint_rc = _lint_perf_metrics(td)
    return rc or lint_rc


def run_selftest() -> int:
    from libpga_tpu.perf import (
        CROSS_PROCESS_FLOOR, PerfHistory, detect,
    )
    from libpga_tpu.perf.history import PerfSample
    from libpga_tpu.robustness import faults
    from libpga_tpu.utils import telemetry as T

    _, _, run = _runner()
    key = _gate_key()
    failures = []
    with tempfile.TemporaryDirectory() as td:
        # Clean baseline through the real estimator, persisted through
        # the real atomic-save/load path.
        clean = _measure(run)
        hist = PerfHistory()
        for i, s in enumerate(clean):
            hist.add(PerfSample(key=key, metric=GATE_METRIC, value=s,
                                run_id=i + 1, source="selftest"))
        db = os.path.join(td, "history.json")
        hist.save(db)
        hist = PerfHistory.load(db)
        baseline = [s.value for s in hist.series(key, GATE_METRIC)]
        clean_med = statistics.median(baseline)

        # Acquit: a fresh clean re-measure must NOT be convicted. The
        # floor here is deliberately looser than the gate's (2x the
        # cross-process floor): this half of the selftest only needs to
        # separate noise from the ~40% injection below, and a tight bar
        # would make the selftest itself the flakiest stage in ci.
        v_clean = detect(baseline, statistics.median(_measure(run, 3)),
                         metric=GATE_METRIC,
                         drift_floor=2 * CROSS_PROCESS_FLOOR)
        print(f"perf_gate selftest: clean verdict {v_clean.as_dict()}")
        if v_clean.regressed:
            failures.append("clean run convicted (estimator noise?)")

        # Convict: inject a ~60% work-proportional slowdown into the
        # timed window and re-measure through the same path.
        plan = faults.FaultPlan(
            site="bench.measure", kind="slow", probability=1.0,
            times=None, param=0.6 / clean_med,
        )
        faults.install(plan)
        try:
            v_slow = detect(baseline, statistics.median(_measure(run, 2)),
                            metric=GATE_METRIC,
                            drift_floor=CROSS_PROCESS_FLOOR)
        finally:
            faults.clear()
        print(f"perf_gate selftest: slowed verdict {v_slow.as_dict()}")
        if not v_slow.regressed:
            failures.append("injected slowdown NOT convicted")
        else:
            _trip(v_slow, os.path.join(td, "events.jsonl"))
            try:
                recs = T.validate_log(os.path.join(td, "events.jsonl"))
                if not any(r["event"] == "perf_regression" for r in recs):
                    failures.append("no perf_regression event emitted")
            except ValueError as exc:
                failures.append(f"perf_regression event invalid: {exc}")

        lint_rc = _lint_perf_metrics(td)
        if lint_rc:
            failures.append("prometheus lint failed")

    if failures:
        print("perf_gate selftest: FAIL — " + "; ".join(failures))
        return 1
    print("perf_gate selftest: ok (clean acquitted, injected slowdown "
          "convicted, event schema-valid, perf.* scrape-able)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--db", default=DEFAULT_DB)
    ap.add_argument("--record", action="store_true",
                    help="append this run's samples to the baseline DB")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the trip wire via an injected slowdown")
    args = ap.parse_args(argv)
    if args.selftest:
        return run_selftest()
    return run_gate(args.db, args.record)


if __name__ == "__main__":
    sys.exit(main())
