#!/usr/bin/env python
"""Invariant guard runner: AST lints + IR contract audit + C-ABI
cross-check (ISSUE 13, CI stage 14).

    tools/lint_pga.py                 # lint the whole tree (fast, no jax)
    tools/lint_pga.py path.py ...     # lint specific files
    tools/lint_pga.py --abi           # C-ABI cross-check only
    tools/lint_pga.py --ir            # IR contracts on the live engine
    tools/lint_pga.py --all           # lint + ABI + IR  (the CI gate)
    tools/lint_pga.py --changed       # git-diff-scoped fast path

Exit status: 0 on a clean tree, 1 with ``file:line: [rule] message``
diagnostics otherwise, 2 on an internal error.

``--changed`` keeps the full-tree walk out of the edit loop: it lints
only files touched per ``git status`` (staged, unstaged and untracked),
adds the ABI cross-check exactly when an ABI layer file changed, and
skips the IR audit (which needs a jax import + engine lowerings —
that's the CI stage's job).

The lint and ABI passes import NOTHING from the package (the analysis
modules are loaded standalone from their file paths), so they run in
milliseconds even where jax is missing or broken. Only ``--ir`` pays
the jax import; it forces the simulated 8-device CPU platform first,
exactly as tests/conftest.py does.
"""

import argparse
import importlib.util
import os
import subprocess
import sys
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Files whose change triggers the ABI cross-check under --changed.
ABI_FILES = (
    "capi/pga_tpu.h",
    "capi/pga_tpu.cc",
    "libpga_tpu/capi_bridge.py",
    "capi/test_serving.c",
)


def _load_standalone(relpath: str, dotted: str):
    """Load an analysis module from its file path WITHOUT importing the
    libpga_tpu package (whose __init__ pulls jax). The module is
    registered under its dotted name — with stub parent packages — so
    the analyzers' own `from libpga_tpu.analysis.lint import ...`
    statements resolve from sys.modules instead of triggering the real
    package import."""
    if dotted in sys.modules:
        return sys.modules[dotted]
    parts = dotted.split(".")
    for i in range(1, len(parts)):
        pkg = ".".join(parts[:i])
        if pkg not in sys.modules:
            stub = types.ModuleType(pkg)
            stub.__path__ = []  # mark as package
            sys.modules[pkg] = stub
    spec = importlib.util.spec_from_file_location(
        dotted, os.path.join(REPO_ROOT, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[dotted] = mod
    spec.loader.exec_module(mod)
    return mod


def _lint_module():
    return _load_standalone(
        "libpga_tpu/analysis/lint.py", "libpga_tpu.analysis.lint"
    )


def _abi_module():
    _lint_module()  # Finding import target
    return _load_standalone(
        "libpga_tpu/analysis/abi_check.py", "libpga_tpu.analysis.abi_check"
    )


def changed_files():
    """Repo-relative paths touched per git (staged + unstaged +
    untracked); None when git is unavailable."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=30,
        )
    except OSError:
        return None
    if out.returncode != 0:
        return None
    files = []
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: lint the new name
            path = path.split(" -> ", 1)[1]
        files.append(path.strip('"'))
    return files


def run_lint(paths, lint):
    findings = lint.lint_paths(paths)
    # parse errors are real failures too, but syntactically broken
    # files are pytest's department — keep them visible regardless.
    return findings


def run_ir(verbose):
    # Mirror tests/conftest.py: the sharded contract needs a simulated
    # multi-device CPU platform, configured BEFORE jax initializes.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.path.insert(0, REPO_ROOT)
    # drop the standalone stubs so the real package imports cleanly
    for name in [
        n for n in list(sys.modules)
        if n == "libpga_tpu" or n.startswith("libpga_tpu.")
    ]:
        del sys.modules[name]
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)

    from libpga_tpu.analysis import ir_audit

    return ir_audit.audit_repo(verbose=verbose)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="repo-specific static analysis (lint + IR + ABI)"
    )
    ap.add_argument("paths", nargs="*", help="files to lint (default: tree)")
    ap.add_argument("--lint", action="store_true", help="AST lint pass")
    ap.add_argument("--abi", action="store_true", help="C-ABI cross-check")
    ap.add_argument("--ir", action="store_true",
                    help="IR contract audit (imports jax)")
    ap.add_argument("--all", action="store_true", help="lint + ABI + IR")
    ap.add_argument("--changed", action="store_true",
                    help="git-diff-scoped fast path")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    do_lint = args.lint or args.all or args.changed or (
        not (args.abi or args.ir)
    )
    do_abi = args.abi or args.all
    do_ir = args.ir or args.all

    lint = _lint_module()
    problems = 0

    if args.changed:
        changed = changed_files()
        if changed is None:
            print("lint_pga: --changed needs git; falling back to full tree")
            changed = None
        if changed is not None:
            py = [
                os.path.join(REPO_ROOT, p) for p in changed
                if p.endswith(".py") and "fixtures" not in p.split("/")
                and os.path.exists(os.path.join(REPO_ROOT, p))
            ]
            lint_paths = py
            if any(p in ABI_FILES for p in changed):
                do_abi = True
        else:
            lint_paths = lint.default_paths(REPO_ROOT)
    elif args.paths:
        lint_paths = [os.path.abspath(p) for p in args.paths]
    else:
        lint_paths = lint.default_paths(REPO_ROOT)

    if do_lint:
        findings = run_lint(lint_paths, lint)
        for f in findings:
            print(str(f).replace(REPO_ROOT + os.sep, ""))
        problems += len(findings)
        if args.verbose or findings:
            print(
                f"lint: {len(findings)} finding(s) across "
                f"{len(lint_paths)} file(s)"
            )

    if do_abi:
        abi = _abi_module()
        findings = abi.check_repo_abi(REPO_ROOT)
        for f in findings:
            print(str(f).replace(REPO_ROOT + os.sep, ""))
        problems += len(findings)
        if args.verbose or findings:
            print(f"abi: {len(findings)} finding(s)")

    if do_ir:
        try:
            ir_problems = run_ir(args.verbose)
        except Exception as e:  # an import/lowering crash is a failure
            print(f"ir-audit: crashed: {type(e).__name__}: {e}")
            return 2
        for p in ir_problems:
            print(f"ir-audit: {p}")
        problems += len(ir_problems)
        if args.verbose or ir_problems:
            print(f"ir: {len(ir_problems)} problem(s)")

    if problems:
        print(f"lint_pga: {problems} problem(s)")
        return 1
    print("lint_pga: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
