#!/usr/bin/env python
"""Perf-history reporting CLI (ISSUE 17).

    python tools/perf_report.py --backfill            # BENCH_r*.json -> DB
    python tools/perf_report.py --table               # trajectory table
    python tools/perf_report.py --ingest ART.json ... # add artifacts
    python tools/perf_report.py --report 1048576x100 --dtype bfloat16

``--backfill`` ingests every historical bench artifact (all three
artifact generations — the r01–r06 wrapper shape, the r07–r08
provenance-stamped nested shape, the r09+ flat shape) into one
schema-valid history DB; torn files are skipped and reported, matching
``perf/history.merge_files``. ``--table`` renders the repo's
performance trajectory as one table (the primary metric of each
ingested artifact, in round order). ``--report`` prints the analytic
roofline program report for a shape — the chip-round playbook's
measurement route (ROADMAP item 5): reports and measurements flow
through here instead of hand-edited BASELINE.md tables.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_DB = os.path.join(REPO, "PERF_HISTORY.json")


def _load(path: str):
    from libpga_tpu.perf import PerfHistory

    if os.path.exists(path):
        return PerfHistory.load(path)
    return PerfHistory()


def do_backfill(db_path: str, pattern: str) -> int:
    hist = _load(db_path)
    paths = sorted(glob.glob(pattern))
    if not paths:
        print(f"perf_report: no artifacts match {pattern!r}")
        return 1
    skipped = []
    n_added = 0
    for p in paths:
        try:
            n_added += len(hist.ingest_file(p))
        except Exception as exc:  # torn/partial: skip-and-report
            skipped.append((p, str(exc)))
    hist.save(db_path)
    print(
        f"perf_report: ingested {len(paths) - len(skipped)}/{len(paths)} "
        f"artifacts ({n_added} samples) into {db_path} "
        f"({len(hist)} total samples)"
    )
    for p, why in skipped:
        print(f"  skipped {p}: {why}")
    return 0 if not skipped else 1


def do_table(db_path: str, all_metrics: bool) -> int:
    hist = _load(db_path)
    rows = sorted(
        (s for s in hist.samples.values()
         if all_metrics or s.note == "primary"),
        key=lambda s: (s.round, s.key.arm, s.metric, s.run_id),
    )
    if not rows:
        print(f"perf_report: {db_path} holds no samples — run --backfill")
        return 1
    print(f"{'round':>5}  {'arm':<10} {'backend':<9} "
          f"{'metric':<44} {'value':>14}  rev")
    for s in rows:
        print(
            f"{s.round:>5}  {s.key.arm:<10} {s.key.backend:<9} "
            f"{s.metric[:44]:<44} {s.value:>14.4g}  {s.git_rev or '-'}"
        )
    print(f"-- {len(rows)} rows ({len(hist)} samples total) from {db_path}")
    return 0


def do_report(shape: str, dtype: str, gp: bool) -> int:
    from libpga_tpu import perf

    pop, _, length = shape.partition("x")
    pop, length = int(pop), int(length or 100)
    if gp:
        from libpga_tpu.gp.encoding import GPConfig

        report = perf.gp_report(pop, GPConfig(max_nodes=length), 64)
    else:
        import jax.numpy as jnp

        report = perf.breed_report(
            pop, length, gene_dtype=jnp.dtype(dtype).type
        )
    print(json.dumps(report, indent=1, default=str))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--db", default=DEFAULT_DB)
    ap.add_argument("--backfill", nargs="?", const="BENCH_r*.json",
                    metavar="GLOB")
    ap.add_argument("--ingest", nargs="+", metavar="FILE")
    ap.add_argument("--table", action="store_true")
    ap.add_argument("--all-metrics", action="store_true",
                    help="--table: every sample, not just primaries")
    ap.add_argument("--report", metavar="POPxLEN")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--gp", action="store_true",
                    help="--report: GP-eval report (LEN = max_nodes)")
    args = ap.parse_args(argv)

    if args.backfill:
        pattern = args.backfill
        if not os.path.isabs(pattern):
            pattern = os.path.join(REPO, pattern)
        return do_backfill(args.db, pattern)
    if args.ingest:
        hist = _load(args.db)
        for p in args.ingest:
            n = len(hist.ingest_file(p))
            print(f"perf_report: {p}: {n} samples")
        hist.save(args.db)
        return 0
    if args.report:
        return do_report(args.report, args.dtype, args.gp)
    return do_table(args.db, args.all_metrics)


if __name__ == "__main__":
    sys.exit(main())
