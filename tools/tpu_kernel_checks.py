"""Hardware validation for the fused Pallas breed kernel.

Run on a real TPU (``python tools/tpu_kernel_checks.py``). Complements the
CPU interpret-mode structural tests in ``tests/test_pallas.py`` with the
distributional properties that need real in-kernel PRNG entropy:

1. Parentage: every child's genes come from ≤2 parents, both inside the
   child's source deme (validates in-deme one-hot selection + the
   riffle-shuffle output mapping under random indices).
2. Gene exactness: selected gene values match the parent rows bit-exactly
   (bf16 hi/lo one-hot matmul reconstruction).
3. Selection pressure: mean parent score ≈ 2/3 quantile of uniform scores
   (tournament-2 expectation E[max(U1,U2)] = 2/3).
4. Mutation: at rate=1 exactly one gene per row changes, uniformly over
   positions; at rate=0 nothing changes.
5. Convergence: the engine's Pallas path solves OneMax to >99% optimum.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.ops.pallas_step import make_pallas_breed


def check(name, ok):
    print(("PASS" if ok else "FAIL"), name, flush=True)
    return ok


def main() -> int:
    if jax.default_backend() != "tpu":
        print("SKIP: not running on TPU")
        return 0
    good = True
    P, L, K = 4096, 100, 256
    G = P // K

    breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
    genomes = (
        jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)) / P
    )
    scores = jax.random.uniform(jax.random.key(1), (P,))
    out = np.asarray(breed(genomes, scores, jax.random.key(2)))
    sn = np.asarray(scores)

    parent_ok, exact_ok = True, True
    parent_scores = []
    for r in range(P):
        ids = np.round(out[r] * P)
        # i/P genes round-trip the bf16 hi/lo split exactly for P=4096
        exact_ok &= bool(np.all(ids == out[r] * P))
        ids = np.unique(ids.astype(int))
        d = r % G
        parent_ok &= len(ids) <= 2 and all(d * K <= p < (d + 1) * K for p in ids)
        parent_scores.extend(sn[ids])
    good &= check("parentage within shuffled demes", parent_ok)
    good &= check("gene values exact for 16-bit genes", exact_ok)
    pressure = float(np.mean(parent_scores))
    good &= check(
        f"selection pressure ~2/3 (got {pressure:.3f})", 0.63 < pressure < 0.70
    )

    breed1 = make_pallas_breed(P, L, deme_size=K, mutation_rate=1.0)
    outm = np.asarray(breed1(jnp.zeros((P, L)), scores, jax.random.key(3)))
    changed = (outm != 0).sum(axis=1)
    pos = np.argmax(outm != 0, axis=1)
    good &= check(
        "mutation rate=1: exactly one gene per row",
        float((changed == 1).mean()) > 0.99,  # val==0.0 draws are ~2^-24
    )
    good &= check(
        f"mutation positions uniform (mean {pos.mean():.1f} ~ {(L-1)/2})",
        abs(pos.mean() - (L - 1) / 2) < 2.0,
    )

    # k=4 tournament: mean winner score of uniform scores is E[max of 4]
    # = 4/5 (tournament-2's 2/3 analog) — validates the k-way winner fold.
    breed4 = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0,
                               tournament_size=4)
    out4 = np.asarray(breed4(genomes, scores, jax.random.key(11)))
    p4 = []
    for r in range(0, P, 3):
        ids = np.unique(np.round(out4[r] * P).astype(int))
        p4.extend(sn[ids])
    pressure4 = float(np.mean(p4))
    good &= check(
        f"k=4 selection pressure ~4/5 (got {pressure4:.3f})",
        0.77 < pressure4 < 0.83,
    )

    # Padded population (no deme divides 3000): with real entropy, every
    # child must still descend from VALID rows only — the last deme holds
    # 3000 - 11*256 = 184 real rows and 72 pads the tournament sampler
    # must never pick.
    Pq = 3000
    breedp = make_pallas_breed(Pq, L, deme_size=K, mutation_rate=0.0)
    Gp = breedp.Pp // K
    genomesq = (
        jnp.broadcast_to(jnp.arange(Pq, dtype=jnp.float32)[:, None], (Pq, L))
        / 4096.0  # /4096 keeps genes bf16-hi/lo-exact like the main check
    )
    outq = np.asarray(
        breedp(genomesq, jax.random.uniform(jax.random.key(4), (Pq,)),
               jax.random.key(5))
    )
    pad_ok = True
    for r in range(Pq):
        ids = np.unique(np.round(outq[r] * 4096).astype(int))
        d = r % Gp
        lo, hi = d * K, min((d + 1) * K, Pq)
        pad_ok &= len(ids) <= 2 and all(lo <= p < hi for p in ids)
    good &= check("padded population: pad rows never selected", pad_ok)

    # Tie fairness: with ALL scores equal, per-row selection mass must be
    # uncorrelated with in-deme row position. The round-3 review caught
    # the index tie-break handing rank-0 rows ~2x the mass of rank-(K-1)
    # rows inside a tie block; the per-generation random tie shuffle
    # equalizes it (|Pearson r| noise floor at P=4096 is ~0.02).
    outt = np.asarray(
        breed(genomes, jnp.zeros((P,)), jax.random.key(21))
    )
    counts = np.zeros(P)
    for r in range(P):
        for pid in np.unique(np.round(outt[r] * P).astype(int)):
            counts[pid] += 1
    pos_in_deme = np.arange(P) % K
    rcorr = float(np.corrcoef(pos_in_deme, counts)[0, 1])
    good &= check(
        f"tie fairness: selection mass uncorrelated with row (r={rcorr:+.3f})",
        abs(rcorr) < 0.05,
    )

    # Alternate selection strategies (round 3: the reference's
    # placeholder enum made real). Truncation tau=0.25 on uniform
    # scores: winners uniform over the top quartile -> mean 0.875.
    # Linear ranking s=2 has tournament-2 intensity -> mean 2/3.
    breedq = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0,
                               selection_kind="truncation",
                               selection_param=0.25)
    outq2 = np.asarray(breedq(genomes, scores, jax.random.key(31)))
    pq = []
    for r in range(0, P, 3):
        ids = np.unique(np.round(outq2[r] * P).astype(int))
        pq.extend(sn[ids])
    mq = float(np.mean(pq))
    good &= check(
        f"truncation tau=.25 mean winner ~0.875 (got {mq:.3f})",
        0.85 < mq < 0.90,
    )
    breedl = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0,
                               selection_kind="linear_rank",
                               selection_param=2.0)
    outl = np.asarray(breedl(genomes, scores, jax.random.key(32)))
    pl_ = []
    for r in range(0, P, 3):
        ids = np.unique(np.round(outl[r] * P).astype(int))
        pl_.extend(sn[ids])
    ml = float(np.mean(pl_))
    good &= check(
        f"linear_rank s=2 mean winner ~2/3 (got {ml:.3f})",
        0.63 < ml < 0.70,
    )

    # Gaussian mutation statistics: uniform population at 0.5 with equal
    # scores makes selection and crossover no-ops, isolating the mutation.
    # rate=0.3, sigma=0.05 -> ~30% of genes perturbed with std ~sigma
    # (clipping is an 8-sigma event, negligible).
    breedg = make_pallas_breed(
        P, L, deme_size=K, mutate_kind="gaussian",
        mutation_rate=0.3, mutation_sigma=0.05,
    )
    outg = np.asarray(
        breedg(jnp.full((P, L), 0.5), jnp.zeros((P,)), jax.random.key(6))
    )
    delta = outg - 0.5
    fired = delta != 0
    frac = float(fired.mean())
    stdev = float(delta[fired].std()) if fired.any() else 0.0
    good &= check(
        f"gaussian fire fraction ~0.30 (got {frac:.3f})", 0.27 < frac < 0.33
    )
    good &= check(
        f"gaussian noise std ~0.050 (got {stdev:.4f})", 0.045 < stdev < 0.055
    )

    # Elitism epilogue (fused): rows 0..1 must be the previous top-2.
    from libpga_tpu.objectives import onemax as _om

    breede = make_pallas_breed(
        P, L, deme_size=K, mutation_rate=0.01, elitism=2,
        fused_obj=_om.kernel_rowwise,
    )
    ge = jax.random.uniform(jax.random.key(8), (P, L))
    se = jnp.sum(ge, axis=1)
    g2e, s2e = breede(ge, se, jax.random.key(9))
    top_i = np.argsort(-np.asarray(se))[:2]
    elite_ok = np.allclose(
        np.asarray(g2e[:2]), np.asarray(ge)[top_i], atol=2e-5
    ) and np.allclose(
        np.asarray(s2e[:2]), np.asarray(se)[top_i], atol=1e-5
    )
    good &= check("elitism: prev top-2 carried into rows 0..1", elite_ok)

    # Permutation path: order-preserving crossover + swap mutation.
    # From a population of PERFECT permutations, every child must itself
    # be a perfect permutation: OPC from a duplicate-free p1 reduces to
    # p1 (no fallback can fire), and a swap preserves uniqueness. From
    # continuous random parents (~63.4 distinct decoded cities per 100),
    # OPC repairs duplicates — children must decode strictly more unique
    # cities on average.
    def uniq_counts(arr, n=None):
        n = L if n is None else n  # decode convention: city = floor(g*n)
        c = np.clip(np.floor(arr * n).astype(int), 0, n - 1)
        return np.array([len(set(row.tolist())) for row in c])

    breedo = make_pallas_breed(
        P, L, deme_size=K, crossover_kind="order", mutate_kind="swap",
        mutation_rate=1.0,
    )
    perm_rng = np.random.default_rng(12)
    perms = (
        perm_rng.permuted(np.tile(np.arange(L), (P, 1)), axis=1) + 0.5
    ).astype(np.float32) / L
    outo = np.asarray(
        breedo(jnp.asarray(perms), jax.random.uniform(jax.random.key(13), (P,)),
               jax.random.key(14))
    )
    good &= check(
        "order+swap: permutation parents -> permutation children",
        bool((uniq_counts(outo) == L).all()),
    )
    randg = jax.random.uniform(jax.random.key(15), (P, L))
    outr = np.asarray(
        breedo(randg, jax.random.uniform(jax.random.key(16), (P,)),
               jax.random.key(17))
    )
    u_parent = float(uniq_counts(np.asarray(randg)).mean())
    u_child = float(uniq_counts(outr).mean())
    good &= check(
        f"order crossover repairs duplicates ({u_parent:.1f} -> {u_child:.1f} "
        "unique cities)",
        u_child > u_parent + 5.0,
    )

    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=7, config=PGAConfig(use_pallas=True))
    h = pga.create_population(1 << 16, 100)
    pga.set_objective("onemax")
    pga.run(300)
    _, best = pga.get_best_with_score(h)
    good &= check(f"OneMax convergence (best {best:.1f}/100)", best > 99.0)

    # Every expression-language op class must LOWER through Mosaic when
    # fused into the breed kernel (interpret-mode tests can't prove
    # this; %, ** with array exponents, tan, and round appear in no
    # builtin objective). One run per expression, real hardware.
    from libpga_tpu.engine import _XLA_FALLBACK
    from libpga_tpu.objectives import from_expression

    lowered = True
    rng = np.random.default_rng(0)
    for e, consts in (
        ("sum(g % 0.25)", {}),
        ("sum(g ** g)", {}),
        ("sum(tan(g) * 0.001) + sum(round(g))", {}),
        ("mean(tanh(g)) + min(g) - max(g) + sum(abs(g - 0.5))", {}),
        ("sum(exp(-(g*2)) + log(g + 1) + sqrt(g) + sin(g) + cos(g))", {}),
        ("dot(g, i) / (1 + mean(g)) + where(sum(g) >= L/2, 1, 0)", {}),
        # v2: let-bindings, roll (static lane concat), gather over a
        # shared 1-D table and a per-locus (n, L) table
        ("a = roll(g, 1); b = roll(g, -3); sum(a*g) - mean(b)", {}),
        ("sum(gather(t, g * 7))",
         {"t": rng.random(7).astype(np.float32)}),
        ("b = g >= 0.5;"
         "codes = b + 2*roll(b, 1) + 4*roll(b, 2) + 8*roll(b, 3);"
         "mean(gather(T, codes))",
         {"T": rng.random((16, 32)).astype(np.float32)}),
    ):
        try:
            solver = PGA(seed=0, config=PGAConfig(use_pallas=True))
            solver.create_population(512, 32)
            solver.set_objective(from_expression(e, **consts))
            solver.run(2)
            entry = [
                v for k, v in solver._compiled.items() if k[0] == "engine/run-pallas"
            ]
            fused = bool(entry) and entry[0] is not _XLA_FALLBACK
            if not fused:
                print(f"  NOT FUSED: {e}")
                lowered = False
        except Exception as exc:  # noqa: BLE001
            print(f"  LOWERING FAILED: {e}: {exc}")
            lowered = False
    good &= check("expression ops lower fused through Mosaic", lowered)

    # Expression BREEDING operators (ops/breed_expr.py) must also lower
    # and run on the fused path — the device-speed custom
    # crossover/mutation surface (verdict round-4 item 1).
    from libpga_tpu.ops.breed_expr import (
        crossover_from_expression,
        mutate_from_expression,
    )

    breed_ok = True
    try:
        solver = PGA(seed=0, config=PGAConfig(use_pallas=True, validate=True))
        hb = solver.create_population(65536, 100)
        solver.set_objective("onemax")
        solver.set_crossover(crossover_from_expression(
            "where(r < 0.3, (p1 + p2) / 2, where(r2 < 0.5, p1, p2))"
        ))
        solver.set_mutate(mutate_from_expression(
            "where(r < rate, r2, g)", rate=0.02
        ))
        solver.run(30)
        entry = [v for k, v in solver._compiled.items() if k[0] == "engine/run-pallas"]
        if not (entry and entry[0] is not _XLA_FALLBACK):
            print("  expr breeding NOT FUSED")
            breed_ok = False
        _, bb = solver.get_best_with_score(hb)
        if bb < 60.0:
            print(f"  expr breeding converged poorly: {bb:.1f}")
            breed_ok = False
    except Exception as exc:  # noqa: BLE001
        print(f"  expr breeding failed: {exc}")
        breed_ok = False
    good &= check(
        "expression crossover+mutation lower fused (validated)", breed_ok
    )

    # Gene-major fused TSP evaluation (round 5): the long-genome path —
    # scores must match the XLA oracle on hardware and the best tour
    # must be a permutation after a short validated run.
    tsp_ok = True
    try:
        from libpga_tpu.objectives.classic import (
            make_tsp_coords, random_tsp_coords,
        )
        from libpga_tpu.ops.crossover import order_preserving_crossover
        from libpga_tpu.ops.mutate import make_swap_mutate

        C = 500
        tsp = make_tsp_coords(
            random_tsp_coords(C, seed=4), duplicate_mode="genes"
        )
        # The check is vacuous if the fused path silently declines
        # (validate=True would then compare the XLA oracle to itself):
        # probe that the gene-major evaluator BUILDS for this config...
        probe = make_pallas_breed(
            4096, C, crossover_kind="order", mutate_kind="swap",
            fused_tsp=tsp.kernel_gene_major,
        )
        if probe is None or not probe.fused:
            print("  gene-major TSP evaluator declined to build")
            tsp_ok = False
        solver = PGA(seed=2, config=PGAConfig(use_pallas=True, validate=True))
        ht = solver.create_population(4096, C)
        solver.set_objective(tsp)
        solver.set_crossover(order_preserving_crossover)
        solver.set_mutate(make_swap_mutate(0.5))
        solver.run(60)  # validate=True cross-checks fused scores per run
        # ...and that the engine took the kernel path, not _XLA_FALLBACK
        entry = [v for k, v in solver._compiled.items() if k[0] == "engine/run-pallas"]
        if not (entry and entry[0] is not _XLA_FALLBACK):
            print("  TSP run fell back to the XLA path")
            tsp_ok = False
        best = np.asarray(solver.get_best(ht))
        uniq = int(uniq_counts(best[None, :], C)[0])
        if uniq != C:
            print(f"  TSP best tour not a permutation: {uniq}/{C}")
            tsp_ok = False
    except Exception as exc:  # noqa: BLE001
        print(f"  fused TSP failed: {exc}")
        tsp_ok = False
    good &= check(
        "gene-major fused TSP eval matches oracle (validated, 500 cities)",
        tsp_ok,
    )

    # Composition checks, under validation mode (the XLA-oracle
    # cross-check runs on every installed state): a long genome
    # (Lp > LANE) through the fused run, and an expression objective
    # with a vector constant through the island multigen epoch.
    solver = PGA(seed=0, config=PGAConfig(use_pallas=True, validate=True))
    hl = solver.create_population(65536, 1500)
    solver.set_objective("onemax")
    solver.run(10)
    _, bl = solver.get_best_with_score(hl)
    good &= check(
        f"long genome L=1500 fused+validated (best {bl:.0f}/1500)",
        bl > 760,
    )
    w = np.linspace(0.5, 1.5, 64).astype(np.float32)
    solver2 = PGA(seed=1, config=PGAConfig(use_pallas=True, validate=True))
    for _ in range(4):
        solver2.create_population(16384, 64)
    solver2.set_objective(from_expression("dot(w, g)", w=w))
    solver2.run_islands(20, 10, 0.05)
    b2 = max(
        solver2.get_best_with_score(h2)[1] for h2 in solver2._handles()
    )
    good &= check(
        f"expr objective + island epoch (best {b2:.1f}/{w.sum():.1f})",
        b2 > 0.8 * float(w.sum()),
    )

    print("ALL PASS" if good else "FAILURES", flush=True)
    return 0 if good else 1


if __name__ == "__main__":
    sys.exit(main())
