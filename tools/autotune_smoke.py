"""CI smoke for the self-tuning kernel loop (ISSUE 10, ci.sh stage 11).

Tier-1-safe (CPU, tiny shapes). Gates, in order:

1. ``tools/autotune.py`` on a tiny CPU space produces a DB file that
   schema-validates, and — with the same seed and budget — a second
   run resolves the IDENTICAL knobs (the determinism acceptance: on a
   CPU backend every config memoizes to the one XLA plan, so the
   verdict cannot wobble with timing noise);
2. the never-regress rule holds: the recorded config's measured
   gens/sec is >= the default's measurement minus the drift floor (on
   CPU they are the same memoized measurement — equal by
   construction);
3. a WARM SERVING RUN under the produced DB compiles exactly the
   DB-resolved config: the bucket's program is built under a cache key
   carrying the resolved knobs, ``cache.stats()["tuned"]`` records the
   provenance (every knob either "db" or unchanged default), and a
   schema-valid ``tuned_config`` event is emitted at warm-up;
4. ``tuning.set_tuning_db(None)`` (db=None) leaves the engine's
   traced run program BYTE-IDENTICAL to the tuned-but-default case —
   the resolution layer is host-side only.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POP, LEN = 512, 32


def run_autotune(db_path: str, seed: int = 7) -> dict:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cmd = [
        sys.executable, "tools/autotune.py",
        "--shape", f"{POP}x{LEN}", "--dtype", "f32",
        "--budget", "4", "--seed", str(seed), "--db", db_path,
        "--rounds", "2", "--max-rounds", "3", "--min-rel-ci", "0.5",
        "--ga-pop", "8", "--max-generations", "3",
        "--measure-lo", "2", "--measure-hi", "5",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env)
    if proc.returncode != 0:
        sys.exit(f"autotune failed:\n{proc.stdout}\n{proc.stderr}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="pga-autotune-smoke-")
    db_path = os.path.join(tmp, "tuning.json")

    # -- 1: CLI produces a schema-valid DB; deterministic verdict -----
    first = run_autotune(db_path, seed=7)
    assert os.path.exists(db_path), "autotune produced no DB file"
    from libpga_tpu.tuning import db as tdb

    loaded = tdb.TuningDB.load(db_path)  # schema-validates or raises
    assert len(loaded) == 1, f"expected 1 entry, got {len(loaded)}"
    second = run_autotune(db_path, seed=7)
    if first["knobs"] != second["knobs"] or first["plan"] != second["plan"]:
        sys.exit(
            "autotune verdict not deterministic at fixed seed/budget: "
            f"{first['knobs']}/{first['plan']} vs "
            f"{second['knobs']}/{second['plan']}"
        )
    entry = next(iter(loaded.entries.values()))

    # -- 2: never-regress --------------------------------------------
    floor = entry.default_gens_per_sec * (1.0 - 0.04)
    if entry.gens_per_sec < floor:
        sys.exit(
            f"recorded config regresses the default: "
            f"{entry.gens_per_sec} < {entry.default_gens_per_sec} - floor"
        )

    # -- 3: warm serving run compiles the DB-resolved config ---------
    events_path = os.path.join(tmp, "events.jsonl")
    from libpga_tpu import PGAConfig
    from libpga_tpu import tuning
    from libpga_tpu.serving import BatchedRuns, RunQueue, RunRequest
    from libpga_tpu.serving import cache as scache
    from libpga_tpu.utils import telemetry

    tuning.set_tuning_db(db_path)
    log = telemetry.EventLog(events_path)
    ex = BatchedRuns(
        "onemax", config=PGAConfig(use_pallas=False), events=log,
    )
    from libpga_tpu.config import ServingConfig

    q = RunQueue(
        ex, serving=ServingConfig(max_batch=2, max_wait_ms=0),
        events=log,
    )
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=i))
        for i in range(2)
    ]
    q.drain()
    for t in tickets:
        t.result(timeout=300)
    q.close()
    log.close()

    stats = scache.PROGRAM_CACHE.stats()
    tuned = stats.get("tuned") or []
    mine = [
        t for t in tuned
        if t["population_size"] == POP and t["genome_len"] == LEN
    ]
    if not mine:
        sys.exit(
            f"warm serving run recorded no tuned provenance: {stats}"
        )
    for t in mine:
        if t["knobs"] != entry.knobs:
            sys.exit(
                "serving warm-up compiled knobs != DB entry: "
                f"{t['knobs']} vs {entry.knobs}"
            )
        if os.path.abspath(t["db"] or "") != os.path.abspath(db_path):
            sys.exit(f"provenance names wrong DB: {t['db']}")
    records = telemetry.validate_log(events_path)
    kinds = [r["event"] for r in records]
    if "tuned_config" not in kinds:
        sys.exit(f"no tuned_config event at warm-up (got {sorted(set(kinds))})")

    # -- 4: db=None is byte-identical (analysis.fingerprint gate) ----
    import jax

    from libpga_tpu import PGA
    from libpga_tpu.analysis import fingerprint

    def lowered_text():
        pga = PGA(seed=0, config=PGAConfig(use_pallas=False))
        pga.set_objective("onemax")
        pga.create_population(POP, LEN)
        fn, _ = pga._compiled_run_meta(POP, LEN)
        import jax.numpy as jnp

        g = jax.ShapeDtypeStruct((POP, LEN), jnp.float32)
        k = jax.eval_shape(lambda: jax.random.key(0))
        args = (
            g, jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        )
        return fingerprint(fn, *args)

    with_db = lowered_text()
    tuning.set_tuning_db(None)
    without_db = lowered_text()
    if with_db != without_db:
        sys.exit(
            "db=None changed the traced program (tuning must be "
            "host-side only)"
        )

    print(
        "autotune smoke OK: deterministic DB "
        f"(knobs {entry.knobs}, plan {entry.plan['path']}), "
        f"never-regress holds ({entry.gens_per_sec:.1f} vs default "
        f"{entry.default_gens_per_sec:.1f} gens/sec), warm serving "
        f"compiled the DB-resolved config ({len(mine)} tuned "
        "program(s) in cache), db=None byte-identical"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
