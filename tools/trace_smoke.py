#!/usr/bin/env python
"""Trace-span smoke: capture a profiler trace and assert the spans exist.

Runs a tiny solver through every engine stage inside a
``profiling.trace()`` capture, then scans the emitted artifacts for the
named ``pga/<stage>`` spans (``utils/telemetry.SPAN_STAGES``). This is
the executable proof that a trace capture shows a readable per-stage
timeline instead of anonymous fusions — run by ``tools/ci.sh`` and
``tests/test_telemetry.py``.

Exit status: 0 = all spans found; 1 = spans missing (names printed);
2 = the profiler produced no artifacts at all.

    JAX_PLATFORMS=cpu python tools/trace_smoke.py
"""

from __future__ import annotations

import os as _os
import pathlib
import sys
import tempfile

sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def main(log_dir: str | None = None) -> int:
    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.utils import checkpoint, profiling, telemetry

    log_dir = log_dir or tempfile.mkdtemp(prefix="pga-trace-smoke-")
    pga = PGA(seed=0, config=PGAConfig())
    h = pga.create_population(128, 16)
    pga.create_population(128, 16)
    pga.set_objective("onemax")
    ckpt_path = str(pathlib.Path(log_dir) / "smoke-ckpt.npz")

    with profiling.trace(log_dir):
        pga.run(3)                      # pga/run (fused loop)
        pga.run_islands(2, 1, 0.1)      # pga/run_islands
        pga.evaluate(h)                 # pga/evaluate
        pga.crossover(h)                # pga/select_breed
        pga.mutate(h)                   # pga/mutate
        pga.swap_generations(h)         # pga/swap
        pga.evaluate_all()
        pga.migrate(0.1)                # pga/migrate
        checkpoint.save(pga, ckpt_path)  # pga/checkpoint

    wanted = {
        (telemetry.SPAN_PREFIX + stage).encode()
        for stage in telemetry.SPAN_STAGES
    }
    found: set = set()
    n_files = 0
    for f in pathlib.Path(log_dir).rglob("*"):
        if not f.is_file() or f.suffix == ".npz":
            continue
        n_files += 1
        data = f.read_bytes()
        found.update(name for name in wanted if name in data)
    if n_files == 0:
        print(f"TRACE_SMOKE NO-ARTIFACTS: nothing written under {log_dir}")
        return 2
    missing = sorted(n.decode() for n in wanted - found)
    if missing:
        print(f"TRACE_SMOKE FAIL: spans missing from capture: {missing}")
        return 1
    print(
        f"TRACE_SMOKE PASS: all {len(wanted)} spans present "
        f"({', '.join(sorted(n.decode() for n in wanted))})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
